"""Benchmark: BERT-base train tokens/sec/chip + ResNet-50 train images/sec
(SURVEY §6). Runs on the real chip, bf16 compute, donated buffers; prints
ONE JSON line.

Baselines (BASELINE.json "north star": within 10% of Paddle's own V100
numbers): Paddle-era V100 fp32 ResNet-50 ≈ 360 images/s; BERT-base seq128
≈ 25k tokens/s. vs_baseline is ours ÷ that reference.
"""
import json
import time

import numpy as np

BERT_BASELINE_TOKENS_S = 25000.0   # Paddle V100 BERT-base seq128 approx
RESNET_BASELINE_IMG_S = 360.0      # Paddle V100 fp32 ResNet-50 approx


def _normalize_u8(xb):
    """uint8 image batch -> normalized f32 on device (shared by both
    ResNet benches so they measure identical work)."""
    return (xb.astype("float32") / 255.0 - 0.45) / 0.22


def _probe_pallas_kernels():
    """Probe each Pallas kernel fwd+bwd on the live device and disable
    (pallas.configure) just the ones that fail, so one kernel-compile
    failure degrades that kernel to its XLA path instead of zeroing the
    whole bench."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops import pallas as P

    if not P.on_tpu():
        return  # kernels default off; interpret-mode probes prove nothing

    def flash():
        # Probe BOTH shapes the battery reaches past the seq >= 512
        # gate: seq 2048 (block_k=1024 tiling) and seq 512 (single
        # clamped K block). The r4 VMEM OOMs were shape-dependent, so
        # one shape's probe proves nothing about the other.
        from paddle_tpu.ops.pallas.flash_attention import _flash
        seed = jnp.zeros((2,), jnp.int32)
        for seq in (2048, 512):
            q = jnp.ones((1, 2, seq, 64), jnp.bfloat16)

            def f(q):
                return _flash(q, q, q, None, None, seed, False, None,
                              512, 1024, 0.1).astype(jnp.float32).sum()

            jax.grad(f)(q).block_until_ready()

    def layer_norm():
        # 8192 rows f32 = the seq-2048 bench's worst case (r4 VMEM OOM
        # was f32-and-shape-dependent; a small bf16 probe missed it)
        from paddle_tpu.ops.pallas.layer_norm import _layer_norm2
        x = jnp.ones((8192, 768), jnp.float32)
        w = jnp.ones((768,), jnp.float32)
        b = jnp.zeros((768,), jnp.float32)

        def f(x):
            return _layer_norm2(x, w, b, 1e-12).astype(jnp.float32).sum()

        jax.grad(f)(x).block_until_ready()

    def fused_adam():
        from paddle_tpu.ops.pallas.fused_adam import fused_adam_update
        p = jnp.ones((2048, 768), jnp.float32)
        new_p, _, _ = fused_adam_update(p, p * 0.01, p * 0, p * 0, 1e-3,
                                        0.9, 0.999)
        new_p.block_until_ready()

    def fused_adam_multi():
        from paddle_tpu.ops.pallas.fused_adam import fused_adam_update_multi
        ps = [jnp.ones((512, 768), jnp.float32),
              jnp.ones((768,), jnp.float32)]
        nps, _, _ = fused_adam_update_multi(
            ps, [p * 0.01 for p in ps], [p * 0 for p in ps],
            [p * 0 for p in ps], 1e-3, 0.9, 0.999)
        nps[0].block_until_ready()

    def batch_norm():
        # ResNet-50 stage-1 NHWC shape (the largest BN the bench hits
        # if the channels-last path is headlined): bf16 activations
        from paddle_tpu.ops.pallas.batch_norm import _batch_norm2
        x = jnp.ones((128 * 112 * 112, 64), jnp.bfloat16)
        w = jnp.ones((64,), jnp.float32)
        b = jnp.zeros((64,), jnp.float32)

        def f(x):
            out, _, _ = _batch_norm2(x, w, b, 1e-5)
            return out.astype(jnp.float32).sum()

        jax.grad(f)(x).block_until_ready()

    def softmax_xent():
        # 8192 rows = the real bench shape (batch 64 × seq 128): the r4
        # VMEM blow-up was shape-dependent and a 256-row probe missed it
        from paddle_tpu.ops.pallas.softmax_xent import _softmax_xent2
        x = jnp.ones((8192, 30522), jnp.float32)
        lab = jnp.zeros((8192, 1), jnp.int32)

        def f(x):
            return _softmax_xent2(x, lab).sum()

        jax.grad(f)(x).block_until_ready()

    for name, probe in (("flash_attention", flash),
                        ("layer_norm", layer_norm),
                        ("fused_adam", fused_adam),
                        ("fused_adam_multi", fused_adam_multi),
                        ("batch_norm", batch_norm),
                        ("softmax_xent", softmax_xent)):
        if not P.enabled(name):
            continue  # auto-off kernel: no bench stage can reach it
        try:
            probe()
        except Exception as e:  # pragma: no cover
            print(f"pallas {name} probe failed ({type(e).__name__}); "
                  f"XLA fallback", flush=True)
            P.configure(**{name: False})


def bench_bert(batch=64, seq=128, steps=32, inner=8, measured_key=None,
               **cfg_kw):
    """`inner` REAL optimizer steps (distinct resident batches) run per
    compiled call — one dispatch covers `inner` steps, so the tunnel /
    host-dispatch round-trip amortizes instead of flooring the step
    time. tok/s counts batch*seq*inner per call."""
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as opt, jit, amp
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    pt.seed(0)
    cfg = BertConfig.base(**cfg_kw)
    model = BertForPretraining(cfg)
    o = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      (inner, batch, seq)).astype("i4")
    mlm = np.where(rng.rand(inner, batch, seq) < 0.15,
                   rng.randint(0, cfg.vocab_size, (inner, batch, seq)), -1
                   ).astype("i4")
    nsp = rng.randint(0, 2, (inner, batch)).astype("i4")

    def one(ids, mlm, nsp):
        with amp.auto_cast(dtype="bfloat16"):
            logits, nsp_logits = model(ids)
        loss = model.loss(logits.astype("float32"),
                          nsp_logits.astype("float32"), mlm, nsp)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    def step(ids_k, mlm_k, nsp_k):
        loss = None
        for i in range(inner):
            loss = one(ids_k[i], mlm_k[i], nsp_k[i])
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    t_ids, t_mlm, t_nsp = pt.to_tensor(ids), pt.to_tensor(mlm), \
        pt.to_tensor(nsp)
    fn(t_ids, t_mlm, t_nsp)  # compile
    loss = fn(t_ids, t_mlm, t_nsp)
    loss.numpy()  # sync
    n_calls = max(1, steps // inner)
    t0 = time.perf_counter()
    for _ in range(n_calls):
        loss = fn(t_ids, t_mlm, t_nsp)
    loss.numpy()
    dt = (time.perf_counter() - t0) / (n_calls * inner)
    if measured_key:
        m = _measured_mfu(dt, per_call_steps=inner)
        if m is not None:
            _RESULTS[measured_key] = m
    return batch * seq / dt, float(loss.numpy())


# Headline ResNet layout. scripts/bench_nhwc_resnet.py measures
# NCHW vs NHWC vs NHWC+pallas-BN on chip; flip this (and the pallas
# batch_norm auto default) to whatever wins there.
RESNET_FORMAT = "NCHW"


def bench_resnet(batch=128, steps=12, inner=4, data_format=None,
                 measured_key=None):
    """`inner` real steps per compiled call (distinct resident uint8
    batches, normalized on device) — see bench_bert."""
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as opt, jit, amp
    from paddle_tpu.models.resnet import resnet50

    data_format = data_format or RESNET_FORMAT
    pt.seed(0)
    model = resnet50(data_format=data_format)
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())
    rng = np.random.RandomState(0)
    shape = (inner, batch, 3, 224, 224) if data_format == "NCHW" \
        else (inner, batch, 224, 224, 3)
    x = (rng.rand(*shape) * 255).astype("u1")
    y = rng.randint(0, 1000, (inner, batch)).astype("i4")

    def one(xb, yb):
        with amp.auto_cast(dtype="bfloat16"):
            logits = model(_normalize_u8(xb))
        loss = pt.nn.functional.cross_entropy(logits.astype("float32"), yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    def step(x_k, y_k):
        loss = None
        for i in range(inner):
            loss = one(x_k[i], y_k[i])
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    tx, ty = pt.to_tensor(x), pt.to_tensor(y)
    fn(tx, ty)  # compile
    loss = fn(tx, ty)
    loss.numpy()
    n_calls = max(1, steps // inner)
    t0 = time.perf_counter()
    for _ in range(n_calls):
        loss = fn(tx, ty)
    loss.numpy()
    dt = (time.perf_counter() - t0) / (n_calls * inner)
    if measured_key:
        m = _measured_mfu(dt, per_call_steps=inner)
        if m is not None:
            _RESULTS[measured_key] = m
    return batch / dt, float(loss.numpy())


def bench_resnet_pipeline(batch=128, steps=8):
    """ResNet fed through the REAL input pipeline (io.DataLoader over the
    C++ native batcher, csrc/core.cpp) instead of one resident batch —
    the perf evidence for the host-side arena/prefetch path.

    Feeds uint8 images (like a real decoded-JPEG pipeline) and normalizes
    on device inside the jitted step, so host→device moves 1/4 the bytes.
    Also reports the loader-only rate (C++ shuffle+gather+prefetch), which
    is the csrc claim proper — end-to-end additionally rides this
    environment's tunneled H2D link."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt, jit, amp, io
    from paddle_tpu.models.resnet import resnet50

    pt.seed(0)
    model = resnet50()
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())
    rng = np.random.RandomState(0)
    n = batch * (steps + 2)
    x = (rng.rand(n, 3, 224, 224) * 255).astype("u1")
    y = rng.randint(0, 1000, (n,)).astype("i4")
    ds = io.TensorDataset(x, y)
    loader = io.DataLoader(ds, batch_size=batch, shuffle=True,
                           drop_last=True, use_native=True)

    # loader-only rate: C++ background shuffle+assemble, no device in loop
    for _ in loader:
        pass  # warm epoch (thread spin-up)
    t0 = time.perf_counter()
    got = 0
    for xb, _ in loader:
        got += xb.shape[0]
    loader_ips = got / (time.perf_counter() - t0)

    def step(xb, yb):
        with amp.auto_cast(dtype="bfloat16"):
            logits = model(_normalize_u8(xb))
        loss = pt.nn.functional.cross_entropy(logits.astype("float32"), yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    it = iter(loader)
    xb, yb = next(it)
    fn(pt.to_tensor(xb), pt.to_tensor(yb))  # compile
    done = 0
    t0 = time.perf_counter()
    loss = None
    for xb, yb in it:
        loss = fn(pt.to_tensor(xb), pt.to_tensor(yb))
        done += xb.shape[0]
        if done >= batch * steps:
            break
    loss.numpy()
    dt = time.perf_counter() - t0
    return done / dt, loader_ips


def bench_bert_long(batch=4, seq=2048, steps=8):
    """Long-context secondary metric: BERT-base-width encoder at seq 2048
    — the regime where the flash kernel's O(S) memory vs sdpa's O(S^2)
    scores matters on HBM. inner=2 keeps the unrolled 12-layer seq-2048
    graph's compile time bounded."""
    return bench_bert(batch=batch, seq=seq, steps=steps, inner=2,
                      measured_key="bert_seq2048_mfu_measured",
                      max_position_embeddings=2048)


def bench_bert_seq512(batch=16, seq=512, steps=16, inner=4):
    """Long-sequence headline (VERDICT r4 task 4): seq 512 is the
    smallest shape the flash gate routes to the Pallas kernel, and
    batch 16 x seq 512 keeps tokens/step identical to the seq-128
    headline (8,192) so tok/s is directly comparable."""
    return bench_bert(batch=batch, seq=seq, steps=steps, inner=inner,
                      measured_key="bert_seq512_mfu_measured")


def bench_serving(requests=400, clients=8, max_batch=32,
                  timeout_ms=2.0, dim=256):
    """Online-serving stage: the latency/QPS face of the ledger, next
    to training MFU. A warmed ServingEngine over a (dim -> 4*dim ->
    dim) MLP absorbs ragged concurrent requests (sizes 1/3/7/13) from
    `clients` threads; dynamic batching coalesces them into bucket
    shapes, so the numbers measure the serving tier itself, not a
    compile storm. Returns (p50_ms, p99_ms, qps, mean_batch_fill)."""
    import threading
    import paddle_tpu as pt
    from paddle_tpu import inference, monitor, nn, serving

    pt.seed(0)
    model = nn.Sequential(nn.Linear(dim, 4 * dim), nn.ReLU(),
                          nn.Linear(4 * dim, dim))
    eng = serving.ServingEngine(
        inference.Predictor(model), buckets=[8, max_batch],
        max_batch=max_batch, timeout_ms=timeout_ms, queue_depth=2048)
    eng.warmup([((dim,), "float32")])

    sizes = [1, 3, 7, 13]
    per_client = requests // clients
    latencies = []
    lock = threading.Lock()
    barrier = threading.Barrier(clients)

    def client(k):
        rng = np.random.RandomState(k)
        barrier.wait()
        for i in range(per_client):
            x = rng.rand(sizes[(k + i) % len(sizes)], dim).astype("f4")
            t0 = time.perf_counter()
            eng.run(x, timeout=60)
            with lock:
                latencies.append((time.perf_counter() - t0) * 1e3)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(clients)]
    t_start = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t_start
    eng.close()

    fill = monitor.registry().value("serving.batch_fill") or {}
    mean_fill = (fill.get("sum", 0.0) / fill["count"]) \
        if isinstance(fill, dict) and fill.get("count") else 0.0
    lat = sorted(latencies)

    def pct(p):
        return lat[min(int(len(lat) * p), len(lat) - 1)] if lat else 0.0

    return pct(0.50), pct(0.99), len(lat) / wall, mean_fill


def bench_collective_overlap(timeout_s=600):
    """Gradient-communication stage: runs scripts/comm_smoke.py in a
    subprocess pinned to 8 virtual CPU devices (the collective ring
    needs a multi-device mesh regardless of what backend the rest of
    the bench runs on) and banks its measurements — exposed wire
    seconds exact vs overlap, bucket count, wire/logical comm bytes,
    quantized loss parity. The sentinel bands these via
    collective_overlap_* keys."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "comm_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir", "/tmp/paddle_tpu_bench_comm"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"comm_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    return {
        "collective_overlap_exposed_wire_s":
            r["exposed_wire_overlap_s"],
        "collective_overlap_exact_wire_s": r["exposed_wire_exact_s"],
        "collective_overlap_ratio": r["overlap_ratio"],
        "collective_overlap_bucket_count": r["bucket_count"],
        "comm_bytes_logical": r["comm_bytes_logical"],
        "comm_bytes_wire_int8": r["comm_bytes_wire_int8"],
        "comm_wire_reduction_int8_x": r["wire_reduction_int8_x"],
        "comm_wire_reduction_int4_x": r["wire_reduction_int4_x"],
        "comm_quantized_loss_rel_err": r["quantized_loss_rel_err"],
    }


def bench_serving_degraded(timeout_s=600):
    """Degraded-serving stage: runs scripts/serving_chaos_smoke.py in a
    subprocess pinned to 4 virtual CPU devices and banks what the fleet
    keeps while broken — goodput with 1 of 4 replicas hung mid-load,
    high-priority goodput under 2x overload, and the hedge overhead
    (hedged fraction of traffic) paid for the straggler rescue. The
    sentinel bands the goodputs as floors and the hedge fraction as a
    ceiling — resilience regressions show up here before they show up
    in an outage."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "serving_chaos_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_serving_chaos"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"serving_chaos_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    hedge = r["hedge_win"]
    return {
        "serving_degraded_goodput": r["hang_failover"]["goodput"],
        "serving_degraded_high_goodput":
            r["overload_shed"]["high_goodput"],
        "serving_degraded_hedge_frac":
            round(hedge["hedged"] / max(hedge["submitted"], 1), 4),
        "serving_degraded_failovers": r["hang_failover"]["failovers"],
        "serving_degraded_shed": r["overload_shed"]["total_shed"],
    }


def bench_fused_optimizer(timeout_s=600):
    """Fused-optimizer stage: runs scripts/arena_smoke.py in a
    subprocess (CPU-pinned — the arena layout and the opt.* byte ledger
    are backend-independent) and banks its measurements: optimizer-scope
    bytes_accessed per 5-step run under the multi-tensor per-leaf
    baseline vs the flat arena, the reduction fraction, the surviving
    concat/gather/scatter count, and the post-compile step wall time.
    The sentinel bands the byte metrics tight (deterministic functions
    of the model layout + packing) and the wall time very wide."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "arena_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_arena"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"arena_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    return {
        "fused_optimizer_opt_bytes_base": r["opt_bytes_base"],
        "fused_optimizer_opt_bytes_flat": r["opt_bytes_flat"],
        "fused_optimizer_bytes_reduction": r["opt_bytes_reduction"],
        "fused_optimizer_banned_ops_flat":
            r["opt_concat_gather_scatter_flat"],
        "fused_optimizer_step_time_s": r["step_time_flat_s"],
    }


def bench_planner(timeout_s=600):
    """Auto-sharding planner stage: runs scripts/plan_smoke.py in a
    subprocess pinned to 8 virtual CPU devices and banks the advisor's
    decision: candidate count (tight band — drift means the
    factorization enumeration changed), the winning layout's predicted
    step seconds (very wide band — a modeled time), and the chosen
    factorization label. The smoke itself enforces the hard gates
    (bit-identity with the hand megatron layout, zero extra
    recompiles, predicted-fastest == measured-fastest)."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "plan_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_plan"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"plan_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    return {
        "planner_candidates": r["planner_candidates"],
        "planner_predicted_step_s": r["planner_predicted_step_s"],
        "planner_chosen": r["planner_chosen"],
        "planner_gates_pass": bool(r["pass"]),
    }


def bench_memory_plan(timeout_s=600):
    """Planned-memory stage: runs scripts/remat_smoke.py in a
    subprocess and banks the memory-policy loop's decision: how many
    times past the no-remat ceiling the picked policy trains (tight
    band — the headline capability must not shrink), the picked rung,
    predicted vs simulated peak under the policy, the offload worker's
    exposed-wait fraction, and warm step seconds under none/remat
    (very wide bands — CPU wall-clock noise). The smoke itself
    enforces the hard gates (pre-flight peak under the limit, picker
    never infeasible or host-over-budget, bit-identity)."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8"
                        ).strip()
    smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "remat_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_memory_plan"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"remat_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    return {
        "memory_plan_ceiling_multiple": r["ceiling_multiple"],
        "memory_plan_picked": r["picked"],
        "memory_plan_predicted_peak_bytes": r["predicted_peak_bytes"],
        "memory_plan_measured_peak_bytes":
            r["measured_peak_under_policy"],
        "memory_plan_offload_exposed_frac": r["offload_exposed_frac"],
        "memory_plan_offload_transfer_s":
            round(r["offload_transfer_s"], 6),
        "memory_plan_step_s_none": round(r["step_s_none"], 6),
        "memory_plan_step_s_remat": round(r["step_s_remat"], 6),
        "memory_plan_gates_pass": bool(r["pass"]),
    }


def bench_decode(timeout_s=600):
    """Generative-decode stage: runs scripts/decode_smoke.py in a
    subprocess (CPU, 2 virtual devices for the scale-up phase) and
    banks the continuous-batching numbers: sustained tokens/s under
    continuous refill, the speedup over the drain run-to-completion
    baseline at the same slot count, decode-batch occupancy, and the
    prefill p50 / decode p99 step latencies. The sentinel bands the
    wall-clock rates very wide (shared-box noise), the speedup and
    occupancy tight — those are scheduling ratios, not clock
    measurements, and a drop means the refill discipline regressed."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "decode_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_decode"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"decode_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    tp = r["throughput"]
    return {
        "decode_tokens_per_s": tp["continuous_tokens_per_s"],
        "decode_drain_tokens_per_s": tp["drain_tokens_per_s"],
        "decode_speedup_x": tp["speedup_x"],
        "decode_batch_occupancy": tp["continuous_occupancy"],
        "decode_prefill_p50_ms": tp["prefill_p50_ms"],
        "decode_p99_ms": tp["decode_p99_ms"],
        "decode_ttft_p50_ms": tp.get("ttft_p50_ms"),
        "decode_ttft_p99_ms": tp.get("ttft_p99_ms"),
        "decode_tpot_p50_ms": tp.get("tpot_p50_ms"),
        "decode_tpot_p99_ms": tp.get("tpot_p99_ms"),
        "decode_gates_pass": bool(r["ok"]),
    }


def bench_spec_decode(timeout_s=900):
    """Speculative-decode stage: runs scripts/spec_smoke.py in a
    subprocess (CPU) and banks the draft-verify numbers: plain sampled
    tokens/s vs speculative at k=4 and k=8 on the distilled demo pair,
    the two speedup ratios, and the measured accept rates. The
    sentinel bands the wall-clock rates very wide; the speedup ratios
    get a wide band too (they divide two CPU clocks), but the accept
    rate is pure arithmetic over the verify ledger — tight band, a
    drop means the accept-prefix rule or the draft distillation
    regressed, not the weather."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    smoke = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "spec_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_spec"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"spec_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    sp = r["speedup"]
    return {
        "decode_sampled_tokens_per_s": sp["plain_tokens_per_s"],
        "decode_spec_tokens_per_s": sp["spec_k8_tokens_per_s"],
        "decode_spec_speedup_x": sp["speedup_k4_x"],
        "decode_spec_speedup_k8_x": sp["speedup_k8_x"],
        "decode_accept_rate": sp["accept_rate_k4"],
        "decode_accept_rate_k8": sp["accept_rate_k8"],
        "decode_spec_gates_pass": bool(r["ok"]),
    }


def bench_lifecycle(timeout_s=900):
    """Serving-lifecycle stage: runs scripts/lifecycle_smoke.py and a
    short scripts/soak_chaos.py in subprocesses (CPU, 4 virtual
    devices) and banks the zero-downtime numbers: the p99 of a full
    fleet drain (in-flight decode streams run to completion), requests
    dropped across a rolling weight hot-swap (must be zero — the swap
    migrates, never sheds), and the goodput the fleet holds through the
    mixed-fault chaos soak. The sentinel bands the drain latency very
    wide (it's CPU decode wall-clock), but swap drops and soak goodput
    tight — those are correctness ratios, and any drift means the
    drain/migrate/swap discipline regressed."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    here = os.path.dirname(os.path.abspath(__file__))
    smoke = os.path.join(here, "scripts", "lifecycle_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_lifecycle"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"lifecycle_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    soak = os.path.join(here, "scripts", "soak_chaos.py")
    sproc = subprocess.run(
        [sys.executable, soak, "--out-dir",
         "/tmp/paddle_tpu_bench_soak", "--duration", "20"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    sline = next((ln for ln in reversed(sproc.stdout.splitlines())
                  if ln.startswith("{")), None)
    if sproc.returncode != 0 or sline is None:
        raise RuntimeError(
            f"soak_chaos rc={sproc.returncode}: "
            f"{(sproc.stderr or sproc.stdout)[-400:]}")
    s = json.loads(sline)
    return {
        "lifecycle_drain_p99_ms": r["drain_p99_ms"],
        "lifecycle_swap_dropped": r["swap_dropped"],
        "lifecycle_soak_goodput": s["goodput"],
        "lifecycle_soak_requests": s["requests"],
        "lifecycle_gates_pass": bool(r["ok"]),
        "lifecycle_soak_gates_pass": bool(s["ok_gate"]),
    }


def bench_fleet_telemetry(timeout_s=600):
    """Fleet telemetry stage: runs scripts/telemetry_smoke.py (a
    4-process decode fleet publishing snapshots, with one straggler and
    one compile-storm worker injected) and banks the plane's two costs:
    the CPU a worker burns publishing snapshots as a percentage of its
    run (must stay tiny — this is the price every fleet member pays)
    and the wall-clock from load start to the first anomaly alert
    firing (the page-the-operator latency). Both band wide in the
    sentinel — they are wall-clock on a shared box — but the gates_pass
    bit is exact: merge oracle, alert discipline, goodput
    reconciliation, and disabled-mode silence all held."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    here = os.path.dirname(os.path.abspath(__file__))
    smoke = os.path.join(here, "scripts", "telemetry_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_telemetry", "--fast"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"telemetry_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    return {
        "fleet_agg_overhead_pct": r["fleet_agg_overhead_pct"],
        "alert_detection_latency_s": r["alert_detection_latency_s"],
        "fleet_sources": r["sources"],
        "telemetry_gates_pass": bool(r["ok"]),
    }


def bench_disagg(timeout_s=900):
    """Disaggregated-serving stage: runs scripts/disagg_smoke.py (a
    prefill pool and a decode pool split across 2 virtual CPU devices,
    KV handed off over the PR 12 comm model, with a shared-prefix
    cache in front of prefill) and banks the split's headline numbers:
    the prefix-cache hit rate at 50% structured reuse, the hit-vs-miss
    TTFT split the cache buys (a hit skips prefill entirely, so hit
    p50 must stay well under miss p50), the per-request KV handoff
    cost, and the split topology's end-to-end tokens/s. Wall-clock
    series band wide in the sentinel (shared box); the gates_pass bit
    is exact: bit-parity with the single-engine oracle through a
    mid-stream drain, handoff bytes == plan, per-pool SLO autoscale,
    and goodput >= 0.90 with one prefill replica hung."""
    import os
    import subprocess
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    here = os.path.dirname(os.path.abspath(__file__))
    smoke = os.path.join(here, "scripts", "disagg_smoke.py")
    proc = subprocess.run(
        [sys.executable, smoke, "--out-dir",
         "/tmp/paddle_tpu_bench_disagg"],
        capture_output=True, text=True, timeout=timeout_s, env=env)
    line = next((ln for ln in reversed(proc.stdout.splitlines())
                 if ln.startswith("{")), None)
    if proc.returncode != 0 or line is None:
        raise RuntimeError(
            f"disagg_smoke rc={proc.returncode}: "
            f"{(proc.stderr or proc.stdout)[-400:]}")
    r = json.loads(line)
    return {
        "disagg_prefix_hit_rate": r["prefix_hit_rate"],
        "disagg_ttft_hit_p50_ms": r["ttft_hit_p50_ms"],
        "disagg_ttft_miss_p50_ms": r["ttft_miss_p50_ms"],
        "disagg_handoff_ms": r["handoff_p50_ms"],
        "disagg_tokens_per_s": r["tokens_per_s"],
        "disagg_gates_pass": bool(r["ok"]),
    }


def bench_hotspot(label=None, top_k=5):
    """Hotspot stage: parse the newest captured step executable's HLO
    into the per-op cost ledger (monitor.profile) and bank the ranked
    fusion menu next to the throughput it explains — which region, at
    what attributed fraction, with how much memory-bound headroom. The
    sentinel bands hotspot_count tight (the menu must not silently go
    empty) and the fractions wide."""
    from paddle_tpu import monitor
    rep = monitor.profile.report(label=label, top_k=top_k,
                                 emit_records=False)
    if rep is None:
        return None
    recon = rep.get("flops_reconciliation")
    top = rep["hotspots"][0] if rep["hotspots"] else None
    return {
        "hotspot_count": len(rep["hotspots"]),
        "hotspot_attributed_frac": round(rep["attributed_frac"], 4),
        "hotspot_top_headroom_s":
            round(top["headroom_s"], 9) if top else None,
        "hotspot_flops_reconciliation":
            round(recon, 4) if recon else None,
        "hotspot_top_regions": [
            {"region": h["region"], "bound": h["bound"],
             "flops": h["flops"],
             "headroom_s": round(h["headroom_s"], 9)}
            for h in rep["hotspots"][:3]],
        "hotspot_device_kind": rep["ceilings"]["device_kind"],
        "hotspot_assumed_roofline": rep["ceilings"]["assumed"],
    }


def bench_memory(label=None, top_k=5):
    """Memory stage: run the buffer-liveness model (monitor.memory)
    over the newest captured step executable and bank the predicted
    HBM peak next to XLA's own memory_analysis() peak and the live
    device watermark — which class (param / activation / opt_state /
    temp) owns the peak, at what attributed fraction. The sentinel
    bands the reconciliation tight (the model must keep agreeing with
    the compiler) and the absolute peaks wide (they move with every
    legitimate model-size change)."""
    from paddle_tpu import monitor
    rep = monitor.memory.report(label=label, top_k=top_k,
                                emit_records=False)
    if rep is None:
        return None
    recon = rep.get("reconciliation")
    top = rep["contributors"][0] if rep["contributors"] else None
    return {
        "memory_predicted_peak_bytes": rep["predicted_peak_bytes"],
        "memory_xla_peak_bytes": rep["xla_peak_bytes"],
        "memory_reconciliation": round(recon, 4) if recon else None,
        "memory_attributed_frac": round(rep["attributed_frac"], 4),
        "memory_measured_peak_bytes": rep["measured_peak_bytes"],
        "memory_by_class": rep["by_class"],
        "memory_top_contributor": (
            {"class": top["class"], "region": top["region"],
             "bytes": top["bytes"]} if top else None),
        "memory_n_donated": rep["n_donated"],
    }


_RESULTS = {}  # metrics banked as each stage finishes (partial-credit)


def _mfu(rate_per_s, flops_per_item):
    """MFU from a throughput: items/s × train flops/item ÷ the live
    device's peak bf16 flops (monitor's per-device_kind table, or the
    PADDLE_TPU_FLOPS_CEILING override). None when the ceiling is
    unknown (CPU, unrecognized kind) — absent beats fabricated."""
    try:
        from paddle_tpu import monitor
        peak = monitor.peak_flops_for_device()
    except Exception:
        peak = None
    if not peak or not rate_per_s:
        return None
    return round(rate_per_s * flops_per_item / peak, 4)


def _measured_mfu(step_time_s, label="jit.step", per_call_steps=1):
    """MFU from the XLA-counted flops of the bench's compiled step
    (monitor.xla captures the executable on first compile): flops per
    call ÷ steps-per-call, over the measured step time × peak.
    Complements _mfu's analytic 6N figure — agreement within ~20%
    validates the analytic denominator; a bigger gap means remat, a
    miscounted model, or a fused step doing extra work. None off-TPU
    or when no capture landed (absent beats fabricated)."""
    try:
        from paddle_tpu import monitor
        f = monitor.xla.flops(label)
        peak = monitor.peak_flops_for_device()
    except Exception:
        return None
    if not f or not peak or not step_time_s:
        return None
    return round(f / per_call_steps / step_time_s / peak, 4)


def _note_mfu_divergence(prefix):
    """Bank an explicit flag when analytic and XLA-measured MFU disagree
    by >20% — the ratio rides the perf line so a drifting denominator
    is visible in the ledger, not just in a warning on stderr."""
    a = _RESULTS.get(f"{prefix}_mfu")
    m = _RESULTS.get(f"{prefix}_mfu_measured")
    if a and m and abs(m / a - 1.0) > 0.2:
        _RESULTS[f"{prefix}_mfu_divergence"] = round(m / a, 3)


def _bert_flops_per_token():
    """Params-only 6N convention (no attention quadratic term), the
    common MFU denominator — keeps seq-128/512/2048 rows comparable."""
    from paddle_tpu import monitor
    return monitor.transformer_train_flops_per_token(
        monitor.BERT_BASE_PARAMS)


def _provenance(with_device=False):
    """Who/where/what for the perf ledger: every emitted line (success
    or _fail_json) carries enough to re-attribute the number later.
    Device fields are added only after backend init proves the tunnel
    answers (touching jax.devices() on a wedged tunnel hangs)."""
    import datetime
    import os
    import platform
    import subprocess
    prov = {
        "measured_at": datetime.datetime.now(
            datetime.timezone.utc).isoformat(timespec="seconds"),
        "host": platform.node(),
    }
    try:
        rev = subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"],
            capture_output=True, text=True, timeout=10,
            cwd=os.path.dirname(os.path.abspath(__file__))).stdout.strip()
        prov["git_rev"] = rev or None
    except Exception:
        prov["git_rev"] = None
    try:
        import jax
        prov["jax_version"] = jax.__version__
        if with_device:
            d = jax.devices()[0]
            prov["device_platform"] = d.platform
            prov["device_kind"] = getattr(d, "device_kind", None)
            from paddle_tpu import monitor
            prov["peak_flops_bf16"] = monitor.peak_flops_for_device(d)
    except Exception:
        pass
    return prov


def _append_result_jsonl(out):
    """Append the result line to $PADDLE_TPU_BENCH_JSONL (one JSON
    object per line) — the running artifact scripts/perf_sentinel.py
    audits for regressions. Best-effort: the bench's one guaranteed
    output stays the stdout line."""
    import os
    path = os.environ.get("PADDLE_TPU_BENCH_JSONL", "")
    if not path:
        return
    try:
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "a") as fh:
            fh.write(json.dumps(out) + "\n")
    except Exception:
        pass


def _fail_json(msg):
    """Emit the SAME JSON schema as a successful run so the driver always
    records a parseable line (r3's backend-init exception escaped main()
    and the round's only number was a raw traceback). Any stage that
    already finished contributes its REAL number instead of a zero.
    The headline stays 0.0 on failure — but the line carries a labeled
    pointer to the most recent COMMITTED on-chip measurement (the
    watcher's bench_latest_measured.json, else the r4 snapshot) so a
    wedged tunnel doesn't erase where the repo's measured state lives."""
    out = {
        "metric": "bert_base_tokens/sec/chip", "value": 0.0,
        "unit": "tokens/s", "vs_baseline": 0.0,
        "resnet50_images_per_sec": 0.0, "resnet50_vs_baseline": 0.0,
    }
    out.update(_RESULTS)
    out["error"] = msg[:500]
    try:
        import os
        here = os.path.dirname(os.path.abspath(__file__))
        for rel in ("docs/bench_latest_measured.json",
                    "docs/bench_r04_measured.json"):
            path = os.path.join(here, rel)
            if os.path.exists(path):
                with open(path) as fh:
                    snap = json.load(fh)
                keep = {k: snap[k] for k in
                        ("measured_at", "git_rev", "value", "vs_baseline",
                         "resnet50_images_per_sec", "resnet50_vs_baseline",
                         "bert_base_seq128_tokens_per_sec",
                         "bert_vs_v100_baseline_25k",
                         "resnet50_vs_v100_baseline_360", "note")
                        if k in snap}
                out["last_committed_measurement"] = keep
                out["last_committed_measurement_file"] = rel
                break
    except Exception:
        pass  # the pointer is best-effort; never break the fail line
    _append_result_jsonl(out)
    print(json.dumps(out), flush=True)


def _subprocess_probe(timeout_s=60):
    """First contact with a wedged tunnel BLOCKS UNINTERRUPTIBLY (the
    hang sits in C, so an in-process SIGALRM never fires — observed
    r4). Probe in a SUBPROCESS that an external kill can always reap;
    only touch jax in this process once the probe proves the backend
    answers. A live tunnel answers this probe in ~5-15s, so 60s is
    ample; a wedged tunnel then costs 3x60s, not 3x300s (r4 burned 15
    min of the driver's patience learning the tunnel was down)."""
    import os
    import subprocess
    import sys

    probe_py = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "scripts", "probe_tpu.py")
    try:
        proc = subprocess.run([sys.executable, probe_py],
                              capture_output=True, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired:
        return False, f"no backend response in {timeout_s}s (tunnel " \
                      "wedged: first contact blocks uninterruptibly)"
    if proc.returncode == 0 and "PROBE_OK" in proc.stdout:
        return True, proc.stdout.strip().splitlines()[-1]
    return False, (proc.stderr or proc.stdout).strip()[-300:]


def _init_backend_with_retry(attempts=3, backoff=20):
    """The axon tunnel wedges transiently: first contact can raise
    'UNAVAILABLE: TPU backend setup/compile error' — or hang forever.
    Each attempt is a subprocess probe (see _subprocess_probe); the
    in-process backend is touched only after a probe succeeds."""
    last = None
    for i in range(attempts):
        ok, msg = _subprocess_probe()
        if ok:
            try:
                import jax
                import jax.numpy as jnp
                jnp.zeros((8,), jnp.float32).block_until_ready()
                print(f"backend ok: {jax.devices()[0].platform} "
                      f"(attempt {i + 1})", flush=True)
                return True
            except Exception as e:  # transient per-connection failure:
                # clear the cached bad backend and keep retrying (an
                # in-process HANG here remains possible but the probe
                # narrowed that window to seconds)
                msg = f"probe ok but in-process init failed: " \
                      f"{type(e).__name__}: {e}"
                try:
                    from jax.extend import backend as _jeb
                    _jeb.clear_backends()
                except Exception:
                    try:
                        jax.clear_backends()  # older spelling
                    except Exception:
                        pass
        last = msg
        print(f"backend init attempt {i + 1}/{attempts} failed: {msg}",
              flush=True)
        if i + 1 < attempts:
            time.sleep(backoff * (i + 1))
    _fail_json(f"backend init failed after {attempts} attempts: {last}")
    return False


def _arm_watchdog(seconds=3300):
    """If the device tunnel is wedged (first jax op blocks forever), bail
    with a diagnostic JSON line instead of hanging past the driver's
    patience."""
    import os
    import signal

    def on_alarm(signum, frame):
        _fail_json(f"watchdog: no result within {seconds}s "
                   "(device/tunnel unresponsive)")
        os._exit(2)

    signal.signal(signal.SIGALRM, on_alarm)
    signal.alarm(seconds)


def _enable_monitoring_and_cache():
    """Persist XLA compilations across bench processes (first compile of
    a BERT-size step over the tunnel costs minutes — a cache seeded by an
    earlier run makes this one start from warm executables) and turn on
    the in-memory monitor so compiles_per_stage can ride the perf line.
    Called only AFTER backend init: importing paddle_tpu earlier would
    touch jax before the subprocess probe proved the tunnel answers."""
    from paddle_tpu import monitor
    from paddle_tpu.device import enable_compilation_cache
    if enable_compilation_cache("/tmp/paddle_tpu_xla_cache") is None:
        print("compile cache unavailable", flush=True)
    monitor.enable()  # no sink path: in-memory counters only
    # label every layer/optimizer scope in the step HLO so the hotspot
    # stage can attribute the cost ledger to real model parts
    monitor.profile.enable()


_COMPILES_SEEN = {"n": 0}


def _record_stage_compiles(stage):
    """Bank how many fresh XLA executables this stage minted (jit +
    executor compile counters) — next to throughput, the evidence that
    shape bucketing / the persistent cache keep the compile count flat."""
    try:
        from paddle_tpu import monitor
        reg = monitor.registry()
        total = int(reg.value("jit.compile", 0)) + \
            int(reg.value("executor.compile", 0)) + \
            int(reg.value("inference.compile", 0)) + \
            int(reg.value("inference.aot_warmup", 0))
    except Exception:
        return
    delta, _COMPILES_SEEN["n"] = total - _COMPILES_SEEN["n"], total
    _RESULTS.setdefault("compiles_per_stage", {})[stage] = delta


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--fast", action="store_true",
                    help="headline BERT + ResNet only (fits a brief "
                         "tunnel window; skips pipeline/long-seq "
                         "stages)")
    args = ap.parse_args()
    _arm_watchdog()
    _RESULTS["provenance"] = _provenance()  # fail lines carry it too
    if not _init_backend_with_retry():
        return
    _RESULTS["provenance"] = _provenance(with_device=True)
    _enable_monitoring_and_cache()
    _probe_pallas_kernels()
    bert_tps, bert_loss = bench_bert(measured_key="bert_mfu_measured")
    _record_stage_compiles("bert_seq128")
    # partial lines are deliberately NOT json (exactly one JSON line at
    # the end) — they leave evidence if the harness kills us mid-run
    print(f"partial bert_tokens_per_sec={bert_tps:.1f}", flush=True)
    _RESULTS.update(value=round(bert_tps, 1),
                    vs_baseline=round(bert_tps / BERT_BASELINE_TOKENS_S,
                                      3),
                    bert_loss=round(bert_loss, 4),
                    bert_mfu=_mfu(bert_tps, _bert_flops_per_token()))
    _note_mfu_divergence("bert")
    try:
        hs = bench_hotspot()  # newest capture: the BERT train step
    except Exception as e:
        print(f"hotspot stage failed: {type(e).__name__}: {e}",
              flush=True)
    else:
        if hs:
            print(f"partial hotspot_count={hs['hotspot_count']} "
                  f"attributed={hs['hotspot_attributed_frac']}",
                  flush=True)
            _RESULTS.update(hs)
    try:
        mm = bench_memory()  # same capture the hotspot stage read
    except Exception as e:
        print(f"memory stage failed: {type(e).__name__}: {e}",
              flush=True)
    else:
        if mm:
            print(f"partial memory_reconciliation="
                  f"{mm['memory_reconciliation']} "
                  f"attributed={mm['memory_attributed_frac']}",
                  flush=True)
            _RESULTS.update(mm)
    rn_ips, rn_loss = bench_resnet(measured_key="resnet50_mfu_measured")
    _record_stage_compiles("resnet50")
    print(f"partial resnet_images_per_sec={rn_ips:.1f}", flush=True)
    from paddle_tpu import monitor as _mon
    _RESULTS.update(
        resnet50_images_per_sec=round(rn_ips, 1),
        resnet50_vs_baseline=round(rn_ips / RESNET_BASELINE_IMG_S, 3),
        resnet50_loss=round(rn_loss, 4),
        resnet50_mfu=_mfu(rn_ips, _mon.RESNET50_TRAIN_FLOPS_PER_IMAGE))
    _note_mfu_divergence("resnet50")
    try:
        s50, s99, sqps, sfill = bench_serving()
    except Exception as e:
        print(f"serving bench failed: {type(e).__name__}: {e}",
              flush=True)
    else:
        print(f"partial serving_qps={sqps:.1f} p99_ms={s99:.2f}",
              flush=True)
        _RESULTS.update(serving_p50_ms=round(s50, 3),
                        serving_p99_ms=round(s99, 3),
                        serving_qps=round(sqps, 1),
                        serving_batch_fill=round(sfill, 2))
    _record_stage_compiles("serving")
    try:
        sd = bench_serving_degraded()
    except Exception as e:
        print(f"serving_degraded bench failed: {type(e).__name__}: {e}",
              flush=True)
    else:
        print(f"partial serving_degraded_goodput="
              f"{sd['serving_degraded_goodput']} "
              f"high={sd['serving_degraded_high_goodput']}", flush=True)
        _RESULTS.update(sd)
    if not args.fast:
        try:
            pipe_ips, loader_ips = bench_resnet_pipeline()
        except Exception as e:
            print(f"pipeline bench failed: {type(e).__name__}: {e}",
                  flush=True)
            pipe_ips, loader_ips = 0.0, 0.0
        _record_stage_compiles("resnet50_pipeline")
        print(f"partial pipeline_images_per_sec={pipe_ips:.1f}",
              flush=True)
        _RESULTS.update(
            resnet50_pipeline_images_per_sec=round(pipe_ips, 1),
            loader_images_per_sec=round(loader_ips, 1))
        for key, fn in (("bert_seq512_tokens_per_sec", bench_bert_seq512),
                        ("bert_seq2048_tokens_per_sec", bench_bert_long)):
            try:
                tps, _ = fn()
            except Exception as e:
                print(f"{key} bench failed: {type(e).__name__}: {e}",
                      flush=True)
                tps = 0.0
            _record_stage_compiles(key.replace("_tokens_per_sec", ""))
            print(f"partial {key}={tps:.1f}", flush=True)
            _RESULTS[key] = round(tps, 1)
            _RESULTS[key.replace("_tokens_per_sec", "_mfu")] = \
                _mfu(tps, _bert_flops_per_token())
            _note_mfu_divergence(key.replace("_tokens_per_sec", ""))
        try:
            comm = bench_collective_overlap()
        except Exception as e:
            print(f"collective_overlap bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial collective_overlap_ratio="
                  f"{comm['collective_overlap_ratio']}", flush=True)
            _RESULTS.update(comm)
        try:
            fo = bench_fused_optimizer()
        except Exception as e:
            print(f"fused_optimizer bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial fused_optimizer_bytes_reduction="
                  f"{fo['fused_optimizer_bytes_reduction']}", flush=True)
            _RESULTS.update(fo)
        try:
            pl = bench_planner()
        except Exception as e:
            print(f"planner bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial planner_chosen={pl['planner_chosen']} "
                  f"candidates={pl['planner_candidates']}", flush=True)
            _RESULTS.update(pl)
        try:
            mpl = bench_memory_plan()
        except Exception as e:
            print(f"memory_plan bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial memory_plan_picked={mpl['memory_plan_picked']} "
                  f"ceiling_multiple="
                  f"{mpl['memory_plan_ceiling_multiple']}", flush=True)
            _RESULTS.update(mpl)
        try:
            dec = bench_decode()
        except Exception as e:
            print(f"decode bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial decode_tokens_per_s="
                  f"{dec['decode_tokens_per_s']} "
                  f"speedup_x={dec['decode_speedup_x']}", flush=True)
            _RESULTS.update(dec)
        try:
            spd = bench_spec_decode()
        except Exception as e:
            print(f"spec_decode bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial decode_spec_speedup_x="
                  f"{spd['decode_spec_speedup_x']} "
                  f"accept_rate={spd['decode_accept_rate']}", flush=True)
            _RESULTS.update(spd)
        try:
            lcy = bench_lifecycle()
        except Exception as e:
            print(f"lifecycle bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial lifecycle_drain_p99_ms="
                  f"{lcy['lifecycle_drain_p99_ms']} "
                  f"soak_goodput={lcy['lifecycle_soak_goodput']}",
                  flush=True)
            _RESULTS.update(lcy)
        try:
            tlm = bench_fleet_telemetry()
        except Exception as e:
            print(f"fleet telemetry bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial fleet_agg_overhead_pct="
                  f"{tlm['fleet_agg_overhead_pct']} "
                  f"alert_latency_s="
                  f"{tlm['alert_detection_latency_s']}", flush=True)
            _RESULTS.update(tlm)
        try:
            dsg = bench_disagg()
        except Exception as e:
            print(f"disagg bench failed: "
                  f"{type(e).__name__}: {e}", flush=True)
        else:
            print(f"partial disagg_prefix_hit_rate="
                  f"{dsg['disagg_prefix_hit_rate']} "
                  f"ttft_hit_p50={dsg['disagg_ttft_hit_p50_ms']} "
                  f"tokens_per_s={dsg['disagg_tokens_per_s']}",
                  flush=True)
            _RESULTS.update(dsg)
    # ONE output schema: everything was banked into _RESULTS as its
    # stage finished (the same dict _fail_json reports from)
    result = {"metric": "bert_base_tokens/sec/chip", "unit": "tokens/s",
              **_RESULTS}
    _append_result_jsonl(result)
    print(json.dumps(result))


if __name__ == "__main__":
    try:
        main()
    except BaseException as e:  # noqa: BLE001 - last-resort diagnostic
        if isinstance(e, SystemExit):
            raise
        import traceback
        traceback.print_exc()
        _fail_json(f"{type(e).__name__}: {e}")
