"""Benchmark: BERT-base train tokens/sec/chip + ResNet-50 train images/sec
(SURVEY §6). Runs on the real chip, bf16 compute, donated buffers; prints
ONE JSON line.

Baselines (BASELINE.json "north star": within 10% of Paddle's own V100
numbers): Paddle-era V100 fp32 ResNet-50 ≈ 360 images/s; BERT-base seq128
≈ 25k tokens/s. vs_baseline is ours ÷ that reference.
"""
import json
import time

import numpy as np

BERT_BASELINE_TOKENS_S = 25000.0   # Paddle V100 BERT-base seq128 approx
RESNET_BASELINE_IMG_S = 360.0      # Paddle V100 fp32 ResNet-50 approx


def _flash_ok():
    """Probe the Pallas flash kernel fwd+bwd on the live device so a
    kernel-compile failure degrades the bench to sdpa instead of zeroing
    it."""
    try:
        import jax
        import jax.numpy as jnp
        from paddle_tpu.ops.pallas.flash_attention import _flash
        q = jnp.ones((1, 2, 128, 64), jnp.bfloat16)
        seed = jnp.zeros((2,), jnp.int32)

        def f(q):
            return _flash(q, q, q, None, None, seed, False, None, 512,
                          512, 0.1).astype(jnp.float32).sum()

        jax.grad(f)(q).block_until_ready()
        return True
    except Exception as e:  # pragma: no cover
        print(f"flash probe failed ({type(e).__name__}); sdpa fallback",
              flush=True)
        return False


def bench_bert(batch=32, seq=128, steps=20):
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as opt, jit, amp
    from paddle_tpu.models.bert import BertConfig, BertForPretraining

    pt.seed(0)
    cfg = BertConfig.base(use_flash_attention=_flash_ok())
    model = BertForPretraining(cfg)
    o = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype("i4")
    mlm = np.where(rng.rand(batch, seq) < 0.15,
                   rng.randint(0, cfg.vocab_size, (batch, seq)), -1
                   ).astype("i4")
    nsp = rng.randint(0, 2, (batch,)).astype("i4")

    def step(ids, mlm, nsp):
        with amp.auto_cast(dtype="bfloat16"):
            logits, nsp_logits = model(ids)
        loss = model.loss(logits.astype("float32"),
                          nsp_logits.astype("float32"), mlm, nsp)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    t_ids, t_mlm, t_nsp = pt.to_tensor(ids), pt.to_tensor(mlm), \
        pt.to_tensor(nsp)
    fn(t_ids, t_mlm, t_nsp)  # compile
    loss = fn(t_ids, t_mlm, t_nsp)
    loss.numpy()  # sync
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = fn(t_ids, t_mlm, t_nsp)
    loss.numpy()
    dt = (time.perf_counter() - t0) / steps
    return batch * seq / dt, float(loss.numpy())


def bench_resnet(batch=128, steps=10):
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as opt, jit, amp
    from paddle_tpu.models.resnet import resnet50

    pt.seed(0)
    model = resnet50()
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = rng.rand(batch, 3, 224, 224).astype("f4")
    y = rng.randint(0, 1000, (batch,)).astype("i4")

    def step(xb, yb):
        with amp.auto_cast(dtype="bfloat16"):
            logits = model(xb)
        loss = pt.nn.functional.cross_entropy(logits.astype("float32"), yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    tx, ty = pt.to_tensor(x), pt.to_tensor(y)
    fn(tx, ty)  # compile
    loss = fn(tx, ty)
    loss.numpy()
    t0 = time.perf_counter()
    for _ in range(steps):
        loss = fn(tx, ty)
    loss.numpy()
    dt = (time.perf_counter() - t0) / steps
    return batch / dt, float(loss.numpy())


def bench_resnet_pipeline(batch=128, steps=8):
    """ResNet fed through the REAL input pipeline (io.DataLoader over the
    C++ native batcher, csrc/core.cpp) instead of one resident batch —
    the perf evidence for the host-side arena/prefetch path."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt, jit, amp, io
    from paddle_tpu.models.resnet import resnet50

    pt.seed(0)
    model = resnet50()
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())
    rng = np.random.RandomState(0)
    n = batch * (steps + 2)
    x = rng.rand(n, 3, 224, 224).astype("f4")
    y = rng.randint(0, 1000, (n,)).astype("i4")
    ds = io.TensorDataset(x, y)
    loader = io.DataLoader(ds, batch_size=batch, shuffle=True,
                           drop_last=True, use_native=True)

    def step(xb, yb):
        with amp.auto_cast(dtype="bfloat16"):
            logits = model(xb)
        loss = pt.nn.functional.cross_entropy(logits.astype("float32"), yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    it = iter(loader)
    xb, yb = next(it)
    fn(xb, yb)  # compile
    done = 0
    t0 = time.perf_counter()
    loss = None
    for xb, yb in it:
        loss = fn(xb, yb)
        done += xb.shape[0]
        if done >= batch * steps:
            break
    loss.numpy()
    dt = time.perf_counter() - t0
    return done / dt, float(loss.numpy())


def main():
    bert_tps, bert_loss = bench_bert()
    rn_ips, rn_loss = bench_resnet()
    try:
        pipe_ips, _ = bench_resnet_pipeline()
    except Exception as e:
        print(f"pipeline bench failed: {type(e).__name__}: {e}",
              flush=True)
        pipe_ips = 0.0
    result = {
        "metric": "bert_base_tokens/sec/chip",
        "value": round(bert_tps, 1),
        "unit": "tokens/s",
        "vs_baseline": round(bert_tps / BERT_BASELINE_TOKENS_S, 3),
        "resnet50_images_per_sec": round(rn_ips, 1),
        "resnet50_vs_baseline": round(rn_ips / RESNET_BASELINE_IMG_S, 3),
        "resnet50_pipeline_images_per_sec": round(pipe_ips, 1),
        "bert_loss": round(bert_loss, 4),
        "resnet50_loss": round(rn_loss, 4),
    }
    print(json.dumps(result))


if __name__ == "__main__":
    main()
