"""Memory-plan smoke gate (tier-1-safe: tiny MLPs, CPU, ~a minute).

Exercises PR 13's planned-memory loop end to end under a virtual
``PADDLE_TPU_HBM_LIMIT_BYTES`` budget:

* **ceiling scan** — compile the same activation-heavy MLP step at
  growing batch sizes and read each no-remat predicted peak; place the
  budget so the no-remat ceiling is ``--ceil-batch`` and then train a
  model 4x past it with the policy ``plan_memory(auto=True)`` picked,
  losses staying finite (ROADMAP item 4's >=4x gate)
* **pre-flight** — the picked candidate's predicted peak is under the
  budget *before* the step recompiles, and the pick is never an
  infeasible or host-over-budget row
* **picker sanity** — a generous budget picks "none" (zero-overhead
  baseline), an impossible budget refuses every candidate with
  ValueError, and a budget only the offload rung satisfies is refused
  when ``PADDLE_TPU_HOST_MEM_LIMIT_BYTES`` can't take the paged state
  but picked once the host budget allows it
* **offload overlap** — ``fit(memory="offload")`` pages the arena's
  Adam moments through the comm-worker-thread pattern: ``offload.d2h``
  / ``offload.h2d`` spans land on a non-main trace track and the
  exposed wait is <= 40% of the blocking transfer time
* **bit-identity** — remat ("full") losses equal the no-remat run
  bit-for-bit on the ``to_static`` surface, and offload-on equals the
  same split step with paging no-opped (paging is value-preserving)

Writes the monitor JSONL to --out-dir and prints one JSON result line.
Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

DIN, HID, DEPTH = 32, 32, 10


def _build(nn, pt):
    pt.seed(0)
    layers = [nn.Linear(DIN, HID), nn.ReLU()]
    for _ in range(DEPTH):
        layers += [nn.Linear(HID, HID), nn.ReLU()]
    layers += [nn.Linear(HID, 10)]
    return nn.Sequential(*layers)


def _spans(events):
    """Pair B/E trace events into (name, tid, t0, t1) via per-tid
    stacks (spans nest properly within a thread)."""
    stacks, out = {}, []
    for ev in events:
        kind, name, tid, ts = ev[0], ev[1], ev[2], ev[3]
        if kind == "B":
            stacks.setdefault(tid, []).append((name, ts))
        elif kind == "E" and stacks.get(tid):
            name0, t0 = stacks[tid].pop()
            out.append((name0, tid, t0, ts))
        elif kind == "X":                    # complete span: ts + dur
            out.append((name, tid, ts, ts + ev[4]))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_remat_smoke")
    ap.add_argument("--ceil-batch", type=int, default=64,
                    help="target no-remat ceiling batch; the big model "
                         "trains at 4x this")
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import hapi, jit, memory_plan as mp, monitor, nn, \
        optimizer as opt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.monitor import memory, trace

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir, "remat_smoke.jsonl"))
    monitor.profile.enable()
    gates = {}

    # -- part 1: ceiling scan --------------------------------------------
    def step_at(batch, remat=None, steps=1):
        model = _build(nn, pt)
        adam = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

        @jit.to_static(models=[model], optimizers=[adam], remat=remat)
        def step(x, y):
            loss = F.cross_entropy(model(x), y)
            loss.backward()
            adam.step()
            return loss

        rng = np.random.RandomState(0)
        x = pt.to_tensor(rng.randn(batch, DIN).astype("f4"))
        y = pt.to_tensor(rng.randint(0, 10, (batch,)).astype("i8"))
        losses = [float(step(x, y).numpy())]   # warmup pays the compile
        t0 = time.perf_counter()
        for _ in range(steps):
            losses.append(float(step(x, y).numpy()))
        dt = (time.perf_counter() - t0) / steps
        return memory.report(emit_records=False), losses, dt

    ceil_b, big_b = args.ceil_batch, 4 * args.ceil_batch
    scan = {}
    for b in (ceil_b // 2, ceil_b, 2 * ceil_b, big_b):
        rep, _, _ = step_at(b)
        scan[b] = rep["predicted_peak_bytes"]
    big_rep, _, t_none = step_at(big_b)  # newest capture feeds the picker
    bc = big_rep["by_class"]
    big_act = float(bc.get("activation", 0)) + float(bc.get("remat", 0))
    full_pred = scan[big_b] - 0.9 * big_act

    # the budget: above the ceiling batch's no-remat peak (and the big
    # model's full-remat predicted peak) but below the next batch up —
    # so batch=ceil_b is the honest no-remat ceiling and the 4x model
    # only fits rematerialized
    lo = max(scan[ceil_b], full_pred)
    hi = min(scan[2 * ceil_b], scan[big_b])
    gates["ceiling_window_exists"] = lo < hi
    limit = (lo + hi) / 2.0
    os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"] = str(int(limit))

    decision = mp.plan_memory(auto=True)
    pick_row = next(r for r in decision["table"]
                    if r["name"] == decision["picked"])
    gates["preflight_peak_under_limit"] = (
        decision["predicted_peak_bytes"] <= limit)
    gates["pick_not_baseline"] = decision["picked"] != "none"
    gates["pick_feasible_and_host_ok"] = (
        pick_row["feasible"] and pick_row["host_ok"])

    # train the 4x model under the picked policy: the >=4x gate
    pol = decision["policy"]
    rep_remat, losses_big, t_remat = step_at(
        big_b, remat=pol.remat if pol.remat else None, steps=3)
    gates["trained_4x_finite"] = all(np.isfinite(losses_big))
    ceiling_multiple = float(big_b) / float(ceil_b)
    gates["ceiling_multiple>=4"] = ceiling_multiple >= 4.0

    # -- part 2: picker sanity -------------------------------------------
    os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"] = str(1 << 40)
    gates["generous_limit_picks_none"] = (
        mp.plan_memory(auto=True)["picked"] == "none")

    os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"] = "1"
    refused_all = False
    try:
        mp.plan_memory(auto=True)
    except ValueError:
        refused_all = True
    gates["impossible_limit_refused"] = refused_all

    # a budget only the offload rung satisfies: refused when the host
    # can't take the paged state, picked when it can
    table = decision["table"]
    off_row = next(r for r in table if r["name"] == "full+offload")
    full_row = next(r for r in table if r["name"] == "full")
    off_limit = (off_row["predicted_peak_bytes"]
                 + full_row["predicted_peak_bytes"]) / 2.0
    os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"] = str(int(off_limit))
    os.environ["PADDLE_TPU_HOST_MEM_LIMIT_BYTES"] = "1"
    host_refused = False
    try:
        mp.plan_memory(auto=True)
    except ValueError:
        host_refused = True
    gates["host_over_budget_refused"] = host_refused
    os.environ["PADDLE_TPU_HOST_MEM_LIMIT_BYTES"] = str(1 << 40)
    gates["host_ok_picks_offload"] = (
        mp.plan_memory(auto=True)["picked"] == "full+offload")
    del os.environ["PADDLE_TPU_HOST_MEM_LIMIT_BYTES"]
    del os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"]

    # -- part 3: offload overlap ------------------------------------------
    rng = np.random.RandomState(1)
    w = rng.randn(DIN, 3)
    fx = rng.randn(128, DIN).astype("f4")
    fy = (fx @ w).argmax(-1).astype("i4")

    def fit_offload(paging=True, epochs=2):
        pt.seed(3)
        net = nn.Sequential(nn.Linear(DIN, 256), nn.ReLU(),
                            nn.Linear(256, 256), nn.ReLU(),
                            nn.Linear(256, 3))
        m = hapi.Model(net)
        m.prepare(optimizer=opt.Adam(learning_rate=1e-3,
                                     parameters=m.parameters()),
                  loss_function=hapi.CrossEntropy())
        orig = mp.ArenaOffloader
        if not paging:
            class _Noop(mp.ArenaOffloader):
                def collect(self, arena, count_exposed=True):
                    pass

                def page_out(self, arena):
                    pass
            mp.ArenaOffloader = _Noop
        try:
            h = m.fit(TensorDataset(fx, fy), batch_size=32,
                      epochs=epochs, verbose=0, shuffle=False,
                      memory="offload")
        finally:
            mp.ArenaOffloader = orig
        return m, h["loss"]

    trace.enable()
    m_off, losses_off = fit_offload()
    events = list(trace.events())
    trace.disable()
    off = m_off._optimizer._offloader
    spans = _spans(events)
    d2h = [s for s in spans if s[0] == "offload.d2h"]
    h2d = [s for s in spans if s[0] == "offload.h2d"]
    waits = [s for s in spans if s[0] == "offload.wait"]
    main_tids = {s[1] for s in waits}
    worker_tids = {s[1] for s in d2h} | {s[1] for s in h2d}
    gates["offload_spans_present"] = bool(d2h) and bool(h2d)
    gates["offload_own_track"] = (
        bool(worker_tids) and not (worker_tids & main_tids))
    exposed_frac = (off.exposed_wait_s / off.transfer_s
                    if off.transfer_s else 0.0)
    gates["exposed_wait<=40pct"] = (
        off.transfer_s > 0 and exposed_frac <= 0.40)

    # -- part 4: bit-identity ---------------------------------------------
    _, l_none, _ = step_at(32, remat=None, steps=3)
    _, l_full, _ = step_at(32, remat="full", steps=3)
    gates["remat_bit_identical"] = l_none == l_full

    _, l_page = fit_offload(paging=True, epochs=1)
    _, l_noop = fit_offload(paging=False, epochs=1)
    gates["offload_bit_identical"] = l_page == l_noop

    monitor.disable()

    result = {
        "metric": "remat_smoke",
        "ceiling_batch": ceil_b,
        "big_batch": big_b,
        "ceiling_multiple": ceiling_multiple,
        "hbm_limit_bytes": int(limit),
        "scan_peaks": {str(k): v for k, v in scan.items()},
        "picked": decision["picked"],
        "predicted_peak_bytes": decision["predicted_peak_bytes"],
        "baseline_peak_bytes": decision["baseline_peak_bytes"],
        "measured_peak_under_policy": rep_remat["predicted_peak_bytes"],
        "remat_class_bytes": rep_remat["by_class"].get("remat", 0),
        "plan_overhead_s": decision["overhead_s"],
        "step_s_none": t_none,
        "step_s_remat": t_remat,
        "offload_exposed_wait_s": off.exposed_wait_s,
        "offload_transfer_s": off.transfer_s,
        "offload_exposed_frac": round(exposed_frac, 4),
        "offload_bytes_out": off.bytes_out,
        "offload_steps": off.steps,
        "jsonl": jsonl,
    }
    result["gates"] = gates
    result["pass"] = all(gates.values())
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
