"""Flash-attention block-size sweep on the real chip: block_q x block_k
over BERT-base-shaped attention at seq 128 / 512 / 2048, fwd+bwd.
Prints one line per config; the best (block_q, block_k) per seq length
feeds flash_attention's defaults (and the flash_min_seq crossover comes
from comparing against the sdpa row). Run:
    python -u scripts/tune_flash.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def bench_attention(seq, block_q, block_k, use_flash, batch=8, heads=12,
                    head_dim=64, steps=10):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.flash_attention import _flash

    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(batch, heads, seq, head_dim),
                    jnp.bfloat16)
    seed = jnp.zeros((2,), jnp.int32)

    if use_flash:
        def f(q):
            out = _flash(q, q, q, None, None, seed, False, None,
                         block_q, block_k, 0.0)
            return out.astype(jnp.float32).sum()
    else:
        def f(q):
            s = jnp.einsum("bhqd,bhkd->bhqk", q, q) / np.sqrt(head_dim)
            p = jax.nn.softmax(s.astype(jnp.float32), axis=-1)
            out = jnp.einsum("bhqk,bhkd->bhqd", p.astype(q.dtype), q)
            return out.astype(jnp.float32).sum()

    g = jax.jit(jax.grad(f))
    g(q).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        out = g(q)
    out.block_until_ready()
    dt = (time.perf_counter() - t0) / steps
    # attention fwd+bwd ~ 4x the 2*B*H*S^2*D fwd matmul FLOPs
    flops = 4 * 2 * batch * heads * seq * seq * head_dim
    return dt * 1e3, flops / dt / 1e12


def main():
    for seq in (128, 512, 2048):
        ms, tf = bench_attention(seq, 0, 0, use_flash=False)
        print(f"seq={seq:5d} sdpa:              {ms:8.2f} ms  "
              f"{tf:6.2f} TF/s", flush=True)
        for bq in (256, 512, 1024):
            for bk in (256, 512, 1024):
                if bq > seq * 2 or bk > seq * 2:
                    continue
                try:
                    ms, tf = bench_attention(seq, bq, bk, use_flash=True)
                    print(f"seq={seq:5d} flash bq={bq:4d} bk={bk:4d}: "
                          f"{ms:8.2f} ms  {tf:6.2f} TF/s", flush=True)
                except Exception as e:
                    print(f"seq={seq:5d} flash bq={bq:4d} bk={bk:4d}: "
                          f"FAIL {type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
