#!/usr/bin/env bash
# Zero-downtime serving lifecycle gate: preempt drain under load,
# SIGTERM fleet drain, rolling weight hot-swap, corrupt-publish refusal.
# Forces the 4-device CPU topology before any jax import.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT_DIR="${1:-/tmp/paddle_tpu_lifecycle_smoke}"

JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=4" \
python scripts/lifecycle_smoke.py --out-dir "$OUT_DIR"
