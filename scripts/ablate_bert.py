"""BERT train-step ablation on the real chip: flash / pallas-LN /
fused-adam each on-off, batch 32 and 64. Prints tok/s for each combo."""
import itertools
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def bench(batch, seq, flash, pallas_ln, fused_adam, xent, steps=16,
          inner=4, adam_multi=False):
    """`inner` real optimizer steps per compiled call (same amortization
    as bench.py): the tunnel's 30-45 ms per-dispatch overhead would
    otherwise drown the per-kernel deltas this ablation exists to
    measure."""
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt, jit, amp
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu.ops import pallas as P

    pt.seed(0)
    # flash_min_seq=0: the ablation exists to measure BOTH sides of the
    # crossover, so the seq gate must not silently reroute flash=1 rows
    # to sdpa at seq 128
    P.configure(flash_attention=flash, layer_norm=pallas_ln,
                fused_adam=fused_adam, softmax_xent=xent, flash_min_seq=0,
                fused_adam_multi=adam_multi)
    cfg = BertConfig.base(use_flash_attention=flash)
    model = BertForPretraining(cfg)
    o = opt.AdamW(learning_rate=1e-4, parameters=model.parameters())

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size,
                      (inner, batch, seq)).astype("i4")
    mlm = np.where(rng.rand(inner, batch, seq) < 0.15,
                   rng.randint(0, cfg.vocab_size, (inner, batch, seq)),
                   -1).astype("i4")
    nsp = rng.randint(0, 2, (inner, batch)).astype("i4")

    def one(ids, mlm, nsp):
        with amp.auto_cast(dtype="bfloat16"):
            logits, nsp_logits = model(ids)
        loss = model.loss(logits.astype("float32"),
                          nsp_logits.astype("float32"), mlm, nsp)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    def step(ids_k, mlm_k, nsp_k):
        loss = None
        for i in range(inner):
            loss = one(ids_k[i], mlm_k[i], nsp_k[i])
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    t_ids, t_mlm, t_nsp = pt.to_tensor(ids), pt.to_tensor(mlm), \
        pt.to_tensor(nsp)
    fn(t_ids, t_mlm, t_nsp)
    loss = fn(t_ids, t_mlm, t_nsp)
    loss.numpy()
    n_calls = max(1, steps // inner)
    t0 = time.perf_counter()
    for _ in range(n_calls):
        loss = fn(t_ids, t_mlm, t_nsp)
    loss.numpy()
    dt = (time.perf_counter() - t0) / (n_calls * inner)
    return batch * seq / dt, float(loss.numpy())


CONFIGS = [
    # (batch, flash, pallas_ln, fused_adam, softmax_xent)
    (32, 0, 0, 0, 0),
    (32, 1, 0, 0, 0),
    (32, 0, 1, 0, 0),
    (32, 0, 0, 1, 0),
    (32, 0, 0, 0, 1),
    (32, 1, 1, 1, 1),
    (64, 0, 0, 0, 0),
    (64, 1, 1, 1, 1),
]


def main():
    for batch, flash, ln, fa, xe in CONFIGS:
        try:
            tps, loss = bench(batch, 128, bool(flash), bool(ln),
                              bool(fa), bool(xe))
            print(f"batch={batch} flash={flash} ln={ln} "
                  f"adam={fa} xent={xe}: {tps:,.0f} tok/s "
                  f"loss={loss:.4f}", flush=True)
        except Exception as e:
            print(f"batch={batch} flash={flash} ln={ln} "
                  f"adam={fa} xent={xe}: FAIL {type(e).__name__}: {e}",
                  flush=True)
    # full-model multi-tensor adam row (r5): one dispatch over all params
    # vs XLA's fused update, in situ at the headline shape
    for multi in (0, 1):
        try:
            tps, _ = bench(64, 128, True, True, False, False,
                           adam_multi=bool(multi))
            print(f"batch=64 adam_multi={multi}: {tps:,.0f} tok/s",
                  flush=True)
        except Exception as e:
            print(f"batch=64 adam_multi={multi}: FAIL "
                  f"{type(e).__name__}: {e}", flush=True)
    # full-model check of the flash_min_seq=512 crossover (the sweep's
    # kernel-only verdict at 512 was a wash; this decides it in situ)
    for flash in (0, 1):
        try:
            tps, _ = bench(16, 512, bool(flash), True, False, False,
                           steps=8, inner=2)
            print(f"seq=512 batch=16 flash={flash}: {tps:,.0f} tok/s",
                  flush=True)
        except Exception as e:
            print(f"seq=512 batch=16 flash={flash}: FAIL "
                  f"{type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
