"""Memory-observability smoke gate (tier-1-safe: tiny MLP, CPU,
seconds end to end).

One 2-layer MLP + Adam ``jit.to_static`` train step feeds the buffer
liveness model; the gates assert the ISSUE's acceptance criteria
directly:

* the simulated peak reconciles with XLA's own ``memory_analysis()``
  peak within 10%
* the peak-contributor ledger is non-empty, rank-ordered, and >= 90%
  of live-at-peak bytes attribute to named framework scopes
* an injected RESOURCE_EXHAUSTED inside ``hapi.fit`` leaves an ``oom``
  flight-recorder bundle containing both ``op_ledger.json`` and
  ``memory_report.json`` (the postmortem loop)
* with a synthetic HBM budget between the smallest and largest
  candidate peak, ``planner.advise()`` marks at least one layout
  infeasible and ``plan(auto=True)`` never picks it; with an
  impossible budget every candidate is refused (the pre-flight loop)
* disabled mode stays free: with the monitor off, a step retains no
  memory report and ``trace.counter`` records nothing

Writes the monitor JSONL to --out-dir and prints one JSON result line.
Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_mem_smoke")
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import hapi, jit, monitor, nn, optimizer as opt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.monitor import memory
    from paddle_tpu.parallel import planner
    from paddle_tpu.parallel.megatron import MegatronConfig
    from paddle_tpu.resilience import faults

    os.makedirs(args.out_dir, exist_ok=True)
    os.environ["PADDLE_TPU_FLIGHT_DIR"] = os.path.join(args.out_dir, "fl")
    os.environ["PADDLE_TPU_FLIGHT_MAX"] = "64"
    jsonl = monitor.enable(os.path.join(args.out_dir, "mem_smoke.jsonl"))
    monitor.profile.enable()

    # -- part 1: reconciliation + attribution over the to_static step ------
    pt.seed(0)
    model = nn.Sequential(nn.Linear(16, args.hidden), nn.ReLU(),
                          nn.Linear(args.hidden, 10))
    adam = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

    @jit.to_static(models=[model], optimizers=[adam])
    def step(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        adam.step()
        return loss

    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(args.batch, 16).astype("f4"))
    y = pt.to_tensor(rng.randint(0, 10, (args.batch,)).astype("i8"))
    step(x, y).numpy()

    rep = memory.report(top_k=8)
    if rep is None:
        print(json.dumps({"metric": "mem_smoke", "pass": False,
                          "error": "no captured executable"}))
        return 1
    recon = rep["reconciliation"]
    ranks = [c["rank"] for c in rep["contributors"]]

    # -- part 2: injected OOM leaves the full postmortem bundle ------------
    monitor.profile.report()   # ensure the op ledger rides the flight dump
    w = rng.randn(8, 3)
    fx = rng.randn(32, 8).astype("f4")
    fy = (fx @ w).argmax(-1).astype("i4")
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.05,
                                parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    faults.inject("host_loss", step=1, exc=lambda: RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "34359738368 bytes (injected)"))
    oom_raised = False
    try:
        m.fit(TensorDataset(fx, fy), epochs=1, batch_size=8, verbose=0)
    except RuntimeError as e:
        oom_raised = "RESOURCE_EXHAUSTED" in str(e)
    finally:
        faults.clear()
    oom = memory.last_oom()
    flight_files = (sorted(os.listdir(oom["path"]))
                    if oom and oom.get("path") else [])

    # -- part 3: the pre-flight budget loop --------------------------------
    cfg = MegatronConfig(vocab_size=64, hidden=32, n_heads=4,
                         layers_per_stage=1, seq_len=16, microbatch=2,
                         n_micro=1, use_moe=False)
    free = planner.advise(n_devices=8, cfg=cfg)
    peaks = sorted(r["peak_hbm_bytes"] for r in free)
    limit = (peaks[0] + peaks[-1]) / 2.0
    os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"] = str(limit)
    table = planner.advise(n_devices=8, cfg=cfg)
    flags = [r["feasible"] for r in table]
    p = planner.plan(auto=True, cfg=cfg, n_devices=8)
    chosen = planner.last_decision()["chosen"]
    chosen_row = next(r for r in p.advice
                      if dict(r["sizes"]) == dict(chosen))
    os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"] = "1"
    all_refused = False
    try:
        planner.plan(auto=True, cfg=cfg, n_devices=8)
    except ValueError:
        all_refused = True
    del os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"]

    # -- part 4: disabled mode retains nothing -----------------------------
    monitor.disable()
    memory.reset()
    from paddle_tpu.monitor import trace
    trace.counter("hbm.predicted[x]", {"bytes": 1})
    model2 = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
    adam2 = opt.Adam(learning_rate=1e-3, parameters=model2.parameters())

    @jit.to_static(models=[model2], optimizers=[adam2])
    def step2(x, y):
        loss = F.cross_entropy(model2(x), y)
        loss.backward()
        adam2.step()
        return loss

    step2(pt.to_tensor(np.ones((2, 4), dtype="f4")),
          pt.to_tensor(np.zeros((2,), dtype="i8"))).numpy()
    disabled_clean = (memory.last_report() is None
                      and trace.events() == [])

    result = {
        "metric": "mem_smoke",
        "label": rep["label"],
        "predicted_peak_bytes": rep["predicted_peak_bytes"],
        "xla_peak_bytes": rep["xla_peak_bytes"],
        "reconciliation": (round(recon, 4) if recon else None),
        "attributed_frac": round(rep["attributed_frac"], 4),
        "contributors": len(rep["contributors"]),
        "n_donated": rep["n_donated"],
        "by_class": rep["by_class"],
        "oom_flight": oom.get("path") if oom else None,
        "flight_files": flight_files,
        "hbm_limit_probe": limit,
        "infeasible_candidates": flags.count(False),
        "chosen_sizes": dict(chosen),
        "jsonl": jsonl,
    }
    gates = {
        "peak_reconciles_10pct": (recon is not None
                                  and abs(recon - 1.0) <= 0.10),
        "attributed_frac>=0.9": rep["attributed_frac"] >= 0.90,
        "ledger_nonempty_ranked": (
            len(rep["contributors"]) >= 3
            and ranks == list(range(1, len(ranks) + 1))),
        "oom_raised_and_recorded": (oom_raised and oom is not None
                                    and oom["where"] == "fit"),
        "oom_bundle_complete": ("memory_report.json" in flight_files
                                and "op_ledger.json" in flight_files),
        "advise_marks_infeasible": (True in flags and False in flags),
        "auto_pick_feasible": bool(chosen_row["feasible"]),
        "all_infeasible_refused": all_refused,
        "disabled_mode_clean": disabled_clean,
    }
    result["gates"] = gates
    result["pass"] = all(gates.values())
    print(memory.format_table(rep), file=sys.stderr)
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
