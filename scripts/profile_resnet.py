"""ResNet-50 perf triage on the real chip: where does the step time go?

Times (a) conv-only microbench ceiling, (b) jitted fwd, (c) fwd+bwd,
(d) full train step, at batch 128/256, bf16. Prints a small table.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def timeit(fn, *args, steps=10):
    fn(*args)[0].block_until_ready() if isinstance(fn(*args), tuple) else \
        fn(*args).block_until_ready()
    out = fn(*args)
    (out[0] if isinstance(out, tuple) else out).block_until_ready()
    t0 = time.perf_counter()
    for _ in range(steps):
        out = fn(*args)
    (out[0] if isinstance(out, tuple) else out).block_until_ready()
    return (time.perf_counter() - t0) / steps


def conv_ceiling(batch, layout="NHWC"):
    """Single biggest-FLOP resnet conv (layer3 3x3): measures achievable
    conv throughput in the given layout."""
    if layout == "NHWC":
        x = jnp.ones((batch, 28, 28, 256), jnp.bfloat16)
        w = jnp.ones((3, 3, 256, 256), jnp.bfloat16)
        dn = ("NHWC", "HWIO", "NHWC")
    else:
        x = jnp.ones((batch, 256, 28, 28), jnp.bfloat16)
        w = jnp.ones((256, 256, 3, 3), jnp.bfloat16)
        dn = ("NCHW", "OIHW", "NCHW")

    @jax.jit
    def f(x, w):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=dn)

    dt = timeit(f, x, w)
    flops = 2 * batch * 28 * 28 * 256 * 256 * 9
    return flops / dt / 1e12


def model_stages(batch, data_format="NCHW"):
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt, jit, amp
    from paddle_tpu.models.resnet import resnet50

    pt.seed(0)
    model = resnet50(data_format=data_format)
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())
    rng = np.random.RandomState(0)
    shape = (batch, 3, 224, 224) if data_format == "NCHW" else \
        (batch, 224, 224, 3)
    x = rng.rand(*shape).astype("f4")
    y = rng.randint(0, 1000, (batch,)).astype("i4")
    tx, ty = pt.to_tensor(x), pt.to_tensor(y)

    def fwd(xb, yb):
        with amp.auto_cast(dtype="bfloat16"):
            logits = model(xb)
        return pt.nn.functional.cross_entropy(
            logits.astype("float32"), yb)

    def step(xb, yb):
        with amp.auto_cast(dtype="bfloat16"):
            logits = model(xb)
        loss = pt.nn.functional.cross_entropy(logits.astype("float32"), yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    ffwd = jit.to_static(fwd, models=[model])
    fstep = jit.to_static(step, models=[model], optimizers=[o])

    def t(f):
        f(tx, ty)
        r = f(tx, ty)
        r.numpy()
        t0 = time.perf_counter()
        for _ in range(8):
            r = f(tx, ty)
        r.numpy()
        return (time.perf_counter() - t0) / 8

    tf = t(ffwd)
    ts = t(fstep)
    return tf, ts


def main():
    for batch in (128, 256):
        ceil = conv_ceiling(batch, "NHWC")
        ceil_nchw = conv_ceiling(batch, "NCHW")
        tf, ts = model_stages(batch)
        tfh, tsh = model_stages(batch, data_format="NHWC")
        tr_flops = 3 * 4.1e9 * batch  # fwd+bwd ~3x fwd, 4.1 GFLOP/img
        print(f"batch={batch}: conv_NHWC={ceil:.1f} conv_NCHW={ceil_nchw:.1f}"
              f" TF/s  nchw_step={ts*1e3:.1f}ms ({batch/ts:.0f} img/s)  "
              f"nhwc_step={tsh*1e3:.1f}ms ({batch/tsh:.0f} img/s)  "
              f"step_TF/s={tr_flops/ts/1e12:.1f}", flush=True)


if __name__ == "__main__":
    main()
