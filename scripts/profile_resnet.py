"""ResNet-50 perf triage on the real chip: where does the step time go?

Measurement rules for this environment (docs/perf_r04.md): repeated
identical dispatches are served from cache and `block_until_ready` is
not a real sync, so (a) the conv/matmul ceilings use a fori_loop
dependency CHAIN with a scalar D2H at the end, and (b) the model rows
time full train steps (optimizer state advances every call) with a
final `.numpy()`. The per-call fixed overhead (~66 ms) is reported
separately via a 16-vs-64-iteration chain solve.

Also writes a jax.profiler trace of the train step and prints the
per-op-family table via utils.profiler.summarize_trace — the view that
found BN's reduce chains at ~70% of the r4 step.
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np
import jax
import jax.numpy as jnp


def chained(make_body, x0, iters):
    """Time `iters` chained applications with one scalar D2H sync."""
    @jax.jit
    def chain(x):
        def body(i, x):
            return make_body(x)
        out = jax.lax.fori_loop(0, iters, body, x)
        return jnp.ravel(out)[0]

    float(chain(x0))  # compile + warm
    t0 = time.perf_counter()
    float(chain(x0))
    return time.perf_counter() - t0


def conv_ceiling(batch, layout="NHWC"):
    """Marginal time of the biggest-FLOP resnet conv (layer3 3x3) from
    a 16-vs-64 chain solve; returns (marginal_ms, TF/s, fixed_ms)."""
    rng = np.random.RandomState(0)
    if layout == "NHWC":
        x = jnp.asarray(rng.randn(batch, 28, 28, 256) * 0.1, jnp.bfloat16)
        w = jnp.asarray(rng.randn(3, 3, 256, 256) * 0.01, jnp.bfloat16)
        dn = ("NHWC", "HWIO", "NHWC")
    else:
        x = jnp.asarray(rng.randn(batch, 256, 28, 28) * 0.1, jnp.bfloat16)
        w = jnp.asarray(rng.randn(256, 256, 3, 3) * 0.01, jnp.bfloat16)
        dn = ("NCHW", "OIHW", "NCHW")

    def body(x):
        return jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME", dimension_numbers=dn) * 0.01

    # min-of-3 per point: the t64−t16 difference being solved for
    # (~29 ms) is smaller than one bad HTTP-dispatch jitter spike
    t16 = min(chained(body, x, 16) for _ in range(3))
    t64 = min(chained(body, x, 64) for _ in range(3))
    marginal = (t64 - t16) / 48
    fixed = t16 - 16 * marginal
    flops = 2 * batch * 28 * 28 * 256 * 256 * 9
    return marginal * 1e3, flops / marginal / 1e12, fixed * 1e3


def train_step_rate(batch, data_format="NCHW", inner=8, trace_dir=None):
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt, jit, amp
    from paddle_tpu.models.resnet import resnet50

    pt.seed(0)
    model = resnet50(data_format=data_format)
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())
    rng = np.random.RandomState(0)
    shape = (inner, batch, 3, 224, 224) if data_format == "NCHW" else \
        (inner, batch, 224, 224, 3)
    x = rng.rand(*shape).astype("f4")
    y = rng.randint(0, 1000, (inner, batch)).astype("i4")

    def one(xb, yb):
        with amp.auto_cast(dtype="bfloat16"):
            logits = model(xb)
        loss = pt.nn.functional.cross_entropy(logits.astype("float32"), yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    def step(x_k, y_k):
        loss = None
        for i in range(inner):
            loss = one(x_k[i], y_k[i])
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    tx, ty = pt.to_tensor(x), pt.to_tensor(y)
    fn(tx, ty)
    fn(tx, ty).numpy()
    t0 = time.perf_counter()
    for _ in range(2):
        loss = fn(tx, ty)
    loss.numpy()
    dt = (time.perf_counter() - t0) / (2 * inner)
    if trace_dir:
        with jax.profiler.trace(trace_dir):
            fn(tx, ty).numpy()
    return batch / dt, dt * 1e3


def main():
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/paddle_tpu_xla_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    from paddle_tpu import monitor
    monitor.enable()          # in-memory counters + xla capture
    monitor.profile.enable()  # named scopes -> attributable step HLO
    for layout in ("NHWC", "NCHW"):
        ms, tf, fixed = conv_ceiling(128, layout)
        print(f"conv3x3 b128 {layout}: marginal {ms:.3f} ms "
              f"({tf:.0f} TF/s), fixed/dispatch {fixed:.0f} ms",
              flush=True)
    trace_dir = "/tmp/paddle_tpu_profile_resnet"
    for batch, df, td in ((128, "NCHW", trace_dir), (128, "NHWC", None),
                          (256, "NCHW", None)):
        ips, ms = train_step_rate(batch, df, trace_dir=td)
        print(f"train b{batch} {df}: {ms:.1f} ms/step ({ips:,.0f} img/s)",
              flush=True)
    from paddle_tpu.utils.profiler import summarize_trace
    summarize_trace(trace_dir, steps=8)  # the traced call runs inner=8
    # the attributed cost ledger of the newest captured train step:
    # which region tops the fusion menu, at what attributed fraction —
    # the trace view above says WHAT is slow, this says WHOSE it is
    rep = monitor.profile.report(top_k=12, emit_records=False)
    if rep is not None:
        print(flush=True)
        print(monitor.profile.format_table(rep, top_k=12), flush=True)


if __name__ == "__main__":
    main()
