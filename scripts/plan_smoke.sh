#!/usr/bin/env bash
# CI gate for the profile-guided auto-sharding planner: one
# MegatronConfig(mesh_plan=MEGATRON_RULES) line must reproduce the
# hand-written dp/tp megatron layout bit-identically (specs, losses,
# final params), hapi fit(mesh_plan=) must mint zero extra executables
# vs the plan-free fit, the advisor table must be non-empty and
# rank-stable, and its predicted-fastest layout must be the
# measured-fastest in a dp8-vs-dp2tp4 A/B on 8 virtual CPU devices.
# Tier-1-safe: tiny configs, CPU, seconds.
#
# Usage: scripts/plan_smoke.sh [out_dir]
# The monitor JSONL lands in out_dir (default
# /tmp/paddle_tpu_plan_smoke); the last stdout line is one JSON
# result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_plan_smoke}"
JAX_PLATFORMS=cpu python scripts/plan_smoke.py --out-dir "$OUT_DIR"
