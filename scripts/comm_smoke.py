"""Gradient-communication smoke gate (tier-1-safe: 8 virtual CPU
devices, tiny MLP, ~a minute).

Drives the same explicit-DDP training loop through every
``grad_sync`` mode of ``parallel.overlap.GradSyncScheduler`` and
asserts the ISSUE's acceptance criteria directly against measurements
— never against intent:

* **overlap is visible**: >= 1 ``comm.bucket_reduce`` span (on the
  ``comm-worker`` thread track) OVERLAPPING a ``ddp.backward`` span on
  the main thread in the exported Chrome trace
* **overlap is effective**: exposed wire seconds (time the step loop
  spent blocked on unfinished reduces) in overlap+lag-1 mode <= 60% of
  the exact-discrete baseline
* **no compile tax**: overlap mode mints exactly as many bucket-reduce
  executables as exact mode, and none after the first step
* **quantization converges**: int8 bucketed sync reaches the exact
  mode's loss within 1% over --steps steps
* **wire bytes honest**: comm.bytes_wire / comm.bytes_logical ratios
  match the int8 (~4x) and packed-int4 (~8x) wire formats
* **lag-1 is resumable**: an overlap+lag-1 run checkpointed mid-flight
  (scheduler state_dict carries the pending synced grads) restores and
  finishes BIT-IDENTICAL to the uninterrupted run

Writes trace.json + the monitor JSONL to --out-dir as CI artifacts and
prints one JSON result line (bench.py's ``collective_overlap`` stage
re-reads it). Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _mlp_init(rng, d_in=64, hidden=256):
    s = 1.0 / np.sqrt(d_in)
    return {
        "w1": (rng.randn(d_in, hidden) * s).astype("f4"),
        "b1": np.zeros(hidden, "f4"),
        "w2": (rng.randn(hidden, hidden) / np.sqrt(hidden)).astype("f4"),
        "b2": np.zeros(hidden, "f4"),
        "w3": (rng.randn(hidden, 1) / np.sqrt(hidden)).astype("f4"),
        "b3": np.zeros(1, "f4"),
    }


def _spans(trace_dict, name):
    open_by_tid, out = {}, []
    for ev in trace_dict["traceEvents"]:
        if ev.get("name") != name:
            continue
        if ev["ph"] == "B":
            open_by_tid.setdefault(ev["tid"], []).append(ev["ts"])
        elif ev["ph"] == "E" and open_by_tid.get(ev["tid"]):
            out.append((ev["tid"], open_by_tid[ev["tid"]].pop(),
                        ev["ts"]))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_comm_smoke")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--bucket-bytes", type=int, default=1 << 16)
    ap.add_argument("--ratio-ceiling", type=float, default=0.60)
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import monitor
    from paddle_tpu.io import CheckpointManager
    from paddle_tpu.parallel import collective, overlap

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir, "comm_smoke.jsonl"))
    pt.seed(0)

    mesh = collective.make_mesh({"dp": 8})
    rng = np.random.RandomState(0)
    params0 = _mlp_init(rng)
    x = rng.randn(args.batch, 64).astype("f4")
    y = (x[:, :1] * 0.5 + np.sin(x[:, 1:2])).astype("f4")
    batch = (jnp.asarray(x), jnp.asarray(y))

    def loss_fn(params, batch):
        xb, yb = batch
        h = jnp.tanh(xb @ params["w1"] + params["b1"])
        h = jnp.tanh(h @ params["w2"] + params["b2"])
        pred = h @ params["w3"] + params["b3"]
        return jnp.mean((pred - yb) ** 2)

    lvg = overlap.local_value_and_grad(loss_fn, mesh)
    sgd = jax.jit(lambda p, g: jax.tree_util.tree_map(
        lambda a, b: a - 0.05 * b, p, g))

    def run(mode, steps, bits=8, async_apply=None, sched=None,
            params=None, start=0, cm=None, save_at=None):
        """One training run; returns (params, losses, sched,
        compiles_after_first, warm_exposed_s). warm_exposed_s excludes
        the first two steps so first-call XLA compiles never pollute
        the exposed-wire measurement."""
        if sched is None:
            sched = overlap.GradSyncScheduler(
                mode=mode, mesh=mesh, bits=bits,
                bucket_bytes=args.bucket_bytes, async_apply=async_apply)
        params = jax.tree_util.tree_map(jnp.asarray,
                                        params if params is not None
                                        else params0)
        losses, compiles_after_first, warm_mark = [], None, 0.0
        for i in range(start, steps):
            with monitor.trace.span("ddp.step", step=i, mode=mode):
                with monitor.trace.span("ddp.backward", step=i):
                    loss, grads = lvg(params, batch)
                    jax.block_until_ready(loss)
                synced = sched.reduce(grads)
                if synced is not None:
                    params = sgd(params, synced)
            losses.append(float(np.asarray(loss).mean()))
            if compiles_after_first is None:
                compiles_after_first = sched.compiled_buckets
            if i - start == 1:
                warm_mark = sched.exposed_wait_s
            if cm is not None and save_at is not None and i == save_at:
                cm.save(i, extra={
                    "params": {k: np.asarray(jax.device_get(v))
                               for k, v in params.items()},
                    "sched": sched.state_dict()})
        return (params, losses, sched, compiles_after_first,
                sched.exposed_wait_s - warm_mark)

    result = {"metric": "collective_overlap", "jsonl": jsonl}

    # -- exact baseline (discrete f32 reduce, wire time fully exposed) --
    monitor.reset()
    p_exact, l_exact, s_exact, _, exposed_exact = run("exact", args.steps)
    bytes_logical = int(monitor.registry().value("comm.bytes_logical", 0))
    s_exact.shutdown()

    # -- quantized int8: loss parity + wire bytes --
    monitor.reset()
    p_q8, l_q8, s_q8, _, _ = run("quantized", args.steps, bits=8)
    bytes_wire_q8 = int(monitor.registry().value("comm.bytes_wire", 0))
    bytes_logical_q8 = int(
        monitor.registry().value("comm.bytes_logical", 0))
    s_q8.shutdown()

    # -- quantized int4: wire bytes only (few steps) --
    monitor.reset()
    _, _, s_q4, _, _ = run("quantized", 4, bits=4)
    bytes_wire_q4 = int(monitor.registry().value("comm.bytes_wire", 0))
    bytes_logical_q4 = int(
        monitor.registry().value("comm.bytes_logical", 0))
    s_q4.shutdown()

    # -- overlap + lag-1, traced --
    monitor.reset()
    monitor.trace.enable()
    _, l_ov, s_ov, ov_after_first, exposed_overlap = run(
        "overlap", args.steps)
    s_ov.flush()  # the in-flight final gradient
    ov_compiles = s_ov.compiled_buckets
    bucket_count = len(s_ov.last_plan or ())
    s_ov.shutdown()
    trace = monitor.trace.export_chrome_trace()
    trace_path = monitor.trace.export_chrome_trace(
        os.path.join(args.out_dir, "trace.json"))
    monitor.trace.disable()

    reduces = _spans(trace, "comm.bucket_reduce")
    backwards = _spans(trace, "ddp.backward")
    overlapping = sum(
        1 for rt, r0, r1 in reduces for bt, b0, b1 in backwards
        if rt != bt and r0 < b1 and b0 < r1)

    # -- lag-1 checkpoint/restore bit-identity --
    ck_dir = os.path.join(args.out_dir, "ckpt")
    cm = CheckpointManager(ck_dir, max_to_keep=2)
    k, total = 7, 15
    monitor.reset()
    pa, _, sa, _, _ = run("overlap", total, cm=cm, save_at=k)
    sa.flush()
    sa.shutdown()
    state = cm.restore(step=k)
    sb = overlap.GradSyncScheduler(
        mode="overlap", mesh=mesh, bucket_bytes=args.bucket_bytes)
    sb.set_state_dict(state["extra"]["sched"])
    pb, _, sb, _, _ = run("overlap", total, sched=sb,
                          params=state["extra"]["params"], start=k + 1)
    sb.flush()
    sb.shutdown()
    resume_identical = all(
        np.array_equal(np.asarray(jax.device_get(pa[kk])),
                       np.asarray(jax.device_get(pb[kk])))
        for kk in pa)

    ratio = exposed_overlap / max(exposed_exact, 1e-12)
    rel_err = abs(l_q8[-1] - l_exact[-1]) / max(abs(l_exact[-1]), 1e-12)
    q8_reduction = bytes_logical_q8 / max(bytes_wire_q8, 1)
    q4_reduction = bytes_logical_q4 / max(bytes_wire_q4, 1)

    result.update({
        "steps": args.steps,
        "exposed_wire_exact_s": round(exposed_exact, 4),
        "exposed_wire_overlap_s": round(exposed_overlap, 4),
        "overlap_ratio": round(ratio, 4),
        "bucket_count": bucket_count,
        "exact_compiles": s_exact.compiled_buckets,
        "overlap_compiles": ov_compiles,
        "overlap_compiles_after_first_step": ov_after_first,
        "comm_bytes_logical": bytes_logical,
        "comm_bytes_wire_int8": bytes_wire_q8,
        "comm_bytes_wire_int4": bytes_wire_q4,
        "wire_reduction_int8_x": round(q8_reduction, 2),
        "wire_reduction_int4_x": round(q4_reduction, 2),
        "loss_exact": round(l_exact[-1], 6),
        "loss_quantized": round(l_q8[-1], 6),
        "quantized_loss_rel_err": round(rel_err, 5),
        "bucket_reduce_spans": len(reduces),
        "backward_spans": len(backwards),
        "overlapping_pairs": overlapping,
        "lag1_resume_identical": bool(resume_identical),
        "trace_json": trace_path,
    })
    gates = {
        f"overlap_exposed<= {args.ratio_ceiling}x_exact":
            ratio <= args.ratio_ceiling,
        "reduce_overlaps_backward>=1": overlapping >= 1,
        "zero_extra_recompiles_vs_exact":
            ov_compiles == s_exact.compiled_buckets,
        "no_compiles_after_first_step":
            ov_compiles == ov_after_first,
        "buckets>=2": bucket_count >= 2,
        "quantized_loss_within_1pct": rel_err <= 0.01,
        "int8_wire_reduction>=3x": q8_reduction >= 3.0,
        "int4_wire_reduction>=6x": q4_reduction >= 6.0,
        "lag1_resume_bit_identical": resume_identical,
    }
    result["gates"] = gates
    result["pass"] = all(gates.values())
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
