"""Fleet telemetry smoke: 4-process decode fleet, 2 injected anomalies.

The gate behind docs/observability.md "Fleet telemetry": four worker
processes run real decode load against small ``GenerateEngine``s, each
publishing versioned metric snapshots into a shared telemetry directory
(``monitor/fleet.py``). One worker is a straggler (a ``replica_slow``
fault sleeps inside its batch tick), one mints a burst of post-warmup
compiles (a genuine ``jit.to_static`` shape storm). The parent runs the
consumer side of the plane — ``FleetAggregator`` + ``AnomalyDetector``
+ ``AlertManager`` + a (stub-owned) ``ServingSupervisor`` — and asserts
the ISSUE's acceptance bar end to end:

* merged counters equal the per-worker oracle (ints exactly, float
  counters to 1e-9 — summation order is the only difference);
* merged p50/p99 land within one histogram bucket of the nearest-rank
  percentile over the union of every worker's raw events, for both a
  seeded oracle histogram and the live ``serving.ttft_ms`` traffic;
* exactly the two expected alerts fire AND resolve —
  ``straggler(worker-1)`` and ``compile_storm(worker-2)`` — each naming
  the offending source + series, and both appear in the supervisor's
  decision ledger (``anomaly`` decisions / ``anomalies`` context);
* the goodput ledger reconciles to wall time within 5% around a loop
  with a real checkpoint save and a measured input stall;
* snapshot publishing costs <= 1% of a worker's wall time
  (``fleet_agg_overhead_pct``, banked for the perf sentinel along with
  ``alert_detection_latency_s``);
* with the monitor disabled nothing publishes: zero files, no thread.

Prints one JSON result line (last stdout line) for bench.py.

Usage::

    python scripts/telemetry_smoke.py [--out-dir DIR]
    python scripts/telemetry_smoke.py --fast   # shorter phases
"""
import argparse
import json
import math
import os
import subprocess
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_WORKERS = 4
STRAGGLER = 1           # worker-1 drags its decode ticks
STORM = 2               # worker-2 mints a compile burst mid-run
STRAGGLER_DELAY_S = 0.03
STORM_SHAPES = 16       # distinct shapes -> that many jit.compile
ORACLE_SERIES = "fleetsmoke.latency_ms"
ORACLE_EVENTS = 200     # seeded observations per worker


# ---------------------------------------------------------------------------
# worker side


def _drip(eng, rng, until, ttfts, slow_tick=False):
    """Submit single small requests back-to-back until the deadline —
    every worker stays *continuously* active so the detector always has
    >= min_sources live decode series to compare."""
    while time.perf_counter() < until:
        plen = int(rng.randint(1, 13))
        prompt = rng.randint(1, 31, size=plen).tolist()
        new = int(rng.randint(2, 7))
        r = eng.make_request(prompt, max_new_tokens=new, eos_token=None)
        eng.submit_request(r)
        r.future.result(timeout=120)
        rec = (r.trace.ctx.record() if r.trace is not None else None)
        if rec and rec.get("ttft_ms") is not None:
            ttfts.append(float(rec["ttft_ms"]))


def _mint_compile_storm():
    """A real compile storm: one tiny jitted fn called across
    STORM_SHAPES distinct input shapes, each a fresh executable."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import jit

    fn = jit.to_static(lambda x: (x * 2.0 + 1.0).mean())
    for n in range(3, 3 + STORM_SHAPES):
        fn(pt.to_tensor(np.zeros((1, n), dtype="float32")))


def worker_main(args):
    import random

    import numpy as np
    from paddle_tpu import monitor, serving
    from paddle_tpu.monitor import fleet
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving import metrics as smetrics
    from paddle_tpu.serving.metrics import LATENCY_BUCKETS_MS

    idx = args.worker
    tdir = args.telemetry_dir
    monitor.enable(telemetry_dir=tdir)      # source via env, set by parent
    wall0 = time.perf_counter()

    # the seeded oracle histogram: raw values dumped alongside so the
    # parent can nearest-rank the union and check the merged estimate
    rnd = random.Random(1000 + idx)
    raw = [round(math.exp(rnd.gauss(2.0, 1.2)), 6)
           for _ in range(ORACLE_EVENTS)]
    h = monitor.histogram(ORACLE_SERIES, buckets=LATENCY_BUCKETS_MS)
    for v in raw:
        h.observe(v)

    model = serving.demo_model(vocab=64, dim=64, heads=2, layers=1,
                               max_len=48, seed=1)
    smetrics.reset_windows()
    eng = serving.GenerateEngine(
        model, slots=4, page=16, factor=2.0, max_len=48,
        prompt_buckets=(4, 16), queue_depth=64, refill="continuous",
        shed=False, start=True)
    eng.warmup()

    # barrier: warmup compiles land *before* the parent arms the
    # detector, so the only post-go compile burst is the injected one
    with open(os.path.join(tdir, f"ready-{idx}"), "w") as fh:
        fh.write(str(os.getpid()))
    go = os.path.join(tdir, "go")
    deadline = time.perf_counter() + 120
    while not os.path.exists(go):
        if time.perf_counter() > deadline:
            raise RuntimeError("parent never opened the barrier")
        time.sleep(0.05)

    rng = np.random.RandomState(100 + idx)
    ttfts = []

    # phase A: anomalous
    if idx == STRAGGLER:
        faults.inject("replica_slow", delay=STRAGGLER_DELAY_S,
                      times=None)
    t_a = time.perf_counter() + args.phase_s
    storm_at = time.perf_counter() + min(1.0, args.phase_s / 3.0)
    stormed = False
    while time.perf_counter() < t_a:
        _drip(eng, rng, min(t_a, time.perf_counter() + 0.5), ttfts)
        if idx == STORM and not stormed \
                and time.perf_counter() >= storm_at:
            _mint_compile_storm()
            stormed = True
    faults.clear()

    # phase B: clean tail — the anomalies must RESOLVE, not just fire
    _drip(eng, rng, time.perf_counter() + args.phase_s, ttfts)
    eng.close()

    wall_s = time.perf_counter() - wall0
    stats = fleet.publisher_stats() or {"writes": 0, "write_cpu_s": 0.0}
    export = monitor.registry().export_snapshot()
    result = {
        "worker": idx,
        "wall_s": round(wall_s, 3),
        "publisher": stats,
        # CPU burned publishing vs run wall: the wall span of a write
        # on a saturated box mostly measures waiting for the GIL, i.e.
        # time the process spent doing useful decode work
        "overhead_pct": round(100.0 * stats["write_cpu_s"]
                              / max(wall_s, 1e-9), 4),
        "oracle_raw": raw,
        "ttfts": ttfts,
        "counters": export["counters"],
    }
    tmp = args.result + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(result, fh)
    monitor.disable()       # final snapshot lands before the rename:
    os.replace(tmp, args.result)  # result visible => snapshot final
    return 0


# ---------------------------------------------------------------------------
# parent side


class _StubOwner:
    """The minimum MultiDeviceEngine surface a non-scaling supervisor
    tick touches — lets the smoke run the REAL decision ledger without
    standing up a replica fleet in the parent."""
    inflight_timeout_s = 1.0
    _replicas = ()

    def _refresh_hedge_delay(self, p99_ms):
        pass


def _bucket_index(bounds, v):
    for i, b in enumerate(bounds):
        if v <= b:
            return i
    return len(bounds)


def _nearest_rank(values, q):
    s = sorted(values)
    i = min(len(s) - 1, int(round(q * (len(s) - 1))))
    return s[i]


def _check(checks, name, ok, detail):
    checks[name] = {"ok": bool(ok), "detail": detail}
    tag = "ok" if ok else "FAIL"
    print(f"[telemetry_smoke] {tag:>4}  {name}: {detail}",
          file=sys.stderr)


def _check_disabled_mode(checks):
    """Monitor never enabled => the fleet plane must not exist: no
    snapshot files, no publisher thread."""
    with tempfile.TemporaryDirectory() as d:
        code = (
            "import os, threading, paddle_tpu.monitor as m,"
            " paddle_tpu.monitor.fleet as f\n"
            "m.counter('x').inc(); m.emit(kind='noop')\n"
            "assert not m.enabled()\n"
            "assert not f.publisher_active()\n"
            "assert f.publisher_stats() is None\n"
            "threads = [t.name for t in threading.enumerate()]\n"
            "assert not any('telemetry' in n or 'fleet' in n"
            " for n in threads), threads\n"
            f"print(len(os.listdir({d!r})))\n")
        out = subprocess.run(
            [sys.executable, "-c", code],
            env={**os.environ, "JAX_PLATFORMS": "cpu",
                 "PADDLE_TPU_TELEMETRY_DIR": d},
            capture_output=True, text=True, timeout=120)
        ok = out.returncode == 0 and out.stdout.strip() == "0"
        _check(checks, "disabled_zero_files", ok,
               f"rc={out.returncode} files={out.stdout.strip()!r} "
               f"{out.stderr.strip()[-200:]}")


def _check_counters(checks, agg, results):
    """Merged counters vs the oracle: the sum of every worker's final
    export. Integers exactly; float counters to 1e-9 (the aggregator
    and the oracle sum in different orders)."""
    oracle = {}
    for res in results:
        for name, v in res["counters"].items():
            oracle[name] = oracle.get(name, 0) + v
    bad = []
    for name, want in sorted(oracle.items()):
        got = agg.value(name, default=None)
        if got is None:
            bad.append(f"{name}: missing from merge")
        elif isinstance(want, int) and isinstance(got, int):
            if got != want:
                bad.append(f"{name}: {got} != {want}")
        elif not math.isclose(float(got), float(want), rel_tol=1e-9,
                              abs_tol=1e-9):
            bad.append(f"{name}: {got} !~ {want}")
    _check(checks, "merged_counters_exact", not bad,
           f"{len(oracle)} counters" if not bad else "; ".join(bad[:5]))


def _check_percentiles(checks, agg, results):
    from paddle_tpu.serving.metrics import LATENCY_BUCKETS_MS
    bounds = list(LATENCY_BUCKETS_MS)
    for label, key, series in (
            ("oracle", "oracle_raw", ORACLE_SERIES),
            ("ttft", "ttfts", "serving.ttft_ms")):
        union = [v for res in results for v in res[key]]
        h = agg.histogram(series)
        if h is None or not union:
            _check(checks, f"percentile_{label}", False,
                   f"{series}: no merged histogram / no events")
            continue
        details, ok = [], True
        if label == "oracle":
            exact = (h["count"] == len(union)
                     and math.isclose(h["sum"], sum(union),
                                      rel_tol=1e-6))
            ok &= exact
            details.append(f"count/sum exact={exact}")
        for q in (0.50, 0.99):
            want = _nearest_rank(union, q)
            got = agg.percentile(series, q)
            di = abs(_bucket_index(bounds, got)
                     - _bucket_index(bounds, want))
            ok &= di <= 1
            details.append(f"p{int(q * 100)} est={got:.3g} "
                           f"true={want:.3g} d_bucket={di}")
        _check(checks, f"percentile_{label}", ok, "; ".join(details))


def _run_goodput_check(checks):
    """The ledger around a real mini train loop: sleep-compute, one
    measured input stall, one real CheckpointManager save. Wall time
    must reconcile against compute + the ranked losses within 5%."""
    import numpy as np
    from paddle_tpu import io, monitor

    with tempfile.TemporaryDirectory() as ckdir:
        mon = monitor.StepMonitor(items_per_step=1, label="goodput_smoke",
                                  goodput=True)
        cm = io.CheckpointManager(ckdir, max_to_keep=1)
        state = {"w": np.zeros((64, 64), dtype="float32")}
        for step in range(6):
            t0 = time.perf_counter()
            time.sleep(0.02)                      # "compute"
            if step == 2:                         # measured input stall
                s0 = time.perf_counter()
                time.sleep(0.05)
                monitor.counter("prefetch.stall_seconds").inc(
                    time.perf_counter() - s0)
            if step == 3:                         # real checkpoint save
                cm.save(step, extra={"state": state})
            mon.step()
            del t0
        summary = mon.summary()
    g = summary.get("goodput") or {}
    wall = g.get("wall_s", 0.0)
    recon = abs(wall - (g.get("compute_s", 0.0) + g.get("lost_s", 0.0)))
    ok = wall > 0 and recon <= 0.05 * wall
    cats = {row["category"]: row["seconds"] for row in g.get("lost", [])}
    ok &= cats.get("checkpoint", 0.0) > 0.0
    ok &= cats.get("input_stall", 0.0) >= 0.04
    ok &= 0.0 < g.get("goodput_fraction", 0.0) < 1.0
    _check(checks, "goodput_reconciles", ok,
           f"wall={wall:.3f}s residual={recon:.4f}s "
           f"goodput={g.get('goodput_fraction')} "
           f"ckpt={cats.get('checkpoint', 0):.4f}s "
           f"stall={cats.get('input_stall', 0):.4f}s")


def parent_main(args):
    from paddle_tpu import monitor
    from paddle_tpu.monitor import alerts, fleet
    from paddle_tpu.serving.supervisor import ServingSupervisor

    checks = {}
    out_dir = args.out_dir or tempfile.mkdtemp(prefix="telemetry_smoke_")
    os.makedirs(out_dir, exist_ok=True)
    tdir = os.path.join(out_dir, "telemetry")
    os.makedirs(tdir, exist_ok=True)

    # the parent is the consumer, not a source: no publisher here
    os.environ.pop("PADDLE_TPU_TELEMETRY_DIR", None)
    monitor.enable(os.path.join(out_dir, "telemetry_smoke.jsonl"))

    # -- spawn the fleet -------------------------------------------------
    procs, result_paths = [], []
    for i in range(N_WORKERS):
        rpath = os.path.join(out_dir, f"worker-{i}.json")
        result_paths.append(rpath)
        env = {**os.environ,
               "JAX_PLATFORMS": "cpu",
               "PADDLE_TPU_TELEMETRY_SOURCE": f"worker-{i}",
               "PADDLE_TPU_TELEMETRY_INTERVAL_S": "0.2"}
        env.pop("PADDLE_TPU_TELEMETRY_DIR", None)
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--worker", str(i), "--telemetry-dir", tdir,
             "--result", rpath, "--phase-s", str(args.phase_s)],
            env=env, stdout=subprocess.DEVNULL))

    # barrier: wait for every engine to warm up, then open the gate —
    # detection latency is measured from HERE (anomalies start with go)
    deadline = time.time() + 300
    while len([p for p in os.listdir(tdir)
               if p.startswith("ready-")]) < N_WORKERS:
        if time.time() > deadline:
            for p in procs:
                p.kill()
            raise RuntimeError("workers never reached the barrier")
        if any(p.poll() not in (None, 0) for p in procs):
            raise RuntimeError("a worker died before the barrier")
        time.sleep(0.1)
    with open(os.path.join(tdir, "go"), "w") as fh:
        fh.write("go")
    t_go = time.perf_counter()

    # -- the consumer plane ---------------------------------------------
    agg = fleet.FleetAggregator(tdir, staleness_ttl_s=60.0)
    mgr = alerts.AlertManager(rules=[], finding_resolve_after_s=2.0)
    # queue/accept shapes are unit-tested; this gate is straggler +
    # storm, exactly — thresholds park the other two out of reach
    det = alerts.AnomalyDetector(
        manager=mgr, warmup_ticks=1, compile_delta_threshold=6,
        compile_window_s=4.0, z_threshold=3.0, min_sources=3,
        accept_rate_floor=-1.0, queue_min_depth=10 ** 9)
    owner = _StubOwner()
    sup = ServingSupervisor(owner, start=False, scale=False)

    first_fired = {}
    t_end = time.time() + 240
    while time.time() < t_end:
        agg.scrape()
        det.update(agg.source_snapshots())
        firing = mgr.tick()
        sup.tick(owner)
        for a in firing:
            first_fired.setdefault(a["name"],
                                   time.perf_counter() - t_go)
        workers_done = all(p.poll() is not None for p in procs)
        states = [a["state"] for a in mgr.alerts()]
        if workers_done and first_fired \
                and all(s == "resolved" for s in states):
            break
        time.sleep(0.25)
    for p in procs:
        p.wait(timeout=60)
    alerts.clear_findings()

    rcs = [p.returncode for p in procs]
    _check(checks, "workers_exit_clean", all(rc == 0 for rc in rcs),
           f"rcs={rcs}")
    results = []
    for rpath in result_paths:
        with open(rpath) as fh:
            results.append(json.load(fh))

    # -- the acceptance bar ----------------------------------------------
    agg.scrape()        # final snapshots (written at worker disable)
    _check_counters(checks, agg, results)
    _check_percentiles(checks, agg, results)

    expected = {f"straggler(worker-{STRAGGLER})",
                f"compile_storm(worker-{STORM})"}
    hist = mgr.history
    fired = [h for h in hist if h["state"] == "firing"]
    resolved = {h["name"] for h in hist if h["state"] == "resolved"}
    names = {h["name"] for h in fired}
    ok = (names == expected and len(fired) == 2
          and expected <= resolved)
    _check(checks, "exactly_two_alerts_fire_and_resolve", ok,
           f"fired={sorted(names)} x{len(fired)} "
           f"resolved={sorted(resolved & expected)}")

    ok = all(any(h["name"] == n and h.get("source") and h.get("series")
                 for h in fired) for n in expected)
    _check(checks, "alerts_name_replica_and_series", ok,
           str([{k: h.get(k) for k in ('name', 'source', 'series')}
                for h in fired]))

    anomaly_decisions = {d.get("anomaly") for d in sup.decisions
                         if d["decision"] == "anomaly"}
    _check(checks, "supervisor_decision_context",
           expected <= anomaly_decisions,
           f"anomaly decisions={sorted(anomaly_decisions)}")

    overhead = max(res["overhead_pct"] for res in results)
    _check(checks, "aggregation_overhead", overhead <= 1.0,
           f"max worker publish overhead {overhead:.4f}% (<= 1%)")

    detect_s = min(first_fired.values()) if first_fired else None
    _run_goodput_check(checks)
    _check_disabled_mode(checks)

    n_ok = sum(1 for c in checks.values() if c["ok"])
    result = {
        "ok": n_ok == len(checks),
        "checks_passed": n_ok,
        "checks_total": len(checks),
        "checks": {k: v["ok"] for k, v in checks.items()},
        "fleet_agg_overhead_pct": round(overhead, 4),
        "alert_detection_latency_s": (round(detect_s, 3)
                                      if detect_s is not None else None),
        "sources": len(agg.sources()),
        "fired": sorted(names),
    }
    monitor.emit(kind="telemetry_smoke", **{
        k: v for k, v in result.items() if k != "checks"})
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok"] else 1


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--worker", type=int, default=None)
    ap.add_argument("--telemetry-dir", default=None)
    ap.add_argument("--result", default=None)
    ap.add_argument("--out-dir", default=None)
    ap.add_argument("--phase-s", type=float, default=3.5,
                    help="seconds per phase (anomalous, then clean)")
    ap.add_argument("--fast", action="store_true",
                    help="shorter phases (CI smoke)")
    args = ap.parse_args()
    if args.fast:
        args.phase_s = min(args.phase_s, 2.5)
    if args.worker is not None:
        return worker_main(args)
    return parent_main(args)


if __name__ == "__main__":
    sys.exit(main())
