"""Adam update ablation on the real chip: XLA's own fusion vs the
per-tensor Pallas kernel vs the r5 multi-tensor (one-dispatch) kernel,
over the real BERT-base parameter set (~110M params, 200+ tensors).

Methodology (docs/perf_r04.md): each variant jits a fori-free python
chain of `iters` sequential updates with state threading, so the tunnel
dispatch cost amortizes and the device actually executes every update
(outputs feed inputs; nothing is dead-code eliminated).

The decision rule for _AUTO_ON['fused_adam_multi'] is printed at the
end: multi wins only if it beats the XLA baseline.

Run: python -u scripts/bench_adam_multi.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def param_set():
    """The real BERT-base pretraining parameter shapes."""
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    import paddle_tpu as pt
    pt.seed(0)
    model = BertForPretraining(BertConfig.base())
    shapes = [tuple(p.data.shape) for p in model.parameters()
              if not p.stop_gradient]
    del model
    return shapes


def bench(mode, shapes, iters=10):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.fused_adam import (
        adam_step, fused_adam_update_multi)

    rng = np.random.RandomState(0)
    ps = [jnp.asarray(rng.randn(*s).astype("f4") * 0.02) for s in shapes]
    gs = [jnp.asarray(rng.randn(*s).astype("f4") * 1e-3) for s in shapes]
    ms = [jnp.zeros(s, jnp.float32) for s in shapes]
    vs = [jnp.zeros(s, jnp.float32) for s in shapes]

    def one(ps, ms, vs, b1p, b2p):
        if mode == "multi":
            nps, nms, nvs = fused_adam_update_multi(
                ps, gs, ms, vs, 1e-4, b1p, b2p)
        else:
            nps, nms, nvs = [], [], []
            for p, g, m, v in zip(ps, gs, ms, vs):
                np_, nm, nv = adam_step(p, g, m, v, 1e-4, b1p, b2p,
                                        use_fused=(mode == "pallas"))
                nps.append(np_)
                nms.append(nm)
                nvs.append(nv)
        return nps, nms, nvs

    @jax.jit
    def chain(ps, ms, vs):
        b1p, b2p = jnp.float32(1.0), jnp.float32(1.0)
        for _ in range(iters):
            b1p, b2p = b1p * 0.9, b2p * 0.999
            ps, ms, vs = one(ps, ms, vs, b1p, b2p)
        return ps, ms, vs

    out = chain(ps, ms, vs)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = chain(ps, ms, vs)
    jax.block_until_ready(out)
    dt = (time.perf_counter() - t0) / iters
    n = sum(int(np.prod(s)) for s in shapes)
    # ideal traffic: read p,g,m,v + write p,m,v = 7 x 4B x n
    gbs = 7 * 4 * n / dt / 1e9
    return dt * 1e3, gbs


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/paddle_tpu_xla_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    shapes = param_set()
    n = sum(int(np.prod(s)) for s in shapes)
    print(f"param set: {len(shapes)} tensors, {n / 1e6:.1f}M params",
          flush=True)
    results = {}
    for mode in ("xla", "pallas", "multi"):
        try:
            ms, gbs = bench(mode, shapes)
            results[mode] = ms
            print(f"adam {mode:>6}: {ms:8.3f} ms/step  "
                  f"({gbs:6.0f} GB/s update-traffic equiv)", flush=True)
        except Exception as e:
            print(f"adam {mode:>6}: FAIL {type(e).__name__}: {e}",
                  flush=True)
    if "xla" in results and "multi" in results:
        win = results["multi"] < results["xla"]
        rel = (results["xla"] - results["multi"]) / results["xla"] * 100
        print(f"multi vs xla: {rel:+.1f}%  -> "
              f"{'FLIP fused_adam_multi AUTO-ON' if win else 'keep auto-off'}",
              flush=True)


if __name__ == "__main__":
    main()
