#!/usr/bin/env bash
# CI gate for the step-pipelining acceptance criteria: a ragged-tail
# epoch must run at >= 10 steps per XLA compile (shape bucketing) with
# zero blocking device_gets (async fetch). Tier-1-safe: tiny MLP, 30
# steps, CPU backend, a few seconds end to end.
#
# Usage: scripts/perf_smoke.sh [out_dir]
# The monitor JSONL stream lands in out_dir (default
# /tmp/paddle_tpu_perf_smoke) as the CI artifact; the last stdout line
# is one JSON result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_perf_smoke}"
JAX_PLATFORMS=cpu python scripts/perf_smoke.py --out-dir "$OUT_DIR"
