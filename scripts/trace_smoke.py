"""Span-tracing smoke gate (tier-1-safe: tiny MLP fit, CPU, seconds).

Drives a short ``hapi.Model.fit`` with device prefetch and tracing on,
exports the timeline, and asserts the ISSUE's acceptance criteria
directly against the Chrome trace JSON:

* the export is loadable trace-event JSON (every record carries
  ph/name/pid/tid/ts; B/E events balance per thread)
* >= 2 named thread tracks — the prefetch producer runs on its own
  thread, so a correct trace shows it separately from the step loop
* >= 1 ``prefetch.produce`` span OVERLAPPING a ``fit.step`` span on a
  different thread (the pipelining picture the tracer exists to draw)
* disabled mode adds ZERO events — span() must be a flag check, not a
  recorder

Writes trace.json + the monitor JSONL to --out-dir as CI artifacts and
prints one JSON result line. Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _spans(trace_dict, name):
    """Pair B/E events into (tid, t0, t1) intervals for one span name."""
    open_by_tid, out = {}, []
    for ev in trace_dict["traceEvents"]:
        if ev.get("name") != name:
            continue
        if ev["ph"] == "B":
            open_by_tid.setdefault(ev["tid"], []).append(ev["ts"])
        elif ev["ph"] == "E" and open_by_tid.get(ev["tid"]):
            out.append((ev["tid"], open_by_tid[ev["tid"]].pop(), ev["ts"]))
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_trace_smoke")
    ap.add_argument("--epochs", type=int, default=2)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--n", type=int, default=128)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import hapi, io, monitor, nn, optimizer as opt

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir, "trace_smoke.jsonl"))
    monitor.trace.enable()

    pt.seed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(args.n, 8).astype("f4")
    y = (x.sum(-1) > 0).astype("i4")
    ds = io.TensorDataset(x, y)

    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.05,
                                parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    m.fit(ds, batch_size=args.batch, epochs=args.epochs, verbose=0,
          shuffle=False, prefetch=2)

    trace = monitor.trace.export_chrome_trace()  # dict form for the gates
    path = monitor.trace.export_chrome_trace(
        os.path.join(args.out_dir, "trace.json"))

    events = trace["traceEvents"]
    real = [e for e in events if e.get("ph") != "M"]
    bad = [e for e in real
           if not all(k in e for k in ("ph", "name", "pid", "tid", "ts"))]
    tids = {e["tid"] for e in real}

    produce = _spans(trace, "prefetch.produce")
    steps = _spans(trace, "fit.step")
    overlaps = sum(1 for pt_, p0, p1 in produce for st, s0, s1 in steps
                   if pt_ != st and p0 < s1 and s0 < p1)

    # disabled mode must be a no-op: same buffer, zero new events
    monitor.trace.disable()
    before = len(monitor.trace.events())
    with monitor.trace.span("must.not.record"):
        pass
    monitor.trace.instant("must.not.record.either")
    added = len(monitor.trace.events()) - before

    result = {
        "metric": "trace_smoke",
        "events": len(real),
        "thread_tracks": len(tids),
        "produce_spans": len(produce),
        "step_spans": len(steps),
        "overlapping_pairs": overlaps,
        "malformed_events": len(bad),
        "disabled_added_events": added,
        "trace_json": path,
        "jsonl": jsonl,
    }
    gates = {
        "valid_chrome_trace": len(bad) == 0 and len(real) > 0,
        "thread_tracks>=2": len(tids) >= 2,
        "step_spans>=1": len(steps) >= 1,
        "produce_overlaps_step>=1": overlaps >= 1,
        "disabled_adds_no_events": added == 0,
    }
    result["gates"] = gates
    result["pass"] = all(gates.values())
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
