#!/usr/bin/env bash
# Disaggregated serving gate: prefill/decode split bit-parity through a
# mid-stream drain, prefix-cache TTFT split, per-pool SLO autoscale,
# and goodput under a hung prefill replica.
# Forces the 2-device CPU topology before any jax import.
set -euo pipefail
cd "$(dirname "$0")/.."

OUT_DIR="${1:-/tmp/paddle_tpu_disagg_smoke}"

JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=2" \
python scripts/disagg_smoke.py --out-dir "$OUT_DIR"
