"""Zero-downtime serving lifecycle gate (tier-1-safe: tiny model, CPU).

Four scenarios against decode fleets on forced-CPU devices, gating the
ISSUE 18 acceptance criteria:

* **preempt-replica drain** — an injected ``preempt_replica`` notice
  lands on 1 of 3 replicas mid-load: the supervisor flips it to
  ``draining`` and migrates its queued + in-flight streams to peers.
  Gates: 100% completion, zero lost futures, every sampled stream
  bit-identical to the fault-free reference, /healthz shows the
  replica as ``draining`` (not ``open``) while the fleet still admits.
* **SIGTERM fleet drain** — a (simulated) process SIGTERM broadcast
  drains every replica: in-flight work completes, subsequent submits
  shed with ``NoHealthyReplicaError``. Repeated drain/undrain cycles
  bank ``drain_p99_ms``.
* **rolling hot-swap** — ``swap_weights`` rolls a same-shape weight
  publish through the fleet under continuous load. Gates: zero dropped
  requests, zero post-warmup executables, both weight versions appear
  in the reqtrace records, a checkpoint-sourced swap lands too.
* **corrupt publish** — an injected ``publish_corrupt`` garbles one
  committed shard: quorum validation refuses the swap, quarantines the
  publish, and the serving version never moves.

Prints one JSON result line; exit code 0 iff every gate passes.
Run via scripts/lifecycle_smoke.sh (which forces the CPU topology
before jax imports).
"""
import argparse
import json
import os
import signal
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _model(seed=1):
    from paddle_tpu import serving
    return serving.demo_model(vocab=32, dim=32, heads=2, layers=2,
                              max_len=64, seed=seed)


def _workload(n, seed=0):
    """(prompt, max_new, seed) triples — the same list drives the
    reference engine and the fleet, so streams are comparable 1:1."""
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        plen = int(rng.randint(4, 13))
        prompt = rng.randint(1, 31, size=plen).astype(np.int32)
        out.append((prompt, 8 + int(rng.randint(0, 5)), 100 + i))
    return out


def _fleet(model, n_dev, **kw):
    import jax
    from paddle_tpu import serving
    kw.setdefault("slots", 4)
    kw.setdefault("page", 16)
    kw.setdefault("max_len", 48)
    kw.setdefault("prompt_buckets", (16,))
    kw.setdefault("queue_depth", 256)
    return serving.MultiDecodeEngine(
        model, devices=jax.local_devices()[:n_dev], **kw)


def _reference_streams(workload):
    """Fault-free single-engine run: the bit-identity oracle."""
    from paddle_tpu import serving
    eng = serving.GenerateEngine(_model(), slots=4, page=16, max_len=48,
                                 prompt_buckets=(16,), queue_depth=256)
    eng.warmup()
    futs = [eng.submit(p, max_new_tokens=m, seed=s,
                       sampling={"temperature": 0.8})
            for p, m, s in workload]
    ref = [np.asarray(f.result(30)).tolist() for f in futs]
    eng.close()
    return ref


def scenario_preempt_drain(args):
    """preempt_replica on 1 of 3 replicas mid-load: drain + migrate,
    zero loss, bit-identical streams."""
    from paddle_tpu.resilience import faults

    workload = _workload(args.requests)
    ref = _reference_streams(workload)

    # hang detection off (60s): benign queue buildup must not trip a
    # failover mid-scenario — this gate is about the drain path only
    eng = _fleet(_model(), 3, supervisor_interval_s=0.05,
                 inflight_timeout_ms=60000.0)
    eng.warmup()
    eng.start()
    spec = faults.inject("preempt_replica", replica=1, times=1)

    futs, errors = [], []
    rng = np.random.RandomState(7)
    for i, (p, m, s) in enumerate(workload):
        try:
            futs.append(eng.submit(p, max_new_tokens=m, seed=s,
                                   sampling={"temperature": 0.8}))
        except Exception as e:   # noqa: BLE001 - counted
            futs.append(None)
            errors.append(repr(e))
        time.sleep(float(rng.exponential(0.004)))

    got, lost = [], 0
    for f in futs:
        if f is None:
            got.append(None)
            continue
        try:
            got.append(np.asarray(f.result(30)).tolist())
        except Exception as e:   # noqa: BLE001 - counted
            got.append(None)
            errors.append(repr(e))
        if not f.done():
            lost += 1

    health = eng.health()
    rep1 = health["replicas"][1]
    decisions = [d["decision"] for d in eng.supervisor.decisions]
    lifecycle = eng._lifecycle
    stats = eng.stats()
    eng.close(drain=False, timeout=2.0)
    faults.clear()

    identical = sum(1 for a, b in zip(ref, got) if a == b)
    ok = sum(1 for g in got if g is not None)
    return {
        "submitted": len(workload),
        "ok": ok,
        "errors": errors[:5],
        "fault_fired": spec.fired,
        "identical_streams": identical,
        "replica1_state": rep1["state"],
        "decisions": decisions[-8:],
        "lifecycle": lifecycle,
        "draining_replicas": stats["draining_replicas"],
        "gates": {
            "fault_injected": spec.fired >= 1,
            "drain_decided": "drain" in decisions
                             or (lifecycle or {}).get("event") == "drain",
            "completed_100pct": ok == len(workload) and not errors,
            "zero_lost_futures": lost == 0,
            "streams_bit_identical": identical == len(workload),
            "health_shows_draining": rep1["state"] == "draining"
                                     and rep1["breaker"] != "open",
            "fleet_still_admitting": not health["all_open"],
        },
    }


def scenario_sigterm_drain(args):
    """Simulated SIGTERM drains the whole fleet: in-flight completes,
    post-drain submits shed; drain cycles bank drain_p99_ms."""
    from paddle_tpu import serving
    from paddle_tpu.resilience import preempt

    workload = _workload(24, seed=3)
    eng = _fleet(_model(), 2, supervise=False)
    eng.warmup()
    eng.start()

    # warm round: first dispatches pay one-time jax/async costs that
    # would otherwise dominate the first timed drain cycle
    for f in [eng.submit(p, max_new_tokens=m, seed=s,
                         sampling={"temperature": 0.8})
              for p, m, s in workload[:4]]:
        f.result(30)

    drain_ms = []
    # repeated drain/undrain cycles (direct API) for the latency metric
    for cycle in range(4):
        futs = [eng.submit(p, max_new_tokens=m, seed=s,
                           sampling={"temperature": 0.8})
                for p, m, s in workload[cycle * 5:cycle * 5 + 5]]
        t0 = time.monotonic()
        eng.drain_fleet(reason=f"cycle{cycle}")
        drained = eng.drain_wait(timeout_s=20.0)
        drain_ms.append((time.monotonic() - t0) * 1e3)
        assert drained
        for f in futs:
            f.result(30)
        for r in eng._replicas:
            eng.undrain_replica(r, reason=f"cycle{cycle}")

    # the real broadcast path: handler.request(SIGTERM) -> notify() ->
    # every live fleet drains
    inflight = [eng.submit(p, max_new_tokens=m, seed=s,
                           sampling={"temperature": 0.8})
                for p, m, s in workload[20:]]
    handler = preempt.PreemptionHandler(signals=())
    t0 = time.monotonic()
    handler.request(signal.SIGTERM)
    completed = 0
    for f in inflight:
        try:
            f.result(30)
            completed += 1
        except Exception:   # noqa: BLE001 - gated below
            pass
    drained = eng.drain_wait(timeout_s=20.0)
    drain_ms.append((time.monotonic() - t0) * 1e3)
    shed = False
    try:
        eng.submit(workload[0][0], max_new_tokens=4)
    except serving.NoHealthyReplicaError:
        shed = True
    except Exception:   # noqa: BLE001 - wrong error type fails the gate
        pass
    health = eng.health()
    eng.close(drain=False, timeout=2.0)

    drain_sorted = sorted(drain_ms)
    p99 = drain_sorted[min(len(drain_sorted) - 1,
                           int(0.99 * len(drain_sorted)))]
    return {
        "drain_cycles": len(drain_ms),
        "drain_p99_ms": round(p99, 3),
        "drain_ms": [round(v, 3) for v in drain_ms],
        "inflight_completed": completed,
        "gates": {
            "sigterm_drained_fleet": drained
                and all(r["draining"] for r in health["replicas"]),
            "inflight_completed": completed == len(inflight),
            "post_drain_submit_sheds": shed,
            "fleet_reads_all_open": health["all_open"],
        },
    }


def scenario_rolling_swap(args):
    """swap_weights under load: zero drops, zero new executables, both
    versions stamped into records; checkpoint-sourced swap lands."""
    import jax
    from paddle_tpu.io import sharded
    from paddle_tpu.serving import reqtrace

    reqtrace.reset()
    eng = _fleet(_model(seed=1), 2, supervise=False)
    eng.warmup()
    eng.start()
    n_exec0 = sum(e.executables()[0] for e in eng.engines)

    workload = _workload(args.requests, seed=11)
    results, errors = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def client(k):
        rng = np.random.RandomState(k)
        for p, m, s in workload[k::2]:
            if stop.is_set():
                return
            try:
                r = np.asarray(
                    eng.submit(p, max_new_tokens=m, seed=s,
                               sampling={"temperature": 0.8}).result(30))
                with lock:
                    results.append(r)
            except Exception as e:   # noqa: BLE001 - counted
                with lock:
                    errors.append(repr(e))
            time.sleep(float(rng.exponential(0.004)))

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(2)]
    for t in threads:
        t.start()
    time.sleep(0.15)
    v1 = eng.swap_weights(_model(seed=9).state)
    for t in threads:
        t.join()

    n_exec1 = sum(e.executables()[0] for e in eng.engines)
    versions = {rec.get("weights_version")
                for rec in reqtrace.recent() if rec is not None}

    # checkpoint-sourced swap: publish the tree, validate-then-swap
    with tempfile.TemporaryDirectory() as d:
        ck = os.path.join(d, "publish-1.sharded")
        sharded.save_state(ck, jax.device_get(_model(seed=5).state))
        v2 = eng.swap_weights(ck)
    health = eng.health()
    eng.close(drain=False, timeout=2.0)

    dropped = len(errors)
    return {
        "completed": len(results),
        "dropped": dropped,
        "swap_dropped": dropped,
        "errors": errors[:5],
        "versions_seen": sorted(v for v in versions if v is not None),
        "exec_before": n_exec0,
        "exec_after": n_exec1,
        "final_version": health["weights_version"],
        "gates": {
            "zero_dropped_requests": dropped == 0
                and len(results) == len(workload),
            "zero_new_executables": n_exec1 == n_exec0,
            "both_versions_served": {0, 1} <= versions,
            "live_swap_versioned": v1 == 1,
            "checkpoint_swap_landed": v2 == 2
                and health["weights_version"] == 2,
            "no_replica_left_draining":
                not any(r["draining"] for r in health["replicas"]),
        },
    }


def scenario_corrupt_publish(args):
    """publish_corrupt garbles a committed shard: the swap is refused,
    the publish quarantined, the serving version unchanged."""
    import jax
    from paddle_tpu import monitor
    from paddle_tpu.io import sharded
    from paddle_tpu.resilience import faults

    eng = _fleet(_model(seed=1), 2, supervise=False)
    eng.warmup()
    eng.start()
    f = eng.submit(np.arange(1, 7, dtype=np.int32), max_new_tokens=4)
    f.result(30)

    refused = quarantined = False
    why = None
    with tempfile.TemporaryDirectory() as d:
        ck = os.path.join(d, "publish-bad.sharded")
        sharded.save_state(ck, jax.device_get(_model(seed=13).state))
        spec = faults.inject("publish_corrupt", times=1)
        try:
            eng.swap_weights(ck)
        except ValueError as e:
            refused = True
            why = str(e)
        quarantined = os.path.isdir(ck + ".corrupt")
    refusals = int(monitor.registry().value(
        "serving.lifecycle.swap_refused", 0))
    version = eng.weights_version
    still_serving = np.asarray(
        eng.submit(np.arange(1, 7, dtype=np.int32),
                   max_new_tokens=4).result(30)) is not None
    eng.close(drain=False, timeout=2.0)
    faults.clear()

    return {
        "refused": refused,
        "why": (why or "")[:160],
        "quarantined": quarantined,
        "refusal_count": refusals,
        "version": version,
        "gates": {
            "fault_injected": spec.fired >= 1,
            "corrupt_publish_refused": refused,
            "publish_quarantined": quarantined,
            "version_unchanged": version == 0,
            "refusal_counted": refusals >= 1,
            "fleet_kept_serving": still_serving,
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir",
                    default="/tmp/paddle_tpu_lifecycle_smoke")
    ap.add_argument("--requests", type=int, default=48,
                    help="per-scenario request scale")
    args = ap.parse_args()

    from paddle_tpu import monitor
    from paddle_tpu.serving import metrics as smetrics

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "lifecycle_smoke.jsonl"))

    result = {"jsonl": jsonl}
    t0 = time.perf_counter()
    for name, fn in (("preempt_drain", scenario_preempt_drain),
                     ("sigterm_drain", scenario_sigterm_drain),
                     ("rolling_swap", scenario_rolling_swap),
                     ("corrupt_publish", scenario_corrupt_publish)):
        smetrics.reset_windows()
        result[name] = fn(args)
    result["wall_s"] = round(time.perf_counter() - t0, 3)
    result["drain_p99_ms"] = result["sigterm_drain"]["drain_p99_ms"]
    result["swap_dropped"] = result["rolling_swap"]["swap_dropped"]

    gates = {}
    for name in ("preempt_drain", "sigterm_drain", "rolling_swap",
                 "corrupt_publish"):
        for g, v in result[name]["gates"].items():
            gates[f"{name}.{g}"] = bool(v)
    result["gates"] = gates
    result["ok"] = all(gates.values())
    monitor.emit(kind="lifecycle_smoke",
                 **{k: v for k, v in result.items() if k != "jsonl"})
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
