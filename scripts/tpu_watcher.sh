#!/bin/bash
# Tunnel watcher: probe the axon TPU in a killable subprocess every
# 10 min; on recovery run the bench battery once (warms the persistent
# XLA compile cache so the driver's recorded run starts from warm
# executables) and log everything to /tmp/tpu_watcher/.
# Usage: nohup bash scripts/tpu_watcher.sh &
set -u
OUT=/tmp/tpu_watcher
mkdir -p "$OUT"
cd "$(dirname "$0")/.."

probe() {
    timeout -k 10 240 python -c "
import jax, jax.numpy as jnp
jnp.zeros((8,), jnp.float32).block_until_ready()
print('PROBE_OK', jax.devices()[0].platform)
" 2>/dev/null | grep -q PROBE_OK
}

while true; do
    if probe; then
        echo "$(date -Is) tunnel ALIVE" >> "$OUT/status.log"
        echo "$(date -Is) running battery" >> "$OUT/status.log"
        python bench.py > "$OUT/bench.log" 2>&1
        python scripts/bench_int8.py > "$OUT/int8.log" 2>&1
        python -u scripts/bench_pallas_bn.py > "$OUT/pallas_bn.log" 2>&1
        python -u scripts/profile_resnet.py > "$OUT/profile_resnet.log" 2>&1
        python -u scripts/ablate_bert.py > "$OUT/ablate.log" 2>&1
        echo "$(date -Is) battery done; exiting (single-shot: a looping" \
             "watcher could hold the chip when the driver's recorded" \
             "bench runs)" >> "$OUT/status.log"
        exit 0
    else
        echo "$(date -Is) tunnel DEAD" >> "$OUT/status.log"
        sleep 600
    fi
done
