#!/bin/bash
# Tunnel watcher: probe the axon TPU in a killable subprocess every few
# minutes; whenever the tunnel answers, run the bench battery
# (scripts/watcher_battery.py), which atomically refreshes
# docs/bench_latest_measured.json and warms the persistent XLA compile
# cache so the driver's recorded bench.py run starts from warm
# executables.
#
# r4 lesson: a single-shot watcher that exits after one battery misses
# later windows; a free-running loop could hold the chip when the
# driver's recorded bench runs. So: loop, but cap at MAX_BATTERIES
# successful batteries, never START a battery that could still be
# running past MAX_RUNTIME, space batteries >= BATTERY_GAP apart, and
# honor a stop file (checked during sleeps too).
# Usage: nohup bash scripts/tpu_watcher.sh &
set -u
OUT=/tmp/tpu_watcher
mkdir -p "$OUT"
cd "$(dirname "$0")/.."
# WATCHER_START overrides the anchor (epoch seconds) so a restarted
# watcher keeps its cutoffs relative to the ROUND start, not the
# restart time
START=${WATCHER_START:-$(date +%s)}
MAX_RUNTIME=$((11 * 3600 + 1200))  # probe up to ~T+11h20m (round is
                             # ~12h; the r4 chip window opened in the
                             # final hours, so the watcher must stay
                             # alive into them without ever letting a
                             # battery overlap the driver's round-end
                             # bench)
BATTERY_TIMEOUT=7500         # watcher_battery.py's own deadline is
                             # 7200s; +300s slack so the battery's
                             # bounded skip logic, not SIGKILL, ends it
FAST_AFTER=$((8 * 3600))     # past T+8h, batteries run the FAST
                             # profile (bench --fast + top ablations,
                             # 3300s budget) so a late window still
                             # fits before the cutoff
FAST_TIMEOUT=3600
MAX_BATTERIES=3
BATTERY_GAP=4500             # >= 75 min between batteries
BATTERIES=0

log() { echo "$(date -Is) $*" >> "$OUT/status.log"; }

probe() {
    timeout -k 10 90 python scripts/probe_tpu.py 2>/dev/null \
        | grep -q PROBE_OK
}

# Sleep in short slices so the stop file stays responsive.
nap() {
    local remaining=$1
    while (( remaining > 0 )); do
        [ -f "$OUT/stop" ] && return 1
        local slice=$(( remaining < 30 ? remaining : 30 ))
        sleep "$slice"
        remaining=$(( remaining - slice ))
    done
    return 0
}

log "watcher started (pid $$)"
while true; do
    now=$(date +%s)
    if [ -f "$OUT/stop" ]; then
        log "stop file present; retiring"
        exit 0
    fi
    if (( now - START > FAST_AFTER )); then
        CUR_TIMEOUT=$FAST_TIMEOUT
        CUR_ENV="BATTERY_BUDGET_S=3300 BATTERY_FAST=1"
    else
        CUR_TIMEOUT=$BATTERY_TIMEOUT
        CUR_ENV=""
    fi
    if (( now - START > MAX_RUNTIME - CUR_TIMEOUT )); then
        log "too close to max runtime to start another battery; retiring"
        exit 0
    fi
    if probe; then
        log "tunnel ALIVE; running battery $((BATTERIES + 1)) (timeout ${CUR_TIMEOUT}s ${CUR_ENV})"
        env $CUR_ENV timeout -k 30 "$CUR_TIMEOUT" python -u scripts/watcher_battery.py \
            >> "$OUT/battery.log" 2>&1
        log "battery $((BATTERIES + 1)) rc=$?"
        BATTERIES=$((BATTERIES + 1))
        if (( BATTERIES >= MAX_BATTERIES )); then
            log "max batteries ($MAX_BATTERIES) done; retiring"
            exit 0
        fi
        nap "$BATTERY_GAP" || { log "stop during gap; retiring"; exit 0; }
    else
        log "tunnel DEAD"
        nap 240 || { log "stop during wait; retiring"; exit 0; }
    fi
done
