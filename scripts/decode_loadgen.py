"""Closed-loop generative-decode load generator (CPU-safe, seconds).

Offers a Poisson arrival stream of ragged generation requests — prompt
lengths drawn across the prefill buckets, output lengths skewed the way
real decode traffic is (mostly short answers, a long tail of long ones)
— against a warmed :class:`~paddle_tpu.serving.generate.GenerateEngine`
and measures sustained token throughput from first submit to last
completion.

The A/B that matters is ``--mode both``: the SAME engine class, model,
slot count, and executables run twice, once with ``refill="continuous"``
(finished sequences free their slot immediately; queued requests join
the running batch at the next tick) and once with ``refill="drain"``
(the classic run-to-completion static batcher: the batch only refills
once EVERY sequence in it has finished, so the whole batch waits on its
longest member). The tokens/s ratio between the two is the continuous-
batching win — the tail-length skew is exactly what makes drain bleed
slot-time.

Prints one JSON result line::

    {"continuous": {...}, "drain": {...}, "speedup_x": 2.7, ...}

With ``--sampling`` the same load runs sampled (temperature / top-k /
top-p, per-request seeds ``--seed-base + i`` so any run is bit-
reproducible); with ``--spec`` the A/B becomes speculative-vs-plain
decode on the SAME sampled traffic and slot count — the tokens/s ratio
is the draft-verify win, and the result line carries the measured
accept rate.

With ``--prefix-reuse FRAC`` the workload turns head-heavy — FRAC of
requests carry one of ``--prefix-heads`` shared system-prompt heads —
and runs against the disaggregated topology
(:class:`~paddle_tpu.serving.disagg.DisaggServer`: prefill pool +
prefix cache + priced handoff + decode pool). The result reports the
measured prefix hit rate and the TTFT distribution split by hit/miss —
the cache's latency win, measured rather than asserted.

Usage::

    python scripts/decode_loadgen.py --requests 64 --slots 8
    python scripts/decode_loadgen.py --mode continuous --rate 200
    python scripts/decode_loadgen.py --sampling temperature=1.0,top_k=8
    python scripts/decode_loadgen.py --spec --spec-k 8 --draft pair
    python scripts/decode_loadgen.py --prefix-reuse 0.6 --prefix-heads 3
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

# short answers dominate; the long tail is what run-to-completion
# batching stalls a whole batch on
SHORT_NEW = (4, 8)       # 85% of requests
LONG_NEW = (64, 80)      # 15% of requests
LONG_FRAC = 0.15


def make_workload(n, prompt_buckets, max_len, seed=0):
    """(prompt tokens, max_new_tokens, inter-arrival gap s) per request.
    Prompt lengths are ragged across the bucket family; output lengths
    are bimodal-skewed; gaps are exponential (Poisson process)."""
    rng = np.random.RandomState(seed)
    reqs = []
    for _ in range(n):
        if rng.rand() < LONG_FRAC:
            new = int(rng.randint(LONG_NEW[0], LONG_NEW[1] + 1))
        else:
            new = int(rng.randint(SHORT_NEW[0], SHORT_NEW[1] + 1))
        hi = min(int(prompt_buckets[-1]), max_len - new)
        plen = int(rng.randint(1, hi + 1))
        prompt = rng.randint(1, 31, size=plen).tolist()
        reqs.append((prompt, new))
    return reqs


def make_prefix_workload(n, reuse_frac, heads, prompt_buckets, max_len,
                         seed=0):
    """Head-heavy traffic: ``reuse_frac`` of requests carry one of
    ``heads`` shared system-prompt heads (the FULL prompt repeats —
    the prefix cache is keyed on the whole prompt), the rest are the
    ragged unique prompts of :func:`make_workload`. Output lengths keep
    the same bimodal skew."""
    rng = np.random.RandomState(seed)
    base = make_workload(n, prompt_buckets, max_len, seed=seed)
    head_len = int(prompt_buckets[-1])
    head_prompts = [rng.randint(1, 31, size=head_len).tolist()
                    for _ in range(max(1, int(heads)))]
    out = []
    for prompt, new in base:
        if rng.rand() < reuse_frac:
            prompt = head_prompts[int(rng.randint(len(head_prompts)))]
            new = min(new, max_len - head_len)
        out.append((prompt, new))
    return out


def _pct(sorted_vals, q):
    if not sorted_vals:
        return None
    i = min(len(sorted_vals) - 1, int(round(q * (len(sorted_vals) - 1))))
    return sorted_vals[i]


def run_load(model, mode, workload, slots, max_len, prompt_buckets,
             rate=None, seed=0, record_path=None, sampling=None,
             seed_base=None, draft=None, spec_k=4):
    """Drive one engine in ``mode`` over the workload; return the
    measurement dict. ``rate`` is the Poisson arrival rate in req/s
    (None = offered all at once — pure capacity measurement). With the
    monitor enabled, every request's ``serving.request`` record (ttft,
    tpot, stage waterfall, hops) is collected; ``record_path`` appends
    them as one-JSONL-per-request artifact. ``sampling`` (a dict /
    SamplingParams) turns every request sampled, with per-request seed
    ``seed_base + i``; ``draft`` plugs a draft model in for the
    speculative verify loop (the result then carries accept-rate and
    tokens-per-verify)."""
    from paddle_tpu import serving
    from paddle_tpu.serving import metrics

    metrics.reset_windows()
    eng = serving.GenerateEngine(
        model, slots=slots, page=32, factor=2.0, max_len=max_len,
        prompt_buckets=prompt_buckets, queue_depth=len(workload) + 8,
        refill=mode, shed=False, start=True,
        draft_model=draft, spec_k=spec_k)
    eng.warmup()
    n_exec, n_trace = eng.executables()

    rng = np.random.RandomState(seed + 1)
    reqs = []
    t0 = time.perf_counter()
    for i, (prompt, new) in enumerate(workload):
        if rate:
            time.sleep(float(rng.exponential(1.0 / rate)))
        r = eng.make_request(
            prompt, max_new_tokens=new, eos_token=None,
            sampling=sampling,
            seed=(seed_base + i) if seed_base is not None else None)
        eng.submit_request(r)
        reqs.append(r)
    outs = [r.future.result(timeout=120) for r in reqs]
    wall_s = time.perf_counter() - t0

    rollup = metrics.decode_rollup()
    stats = eng.stats()
    n_exec2, n_trace2 = eng.executables()
    eng.close()

    # per-request attribution (monitor-enabled runs only: trace is None
    # otherwise and the loadgen degrades to the throughput headline)
    records = [r.trace.ctx.record() for r in reqs
               if r.trace is not None and r.trace.ctx.record() is not None]
    slo = {}
    if records:
        if record_path:
            with open(record_path, "a") as fh:
                for rec in records:
                    fh.write(json.dumps({"mode": mode, **rec}) + "\n")
        ttfts = sorted(r["ttft_ms"] for r in records
                       if r.get("ttft_ms") is not None)
        tpots = sorted(r["tpot_ms"] for r in records
                       if r.get("tpot_ms") is not None)
        queues = sorted(r.get("queue_ms", 0.0) for r in records)
        rnd = lambda v: round(v, 3) if v is not None else None  # noqa: E731
        slo = {
            "records": len(records),
            "ttft_p50_ms": rnd(_pct(ttfts, 0.50)),
            "ttft_p99_ms": rnd(_pct(ttfts, 0.99)),
            "tpot_p50_ms": rnd(_pct(tpots, 0.50)),
            "tpot_p99_ms": rnd(_pct(tpots, 0.99)),
            "queue_p99_ms": rnd(_pct(queues, 0.99)),
        }

    spec = {}
    if draft is not None:
        spec = {
            "spec_k": spec_k,
            "verify_steps": stats["verify_steps"],
            "accept_rate": (round(stats["spec_accepted"]
                                  / max(stats["spec_proposed"], 1), 4)),
            "spec_tokens_per_step": (round(stats["tokens"]
                                           / max(stats["verify_steps"],
                                                 1), 3)),
            "pool_rollbacks": stats.get("pool_rollbacks", 0),
        }

    tokens = int(sum(len(o) for o in outs))
    return {
        **slo,
        **spec,
        "mode": mode,
        "requests": len(workload),
        "tokens": tokens,
        "wall_s": round(wall_s, 3),
        "tokens_per_s": round(tokens / wall_s, 1),
        "batch_occupancy": round(stats["avg_occupancy"], 4),
        "ticks": stats["ticks"],
        "prefill_p50_ms": (round(rollup["prefill_p50_ms"], 3)
                           if rollup["prefill_p50_ms"] is not None
                           else None),
        "decode_p99_ms": (round(rollup["decode_p99_ms"], 3)
                          if rollup["decode_p99_ms"] is not None
                          else None),
        "prefill_ratio": (round(rollup["prefill_ratio"], 4)
                          if rollup["prefill_ratio"] is not None
                          else None),
        "executables": n_exec2,
        "post_warmup_compiles": (n_exec2 - n_exec) + (n_trace2 - n_trace),
        "pool_bytes": stats["pool_cache_bytes"],
        "grows": stats["grows"],
    }


def run_disagg_load(model, workload, slots, max_len, prompt_buckets,
                    rate=None, seed=0, record_path=None, sampling=None,
                    seed_base=None, prefill_replicas=1,
                    decode_replicas=1):
    """Drive the disaggregated topology over the workload. Returns the
    measurement dict with the prefix hit rate and TTFT split by
    hit/miss (from each request's ``serving.request`` record — the
    ``prefix_hit`` field the reqtrace satellite added)."""
    from paddle_tpu import serving
    from paddle_tpu.serving import metrics, reqtrace

    metrics.reset_windows()
    reqtrace.reset()
    srv = serving.DisaggServer(
        model, prefill_replicas=prefill_replicas,
        decode_replicas=decode_replicas, slots=slots, page=32,
        factor=2.0, max_len=max_len, prompt_buckets=prompt_buckets,
        queue_depth=len(workload) + 8, supervise=False)
    srv.warmup()

    def execs():
        pools = (srv.prefill_pool, srv.decode_pool)
        return tuple(r.engine.executables()
                     for pool in pools for r in pool._replicas)

    ex0 = execs()
    rng = np.random.RandomState(seed + 1)
    futs = []
    t0 = time.perf_counter()
    for i, (prompt, new) in enumerate(workload):
        if rate:
            time.sleep(float(rng.exponential(1.0 / rate)))
        futs.append(srv.submit(
            prompt, max_new_tokens=new, sampling=sampling,
            seed=(seed_base + i) if seed_base is not None else None))
    outs = [f.result(timeout=120) for f in futs]
    wall_s = time.perf_counter() - t0
    ex1 = execs()

    stats = srv.stats()
    records = [r for r in reqtrace.recent() if r["outcome"] == "ok"]
    srv.close()

    slo = {}
    if records:
        if record_path:
            with open(record_path, "a") as fh:
                for rec in records:
                    fh.write(json.dumps({"mode": "disagg", **rec}) + "\n")
        hits = [r for r in records if r.get("prefix_hit") is True]
        misses = [r for r in records if r.get("prefix_hit") is False]
        rnd = lambda v: round(v, 3) if v is not None else None  # noqa: E731

        def ttfts(rs):
            return sorted(r["ttft_ms"] for r in rs
                          if r.get("ttft_ms") is not None)

        t_hit, t_miss = ttfts(hits), ttfts(misses)
        handoffs = sorted(r["handoff_ms"] for r in records
                          if r.get("handoff_ms") is not None)
        slo = {
            "records": len(records),
            "prefix_hit_rate": round(len(hits) / len(records), 4),
            "ttft_hit_p50_ms": rnd(_pct(t_hit, 0.50)),
            "ttft_hit_p99_ms": rnd(_pct(t_hit, 0.99)),
            "ttft_miss_p50_ms": rnd(_pct(t_miss, 0.50)),
            "ttft_miss_p99_ms": rnd(_pct(t_miss, 0.99)),
            "handoff_p50_ms": rnd(_pct(handoffs, 0.50)),
            "handoff_p99_ms": rnd(_pct(handoffs, 0.99)),
        }

    tokens = int(sum(len(o) for o in outs))
    return {
        **slo,
        "mode": "disagg",
        "requests": len(workload),
        "tokens": tokens,
        "wall_s": round(wall_s, 3),
        "tokens_per_s": round(tokens / wall_s, 1),
        "handoffs": stats["handoffs"],
        "handoff_bytes": stats["handoff_bytes"],
        "prefix_cache": stats.get("prefix"),
        "post_warmup_compiles": sum(
            (b[0] - a[0]) + (b[1] - a[1]) for a, b in zip(ex0, ex1)),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--max-len", type=int, default=96)
    ap.add_argument("--rate", type=float, default=0.0,
                    help="Poisson arrival rate req/s (0 = all at once)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--mode", choices=["both", "continuous", "drain"],
                    default="both")
    ap.add_argument("--sampling", default=None,
                    help="comma key=value SamplingParams, e.g. "
                         "temperature=1.0,top_k=8,top_p=0.9")
    ap.add_argument("--seed-base", type=int, default=1000,
                    help="request i samples with seed seed-base + i")
    ap.add_argument("--spec", action="store_true",
                    help="A/B speculative vs plain decode instead of "
                         "continuous vs drain (implies sampled traffic)")
    ap.add_argument("--spec-k", type=int, default=8,
                    help="draft tokens proposed per verify step")
    ap.add_argument("--draft", choices=["pair", "self"], default="pair",
                    help="pair = distilled demo draft/target pair; "
                         "self = target drafts for itself (accept ~1)")
    ap.add_argument("--prefix-reuse", type=float, default=0.0,
                    help="fraction of requests sharing one of "
                         "--prefix-heads system-prompt heads; >0 runs "
                         "the disaggregated topology and splits TTFT "
                         "by prefix hit/miss")
    ap.add_argument("--prefix-heads", type=int, default=4,
                    help="number of distinct shared prompt heads")
    ap.add_argument("--out-dir", default=None,
                    help="enable the monitor JSONL sink here")
    ap.add_argument("--telemetry-dir", default=None,
                    help="publish fleet-aggregator-compatible metric "
                         "snapshots here (monitor/fleet.py) — the "
                         "loadgen as a fleet telemetry source")
    args = ap.parse_args()

    from paddle_tpu import monitor, serving

    record_path = None
    if args.out_dir:
        os.makedirs(args.out_dir, exist_ok=True)
        monitor.enable(os.path.join(args.out_dir, "decode_loadgen.jsonl"),
                       telemetry_dir=args.telemetry_dir)
        record_path = os.path.join(args.out_dir,
                                   "decode_loadgen_requests.jsonl")
    else:
        # in-memory monitor (no sink): per-request traces still mint, so
        # the TTFT/TPOT table works without an artifact directory
        monitor.enable(telemetry_dir=args.telemetry_dir)

    sampling = None
    if args.sampling:
        sampling = {}
        for kv in args.sampling.split(","):
            k, _, v = kv.partition("=")
            sampling[k.strip()] = (int(v) if k.strip() == "top_k"
                                   else float(v))
    prompt_buckets = (4, 16)
    workload = make_workload(args.requests, prompt_buckets,
                             args.max_len, seed=args.seed)
    result = {"requests": args.requests, "slots": args.slots,
              "rate": args.rate or None, "sampling": sampling}

    if args.prefix_reuse > 0.0:
        # disaggregated topology under head-heavy traffic: the point
        # is the hit/miss TTFT split, so the workload repeats whole
        # prompts (the cache keys the full sequence)
        model = serving.demo_model(vocab=64, dim=256, heads=4, layers=2,
                                   max_len=args.max_len, seed=1)
        workload = make_prefix_workload(
            args.requests, args.prefix_reuse, args.prefix_heads,
            prompt_buckets, args.max_len, seed=args.seed)
        result["prefix_reuse"] = args.prefix_reuse
        result["prefix_heads"] = args.prefix_heads
        result["disagg"] = run_disagg_load(
            model, workload, args.slots, args.max_len, prompt_buckets,
            rate=args.rate or None, seed=args.seed,
            record_path=record_path, sampling=sampling,
            seed_base=args.seed_base if sampling else None)
        r = result["disagg"]
        print(f"[    disagg] {r['tokens_per_s']:>8} tok/s | "
              f"hit rate {r.get('prefix_hit_rate')} | "
              f"ttft hit p50 {r.get('ttft_hit_p50_ms')} ms vs "
              f"miss p50 {r.get('ttft_miss_p50_ms')} ms | "
              f"handoff p50 {r.get('handoff_p50_ms')} ms "
              f"({r.get('records', 0)} records)", file=sys.stderr)
        modes = []
    elif args.spec:
        # speculative A/B: same sampled traffic, same slots, draft
        # on/off. The pair's deep target amortises each verify over
        # spec_k drafted tokens; "self" isolates the loop's overhead
        # at accept rate ~1.
        sampling = sampling or {"temperature": 1.0}
        result["sampling"] = sampling
        if args.draft == "pair":
            target, draft = serving.demo_spec_pair(
                vocab=64, dim=192, heads=2, draft_layers=1,
                extra_layers=7, max_len=args.max_len, seed=1,
                distill=0.10)
        else:
            target = serving.demo_model(vocab=64, dim=192, heads=2,
                                        layers=2, max_len=args.max_len,
                                        seed=1)
            draft = target
        result["nonspec"] = run_load(
            target, "continuous", workload, args.slots, args.max_len,
            prompt_buckets, rate=args.rate or None, seed=args.seed,
            record_path=record_path, sampling=sampling,
            seed_base=args.seed_base)
        result["spec"] = run_load(
            target, "continuous", workload, args.slots, args.max_len,
            prompt_buckets, rate=args.rate or None, seed=args.seed,
            record_path=record_path, sampling=sampling,
            seed_base=args.seed_base, draft=draft, spec_k=args.spec_k)
        result["spec_speedup_x"] = round(
            result["spec"]["tokens_per_s"]
            / max(result["nonspec"]["tokens_per_s"], 1e-9), 2)
        result["accept_rate"] = result["spec"]["accept_rate"]
        modes = ["nonspec", "spec"]
    else:
        # dim 256 keeps the fused decode step expensive enough that the
        # slot-efficiency ratio (not host overhead) dominates the A/B
        model = serving.demo_model(vocab=64, dim=256, heads=4, layers=2,
                                   max_len=args.max_len, seed=1)
        modes = (["continuous", "drain"] if args.mode == "both"
                 else [args.mode])
        for mode in modes:
            result[mode] = run_load(
                model, mode, workload, args.slots, args.max_len,
                prompt_buckets, rate=args.rate or None, seed=args.seed,
                record_path=record_path, sampling=sampling,
                seed_base=args.seed_base if sampling else None)
        if "continuous" in result and "drain" in result:
            result["speedup_x"] = round(
                result["continuous"]["tokens_per_s"]
                / max(result["drain"]["tokens_per_s"], 1e-9), 2)

    # the SLO table rides next to the tokens/s headline (stderr, so the
    # stdout contract stays one JSON line)
    for mode in modes:
        r = result[mode]
        if r.get("ttft_p50_ms") is None:
            continue
        print(f"[{mode:>10}] {r['tokens_per_s']:>8} tok/s | "
              f"ttft p50/p99 {r['ttft_p50_ms']}/{r['ttft_p99_ms']} ms | "
              f"tpot p50/p99 {r['tpot_p50_ms']}/{r['tpot_p99_ms']} ms | "
              f"queue p99 {r['queue_p99_ms']} ms "
              f"({r['records']} records)", file=sys.stderr)

    if args.out_dir:
        monitor.emit(kind="decode_loadgen",
                     **{k: v for k, v in result.items()})
    monitor.disable()
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
