"""Single source of truth for the TPU liveness probe (invoked by both
scripts/tpu_watcher.sh and bench.py's _subprocess_probe, so timeout
tuning or hang-handling fixes land in one place).

Prints 'PROBE_OK <platform>' and exits 0 iff the backend answers a real
device computation. Run it under an external timeout: a wedged tunnel
blocks uninterruptibly in C on first contact (observed r4), so only a
kill from outside can reap it.
"""
import jax
import jax.numpy as jnp

jnp.zeros((8,), jnp.float32).block_until_ready()
print("PROBE_OK", jax.devices()[0].platform)
