#!/usr/bin/env bash
# Full test battery: overrides pytest.ini's `-m "not slow"` default so
# the slow-marked gold parity suites (SPMD 8-dev shard_map tests,
# long-seq kernels) actually run, with the monitor runtime enabled so
# the run leaves a JSONL evidence stream behind.
#
#   scripts/run_full_suite.sh [extra pytest args...]
#
# Env: PADDLE_TPU_SUITE_PLATFORM=cpu|tpu (default cpu) picks the jax
# backend; the monitor sink lands in ${PADDLE_TPU_MONITOR_DIR:-/tmp/paddle_tpu_suite}.
set -u
cd "$(dirname "$0")/.."

PLATFORM="${PADDLE_TPU_SUITE_PLATFORM:-cpu}"
MONITOR_DIR="${PADDLE_TPU_MONITOR_DIR:-/tmp/paddle_tpu_suite}"
mkdir -p "$MONITOR_DIR"

JAX_PLATFORMS="$PLATFORM" \
PADDLE_TPU_MONITOR=1 \
PADDLE_TPU_MONITOR_DIR="$MONITOR_DIR" \
python -m pytest tests/ -q -m "" \
    --continue-on-collection-errors \
    -p no:cacheprovider -p no:randomly \
    "$@"
rc=$?

# span-tracer gate: Perfetto-valid export, separate producer/step
# tracks, overlapping prefetch/step spans, disabled mode records nothing
echo ""
echo "-- trace smoke gate --"
bash scripts/trace_smoke.sh "$MONITOR_DIR/trace_smoke"
trc=$?
[ $trc -ne 0 ] && rc=$((rc == 0 ? trc : rc))

# serving gate: 200 concurrent requests must coalesce (batch_fill > 1),
# mint zero post-warmup executables, lose no futures, record p99 JSONL
echo ""
echo "-- serving smoke gate --"
bash scripts/serving_smoke.sh "$MONITOR_DIR/serving_smoke"
srv=$?
[ $srv -ne 0 ] && rc=$((rc == 0 ? srv : rc))

# serving chaos gate: self-healing fleet under injected faults —
# replica-hang failover (goodput >= 0.90, breaker re-closes via
# half-open probe), hedge-win under a straggler inside the 5% budget,
# 2x-overload priority shed (high goodput >= 0.95, every shed error
# retryable with retry-after), zero lost futures throughout
echo ""
echo "-- serving chaos smoke gate --"
bash scripts/serving_chaos_smoke.sh "$MONITOR_DIR/serving_chaos_smoke"
svc=$?
[ $svc -ne 0 ] && rc=$((rc == 0 ? svc : rc))

# telemetry gate: scrape /metrics + /healthz mid-fit (OpenMetrics with
# executor/prefetch/mem_* series, live watchdog state), clean teardown
echo ""
echo "-- export smoke gate --"
bash scripts/export_smoke.sh "$MONITOR_DIR/export_smoke"
exp=$?
[ $exp -ne 0 ] && rc=$((rc == 0 ? exp : rc))

# chaos gate: every injected fault class absorbed end to end — loader
# retry, NaN skip, preempt save/resume, quarantine, plus the sharded
# trio (preempt-triggered sharded save, mesh-resize resume at the exact
# next step, corrupt-one-shard-never-wins quorum fallback)
echo ""
echo "-- chaos smoke gate --"
bash scripts/chaos_smoke.sh "$MONITOR_DIR/chaos_smoke"
chs=$?
[ $chs -ne 0 ] && rc=$((rc == 0 ? chs : rc))

# comm gate: bucketed/overlapped/quantized grad collectives on 8
# virtual CPU devices — overlap hides wire time (<=60% of exact,
# reduce spans overlap backward in the Chrome trace), no compile tax,
# int8/int4 wire-byte honesty, lag-1 resumes bit-identical
echo ""
echo "-- comm smoke gate --"
bash scripts/comm_smoke.sh "$MONITOR_DIR/comm_smoke"
cms=$?
[ $cms -ne 0 ] && rc=$((rc == 0 ? cms : rc))

# profile gate: the 2-layer to_static step must attribute >=90% of its
# flops to named scopes, reconcile with cost_analysis() within 1%, and
# rank a non-empty hotspot menu with one JSONL record per region
echo ""
echo "-- profile smoke gate --"
bash scripts/profile_smoke.sh "$MONITOR_DIR/profile_smoke"
prf=$?
[ $prf -ne 0 ] && rc=$((rc == 0 ? prf : rc))

# arena gate: per-leaf vs flat_arena Adam must be bit-identical, cut
# opt.* bytes >=40% vs the multi-tensor baseline, leave zero
# concat/gather/scatter in the optimizer scope, and compile exactly
# once with zero recompiles
echo ""
echo "-- arena smoke gate --"
bash scripts/arena_smoke.sh "$MONITOR_DIR/arena_smoke"
arn=$?
[ $arn -ne 0 ] && rc=$((rc == 0 ? arn : rc))

# planner gate: MegatronConfig(mesh_plan=MEGATRON_RULES) reproduces the
# hand dp/tp layout bit-identically, fit(mesh_plan=) mints zero extra
# executables, the advisor table is non-empty + rank-stable, and its
# predicted-fastest layout is the measured-fastest in the dp8-vs-dp2tp4
# A/B on 8 virtual devices
echo ""
echo "-- plan smoke gate --"
bash scripts/plan_smoke.sh "$MONITOR_DIR/plan_smoke"
pln=$?
[ $pln -ne 0 ] && rc=$((rc == 0 ? pln : rc))

# memory gate: the to_static step's simulated HBM peak must reconcile
# with memory_analysis() within 10% and attribute >=90% of live-at-peak
# bytes, an injected RESOURCE_EXHAUSTED must leave the full oom flight
# bundle, and the planner must never auto-pick an over-budget layout
echo ""
echo "-- mem smoke gate --"
bash scripts/mem_smoke.sh "$MONITOR_DIR/mem_smoke"
mem=$?
[ $mem -ne 0 ] && rc=$((rc == 0 ? mem : rc))

# decode gate: continuous-batching generative decode — slot churn with
# zero lost futures and zero post-warmup compiles, KV-pool bytes equal
# to the closed-form budget prediction under a virtual HBM limit,
# continuous refill >= 2x the drain run-to-completion baseline's
# tokens/s, and a tokens_floor supervisor scale-up off the live decode
# SLO window
echo ""
echo "-- decode smoke gate --"
bash scripts/decode_smoke.sh "$MONITOR_DIR/decode_smoke"
dcd=$?
[ $dcd -ne 0 ] && rc=$((rc == 0 ? dcd : rc))

# spec gate: sampled + speculative decoding — greedy spec bit-identical
# to non-spec, sampled self-draft bit-identical with every proposal
# accepted, seed-reproducible streams across admission orders, and the
# loadgen A/B on the distilled pair (>= 1.5x at k=4, >= 2.0x at k=8,
# accept >= 0.9, zero post-warmup compiles in every arm)
echo ""
echo "-- spec smoke gate --"
bash scripts/spec_smoke.sh "$MONITOR_DIR/spec_smoke"
spc=$?
[ $spc -ne 0 ] && rc=$((rc == 0 ? spc : rc))

# memory-plan gate: under a virtual HBM budget, a model 4x past the
# no-remat ceiling trains under the auto-picked policy (predicted peak
# under the limit pre-flight), offload spans ride their own track with
# exposed wait <=40% of the transfer, the picker never chooses an
# infeasible or host-over-budget rung, remat/offload bit-identical
echo ""
echo "-- remat smoke gate --"
bash scripts/remat_smoke.sh "$MONITOR_DIR/remat_smoke"
rmt=$?
[ $rmt -ne 0 ] && rc=$((rc == 0 ? rmt : rc))

# request-tracing gate: under injected straggler + hung-replica faults,
# every request (hedged, failed-over, shed-then-retried included) emits
# exactly one serving.request record whose stage waterfall reconciles
# with the measured e2e within 5%; slo.ttft/tpot p99 gauges live on
# /metrics; per-KV-slot occupancy lanes + linked flow arrows in the
# Chrome export; disabled mode records nothing
echo ""
echo "-- request smoke gate --"
bash scripts/request_smoke.sh "$MONITOR_DIR/request_smoke"
rqs=$?
[ $rqs -ne 0 ] && rc=$((rc == 0 ? rqs : rc))

# serving-lifecycle gate: an injected preemption drains its replica and
# migrates queued + in-flight decode streams with zero loss and
# bit-identical outputs; SIGTERM drains the whole fleet (in-flight
# completes, post-drain submits shed); a rolling weight hot-swap lands
# under load with zero dropped requests and zero new executables; a
# corrupt publish is refused by quorum validation and quarantined
echo ""
echo "-- lifecycle smoke gate --"
bash scripts/lifecycle_smoke.sh "$MONITOR_DIR/lifecycle_smoke"
lcy=$?
[ $lcy -ne 0 ] && rc=$((rc == 0 ? lcy : rc))

# fleet telemetry: a 4-process decode fleet publishes snapshots into a
# shared directory; the aggregator's merged counters/percentiles must
# match the per-worker oracle, exactly the two injected anomalies
# (straggler + compile storm) must fire and resolve as alerts citing
# source and series — and land in the supervisor's decision ledger —
# the goodput ledger must reconcile to wall time, and publishing must
# cost <= 1% of worker wall (zero files with the monitor disabled)
echo ""
echo "-- telemetry smoke gate --"
bash scripts/telemetry_smoke.sh "$MONITOR_DIR/telemetry_smoke"
tlm=$?
[ $tlm -ne 0 ] && rc=$((rc == 0 ? tlm : rc))

# disaggregated-serving gate: prefill/decode split streams bit-identical
# to the single-engine oracle through a mid-stream decode drain, handoff
# bytes exactly equal the comm-model prediction, prefix hits skip
# prefill with hit TTFT <= 0.5x miss and zero new executables, each
# pool's supervisor scales on its own SLO (prefill: queue depth / TTFT
# ceiling; decode: tokens/s floor), and goodput holds >= 0.90 with one
# prefill replica hung
echo ""
echo "-- disagg smoke gate --"
bash scripts/disagg_smoke.sh "$MONITOR_DIR/disagg_smoke"
dsg=$?
[ $dsg -ne 0 ] && rc=$((rc == 0 ? dsg : rc))

# final gate: the perf regression sentinel over the repo's banked bench
# artifacts — nonzero iff a real measurement fell out of its tolerance
# band (outage-shaped zero/error lines are skipped, not failed)
echo ""
echo "-- perf sentinel gate --"
python scripts/perf_sentinel.py
sen=$?
[ $sen -ne 0 ] && rc=$((rc == 0 ? sen : rc))

latest=$(ls -t "$MONITOR_DIR"/events-*.jsonl 2>/dev/null | head -1)
echo ""
echo "monitor JSONL: ${latest:-<none written>} (dir: $MONITOR_DIR)"
exit $rc
