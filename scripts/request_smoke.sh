#!/usr/bin/env bash
# CI gate for request-scoped tracing / SLO attribution: with injected
# straggler + hung-replica faults, 100% of requests (hedged,
# failed-over, and shed-then-retried included) emit exactly one
# serving.request record whose stage breakdown reconciles with the
# measured e2e latency within 5%; slo.ttft_p99_ms / slo.tpot_p99_ms are
# live on /metrics; the Chrome export shows >= 1 occupancy interval on
# every KV slot lane with linked flow arrows; disabled mode adds zero
# records. Tier-1-safe: tiny models, CPU (2 virtual devices), seconds.
#
# Usage: scripts/request_smoke.sh [out_dir]
# The monitor JSONL (with the request_smoke record) lands in out_dir
# (default /tmp/paddle_tpu_request_smoke); the last stdout line is one
# JSON result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_request_smoke}"
JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=2" \
python scripts/request_smoke.py --out-dir "$OUT_DIR"
