#!/usr/bin/env bash
# Gradient-communication smoke gate: bucketed/overlapped/quantized
# collectives on 8 virtual CPU devices. See scripts/comm_smoke.py for
# the gates. Usage: comm_smoke.sh [out_dir]
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_comm_smoke}"
JAX_PLATFORMS=cpu python scripts/comm_smoke.py --out-dir "$OUT_DIR"
