"""Generative-decode smoke gate (tier-1-safe: CPU, tiny models, ~1 min).

Four phases, each mapping to an ISSUE acceptance criterion for the
continuous-batching decode engine:

* **churn** — ragged prompts and output lengths, EOS early-exits, and a
  capacity grow, through one warmed :class:`GenerateEngine`: every
  future resolves (zero lost under slot join/leave) and the executable
  cache + trace count stay EXACTLY flat after warmup — slot churn and
  cache growth never recompile.
* **budget** — under a virtual HBM limit
  (``PADDLE_TPU_HBM_LIMIT_BYTES``), the KV pool's live device bytes
  must equal its own closed-form prediction
  (``bytes_per_token x slots x capacity``), sit inside the limit with
  the headroom the pool reports, and ``fits_budget`` must reject a
  limit smaller than the arena.
* **throughput** — the scripts/decode_loadgen.py A/B: continuous
  refill must sustain >= 2x the tokens/s of the ``refill="drain"``
  run-to-completion baseline at the same slot count, with zero
  post-warmup compiles in BOTH modes.
* **scale_up** — a 2-replica :class:`MultiDecodeEngine` (1 active)
  under a ``tokens_floor`` the live decode window cannot meet: one
  supervisor tick must activate the second replica and log a
  ``scale_up`` decision carrying the observed ``tokens_per_s``.

Prints one JSON result line; exit 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def phase_churn(serving):
    """Ragged churn through one engine: zero lost futures, zero
    post-warmup compiles, exact pool byte accounting."""
    model = serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                               max_len=64, seed=1)
    eng = serving.GenerateEngine(model, slots=4, page=16, factor=2.0,
                                 max_len=64, prompt_buckets=(4, 8, 16),
                                 queue_depth=128, shed=False, start=True)
    eng.warmup()
    n_exec, n_trace = eng.executables()

    rng = np.random.RandomState(0)
    futs = []
    for i in range(40):
        plen = int(rng.randint(1, 17))
        new = int(rng.randint(1, 40))
        # seed-1 DemoLM emits 12/26 often: eos on half the requests
        # makes sequences finish early at unpredictable ticks (churn)
        eos = 12 if i % 2 else None
        futs.append(eng.submit(rng.randint(1, 31, size=plen).tolist(),
                               max_new_tokens=new, eos_token=eos))
    outs = [f.result(timeout=60) for f in futs]
    n_exec2, n_trace2 = eng.executables()
    stats = eng.stats()
    pool_exact = eng.pool.allocated_bytes() == eng.pool.bytes()
    eng.close()

    lost = sum(1 for o in outs if o is None or len(o) == 0)
    return {
        "requests": len(futs),
        "completed": stats["completed"],
        "lost": lost,
        "executables_warmup": n_exec,
        "executables_final": n_exec2,
        "traces_warmup": n_trace,
        "traces_final": n_trace2,
        "grows": stats["grows"],
        "pool_bytes_exact": bool(pool_exact),
        "ok": (lost == 0 and stats["completed"] == len(futs)
               and n_exec2 == n_exec and n_trace2 == n_trace
               and pool_exact),
    }


def phase_budget(serving, kv_cache):
    """KV-pool byte accounting vs a virtual HBM budget."""
    model = serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                               max_len=64, seed=1)
    spec = model.kv_spec()
    limit = 8 * 1024 * 1024                      # 8 MiB virtual budget
    os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"] = str(limit)
    try:
        pool = kv_cache.KVCachePool(spec, slots=4, page=16, factor=2.0,
                                    max_len=64)
        predicted = (kv_cache.bytes_per_token(spec) * pool.slots
                     * pool.capacity)
        allocated = pool.allocated_bytes()
        headroom, lim = pool.headroom()
        max_predicted = (kv_cache.bytes_per_token(spec) * pool.slots
                         * pool.seq_buckets[-1])
        fits, needed, _ = kv_cache.fits_budget(spec, 4, 64,
                                               limit_bytes=limit)
        too_small, _, _ = kv_cache.fits_budget(
            spec, 4, 64, limit_bytes=max_predicted - 1)
        planned = kv_cache.plan_slots(spec, 64, limit_bytes=limit,
                                      reserve_frac=0.5)
    finally:
        del os.environ["PADDLE_TPU_HBM_LIMIT_BYTES"]
    return {
        "limit_bytes": limit,
        "predicted_bytes": int(predicted),
        "allocated_bytes": int(allocated),
        "max_bytes": int(pool.max_bytes()),
        "headroom_bytes": int(headroom) if headroom is not None else None,
        "planned_slots": planned,
        "ok": (allocated == predicted == pool.bytes()
               and lim == limit
               # headroom is vs the grown-to-max arena, not the current
               # capacity: growth never shrinks, so budget for the worst
               and headroom == limit - pool.max_bytes()
               and headroom >= 0
               and pool.max_bytes() == max_predicted == needed
               and fits and not too_small
               and planned >= 4),
    }


def phase_throughput(serving, requests, slots):
    """The loadgen A/B: continuous vs drain on the same executables."""
    from decode_loadgen import make_workload, run_load
    model = serving.demo_model(vocab=64, dim=256, heads=4, layers=2,
                               max_len=96, seed=1)
    buckets = (4, 16)
    wl = make_workload(requests, buckets, 96, seed=0)
    cont = run_load(model, "continuous", wl, slots, 96, buckets)
    drain = run_load(model, "drain", wl, slots, 96, buckets)
    speedup = cont["tokens_per_s"] / max(drain["tokens_per_s"], 1e-9)
    return {
        "continuous_tokens_per_s": cont["tokens_per_s"],
        "drain_tokens_per_s": drain["tokens_per_s"],
        "speedup_x": round(speedup, 2),
        "continuous_occupancy": cont["batch_occupancy"],
        "drain_occupancy": drain["batch_occupancy"],
        "prefill_p50_ms": cont["prefill_p50_ms"],
        "decode_p99_ms": cont["decode_p99_ms"],
        # per-request SLO attribution from the serving.request records
        # (reqtrace) minted during the continuous run
        "ttft_p50_ms": cont.get("ttft_p50_ms"),
        "ttft_p99_ms": cont.get("ttft_p99_ms"),
        "tpot_p50_ms": cont.get("tpot_p50_ms"),
        "tpot_p99_ms": cont.get("tpot_p99_ms"),
        "post_warmup_compiles": (cont["post_warmup_compiles"]
                                 + drain["post_warmup_compiles"]),
        "ok": (speedup >= 2.0
               and cont["post_warmup_compiles"] == 0
               and drain["post_warmup_compiles"] == 0),
    }


def phase_scale_up(serving, metrics):
    """Decode-SLO autoscale: live tokens/s below tokens_floor must
    activate the second replica within one supervisor tick."""
    import jax
    from paddle_tpu.serving.supervisor import ServingSupervisor
    if len(jax.devices()) < 2:
        return {"ok": False, "error": "needs >=2 devices (XLA_FLAGS)"}

    metrics.reset_windows()
    model = serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                               max_len=64, seed=1)
    fleet = serving.MultiDecodeEngine(
        model, hedge_ms=0, supervise=False, initial_active=1,
        slots=4, page=16, factor=2.0, max_len=64,
        prompt_buckets=(4, 8, 16), shed=False)
    # goodput_floor=0 disables the fixed-shape goodput branch so the
    # decision below is attributable to the decode window alone
    sup = ServingSupervisor(fleet, start=False, goodput_floor=0.0,
                            tokens_floor=10_000_000.0)
    try:
        fleet.warmup()
        active_before = fleet._active_count()
        futs = [fleet.submit([1, 2, 3], max_new_tokens=8)
                for _ in range(6)]
        for f in futs:
            f.result(timeout=30)
        sup.tick(fleet)
        decision = sup.last_decision()
        active_after = fleet._active_count()
    finally:
        sup.stop()
        fleet.close()
    return {
        "active_before": active_before,
        "active_after": active_after,
        "decision": ({k: v for k, v in decision.items() if k != "t"}
                     if decision else None),
        "ok": (active_before == 1 and active_after == 2
               and decision is not None
               and decision["decision"] == "scale_up"
               and "tokens_per_s" in decision),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_decode_smoke")
    ap.add_argument("--requests", type=int, default=96)
    ap.add_argument("--slots", type=int, default=8)
    args = ap.parse_args()

    from paddle_tpu import monitor, serving
    from paddle_tpu.serving import kv_cache, metrics

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "decode_smoke.jsonl"))

    t0 = time.perf_counter()
    result = {
        "churn": phase_churn(serving),
        "budget": phase_budget(serving, kv_cache),
        "throughput": phase_throughput(serving, args.requests,
                                       args.slots),
        "scale_up": phase_scale_up(serving, metrics),
    }
    result["wall_s"] = round(time.perf_counter() - t0, 1)
    result["jsonl"] = jsonl
    result["ok"] = all(result[k]["ok"] for k in
                       ("churn", "budget", "throughput", "scale_up"))
    monitor.emit(kind="decode_smoke",
                 **{k: v for k, v in result.items() if k != "jsonl"})
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
