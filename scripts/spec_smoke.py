"""Sampled + speculative decoding smoke gate (CPU, tiny models).

Three phases, each mapping to a PR-17 acceptance criterion:

* **parity** — exactness, bit for bit: greedy speculative output must
  equal greedy non-speculative output (a distilled draft proposes, the
  target disposes — the accept-prefix rule keeps only target argmaxes,
  so the draft can NEVER change the stream); sampled self-draft output
  must equal non-speculative sampled output at the same per-request
  seeds with every proposal accepted; and both engines must mint zero
  executables after warmup.
* **seed_repro** — the counter-key contract: the same
  (prompt, params, seed) tuples produce identical streams whether the
  requests were admitted as one batch or one-at-a-time in reverse
  order with decode ticks in between, speculation on.
* **speedup** — the loadgen A/B (scripts/decode_loadgen.py
  ``run_load``) on acceptance-friendly traffic over the distilled
  demo pair: spec at k=4 must beat plain sampled decode by >= 1.5x
  tokens/s at the same slot count, k=8 by >= 2.0x, both with accept
  rate >= 0.9 and zero post-warmup compiles in every arm (best-of-N
  reps absorb CPU timer noise).

Prints one JSON result line; exit 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _drive(eng, futs):
    for _ in range(5000):
        eng.tick()
        if all(f.done() for f in futs):
            return [f.result() for f in futs]
    raise RuntimeError("decode did not finish")


def _small_engine(serving, model, draft=None, k=4):
    return serving.GenerateEngine(
        model, slots=4, page=16, max_len=32, prompt_buckets=(16,),
        queue_depth=64, shed=False, start=False, draft_model=draft,
        spec_k=k)


def phase_parity(serving):
    """Greedy spec == greedy non-spec (distilled draft), sampled
    self-draft bit-identical, zero post-warmup compiles both modes."""
    target, draft = serving.demo_spec_pair(
        vocab=32, dim=16, heads=2, draft_layers=1, extra_layers=1,
        max_len=64, seed=1, distill=0.2)
    prompts = [[3, 1, 4], [1, 5, 9, 2, 6], [7], [2, 7, 1, 8]]
    configs = [None, None,
               {"temperature": 1.0, "top_k": 8},
               {"temperature": 0.9, "top_p": 0.9}]

    plain = _small_engine(serving, target)
    plain.warmup()
    base = plain.executables()
    want = _drive(plain, [plain.submit(p, max_new_tokens=20,
                                       sampling=c, seed=50 + i)
                          for i, (p, c) in enumerate(zip(prompts,
                                                         configs))])
    plain_flat = plain.executables() == base
    plain.close(drain=False)

    # greedy rows verify against the distilled draft; sampled rows
    # against the self-draft (q == p -> accept everything) — one spec
    # engine per draft so each guarantee is isolated
    results = {"plain_compiles_flat": bool(plain_flat)}
    ok = plain_flat
    for name, d, idx in (("greedy_pair", draft, [0, 1]),
                         ("sampled_self", target, [2, 3])):
        spec = _small_engine(serving, target, draft=d, k=3)
        spec.warmup()
        sbase = spec.executables()
        got = _drive(spec, [spec.submit(prompts[i], max_new_tokens=20,
                                        sampling=configs[i], seed=50 + i)
                            for i in idx])
        st = spec.stats()
        flat = spec.executables() == sbase
        spec.close(drain=False)
        match = all(np.array_equal(g, want[i]) for g, i in zip(got, idx))
        results[name] = {
            "bit_identical": bool(match),
            "compiles_flat": bool(flat),
            "verify_steps": st["verify_steps"],
            "accept_rate": round(st["spec_accepted"]
                                 / max(st["spec_proposed"], 1), 4),
        }
        ok = ok and match and flat and st["verify_steps"] > 0
        if name == "sampled_self":     # q == p accepts every proposal
            ok = ok and st["spec_accepted"] == st["spec_proposed"]
    results["ok"] = bool(ok)
    return results


def phase_seed_repro(serving):
    """Batch admission vs reversed one-at-a-time admission, spec on:
    identical streams per (prompt, params, seed)."""
    target, draft = serving.demo_spec_pair(
        vocab=32, dim=16, heads=2, draft_layers=1, extra_layers=1,
        max_len=64, seed=1, distill=0.2)
    reqs = [([2 + i, 5], {"temperature": 1.0, "top_k": 8}, 70 + i)
            for i in range(4)]

    eng = _small_engine(serving, target, draft=draft, k=3)
    eng.warmup()
    want = _drive(eng, [eng.submit(p, max_new_tokens=12, sampling=c,
                                   seed=s) for p, c, s in reqs])
    eng.close(drain=False)

    eng2 = _small_engine(serving, target, draft=draft, k=3)
    eng2.warmup()
    staggered = {}
    for p, c, s in reversed(reqs):
        staggered[s] = eng2.submit(p, max_new_tokens=12, sampling=c,
                                   seed=s)
        eng2.tick()                    # partial progress between admits
    got = _drive(eng2, [staggered[s] for _, _, s in reqs])
    eng2.close(drain=False)

    match = all(np.array_equal(g, w) for g, w in zip(got, want))
    return {"requests": len(reqs), "bit_identical": bool(match),
            "ok": bool(match)}


def phase_speedup(serving, slots, reps):
    """decode_loadgen run_load A/B on the distilled pair: spec k=4
    >= 1.5x and k=8 >= 2.0x plain sampled tokens/s, accept >= 0.9,
    zero post-warmup compiles in every arm."""
    from decode_loadgen import run_load
    max_len = 96
    buckets = (4, 16)
    # acceptance-friendly traffic: long generations give the verify
    # loop room to amortise (the bimodal short-answer mix is the
    # continuous-vs-drain story, not this one)
    rng = np.random.RandomState(0)
    workload = [(rng.randint(1, 31,
                             size=int(rng.randint(1, 9))).tolist(),
                 int(rng.randint(56, 73))) for _ in range(48)]
    target, draft = serving.demo_spec_pair(
        vocab=64, dim=192, heads=2, draft_layers=1, extra_layers=7,
        max_len=max_len, seed=1, distill=0.10)
    sampling = {"temperature": 1.0}

    def best_of(draft_model, spec_k):
        best = None
        for _ in range(reps):
            r = run_load(target, "continuous", workload, slots, max_len,
                         buckets, sampling=sampling, seed_base=500,
                         draft=draft_model, spec_k=spec_k)
            if best is None or r["tokens_per_s"] > best["tokens_per_s"]:
                best = r
        return best

    plain = best_of(None, 4)
    k4 = best_of(draft, 4)
    k8 = best_of(draft, 8)
    up4 = k4["tokens_per_s"] / max(plain["tokens_per_s"], 1e-9)
    up8 = k8["tokens_per_s"] / max(plain["tokens_per_s"], 1e-9)
    compiles = (plain["post_warmup_compiles"]
                + k4["post_warmup_compiles"]
                + k8["post_warmup_compiles"])
    return {
        "plain_tokens_per_s": plain["tokens_per_s"],
        "spec_k4_tokens_per_s": k4["tokens_per_s"],
        "spec_k8_tokens_per_s": k8["tokens_per_s"],
        "speedup_k4_x": round(up4, 2),
        "speedup_k8_x": round(up8, 2),
        "accept_rate_k4": k4["accept_rate"],
        "accept_rate_k8": k8["accept_rate"],
        "spec_tokens_per_step_k8": k8["spec_tokens_per_step"],
        "post_warmup_compiles": compiles,
        "ok": (up4 >= 1.5 and up8 >= 2.0
               and k4["accept_rate"] >= 0.9
               and k8["accept_rate"] >= 0.9
               and compiles == 0),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_spec_smoke")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--reps", type=int, default=2,
                    help="best-of reps per speedup arm")
    args = ap.parse_args()

    from paddle_tpu import monitor, serving

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "spec_smoke.jsonl"))

    t0 = time.perf_counter()
    result = {
        "parity": phase_parity(serving),
        "seed_repro": phase_seed_repro(serving),
        "speedup": phase_speedup(serving, args.slots, args.reps),
    }
    result["wall_s"] = round(time.perf_counter() - t0, 1)
    result["jsonl"] = jsonl
    result["ok"] = all(result[k]["ok"] for k in
                       ("parity", "seed_repro", "speedup"))
    monitor.emit(kind="spec_smoke",
                 **{k: v for k, v in result.items() if k != "jsonl"})
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
