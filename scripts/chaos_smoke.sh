#!/usr/bin/env bash
# CI gate for the fault-tolerant training runtime (paddle_tpu.resilience):
# one run absorbs an injected loader fault, a NaN step and a mid-run
# preemption; a second run auto-resumes from the atomic checkpoint at the
# right step; a planted truncated checkpoint must never win latest_step().
# Tier-1-safe: tiny MLP, CPU backend, seconds end to end.
#
# Usage: scripts/chaos_smoke.sh [out_dir]
# The monitor JSONL stream lands in out_dir (default
# /tmp/paddle_tpu_chaos_smoke) as the CI artifact; the last stdout line
# is one JSON result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_chaos_smoke}"
rm -rf "$OUT_DIR"
JAX_PLATFORMS=cpu python scripts/chaos_smoke.py --out-dir "$OUT_DIR"
