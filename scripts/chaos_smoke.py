"""Chaos smoke gate for the fault-tolerant training runtime
(paddle_tpu.resilience). Tier-1-safe: tiny MLP, CPU, seconds end to end.

One training run absorbs every injected fault class and a second run
resumes from the wreckage; the gates assert the ISSUE's acceptance
criteria from the monitor JSONL stream:

* a transient loader fault at one batch retries (``resilience.retry``)
  and the epoch still yields every batch
* a NaN-poisoned step is skipped (``resilience.nan_skip``) and the run's
  epoch losses stay finite
* a mid-run preemption writes one atomic checkpoint
  (``resilience.preempt_save``) and stops cleanly
* a truncated checkpoint planted at a NEWER step never wins
  ``latest_step()`` and is quarantined on restore
* the resumed run continues at exactly the step after the preemption
  save (``resilience.auto_resume``) and finishes with finite loss

Sharded / topology-elastic gates (ISSUE 7), on an 8-virtual-device mesh:

* a preemption during a ``sharded=True`` run triggers a final per-shard
  save whose manifest + every shard validate
  (``preempt_triggered_sharded_save``)
* a run on a RESIZED mesh (4×2 → 2×4) auto-resumes from that sharded
  checkpoint at exactly the next step, recording
  ``ckpt.restore_resharded`` (``mesh_resize_resumed_at_next_step``)
* garbling one shard of the newest checkpoint disqualifies the whole
  step — quorum rule — and restore falls back to the previous complete
  one (``corrupt_one_shard_never_wins``)

Writes the monitor JSONL to --out-dir as the CI artifact and prints one
JSON result line. Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import warnings

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_chaos_smoke")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import hapi, monitor, nn, optimizer as opt
    from paddle_tpu.io import CheckpointManager, TensorDataset
    from paddle_tpu.resilience import NaNGuard, faults

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir, "chaos_smoke.jsonl"))
    ckpt_dir = os.path.join(args.out_dir, "ckpts")

    rng = np.random.RandomState(0)
    w = rng.randn(8, 3)
    x = rng.randn(64, 8).astype("f4")
    y = (x @ w).argmax(-1).astype("i4")
    ds = TensorDataset(x, y)
    steps_per_epoch = 64 // args.batch

    def model():
        pt.seed(7)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
        m = hapi.Model(net)
        m.prepare(optimizer=opt.SGD(learning_rate=0.05,
                                    parameters=m.parameters()),
                  loss_function=hapi.CrossEntropy())
        return m

    # -- run 1: loader fault + NaN step + mid-run preemption ----------------
    preempt_step = steps_per_epoch + 2  # epoch 1, batch 2
    loader_spec = faults.inject("loader", step=1, times=2)
    nan_spec = faults.inject("nan_grad", step=3)
    faults.inject("preempt", step=preempt_step)

    guard = NaNGuard("skip")
    cm = CheckpointManager(ckpt_dir)
    m1 = model()
    h1 = m1.fit(ds, batch_size=args.batch, epochs=args.epochs, verbose=0,
                shuffle=False, checkpoint=cm, nan_guard=guard)
    faults.clear()

    # a truncated checkpoint at a NEWER step (simulated SIGKILL mid-write
    # without the atomic rename) must never win latest_step()
    bogus = cm._path(99)
    with open(bogus, "wb") as f:
        f.write(b"\x80truncated-checkpoint")
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        latest_after_truncation = cm.latest_step()

    # -- run 2: auto-resume from the preemption checkpoint ------------------
    m2 = model()
    h2 = m2.fit(ds, batch_size=args.batch, epochs=args.epochs, verbose=0,
                shuffle=False, checkpoint=cm, auto_resume=True,
                nan_guard="skip")
    monitor.emit(kind="chaos", event="marker", phase="sharded")

    # -- run 3: SHARDED checkpoints on a 4×2 mesh, preempt mid-run ----------
    import jax
    from jax.sharding import PartitionSpec as P
    from paddle_tpu.io import sharded as shio
    from paddle_tpu.parallel import collective

    def smodel(mesh):
        """The same MLP, tp-row-sharded so sharded saves write real
        multi-file shards."""
        m = model()
        for p in m.parameters():
            if p.data.ndim == 2 and \
                    p.shape[0] % mesh.shape["tp"] == 0:
                collective.shard(p, P("tp", None), mesh)
            else:
                collective.replicated(p, mesh)
        return m

    ckpt2_dir = os.path.join(args.out_dir, "ckpts_sharded")
    cm2 = CheckpointManager(ckpt2_dir, sharded=True)
    preempt2 = steps_per_epoch + 1  # epoch 1, batch 1
    faults.inject("preempt", step=preempt2)
    mesh_save = collective.make_mesh({"dp": 4, "tp": 2})
    m3 = smodel(mesh_save)
    h3 = m3.fit(ds, batch_size=args.batch, epochs=args.epochs, verbose=0,
                shuffle=False, checkpoint=cm2, save_steps=2)
    faults.clear()
    sharded_dir = cm2._sharded_path(preempt2)
    sharded_save_ok = os.path.isdir(sharded_dir) and \
        shio.validate(sharded_dir)[0]
    monitor.emit(kind="chaos", event="marker", phase="resize")

    # -- run 4: resume the sharded checkpoint on a RESIZED 2×4 mesh ---------
    mesh_resize = collective.make_mesh({"dp": 2, "tp": 4})
    m4 = smodel(mesh_resize)
    h4 = m4.fit(ds, batch_size=args.batch, epochs=args.epochs, verbose=0,
                shuffle=False, checkpoint=cm2, auto_resume=True,
                save_steps=2)
    monitor.emit(kind="chaos", event="marker", phase="corrupt")

    # -- run 5: garble ONE shard of the newest checkpoint -------------------
    valid_before = cm2.valid_steps()
    newest = cm2._sharded_path(valid_before[-1])
    shard0 = sorted(f for f in os.listdir(newest)
                    if f.endswith(".npy"))[0]
    faults.garble_file(os.path.join(newest, shard0))
    m5 = smodel(mesh_resize)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        latest_after_shard_corrupt = cm2.latest_step()
        state5 = cm2.restore(model=m5)
    monitor.disable()

    all_records = monitor.read_jsonl(jsonl)
    phases, cur = {"base": []}, "base"
    for r in all_records:
        if r.get("kind") == "chaos" and r.get("event") == "marker":
            cur = r["phase"]
            phases[cur] = []
        else:
            phases.setdefault(cur, []).append(r)

    def by_event(phase, kind="resilience"):
        out = {}
        for r in phases.get(phase, []):
            if r.get("kind") == kind:
                out.setdefault(r["event"], []).append(r)
        return out

    events = by_event("base")
    resume_steps = [r.get("step") for r in events.get("auto_resume", [])]
    sharded_ev = by_event("sharded")
    resize_ev = by_event("resize")
    resize_ckpt_ev = by_event("resize", kind="ckpt")

    finite_losses = [float(v)
                     for v in h1["loss"] + h2["loss"] + h3["loss"] +
                     h4["loss"]]
    gates = {
        "loader_fault_fired_twice": loader_spec.fired == 2,
        "nan_fault_fired": nan_spec.fired == 1,
        "retry_events": len(events.get("retry", [])) >= 2,
        "nan_skip_events": len(events.get("nan_skip", [])) == 1,
        "losses_all_finite": all(np.isfinite(finite_losses)),
        "preempted_and_stopped": bool(m1.stop_training),
        "preempt_save_at_right_step": [
            r.get("step") for r in events.get("preempt_save", [])
        ] == [preempt_step],
        "truncated_ckpt_never_wins": latest_after_truncation == preempt_step,
        "corrupt_ckpt_quarantined": os.path.exists(bogus + ".corrupt")
        and not os.path.exists(bogus),
        "resumed_at_next_step": resume_steps == [preempt_step + 1],
        # ISSUE 7 gates ----------------------------------------------------
        "preempt_triggered_sharded_save": sharded_save_ok and [
            r.get("step") for r in sharded_ev.get("preempt_save", [])
        ] == [preempt2],
        "mesh_resize_resumed_at_next_step": [
            r.get("step") for r in resize_ev.get("auto_resume", [])
        ] == [preempt2 + 1] and
        len(resize_ckpt_ev.get("restore_resharded", [])) >= 1,
        "corrupt_one_shard_never_wins":
            latest_after_shard_corrupt == valid_before[-2] and
            state5 is not None and
            state5.get("step") == valid_before[-2] and
            os.path.isdir(newest + ".corrupt"),
    }
    result = {
        "gates": gates,
        "ok": all(gates.values()),
        "run1_loss": h1["loss"],
        "run2_loss": h2["loss"],
        "run3_loss": h3["loss"],
        "run4_loss": h4["loss"],
        "jsonl": jsonl,
    }
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
