"""Chaos smoke gate for the fault-tolerant training runtime
(paddle_tpu.resilience). Tier-1-safe: tiny MLP, CPU, seconds end to end.

One training run absorbs every injected fault class and a second run
resumes from the wreckage; the gates assert the ISSUE's acceptance
criteria from the monitor JSONL stream:

* a transient loader fault at one batch retries (``resilience.retry``)
  and the epoch still yields every batch
* a NaN-poisoned step is skipped (``resilience.nan_skip``) and the run's
  epoch losses stay finite
* a mid-run preemption writes one atomic checkpoint
  (``resilience.preempt_save``) and stops cleanly
* a truncated checkpoint planted at a NEWER step never wins
  ``latest_step()`` and is quarantined on restore
* the resumed run continues at exactly the step after the preemption
  save (``resilience.auto_resume``) and finishes with finite loss

Writes the monitor JSONL to --out-dir as the CI artifact and prints one
JSON result line. Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_chaos_smoke")
    ap.add_argument("--epochs", type=int, default=4)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import hapi, monitor, nn, optimizer as opt
    from paddle_tpu.io import CheckpointManager, TensorDataset
    from paddle_tpu.resilience import NaNGuard, faults

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir, "chaos_smoke.jsonl"))
    ckpt_dir = os.path.join(args.out_dir, "ckpts")

    rng = np.random.RandomState(0)
    w = rng.randn(8, 3)
    x = rng.randn(64, 8).astype("f4")
    y = (x @ w).argmax(-1).astype("i4")
    ds = TensorDataset(x, y)
    steps_per_epoch = 64 // args.batch

    def model():
        pt.seed(7)
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
        m = hapi.Model(net)
        m.prepare(optimizer=opt.SGD(learning_rate=0.05,
                                    parameters=m.parameters()),
                  loss_function=hapi.CrossEntropy())
        return m

    # -- run 1: loader fault + NaN step + mid-run preemption ----------------
    preempt_step = steps_per_epoch + 2  # epoch 1, batch 2
    loader_spec = faults.inject("loader", step=1, times=2)
    nan_spec = faults.inject("nan_grad", step=3)
    faults.inject("preempt", step=preempt_step)

    guard = NaNGuard("skip")
    cm = CheckpointManager(ckpt_dir)
    m1 = model()
    h1 = m1.fit(ds, batch_size=args.batch, epochs=args.epochs, verbose=0,
                shuffle=False, checkpoint=cm, nan_guard=guard)
    faults.clear()

    # a truncated checkpoint at a NEWER step (simulated SIGKILL mid-write
    # without the atomic rename) must never win latest_step()
    bogus = cm._path(99)
    with open(bogus, "wb") as f:
        f.write(b"\x80truncated-checkpoint")
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        latest_after_truncation = cm.latest_step()

    # -- run 2: auto-resume from the preemption checkpoint ------------------
    m2 = model()
    h2 = m2.fit(ds, batch_size=args.batch, epochs=args.epochs, verbose=0,
                shuffle=False, checkpoint=cm, auto_resume=True,
                nan_guard="skip")
    monitor.disable()

    records = [r for r in monitor.read_jsonl(jsonl)
               if r.get("kind") == "resilience"]
    events = {}
    for r in records:
        events.setdefault(r["event"], []).append(r)
    resume_steps = [r.get("step") for r in events.get("auto_resume", [])]

    finite_losses = [float(v) for v in h1["loss"] + h2["loss"]]
    gates = {
        "loader_fault_fired_twice": loader_spec.fired == 2,
        "nan_fault_fired": nan_spec.fired == 1,
        "retry_events": len(events.get("retry", [])) >= 2,
        "nan_skip_events": len(events.get("nan_skip", [])) == 1,
        "losses_all_finite": all(np.isfinite(finite_losses)),
        "preempted_and_stopped": bool(m1.stop_training),
        "preempt_save_at_right_step": [
            r.get("step") for r in events.get("preempt_save", [])
        ] == [preempt_step],
        "truncated_ckpt_never_wins": latest_after_truncation == preempt_step,
        "corrupt_ckpt_quarantined": os.path.exists(bogus + ".corrupt")
        and not os.path.exists(bogus),
        "resumed_at_next_step": resume_steps == [preempt_step + 1],
    }
    result = {
        "gates": gates,
        "ok": all(gates.values()),
        "run1_loss": h1["loss"],
        "run2_loss": h2["loss"],
        "jsonl": jsonl,
    }
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
