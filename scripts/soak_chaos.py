"""Mixed-fault chaos soak over the full serving lifecycle (CPU-safe).

Closed-loop seeded Poisson decode load against a 3-replica
:class:`MultiDecodeEngine` while a seeded chaos schedule mixes every
lifecycle disturbance the stack claims to survive:

* ``replica_hang``    — a replica wedges mid-step long enough to trip
  the supervisor's hang failover
* ``replica_slow``    — straggler injections
* ``preempt_replica`` — the supervisor drains + migrates the replica,
  then the schedule readmits it
* live weight hot-swap — rolling ``swap_weights`` between two
  same-shape weight publishes
* corrupt publish      — a garbled checkpoint swap attempt that quorum
  validation must refuse (and quarantine) without interrupting service

Invariants gated at the end:

* goodput >= 0.90 (completed / offered; sheds + failures count against)
* zero lost futures (every submitted future resolves)
* exactly one ``serving.request`` record per admitted request (parsed
  back out of the soak's own monitor JSONL — no double-finalize, no
  silent loss across drain/failover/swap hops)
* zero post-warmup compiles (same-shape swaps ride the
  state-as-argument jit contract; per-engine executable counts must
  not move)
* seeded bit-reproducibility: a quiet epilogue batch on the soaked
  fleet is bit-identical to a fresh single engine holding the final
  weights version
* corrupt publishes refused, never swapped in; final version reflects
  only the successful swaps

Short mode (the default, ``--duration 60``) is the tier-1 gate; crank
``--duration`` for a real soak. Prints one JSON line; exit 0 iff all
invariants hold.
"""
import argparse
import collections
import json
import os
import sys
import tempfile
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

WEIGHT_SEEDS = (1, 9)    # the two same-shape publishes the soak rolls
VOCAB, DIM = 32, 32


def _model(seed):
    from paddle_tpu import serving
    return serving.demo_model(vocab=VOCAB, dim=DIM, heads=2, layers=2,
                              max_len=64, seed=seed)


def _request(rid, base_seed):
    """Deterministic (prompt, max_new, seed) for request `rid` — the
    same function drives the soak clients and the replay oracle."""
    rng = np.random.RandomState((base_seed * 100003 + rid) % (2 ** 31))
    plen = int(rng.randint(4, 13))
    prompt = rng.randint(1, VOCAB - 1, size=plen).astype(np.int32)
    return prompt, 8 + int(rng.randint(0, 5)), 50000 + rid


def run_soak(args):
    import jax
    from paddle_tpu import monitor, serving
    from paddle_tpu.io import sharded
    from paddle_tpu.resilience import faults
    from paddle_tpu.serving import reqtrace

    reqtrace.reset()
    eng = serving.MultiDecodeEngine(
        _model(WEIGHT_SEEDS[0]), devices=jax.local_devices()[:3],
        slots=4, page=16, max_len=48, prompt_buckets=(16,),
        queue_depth=256, supervisor_interval_s=0.05,
        inflight_timeout_ms=2500.0, breaker_cooldown_s=0.8)
    eng.warmup()
    eng.start()
    execs0 = [e.executables()[0] for e in eng.engines]

    counts = collections.Counter()
    lost = []
    admitted_rids = set()     # client traces that reached an engine —
    client_rids = set()       # the exactly-one-record census universe
    rid_counter = [0]
    rid_lock = threading.Lock()
    stop = threading.Event()

    def one_request(rid, retries=5):
        """One logical request: shed/failure retries share ONE
        RequestTrace, so the done-latch keeps the terminal record
        unique however many hops it takes. Returns True iff the
        request ultimately completed."""
        prompt, max_new, seed = _request(rid, args.seed)
        tr = reqtrace.RequestTrace(kind="decode")
        with rid_lock:
            client_rids.add(tr.rid)
        for _ in range(retries):
            try:
                fut = eng.submit(prompt, max_new_tokens=max_new,
                                 seed=seed, trace=tr,
                                 sampling={"temperature": 0.8})
            except serving.NoHealthyReplicaError:
                with rid_lock:
                    counts["shed_attempts"] += 1
                time.sleep(0.08)
                continue
            with rid_lock:
                admitted_rids.add(tr.rid)
            try:
                fut.result(45)
                with rid_lock:
                    counts["ok"] += 1
                return True
            except Exception as e:   # noqa: BLE001 - tallied + retried
                with rid_lock:
                    counts["failed_attempts"] += 1
                    counts[f"err:{type(e).__name__}"] += 1
                if not fut.done():
                    lost.append(tr.rid)
                time.sleep(0.05)
        with rid_lock:
            counts["gave_up"] += 1
        return False

    def client(k):
        rng = np.random.RandomState(args.seed * 7919 + k)
        while not stop.is_set():
            with rid_lock:
                rid = rid_counter[0]
                rid_counter[0] += 1
            one_request(rid)
            time.sleep(float(rng.exponential(0.01)))

    threads = [threading.Thread(target=client, args=(k,), daemon=True)
               for k in range(args.clients)]
    for t in threads:
        t.start()

    # -- the seeded chaos schedule ---------------------------------------
    chaos = np.random.RandomState(args.seed)
    events = collections.Counter()
    deadline = time.monotonic() + args.duration
    weight_idx = 0          # index into WEIGHT_SEEDS of the live tree
    refusals = 0
    swap_errors = []
    with tempfile.TemporaryDirectory() as tmp:
        while time.monotonic() < deadline:
            time.sleep(float(chaos.uniform(1.2, 2.4)))
            if time.monotonic() >= deadline:
                break
            # readmit anything a previous preempt left draining
            for r in eng._replicas:
                if r.draining:
                    eng.undrain_replica(r, reason="chaos_readmit")
            kind = chaos.choice(["hang", "slow", "preempt", "swap",
                                 "corrupt"])
            replica = int(chaos.randint(0, 3))
            events[kind] += 1
            if kind == "hang":
                faults.inject("replica_hang", replica=replica,
                              delay=1.2, times=1)
            elif kind == "slow":
                faults.inject("replica_slow", replica=replica,
                              delay=0.12, times=3)
            elif kind == "preempt":
                faults.inject("preempt_replica", replica=replica,
                              times=1)
            elif kind == "swap":
                nxt = (weight_idx + 1) % len(WEIGHT_SEEDS)
                try:
                    eng.swap_weights(_model(WEIGHT_SEEDS[nxt]).state,
                                     drain_timeout_s=30.0,
                                     probe_timeout_s=10.0)
                    weight_idx = nxt
                except RuntimeError as e:   # unwound roll: still v_old
                    swap_errors.append(repr(e))
            elif kind == "corrupt":
                ck = os.path.join(tmp, f"bad-{events['corrupt']}.sharded")
                sharded.save_state(
                    ck, jax.device_get(_model(WEIGHT_SEEDS[1]).state))
                faults.inject("publish_corrupt", times=1)
                try:
                    eng.swap_weights(ck)
                except ValueError:
                    refusals += 1
                faults.clear("publish_corrupt")

        # -- quiesce: stop chaos, readmit everyone, let load drain -------
        faults.clear()
        stop.set()
        for t in threads:
            t.join(timeout=60)
        for r in eng._replicas:
            if r.draining:
                eng.undrain_replica(r, reason="chaos_done")
        eng.drain_fleet(reason="soak_epilogue")
        eng.drain_wait(timeout_s=60.0)
        for r in eng._replicas:
            eng.undrain_replica(r, reason="soak_epilogue")

    # -- epilogue: seeded bit-reproducibility on the final version -------
    epi_base = rid_counter[0] + 1000
    epi = [_request(epi_base + i, args.seed) for i in range(args.replay)]
    epi_traces = [reqtrace.RequestTrace(kind="decode") for _ in epi]
    for tr in epi_traces:
        client_rids.add(tr.rid)
        admitted_rids.add(tr.rid)
    epi_futs = [eng.submit(p, max_new_tokens=m, seed=s, trace=tr,
                           sampling={"temperature": 0.8})
                for (p, m, s), tr in zip(epi, epi_traces)]
    epi_tokens = [np.asarray(f.result(45)).tolist() for f in epi_futs]

    execs1 = [e.executables()[0] for e in eng.engines]
    final_version = eng.weights_version
    stats = eng.stats()
    eng.close(drain=False, timeout=5.0)

    ref_eng = serving.GenerateEngine(
        _model(WEIGHT_SEEDS[weight_idx]), slots=4, page=16, max_len=48,
        prompt_buckets=(16,), queue_depth=256)
    ref_eng.warmup()
    ref = [np.asarray(
        ref_eng.submit(p, max_new_tokens=m, seed=s,
                       sampling={"temperature": 0.8}).result(45)).tolist()
           for p, m, s in epi]
    ref_eng.close()
    replay_identical = sum(1 for a, b in zip(epi_tokens, ref) if a == b)

    # -- exactly-one reqtrace record per admitted logical request --------
    # (census restricted to client-owned rids: probes and warmup also
    # trace, legitimately, and must not skew the count)
    rid_records = collections.Counter()
    jsonl = monitor.jsonl_path()
    if jsonl and os.path.exists(jsonl):
        with open(jsonl) as fh:
            for line in fh:
                try:
                    rec = json.loads(line)
                except ValueError:
                    continue
                if (rec.get("kind") == "serving.request"
                        and rec.get("rid") in client_rids):
                    rid_records[rec["rid"]] += 1
    dupes = {r: c for r, c in rid_records.items() if c != 1}
    missing = [r for r in admitted_rids if r not in rid_records]
    requests = len(client_rids)
    completed = counts["ok"] + len(epi)
    goodput = completed / requests if requests else 0.0

    result = {
        "duration_s": args.duration,
        "seed": args.seed,
        "requests": requests,
        "admitted": len(admitted_rids),
        "completed": completed,
        "gave_up": counts["gave_up"],
        "shed_attempts": counts["shed_attempts"],
        "failed_attempts": counts["failed_attempts"],
        "errors": {k[4:]: v for k, v in counts.items()
                   if k.startswith("err:")},
        "events": dict(events),
        "swap_errors": swap_errors[:3],
        "corrupt_refusals": refusals,
        "goodput": round(goodput, 4),
        "final_version": final_version,
        "failovers": stats.get("failovers", 0),
        "records": sum(rid_records.values()),
        "record_dupes": len(dupes),
        "records_missing": len(missing),
        "replay_identical": replay_identical,
        "replay_total": len(epi),
        "execs_before": execs0,
        "execs_after": execs1,
        "gates": {
            "goodput_floor": goodput >= 0.90,
            "zero_lost_futures": not lost,
            "exactly_one_record": not dupes and not missing,
            "zero_postwarmup_compiles": execs1 == execs0,
            "replay_bit_identical": replay_identical == len(epi),
            "corrupt_never_swapped": refusals == events["corrupt"],
            "load_actually_ran": completed >= args.duration * 2,
        },
    }
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_soak_chaos")
    ap.add_argument("--duration", type=float, default=60.0,
                    help="chaos phase length in seconds (short mode)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--clients", type=int, default=6)
    ap.add_argument("--replay", type=int, default=6,
                    help="epilogue bit-replay batch size")
    args = ap.parse_args()

    from paddle_tpu import monitor

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = os.path.join(args.out_dir, "soak_chaos.jsonl")
    if os.path.exists(jsonl):
        os.unlink(jsonl)   # the sink appends; stale records would
                           # corrupt the exactly-one-record census
    monitor.enable(jsonl)
    t0 = time.perf_counter()
    result = run_soak(args)
    result["wall_s"] = round(time.perf_counter() - t0, 3)
    result["ok_gate"] = all(result["gates"].values())
    monitor.emit(kind="soak_chaos", **result)
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok_gate"] else 1


if __name__ == "__main__":
    sys.exit(main())
