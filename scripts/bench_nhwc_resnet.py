"""End-to-end ResNet-50 train-step ablation on the real chip:
NCHW (the r4 headline layout) vs NHWC vs NHWC + fused Pallas BN.

This is the measurement VERDICT r4 task 2 asks for: the r4 roofline
(docs/perf_r04.md) showed BN's memory-bound chains at ~70% of the NCHW
step and named "fused stats+normalize Pallas BN, NHWC-native layout" as
the fix — this script decides whether to flip the headline layout and
_AUTO_ON['batch_norm'].

Methodology: same as bench.py — `inner` real optimizer steps chained in
one compiled call over distinct resident uint8 batches (normalize on
device), so tunnel dispatch amortizes.

Run: python -u scripts/bench_nhwc_resnet.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def run(data_format, pallas_bn, batch=128, inner=4, calls=3):
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt, jit, amp
    from paddle_tpu.models.resnet import resnet50
    from paddle_tpu.ops import pallas as P

    P.configure(batch_norm=pallas_bn)
    try:
        pt.seed(0)
        model = resnet50(data_format=data_format)
        o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                         parameters=model.parameters())
        rng = np.random.RandomState(0)
        shape = (inner, batch, 3, 224, 224) if data_format == "NCHW" \
            else (inner, batch, 224, 224, 3)
        x = (rng.rand(*shape) * 255).astype("u1")
        y = rng.randint(0, 1000, (inner, batch)).astype("i4")

        def norm(xb):
            return (xb.astype("float32") / 255.0 - 0.45) / 0.22

        def one(xb, yb):
            with amp.auto_cast(dtype="bfloat16"):
                logits = model(norm(xb))
            loss = pt.nn.functional.cross_entropy(
                logits.astype("float32"), yb)
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        def step(x_k, y_k):
            loss = None
            for i in range(inner):
                loss = one(x_k[i], y_k[i])
            return loss

        fn = jit.to_static(step, models=[model], optimizers=[o])
        tx, ty = pt.to_tensor(x), pt.to_tensor(y)
        fn(tx, ty)
        fn(tx, ty).numpy()
        t0 = time.perf_counter()
        loss = None
        for _ in range(calls):
            loss = fn(tx, ty)
        loss.numpy()
        dt = (time.perf_counter() - t0) / (calls * inner)
        return batch / dt, float(loss.numpy())
    finally:
        P.configure(batch_norm=None)


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/paddle_tpu_xla_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    rows = [("NCHW xla-bn", "NCHW", False),
            ("NHWC xla-bn", "NHWC", False),
            ("NHWC pallas-bn", "NHWC", True)]
    results = {}
    for label, fmt, pbn in rows:
        try:
            ips, loss = run(fmt, pbn)
            results[label] = ips
            print(f"resnet50 {label:>15}: {ips:8,.1f} img/s  "
                  f"loss={loss:.4f}", flush=True)
        except Exception as e:
            print(f"resnet50 {label:>15}: FAIL {type(e).__name__}: {e}",
                  flush=True)
    if results:
        best = max(results, key=results.get)
        base = results.get("NCHW xla-bn")
        print(f"winner: {best}" + (
            f"  ({(results[best] / base - 1) * 100:+.1f}% vs NCHW)"
            if base else ""), flush=True)
        if best == "NHWC pallas-bn":
            print("-> flip _AUTO_ON['batch_norm']=True (channels-last) "
                  "and headline NHWC in bench.py", flush=True)
        elif best == "NHWC xla-bn":
            print("-> headline NHWC in bench.py; keep pallas BN off",
                  flush=True)
        else:
            print("-> keep NCHW headline; record table in "
                  "docs/perf_r05.md", flush=True)


if __name__ == "__main__":
    main()
