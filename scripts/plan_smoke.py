"""Auto-sharding planner smoke gate (tier-1-safe: 8 virtual CPU
devices, seconds).

The PR 11 acceptance run, end to end:

* **bit identity** — ``MegatronConfig(mesh_plan=MEGATRON_RULES)`` must
  reproduce the hand-written dp2/tp2/ep2 megatron layout exactly: every
  PartitionSpec matches in lists form, and training is bit-identical
  (losses AND final params) against the hand config for every step.
* **zero extra recompiles** — an ``hapi.Model.fit(mesh_plan=...)`` run
  compiles exactly as often as the identical plan-free fit (once), with
  ``jit.recompile`` flat.
* **advisor sanity** — ``planner.advise`` returns a non-empty ranked
  table and is rank-stable across calls.
* **prediction vs reality** — an A/B between two mesh factorizations
  (dp8 vs dp2xtp4, same GLOBAL batch fed to both): the layout the cost
  model ranks fastest must BE the measured-fastest. The model sizes are
  chosen so the gap is structural (tp replicates the vocab logits
  matmul per rank), not a timing coin-flip.

Writes the monitor JSONL to --out-dir and prints one JSON result line
(the bench `planner` stage parses it). Exit code 0 iff every gate
passes.
"""
import argparse
import json
import os
import statistics
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_plan_smoke")
    ap.add_argument("--steps", type=int, default=3,
                    help="bit-identity training steps")
    ap.add_argument("--timing-steps", type=int, default=5,
                    help="measured steps per A/B layout (post-warmup)")
    args = ap.parse_args()

    import jax
    import jax.numpy as jnp
    import paddle_tpu as pt
    from paddle_tpu import hapi, monitor, nn, optimizer as opt
    from paddle_tpu.io import TensorDataset
    from paddle_tpu.parallel import layout, megatron as M, planner

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "plan_smoke.jsonl"))
    reg = monitor.registry()
    assert len(jax.devices()) >= 8, "needs 8 virtual devices"

    # -- gate 1+2: one config line == the hand megatron layout --------
    mesh, sizes = M.make_mesh(8, sizes={"dp": 2, "tp": 2, "ep": 2})
    cfg = M.MegatronConfig(vocab_size=128, hidden=32, n_heads=2,
                           layers_per_stage=1, seq_len=16, microbatch=2,
                           n_micro=2)
    params, hand_specs = M.init_params(cfg, mesh)
    mplan = planner.MeshPlan(planner.MEGATRON_RULES, mesh=mesh,
                             name="megatron")
    mismatches = []
    for name, value in params.items():
        nd = np.asarray(jax.device_get(value)).ndim
        want = layout.spec_to_lists(hand_specs[name], nd)
        got = layout.spec_to_lists(mplan.spec_for(name, np.shape(value)),
                                   nd)
        if got != want:
            mismatches.append((name, got, want))

    s_hand, step_hand = M.build_train_step(cfg, mesh)
    s_plan, step_plan = M.build_train_step(
        cfg._replace(mesh_plan=planner.MEGATRON_RULES), mesh)
    rng = np.random.RandomState(0)
    batch_g = cfg.microbatch * sizes["dp"]
    losses_hand, losses_plan = [], []
    for _ in range(args.steps):
        toks = jnp.asarray(rng.randint(0, cfg.vocab_size,
                                       (cfg.n_micro, batch_g,
                                        cfg.seq_len)), jnp.int32)
        s_hand, lh = step_hand(s_hand, toks)
        s_plan, lp = step_plan(s_plan, toks)
        losses_hand.append(float(lh))
        losses_plan.append(float(lp))
    params_equal = all(
        np.array_equal(np.asarray(jax.device_get(s_hand["params"][k])),
                       np.asarray(jax.device_get(s_plan["params"][k])))
        for k in s_hand["params"])
    bit_identical = losses_hand == losses_plan and params_equal

    # -- gate 3: fit(mesh_plan=) costs zero extra executables ---------
    def _fit(mesh_plan):
        pt.seed(0)
        r = np.random.RandomState(1)
        x = r.randn(64, 8).astype("f4")
        y = r.randint(0, 3, size=(64,)).astype("i4")
        net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                            nn.Linear(16, 3))
        m = hapi.Model(net)
        m.prepare(optimizer=opt.Adam(learning_rate=0.05,
                                     parameters=m.parameters()),
                  loss_function=hapi.CrossEntropy())
        c0 = reg.value("jit.compile", 0)
        r0 = reg.value("jit.recompile", 0)
        m.fit(TensorDataset(x, y), batch_size=16, epochs=2, verbose=0,
              mesh_plan=mesh_plan)
        return (reg.value("jit.compile", 0) - c0,
                reg.value("jit.recompile", 0) - r0)

    fit_plan = planner.MeshPlan(planner.TRANSFORMER_RULES,
                                mesh=jax.sharding.Mesh(
                                    np.asarray(jax.devices()).reshape(
                                        4, 2), ("dp", "tp")))
    compiles_hand, rec_hand = _fit(None)
    compiles_plan, rec_plan = _fit(fit_plan)
    zero_extra = (compiles_plan == compiles_hand == 1
                  and rec_plan == rec_hand == 0)

    # -- gate 4: advisor table non-empty + rank-stable ----------------
    acfg = M.MegatronConfig(vocab_size=512, hidden=64, n_heads=4,
                            layers_per_stage=1, seq_len=32, microbatch=8,
                            n_micro=1, use_moe=False)
    t1 = planner.advise(n_devices=8, cfg=acfg, global_batch=8)
    t2 = planner.advise(n_devices=8, cfg=acfg, global_batch=8)
    advisor_ok = (len(t1) >= 2
                  and [r["sizes"] for r in t1] == [r["sizes"] for r in t2]
                  and [r["rank"] for r in t1] == list(range(1,
                                                            len(t1) + 1)))

    # -- gate 5: predicted-fastest == measured-fastest (A/B) ----------
    cand = [{"dp": 8}, {"dp": 2, "tp": 4}]
    ab = planner.advise(cfg=acfg, candidates=cand, global_batch=8)
    predicted_best = ab[0]["sizes"]

    measured = {}
    for c in cand:
        mesh_c, sizes_c = M.make_mesh(8, sizes=c)
        cfg_c = acfg._replace(microbatch=8 // sizes_c["dp"])
        state, step = M.build_train_step(cfg_c, mesh_c)
        r = np.random.RandomState(7)
        toks = jnp.asarray(r.randint(0, acfg.vocab_size,
                                     (acfg.n_micro, 8, acfg.seq_len)),
                           jnp.int32)
        state, loss = step(state, toks)       # warmup: compile
        jax.block_until_ready(loss)
        ts = []
        for _ in range(args.timing_steps):
            t0 = time.perf_counter()
            state, loss = step(state, toks)
            jax.block_until_ready(loss)
            ts.append(time.perf_counter() - t0)
        measured[json.dumps(c, sort_keys=True)] = statistics.median(ts)
    measured_best = json.loads(min(measured, key=measured.get))
    prediction_ok = predicted_best == measured_best

    # -- ledger: record the decision the bench stage banks ------------
    chosen = planner.plan(auto=True, cfg=acfg, n_devices=8,
                          candidates=cand, global_batch=8,
                          name="plan_smoke")
    decision = planner.last_decision()

    result = {
        "metric": "plan_smoke",
        "spec_mismatches": len(mismatches),
        "losses_hand": losses_hand,
        "losses_planned": losses_plan,
        "fit_compiles_hand": compiles_hand,
        "fit_compiles_planned": compiles_plan,
        "fit_recompiles_planned": rec_plan,
        "advisor_table": [{k: r[k] for k in ("rank", "sizes",
                                             "pred_step_s", "bound")}
                          for r in t1],
        "ab_predicted_best": predicted_best,
        "ab_measured_best": measured_best,
        "ab_measured_s": measured,
        "planner_candidates": len(t1),
        "planner_predicted_step_s": round(ab[0]["pred_step_s"], 9),
        "planner_chosen": "x".join(f"{a}{s}" for a, s in
                                   sorted(chosen.sizes.items())
                                   if s > 1),
        "planner_decision_recorded": bool(decision),
        "jsonl": jsonl,
    }
    gates = {
        "specs_match_hand": not mismatches,
        "bit_identical": bit_identical,
        "zero_extra_recompiles": zero_extra,
        "advisor_nonempty_rank_stable": advisor_ok,
        "predicted_matches_measured": prediction_ok,
    }
    result["gates"] = gates
    result["pass"] = all(gates.values())
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
