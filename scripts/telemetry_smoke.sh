#!/usr/bin/env bash
# CI gate for the fleet telemetry plane (monitor/fleet.py + alerts.py):
# a 4-process decode fleet publishes versioned metric snapshots into a
# shared directory while one worker drags its ticks (replica_slow
# fault) and one mints a post-warmup compile burst. The parent's
# FleetAggregator + AnomalyDetector + AlertManager must: merge counters
# to the per-worker oracle exactly, land merged p50/p99 within one
# histogram bucket of the union-of-events percentile, fire AND resolve
# exactly the two expected alerts (straggler + compile storm, each
# naming source and series, both cited in the supervisor's decision
# ledger), reconcile the goodput ledger to wall time within 5%, keep
# publish overhead <= 1% of worker wall, and publish NOTHING with the
# monitor disabled. CPU-only, ~1 min.
#
# Usage: scripts/telemetry_smoke.sh [out_dir]
# The last stdout line is one JSON result record (bench.py parses it).
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_telemetry_smoke}"
JAX_PLATFORMS=cpu \
python scripts/telemetry_smoke.py --out-dir "$OUT_DIR"
