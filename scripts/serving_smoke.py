"""Online-serving smoke gate (tier-1-safe: tiny MLP, CPU, seconds).

Drives 200 concurrent ragged requests through a warmed ServingEngine
and asserts the ISSUE 5 acceptance criteria from the monitor counters
and the engine's own ledger:

* ``serving.compiles`` stops growing after warmup — steady-state
  traffic performs ZERO fresh XLA compiles
* ``serving.batch_fill`` mean > 1 — dynamic batching actually
  coalesces (requests per executed batch)
* zero lost futures — every submitted request resolves with a result
  (no hang, no silent drop); rejected submits raise synchronously and
  are counted, not lost
* p99 latency is measured and recorded to the monitor JSONL (one
  ``serving_smoke`` record) as the CI artifact

Prints one JSON result line; exit code 0 iff every gate passes.
"""
import argparse
import concurrent.futures
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_serving_smoke")
    ap.add_argument("--requests", type=int, default=200)
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--timeout-ms", type=float, default=3.0)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import inference, monitor, nn, serving

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "serving_smoke.jsonl"))

    pt.seed(0)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                          nn.Linear(64, 4))
    eng = serving.ServingEngine(
        inference.Predictor(model), buckets=[8, args.max_batch],
        max_batch=args.max_batch, timeout_ms=args.timeout_ms,
        queue_depth=1024)
    eng.warmup([((16,), "float32")])
    reg = monitor.registry()
    compiles_after_warmup = int(reg.value("serving.compiles", 0))

    sizes = [1, 3, 7, 13]
    per_client = args.requests // args.clients
    latencies, errors = [], []
    lat_lock = threading.Lock()
    barrier = threading.Barrier(args.clients)

    def client(k):
        rng = np.random.RandomState(k)
        barrier.wait()
        for i in range(per_client):
            x = rng.rand(sizes[(k + i) % len(sizes)], 16).astype("f4")
            t0 = time.perf_counter()
            try:
                out = eng.run(x, timeout=30)
                if out.shape != (x.shape[0], 4):
                    raise AssertionError(f"bad shape {out.shape}")
            except Exception as e:  # noqa: BLE001 - gate counts these
                errors.append(repr(e))
                continue
            with lat_lock:
                latencies.append((time.perf_counter() - t0) * 1e3)

    t_start = time.perf_counter()
    threads = [threading.Thread(target=client, args=(k,))
               for k in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall_s = time.perf_counter() - t_start
    eng.close()

    n_sent = per_client * args.clients
    stats = eng.stats()
    compiles_final = int(reg.value("serving.compiles", 0))
    fill = reg.value("serving.batch_fill") or {}
    mean_fill = (fill.get("sum", 0.0) / fill["count"]) \
        if fill.get("count") else 0.0
    lat = sorted(latencies)

    def pct(p):
        return round(lat[min(int(len(lat) * p), len(lat) - 1)], 3) \
            if lat else None

    gates = {
        "no_post_warmup_compiles": compiles_final == compiles_after_warmup,
        "batch_fill_gt_1": mean_fill > 1.0,
        "zero_lost_futures": (not errors
                              and len(latencies) == n_sent
                              and stats["completed"] == n_sent),
        "p99_recorded": bool(lat),
    }
    result = {
        "requests": n_sent,
        "clients": args.clients,
        "wall_s": round(wall_s, 3),
        "qps": round(n_sent / wall_s, 1),
        "p50_ms": pct(0.50),
        "p99_ms": pct(0.99),
        "mean_batch_fill": round(mean_fill, 3),
        "batches": stats["batches"],
        "compiles_warmup": compiles_after_warmup,
        "compiles_final": compiles_final,
        "errors": errors[:5],
        "gates": gates,
        "jsonl": jsonl,
        "ok": all(gates.values()),
    }
    monitor.emit(kind="serving_smoke", **{k: v for k, v in result.items()
                                          if k not in ("jsonl",)})
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
