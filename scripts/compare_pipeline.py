"""PipelineStack (GSPMD stacked-scan) vs the explicit 1F1B executor at
the SAME geometry on the virtual CPU mesh — the data behind
docs/distributed.md's production-path decision (VERDICT r4 task 7).

Geometry: 4 stages x 1 block/stage, hidden H, global batch B split into
M microbatches for the executor; the stack consumes the full batch in
one scan. Reports wall step-time (CPU-mesh proxy — ICI-free, so only
the schedule/dispatch overheads differ, NOT collective time) plus the
analytic schedule numbers (bubble fraction, peak live activations) that
do transfer to real hardware.

Run: python -u scripts/compare_pipeline.py
"""
import os
import sys
import time

os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import jax  # noqa: E402
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax.sharding import Mesh, PartitionSpec as P  # noqa: E402

N_RANKS, N_MICRO, H, B = 4, 8, 256, 32
STEPS = 20


def _params(rng):
    return {
        "w": jnp.asarray(rng.randn(N_RANKS, H, H) * 0.1, jnp.float32),
        "b": jnp.zeros((N_RANKS, H), jnp.float32),
    }


def run_stack():
    """GSPMD path: full batch, stage-stacked weights, lax.scan; grads by
    plain jax.grad; mesh pp4 shards the stacked axis."""
    rng = np.random.RandomState(0)
    params = _params(rng)
    mesh = Mesh(np.asarray(jax.devices()[:N_RANKS]), ("pp",))
    from jax.sharding import NamedSharding
    params = {k: jax.device_put(v, NamedSharding(
        mesh, P(*(("pp",) + (None,) * (v.ndim - 1)))))
        for k, v in params.items()}
    x = jnp.asarray(rng.randn(B, H), jnp.float32)
    lab = jnp.asarray(rng.randn(B, H), jnp.float32)

    def fwd(params, x):
        def body(h, sl):
            return h + jnp.tanh(h @ sl[0] + sl[1]), None
        h, _ = jax.lax.scan(body, x, (params["w"], params["b"]))
        return h

    @jax.jit
    def step(params, x, lab):
        def loss_fn(p):
            return jnp.mean((fwd(p, x) - lab) ** 2)
        loss, g = jax.value_and_grad(loss_fn)(params)
        return loss, g

    step(params, x, lab)[0].block_until_ready()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        loss, g = step(params, x, lab)
    loss.block_until_ready()
    return (time.perf_counter() - t0) / STEPS * 1e3, float(loss)


def run_executor(kind="1f1b"):
    """Explicit schedule: M microbatches over a ppermute ring."""
    from paddle_tpu.parallel.pipeline import build_schedule, pipeline_step
    rng = np.random.RandomState(0)
    params = _params(rng)
    sched = build_schedule(kind, N_RANKS, N_MICRO)
    x = jnp.asarray(rng.randn(N_MICRO, B // N_MICRO, H), jnp.float32)
    lab = jnp.asarray(rng.randn(N_MICRO, B // N_MICRO, H), jnp.float32)
    mesh = Mesh(np.asarray(jax.devices()[:N_RANKS]), ("pp",))

    def stage(h, p):
        return h + jnp.tanh(h @ p["w"] + p["b"])

    def loss_fn(y, t):
        return jnp.mean((y - t) ** 2)

    def fn(params, x, lab):
        return pipeline_step(sched, stage, loss_fn, params, x, lab,
                             axis="pp")

    step = jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), params),
                  P(), P()),
        out_specs=(P(), jax.tree_util.tree_map(lambda _: P("pp"),
                                               params)),
        check_vma=False))
    step(params, x, lab)[0].block_until_ready()
    t0 = time.perf_counter()
    for _ in range(STEPS):
        loss, g = step(params, x, lab)
    loss.block_until_ready()
    return ((time.perf_counter() - t0) / STEPS * 1e3, float(loss), sched)


def main():
    ms_stack, loss_s = run_stack()
    print(f"PipelineStack  (GSPMD scan, pp{N_RANKS}, full batch {B}): "
          f"{ms_stack:8.2f} ms/step  loss={loss_s:.4f}")
    for kind in ("1f1b", "gpipe"):
        ms, loss, sched = run_executor(kind)
        print(f"executor {kind:>6} (pp{N_RANKS} x {N_MICRO} micro):"
              f"          {ms:8.2f} ms/step  loss={loss:.4f}  "
              f"bubble={sched.bubble_fraction():.3f}  "
              f"peak_acts={sched.peak_live_activations()} micro "
              f"(= {sched.peak_live_activations() * B // N_MICRO} rows "
              f"vs stack's {B})")


if __name__ == "__main__":
    main()
