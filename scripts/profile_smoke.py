"""Per-op cost-attribution smoke gate (tier-1-safe: tiny MLP, CPU,
seconds).

Runs a 2-layer MLP + Adam train step under ``jit.to_static`` with
profiling scopes armed, builds the per-op cost ledger from the captured
step executable, and asserts the acceptance criteria directly:

* >= 90% of the step's flops attribute to named framework scopes
  (layers / functional ops / the optimizer update — never the root)
* the parser's flop total reconciles with XLA's own ``cost_analysis()``
  within 1%
* the ranked hotspot list is non-empty, rank-ordered 1..k, and sorted
  by fusion headroom (descending)
* one ``hotspot`` JSONL record per ranked region landed in the sink
* disabled mode stays free: with scopes off, a layer call must not
  touch the scope registry

Writes the monitor JSONL to --out-dir and prints one JSON result line.
Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_profile_smoke")
    ap.add_argument("--hidden", type=int, default=32)
    ap.add_argument("--batch", type=int, default=16)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import jit, monitor, nn, optimizer as opt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.monitor.registry import read_jsonl

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "profile_smoke.jsonl"))
    monitor.profile.enable()

    pt.seed(0)
    model = nn.Sequential(nn.Linear(16, args.hidden), nn.ReLU(),
                          nn.Linear(args.hidden, 10))
    adam = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

    @jit.to_static(models=[model], optimizers=[adam])
    def step(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        adam.step()
        return loss

    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(args.batch, 16).astype("f4"))
    y = pt.to_tensor(rng.randint(0, 10, (args.batch,)).astype("i8"))
    for _ in range(2):
        loss = step(x, y)
    loss.numpy()

    rep = monitor.profile.report(top_k=8)
    if rep is None:
        print(json.dumps({"metric": "profile_smoke", "pass": False,
                          "error": "no captured executable"}))
        return 1

    heads = [h["headroom_s"] for h in rep["hotspots"]]
    ranks = [h["rank"] for h in rep["hotspots"]]
    recs = [r for r in read_jsonl(jsonl) if r.get("kind") == "hotspot"]
    recon = rep["flops_reconciliation"]

    # disabled mode: one flag check, no registry traffic
    monitor.profile.disable()
    scopes_before = len(monitor.profile.scopes())
    nn.Linear(4, 4)(pt.to_tensor(np.zeros((2, 4), dtype="f4")))
    scopes_added = len(monitor.profile.scopes()) - scopes_before

    result = {
        "metric": "profile_smoke",
        "label": rep["label"],
        "total_flops": rep["total_flops"],
        "attributed_frac": round(rep["attributed_frac"], 4),
        "flops_reconciliation": (round(recon, 4)
                                 if recon is not None else None),
        "hotspot_count": len(rep["hotspots"]),
        "top_region": (rep["hotspots"][0]["region"]
                       if rep["hotspots"] else None),
        "device_kind": rep["ceilings"]["device_kind"],
        "assumed_roofline": rep["ceilings"]["assumed"],
        "hotspot_jsonl_records": len(recs),
        "disabled_scopes_added": scopes_added,
        "jsonl": jsonl,
    }
    gates = {
        "attributed_frac>=0.9": rep["attributed_frac"] >= 0.9,
        "flops_reconcile_1pct": (recon is not None
                                 and abs(recon - 1.0) <= 0.01),
        "hotspots_nonempty": len(rep["hotspots"]) >= 1,
        "hotspots_rank_ordered": (
            ranks == list(range(1, len(ranks) + 1))
            and heads == sorted(heads, reverse=True)),
        "hotspot_jsonl_records==count":
            len(recs) == len(rep["hotspots"]),
        "disabled_adds_no_scopes": scopes_added == 0,
    }
    result["gates"] = gates
    result["pass"] = all(gates.values())
    print(monitor.profile.format_table(rep), file=sys.stderr)
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
