#!/usr/bin/env bash
# CI gate for the span tracer: a short traced hapi fit must export a
# Perfetto-loadable Chrome trace with the prefetch producer and the
# step loop on separate thread tracks, at least one overlapping
# prefetch.produce/fit.step span pair, and a disabled-mode tracer that
# records nothing. Tier-1-safe: tiny MLP, CPU backend, seconds.
#
# Usage: scripts/trace_smoke.sh [out_dir]
# trace.json + the monitor JSONL land in out_dir (default
# /tmp/paddle_tpu_trace_smoke) as CI artifacts; the last stdout line is
# one JSON result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_trace_smoke}"
JAX_PLATFORMS=cpu python scripts/trace_smoke.py --out-dir "$OUT_DIR"
