#!/usr/bin/env bash
# CI gate for per-op cost attribution: a 2-layer MLP + Adam to_static
# step must attribute >= 90% of its XLA-counted flops to named
# framework scopes, reconcile the parsed flop total with
# cost_analysis() within 1%, rank a non-empty hotspot menu by fusion
# headroom, and land one `hotspot` JSONL record per ranked region.
# Tier-1-safe: tiny MLP, CPU, seconds.
#
# Usage: scripts/profile_smoke.sh [out_dir]
# The monitor JSONL lands in out_dir (default
# /tmp/paddle_tpu_profile_smoke); the last stdout line is one JSON
# result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_profile_smoke}"
JAX_PLATFORMS=cpu python scripts/profile_smoke.py --out-dir "$OUT_DIR"
