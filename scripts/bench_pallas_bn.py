"""Fused Pallas batch-norm vs XLA on the real chip.

Two measurements, both with per-call state advancement (this tunnel
serves repeated identical dispatches from cache — docs/perf_r04.md):

1. BN-microbench: chained fwd+bwd over a ResNet-stage-shaped (M, C)
   activation, Pallas kernel vs the one-pass XLA path.
2. Full NHWC ResNet-50 train step (the kernel requires channels-last),
   batch_norm kernel on vs off.

If the kernel wins, flip _AUTO_ON['batch_norm'] (channels-last only).
Run: python -u scripts/bench_pallas_bn.py
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def micro(use_pallas, m=128 * 28 * 28, c=256, iters=12):
    import jax
    import jax.numpy as jnp
    from paddle_tpu.ops.pallas.batch_norm import _batch_norm2

    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(m, c), jnp.bfloat16)
    w = jnp.asarray(rng.rand(c) + 0.5, jnp.float32)
    b = jnp.asarray(rng.randn(c), jnp.float32)

    def xla_bn(x2, w, b, eps=1e-5):
        # Baseline = the PRODUCTION XLA path (shifted one-pass moments +
        # folded scale/shift from nn_ops), not a hand-rolled variant:
        # the auto-on decision must compare the kernel against the exact
        # program it would replace (r4 advisor finding).
        from paddle_tpu.ops.nn_ops import _fold_scale_shift, \
            _one_pass_moments
        mean, var = _one_pass_moments(x2, (0,))
        return _fold_scale_shift(x2, mean, var, w, b, eps, (1, x2.shape[1]))

    bn = (lambda x: _batch_norm2(x, w, b, 1e-5)[0]) if use_pallas \
        else (lambda x: xla_bn(x, w, b))

    @jax.jit
    def chain(x):
        def body(i, x):
            def f(x):
                return jnp.sum(bn(x).astype(jnp.float32)) * 1e-6
            g = jax.grad(f)(x)
            return (x + g.astype(x.dtype)).astype(x.dtype)
        return jax.lax.fori_loop(0, iters, body, x)[0, 0]

    float(chain(x))  # compile + warm
    t0 = time.perf_counter()
    float(chain(x))
    dt = (time.perf_counter() - t0) / iters
    # fwd: 2 reads + 1 write; bwd: 2+2 reads + 1 write (bf16)
    gb = m * c * 2 * 8 / 1e9
    return dt * 1e3, gb / dt


def full_resnet(use_pallas, batch=128, inner=8):
    from paddle_tpu.ops import pallas as P

    P.configure(batch_norm=use_pallas)
    try:
        return _full_resnet_body(batch, inner)
    finally:
        P.configure(batch_norm=None)


def _full_resnet_body(batch, inner):
    import paddle_tpu as pt
    from paddle_tpu import optimizer as opt, jit, amp
    from paddle_tpu.models.resnet import resnet50

    pt.seed(0)
    model = resnet50(data_format="NHWC")
    o = opt.Momentum(learning_rate=0.1, momentum=0.9,
                     parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = rng.rand(inner, batch, 224, 224, 3).astype("f4")
    y = rng.randint(0, 1000, (inner, batch)).astype("i4")

    def one(xb, yb):
        with amp.auto_cast(dtype="bfloat16"):
            logits = model(xb)
        loss = pt.nn.functional.cross_entropy(logits.astype("float32"), yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    def step(x_k, y_k):
        loss = None
        for i in range(inner):
            loss = one(x_k[i], y_k[i])
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    tx, ty = pt.to_tensor(x), pt.to_tensor(y)
    fn(tx, ty)
    fn(tx, ty).numpy()
    t0 = time.perf_counter()
    for _ in range(2):
        loss = fn(tx, ty)
    loss.numpy()
    dt = (time.perf_counter() - t0) / (2 * inner)
    return batch / dt, float(loss.numpy())


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/paddle_tpu_xla_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    for use in (False, True):
        ms, gbs = micro(use)
        print(f"micro  pallas={int(use)}: {ms:7.3f} ms/iter  "
              f"{gbs:6.0f} GB/s effective", flush=True)
    for use in (False, True):
        try:
            ips, loss = full_resnet(use)
            print(f"resnet NHWC pallas={int(use)}: {ips:,.1f} img/s "
                  f"loss={loss:.4f}", flush=True)
        except Exception as e:
            print(f"resnet NHWC pallas={int(use)}: FAIL "
                  f"{type(e).__name__}: {e}", flush=True)


if __name__ == "__main__":
    main()
