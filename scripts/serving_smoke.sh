#!/usr/bin/env bash
# CI gate for the serving tier: 200 concurrent ragged requests through
# a warmed ServingEngine must coalesce (mean batch_fill > 1), perform
# zero post-warmup XLA compiles, lose no futures, and record p50/p99
# latency to the monitor JSONL. Tier-1-safe: tiny MLP, CPU, seconds.
#
# Usage: scripts/serving_smoke.sh [out_dir]
# The monitor JSONL (with the serving_smoke record) lands in out_dir
# (default /tmp/paddle_tpu_serving_smoke) as the CI artifact; the last
# stdout line is one JSON result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_serving_smoke}"
JAX_PLATFORMS=cpu python scripts/serving_smoke.py --out-dir "$OUT_DIR"
