#!/usr/bin/env bash
# CI gate for continuous-batching generative decode: slot churn with
# zero lost futures and zero post-warmup compiles, KV-pool bytes equal
# to the closed-form budget prediction under a virtual HBM limit,
# continuous refill >= 2x the run-to-completion drain baseline's
# tokens/s at the same slot count, and a tokens_floor supervisor
# scale-up driven by the live decode SLO window. Tier-1-safe: tiny
# models, CPU (2 virtual devices for the scale-up phase), ~1 min.
#
# Usage: scripts/decode_smoke.sh [out_dir]
# The monitor JSONL (with the decode_smoke record) lands in out_dir
# (default /tmp/paddle_tpu_decode_smoke); the last stdout line is one
# JSON result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_decode_smoke}"
JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=2" \
python scripts/decode_smoke.py --out-dir "$OUT_DIR"
