"""Native C MultiSlot parser vs python tokenization (CPU-side, no TPU
needed — the host ingest half of the CTR pipeline, reference
data_feed.cc). Prints MB/s for both paths over a synthetic Criteo-like
file (26 int id slots + 13 dense floats + label).

Run: python -u scripts/bench_multislot.py
"""
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def make_file(path, n_records=20000):
    rng = np.random.RandomState(0)
    with open(path, "w") as fh:
        for _ in range(n_records):
            ids = rng.randint(0, 10**9, 26)
            dense = rng.rand(13)
            parts = ["26", " ".join(map(str, ids)),
                     "13", " ".join(f"{v:.6f}" for v in dense),
                     "1", str(rng.randint(0, 2))]
            fh.write(" ".join(parts) + "\n")
    return os.path.getsize(path)


def bench(ds, reps=3):
    t0 = time.perf_counter()
    for _ in range(reps):
        ds.load_into_memory()
    load = (time.perf_counter() - t0) / reps
    t0 = time.perf_counter()
    for _ in range(reps):
        nb = sum(1 for _ in ds._batches())
    return load, (time.perf_counter() - t0) / reps, nb


def main():
    from paddle_tpu import fluid

    class V:
        def __init__(self, name, dtype):
            self.name, self.dtype = name, dtype

    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "criteo.txt")
        nbytes = make_file(path)
        print(f"file: {nbytes / 1e6:.1f} MB, 20k records "
              f"(26 int-id slots, 13 dense, label)")
        results = {}
        for use_native in (False, True):
            ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
            ds.set_batch_size(256)
            ds.set_filelist([path])
            ds.set_use_var([V("ids", "int64"), V("dense", "float32"),
                            V("label", "int64")])
            ds.use_native_parse = use_native
            load, batcht, nb = bench(ds)
            label = "native C" if use_native else "python  "
            results[use_native] = load + batcht
            print(f"{label}: load {load * 1e3:7.1f} ms "
                  f"({nbytes / load / 1e6:6.1f} MB/s)  "
                  f"+ assemble {batcht * 1e3:7.1f} ms ({nb} batches)")
        sp = results[False] / results[True]
        print(f"native end-to-end (load+assemble) speedup: {sp:.2f}x")


if __name__ == "__main__":
    main()
