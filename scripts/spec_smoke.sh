#!/usr/bin/env bash
# CI gate for sampled + speculative decoding: greedy spec output bit-
# identical to non-spec (distilled draft), sampled self-draft streams
# bit-identical with every proposal accepted, seed-reproducible streams
# across admission orders, and the loadgen A/B on the distilled demo
# pair — spec >= 1.5x plain sampled tokens/s at k=4 and >= 2.0x at
# k=8, accept rate >= 0.9, zero post-warmup compiles in every arm.
# Tier-1-safe: tiny models, CPU, a few minutes.
#
# Usage: scripts/spec_smoke.sh [out_dir]
# The monitor JSONL (with the spec_smoke record) lands in out_dir
# (default /tmp/paddle_tpu_spec_smoke); the last stdout line is one
# JSON result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_spec_smoke}"
JAX_PLATFORMS=cpu \
python scripts/spec_smoke.py --out-dir "$OUT_DIR"
