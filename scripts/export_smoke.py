"""Live-telemetry smoke gate (tier-1-safe: tiny MLP, CPU, seconds).

Trains a small hapi.Model with the telemetry plane armed
(``fit(metrics_port=0)``) and scrapes the HTTP endpoints FROM INSIDE
the training loop (a mid-run callback) — the acceptance criterion is
literally "curl /metrics during fit and get live series back":

* ``/metrics`` mid-run parses as OpenMetrics (``# TYPE`` lines, final
  ``# EOF``) and contains executor/dispatch activity counters AND at
  least one sampled ``mem_*`` gauge (``mem.host.rss_bytes`` is
  guaranteed even on CPU, where per-device HBM stats are empty)
* ``/healthz`` answers 200 with watchdog + NaN-guard state mid-run
* ``/snapshot`` answers with the counter snapshot
* ``monitor.disable()`` tears everything down: no paddle_tpu
  threads survive, the port stops answering
* ``scripts/perf_sentinel.py`` passes on the repo's own banked
  artifacts (module-level invocation — the gate proves the sentinel
  runs clean at head, not just in its unit tests)

Prints one JSON result line; exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import time
import urllib.request

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _ROOT)

import numpy as np


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode("utf-8"), \
            r.headers.get("Content-Type", "")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_export_smoke")
    ap.add_argument("--steps", type=int, default=48)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import hapi, io, monitor, nn, optimizer as opt

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "export_smoke.jsonl"))
    # fast sampler tick so a ~seconds-long fit gets several samples
    os.environ["PADDLE_TPU_SAMPLER_INTERVAL_S"] = "0.05"

    pt.seed(0)
    rng = np.random.RandomState(0)
    x = rng.rand(args.steps * 8, 16).astype("f4")
    y = rng.randint(0, 4, (args.steps * 8,)).astype("i8")
    ds = io.TensorDataset(x, y)

    m = hapi.Model(nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                                 nn.Linear(32, 4)))
    m.prepare(optimizer=opt.Adam(learning_rate=0.05,
                                 parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())

    scraped = {}

    class MidRunScrape(hapi.Callback):
        """Scrape every endpoint while the step loop is live."""

        def on_train_batch_end(self, step, logs=None):
            if scraped or step < args.steps // 2:
                return
            port = monitor.export.port()
            time.sleep(0.15)  # let the sampler tick at least twice
            scraped["port"] = port
            scraped["metrics"] = _get(port, "/metrics")
            scraped["healthz"] = _get(port, "/healthz")
            scraped["snapshot"] = _get(port, "/snapshot")

    m.fit(ds, batch_size=8, epochs=1, verbose=0, watchdog=True,
          prefetch=2, metrics_port=0, callbacks=[MidRunScrape()])

    port = scraped.get("port")
    status, text, ctype = scraped.get("metrics", (0, "", ""))
    h_status, h_body, _ = scraped.get("healthz", (0, "{}", ""))
    s_status, s_body, _ = scraped.get("snapshot", (0, "{}", ""))
    health = json.loads(h_body or "{}")
    snap = json.loads(s_body or "{}")
    metric_names = {line.split("{")[0].split(" ")[0]
                    for line in text.splitlines()
                    if line and not line.startswith("#")}

    # teardown: disable() must join the server + sampler and free the port
    monitor.disable()
    time.sleep(0.3)
    import threading
    leaked = [t.name for t in threading.enumerate()
              if "paddle_tpu" in t.name]
    port_dead = True
    try:
        _get(port, "/healthz")
        port_dead = False
    except Exception:
        pass

    sentinel_rc = None
    try:
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "perf_sentinel", os.path.join(_ROOT, "scripts",
                                          "perf_sentinel.py"))
        sentinel = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sentinel)
        sentinel_rc = sentinel.main(["--repo-root", _ROOT])
    except Exception as e:  # noqa: BLE001 - gate reports, not raises
        sentinel_rc = f"crashed: {e!r}"

    gates = {
        "metrics_200_openmetrics": (status == 200
                                    and "openmetrics-text" in ctype
                                    and text.rstrip().endswith("# EOF")
                                    and "# TYPE" in text),
        "executor_series_present": any(
            n.startswith(("executor_", "dispatch_", "jit_"))
            for n in metric_names),
        "mem_gauge_present": any(n.startswith("mem_")
                                 for n in metric_names),
        "prefetch_series_present": any(n.startswith("prefetch_")
                                       for n in metric_names),
        "healthz_ok_midrun": (h_status == 200
                              and health.get("status") == "ok"
                              and health.get("watchdogs")
                              and "nan_guard" in health),
        "snapshot_answers": s_status == 200 and "counters" in snap,
        "teardown_clean": port_dead and not leaked,
        "sentinel_clean_at_head": sentinel_rc == 0,
    }
    result = {
        "port": port,
        "metrics_bytes": len(text),
        "n_series": len(metric_names),
        "watchdogs": health.get("watchdogs"),
        "leaked_threads": leaked,
        "sentinel_rc": sentinel_rc,
        "gates": gates,
        "jsonl": jsonl,
        "ok": all(gates.values()),
    }
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
