"""Request-tracing smoke gate (tier-1-safe: CPU, tiny models, seconds).

Four phases, each mapping to an ISSUE 16 acceptance criterion for the
request-scoped tracing / SLO-attribution layer:

* **disabled** — with the monitor off, ``submit()`` mints no trace
  (``req.trace is None``), no ``serving.request`` record is ever
  produced, and per-request overhead stays at one flag check.
* **attribution** — a 2-replica :class:`MultiDecodeEngine` under
  injected faults (a ``replica_slow`` straggler that triggers hedges, a
  ``replica_hang`` that triggers supervisor failover) plus a
  shed-then-retry on a depth-4 queue: **100% of logical requests —
  hedged, failed-over, and shed-then-retried included — emit exactly
  one ``serving.request`` record**, every record's stage breakdown sums
  to the measured e2e latency within ``RECON_TOL`` (5%), and the hop
  lineage carries the hedge / failover / shed evidence.
* **gauges** — after decode traffic, ``slo.ttft_p99_ms`` /
  ``slo.tpot_p99_ms`` are live gauges on the /metrics OpenMetrics
  payload and the ``serving.ttft_ms`` / ``serving.tpot_ms`` histograms
  use the decode-scale (sub-ms .. 10s log-spaced) bucket bounds.
* **timeline** — with the span tracer armed, a 4-slot
  :class:`GenerateEngine` run exports a Chrome trace whose per-slot KV
  lanes each carry >= 1 occupied-by-request interval, with matching
  flow ``s``/``f`` events linking the request's cross-thread spans.

Prints one JSON result line; exit 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _model(serving):
    return serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                              max_len=64, seed=1)


def phase_disabled(serving, reqtrace):
    """Monitor off: no trace objects, no records, no lane/flow events."""
    reqtrace.reset()
    eng = serving.GenerateEngine(_model(serving), slots=2, page=16,
                                 factor=2.0, max_len=64,
                                 prompt_buckets=(4, 8), shed=False,
                                 start=False)
    req = eng.make_request([1, 2, 3], max_new_tokens=4)
    trace_none = req.trace is None
    eng.submit_request(req)
    while not req.future.done():
        eng.tick()
    tokens = len(req.future.result())
    eng.close()
    return {
        "trace_is_none": bool(trace_none),
        "tokens": tokens,
        "records": len(reqtrace.recent()),
        "ok": trace_none and tokens == 4 and not reqtrace.recent(),
    }


def phase_attribution(serving, reqtrace, requests):
    """Faulted fleet: exactly one reconciling record per logical
    request, with hedge / failover / shed-retry lineage evidence."""
    import jax
    from paddle_tpu.resilience import faults
    if len(jax.devices()) < 2:
        return {"ok": False, "error": "needs >=2 devices (XLA_FLAGS)"}

    reqtrace.reset()
    model = _model(serving)
    fleet = serving.MultiDecodeEngine(
        model, hedge_ms=40.0, hedge_budget=0.5,
        # inflight_age is the CURRENT TICK's duration, and an honest CPU
        # tick (up to `slots` prefills + the fused decode step) runs
        # hundreds of ms — the hung verdict must sit above that but well
        # below the 3s injected hang
        inflight_timeout_ms=1200.0,
        # long cooldown: once the hung replica is tripped it stays out
        # for the rest of the phase and the fleet drains on the healthy
        # peer (re-probing a still-hung replica would just re-trip)
        breaker_cooldown_s=5.0,
        supervisor_interval_s=0.05,
        # min_replicas=2: warmup takes long enough that the idle
        # supervisor would otherwise scale the fleet down to one
        # replica before traffic arrives
        min_replicas=2,
        slots=4, page=16, factor=2.0, max_len=64,
        prompt_buckets=(4, 8, 16), queue_depth=256, shed=False)
    fleet.warmup()
    # a straggler on replica 0 (hedge food — slow enough to outlive the
    # 40ms hedge delay, nowhere near the hang verdict) and one hung
    # dispatch on replica 1 (supervisor failover food)
    slow = faults.inject("replica_slow", replica=0, delay=0.06, times=2)
    hang = faults.inject("replica_hang", replica=1, delay=3.0, times=1)

    rng = np.random.RandomState(0)
    futs = []
    try:
        for _ in range(requests):
            plen = int(rng.randint(1, 17))
            futs.append(fleet.submit(
                rng.randint(1, 31, size=plen).tolist(),
                max_new_tokens=int(rng.randint(2, 12))))
            time.sleep(0.005)
        lost = 0
        for f in futs:
            try:
                f.result(timeout=30)
            except Exception:   # noqa: BLE001 - counted as lost goodput
                lost += 1
        stats = fleet.stats()
    finally:
        fleet.close()
        faults.clear()

    fleet_recs = reqtrace.recent()

    # shed-then-retry continuity: a depth-4 queue with no drain thread
    # sheds a low-priority submit at ladder level 1; the caller
    # resubmits with the SAME trace and the backoff lands in
    # shed_retry_ms of the one terminal record
    eng = serving.GenerateEngine(model, slots=2, page=16, factor=2.0,
                                 max_len=64, prompt_buckets=(4, 8),
                                 queue_depth=4, shed=True, start=False)
    held = [eng.submit([1, 2, 3], max_new_tokens=2) for _ in range(2)]
    shed_req = eng.make_request([1, 2, 3, 4], max_new_tokens=3,
                                priority="low")
    shed_raised = False
    try:
        eng.submit_request(shed_req)
    except serving.ShedError:
        shed_raised = True
    time.sleep(0.02)                    # the retry backoff being blamed
    retry = eng.make_request([1, 2, 3, 4], max_new_tokens=3,
                             priority="high", trace=shed_req.trace)
    eng.submit_request(retry)
    deadline = time.monotonic() + 30
    while (not retry.future.done() or not all(h.done() for h in held)) \
            and time.monotonic() < deadline:
        eng.tick()
    retry_tokens = len(retry.future.result(timeout=5))
    eng.close()
    shed_rec = retry.trace.ctx.record() if retry.trace is not None else None

    from paddle_tpu.serving.reqtrace import RECON_TOL
    all_recs = reqtrace.recent()
    by_rid = {}
    for r in all_recs:
        by_rid[r["rid"]] = by_rid.get(r["rid"], 0) + 1
    dupes = sum(1 for c in by_rid.values() if c != 1)
    recon_fail = sum(1 for r in all_recs
                     if abs(r["recon"] - 1.0) > RECON_TOL)
    hedge_hops = sum(1 for r in fleet_recs
                     if any(h["hop"] == "hedge" for h in r["hops"]))
    failover_hops = sum(1 for r in fleet_recs
                        if any(h["hop"] == "failover" for h in r["hops"]))
    return {
        "requests": requests,
        "lost": lost,
        "fleet_records": len(fleet_recs),
        "duplicate_records": dupes,
        "recon_failures": recon_fail,
        "hedged": stats["hedged"],
        "hedge_hop_records": hedge_hops,
        "failover_hop_records": failover_hops,
        "slow_fired": slow.fired,
        "hang_fired": hang.fired,
        "shed_raised": bool(shed_raised),
        "shed_record": ({k: shed_rec[k] for k in
                         ("outcome", "origin", "attempts", "sheds",
                          "shed_retry_ms", "recon")}
                        if shed_rec else None),
        "ok": (lost == 0
               and len(fleet_recs) == requests
               and dupes == 0
               and recon_fail == 0
               and stats["hedged"] >= 1 and hedge_hops >= 1
               and hang.fired >= 1 and failover_hops >= 1
               and shed_raised
               and shed_rec is not None
               and shed_rec["outcome"] == "ok"
               and shed_rec["origin"] == "retry"
               and shed_rec["sheds"] >= 1
               and shed_rec.get("shed_retry_ms", 0) > 0
               and retry_tokens == 3),
    }


def phase_gauges(serving, reqtrace):
    """slo.ttft/tpot gauges live on /metrics; decode-scale histogram
    bucket bounds on the request-latency series."""
    from paddle_tpu.monitor import export
    from paddle_tpu.serving import metrics

    metrics.reset_windows()
    reqtrace.reset()
    eng = serving.GenerateEngine(_model(serving), slots=2, page=16,
                                 factor=2.0, max_len=64,
                                 prompt_buckets=(4, 8), shed=False,
                                 start=True)
    futs = [eng.submit([1, 2, 3], max_new_tokens=6) for _ in range(6)]
    for f in futs:
        f.result(timeout=30)
    eng.close()
    roll = metrics.slo_rollup()
    text = export.render_openmetrics()
    b = metrics.LATENCY_BUCKETS_MS
    buckets_ok = (b[0] <= 0.01 and b[-1] >= 10_000.0
                  and all(x < y for x, y in zip(b, b[1:])))
    return {
        "ttft_p99_ms": roll.get("ttft_p99_ms"),
        "tpot_p99_ms": roll.get("tpot_p99_ms"),
        "gauges_on_metrics": ("slo_ttft_p99_ms" in text
                              and "slo_tpot_p99_ms" in text),
        "histograms_on_metrics": ("serving_ttft_ms" in text
                                  and "serving_tpot_ms" in text),
        "bucket_lo_ms": b[0],
        "bucket_hi_ms": b[-1],
        "ok": (roll.get("ttft_p99_ms") is not None
               and roll.get("tpot_p99_ms") is not None
               and "slo_ttft_p99_ms" in text
               and "slo_tpot_p99_ms" in text
               and "serving_ttft_ms" in text
               and buckets_ok),
    }


def phase_timeline(serving, reqtrace, out_dir):
    """Per-slot decode timeline in the Chrome export: every slot lane
    shows >= 1 occupancy interval; flow s/f events share an id."""
    from paddle_tpu import monitor
    monitor.trace.enable()
    monitor.trace.clear()
    reqtrace.reset()
    slots = 4
    eng = serving.GenerateEngine(_model(serving), slots=slots, page=16,
                                 factor=2.0, max_len=64,
                                 prompt_buckets=(4, 8), shed=False,
                                 start=True)
    futs = [eng.submit([1 + i, 2, 3], max_new_tokens=8)
            for i in range(3 * slots)]
    for f in futs:
        f.result(timeout=30)
    eng.close()
    path = os.path.join(out_dir, "request_timeline.json")
    monitor.trace.export_chrome_trace(path)
    lanes = monitor.trace.lanes()
    monitor.trace.disable()
    monitor.trace.clear()

    evs = json.load(open(path))["traceEvents"]
    lane_tids = {tid for name, tid in lanes.items() if ".slot" in name}
    occupied = {}
    for e in evs:
        if e.get("ph") == "X" and e.get("tid") in lane_tids \
                and str(e.get("name", "")).startswith("req"):
            occupied[e["tid"]] = occupied.get(e["tid"], 0) + 1
    starts = {e["id"] for e in evs if e.get("ph") == "s"}
    ends = {e["id"] for e in evs if e.get("ph") == "f"}
    lane_names = {e.get("args", {}).get("name") for e in evs
                  if e.get("ph") == "M" and e.get("name") == "thread_name"}
    return {
        "slot_lanes": len(lane_tids),
        "lanes_with_occupancy": len(occupied),
        "flow_starts": len(starts),
        "flow_ends": len(ends),
        "linked_flows": len(starts & ends),
        "lane_tracks_named": sum(1 for n in lane_names
                                 if n and ".slot" in n),
        "ok": (len(lane_tids) == slots
               and len(occupied) == slots
               and min(occupied.values(), default=0) >= 1
               and len(starts & ends) >= 1
               and sum(1 for n in lane_names if n and ".slot" in n)
               == slots),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_request_smoke")
    ap.add_argument("--requests", type=int, default=24)
    args = ap.parse_args()

    from paddle_tpu import monitor, serving
    from paddle_tpu.serving import reqtrace

    os.makedirs(args.out_dir, exist_ok=True)

    t0 = time.perf_counter()
    # the disabled phase must run BEFORE the monitor arms
    result = {"disabled": phase_disabled(serving, reqtrace)}
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "request_smoke.jsonl"))
    result["attribution"] = phase_attribution(serving, reqtrace,
                                              args.requests)
    result["gauges"] = phase_gauges(serving, reqtrace)
    result["timeline"] = phase_timeline(serving, reqtrace, args.out_dir)
    result["wall_s"] = round(time.perf_counter() - t0, 1)
    result["jsonl"] = jsonl
    result["ok"] = all(result[k]["ok"] for k in
                       ("disabled", "attribution", "gauges", "timeline"))
    monitor.emit(kind="request_smoke",
                 **{k: v for k, v in result.items() if k != "jsonl"})
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
