#!/usr/bin/env bash
# CI gate for the zero-copy flat parameter arena: the same MLP + Adam
# to_static step, per-leaf vs flat_arena=True, must be bit-identical,
# cut opt.* bytes_accessed >= 40% against the multi-tensor baseline
# (whose per-step concat traffic must be VISIBLE in the baseline HLO),
# leave zero concat/gather/scatter attributed to the optimizer scope,
# and compile exactly once with zero recompiles over the run.
# Tier-1-safe: small MLP, CPU, seconds.
#
# Usage: scripts/arena_smoke.sh [out_dir]
# The monitor JSONL lands in out_dir (default
# /tmp/paddle_tpu_arena_smoke); the last stdout line is one JSON
# result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_arena_smoke}"
JAX_PLATFORMS=cpu python scripts/arena_smoke.py --out-dir "$OUT_DIR"
