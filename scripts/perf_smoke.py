"""Step-pipelining smoke benchmark (tier-1-safe: tiny MLP, 30 steps, CPU).

Drives one ragged-final-batch training run through the whole pipelining
surface — AOT warmup, device prefetch, shape bucketing, async fetches —
and asserts the ISSUE's acceptance criteria from the monitor counters:

* steps-per-XLA-compile >= 10 on an epoch whose final batch is ragged
  (300 rows / batch 32 -> 9 full + one 12-row batch per epoch; bucketing
  pads the tail to 32 so the epoch reuses ONE executable)
* zero host-side blocking device_gets in async-fetch mode
  (``executor.fetch_blocking == 0``)

Writes the monitor JSONL stream to --out-dir as the CI artifact and
prints one JSON result line. Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_perf_smoke")
    ap.add_argument("--epochs", type=int, default=3)
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--n", type=int, default=300)
    ap.add_argument("--floor", type=float, default=10.0,
                    help="minimum steps per XLA compile")
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import static, optimizer as opt, monitor, io
    from paddle_tpu.fluid import layers as FL

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir, "perf_smoke.jsonl"))

    pt.seed(0)
    pt.enable_static()
    try:
        prog, sprog = static.Program(), static.Program()
        with static.program_guard(prog, sprog):
            x = static.data("x", [None, 16], "float32")
            y = static.data("y", [None, 1], "float32")
            h = FL.fc(x, 32, act="relu")
            out = FL.fc(h, 1)
            loss = ((out - y) ** 2).mean()
            opt.SGD(learning_rate=0.05).minimize(loss)

        rng = np.random.RandomState(0)
        xs = rng.rand(args.n, 16).astype("f4")
        ys = (xs.sum(-1, keepdims=True) * 0.25).astype("f4")

        exe = static.Executor()
        exe.run(sprog)
        # AOT: the one executable exists before the first batch arrives
        exe.warmup(prog,
                   feed_specs={"x": ((args.batch, 16), "float32"),
                               "y": ((args.batch, 1), "float32")},
                   fetch_list=[loss], bucket=True, buckets=[args.batch])

        def feeds():
            for i in range(0, args.n, args.batch):
                yield {"x": xs[i:i + args.batch], "y": ys[i:i + args.batch]}

        t0 = time.perf_counter()
        first = last = None
        for _ in range(args.epochs):
            for feed in io.prefetch_to_device(feeds(), size=2):
                got = exe.run(prog, feed=feed, fetch_list=[loss],
                              bucket=True, buckets=[args.batch],
                              async_fetch=True)
                if got is not None:
                    last = float(got[0])
                    if first is None:
                        first = last
            tail = exe.flush_fetches()
            if tail is not None:
                last = float(tail[0])
        wall = time.perf_counter() - t0

        reg = monitor.registry()
        runs = int(reg.value("executor.run", 0))
        compiles = int(reg.value("executor.compile", 0))
        result = {
            "metric": "steps_per_compile",
            "value": runs / max(compiles, 1),
            "steps": runs,
            "compiles": compiles,
            "aot_warmup": int(reg.value("executor.aot_warmup", 0)),
            "bucket_pad": int(reg.value("executor.bucket_pad", 0)),
            "recompiles": int(reg.value("executor.recompile", 0)),
            "fetch_blocking": int(reg.value("executor.fetch_blocking", 0)),
            "fetch_async": int(reg.value("executor.fetch_async", 0)),
            "prefetch_batches": int(reg.value("prefetch.batches", 0)),
            "prefetch_stall_s": round(
                float(reg.value("prefetch.stall_seconds", 0.0)), 4),
            "first_loss": first, "last_loss": last,
            "wall_seconds": round(wall, 3),
            "jsonl": jsonl,
        }
        gates = {
            f"steps_per_compile>={args.floor}":
                result["value"] >= args.floor,
            "fetch_blocking==0": result["fetch_blocking"] == 0,
            "recompiles==0": result["recompiles"] == 0,
            "ragged_batches_padded": result["bucket_pad"] >= args.epochs,
            "all_batches_prefetched":
                result["prefetch_batches"] == result["steps"],
            "loss_decreased": (first is not None and last is not None
                               and last < first),
        }
        result["gates"] = gates
        result["pass"] = all(gates.values())
        monitor.disable()  # flushes the counters snapshot into the JSONL
        print(json.dumps(result))
        return 0 if result["pass"] else 1
    finally:
        pt.disable_static()


if __name__ == "__main__":
    sys.exit(main())
