#!/usr/bin/env bash
# CI gate for planned memory: under a virtual PADDLE_TPU_HBM_LIMIT_BYTES
# budget the no-remat ceiling is found by scanning predicted peaks, a
# model 4x past it trains under the policy plan_memory(auto=True)
# picked (predicted peak under the limit pre-flight), offload.d2h/h2d
# spans ride their own trace track with exposed wait <= 40% of the
# blocking transfer, the picker chooses "none" when everything fits and
# never an infeasible or host-over-budget rung, and remat/offload are
# bit-identical where exactness is claimed. Tier-1-safe: tiny MLPs,
# CPU, ~a minute.
#
# Usage: scripts/remat_smoke.sh [out_dir]
# The monitor JSONL lands in out_dir (default
# /tmp/paddle_tpu_remat_smoke); the last stdout line is one JSON
# result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_remat_smoke}"
JAX_PLATFORMS=cpu python scripts/remat_smoke.py --out-dir "$OUT_DIR"
