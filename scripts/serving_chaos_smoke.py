"""Self-healing-serving chaos gate (tier-1-safe: tiny MLP, CPU, seconds).

Three scenarios against a MultiDeviceEngine fleet on 4 forced-CPU
devices, driven by the resilience/faults.py serving fault kinds, gating
the ISSUE 14 acceptance criteria:

* **replica-hang failover** — one of 4 replicas hangs mid-load
  (``replica_hang``): the supervisor trips its breaker, fails its
  queued + in-flight requests over to healthy peers, and the breaker
  re-closes via a half-open probe once the fault clears. Gates:
  goodput >= 0.90, zero lost futures, breaker opened >= 1 and ended
  closed.
* **hedge-win under a straggler** — an injected ``replica_slow`` makes
  one replica a straggler; hedged re-dispatch rescues its requests.
  Gates: hedged >= 1, hedge_wins >= 1, hedges within the 5% budget.
* **overload shed with priority goodput** — 2x-capacity mixed-priority
  load against a deliberately slowed single replica: the admission
  ladder sheds low/normal first. Gates: high-priority goodput >= 0.95,
  every shed error transient with retry_after_ms > 0, zero lost
  futures.

Prints one JSON result line; exit code 0 iff every gate passes.
Run via scripts/serving_chaos_smoke.sh (which forces the 4-device CPU
topology before jax imports).
"""
import argparse
import json
import os
import sys
import threading
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=4")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _mlp():
    import paddle_tpu as pt
    from paddle_tpu import nn
    pt.seed(0)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _await_state(breaker, want, timeout_s=10.0):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout_s:
        if breaker.state == want:
            return True
        time.sleep(0.05)
    return breaker.state == want


def scenario_hang_failover(args):
    """1 of 4 replicas hangs mid-load; the fleet routes around it."""
    import jax
    from paddle_tpu import inference, serving
    from paddle_tpu.resilience import faults

    devices = jax.local_devices()[:4]
    eng = serving.MultiDeviceEngine(
        inference.Predictor(_mlp()), devices=devices,
        max_batch=8, timeout_ms=2.0, queue_depth=256,
        deadline_ms=800.0,
        inflight_timeout_ms=200.0, breaker_cooldown_s=0.8,
        supervisor_interval_s=0.05)
    eng.warmup([((16,), "float32")])
    hang = faults.inject("replica_hang", replica=1, delay=1.2, times=1)

    n_clients, per_client = 6, args.requests // 6
    ok = errors = 0
    lock = threading.Lock()
    unresolved = []

    def client(k):
        nonlocal ok, errors
        rng = np.random.RandomState(k)
        for i in range(per_client):
            x = rng.rand(1 + (k + i) % 4, 16).astype("f4")
            try:
                fut = eng.submit(x)
            except Exception as e:  # noqa: BLE001 - counted
                with lock:
                    errors += 1
                continue
            try:
                fut.result(timeout=10)
                with lock:
                    ok += 1
            except Exception:  # noqa: BLE001 - counted
                with lock:
                    errors += 1
            if not fut.done():
                unresolved.append(i)
            time.sleep(0.002)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()

    # fault clears at ~1.2s; cooldown 0.8s -> half_open -> probe -> closed
    breaker1 = eng._replicas[1].breaker
    reclosed = _await_state(breaker1, "closed", timeout_s=10.0)
    stats = eng.stats()
    health = eng.health()
    eng.close()
    faults.clear()

    submitted = ok + errors
    goodput = ok / submitted if submitted else 0.0
    return {
        "submitted": submitted,
        "ok": ok,
        "errors": errors,
        "goodput": round(goodput, 4),
        "hang_fired": hang.fired,
        "failovers": stats["failovers"],
        "restarts": stats["restarts"],
        "breaker_opened": breaker1.open_count,
        "breaker_final": breaker1.state,
        "health_all_open": health["all_open"],
        "gates": {
            "fault_injected": hang.fired >= 1,
            "goodput_ge_090": goodput >= 0.90,
            "zero_lost_futures": not unresolved and submitted == ok + errors,
            "failover_happened": stats["failovers"] >= 1,
            "breaker_opened": breaker1.open_count >= 1,
            "breaker_reclosed": reclosed,
        },
    }


def scenario_hedge_win(args):
    """One replica turns straggler; hedges beat it within budget."""
    import jax
    from paddle_tpu import inference, serving
    from paddle_tpu.resilience import faults

    devices = jax.local_devices()[:2]
    eng = serving.MultiDeviceEngine(
        inference.Predictor(_mlp()), devices=devices,
        max_batch=8, timeout_ms=1.0, queue_depth=256,
        hedge_ms=40.0, hedge_budget=0.05,
        supervisor_interval_s=0.1)
    eng.warmup([((16,), "float32")])

    rng = np.random.RandomState(0)
    futs = []
    # prime the hedge budget with clean traffic
    for _ in range(args.requests):
        futs.append(eng.submit(rng.rand(2, 16).astype("f4")))
    for f in futs:
        f.result(timeout=10)

    faults.inject("replica_slow", replica=0, delay=0.35, times=4,
                  probability=1.0)
    futs2 = []
    for _ in range(40):
        futs2.append(eng.submit(rng.rand(2, 16).astype("f4")))
        time.sleep(0.004)
    unresolved = 0
    for f in futs2:
        try:
            f.result(timeout=10)
        except Exception:  # noqa: BLE001 - tallied below
            pass
        if not f.done():
            unresolved += 1
    stats = eng.stats()
    eng.close()
    faults.clear()

    budget_cap = int(0.05 * stats["submitted"]) + 1
    return {
        "submitted": stats["submitted"],
        "hedged": stats["hedged"],
        "hedge_wins": stats["hedge_wins"],
        "budget_cap": budget_cap,
        "gates": {
            "hedged_ge_1": stats["hedged"] >= 1,
            "hedge_win_ge_1": stats["hedge_wins"] >= 1,
            "hedges_within_budget": stats["hedged"] <= budget_cap,
            "zero_lost_futures": unresolved == 0,
        },
    }


def scenario_overload_shed(args):
    """2x-capacity mixed-priority load on a slowed replica: the ladder
    sheds low classes first and keeps high-priority goodput."""
    import jax
    from paddle_tpu import inference, serving
    from paddle_tpu.resilience import faults, retry

    eng = serving.ServingEngine(
        inference.Predictor(_mlp()), max_batch=8, timeout_ms=1.0,
        queue_depth=32, deadline_ms=2000.0, slo_goodput_floor=None)
    eng.warmup([((16,), "float32")])
    # ~20ms per batch -> ~400 rows/s service rate; clients offer ~2x that
    faults.inject("replica_slow", delay=0.02, times=None, probability=1.0)

    counts = {p: {"attempted": 0, "ok": 0, "shed": 0, "failed": 0}
              for p in ("high", "normal", "low")}
    bad_shed_errors = []
    lock = threading.Lock()

    def client(k):
        rng = np.random.RandomState(k)
        prios = ("high", "normal", "low")
        for i in range(args.requests):
            p = prios[(k + i) % 3]
            x = rng.rand(1, 16).astype("f4")
            with lock:
                counts[p]["attempted"] += 1
            try:
                fut = eng.submit(x, priority=p)
            except serving.ShedError as e:
                with lock:
                    counts[p]["shed"] += 1
                    if not (retry.is_transient(e)
                            and getattr(e, "retry_after_ms", 0) > 0):
                        bad_shed_errors.append(repr(e))
                time.sleep(min(e.retry_after_s, 0.05))
                continue
            except Exception as e:  # noqa: BLE001 - counted
                with lock:
                    counts[p]["failed"] += 1
                continue
            def _done(f, _p=p):
                with lock:
                    if f.cancelled() or f.exception() is not None:
                        counts[_p]["failed"] += 1
                    else:
                        counts[_p]["ok"] += 1
            fut.add_done_callback(_done)
            time.sleep(0.0025)

    threads = [threading.Thread(target=client, args=(k,)) for k in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    eng.close()           # drain=True: every queued future resolves
    faults.clear()
    stats = eng.stats()

    resolved = sum(c["ok"] + c["shed"] + c["failed"]
                   for c in counts.values())
    attempted = sum(c["attempted"] for c in counts.values())
    hi = counts["high"]
    hi_goodput = hi["ok"] / hi["attempted"] if hi["attempted"] else 0.0
    total_shed = sum(c["shed"] for c in counts.values())
    return {
        "counts": counts,
        "high_goodput": round(hi_goodput, 4),
        "total_shed": total_shed,
        "engine_shed": stats["shed"],
        "engine_rejected": stats["rejected"],
        "bad_shed_errors": bad_shed_errors[:5],
        "gates": {
            "overload_shed_happened": total_shed >= 1,
            "high_goodput_ge_095": hi_goodput >= 0.95,
            "shed_mostly_low_priority":
                counts["low"]["shed"] >= counts["high"]["shed"],
            "all_shed_retryable": not bad_shed_errors,
            "zero_lost_futures": resolved == attempted,
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir",
                    default="/tmp/paddle_tpu_serving_chaos_smoke")
    ap.add_argument("--requests", type=int, default=120,
                    help="per-scenario request scale")
    args = ap.parse_args()

    from paddle_tpu import monitor
    from paddle_tpu.serving import metrics as smetrics

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "serving_chaos_smoke.jsonl"))

    result = {"jsonl": jsonl}
    t0 = time.perf_counter()
    for name, fn in (("hang_failover", scenario_hang_failover),
                     ("hedge_win", scenario_hedge_win),
                     ("overload_shed", scenario_overload_shed)):
        smetrics.reset_windows()
        result[name] = fn(args)
    result["wall_s"] = round(time.perf_counter() - t0, 3)

    gates = {}
    for name in ("hang_failover", "hedge_win", "overload_shed"):
        for g, v in result[name]["gates"].items():
            gates[f"{name}.{g}"] = bool(v)
    result["gates"] = gates
    result["ok"] = all(gates.values())
    monitor.emit(kind="serving_chaos_smoke",
                 **{k: v for k, v in result.items() if k != "jsonl"})
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
