#!/usr/bin/env bash
# CI gate for the live telemetry plane: train a tiny hapi.Model with
# fit(metrics_port=0), scrape /metrics + /healthz + /snapshot MID-RUN
# (must parse as OpenMetrics with executor counters, at least one
# sampled mem_* gauge, and live watchdog/NaN-guard health), prove
# monitor.disable() frees the port and every thread, then run the perf
# regression sentinel over the repo's banked bench artifacts.
# Tier-1-safe: tiny MLP, CPU, seconds.
#
# Usage: scripts/export_smoke.sh [out_dir]
# The monitor JSONL lands in out_dir (default
# /tmp/paddle_tpu_export_smoke); the last stdout line is one JSON
# result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_export_smoke}"
JAX_PLATFORMS=cpu python scripts/export_smoke.py --out-dir "$OUT_DIR"
