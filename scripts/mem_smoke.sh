#!/usr/bin/env bash
# CI gate for memory observability: the 2-layer MLP + Adam to_static
# step's simulated HBM peak must reconcile with memory_analysis()
# within 10% and attribute >= 90% of live-at-peak bytes to named
# scopes; an injected RESOURCE_EXHAUSTED in hapi.fit must leave an
# `oom` flight bundle with op_ledger.json + memory_report.json; the
# planner must mark over-budget layouts infeasible and never auto-pick
# one; disabled mode must retain nothing. Tier-1-safe: tiny MLP, CPU,
# seconds.
#
# Usage: scripts/mem_smoke.sh [out_dir]
# The monitor JSONL lands in out_dir (default
# /tmp/paddle_tpu_mem_smoke); the last stdout line is one JSON
# result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_mem_smoke}"
JAX_PLATFORMS=cpu python scripts/mem_smoke.py --out-dir "$OUT_DIR"
