"""Perf regression sentinel — the ledger's tripwire.

The repo banks real on-chip measurements (``BENCH_r0*.json`` round
artifacts, ``docs/bench_r04_measured.json`` /
``docs/bench_latest_measured.json`` committed snapshots, and the
``$PADDLE_TPU_BENCH_JSONL`` running artifact bench.py appends to). This
script compares the NEWEST candidate measurement against the newest
*committed* baseline, per metric, with per-metric tolerance bands — and
exits nonzero iff something actually regressed.

Three verdicts per metric, and the distinction is the whole point:

* ``regression`` — a real number moved past its tolerance band in the
  bad direction. Exit 1.
* ``ok`` / ``improved`` — within band, or moved the good way. A better
  candidate also prints a nudge to re-bank the baseline.
* ``outage``  — the candidate is an error line (``value == 0`` with an
  ``error`` field: the chip-tunnel wedge this environment documents in
  ROADMAP.md). That is NOT a perf regression — the metric is SKIPPED,
  loudly, and does not fail the gate. Zero-throughput-without-error
  still trips: a silent zero is a regression, not an outage.

Only the newest round is a candidate: older rounds are history (they
were legitimately slower than today's baseline) and serve solely as
baseline sources. A candidate older than the baseline it would be
judged against is skipped for the same reason.

Usage::

    python scripts/perf_sentinel.py                  # audit the repo
    python scripts/perf_sentinel.py --candidate f.json --baseline g.json
    python scripts/perf_sentinel.py --jsonl /tmp/bench.jsonl
    python scripts/perf_sentinel.py --tolerance 0.2  # widen every band

Exit codes: 0 clean (incl. outage-skips and "no comparable data"),
1 regression(s), 2 bad invocation/unreadable input.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys

# (name, candidate keys tried in order, baseline keys tried in order,
#  direction, default tolerance). Throughputs get the ISSUE's 10%;
# serving latency/qps run wider — a shared CI box breathes harder than
# an MXU.
METRICS = [
    ("bert_tokens_per_sec",
     ("bert_base_seq128_tokens_per_sec", "value"),
     ("bert_base_seq128_tokens_per_sec", "value"), "higher", 0.10),
    ("resnet50_images_per_sec",
     ("resnet50_images_per_sec",), ("resnet50_images_per_sec",),
     "higher", 0.10),
    ("loader_images_per_sec",
     ("loader_images_per_sec", "loader_only_images_per_sec"),
     ("loader_images_per_sec", "loader_only_images_per_sec"),
     "higher", 0.15),
    ("bert_seq512_tokens_per_sec",
     ("bert_seq512_tokens_per_sec",), ("bert_seq512_tokens_per_sec",),
     "higher", 0.10),
    ("bert_seq2048_tokens_per_sec",
     ("bert_seq2048_tokens_per_sec",), ("bert_seq2048_tokens_per_sec",),
     "higher", 0.10),
    ("serving_qps", ("serving_qps", "qps"), ("serving_qps", "qps"),
     "higher", 0.25),
    ("serving_p99_ms", ("serving_p99_ms", "p99_ms"),
     ("serving_p99_ms", "p99_ms"), "lower", 0.50),
    # degraded-serving stage (bench_serving_degraded): what the fleet
    # keeps while broken. Goodputs are floors (tight — they're ratios,
    # not wall-clock); the hedge fraction is a ceiling (wide — a few
    # extra hedges on a loaded box is noise, 3x the budget is a bug)
    ("serving_degraded_goodput",
     ("serving_degraded_goodput",), ("serving_degraded_goodput",),
     "higher", 0.10),
    ("serving_degraded_high_goodput",
     ("serving_degraded_high_goodput",),
     ("serving_degraded_high_goodput",), "higher", 0.10),
    ("serving_degraded_hedge_frac",
     ("serving_degraded_hedge_frac",),
     ("serving_degraded_hedge_frac",), "lower", 1.00),
    # gradient-communication stage (bench_collective_overlap): exposed
    # wire seconds breathe with CI load (wide bands); bucket count and
    # wire bytes are deterministic functions of the model + bucket size
    # (tight bands — drift means the bucketing or wire format changed)
    ("collective_overlap_exposed_wire_s",
     ("collective_overlap_exposed_wire_s",),
     ("collective_overlap_exposed_wire_s",), "lower", 1.00),
    ("collective_overlap_ratio",
     ("collective_overlap_ratio",), ("collective_overlap_ratio",),
     "lower", 0.75),
    ("collective_overlap_bucket_count",
     ("collective_overlap_bucket_count",),
     ("collective_overlap_bucket_count",), "lower", 0.10),
    ("comm_bytes_wire_int8",
     ("comm_bytes_wire_int8",), ("comm_bytes_wire_int8",),
     "lower", 0.10),
    ("comm_wire_reduction_int4_x",
     ("comm_wire_reduction_int4_x",), ("comm_wire_reduction_int4_x",),
     "higher", 0.10),
    # fused-optimizer stage (bench_fused_optimizer / arena_smoke): the
    # opt.* byte ledger is a deterministic function of the model layout
    # and the arena packing (tight bands — drift means the packing, the
    # multi-tensor baseline, or the scope attribution changed); the
    # post-compile step wall time breathes with CI load (very wide)
    ("fused_optimizer_opt_bytes_flat",
     ("fused_optimizer_opt_bytes_flat",),
     ("fused_optimizer_opt_bytes_flat",), "lower", 0.10),
    ("fused_optimizer_bytes_reduction",
     ("fused_optimizer_bytes_reduction",),
     ("fused_optimizer_bytes_reduction",), "higher", 0.10),
    ("fused_optimizer_step_time_s",
     ("fused_optimizer_step_time_s",),
     ("fused_optimizer_step_time_s",), "lower", 1.00),
    # hotspot stage (bench_hotspot): the ranked fusion menu and the
    # attributed fraction are deterministic functions of the step HLO
    # (tight bands — shrinkage means scope labels or the parser broke);
    # the top region's headroom is a modeled time (very wide band)
    ("hotspot_count", ("hotspot_count",), ("hotspot_count",),
     "higher", 0.10),
    ("hotspot_attributed_frac",
     ("hotspot_attributed_frac",), ("hotspot_attributed_frac",),
     "higher", 0.10),
    ("hotspot_top_headroom_s",
     ("hotspot_top_headroom_s",), ("hotspot_top_headroom_s",),
     "lower", 1.00),
    # planner stage (bench_planner / plan_smoke): the candidate count
    # is a deterministic function of the device count and axis set
    # (tight band — drift means the factorization enumeration changed);
    # the winner's predicted step time is a modeled quantity fed by the
    # cost model's constants (very wide band)
    ("planner_candidates", ("planner_candidates",),
     ("planner_candidates",), "higher", 0.10),
    ("planner_predicted_step_s", ("planner_predicted_step_s",),
     ("planner_predicted_step_s",), "lower", 1.00),
    # memory stage (bench_memory / mem_smoke): the liveness model's
    # agreement with memory_analysis() and the attributed fraction are
    # deterministic functions of the step HLO (tight bands — drift
    # means the parser or the scope labels broke); the absolute peak
    # moves with any legitimate model change (wide band)
    ("memory_reconciliation",
     ("memory_reconciliation",), ("memory_reconciliation",),
     "higher", 0.10),
    ("memory_attributed_frac",
     ("memory_attributed_frac",), ("memory_attributed_frac",),
     "higher", 0.10),
    ("memory_predicted_peak_bytes",
     ("memory_predicted_peak_bytes",), ("memory_predicted_peak_bytes",),
     "lower", 0.50),
    # memory-plan stage (bench_memory_plan / remat_smoke): how far past
    # the no-remat ceiling the picked policy trains is the headline
    # capability (tight band — it must not quietly shrink below 4x);
    # the picked rung's predicted peak moves with any legitimate model
    # change (wide band); the offload exposed-wait fraction and the
    # warm step timings are CPU wall-clock (very wide bands)
    ("memory_plan_ceiling_multiple",
     ("memory_plan_ceiling_multiple",), ("memory_plan_ceiling_multiple",),
     "higher", 0.10),
    ("memory_plan_predicted_peak_bytes",
     ("memory_plan_predicted_peak_bytes",),
     ("memory_plan_predicted_peak_bytes",), "lower", 0.50),
    ("memory_plan_offload_exposed_frac",
     ("memory_plan_offload_exposed_frac",),
     ("memory_plan_offload_exposed_frac",), "lower", 1.00),
    ("memory_plan_step_s_remat",
     ("memory_plan_step_s_remat",), ("memory_plan_step_s_remat",),
     "lower", 1.00),
    # generative-decode stage (bench_decode / decode_smoke): tokens/s
    # and step latencies are shared-box wall-clock (very wide bands);
    # the continuous-vs-drain speedup and the decode-batch occupancy
    # are scheduling ratios — tight bands, a drop means the refill
    # discipline or slot accounting regressed, not the weather
    ("decode_tokens_per_s",
     ("decode_tokens_per_s",), ("decode_tokens_per_s",),
     "higher", 1.00),
    ("decode_speedup_x",
     ("decode_speedup_x",), ("decode_speedup_x",), "higher", 0.20),
    ("decode_batch_occupancy",
     ("decode_batch_occupancy",), ("decode_batch_occupancy",),
     "higher", 0.10),
    ("decode_prefill_p50_ms",
     ("decode_prefill_p50_ms",), ("decode_prefill_p50_ms",),
     "lower", 1.00),
    ("decode_p99_ms",
     ("decode_p99_ms",), ("decode_p99_ms",), "lower", 1.00),
    # per-request SLO attribution (reqtrace serving.request records):
    # TTFT/TPOT are end-to-end wall-clock under shared-box load — wide
    # bands; they exist to catch order-of-magnitude attribution bugs
    # (e.g. first-token stamped at submit instead of prefill exit), not
    # scheduler noise
    ("decode_ttft_p99_ms",
     ("decode_ttft_p99_ms",), ("decode_ttft_p99_ms",), "lower", 1.00),
    ("decode_tpot_p99_ms",
     ("decode_tpot_p99_ms",), ("decode_tpot_p99_ms",), "lower", 1.00),
    # speculative-decode stage (bench_spec_decode / spec_smoke): the
    # spec-vs-plain speedup divides two shared-box clocks — wide band;
    # the accept rate is pure verify-ledger arithmetic on fixed seeds —
    # tight band, a drop means the accept-prefix rule or the draft
    # distillation regressed, not the weather
    ("decode_spec_speedup_x",
     ("decode_spec_speedup_x",), ("decode_spec_speedup_x",),
     "higher", 1.00),
    ("decode_spec_speedup_k8_x",
     ("decode_spec_speedup_k8_x",), ("decode_spec_speedup_k8_x",),
     "higher", 1.00),
    ("decode_accept_rate",
     ("decode_accept_rate",), ("decode_accept_rate",), "higher", 0.10),
    ("decode_spec_tokens_per_s",
     ("decode_spec_tokens_per_s",), ("decode_spec_tokens_per_s",),
     "higher", 1.00),
    # serving-lifecycle stage (bench_lifecycle): fleet drain latency is
    # CPU decode wall-clock (very wide band); swap drops and the chaos
    # soak's goodput are correctness ratios — tight bands, any drift
    # means the drain/migrate/swap discipline itself regressed
    ("lifecycle_drain_p99_ms",
     ("lifecycle_drain_p99_ms",), ("lifecycle_drain_p99_ms",),
     "lower", 1.00),
    ("lifecycle_swap_dropped",
     ("lifecycle_swap_dropped",), ("lifecycle_swap_dropped",),
     "lower", 0.10),
    ("lifecycle_soak_goodput",
     ("lifecycle_soak_goodput",), ("lifecycle_soak_goodput",),
     "higher", 0.10),
    # fleet telemetry stage (bench_fleet_telemetry): both are
    # wall-clock on a loaded shared box — publish overhead is CPU-time
    # divided by worker wall, detection latency rides the scrape and
    # snapshot cadences — so the bands are very wide; the hard
    # correctness bar (merge oracle, exactly-two-alerts, goodput
    # reconciliation) is the smoke gate itself, not the sentinel
    ("fleet_agg_overhead_pct",
     ("fleet_agg_overhead_pct",), ("fleet_agg_overhead_pct",),
     "lower", 1.00),
    ("alert_detection_latency_s",
     ("alert_detection_latency_s",), ("alert_detection_latency_s",),
     "lower", 1.00),
    # disaggregated-serving stage (bench_disagg / disagg_smoke): the
    # prefix hit rate is pure workload arithmetic on fixed seeds —
    # tight band, a drop means the full-prompt keying or the insert
    # path regressed, not the weather; TTFT/handoff/tokens-per-s are
    # shared-box wall-clock (very wide bands) — the hard bars (hit
    # TTFT <= 0.5x miss, handoff bytes == plan, bit-parity) live in
    # the smoke's gates, folded into disagg_gates_pass
    ("disagg_prefix_hit_rate",
     ("disagg_prefix_hit_rate",), ("disagg_prefix_hit_rate",),
     "higher", 0.10),
    ("disagg_ttft_hit_p50_ms",
     ("disagg_ttft_hit_p50_ms",), ("disagg_ttft_hit_p50_ms",),
     "lower", 1.00),
    ("disagg_handoff_ms",
     ("disagg_handoff_ms",), ("disagg_handoff_ms",), "lower", 1.00),
    ("disagg_tokens_per_s",
     ("disagg_tokens_per_s",), ("disagg_tokens_per_s",),
     "higher", 1.00),
]


def _load_json(path):
    with open(path) as fh:
        return json.load(fh)


def _first(blob, keys):
    for k in keys:
        v = blob.get(k)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            return float(v)
    return None


def _is_outage(blob):
    """An error line whose numbers are the zeros of a dead tunnel, not
    of slow code: headline value 0/absent AND an explicit error."""
    if not blob.get("error"):
        return False
    return not _first(blob, ("value",
                             "bert_base_seq128_tokens_per_sec"))


def _measurement_blob(raw):
    """Normalize any supported artifact into one flat metric dict.

    * driver round files ({n, cmd, rc, tail, parsed}) -> parsed (which
      may be None: rc!=0 with no JSON line — treated as an outage line)
    * bench stdout/JSONL lines and committed snapshots -> as-is
    """
    if not isinstance(raw, dict):
        return None
    if "parsed" in raw and "cmd" in raw:
        parsed = raw.get("parsed")
        if parsed is None:
            # the round produced no JSON line at all (e.g. BENCH_r03's
            # raw-traceback round): outage-shaped by construction
            return {"value": 0.0,
                    "error": f"round emitted no parseable result "
                             f"(rc={raw.get('rc')})"}
        return parsed
    return raw


def _last_jsonl_line(path):
    last = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            try:
                last = json.loads(line)
            except ValueError:
                continue
    return last


def _round_files(root):
    """BENCH_r*.json sorted oldest->newest by round number."""
    def key(p):
        import re
        m = re.search(r"_r(\d+)", os.path.basename(p))
        return int(m.group(1)) if m else -1
    return sorted(glob.glob(os.path.join(root, "BENCH_r*.json")), key=key)


def discover_baseline(root):
    """The newest committed measurement, searched newest-first:
    ``last_committed_measurement`` banked inside the newest round
    files, then docs/bench_latest_measured.json, then the r4 snapshot.
    Returns (blob, provenance-string) or (None, None)."""
    for path in reversed(_round_files(root)):
        try:
            blob = _measurement_blob(_load_json(path))
        except Exception:
            continue
        if not blob:
            continue
        lcm = blob.get("last_committed_measurement")
        if isinstance(lcm, dict) and _first(
                lcm, ("bert_base_seq128_tokens_per_sec", "value")):
            src = blob.get("last_committed_measurement_file") or path
            return lcm, f"{os.path.basename(path)} -> {src}"
        # a round that itself measured real numbers IS the baseline
        if not _is_outage(blob) and _first(
                blob, ("value", "bert_base_seq128_tokens_per_sec")):
            return blob, os.path.basename(path)
    for rel in ("docs/bench_latest_measured.json",
                "docs/bench_r04_measured.json"):
        path = os.path.join(root, rel)
        if os.path.exists(path):
            try:
                return _load_json(path), rel
            except Exception:
                continue
    return None, None


def discover_candidate(root, jsonl_paths=()):
    """The newest measurement to judge: the last line of any given
    JSONL artifact (newest file wins), else $PADDLE_TPU_BENCH_JSONL,
    else the newest BENCH_r*.json round. Returns (blob, provenance)."""
    paths = [p for p in jsonl_paths if p and os.path.exists(p)]
    env = os.environ.get("PADDLE_TPU_BENCH_JSONL", "")
    if not paths and env and os.path.exists(env):
        paths = [env]
    if paths:
        newest = max(paths, key=os.path.getmtime)
        blob = _last_jsonl_line(newest)
        if blob is not None:
            return _measurement_blob(blob), newest
    rounds = _round_files(root)
    if rounds:
        path = rounds[-1]
        try:
            return _measurement_blob(_load_json(path)), \
                os.path.basename(path)
        except Exception as e:
            raise SystemExit(f"perf_sentinel: unreadable {path}: {e}")
    return None, None


def compare(candidate, baseline, tolerance=None):
    """Per-metric verdicts. Returns a list of dicts
    {metric, verdict, candidate, baseline, band} where verdict is one
    of regression/ok/improved/outage/no_data."""
    out = []
    outage = _is_outage(candidate)
    for name, ckeys, bkeys, direction, tol in METRICS:
        tol = tolerance if tolerance is not None else tol
        base = _first(baseline, bkeys)
        cand = _first(candidate, ckeys)
        row = {"metric": name, "candidate": cand, "baseline": base,
               "direction": direction, "tolerance": tol}
        if base is None or cand is None:
            row["verdict"] = "no_data"
        elif outage and not cand:
            # zero riding an error line: the tunnel died, the code
            # didn't get slower — skip, don't fail
            row["verdict"] = "outage"
        elif direction == "higher":
            floor = base * (1.0 - tol)
            row["band"] = round(floor, 3)
            row["verdict"] = ("regression" if cand < floor else
                              "improved" if cand > base else "ok")
        else:
            ceil = base * (1.0 + tol)
            row["band"] = round(ceil, 3)
            row["verdict"] = ("regression" if cand > ceil else
                              "improved" if cand < base else "ok")
        out.append(row)
    return out


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--repo-root", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    ap.add_argument("--candidate", default=None,
                    help="explicit candidate measurement JSON file "
                         "(default: newest JSONL artifact / round file)")
    ap.add_argument("--baseline", default=None,
                    help="explicit baseline JSON file (default: newest "
                         "committed measurement)")
    ap.add_argument("--jsonl", action="append", default=[],
                    help="bench/smoke JSONL artifact; last parseable "
                         "line is the candidate (repeatable)")
    ap.add_argument("--tolerance", type=float, default=None,
                    help="override every per-metric band (fraction, "
                         "e.g. 0.1)")
    args = ap.parse_args(argv)
    root = args.repo_root

    try:
        if args.baseline:
            baseline, base_src = _load_json(args.baseline), args.baseline
        else:
            baseline, base_src = discover_baseline(root)
        if args.candidate:
            candidate = _measurement_blob(_load_json(args.candidate))
            cand_src = args.candidate
        else:
            candidate, cand_src = discover_candidate(root, args.jsonl)
    except SystemExit:
        raise
    except Exception as e:
        print(f"perf_sentinel: cannot read inputs: {e}", file=sys.stderr)
        return 2

    if baseline is None:
        print(json.dumps({"sentinel": "perf", "ok": True,
                          "note": "no committed baseline found; "
                                  "nothing to compare"}))
        return 0
    if candidate is None:
        print(json.dumps({"sentinel": "perf", "ok": True,
                          "note": "no candidate measurement found; "
                                  "nothing to compare"}))
        return 0

    rows = compare(candidate, baseline, tolerance=args.tolerance)
    regressions = [r for r in rows if r["verdict"] == "regression"]
    improved = [r for r in rows if r["verdict"] == "improved"]
    outages = [r for r in rows if r["verdict"] == "outage"]

    for r in rows:
        if r["verdict"] == "no_data":
            continue
        mark = {"regression": "FAIL", "outage": "SKIP",
                "improved": "  up", "ok": "  ok"}[r["verdict"]]
        band = f" (band {r.get('band')})" if "band" in r else ""
        print(f"[{mark}] {r['metric']}: {r['candidate']} vs baseline "
              f"{r['baseline']}{band}", file=sys.stderr)
    if outages:
        err = str(candidate.get("error", ""))[:160]
        print(f"[note] outage-shaped candidate (error: {err}) — "
              f"{len(outages)} metric(s) skipped, not failed",
              file=sys.stderr)
    if improved and not regressions:
        print("[note] candidate beats the baseline — consider re-banking "
              "docs/bench_latest_measured.json", file=sys.stderr)

    print(json.dumps({
        "sentinel": "perf", "ok": not regressions,
        "candidate": cand_src, "baseline": base_src,
        "regressions": regressions,
        "verdicts": {r["metric"]: r["verdict"] for r in rows
                     if r["verdict"] != "no_data"},
    }))
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
