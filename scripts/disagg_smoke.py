"""Disaggregated serving gate (tier-1-safe: tiny models, CPU).

Four phases against the PR 20 split topology, gating the acceptance
criteria:

* **parity** — mixed greedy/sampled traffic through (prefill pool →
  priced handoff → decode pool), with a mid-stream drain of the seated
  decode replica. Gates: every stream byte-identical to the
  single-engine oracle, zero post-warmup executables in BOTH pools,
  recorded handoff bytes exactly equal the comm-model prediction
  (per-token KV spec bytes × prompt bucket), decode pool never runs
  prefill.
* **prefix** — head-heavy traffic at >= 50% reuse against the shared
  PrefixCache. Gates: a hit skips prefill entirely (prefill count ==
  cache misses), hit TTFT p50 <= 0.5x miss TTFT p50, zero new
  executables after warmup (a hit never mints a shape).
* **autoscale** — each pool held at 1-of-2 active replicas under load.
  Gates: the prefill supervisor scales up on ITS SLO (the decision
  carries ``queue_depth``/``queue_depth_ceiling``, never a goodput or
  tokens context) and the decode supervisor scales up on ITS SLO (the
  decision carries ``tokens_floor``); both pools end at 2 active.
* **hang** — one of two prefill replicas hangs mid-prefill
  (``replica_hang``). Gates: the supervisor fails the work over to the
  healthy peer and goodput stays >= 0.90 with zero lost futures.

Prints one JSON result line; exit code 0 iff every gate passes.
Run via scripts/disagg_smoke.sh (which forces the CPU topology before
jax imports).
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=2")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np


def _model(dim=32, seed=1, max_len=64, vocab=32, heads=2):
    from paddle_tpu import serving
    return serving.demo_model(vocab=vocab, dim=dim, heads=heads,
                              layers=2, max_len=max_len, seed=seed)


def _oracle(model, jobs, **kw):
    """Fault-free single-engine run: the bit-identity oracle."""
    from paddle_tpu.serving.generate import GenerateEngine
    eng = GenerateEngine(model, **kw)
    eng.warmup()
    futs = [eng.submit(p, max_new_tokens=n, sampling=sp, seed=s)
            for p, n, sp, s in jobs]
    out = [[int(t) for t in f.result(timeout=30)] for f in futs]
    eng.close()
    return out


def _execs(srv):
    return tuple(r.engine.executables()
                 for pool in (srv.prefill_pool, srv.decode_pool)
                 for r in pool._replicas)


def phase_parity(args):
    """Split-topology streams == oracle streams, through a mid-stream
    decode drain, with priced handoffs and zero fresh executables."""
    from paddle_tpu import serving
    from paddle_tpu.serving import reqtrace

    model = _model()
    rng = np.random.RandomState(3)
    jobs = []
    for i in range(args.requests):
        plen = int(rng.randint(2, 25))
        prompt = rng.randint(1, 31, size=plen).tolist()
        sp = {"temperature": 0.9, "top_k": 8} if i % 2 else None
        jobs.append((prompt, 8 + int(rng.randint(0, 5)), sp,
                     500 + i if sp else None))
    jobs.append((jobs[0][0], 8, None, None))    # repeat → prefix hit
    kw = dict(slots=4, page=16, factor=2.0, max_len=64,
              prompt_buckets=(8, 32))
    want = _oracle(model, jobs, **kw)

    srv = serving.DisaggServer(model, prefill_replicas=1,
                               decode_replicas=2, supervise=False, **kw)
    srv.warmup()
    ex0 = _execs(srv)
    reqtrace.reset()
    t_load = time.perf_counter()
    futs = [srv.submit(p, max_new_tokens=n, sampling=sp, seed=s)
            for p, n, sp, s in jobs]

    # drain whichever decode replica seated work first: its streams
    # must move (KV and all) and resume bit-identically on the peer
    victim, deadline = None, time.monotonic() + 10
    while victim is None and time.monotonic() < deadline:
        for r in srv.decode_pool._replicas:
            if r.engine.stats()["kv_imports"] > 0:
                victim = r.index
                break
        time.sleep(0.005)
    moved = srv.drain_decode_replica(victim, reason="smoke") \
        if victim is not None else 0

    got = [[int(t) for t in f.result(timeout=30)] for f in futs]
    load_wall = time.perf_counter() - t_load
    tokens = sum(len(g) for g in got)
    handoffs_ms = sorted(r["handoff_ms"] for r in reqtrace.recent()
                         if r.get("handoff_ms") is not None)
    handoff_p50 = handoffs_ms[len(handoffs_ms) // 2] if handoffs_ms \
        else None
    fresh = sum((b[0] - a[0]) + (b[1] - a[1])
                for a, b in zip(ex0, _execs(srv)))
    st = srv.stats()
    planned_bytes = sum(srv.planned_handoff_ms(len(p))[0]
                        for p, _n, _sp, _s in jobs)
    srv.close()

    identical = sum(1 for a, b in zip(want, got) if a == b)
    return {
        "requests": len(jobs),
        "identical": identical,
        "drained_moved": moved,
        "post_warmup_compiles": fresh,
        "handoffs": st["handoffs"],
        "handoff_bytes": st["handoff_bytes"],
        "handoff_p50_ms": round(handoff_p50, 3)
        if handoff_p50 is not None else None,
        "tokens_per_s": round(tokens / load_wall, 1),
        "planned_bytes": planned_bytes,
        "prefix_hits": st["prefix"]["hits"],
        "gates": {
            "bit_identical": identical == len(jobs),
            "zero_fresh_executables": fresh == 0,
            "handoff_bytes_match_plan":
                st["handoff_bytes"] == planned_bytes,
            "every_request_handed_off": st["handoffs"] == len(jobs),
            "decode_pool_never_prefills": st["decode"]["prefills"] == 0,
            "drain_moved_inflight": moved >= 1,
        },
    }


def phase_prefix(args):
    """>=50% reuse on shared heads: hits skip prefill and halve TTFT."""
    from paddle_tpu import serving
    from paddle_tpu.serving import reqtrace

    # prefill cost must dominate the hit path's standalone sample, so
    # the TTFT split is physics, not noise: wide model, long heads
    model = _model(dim=256, heads=4, vocab=64, max_len=96)
    srv = serving.DisaggServer(model, prefill_replicas=1,
                               decode_replicas=1, slots=4, page=16,
                               factor=2.0, max_len=96,
                               prompt_buckets=(16, 64),
                               supervise=False)
    srv.warmup()
    ex0 = _execs(srv)

    rng = np.random.RandomState(5)
    heads = [rng.randint(1, 63, size=48).tolist() for _ in range(2)]
    for h in heads:                     # warm the cache: one miss each
        srv.run(h, max_new_tokens=2, timeout=30)

    reqtrace.reset()
    n_hit = n_miss = args.requests // 2
    plan = ([(heads[i % 2], True) for i in range(n_hit)]
            + [(rng.randint(1, 63, size=48).tolist(), False)
               for _ in range(n_miss)])
    rng.shuffle(plan)
    # sequential closed loop: TTFT measures the service path (lookup +
    # sample vs full prefill), not queueing behind the previous request
    for prompt, _is_hit in plan:
        srv.run(prompt, max_new_tokens=2, timeout=30)

    recs = [r for r in reqtrace.recent() if r["outcome"] == "ok"]
    hit_ttft = sorted(r["ttft_ms"] for r in recs if r["prefix_hit"])
    miss_ttft = sorted(r["ttft_ms"] for r in recs if not r["prefix_hit"])
    fresh = sum((b[0] - a[0]) + (b[1] - a[1])
                for a, b in zip(ex0, _execs(srv)))
    st = srv.stats()
    srv.close()

    def p50(xs):
        return xs[len(xs) // 2] if xs else None

    hit_p50, miss_p50 = p50(hit_ttft), p50(miss_ttft)
    hit_rate = len(hit_ttft) / max(len(recs), 1)
    return {
        "requests": len(recs),
        "hit_rate": round(hit_rate, 4),
        "ttft_hit_p50_ms": round(hit_p50, 3) if hit_p50 else None,
        "ttft_miss_p50_ms": round(miss_p50, 3) if miss_p50 else None,
        "prefills": st["prefill"]["prefills"],
        "cache": st["prefix"],
        "post_warmup_compiles": fresh,
        "gates": {
            "reuse_ge_half": hit_rate >= 0.5,
            "hit_ttft_le_half_miss":
                hit_p50 is not None and miss_p50 is not None
                and hit_p50 <= 0.5 * miss_p50,
            "hits_skip_prefill":
                st["prefill"]["prefills"] == st["prefix"]["misses"],
            "zero_fresh_executables": fresh == 0,
        },
    }


def phase_autoscale(args):
    """Each pool scales on its own SLO: prefill on queue depth / TTFT,
    decode on the tokens/s floor — never on the generic goodput rung."""
    from paddle_tpu import serving

    model = _model()
    # both pools pinned to 1-of-2 active; ceilings set so any real
    # traffic breaches them (the gate is WHICH branch fired, not when)
    srv = serving.DisaggServer(
        model, prefill_replicas=2, decode_replicas=2, slots=2,
        page=16, factor=2.0, max_len=64, prompt_buckets=(8, 32),
        supervise=True, supervisor_interval_s=0.05,
        queue_depth_ceiling=1, tokens_floor=10_000_000.0,
        prefill_initial_active=1, decode_initial_active=1)
    srv.warmup()
    rng = np.random.RandomState(11)
    futs = []
    for _ in range(args.requests):
        plen = int(rng.randint(2, 25))
        futs.append(srv.submit(rng.randint(1, 31, size=plen).tolist(),
                               max_new_tokens=8))
    for f in futs:
        f.result(timeout=30)
    # let the decode supervisor observe the now-filled tokens/s window
    deadline = time.monotonic() + 5
    while time.monotonic() < deadline:
        if any(d["decision"] == "scale_up"
               for d in srv.decode_supervisor.decisions):
            break
        time.sleep(0.05)

    pre = [d for d in srv.prefill_supervisor.decisions
           if d["decision"] == "scale_up"]
    dec = [d for d in srv.decode_supervisor.decisions
           if d["decision"] == "scale_up"]
    pre_active = srv.prefill_pool._active_count()
    dec_active = srv.decode_pool._active_count()
    srv.close()

    return {
        "prefill_scale_ups": len(pre),
        "decode_scale_ups": len(dec),
        "prefill_decision": pre[0] if pre else None,
        "decode_decision": dec[0] if dec else None,
        "gates": {
            "prefill_scaled_on_own_slo":
                bool(pre) and "queue_depth_ceiling" in pre[0]
                and "goodput" not in pre[0]
                and "tokens_floor" not in pre[0],
            "decode_scaled_on_own_slo":
                bool(dec) and "tokens_floor" in dec[0]
                and "goodput" not in dec[0]
                and "queue_depth_ceiling" not in dec[0],
            "prefill_pool_grew": pre_active == 2,
            "decode_pool_grew": dec_active == 2,
        },
    }


def phase_hang(args):
    """One of two prefill replicas hangs mid-prefill: failover keeps
    goodput >= 0.90 with zero lost futures."""
    from paddle_tpu import serving
    from paddle_tpu.resilience import faults

    model = _model()
    srv = serving.DisaggServer(
        model, prefill_replicas=2, decode_replicas=1, slots=4,
        page=16, factor=2.0, max_len=64, prompt_buckets=(8, 32),
        supervise=True, supervisor_interval_s=0.05,
        prefill_inflight_timeout_ms=250.0)
    srv.warmup()
    spec = faults.inject("replica_hang", replica=0, delay=1.5, times=1,
                         site="prefill")

    rng = np.random.RandomState(17)
    futs, errors = [], []
    for i in range(args.requests):
        plen = int(rng.randint(2, 25))
        futs.append(srv.submit(rng.randint(1, 31, size=plen).tolist(),
                               max_new_tokens=8, seed=900 + i,
                               sampling={"temperature": 0.8}))
        time.sleep(float(rng.exponential(0.004)))

    ok = lost = 0
    for f in futs:
        try:
            f.result(timeout=30)
            ok += 1
        except Exception as e:   # noqa: BLE001 - counted
            errors.append(repr(e))
        if not f.done():
            lost += 1
    srv.close()
    faults.clear()

    goodput = ok / len(futs) if futs else 0.0
    return {
        "submitted": len(futs),
        "ok": ok,
        "lost": lost,
        "errors": errors[:3],
        "goodput": round(goodput, 4),
        "fault_fired": spec.fired,
        "gates": {
            "fault_injected": spec.fired >= 1,
            "goodput_ge_090": goodput >= 0.90,
            "zero_lost_futures": lost == 0,
        },
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_disagg_smoke")
    ap.add_argument("--requests", type=int, default=16,
                    help="per-phase request scale")
    args = ap.parse_args()

    from paddle_tpu import monitor
    from paddle_tpu.serving import metrics as smetrics

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "disagg_smoke.jsonl"))

    result = {"jsonl": jsonl}
    t0 = time.perf_counter()
    for name, fn in (("parity", phase_parity),
                     ("prefix", phase_prefix),
                     ("autoscale", phase_autoscale),
                     ("hang", phase_hang)):
        smetrics.reset_windows()
        result[name] = fn(args)
    result["wall_s"] = round(time.perf_counter() - t0, 3)
    # the bench harness banks these
    result["prefix_hit_rate"] = result["prefix"]["hit_rate"]
    result["ttft_hit_p50_ms"] = result["prefix"]["ttft_hit_p50_ms"]
    result["ttft_miss_p50_ms"] = result["prefix"]["ttft_miss_p50_ms"]
    result["handoff_p50_ms"] = result["parity"]["handoff_p50_ms"]
    result["tokens_per_s"] = result["parity"]["tokens_per_s"]

    gates = {}
    for name in ("parity", "prefix", "autoscale", "hang"):
        for g, v in result[name]["gates"].items():
            gates[f"{name}.{g}"] = bool(v)
    result["gates"] = gates
    result["ok"] = all(gates.values())
    monitor.emit(kind="disagg_smoke",
                 **{k: v for k, v in result.items() if k != "jsonl"})
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
