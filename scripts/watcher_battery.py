"""One bench battery, run by scripts/tpu_watcher.sh whenever the TPU
tunnel answers.

Runs bench.py first (the headline metrics) and, if it produced a real
number, atomically refreshes ``docs/bench_latest_measured.json`` — the
committed, timestamped record of the most recent successful on-chip
measurement (VERDICT r4 task 1a). Then runs the secondary measurement
scripts (per-kernel ablation, Pallas BN sweep, int8 table, roofline
profile), teeing each log into ``docs/watcher_logs/`` so the evidence is
committed even if the tunnel wedges again before a human looks at /tmp.
"""
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LOGS = os.path.join(REPO, "docs", "watcher_logs")
LATEST = os.path.join(REPO, "docs", "bench_latest_measured.json")
# Global deadline: the whole battery finishes inside this budget, by
# skipping/trimming extras — NOT by being SIGKILLed mid-stage (the
# watcher's outer timeout is this +300s slack). Keeps one battery from
# holding the chip for hours when every stage runs long.
DEADLINE = time.time() + int(os.environ.get("BATTERY_BUDGET_S", "7200"))


def _git_rev():
    try:
        return subprocess.run(
            ["git", "rev-parse", "--short", "HEAD"], cwd=REPO,
            capture_output=True, text=True, timeout=30).stdout.strip()
    except Exception:
        return "unknown"


def _run(cmd, log_name, timeout_s):
    os.makedirs(LOGS, exist_ok=True)
    path = os.path.join(LOGS, log_name)
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, cwd=REPO, capture_output=True,
                              text=True, timeout=timeout_s)
        out = proc.stdout + ("\n--- stderr ---\n" + proc.stderr
                             if proc.stderr else "")
        rc = proc.returncode
    except subprocess.TimeoutExpired as e:
        # TimeoutExpired carries raw bytes even under text=True
        partial = e.stdout or ""
        if isinstance(partial, bytes):
            partial = partial.decode("utf-8", "replace")
        out = partial + f"\n--- TIMEOUT after {timeout_s}s ---\n"
        rc = -1
    header = (f"# cmd: {' '.join(cmd)}\n# rc: {rc}"
              f"  wall: {time.time() - t0:.0f}s"
              f"  at: {time.strftime('%Y-%m-%dT%H:%M:%S')}"
              f"  rev: {_git_rev()}\n")
    with open(path, "w") as f:
        f.write(header + out)
    print(f"[battery] {log_name}: rc={rc} wall={time.time() - t0:.0f}s",
          flush=True)
    return rc, out


def _last_json_line(text):
    for line in reversed(text.splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main():
    fast = os.environ.get("BATTERY_FAST", "") == "1"
    # 3420 > bench.py's own 3300s watchdog: a wedged bench gets killed
    # by ITS watchdog first, which emits the partial-credit fail-JSON
    # carrying any stages that did finish — so a real bert number from a
    # run that wedged at the resnet stage still refreshes LATEST.
    cmd = [sys.executable, "bench.py"] + (["--fast"] if fast else [])
    rc, out = _run(cmd, "bench.log", 2400 if fast else 3420)
    parsed = _last_json_line(out)
    if parsed and parsed.get("value", 0) > 0:
        record = {
            "measured_at": time.strftime("%Y-%m-%dT%H:%M:%S"),
            "git_rev": _git_rev(),
            "source": "scripts/watcher_battery.py (on-chip, via "
                      "scripts/tpu_watcher.sh)",
            **parsed,
        }
        tmp = LATEST + ".tmp"
        with open(tmp, "w") as f:
            json.dump(record, f, indent=2)
            f.write("\n")
        os.replace(tmp, LATEST)
        print(f"[battery] refreshed {LATEST}: "
              f"bert={parsed.get('value')} "
              f"resnet={parsed.get('resnet50_images_per_sec')}",
              flush=True)
    else:
        print("[battery] bench.py produced no positive headline number; "
              "bench_latest_measured.json left untouched", flush=True)

    # Secondary measurements — each independently time-boxed.
    extras = [
        (["scripts/bench_nhwc_resnet.py"], "nhwc_resnet.log", 1800),
        (["scripts/bench_adam_multi.py"], "adam_multi.log", 900),
        (["scripts/ablate_bert.py"], "ablate.log", 1800),
        (["scripts/bench_pallas_bn.py"], "pallas_bn.log", 1200),
        (["scripts/bench_int8.py"], "int8.log", 1200),
        (["scripts/profile_resnet.py"], "profile_resnet.log", 1200),
    ]
    if fast:
        # late-window fast profile: the two flip-decision benches only
        extras = extras[:2]
    for cmd, log_name, budget in extras:
        if not os.path.exists(os.path.join(REPO, cmd[0])):
            print(f"[battery] skip {cmd[0]} (absent)", flush=True)
            continue
        remaining = DEADLINE - time.time()
        if remaining < 120:
            print(f"[battery] skip {cmd[0]} (deadline: {remaining:.0f}s "
                  "left)", flush=True)
            continue
        _run([sys.executable, "-u"] + cmd, log_name,
             min(budget, int(remaining - 60)))


if __name__ == "__main__":
    main()
