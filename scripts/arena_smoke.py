"""Flat-parameter-arena smoke gate (tier-1-safe: small MLP, CPU,
seconds).

Trains the SAME model+Adam step twice under ``jit.to_static`` with
profiling scopes armed — once on the per-leaf (multi-tensor) optimizer
path, once with ``flat_arena=True`` — builds the per-op cost ledger for
both captured executables, and asserts the r10 acceptance criteria:

* the two runs are BIT-IDENTICAL (losses and final params)
* opt.* ``bytes_accessed`` drops >= 40% under the arena (the per-leaf
  gather/concat before the update and the split after it are gone)
* no concatenate / gather / scatter opcodes remain attributed to the
  opt.* region in the flat step
* zero extra recompiles: after step 1 the jit cache only ever hits
  (``jit.recompile`` stays flat for the whole run)

Writes the monitor JSONL to --out-dir and prints one JSON result line.
Exit code 0 iff every gate passes.
"""
import argparse
import json
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import re

import numpy as np

_BANNED_RE = re.compile(r"(concatenate|gather|scatter)\(")


def _opt_rows(rep):
    return [o for o in rep["ops"] if "opt." in (o["region"] or "")]


def _banned_in_opt(hlo_text):
    """concat/gather/scatter instructions (top-level OR inside fusions)
    whose op_name metadata places them in the optimizer scope."""
    return [l.strip()[:160] for l in hlo_text.splitlines()
            if _BANNED_RE.search(l) and "opt." in l]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out-dir", default="/tmp/paddle_tpu_arena_smoke")
    ap.add_argument("--hidden", type=int, default=128)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()

    import paddle_tpu as pt
    from paddle_tpu import jit, monitor, nn, optimizer as opt
    from paddle_tpu.ops import pallas

    # the baseline the arena replaces is the MULTI-TENSOR fused path
    # (one dispatch over concatenated buffers): force it on so the
    # per-step concat/split traffic is in the baseline ledger, exactly
    # like on the chip. The flat run reuses the same kernel on the
    # pre-packed arena buffers — no concat, no split.
    pallas.configure(fused_adam_multi=True)

    os.makedirs(args.out_dir, exist_ok=True)
    jsonl = monitor.enable(os.path.join(args.out_dir,
                                        "arena_smoke.jsonl"))
    monitor.profile.enable()

    def build():
        pt.seed(0)
        return nn.Sequential(nn.Linear(64, args.hidden), nn.ReLU(),
                             nn.Linear(args.hidden, args.hidden),
                             nn.ReLU(),
                             nn.Linear(args.hidden, 10))

    rng = np.random.RandomState(0)
    xs = [rng.randn(args.batch, 64).astype("f4")
          for _ in range(args.steps)]
    ys = [rng.randn(args.batch, 10).astype("f4")
          for _ in range(args.steps)]

    def train(flat):
        model = build()
        adam = opt.Adam(learning_rate=1e-3,
                        parameters=model.parameters(), flat_arena=flat)

        def body(x, y):
            loss = (model(x) - y).square().mean()
            loss.backward()
            adam.step()
            adam.clear_grad()
            return loss

        # distinct names -> distinct monitor.xla capture labels
        body.__name__ = "step_flat" if flat else "step_base"
        fn = jit.to_static(body, models=[model], optimizers=[adam])
        losses, times = [], []
        for x, y in zip(xs, ys):
            t0 = time.perf_counter()
            losses.append(float(fn(pt.to_tensor(x),
                                   pt.to_tensor(y)).numpy()))
            times.append(time.perf_counter() - t0)
        # step 1 pays the compile; bench/sentinel want steady state
        step_s = sum(times[1:]) / max(1, len(times) - 1)
        params = {k: np.asarray(v.numpy())
                  for k, v in model.state_dict().items()}
        rep = monitor.profile.report(emit_records=False)
        hlo = monitor.xla.executable(None).as_text()
        return losses, params, rep, hlo, step_s

    losses_base, params_base, rep_base, hlo_base, step_base_s = \
        train(flat=False)
    rc0 = monitor.counter("jit.recompile")._value
    c0 = monitor.counter("jit.compile")._value
    losses_flat, params_flat, rep_flat, hlo_flat, step_flat_s = \
        train(flat=True)
    recompiles = monitor.counter("jit.recompile")._value - rc0
    compiles = monitor.counter("jit.compile")._value - c0

    if rep_base is None or rep_flat is None:
        print(json.dumps({"metric": "arena_smoke", "pass": False,
                          "error": "no captured executable"}))
        return 1

    base_rows, flat_rows = _opt_rows(rep_base), _opt_rows(rep_flat)
    opt_bytes_base = sum(o["bytes"] for o in base_rows)
    opt_bytes_flat = sum(o["bytes"] for o in flat_rows)
    reduction = (1.0 - opt_bytes_flat / opt_bytes_base
                 if opt_bytes_base else 0.0)
    base_banned = _banned_in_opt(hlo_base)
    flat_banned = _banned_in_opt(hlo_flat)

    bit_identical = losses_base == losses_flat and all(
        np.array_equal(params_base[k], params_flat[k])
        for k in params_base)

    result = {
        "metric": "arena_smoke",
        "steps": args.steps,
        "opt_bytes_base": opt_bytes_base,
        "opt_bytes_flat": opt_bytes_flat,
        "opt_bytes_reduction": round(reduction, 4),
        "opt_ops_base": len(base_rows),
        "opt_ops_flat": len(flat_rows),
        "opt_concat_gather_scatter_base": len(base_banned),
        "opt_concat_gather_scatter_flat": len(flat_banned),
        "flat_compiles": compiles,
        "flat_recompiles": recompiles,
        "step_time_base_s": round(step_base_s, 6),
        "step_time_flat_s": round(step_flat_s, 6),
        "jsonl": jsonl,
    }
    gates = {
        "bit_identical": bit_identical,
        "opt_bytes_reduction>=0.40": reduction >= 0.40,
        # the base run must SHOW the concat traffic the arena removes —
        # otherwise the vanish gate below would be vacuous
        "baseline_has_concat_traffic": len(base_banned) > 0,
        "no_gather_scatter_concat_in_opt": not flat_banned,
        "one_compile_no_recompiles": compiles == 1 and recompiles == 0,
    }
    result["gates"] = gates
    result["pass"] = all(gates.values())
    pallas.configure(fused_adam_multi=None)
    monitor.disable()
    print(json.dumps(result))
    return 0 if result["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
