#!/usr/bin/env bash
# CI gate for self-healing serving (ISSUE 14): against a 4-replica
# forced-CPU fleet under injected faults —
#   * replica-hang: failover keeps goodput >= 0.90, zero lost futures,
#     the breaker opens and re-closes via a half-open probe
#   * straggler: hedged re-dispatch wins at least once, inside the 5%
#     hedge budget
#   * 2x overload: the admission ladder sheds low priority first,
#     high-priority goodput stays >= 0.95, every shed error is
#     transient with a retry-after hint
#
# Usage: scripts/serving_chaos_smoke.sh [out_dir]
# The monitor JSONL (with the serving_chaos_smoke record) lands in
# out_dir (default /tmp/paddle_tpu_serving_chaos_smoke); the last
# stdout line is one JSON result record.
set -euo pipefail
cd "$(dirname "$0")/.."
OUT_DIR="${1:-/tmp/paddle_tpu_serving_chaos_smoke}"
JAX_PLATFORMS=cpu \
XLA_FLAGS="--xla_force_host_platform_device_count=4" \
python scripts/serving_chaos_smoke.py --out-dir "$OUT_DIR"
