"""int8 vs bf16 Predictor throughput on the real chip (VERDICT r3 #7's
bench line). Run: python -u scripts/bench_int8.py

Measures an MXU-bound Linear tower through Predictor.run_device with a
DATA-DEPENDENT CHAIN (each call consumes the previous call's device
output) and a single device→host sync at the end — the only timing
shape this environment measures honestly: repeated identical dispatches
are served from cache, per-call D2H would add ~40 ms of tunnel transfer
around sub-ms compute, and `block_until_ready` is not a real sync
(docs/perf_r04.md). The tower's output shape equals its input shape so
the chain type-checks; int8 activation scales are calibrated on the
true input distribution but the chain's drifting activations only
affect numerics, not throughput.
"""
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      "/tmp/paddle_tpu_xla_cache")
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.inference import Config, Predictor

    pt.seed(0)
    d, layers, batch, steps = 4096, 16, 512, 40
    blocks = []
    for _ in range(layers):
        blocks += [nn.Linear(d, d), nn.ReLU()]
    model = nn.Sequential(*blocks)
    rng = np.random.RandomState(0)
    x = (rng.randn(batch, d) * 0.05).astype("f4")
    cal = [pt.to_tensor(x)]
    gflop_call = 2 * layers * batch * d * d / 1e9

    def rate(predictor):
        y = predictor.run_device(x)       # compile + stage on device
        np.asarray(y[:1, :1])             # sync the warmup
        y = predictor.run_device(x)
        np.asarray(y[:1, :1])             # sync: keep warmup out of t0
        t0 = time.perf_counter()
        for _ in range(steps):
            y = predictor.run_device(y)   # data-dependent chain
        np.asarray(y[:1, :1])             # one tiny D2H sync
        dt = (time.perf_counter() - t0) / steps
        return batch / dt, gflop_call / dt / 1e3  # samples/s, TF/s

    bf16, bf16_tf = rate(Predictor(model, Config().enable_bf16()))
    # enable_int8 quantizes a COPY, so the same model object serves both
    int8, int8_tf = rate(Predictor(model, Config().enable_int8(cal)))
    print(json.dumps({
        "metric": "int8_vs_bf16_inference",
        "bf16_samples_per_sec": round(bf16, 1),
        "int8_samples_per_sec": round(int8, 1),
        "bf16_tf_s": round(bf16_tf, 1),
        "int8_tf_s": round(int8_tf, 1),
        "speedup": round(int8 / bf16, 3),
        "model": f"{layers}x Linear({d},{d}) batch {batch}",
    }))


if __name__ == "__main__":
    main()
