"""int8 vs bf16 Predictor throughput on the real chip (VERDICT r3 #7's
bench line). Run: python -u scripts/bench_int8.py

Measures a Linear-tower inference model (the MXU-bound regime where int8
doubles the systolic-array throughput ceiling) through the Predictor at
bf16 and at calibrated int8, printing one JSON line."""
import json
import sys
import time

import numpy as np

sys.path.insert(0, ".")


def main():
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.inference import Config, Predictor

    pt.seed(0)
    d, layers, batch = 4096, 8, 64
    blocks = []
    for _ in range(layers):
        blocks += [nn.Linear(d, d), nn.ReLU()]
    model = nn.Sequential(*blocks)
    rng = np.random.RandomState(0)
    x = rng.randn(batch, d).astype("f4")
    cal = [pt.to_tensor(x)]

    def rate(predictor, steps=30):
        out = predictor.run(x)  # compile
        np.asarray(out)
        t0 = time.perf_counter()
        for _ in range(steps):
            out = predictor.run(x)
        np.asarray(out)
        return batch * steps / (time.perf_counter() - t0)

    bf16 = rate(Predictor(model, Config().enable_bf16()))
    # enable_int8 quantizes a COPY, so the same model object serves both
    int8 = rate(Predictor(model, Config().enable_int8(cal)))
    print(json.dumps({
        "metric": "int8_vs_bf16_inference",
        "bf16_samples_per_sec": round(bf16, 1),
        "int8_samples_per_sec": round(int8, 1),
        "speedup": round(int8 / bf16, 3),
        "model": f"{layers}x Linear({d},{d})",
    }))


if __name__ == "__main__":
    main()
