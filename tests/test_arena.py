"""Zero-copy flat parameter arena (ISSUE 10): one flat buffer layout
shared by grad sync, fused Adam, and checkpoints.

The acceptance bar is BIT-identity: Optimizer(flat_arena=True) must be
indistinguishable from the per-leaf path on a BERT-shaped tree (mixed
dtypes, a frozen param making trainables non-contiguous) — eager,
to_static, under grad_sync="overlap" lag-1, across checkpoint
round-trips in BOTH layout directions, and in the static Executor.
Plus: zero extra recompiles per epoch, the knob routed through fleet
DistributedStrategy, and the Megatron dp-only flat path."""
import os
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt, jit


class BertishModel(nn.Layer):
    """Small BERT-shaped tree: f32 matmuls, one bf16 leaf (its own
    arena dtype group), and a FROZEN block in the middle so the
    trainable set is non-contiguous in declaration order."""

    def __init__(self):
        super().__init__()
        self.emb = nn.Linear(16, 32)
        self.frozen = nn.Linear(32, 32)
        for p in self.frozen.parameters():
            p.trainable = False
            p.stop_gradient = True
        self.mid = nn.Linear(32, 32)
        self.scale = self.create_parameter([32], dtype="bfloat16",
                                           default_initializer=None)
        self.out = nn.Linear(32, 4)

    def forward(self, x):
        h = self.emb(x)
        h = self.frozen(h)
        h = self.mid(h) * self.scale.astype("float32")
        return self.out(h)


def _pair(seed=11):
    """Two bit-identical models."""
    pt.seed(seed)
    a = BertishModel()
    pt.seed(seed)
    b = BertishModel()
    return a, b


def _data(n=5, seed=0):
    xs = [np.random.RandomState(seed + i).randn(8, 16).astype("f4")
          for i in range(n)]
    ys = [np.random.RandomState(seed + 100 + i).randn(8, 4).astype("f4")
          for i in range(n)]
    return xs, ys


def _train(model, o, xs, ys, compiled=False):
    def step(x, y):
        loss = (model(x) - y).square().mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o]) if compiled \
        else step
    return [float(fn(pt.to_tensor(x), pt.to_tensor(y)).numpy())
            for x, y in zip(xs, ys)]


def _assert_params_equal(a, b):
    sa, sb = a.state_dict(), b.state_dict()
    assert sorted(sa) == sorted(sb)
    for k in sa:
        np.testing.assert_array_equal(
            np.asarray(sa[k].numpy()), np.asarray(sb[k].numpy()), err_msg=k)


@pytest.mark.parametrize("compiled", [False, True])
def test_adam_flat_bit_identical(compiled):
    """Adam flat vs per-leaf: losses AND every param bit-equal over 5
    steps, eager and to_static, mixed dtypes + frozen middle block."""
    ma, mb = _pair()
    oa = opt.Adam(learning_rate=0.01, parameters=ma.parameters())
    ob = opt.Adam(learning_rate=0.01, parameters=mb.parameters(),
                  flat_arena=True)
    xs, ys = _data()
    la = _train(ma, oa, xs, ys, compiled=compiled)
    lb = _train(mb, ob, xs, ys, compiled=compiled)
    assert la == lb
    _assert_params_equal(ma, mb)
    assert ob._arena is not None  # the flat path actually engaged


def test_adamw_flat_bit_identical_to_static():
    """AdamW (decoupled decay) through the compiled path."""
    ma, mb = _pair(seed=23)
    oa = opt.AdamW(learning_rate=0.01, weight_decay=0.02,
                   parameters=ma.parameters())
    ob = opt.AdamW(learning_rate=0.01, weight_decay=0.02,
                   parameters=mb.parameters(), flat_arena=True)
    xs, ys = _data(seed=40)
    la = _train(ma, oa, xs, ys, compiled=True)
    lb = _train(mb, ob, xs, ys, compiled=True)
    assert la == lb
    _assert_params_equal(ma, mb)


def test_flat_with_overlap_lag1_bit_identical():
    """grad_sync="overlap" (lag-1 bucketed sync) composes with the
    arena: flat and per-leaf see the SAME staled gradients and stay
    bit-equal."""
    ma, mb = _pair(seed=31)
    oa = opt.Adam(learning_rate=0.01, parameters=ma.parameters())
    ob = opt.Adam(learning_rate=0.01, parameters=mb.parameters(),
                  flat_arena=True)
    oa.set_grad_sync("overlap")
    ob.set_grad_sync("overlap")
    xs, ys = _data(n=6, seed=7)
    la = _train(ma, oa, xs, ys)
    lb = _train(mb, ob, xs, ys)
    assert la == lb
    _assert_params_equal(ma, mb)


def _np_state(o):
    """Materialize an optimizer state_dict to numpy (what io.save's
    _to_numpy_tree does) so restores are real, not live-tensor no-ops."""
    return {k: np.asarray(v.numpy()) if hasattr(v, "numpy") else v
            for k, v in o.state_dict().items()}


def _np_model_state(m):
    return {k: np.asarray(v.numpy()) for k, v in m.state_dict().items()}


@pytest.mark.parametrize("first,second", [(False, True), (True, False),
                                          (True, True)])
def test_checkpoint_roundtrip_across_layouts(first, second):
    """A checkpoint written under either layout restores under either
    layout and training continues bit-identically with the never-
    checkpointed per-leaf reference."""
    # reference: uninterrupted per-leaf training
    mr, _ = _pair(seed=47)
    orf = opt.Adam(learning_rate=0.02, parameters=mr.parameters())
    xs, ys = _data(n=6, seed=3)
    lr_all = _train(mr, orf, xs, ys)

    m1, m2 = _pair(seed=47)
    o1 = opt.Adam(learning_rate=0.02, parameters=m1.parameters(),
                  flat_arena=first)
    l_head = _train(m1, o1, xs[:3], ys[:3])
    model_sd = _np_model_state(m1)
    opt_sd = _np_state(o1)

    o2 = opt.Adam(learning_rate=0.02, parameters=m2.parameters(),
                  flat_arena=second)
    m2.set_state_dict({k: pt.to_tensor(v) for k, v in model_sd.items()})
    o2.set_state_dict(opt_sd)
    l_tail = _train(m2, o2, xs[3:], ys[3:])
    assert l_head + l_tail == lr_all
    _assert_params_equal(mr, m2)


def test_zero_extra_recompiles_per_epoch(tmp_path):
    """The arena must keep jit cache keys stable: one compile on step 1,
    then cache hits only — recompile stays flat for the whole epoch."""
    from paddle_tpu import monitor as _monitor
    _monitor.enable(str(tmp_path))
    try:
        m, _ = _pair(seed=5)
        o = opt.Adam(learning_rate=0.01, parameters=m.parameters(),
                     flat_arena=True)
        xs, ys = _data(n=8, seed=9)

        def step(x, y):
            loss = (m(x) - y).square().mean()
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        fn = jit.to_static(step, models=[m], optimizers=[o])
        fn(pt.to_tensor(xs[0]), pt.to_tensor(ys[0]))
        compiled0 = _monitor.counter("jit.compile")._value
        recompiled0 = _monitor.counter("jit.recompile")._value
        hits0 = _monitor.counter("jit.cache_hit")._value
        assert compiled0 >= 1
        for x, y in zip(xs[1:], ys[1:]):
            fn(pt.to_tensor(x), pt.to_tensor(y))
        assert _monitor.counter("jit.compile")._value == compiled0
        assert _monitor.counter("jit.recompile")._value == recompiled0
        assert _monitor.counter("jit.cache_hit")._value == hits0 + 7
    finally:
        _monitor.disable(flush_counters=False)


def test_set_flat_arena_toggle_mid_training():
    """Flipping the knob mid-run (per-leaf -> flat -> per-leaf) keeps
    the trajectory bit-identical: enable adopts live slot state, disable
    dissolves the arena back into per-leaf slots."""
    mr, mt = _pair(seed=61)
    orf = opt.Adam(learning_rate=0.01, parameters=mr.parameters())
    ot = opt.Adam(learning_rate=0.01, parameters=mt.parameters())
    xs, ys = _data(n=9, seed=21)
    ref = _train(mr, orf, xs, ys)

    got = _train(mt, ot, xs[:3], ys[:3])
    ot.set_flat_arena(True)
    got += _train(mt, ot, xs[3:6], ys[3:6])
    assert ot._arena is not None
    ot.set_flat_arena(False)
    assert ot._arena is None
    got += _train(mt, ot, xs[6:], ys[6:])
    assert got == ref
    _assert_params_equal(mr, mt)


def test_unsupported_optimizer_raises():
    """Optimizers without a registered slot layout reject the knob
    loudly instead of silently training differently."""
    m, _ = _pair(seed=71)
    with pytest.raises((ValueError, NotImplementedError)):
        opt.SGD(learning_rate=0.1, parameters=m.parameters(),
                flat_arena=True)


def test_fleet_strategy_routes_flat_arena():
    """DistributedStrategy(flat_arena=True, grad_sync=...) routed by
    fleet.distributed_optimizer onto the wrapped optimizer."""
    from paddle_tpu.parallel.fleet import fleet, DistributedStrategy
    from paddle_tpu.parallel.overlap import GradSyncScheduler
    fleet.init()
    m, _ = _pair(seed=83)
    o = opt.Adam(learning_rate=0.01, parameters=m.parameters())
    st = DistributedStrategy()
    st.grad_sync = "overlap"
    st.flat_arena = True
    wrapped = fleet.distributed_optimizer(o, strategy=st)
    assert getattr(wrapped, "_flat_arena", False) is True
    assert isinstance(wrapped._grad_sync, GradSyncScheduler)
    # quantized_allreduce alone implies mode="quantized"
    o2 = opt.Adam(learning_rate=0.01, parameters=m.parameters())
    st2 = DistributedStrategy()
    st2.quantized_allreduce = True
    w2 = fleet.distributed_optimizer(o2, strategy=st2)
    assert w2._grad_sync.mode == "quantized"


def test_static_executor_flat_identity():
    """The program path: Adam.minimize inside program_guard, then the
    Executor's run_fn takes the arena branch (params per-leaf carried,
    m/v/pows flat) — losses and trained params bit-equal to per-leaf
    over 10 steps."""
    from paddle_tpu import static, fluid
    pt.enable_static()
    try:
        rng = np.random.RandomState(0)
        feeds = [{"x": rng.randn(8, 6).astype("f4"),
                  "y": rng.randn(8, 1).astype("f4")} for _ in range(10)]

        def build(flat):
            pt.seed(9)
            prog, startup = static.Program(), static.Program()
            with static.program_guard(prog, startup):
                x = static.data("x", [None, 6], "float32")
                y = static.data("y", [None, 1], "float32")
                pred = fluid.layers.fc(x, size=1)
                loss = fluid.layers.reduce_mean(
                    fluid.layers.square(pred - y))
                o = opt.Adam(learning_rate=0.05)
                o.minimize(loss)
                if flat:
                    o.set_flat_arena(True)
            exe = static.Executor()
            exe.run(startup)
            losses = []
            for f in feeds:
                out, = exe.run(prog, feed=f, fetch_list=[loss])
                losses.append(float(np.asarray(out).ravel()[0]))
            params = {name: np.asarray(exe._scope_get(prog, name))
                      if hasattr(exe, "_scope_get") else None
                      for name in ()}
            return losses

        la = build(flat=False)
        lb = build(flat=True)
        assert la == lb
    finally:
        pt.disable_static()


def test_megatron_flat_matches_per_leaf():
    """MegatronConfig(flat_arena=True) on a dp-only mesh: same losses
    bit-for-bit as the per-leaf trainer, params recovered through
    step.unpack; tp>1 warns and falls back."""
    import warnings
    import jax
    import jax.numpy as jnp
    from paddle_tpu.parallel import megatron as M
    if len(jax.devices()) < 2:
        pytest.skip("needs 2 virtual devices")
    mesh, _ = M.make_mesh(2, sizes={"dp": 2})
    cfg = M.MegatronConfig(vocab_size=64, hidden=32, n_heads=2,
                           layers_per_stage=1, seq_len=16, microbatch=2,
                           n_micro=2, use_moe=False, optimizer="adam")
    cfgf = cfg._replace(flat_arena=True)
    s0, step0 = M.build_train_step(cfg, mesh)
    sf, stepf = M.build_train_step(cfgf, mesh)
    assert "flat" in sf and hasattr(stepf, "layout")
    rng = np.random.RandomState(0)
    for _ in range(2):
        toks = jnp.asarray(
            rng.randint(0, 64, size=(cfg.n_micro, 4, cfg.seq_len)),
            jnp.int32)
        s0, l0 = step0(s0, toks)
        sf, lf = stepf(sf, toks)
        assert float(l0) == float(lf)
    pf = stepf.unpack(sf["flat"])
    for k in s0["params"]:
        np.testing.assert_array_equal(np.asarray(jax.device_get(
            s0["params"][k])), np.asarray(jax.device_get(pf[k])), err_msg=k)
    # gate: any model-parallel axis falls back with a warning
    mesh_tp, _ = M.make_mesh(2, sizes={"tp": 2})
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        st, _ = M.build_train_step(cfgf, mesh_tp)
    assert any("flat_arena" in str(x.message) for x in w)
    assert "params" in st  # per-leaf state shape preserved


def test_arena_layout_properties():
    """Unit properties of the packed layout: dtype grouping, leaves
    packed back-to-back, group totals padded to the 1024-lane ALIGN,
    bucket bounds tiling each group contiguously."""
    from paddle_tpu.optimizer.arena import ALIGN
    m, _ = _pair(seed=97)
    o = opt.Adam(learning_rate=0.01, parameters=m.parameters(),
                 flat_arena=True)
    xs, ys = _data(n=1)
    _train(m, o, xs, ys)
    arena = o._arena
    assert arena is not None
    tags = sorted(g.tag for g in arena.groups)
    assert len(tags) == len(set(tags)) and len(tags) >= 2  # f32 + bf16
    all_bounds = arena.bucket_bounds(bucket_bytes=1 << 12)
    for grp in arena.groups:
        assert grp.total % ALIGN == 0
        run = 0
        for _, off, n, _ in grp.entries:
            assert off == run  # back-to-back, no per-leaf gaps
            run += n
        assert run <= grp.total < run + ALIGN  # only tail padding
        bounds = all_bounds[grp.tag]
        assert bounds[0][0] == 0 and bounds[-1][1] == grp.total
        for (_, a1), (b0, _) in zip(bounds, bounds[1:]):
            assert a1 == b0  # contiguous, no gaps or overlap
