"""Namespace-level parity: every reference __all__ name across
optimizer/initializer/metrics/clip/dygraph.nn/backward resolves, and the
newly added classes compute (reference: the corresponding fluid
modules)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer, metric, static, fluid


# Names that deliberately do NOT resolve, each with the reason. Keep
# this list short and honest — everything else in every reference
# __all__ must resolve (mechanical sweep below).
_PARITY_ALLOWLIST = {
    # none currently: CUDA-only surfaces (cuda_profiler,
    # load_op_library) resolve as explicit-error stubs that explain
    # their TPU replacement rather than being absent.
}


def _reference_all_names(path):
    """Every string literal inside list literals assigned/augmented to
    __all__ (covers `__all__ = [...]`, `__all__ = a.__all__ + [...]`,
    and `__all__ += [...]` — the dynamic `x.__all__` parts are covered
    by sweeping each submodule's own file)."""
    import ast
    try:
        tree = ast.parse(open(path, encoding="utf-8",
                              errors="replace").read())
    except SyntaxError:
        return []
    names = []

    def literals(node):
        for sub in ast.walk(node):
            if isinstance(sub, ast.List):
                for e in sub.elts:
                    if isinstance(e, ast.Constant) and isinstance(
                            e.value, str):
                        names.append(e.value)

    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "__all__":
                    target = node.value
        elif isinstance(node, ast.AugAssign):
            if isinstance(node.target, ast.Name) and \
                    node.target.id == "__all__":
                target = node.value
        if target is not None:
            literals(target)
    return names


def _resolve(dotted):
    """Import the longest importable prefix, then walk attributes."""
    import importlib
    parts = dotted.split(".")
    for k in range(len(parts), 0, -1):
        try:
            obj = importlib.import_module(".".join(parts[:k]))
        except ImportError:
            continue
        try:
            for p in parts[k:]:
                obj = getattr(obj, p)
            return obj
        except AttributeError:
            continue
    return None


def test_every_reference_fluid_all_name_resolves():
    """Mechanical sweep (VERDICT r4 task 5): for EVERY module under
    reference fluid/, fluid/dygraph/, and fluid/layers/, each __all__
    name must resolve at the same module path in paddle_tpu — or at the
    parent package level, which is where reference users consume
    star-imported names (fluid.dygraph.nn.Conv2D is used as
    fluid.dygraph.Conv2D)."""
    import os

    ref_root = "/root/reference/python/paddle/fluid"
    sweeps = [(ref_root, "paddle_tpu.fluid"),
              (os.path.join(ref_root, "dygraph"),
               "paddle_tpu.fluid.dygraph"),
              (os.path.join(ref_root, "layers"),
               "paddle_tpu.fluid.layers")]
    missing = []
    checked = 0
    for base, target_pkg in sweeps:
        for fname in sorted(os.listdir(base)):
            if not fname.endswith(".py"):
                continue
            names = _reference_all_names(os.path.join(base, fname))
            if not names:
                continue
            mod_path = target_pkg if fname == "__init__.py" else \
                f"{target_pkg}.{fname[:-3]}"
            mod = _resolve(mod_path)
            parent = _resolve(target_pkg)
            for n in names:
                checked += 1
                if n in _PARITY_ALLOWLIST:
                    continue
                if (mod is not None and hasattr(mod, n)) or \
                        (parent is not None and hasattr(parent, n)):
                    continue
                missing.append(f"{mod_path}:{n}")
    assert checked > 500, f"sweep only found {checked} names — broken?"
    assert missing == [], f"{len(missing)} missing: {missing}"


# Reference-side __all__ defects (names the REFERENCE itself never
# defines), verified by reading the reference source:
_REFERENCE_ALL_BUGS = {
    # utils/__init__.py lists dump_config but no module defines it
    "dump_config",
    # dataset/conll05.py has __all__ = ['test, get_dict'] — one string
    # with a comma where two names were meant
    "test, get_dict",
}


def _reference_root_exports():
    """Names the reference re-exports at the bare `paddle` root (its
    __init__.py's top-level `from .x import y` statements): only THESE
    may satisfy the sweep at paddle_tpu's root — otherwise an unrelated
    top-level op (e.g. pt.split, the tensor op) would false-pass a
    same-named dataset/reader helper."""
    import ast
    tree = ast.parse(open("/root/reference/python/paddle/__init__.py",
                          encoding="utf-8", errors="replace").read())
    names = set()
    for node in tree.body:
        if isinstance(node, ast.ImportFrom):
            for alias in node.names:
                names.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                names.add((alias.asname or alias.name).split(".")[0])
    return names


def test_every_reference_toplevel_all_name_resolves():
    """Same mechanical sweep over the NON-fluid reference tree
    (python/paddle/**: tensor/, nn/, dataset/, reader/, distributed/,
    incubate/, utils/, ...). Resolution may land at an ancestor
    package — that is where the reference itself re-exports these for
    users (paddle.tensor.math.abs is consumed as paddle.abs) — but the
    bare paddle_tpu root only counts for names the reference root
    itself re-exports (see _reference_root_exports)."""
    import os

    ref_root = "/root/reference/python/paddle"
    root_ok = _reference_root_exports()
    missing = []
    checked = 0
    for dirpath, dirnames, files in os.walk(ref_root):
        dirnames[:] = [d for d in dirnames
                       if d not in ("fluid", "tests", "libs", "proto")]
        for fname in sorted(files):
            if not fname.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fname), ref_root)
            names = _reference_all_names(os.path.join(dirpath, fname))
            if not names:
                continue
            mod = rel[:-3].replace(os.sep, ".")
            if mod.endswith(".__init__"):
                mod = mod[:-len(".__init__")]
            target = "paddle_tpu" + ("" if mod == "__init__"
                                     else "." + mod)
            parts = target.split(".")
            non_root = [_resolve(".".join(parts[:k]))
                        for k in range(len(parts), 1, -1)]
            root = _resolve(parts[0])
            for n in names:
                checked += 1
                if n in _REFERENCE_ALL_BUGS:
                    continue
                if any(o is not None and hasattr(o, n)
                       for o in non_root):
                    continue
                if mod == "__init__" or n in root_ok:
                    if root is not None and hasattr(root, n):
                        continue
                if n == parts[-1] and len(parts) > 1:
                    # reference pattern `module x defines x` (batch.py's
                    # batch): the parent-level attribute IS the name
                    parent = _resolve(".".join(parts[:-1]))
                    if parent is not None and hasattr(parent, n):
                        continue
                missing.append(f"{target}:{n}")
    assert checked > 300, f"sweep only found {checked} names — broken?"
    assert missing == [], f"{len(missing)} missing: {missing}"


def test_conv3d_transpose_layer():
    pt.seed(0)
    m = nn.Conv3DTranspose(2, 4, 2, stride=2)
    x = pt.to_tensor(np.random.rand(1, 2, 3, 3, 3).astype("f4"))
    out = m(x)
    assert out.shape == [1, 4, 6, 6, 6]
    out.sum().backward()
    assert np.isfinite(np.asarray(m.weight.grad)).all()


def test_tree_conv_neighborhood():
    pt.seed(1)
    tc = nn.TreeConv(feature_size=3, output_size=2, act=None)
    # star tree: node0 parent of 1 and 2
    nv = np.zeros((1, 3, 3), "f4")
    nv[0, 1] = [1, 0, 0]
    nv[0, 2] = [0, 1, 0]
    es = np.array([[[0, 1], [0, 2]]], "i4")
    out = tc(pt.to_tensor(nv), pt.to_tensor(es))
    assert out.shape == [1, 3, 2]
    # node0 aggregates its children through the child-side matrix
    w_child = np.asarray(tc.weight.numpy())[1]
    expect0 = nv[0, 1] @ w_child + nv[0, 2] @ w_child
    np.testing.assert_allclose(out.numpy()[0, 0], expect0, rtol=1e-5,
                               atol=1e-6)


def test_static_gradients_dygraph_path():
    x = pt.to_tensor(np.array([2.0, 3.0], "f4"))
    x.stop_gradient = False
    g = static.gradients((x * x).sum(), x)
    g0 = g[0] if isinstance(g, (list, tuple)) else g
    np.testing.assert_allclose(g0.numpy(), [4.0, 6.0], rtol=1e-6)


def test_dgc_momentum_matches_momentum():
    pt.seed(2)
    w1 = pt.Parameter(np.ones((4,), "f4"))
    w2 = pt.Parameter(np.ones((4,), "f4"))
    o1 = optimizer.DGCMomentumOptimizer(0.1, 0.9, parameters=[w1])
    o2 = optimizer.Momentum(0.1, 0.9, parameters=[w2])
    for o, w in ((o1, w1), (o2, w2)):
        (w * w).sum().backward()
        o.step()
        o.clear_grad()
    np.testing.assert_allclose(w1.numpy(), w2.numpy())


def test_detection_map_metric():
    det = np.array([[[1, 0.9, 0, 0, 10, 10]]], "f4")
    lab = np.array([[[1, 0, 0, 10, 10]]], "f4")
    m = metric.DetectionMAP(class_num=2)
    m.update(pt.to_tensor(det), pt.to_tensor(lab))
    assert m.accumulate() == pytest.approx(1.0)


def test_error_clip_applied_by_tape():
    """ErrorClipByValue clips the incoming error signal of the var it is
    attached to (reference fluid/clip.py semantics)."""
    x = pt.to_tensor(np.array([3.0, -3.0], "f4"))
    x.stop_gradient = False
    y = x * 10.0
    y.error_clip = fluid.clip.ErrorClipByValue(max=0.5)
    (y * 1.0).sum().backward()
    # dy arrives as ones → clipped to 0.5 → dx = 0.5 * 10
    np.testing.assert_allclose(np.asarray(x.grad), [5.0, 5.0])


def test_set_gradient_clip_consumed_by_optimizer():
    """set_gradient_clip's global strategy applies when the optimizer got
    no grad_clip of its own."""
    try:
        fluid.clip.set_gradient_clip(fluid.clip.GradientClipByValue(0.01))
        w = pt.Parameter(np.ones((4,), "f4"))
        o = optimizer.SGD(learning_rate=1.0, parameters=[w])
        (w * 100.0).sum().backward()  # raw grad = 100
        o.step()
        # clipped grad 0.01 → w = 1 - 0.01
        np.testing.assert_allclose(w.numpy(), 0.99, rtol=1e-6)
    finally:
        fluid.clip.set_gradient_clip(None)


def test_detection_map_accumulates_globally():
    """accumulate() is the dataset mAP over all banked batches, not a
    mean of per-batch mAPs."""
    m = metric.DetectionMAP(class_num=2)
    # batch 1: one gt, detected correctly at score 0.9
    m.update(pt.to_tensor(np.array([[[1, 0.9, 0, 0, 10, 10]]], "f4")),
             pt.to_tensor(np.array([[[1, 0, 0, 10, 10]]], "f4")))
    # batch 2: one gt, missed entirely; one false positive at HIGHER score
    m.update(pt.to_tensor(np.array([[[1, 0.95, 50, 50, 60, 60]]], "f4")),
             pt.to_tensor(np.array([[[1, 0, 0, 10, 10]]], "f4")))
    # global ranking: FP(0.95), TP(0.9) over npos=2:
    # AP = 0*... + (0.5-0)*prec@TP(=1/2) = 0.25
    assert m.accumulate() == pytest.approx(0.25, abs=1e-6)


def test_xavier_msra_uniform_kwarg():
    """Regression (review r3): the fluid spellings Xavier(uniform=...) /
    MSRA(uniform=...) dispatch to the right variant."""
    import paddle_tpu.initializer as I
    assert isinstance(I.Xavier(), I.XavierUniform)
    assert isinstance(I.Xavier(uniform=False), I.XavierNormal)
    assert isinstance(I.MSRA(), I.KaimingUniform)
    assert isinstance(I.MSRA(uniform=False), I.KaimingNormal)


def test_per_param_gradient_clip():
    """set_gradient_clip(param_list=...) clips only those params."""
    w1 = pt.Parameter(np.ones((2,), "f4"))
    w2 = pt.Parameter(np.ones((2,), "f4"))
    fluid.clip.set_gradient_clip(fluid.clip.GradientClipByValue(0.01),
                                 param_list=[w1])
    o = optimizer.SGD(learning_rate=1.0, parameters=[w1, w2])
    ((w1 + w2) * 100.0).sum().backward()
    o.step()
    np.testing.assert_allclose(w1.numpy(), 0.99, rtol=1e-5)  # clipped
    np.testing.assert_allclose(w2.numpy(), -99.0, rtol=1e-5)  # raw


def test_map_counts_undetected_classes():
    """Regression (review r3): a class with ground truth but zero
    detections contributes AP=0 instead of being dropped."""
    from paddle_tpu.fluid.layers_extra2 import _map_eval
    det = [np.array([[1, 0.9, 0, 0, 10, 10]], "f4")]
    lab = [np.array([[1, 0, 0, 10, 10], [2, 20, 20, 30, 30]], "f4")]
    m = _map_eval(det, lab, class_num=3, background_label=0)
    assert m == pytest.approx(0.5)  # (AP1=1.0 + AP2=0.0) / 2


def test_detection_map_difficult_excluded():
    det = np.array([[[1, 0.9, 0, 0, 10, 10]]], "f4")
    lab6 = np.array([[[1, 0, 0, 10, 10, 1.0]]], "f4")  # difficult gt
    m = metric.DetectionMAP(class_num=2, evaluate_difficult=False)
    m.update(pt.to_tensor(det), pt.to_tensor(lab6))
    assert m.accumulate() == 0.0  # no countable gt → no AP


def test_fluid_incubate_fleet_import_paths():
    """The reference's launch-script import paths must resolve
    (reference: fluid/incubate/fleet/{collective,base,parameter_server})."""
    from paddle_tpu.fluid.incubate.fleet.collective import (
        fleet, CollectiveOptimizer, DistributedStrategy, TrainStatus)
    from paddle_tpu.fluid.incubate.fleet.base.role_maker import (
        PaddleCloudRoleMaker, UserDefinedRoleMaker, MPISymetricRoleMaker)
    from paddle_tpu.fluid.incubate.fleet.parameter_server. \
        distribute_transpiler import fleet as ps_fleet
    from paddle_tpu.fluid.incubate.data_generator import (
        MultiSlotDataGenerator)
    assert fleet is ps_fleet  # one singleton, collective-backed
    assert TrainStatus(3) == TrainStatus(3)
    assert callable(CollectiveOptimizer)


def test_top_level_module_tail():
    """compat/sysconfig/common_ops_import exist with the reference
    semantics (python/paddle/{compat,sysconfig,common_ops_import}.py)."""
    import os
    import paddle_tpu
    from paddle_tpu import compat, sysconfig
    from paddle_tpu import common_ops_import as coi

    assert compat.to_text(b"ab") == "ab"
    assert compat.to_text(["a", b"b"]) == ["a", "b"]
    assert compat.to_text(3.5) == 3.5  # non-string passes through (ref)
    assert compat.to_bytes("ab") == b"ab"
    assert compat.to_bytes(b"ab") == b"ab"
    import pytest as _pytest
    with _pytest.raises(TypeError):
        compat.to_bytes(5)  # six.b semantics: no silent NUL-fill
    # py2-style half-away-from-zero rounding, not banker's
    assert compat.round(0.5) == 1.0
    assert compat.round(-0.5) == -1.0
    assert compat.round(1.5) == 2.0
    assert compat.long_type is int
    assert compat.get_exception_message(ValueError("boom")) == "boom"
    assert os.path.isdir(sysconfig.get_include())
    assert isinstance(sysconfig.get_lib(), str)
    for name in ("Variable", "ParamAttr", "Constant",
                 "convert_np_dtype_to_dtype_", "in_dygraph_mode"):
        assert hasattr(coi, name), name
    assert hasattr(paddle_tpu, "compat")
    assert hasattr(paddle_tpu, "sysconfig")
