"""Fleet telemetry plane: snapshot protocol, histogram merge laws,
aggregator semantics (counters / gauges / staleness), sink rotation,
burn-rate alert state machine, anomaly detection shapes, the goodput
ledger, and the supervisor's anomaly decision context."""
import json
import math
import os
import random
import time

import pytest

from paddle_tpu import monitor
from paddle_tpu.monitor import alerts, fleet
from paddle_tpu.monitor.registry import (JsonlSink, Registry, read_jsonl,
                                         SNAPSHOT_FORMAT_VERSION)
from paddle_tpu.serving.metrics import LATENCY_BUCKETS_MS


@pytest.fixture(autouse=True)
def _clean_monitor():
    """The monitor and the findings board are process-global: every
    test starts disabled/empty and leaves nothing for its neighbours."""
    monitor.disable(flush_counters=False)
    monitor.reset()
    alerts.clear_findings()
    yield
    monitor.disable(flush_counters=False)
    monitor.reset()
    alerts.clear_findings()


def _hist_export(values, buckets=LATENCY_BUCKETS_MS):
    r = Registry()
    h = r.histogram("h", buckets=buckets)
    for v in values:
        h.observe(v)
    return h.export()


def _nearest_rank(values, q):
    s = sorted(values)
    return s[min(len(s) - 1, int(round(q * (len(s) - 1))))]


def _bucket_index(bounds, v):
    for i, b in enumerate(bounds):
        if v <= b:
            return i
    return len(bounds)


# ---------------------------------------------------------------------------
# histogram export + merge laws


def test_histogram_export_full_bounds():
    ex = _hist_export([0.5, 3.0, 250.0])
    assert ex["bounds"] == list(LATENCY_BUCKETS_MS)
    assert len(ex["counts"]) == len(LATENCY_BUCKETS_MS) + 1
    assert ex["count"] == 3 and sum(ex["counts"]) == 3
    assert ex["min"] == 0.5 and ex["max"] == 250.0
    assert math.isclose(ex["sum"], 253.5)


def test_merge_commutative_and_associative():
    rng = random.Random(7)
    parts = [[rng.lognormvariate(2.0, 1.5) for _ in range(rng.randint(5, 80))]
             for _ in range(3)]
    a, b, c = (_hist_export(p) for p in parts)
    ab = fleet.merge_histograms(a, b)
    ba = fleet.merge_histograms(b, a)
    assert ab == ba
    left = fleet.merge_histograms(fleet.merge_histograms(a, b), c)
    right = fleet.merge_histograms(a, fleet.merge_histograms(b, c))
    assert left == right
    whole = _hist_export([v for p in parts for v in p])
    assert left["counts"] == whole["counts"]
    assert left["count"] == whole["count"]
    assert math.isclose(left["sum"], whole["sum"], rel_tol=1e-9)


def test_merge_bounds_mismatch_raises():
    a = _hist_export([1.0])
    b = _hist_export([1.0], buckets=(1.0, 10.0, 100.0))
    with pytest.raises(ValueError):
        fleet.merge_histograms(a, b)


def test_merged_percentile_within_one_bucket_of_population():
    rng = random.Random(11)
    parts = [[rng.lognormvariate(1.5, 1.2) for _ in range(200)]
             for _ in range(4)]
    merged = None
    for p in parts:
        ex = _hist_export(p)
        merged = ex if merged is None else fleet.merge_histograms(
            merged, ex)
    union = [v for p in parts for v in p]
    for q in (0.50, 0.90, 0.99):
        est = fleet.histogram_percentile(merged, q)
        true = _nearest_rank(union, q)
        d = abs(_bucket_index(list(LATENCY_BUCKETS_MS), est)
                - _bucket_index(list(LATENCY_BUCKETS_MS), true))
        assert d <= 1, (q, est, true)


def test_latency_bucket_identity_asserted():
    from paddle_tpu.serving import metrics as smetrics
    monitor.enable()
    smetrics.record_request_slo(ttft_ms=12.0, tpot_ms=3.0)
    checked = smetrics.assert_mergeable_latency_histograms()
    assert "serving.ttft_ms" in checked
    monitor.histogram("serving.rogue_ms", buckets=(1.0, 10.0)).observe(2)
    with pytest.raises(AssertionError, match="serving.rogue_ms"):
        smetrics.assert_mergeable_latency_histograms()


# ---------------------------------------------------------------------------
# snapshot protocol + aggregator


def test_snapshot_write_read_roundtrip(tmp_path):
    r = Registry()
    r.counter("req").inc(5)
    r.gauge("depth").set(3.0)
    r.histogram("lat", buckets=LATENCY_BUCKETS_MS).observe(4.2)
    path = fleet.write_snapshot(str(tmp_path), source="w0", registry=r)
    assert os.path.basename(path) == "snap-w0.json"
    assert not [p for p in os.listdir(tmp_path) if ".tmp" in p]
    snaps = fleet.read_snapshots(str(tmp_path))
    assert len(snaps) == 1
    snap = snaps[0]
    assert snap["format_version"] == SNAPSHOT_FORMAT_VERSION
    assert snap["source"] == "w0" and snap["pid"] == os.getpid()
    assert snap["counters"]["req"] == 5
    assert snap["gauges"]["depth"] == 3.0
    assert snap["histograms"]["lat"]["count"] == 1


def test_read_snapshots_skips_junk_and_foreign_versions(tmp_path):
    r = Registry()
    r.counter("c").inc()
    fleet.write_snapshot(str(tmp_path), source="good", registry=r)
    (tmp_path / "snap-torn.json").write_text("{not json")
    (tmp_path / "snap-future.json").write_text(
        json.dumps({"format_version": 999, "source": "future",
                    "counters": {}, "gauges": {}, "histograms": {}}))
    (tmp_path / "notes.txt").write_text("ignore me")
    snaps = fleet.read_snapshots(str(tmp_path))
    assert [s["source"] for s in snaps] == ["good"]


def test_aggregator_merges_counters_gauges_histograms(tmp_path):
    rngs = {"a": [1.0, 5.0, 40.0], "b": [2.0, 9.0, 300.0]}
    for src, vals in rngs.items():
        r = Registry()
        r.counter("tokens").inc(10 if src == "a" else 32)
        r.gauge("queue_depth").set(2.0 if src == "a" else 7.0)
        h = r.histogram("lat_ms", buckets=LATENCY_BUCKETS_MS)
        for v in vals:
            h.observe(v)
        fleet.write_snapshot(str(tmp_path), source=src, registry=r)
        time.sleep(0.01)        # distinct snapshot ts: b is newest
    agg = fleet.FleetAggregator(str(tmp_path))
    agg.scrape()
    assert agg.value("tokens") == 42
    assert agg.value("queue_depth") == 7.0     # last write wins
    h = agg.histogram("lat_ms")
    assert h["count"] == 6
    assert sorted(s["source"] for s in agg.sources()) == ["a", "b"]
    union = sorted(rngs["a"] + rngs["b"])
    est = agg.percentile("lat_ms", 0.5)
    assert est is not None
    d = abs(_bucket_index(list(LATENCY_BUCKETS_MS), est)
            - _bucket_index(list(LATENCY_BUCKETS_MS),
                            _nearest_rank(union, 0.5)))
    assert d <= 1


def test_aggregator_staleness_ttl_drops_source(tmp_path):
    for src, tok in (("live", 1), ("dead", 100)):
        r = Registry()
        r.counter("tokens").inc(tok)
        r.gauge(f"replica.{src}.depth").set(9.0)
        fleet.write_snapshot(str(tmp_path), source=src, registry=r)
    # age the dead source's snapshot far past the TTL
    p = fleet.snapshot_path(str(tmp_path), "dead")
    snap = json.loads(open(p).read())
    snap["ts"] -= 3600.0
    with open(p, "w") as fh:
        json.dump(snap, fh)
    agg = fleet.FleetAggregator(str(tmp_path), staleness_ttl_s=30.0)
    agg.scrape()
    assert agg.value("tokens") == 1            # stale counters excluded
    assert agg.value("replica.dead.depth", default=None) is None
    meta = {s["source"]: s["stale"] for s in agg.sources()}
    assert meta == {"live": False, "dead": True}


def test_publisher_lifecycle_and_final_snapshot(tmp_path):
    monitor.enable(telemetry_dir=str(tmp_path))
    assert fleet.publisher_active()
    monitor.counter("work").inc(3)
    stats = fleet.publisher_stats()
    assert stats is not None and stats["interval_s"] > 0
    monitor.disable(flush_counters=False)
    assert not fleet.publisher_active()
    snaps = fleet.read_snapshots(str(tmp_path))   # the stop() snapshot
    assert len(snaps) == 1 and snaps[0]["counters"]["work"] == 3


def test_disabled_monitor_publishes_nothing(tmp_path):
    monitor.counter("noop").inc()
    assert not fleet.publisher_active()
    assert fleet.publisher_stats() is None
    assert os.listdir(tmp_path) == []


# ---------------------------------------------------------------------------
# sink rotation


def test_jsonl_sink_rotation(tmp_path):
    path = str(tmp_path / "events.jsonl")
    sink = JsonlSink(path, max_bytes=400)
    for i in range(60):
        sink.emit({"kind": "x", "i": i, "pad": "p" * 20})
    sink.close()
    assert os.path.exists(path)
    assert os.path.exists(path + ".1")
    assert os.path.exists(path + ".2")
    assert os.path.getsize(path + ".1") <= 400 + 80
    # every retained file is intact JSONL and the newest record
    # survived (in `path`, or in `.1` if the last emit rotated)
    rows = read_jsonl(path) + read_jsonl(path + ".1")
    assert any(r["i"] == 59 for r in rows)
    assert all(r["kind"] == "x" for r in rows)


def test_enable_max_bytes_env(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_MONITOR_MAX_BYTES", "300")
    path = monitor.enable(str(tmp_path))
    for i in range(80):
        monitor.emit(kind="spam", i=i, pad="p" * 20)
    monitor.disable(flush_counters=False)
    assert os.path.exists(path + ".1")
    rows = read_jsonl(path) + read_jsonl(path + ".1")
    assert any(r.get("i") == 79 for r in rows)


# ---------------------------------------------------------------------------
# burn-rate alerts


def _mk_rule(**kw):
    kw.setdefault("fast_window_s", 10.0)
    kw.setdefault("slow_window_s", 60.0)
    kw.setdefault("budget", 0.1)
    kw.setdefault("burn_threshold", 2.0)
    return alerts.BurnRateRule("slo-ttft", "slo.ttft_p99_ms", 100.0,
                               direction="above", **kw)


def test_burn_rate_walks_pending_firing_resolved():
    rule = _mk_rule()
    mgr = alerts.AlertManager(rules=[rule], source=lambda s: None)
    t0 = 1000.0
    # seed the slow window clean enough that the first breach burst
    # ignites only the fast window (50 clean + 8 hot = 14% < 20%)
    for i in range(50):
        mgr.feed("slo-ttft", 50.0, now=t0 + i)
    for i in range(8):
        mgr.feed("slo-ttft", 500.0, now=t0 + 50 + i)
    mgr.tick(now=t0 + 57)
    states = [a["state"] for a in mgr.alerts()]
    assert states == ["pending"]       # fast hot, slow not yet
    for i in range(30):
        mgr.feed("slo-ttft", 500.0, now=t0 + 58 + i)
    mgr.tick(now=t0 + 88)
    assert [a["state"] for a in mgr.alerts()] == ["firing"]
    # recovery: fast window all-clean resolves
    for i in range(15):
        mgr.feed("slo-ttft", 50.0, now=t0 + 89 + i)
    mgr.tick(now=t0 + 103)
    assert [a["state"] for a in mgr.alerts()] == ["resolved"]
    seq = [h["state"] for h in mgr.history]
    assert seq == ["pending", "firing", "resolved"]


def test_burn_rate_blip_dissolves_silently():
    rule = _mk_rule()
    mgr = alerts.AlertManager(rules=[rule], source=lambda s: None)
    t0 = 2000.0
    for i in range(30):
        mgr.feed("slo-ttft", 50.0, now=t0 + i)
    for i in range(4):
        mgr.feed("slo-ttft", 500.0, now=t0 + 30 + i)
    mgr.tick(now=t0 + 34)
    assert [a["state"] for a in mgr.alerts()] == ["pending"]
    for i in range(12):
        mgr.feed("slo-ttft", 50.0, now=t0 + 35 + i)
    mgr.tick(now=t0 + 47)
    assert mgr.alerts() == []          # dissolved, never fired
    assert [h["state"] for h in mgr.history] == ["pending"]


def test_default_rules_directions():
    rules = {r.name: r for r in alerts.default_rules()}
    assert rules["slo-ttft-p99"].direction == "above"
    assert rules["slo-tokens-per-s"].direction == "below"
    assert rules["slo-goodput"].direction == "below"
    assert rules["slo-ttft-p99"].breaches(1e9)
    assert rules["slo-tokens-per-s"].breaches(0.0)


# ---------------------------------------------------------------------------
# anomaly shapes


def _snap(src, ts, compiles=0, step_sum=0.0, step_count=0,
          accept=None, depth=None):
    gauges = {}
    if accept is not None:
        gauges["serving.decode.accept_rate"] = accept
    if depth is not None:
        gauges["serving.queue_depth"] = depth
    hists = {}
    if step_count:
        hists["serving.decode.step_ms"] = {
            "bounds": list(LATENCY_BUCKETS_MS),
            "counts": [0] * (len(LATENCY_BUCKETS_MS) + 1),
            "count": step_count, "sum": step_sum,
            "min": 0.0, "max": step_sum}
    return {"format_version": 1, "source": src, "pid": 1, "ts": ts,
            "counters": {"jit.compile": compiles}, "gauges": gauges,
            "histograms": hists}


def test_detector_straggler_leave_one_out():
    det = alerts.AnomalyDetector(warmup_ticks=0, min_sources=3)
    t = 100.0
    base = [_snap(f"w{i}", t, step_sum=50.0, step_count=10)
            for i in range(4)]
    det.update(base, now=t)
    nxt = []
    for i in range(4):
        slow = 400.0 if i == 3 else 100.0
        nxt.append(_snap(f"w{i}", t + 1, step_sum=50.0 + slow,
                         step_count=20))
    found = det.update(nxt, now=t + 1)
    names = [f["name"] for f in found]
    assert names == ["straggler(w3)"]
    f = found[0]
    assert f["source"] == "w3"
    assert f["series"] == "serving.decode.step_ms"
    assert f["z"] > 3.0
    assert [x["name"] for x in alerts.active_findings()] == names


def test_detector_compile_storm_windowed():
    det = alerts.AnomalyDetector(warmup_ticks=0,
                                 compile_delta_threshold=6,
                                 compile_window_s=5.0, min_sources=3)
    t = 200.0
    det.update([_snap(f"w{i}", t, compiles=10) for i in range(3)],
               now=t)
    # the burst lands spread across ticks: 3 + 4 within the window
    det.update([_snap("w0", t + 1, compiles=13),
                _snap("w1", t + 1, compiles=10),
                _snap("w2", t + 1, compiles=10)], now=t + 1)
    found = det.update([_snap("w0", t + 2, compiles=17),
                        _snap("w1", t + 2, compiles=10),
                        _snap("w2", t + 2, compiles=11)], now=t + 2)
    assert [f["name"] for f in found] == ["compile_storm(w0)"]
    assert found[0]["delta"] == 7
    # the window drains: far enough in the future it stops reporting
    later = det.update([_snap("w0", t + 60, compiles=17),
                        _snap("w1", t + 60, compiles=10),
                        _snap("w2", t + 60, compiles=11)], now=t + 60)
    assert later == []


def test_detector_findings_drive_alerts_and_age_out():
    mgr = alerts.AlertManager(rules=[], finding_resolve_after_s=5.0)
    det = alerts.AnomalyDetector(manager=mgr, warmup_ticks=0,
                                 min_sources=3)
    t = 300.0
    det.update([_snap(f"w{i}", t, step_sum=50.0, step_count=10)
                for i in range(3)], now=t)
    det.update([_snap("w0", t + 1, step_sum=550.0, step_count=20),
                _snap("w1", t + 1, step_sum=150.0, step_count=20),
                _snap("w2", t + 1, step_sum=150.0, step_count=20)],
               now=t + 1)
    firing = mgr.tick(now=t + 1)
    assert [a["name"] for a in firing] == ["straggler(w0)"]
    # detector goes quiet -> the alert resolves after the grace window
    mgr.tick(now=t + 20)
    assert [a["state"] for a in mgr.alerts()] == ["resolved"]


def test_supervisor_cites_anomalies_in_decisions():
    from paddle_tpu.serving.supervisor import ServingSupervisor

    class Owner:
        inflight_timeout_s = 1.0
        _replicas = ()

        def _refresh_hedge_delay(self, p99):
            pass

    owner = Owner()
    sup = ServingSupervisor(owner, start=False, scale=False)
    alerts.set_active_findings([
        {"name": "straggler(w1)", "kind": "straggler", "source": "w1",
         "series": "serving.decode.step_ms"}])
    sup.tick(owner)
    anomaly = [d for d in sup.decisions if d["decision"] == "anomaly"]
    assert [d["anomaly"] for d in anomaly] == ["straggler(w1)"]
    assert anomaly[0]["anomalies"] == ["straggler(w1)"]
    sup.tick(owner)     # same finding: one decision per edge
    assert len([d for d in sup.decisions
                if d["decision"] == "anomaly"]) == 1


# ---------------------------------------------------------------------------
# goodput ledger + replica series hygiene


def test_goodput_ledger_reconciles():
    monitor.enable()
    ledger = monitor.GoodputLedger()
    ledger.begin()
    monitor.counter("prefetch.stall_seconds").inc(0.2)
    monitor.counter("ckpt.save_s").inc(0.1)
    out = ledger.finish(wall_s=1.0)
    assert math.isclose(out["wall_s"], 1.0)
    assert math.isclose(out["lost_s"], 0.3, rel_tol=1e-6)
    assert math.isclose(out["compute_s"], 0.7, rel_tol=1e-6)
    assert math.isclose(out["goodput_fraction"], 0.7, rel_tol=1e-3)
    # wall == compute + sum(losses) by construction
    assert math.isclose(
        out["wall_s"], out["compute_s"] + out["lost_s"], rel_tol=1e-9)
    rows = {r["category"]: r["seconds"] for r in out["lost"]}
    assert set(rows) == {c for c, _ in monitor.GOODPUT_CATEGORIES}
    assert math.isclose(rows["input_stall"], 0.2, rel_tol=1e-6)
    assert math.isclose(rows["checkpoint"], 0.1, rel_tol=1e-6)
    assert out["lost"][0]["category"] == "input_stall"   # ranked


def test_goodput_only_counts_deltas_after_begin():
    monitor.enable()
    monitor.counter("prefetch.stall_seconds").inc(5.0)   # pre-history
    ledger = monitor.GoodputLedger()
    ledger.begin()
    monitor.counter("prefetch.stall_seconds").inc(0.25)
    out = ledger.finish(wall_s=1.0)
    rows = {r["category"]: r["seconds"] for r in out["lost"]}
    assert math.isclose(rows["input_stall"], 0.25, rel_tol=1e-6)


def test_clear_replica_series_scoped(tmp_path):
    from paddle_tpu.serving import metrics as smetrics
    monitor.enable(str(tmp_path))
    monitor.gauge("serving.breaker_state.2").set(1.0)
    monitor.gauge("serving.replica.2.inflight_age_s").set(0.4)
    monitor.gauge("serving.breaker_state.3").set(0.0)
    removed = smetrics.clear_replica_series(2)
    assert removed == 2
    reg = monitor.registry()
    assert reg.value("serving.breaker_state.2", default=None) is None
    assert reg.value("serving.replica.2.inflight_age_s",
                     default=None) is None
    assert reg.value("serving.breaker_state.3") == 0.0
