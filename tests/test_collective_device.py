"""Numeric tests for the collective ops nothing else exercised
(SURVEY §2 row 19: reduce_scatter / all_to_all / broadcast / barrier /
world_size) on the 8-device CPU mesh, plus the device API (row 35) and
PRNG helpers (row 34)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.parallel import collective as C


def _shard_run(fn, x, n=4, out_specs=None):
    mesh = Mesh(np.asarray(jax.devices()[:n]), ("dp",))
    f = jax.jit(jax.shard_map(
        fn, mesh=mesh, in_specs=P("dp"),
        out_specs=out_specs if out_specs is not None else P("dp"),
        check_vma=False))
    return np.asarray(f(x))


def test_reduce_scatter_matches_sum_split():
    rng = np.random.RandomState(0)
    x = rng.randn(4, 8).astype("f4")  # each rank holds one (1, 8) row

    def fn(xs):
        # psum_scatter over rows: rank r gets (sum over ranks)[r-th piece]
        return C.reduce_scatter(pt.to_tensor(xs[0]), axis=0,
                                axis_name="dp").data[None]

    out = _shard_run(fn, x)
    total = x.sum(axis=0)          # (8,)
    np.testing.assert_allclose(out.reshape(4, 2), total.reshape(4, 2),
                               atol=1e-5)


def test_all_to_all_transposes_shards():
    # rank r holds row r = [4r, 4r+1, 4r+2, 4r+3]; after all_to_all with
    # split on that axis, rank r holds column r of the rank-major matrix
    x = np.arange(16, dtype="f4").reshape(4, 4)

    def fn(xs):
        return C.all_to_all(pt.to_tensor(xs[0]), split_axis=0,
                            concat_axis=0, axis_name="dp").data[None]

    out = _shard_run(fn, x)
    np.testing.assert_allclose(out, x.T, atol=0)


def test_barrier_and_world_size():
    """world_size must see the bound axis (4) and barrier must be
    callable inside the region; broadcast itself is covered in
    test_parallel."""
    x = np.arange(4, dtype="f4").reshape(4, 1)

    def fn(xs):
        C.barrier(axis_name="dp")
        ws = C.world_size("dp")
        return jnp.full((1, 1), ws, jnp.float32)

    out = _shard_run(fn, x)
    np.testing.assert_allclose(out.ravel(), [4.0] * 4, atol=0)


def test_collectives_identity_outside_spmd():
    x = pt.to_tensor(np.ones((4,), "f4"))
    np.testing.assert_allclose(C.reduce_scatter(x).numpy(), 1.0)
    np.testing.assert_allclose(C.all_to_all(x).numpy(), 1.0)
    assert C.barrier() is None
    assert not C.in_spmd_context("dp")


def test_device_api():
    from paddle_tpu import device as D
    d = D.get_device()
    assert ":" in d
    saved = D._current
    try:
        D.set_device("cpu")
        assert D.get_device().startswith("cpu")
    finally:
        D._current = saved
    p = D.CPUPlace()
    assert p.device.platform == "cpu"
    assert isinstance(D.is_compiled_with_cuda(), bool)
    assert isinstance(D.is_compiled_with_tpu(), bool)


def test_random_helpers():
    from paddle_tpu import random as R
    pt.seed(123)
    assert R.get_seed() == 123
    k1 = R.next_key()
    k2 = R.next_key()
    assert not np.array_equal(np.asarray(k1), np.asarray(k2))
    ks = R.split_keys(4)
    assert len(ks) == 4
    holder = R.global_key_tensor()
    assert holder is R.global_key_tensor()  # stable holder object
