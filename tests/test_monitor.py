"""paddle_tpu.monitor — registry semantics, JSONL round-trip, dispatch /
collective / executor / optimizer instrumentation, StepMonitor MFU, and
the zero-cost-when-disabled contract."""
import json
import threading

import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import monitor, nn, optimizer as opt
from paddle_tpu.monitor.registry import (Counter, Gauge, Histogram,
                                         JsonlSink, Registry, read_jsonl)
from paddle_tpu.parallel import collective


@pytest.fixture(autouse=True)
def _clean_monitor():
    """The monitor is process-global: every test starts disabled/empty
    and leaves nothing behind for its neighbours."""
    monitor.disable(flush_counters=False)
    monitor.reset()
    yield
    monitor.disable(flush_counters=False)
    monitor.reset()


@pytest.fixture
def mon(tmp_path):
    path = monitor.enable(str(tmp_path))
    yield path
    monitor.disable(flush_counters=False)


@pytest.fixture
def mesh8():
    mesh = collective.make_mesh({"dp": 8})
    yield mesh
    collective.set_mesh(None)


# -- registry -----------------------------------------------------------------

def test_counter_gauge_histogram_semantics():
    r = Registry()
    c = r.counter("a.b")
    c.inc()
    c.inc(3)
    assert r.value("a.b") == 4
    with pytest.raises(ValueError):
        c.inc(-1)

    g = r.gauge("g")
    g.set(2.5)
    g.set(1.5)
    assert r.value("g") == 1.5

    h = r.histogram("h")
    for v in (0.5, 2.0, 64.0):
        h.observe(v)
    snap = r.snapshot()["h"]
    assert snap["count"] == 3
    assert snap["sum"] == pytest.approx(66.5)
    assert snap["min"] == 0.5 and snap["max"] == 64.0

    # one name, one kind
    with pytest.raises(TypeError):
        r.gauge("a.b")

    assert set(r.snapshot(prefix="a.")) == {"a.b"}
    r.reset()
    assert r.snapshot() == {}


def test_counter_thread_safety():
    r = Registry()
    c = r.counter("n")

    def work():
        for _ in range(1000):
            c.inc()

    ts = [threading.Thread(target=work) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert c.value == 8000


def test_jsonl_sink_round_trip(tmp_path):
    path = tmp_path / "out" / "ev.jsonl"
    sink = JsonlSink(str(path))
    sink.emit({"kind": "x", "v": 1})
    sink.emit({"kind": "y", "v": [1, 2], "arr": np.float32(2.0)})
    sink.close()
    recs = read_jsonl(str(path))
    assert [r["kind"] for r in recs] == ["x", "y"]
    assert all("ts" in r for r in recs)
    assert recs[1]["v"] == [1, 2]


def test_read_jsonl_skips_truncated_final_line(tmp_path):
    """A run killed mid-write leaves a torn last line; reading the
    stream back must keep every complete record and warn, not raise."""
    path = tmp_path / "ev.jsonl"
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(json.dumps({"kind": "a", "v": 1}) + "\n")
        fh.write(json.dumps({"kind": "b", "v": 2}) + "\n")
        fh.write('{"kind": "c", "v"')       # killed mid-write
    with pytest.warns(UserWarning, match="line 3"):
        recs = read_jsonl(str(path))
    assert [r["kind"] for r in recs] == ["a", "b"]


def test_registry_value_counter_gauge_histogram_matrix():
    """Registry.value must answer for every metric kind: scalar for
    counter/gauge, snapshot dict for histogram (which has no single
    value), default for a missing name."""
    r = Registry()
    r.counter("c").inc(5)
    r.gauge("g").set(2.5)
    h = r.histogram("h")
    h.observe(1.0)
    h.observe(3.0)
    assert r.value("c") == 5
    assert r.value("g") == 2.5
    hv = r.value("h")
    assert isinstance(hv, dict)
    assert hv["count"] == 2 and hv["sum"] == pytest.approx(4.0)
    assert hv["min"] == 1.0 and hv["max"] == 3.0
    assert r.value("missing") == 0
    assert r.value("missing", default=None) is None


# -- dispatch hook ------------------------------------------------------------

def test_dispatch_counts_known_op_sequence(mon):
    a = pt.to_tensor(np.ones((3, 3), np.float32))
    b = pt.to_tensor(np.ones((3, 3), np.float32))
    before = dict(monitor.snapshot("dispatch."))
    for _ in range(3):
        c = a + b
    _ = a * b
    snap = monitor.snapshot("dispatch.")
    assert snap.get("dispatch.add", 0) - before.get("dispatch.add", 0) == 3
    assert snap.get("dispatch.multiply", 0) \
        - before.get("dispatch.multiply", 0) == 1


def test_dispatch_grad_split(mon):
    p = pt.to_tensor(np.ones((2, 2), np.float32), stop_gradient=False)
    q = pt.to_tensor(np.ones((2, 2), np.float32))
    _ = p + q            # on the tape
    with pt.no_grad():
        _ = p + q        # not on the tape
    snap = monitor.snapshot("dispatch.")
    assert snap.get("dispatch.add") == 2
    assert snap.get("dispatch.grad.add") == 1


def test_disabled_mode_no_overhead_state():
    """The contract ISSUE.md asks a test to assert: with the monitor
    off, dispatch carries NO hook (one `is None` flag check and nothing
    else — no per-op dict writes, no registry mutation, no tape of
    metric state)."""
    from paddle_tpu import dispatch
    assert dispatch._monitor_hook is None
    a = pt.to_tensor(np.ones((4,), np.float32))
    b = pt.to_tensor(np.ones((4,), np.float32))
    for _ in range(5):
        _ = a + b
    assert monitor.snapshot() == {}
    assert not monitor.enabled()
    assert monitor.jsonl_path() is None


def test_enable_disable_installs_and_removes_hook(tmp_path):
    from paddle_tpu import dispatch
    monitor.enable(str(tmp_path))
    assert dispatch._monitor_hook is not None
    monitor.disable()
    assert dispatch._monitor_hook is None


def test_enable_twice_closes_previous_sink(tmp_path):
    """Re-enabling with a new path must close the old sink's file
    handle (the leak: N enables -> N open fds) and route subsequent
    events to the new file only."""
    import paddle_tpu.monitor as M
    p1 = monitor.enable(str(tmp_path / "one.jsonl"))
    first_sink = M._sink
    assert first_sink is not None and first_sink._fh is not None
    p2 = monitor.enable(str(tmp_path / "two.jsonl"))
    assert p1 != p2
    assert first_sink._fh is None          # old handle closed
    monitor.emit(kind="after_switch")
    monitor.disable(flush_counters=False)
    assert not any(r.get("kind") == "after_switch"
                   for r in read_jsonl(p1))
    assert any(r.get("kind") == "after_switch"
               for r in read_jsonl(p2))


# -- collectives --------------------------------------------------------------

def test_collective_byte_accounting_under_shard_map(mon, mesh8):
    def f(x):
        y = collective.all_reduce(pt.Tensor(x), op="sum", axis_name="dp")
        return y.data

    xs = jnp.ones((8, 16), jnp.float32)
    out = shard_map(f, mesh=mesh8, in_specs=P("dp"),
                    out_specs=P("dp"))(xs)
    np.testing.assert_allclose(np.asarray(out), 8.0)
    snap = monitor.snapshot("collective.")
    assert snap["collective.c_allreduce_sum.dp.calls"] >= 1
    # per-shard payload: (1, 16) f32 = 64 bytes per traced issue
    assert snap["collective.c_allreduce_sum.dp.bytes"] % 64 == 0
    assert snap["collective.c_allreduce_sum.dp.bytes"] >= 64


def test_collective_identity_fallback_not_counted(mon):
    # outside any SPMD region the op is an eager identity — no record
    _ = collective.all_reduce(pt.to_tensor(np.ones(4, np.float32)),
                              op="sum", axis_name="dp")
    assert monitor.snapshot("collective.") == {}


def test_axis_size_compat(mesh8):
    def f(x):
        return jnp.full_like(x, collective.axis_size("dp"))

    out = shard_map(f, mesh=mesh8, in_specs=P("dp"),
                    out_specs=P("dp"))(jnp.zeros((8,), jnp.float32))
    np.testing.assert_allclose(np.asarray(out), 8.0)
    assert not collective.in_spmd_context("dp")  # outside: no axis bound


# -- executor -----------------------------------------------------------------

def test_executor_counters(mon):
    from paddle_tpu import static
    static.reset_default_programs()
    pt.enable_static()
    try:
        model = nn.Linear(4, 2)
        x = static.data("x", [None, 4], "float32")
        out = model(x)
        exe = static.Executor()
        xv = np.random.randn(3, 4).astype("f4")
        exe.run(feed={"x": xv}, fetch_list=[out])
        exe.run(feed={"x": xv}, fetch_list=[out])
    finally:
        pt.disable_static()
        static.reset_default_programs()
    snap = monitor.snapshot("executor.")
    assert snap["executor.run"] == 2
    assert snap["executor.cache_miss"] == 1
    assert snap["executor.cache_hit"] == 1
    assert snap["executor.compile"] == 1


# -- optimizer ----------------------------------------------------------------

def test_optimizer_step_counter(mon):
    model = nn.Linear(2, 2)
    o = opt.SGD(learning_rate=0.1, parameters=model.parameters())
    loss = model(pt.to_tensor(np.ones((1, 2), np.float32))).sum()
    loss.backward()
    o.step()
    assert monitor.snapshot("optimizer.")["optimizer.step.SGD"] == 1


def test_adam_multi_tensor_fallback_on_unequal_beta_pows(mon):
    model = nn.Linear(4, 4)
    o = opt.Adam(learning_rate=1e-3, parameters=model.parameters(),
                 use_multi_tensor=True)
    loss = model(pt.to_tensor(np.ones((2, 4), np.float32))).sum()
    loss.backward()
    params = [p for p in model.parameters() if p._grad is not None]
    assert len(params) >= 2
    for p in params:
        o._pre_param(p)
    # knock one param out of lockstep (as a partial restore would)
    o._accumulators[id(params[0])]["beta1_pow"].data = \
        jnp.asarray(0.9, jnp.float32)
    opt.Adam._warned_unequal_beta_pow = False
    try:
        with pytest.warns(RuntimeWarning, match="multi-tensor Adam"):
            o.step()
    finally:
        opt.Adam._warned_unequal_beta_pow = False
    assert monitor.snapshot(
        "optimizer.")["optimizer.adam_multi_tensor_fallback"] == 1


def test_linear_lr_warmup_init_peek_leaves_inner_untouched():
    from paddle_tpu.fluid.dygraph_lr import (LinearLrWarmup,
                                             NaturalExpDecay)
    inner = NaturalExpDecay(0.1, decay_steps=10, decay_rate=0.5, begin=0)
    warm = LinearLrWarmup(inner, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    model = nn.Linear(2, 2)
    o = opt.SGD(learning_rate=warm, parameters=model.parameters())
    # constructing the optimizer reads the init lr via peek(): neither
    # the warmup's nor the WRAPPED decay's step_num may advance
    assert inner.step_num == 0
    assert warm.step_num == 1
    assert o.get_lr() == pytest.approx(warm.lr_ratio_before_warmup * 1)
    # past warmup, peek() forwards to the inner schedule without advancing
    warm.step_num = 10
    lr_peek = warm.peek()
    assert inner.step_num == 0
    assert lr_peek == pytest.approx(inner.peek())


# -- one_hot eager range check ------------------------------------------------

def test_one_hot_eager_raises_out_of_range():
    from paddle_tpu.fluid.input import one_hot
    ids = pt.to_tensor(np.array([[0], [5]], np.int32))
    with pytest.raises(ValueError, match="out of range"):
        one_hot(ids, depth=4)


def test_one_hot_allow_out_of_range_zero_rows():
    from paddle_tpu.fluid.input import one_hot
    ids = pt.to_tensor(np.array([1, 7], np.int32))
    out = one_hot(ids, depth=4, allow_out_of_range=True)
    arr = np.asarray(out.numpy())
    np.testing.assert_allclose(arr[0], [0, 1, 0, 0])
    np.testing.assert_allclose(arr[1], [0, 0, 0, 0])  # zero-row semantics


def test_one_hot_traced_ids_keep_zero_row_semantics():
    from paddle_tpu.ops.manip import one_hot as raw_one_hot

    @jax.jit
    def f(ids):
        t = raw_one_hot(pt.Tensor(ids), 4)
        return t.data if hasattr(t, "data") else t

    out = np.asarray(f(jnp.array([1, 9], jnp.int32)))
    np.testing.assert_allclose(out[1], [0, 0, 0, 0])


# -- StepMonitor + end-to-end -------------------------------------------------

def test_step_monitor_mfu_math():
    assert monitor.mfu(100e12, 1.0, peak_flops=200e12) == \
        pytest.approx(0.5)
    assert monitor.mfu(100e12, 1.0, peak_flops=None) is None
    assert monitor.transformer_train_flops_per_token(110e6) == \
        pytest.approx(6.6e8)


class _FakeDevice:
    def __init__(self, kind):
        self.device_kind = kind


@pytest.mark.parametrize("kind,peak", [
    ("TPU v5 lite", 197e12),     # must NOT match the "TPU v5p" entry
    ("TPU v5e", 197e12),
    ("TPU v5p", 459e12),
    ("TPU v4", 275e12),
    ("TPU v6e", 918e12),
    ("TPU v2", 46e12),
    ("NVIDIA A100", None),       # unknown kind -> None, never invented
    ("", None),
])
def test_peak_flops_device_kind_substring_ordering(kind, peak, monkeypatch):
    """The table is substring-matched in order: 'TPU v5 lite' and
    'TPU v5e' are distinct spellings of the same 197e12 chip and neither
    may fall through to the v5p row."""
    monkeypatch.delenv("PADDLE_TPU_FLOPS_CEILING", raising=False)
    assert monitor.peak_flops_for_device(_FakeDevice(kind)) == peak


def test_peak_flops_ceiling_env_override(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLOPS_CEILING", "123e9")
    assert monitor.peak_flops_for_device(_FakeDevice("TPU v4")) == 123e9
    # empty string is "unset", not a parse error: table takes over
    monkeypatch.setenv("PADDLE_TPU_FLOPS_CEILING", "")
    assert monitor.peak_flops_for_device(_FakeDevice("TPU v4")) == 275e12
    assert monitor.peak_flops_for_device(_FakeDevice("mystery")) is None


def test_toy_training_loop_jsonl_stream(tmp_path, mesh8):
    """The ISSUE.md acceptance scenario: a 3-step toy loop with
    monitoring on yields a JSONL stream holding (a) per-op dispatch
    counts, (b) >= 1 collective byte record under an SPMD mesh, and
    (c) a step record carrying throughput and mfu."""
    path = monitor.enable(str(tmp_path))
    model = nn.Linear(8, 8)
    o = opt.SGD(learning_rate=0.01, parameters=model.parameters())
    x = pt.to_tensor(np.random.randn(16, 8).astype("f4"))

    # one SPMD collective so the stream holds a byte record
    def f(v):
        y = collective.all_reduce(pt.Tensor(v), op="sum", axis_name="dp")
        return y.data

    mesh = collective.get_mesh()
    shard_map(f, mesh=mesh, in_specs=P("dp"),
              out_specs=P("dp"))(jnp.ones((8, 4), jnp.float32))

    sm = monitor.StepMonitor(items_per_step=16, flops_per_step=1e9,
                             peak_flops=197e12, item="images")
    sm.start()
    for _ in range(3):
        loss = model(x).sum()
        loss.backward()
        o.step()
        o.clear_grad()
        sm.step(loss=float(loss.numpy()))
    sm.report(print_table=False)
    monitor.disable()

    recs = read_jsonl(path)
    kinds = [r["kind"] for r in recs]
    steps = [r for r in recs if r["kind"] == "step"]
    assert len(steps) == 3
    assert all(r["items_per_sec"] > 0 and r["mfu"] is not None
               for r in steps)
    assert any(r["kind"] == "collective" and r["bytes"] > 0
               for r in recs)
    # final counters snapshot carries the per-op dispatch counts
    counters = [r for r in recs if r["kind"] == "counters"][-1]
    dispatch_counts = {k: v for k, v in counters["counters"].items()
                       if k.startswith("dispatch.")}
    assert dispatch_counts.get("dispatch.linear", 0) >= 3
    assert "step_summary" in kinds
