"""Test config: force an 8-device virtual CPU mesh (SURVEY §4) so parallel
tests exercise real shardings without TPU hardware."""
import os

os.environ.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=8")
if "--xla_force_host_platform_device_count" not in os.environ["XLA_FLAGS"]:
    os.environ["XLA_FLAGS"] += " --xla_force_host_platform_device_count=8"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as pt
    pt.seed(0)
    yield
