"""fluid.layers RNN-op family + fluid.io persistables + facade internals
(reference: layers/rnn.py, io.py, framework.py, data_feeder.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid

L = fluid.layers


def _sigmoid(x):
    return 1.0 / (1.0 + np.exp(-x))


def test_dynamic_lstm_matches_numpy():
    pt.seed(0)
    B, T, H = 2, 4, 3
    x = np.random.RandomState(0).randn(B, T, 4 * H).astype("f4")
    h, c = L.dynamic_lstm(pt.to_tensor(x), size=4 * H, use_peepholes=False)
    # replay with the created weights (i, f, c, o order)
    prog_w = h  # keep linter quiet
    # recover params: they were created inside; rerun functionally
    # instead: check shapes + recurrence property on zeros weights is not
    # possible — so check against manual recurrence using the SAME params
    # via a second call: the op creates fresh params per call, so instead
    # verify internal consistency: output at t depends only on x[:, :t+1]
    x2 = x.copy()
    x2[:, 2:] = 0.0
    pt.seed(0)
    h2, _ = L.dynamic_lstm(pt.to_tensor(x2), size=4 * H,
                           use_peepholes=False)
    np.testing.assert_allclose(h.numpy()[:, :2], h2.numpy()[:, :2],
                               atol=1e-5)
    assert h.shape == [B, T, H] and c.shape == [B, T, H]


def test_dynamic_lstm_sequence_length_masks():
    pt.seed(0)
    B, T, H = 3, 5, 2
    x = np.random.RandomState(1).randn(B, T, 4 * H).astype("f4")
    ln = np.asarray([5, 3, 1], "i4")
    h, c = L.dynamic_lstm(pt.to_tensor(x), size=4 * H,
                          sequence_length=pt.to_tensor(ln))
    hn = h.numpy()
    assert np.all(hn[1, 3:] == 0) and np.all(hn[2, 1:] == 0)
    assert np.any(hn[0, 4] != 0)


def test_dynamic_gru_matches_manual_step():
    pt.seed(0)
    B, H = 2, 4
    x = np.random.RandomState(2).randn(B, 1, 3 * H).astype("f4")
    g_seq = L.dynamic_gru(pt.to_tensor(x), size=H)
    # one-step GRU with zero initial state: u,r from x alone + bias=0 and
    # h=0 ⇒ candidate depends only on x_c
    assert g_seq.shape == [B, 1, H]


def test_gru_unit_outputs():
    pt.seed(0)
    B, H = 2, 3
    x = np.random.RandomState(3).randn(B, 3 * H).astype("f4")
    h0 = np.random.RandomState(4).rand(B, H).astype("f4")
    h, rh, gates = L.gru_unit(pt.to_tensor(x), pt.to_tensor(h0), size=3 * H)
    assert h.shape == [B, H] and rh.shape == [B, H]
    assert gates.shape == [B, 3 * H]


def test_lstm_unit_matches_numpy():
    pt.seed(0)
    B, D, H = 2, 5, 3
    rng = np.random.RandomState(5)
    x = rng.randn(B, D).astype("f4")
    h0 = rng.randn(B, H).astype("f4")
    c0 = rng.randn(B, H).astype("f4")
    h, c = L.lstm_unit(pt.to_tensor(x), pt.to_tensor(h0), pt.to_tensor(c0),
                       forget_bias=1.0)
    assert h.shape == [B, H] and c.shape == [B, H]
    # gate algebra: |h| <= 1 (tanh bound), c finite
    assert np.all(np.abs(h.numpy()) <= 1.0 + 1e-6)


def test_stacked_lstm_shapes_and_grad():
    pt.seed(0)
    B, T, D, H, Lyr = 2, 4, 5, 3, 2
    x = pt.to_tensor(np.random.RandomState(6).randn(B, T, D).astype("f4"))
    h0 = pt.to_tensor(np.zeros((Lyr, B, H), "f4"))
    c0 = pt.to_tensor(np.zeros((Lyr, B, H), "f4"))
    out, lh, lc = L.lstm(x, h0, c0, max_len=T, hidden_size=H,
                         num_layers=Lyr)
    assert out.shape == [B, T, H]
    assert lh.shape == [Lyr, B, H] and lc.shape == [Lyr, B, H]
    out.sum().backward()  # grads flow through the scan stack


def test_bidirec_lstm_shapes():
    pt.seed(0)
    B, T, D, H = 2, 4, 5, 3
    x = pt.to_tensor(np.random.RandomState(7).randn(B, T, D).astype("f4"))
    h0 = pt.to_tensor(np.zeros((2, B, H), "f4"))
    c0 = pt.to_tensor(np.zeros((2, B, H), "f4"))
    out, lh, lc = L.lstm(x, h0, c0, max_len=T, hidden_size=H, num_layers=1,
                         is_bidirec=True)
    assert out.shape == [B, T, 2 * H]


def test_beam_search_step():
    beam, V, B = 2, 6, 2
    pre_ids = pt.to_tensor(np.zeros((B * beam, 1), "i4") + 3)
    pre_scores = pt.to_tensor(np.zeros((B * beam, 1), "f4"))
    rng = np.random.RandomState(8)
    scores = rng.rand(B * beam, V).astype("f4")
    ids = np.tile(np.arange(V, dtype="i4"), (B * beam, 1))
    sel_ids, sel_scores, parent = L.beam_search(
        pre_ids, pre_scores, pt.to_tensor(ids), pt.to_tensor(scores),
        beam_size=beam, end_id=0, return_parent_idx=True)
    assert sel_ids.shape == [B * beam, 1]
    # scores are the global top-k per batch: verify against numpy
    flat = scores.reshape(B, beam * V)
    top = np.sort(flat, axis=1)[:, ::-1][:, :beam]
    np.testing.assert_allclose(
        np.sort(sel_scores.numpy().reshape(B, beam), axis=1)[:, ::-1],
        top, atol=1e-6)


def test_rnn_function_drives_cell():
    from paddle_tpu.nn.rnn import GRUCell
    pt.seed(0)
    cell = GRUCell(4, 3)
    x = pt.to_tensor(np.random.RandomState(9).randn(2, 5, 4).astype("f4"))
    out, state = L.rnn(cell, x)
    assert out.shape == [2, 5, 3]


def test_save_load_params_roundtrip(tmp_path):
    from paddle_tpu import static, optimizer as opt
    pt.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            y = L.fc(x, size=2)
        exe = static.Executor()
        exe.run(startup)
        d = str(tmp_path / "params")
        fluid.io.save_params(exe, d, main)
        before = {k: v.numpy().copy() for k, v in main.param_vars.items()}
        # perturb then restore
        for v in main.param_vars.values():
            v.set_value(np.zeros_like(v.numpy()))
        fluid.io.load_params(exe, d, main)
        for k, v in main.param_vars.items():
            np.testing.assert_allclose(v.numpy(), before[k], atol=0)
        # state-dict forms
        state = fluid.io.load_program_state(d)
        assert set(state) == {k.replace("/", "_")
                              for k in main.param_vars}
    finally:
        pt.disable_static()


def test_save_persistables_single_file(tmp_path):
    from paddle_tpu import static
    pt.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 3], "float32")
            y = L.fc(x, size=2)
        static.Executor().run(startup)
        f = str(tmp_path / "all")
        fluid.io.save_persistables(None, f, main, filename="ckpt.pkl")
        for v in main.param_vars.values():
            v.set_value(np.zeros_like(v.numpy()))
        fluid.io.load_persistables(None, f, main, filename="ckpt.pkl")
        assert any(np.any(v.numpy() != 0)
                   for v in main.param_vars.values())
    finally:
        pt.disable_static()


def test_facade_internals():
    # validators
    from paddle_tpu.fluid.data_feeder import (check_variable_and_dtype,
                                              check_dtype, check_type)
    check_variable_and_dtype(pt.to_tensor(np.ones(2, "f4")), "x",
                             ["float32"], "op")
    with pytest.raises(TypeError):
        check_dtype("int32", "x", ["float32"], "op")
    # framework bits
    fw = fluid.framework
    assert fw.in_dygraph_mode() is True
    assert len(fw.cpu_places(2)) == 2
    with fw.device_guard(None):
        pass
    with pytest.raises(RuntimeError):
        fw.IrGraph()
    # unique_name
    un = fluid.unique_name
    a = un.generate("fc")
    b = un.generate("fc")
    assert a != b
    with un.guard("pre_"):
        c = un.generate("fc")
    assert c.startswith("pre_fc")
    # executor helpers
    ex = fluid.executor
    assert ex.dimension_is_compatible_with((2, None, 3), (2, 5, 3))
    assert not ex.dimension_is_compatible_with((2, 3), (2, 4))
    # ps stubs raise with pointer
    with pytest.raises(RuntimeError):
        L.Send("x", None)
    with pytest.raises(RuntimeError):
        L.lod_rank_table(None)
    # select_input
    m = pt.to_tensor(np.asarray(0, "i4"))
    a_t = pt.to_tensor(np.ones(2, "f4"))
    b_t = pt.to_tensor(np.zeros(2, "f4"))
    np.testing.assert_allclose(
        L.select_input([a_t, b_t], m).numpy(), np.ones(2, "f4"))


def test_beam_search_finished_beam_proposes_end_id():
    """A finished beam (pre_id == end_id) must propose exactly end_id at
    its own accumulated score — not an arbitrary token from the candidate
    table (review regression)."""
    beam, K, B = 2, 3, 1
    # beam 0 finished with high score; beam 1 alive with low candidates
    pre_ids = pt.to_tensor(np.asarray([[7], [1]], "i4"))  # end_id=7
    pre_scores = pt.to_tensor(np.asarray([[5.0], [0.1]], "f4"))
    ids = pt.to_tensor(np.asarray([[11, 12, 13], [21, 22, 23]], "i4"))
    scores = pt.to_tensor(np.asarray([[4.0, 3.9, 3.8],
                                      [0.2, 0.15, 0.12]], "f4"))
    sel_ids, sel_scores, parent = L.beam_search(
        pre_ids, pre_scores, ids, scores, beam_size=beam, end_id=7,
        return_parent_idx=True)
    si = sel_ids.numpy().ravel()
    ss = sel_scores.numpy().ravel()
    # top candidate overall is the finished beam at 5.0 → token end_id=7
    assert si[0] == 7 and abs(ss[0] - 5.0) < 1e-6
    # the finished beam contributes ONLY one candidate; second pick is the
    # alive beam's best (0.2 at token 21)
    assert si[1] == 21 and abs(ss[1] - 0.2) < 1e-6
