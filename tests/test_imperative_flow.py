"""IfElse / Switch / DynamicRNN / tensor-array tests (VERDICT r2 #7;
reference: python/paddle/fluid/tests/unittests/test_dyn_rnn.py,
test_switch.py, test_ifelse.py, test_array_read_write_op.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.imperative_flow import (IfElse, Switch, DynamicRNN,
                                            TensorArray, create_array,
                                            array_write, array_read,
                                            array_length)


class TestTensorArray:
    def test_write_read_length(self):
        arr = create_array()
        for i in range(5):
            array_write(pt.to_tensor(np.full((2,), i, "f4")), i, arr)
        assert int(array_length(arr).numpy()) == 5
        np.testing.assert_allclose(array_read(arr, 3).numpy(), [3.0, 3.0])

    def test_tensor_index(self):
        arr = create_array()
        array_write(pt.to_tensor(np.ones((2,), "f4")),
                    pt.to_tensor(np.array(0, "i4")), arr)
        np.testing.assert_allclose(array_read(
            arr, pt.to_tensor(np.array(0, "i4"))).numpy(), [1, 1])

    def test_stack(self):
        arr = create_array()
        for i in range(3):
            array_write(pt.to_tensor(np.full((4,), i, "f4")), i, arr)
        s = arr.stack()
        assert s.shape == [3, 4]


class TestIfElse:
    def test_rowwise_merge(self):
        x = np.array([[1.0], [-2.0], [3.0], [-4.0]], "f4")
        cond = pt.to_tensor(x > 0)
        tx = pt.to_tensor(x)
        ie = IfElse(cond)
        with ie.true_block():
            d = ie.input(tx)
            ie.output(d * 10.0)
        with ie.false_block():
            d = ie.input(tx)
            ie.output(d - 100.0)
        out, = ie()
        np.testing.assert_allclose(out.numpy(),
                                   [[10.0], [-102.0], [30.0], [-104.0]])

    def test_gradients_flow(self):
        x = pt.to_tensor(np.array([[1.0], [-1.0]], "f4"))
        x.stop_gradient = False
        ie = IfElse(pt.to_tensor(np.array([[True], [False]])))
        with ie.true_block():
            ie.output(ie.input(x) * 3.0)
        with ie.false_block():
            ie.output(ie.input(x) * 5.0)
        out, = ie()
        out.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad), [[3.0], [5.0]])


class TestSwitch:
    def test_first_match_wins(self):
        lr = pt.to_tensor(np.array([0.0], "f4"))
        step = pt.to_tensor(np.array([5.0], "f4"))
        with Switch() as sw:
            with sw.case(step < 3.0):
                pt.ops.assign(pt.to_tensor(np.array([0.1], "f4")), lr)
            with sw.case(step < 10.0):
                pt.ops.assign(pt.to_tensor(np.array([0.01], "f4")), lr)
            with sw.default():
                pt.ops.assign(pt.to_tensor(np.array([0.001], "f4")), lr)
        np.testing.assert_allclose(lr.numpy(), [0.01])

    def test_default_taken(self):
        lr = pt.to_tensor(np.array([0.0], "f4"))
        step = pt.to_tensor(np.array([50.0], "f4"))
        with Switch() as sw:
            with sw.case(step < 3.0):
                pt.ops.assign(pt.to_tensor(np.array([0.1], "f4")), lr)
            with sw.default():
                pt.ops.assign(pt.to_tensor(np.array([0.001], "f4")), lr)
        np.testing.assert_allclose(lr.numpy(), [0.001])

    def test_warmup_lr_pattern(self):
        """The reference's linear-warmup Switch pattern end to end."""
        def lr_at(step_val):
            lr = pt.to_tensor(np.array([0.0], "f4"))
            step = pt.to_tensor(np.array([step_val], "f4"))
            warmup = 10.0
            with Switch() as sw:
                with sw.case(step < warmup):
                    pt.ops.assign(step * pt.to_tensor(
                        np.array([0.01], "f4")), lr)
                with sw.default():
                    pt.ops.assign(pt.to_tensor(np.array([0.1], "f4")), lr)
            return float(lr.numpy()[0])

        np.testing.assert_allclose(lr_at(5.0), 0.05, rtol=1e-6)
        np.testing.assert_allclose(lr_at(20.0), 0.1, rtol=1e-6)


class TestDynamicRNN:
    def test_cumsum_rnn_with_lengths(self):
        """Memory accumulates step inputs; shorter rows freeze at their
        length (LoD parity)."""
        b, t, d = 3, 5, 2
        rng = np.random.RandomState(0)
        x = rng.rand(b, t, d).astype("f4")
        lengths = np.array([5, 3, 1], "i4")

        drnn = DynamicRNN()
        with drnn.block():
            w = drnn.step_input(pt.to_tensor(x),
                                lengths=pt.to_tensor(lengths))
            prev = drnn.memory(shape=(d,), value=0.0)
            new = prev + w
            drnn.update_memory(prev, new)
            drnn.output(new)
        outs = drnn()
        last = drnn.last_state()
        assert outs.shape == [b, t, d]
        # full-length row: plain cumsum
        np.testing.assert_allclose(outs.numpy()[0], np.cumsum(x[0], 0),
                                   rtol=1e-5)
        # short rows: last_state is the sum of the first `len` steps
        np.testing.assert_allclose(last.numpy()[1], x[1, :3].sum(0),
                                   rtol=1e-5)
        np.testing.assert_allclose(last.numpy()[2], x[2, :1].sum(0),
                                   rtol=1e-5)

    def test_outputs_frozen_past_length(self):
        """Step outputs past a row's length re-emit the last valid output
        (review r3 finding #2) — sum-pooling drnn() excludes padding."""
        b, t, d = 2, 4, 2
        x = np.ones((b, t, d), "f4")
        lengths = np.array([4, 2], "i4")
        drnn = DynamicRNN()
        with drnn.block():
            w = drnn.step_input(pt.to_tensor(x),
                                lengths=pt.to_tensor(lengths))
            prev = drnn.memory(shape=(d,), value=0.0)
            new = prev + w
            drnn.update_memory(prev, new)
            drnn.output(new)
        outs = drnn().numpy()
        np.testing.assert_allclose(outs[0, :, 0], [1, 2, 3, 4])
        np.testing.assert_allclose(outs[1, :, 0], [1, 2, 2, 2])

    def test_fc_rnn_matches_manual(self):
        """A linear step body recorded via fluid.layers.fc inside the
        block matches a manual python loop."""
        from paddle_tpu.fluid import layers as FL
        b, t, d, h = 2, 4, 3, 3
        rng = np.random.RandomState(1)
        x = rng.rand(b, t, d).astype("f4")

        pt.seed(0)
        drnn = DynamicRNN()
        with drnn.block():
            w = drnn.step_input(pt.to_tensor(x))
            prev = drnn.memory(shape=(h,), value=0.0)
            new = pt.ops.tanh(w + prev)
            drnn.update_memory(prev, new)
            drnn.output(new)
        outs = drnn().numpy()

        ref = np.zeros((b, h), "f4")
        for i in range(t):
            ref = np.tanh(x[:, i] + ref)
            np.testing.assert_allclose(outs[:, i], ref, rtol=1e-5,
                                       atol=1e-6)

    def test_static_input_broadcast(self):
        b, t, d = 2, 3, 2
        x = np.ones((b, t, d), "f4")
        bias = np.array([[10.0, 20.0], [30.0, 40.0]], "f4")
        drnn = DynamicRNN()
        with drnn.block():
            w = drnn.step_input(pt.to_tensor(x))
            sb = drnn.static_input(pt.to_tensor(bias))
            prev = drnn.memory(shape=(d,), value=0.0)
            new = prev + w + sb
            drnn.update_memory(prev, new)
            drnn.output(new)
        outs = drnn().numpy()
        # step k accumulates k+1 copies of (x + bias_row)
        np.testing.assert_allclose(outs[1, 2], [3 * 31.0, 3 * 41.0],
                                   rtol=1e-5)
        np.testing.assert_allclose(outs[0, 0], [11.0, 21.0])


def test_fluid_exports():
    from paddle_tpu.fluid import layers as FL
    for name in ("IfElse", "Switch", "DynamicRNN", "array_write",
                 "array_read", "array_length", "create_array"):
        assert hasattr(FL, name)
