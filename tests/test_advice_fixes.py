"""Regression tests for advisor findings (round 1 ADVICE.md)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer, static, amp
from paddle_tpu.nn import functional as F


def _tiny_model():
    pt.seed(7)
    return nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))


def test_optimizer_state_dict_roundtrip_fresh_adam():
    """high: restoring a checkpoint into a FRESH optimizer used to crash on
    scalar beta-pow slots (slot lazily created with the param's shape)."""
    m = _tiny_model()
    o = optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
    x = pt.to_tensor(np.random.randn(8, 4).astype("f4"))
    y = pt.to_tensor(np.random.randn(8, 2).astype("f4"))
    for _ in range(3):
        loss = F.mse_loss(m(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
    state = o.state_dict()

    o2 = optimizer.Adam(learning_rate=1e-3, parameters=m.parameters())
    o2.set_state_dict(state)  # must not raise
    for p in m.parameters():
        if p.stop_gradient:
            continue
        slots = o._accumulators[id(p)]
        slots2 = o2._accumulators[id(p)]
        for sname in ("moment1", "moment2", "beta1_pow", "beta2_pow"):
            np.testing.assert_allclose(np.asarray(slots2[sname].data),
                                       np.asarray(slots[sname].data))
            assert slots2[sname].data.shape == slots[sname].data.shape

    # and the restored optimizer continues training identically
    loss = F.mse_loss(m(x), y)
    loss.backward()
    o2.step()
    o2.clear_grad()


def test_static_dropout_varies_across_runs():
    """medium: static-mode dropout used to bake one mask at record time."""
    pt.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog):
            xv = static.data("x", [32, 64], "float32")
            out = F.dropout(xv, p=0.5, training=True)
        exe = static.Executor()
        x = np.ones((32, 64), "f4")
        a = exe.run(prog, feed={"x": x}, fetch_list=[out])[0]
        b = exe.run(prog, feed={"x": x}, fetch_list=[out])[0]
    finally:
        pt.disable_static()
    assert not np.array_equal(a, b), "dropout mask identical across runs"
    # upscale_in_train keeps the expectation about right
    assert 0.5 < a.mean() < 1.5


def test_bce_with_logits_weight_and_pos_weight():
    """medium: weight / pos_weight used to be silently ignored."""
    rs = np.random.RandomState(0)
    x = rs.randn(6, 3).astype("f4")
    y = (rs.rand(6, 3) > 0.5).astype("f4")
    w = rs.rand(6, 3).astype("f4") + 0.5
    pw = rs.rand(3).astype("f4") + 0.5

    def ref(x, y, w=None, pw=None):
        log_sig = -np.log1p(np.exp(-np.abs(x))) + np.minimum(x, 0)
        log_1m = log_sig - x
        pwv = pw if pw is not None else 1.0
        loss = -(pwv * y * log_sig + (1 - y) * log_1m)
        if w is not None:
            loss = loss * w
        return loss.mean()

    got = F.binary_cross_entropy_with_logits(
        pt.to_tensor(x), pt.to_tensor(y))
    np.testing.assert_allclose(float(got.numpy()), ref(x, y), rtol=1e-5)

    got = F.binary_cross_entropy_with_logits(
        pt.to_tensor(x), pt.to_tensor(y), weight=pt.to_tensor(w))
    np.testing.assert_allclose(float(got.numpy()), ref(x, y, w=w), rtol=1e-5)

    got = F.binary_cross_entropy_with_logits(
        pt.to_tensor(x), pt.to_tensor(y), pos_weight=pt.to_tensor(pw))
    np.testing.assert_allclose(float(got.numpy()), ref(x, y, pw=pw),
                               rtol=1e-5)

    got = F.binary_cross_entropy_with_logits(
        pt.to_tensor(x), pt.to_tensor(y), weight=pt.to_tensor(w),
        pos_weight=pt.to_tensor(pw))
    np.testing.assert_allclose(float(got.numpy()), ref(x, y, w=w, pw=pw),
                               rtol=1e-5)

    # matches torch's reference implementation
    torch = pytest.importorskip("torch")
    tref = torch.nn.functional.binary_cross_entropy_with_logits(
        torch.tensor(x), torch.tensor(y), weight=torch.tensor(w),
        pos_weight=torch.tensor(pw)).item()
    got = F.binary_cross_entropy_with_logits(
        pt.to_tensor(x), pt.to_tensor(y), weight=pt.to_tensor(w),
        pos_weight=pt.to_tensor(pw))
    np.testing.assert_allclose(float(got.numpy()), tref, rtol=1e-5)


def test_clip_before_regularization():
    """low: reference clips RAW grads first, then appends regularization."""
    from paddle_tpu.clip import ClipGradByGlobalNorm
    from paddle_tpu.regularizer import L2Decay

    p = pt.Parameter(np.ones(4, "f4") * 2.0)
    p._grad = pt.to_tensor(np.ones(4, "f4") * 10.0).data
    o = optimizer.SGD(learning_rate=1.0, parameters=[p],
                      grad_clip=ClipGradByGlobalNorm(1.0),
                      weight_decay=L2Decay(0.1))
    o.step()
    # clip first: g=10*4 -> norm=20, clipped to g=0.5 each; then +0.1*2.0
    expect = 2.0 - 1.0 * (0.5 + 0.2)
    np.testing.assert_allclose(np.asarray(p.data), expect, rtol=1e-5)


def test_grad_scaler_on_device_and_skips_inf_step():
    m = _tiny_model()
    o = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    scaler = amp.GradScaler(init_loss_scaling=4.0,
                            decr_every_n_nan_or_inf=1, incr_every_n_steps=2)
    x = pt.to_tensor(np.random.randn(8, 4).astype("f4"))
    y = pt.to_tensor(np.random.randn(8, 2).astype("f4"))

    before = [np.asarray(p.data).copy() for p in m.parameters()]
    loss = scaler.scale(F.mse_loss(m(x), y))
    loss.backward()
    scaler.step(o)
    o.clear_grad()
    after = [np.asarray(p.data) for p in m.parameters()]
    assert any(not np.allclose(b, a) for b, a in zip(before, after))
    s0 = scaler.state_dict()
    assert s0["scale"] == 4.0 and s0["good"] == 1

    # poison one grad -> step must be skipped, scale halved
    before = [np.asarray(p.data).copy() for p in m.parameters()]
    loss = scaler.scale(F.mse_loss(m(x), y))
    loss.backward()
    params = list(m.parameters())
    params[0]._grad = params[0]._grad * np.float32("inf")
    scaler.step(o)
    o.clear_grad()
    after = [np.asarray(p.data) for p in m.parameters()]
    for b, a in zip(before, after):
        np.testing.assert_array_equal(b, a)
    s1 = scaler.state_dict()
    assert s1["scale"] == 2.0 and s1["good"] == 0


def test_grad_scaler_first_step_inf_keeps_adam_slots_clean():
    """Rollback of the VERY FIRST step must not leave lazily-created Adam
    slots holding the inf update (slots are ensured before snapshot)."""
    m = _tiny_model()
    o = optimizer.Adam(learning_rate=1e-2, parameters=m.parameters())
    scaler = amp.GradScaler(init_loss_scaling=2.0 ** 15)
    x = pt.to_tensor(np.random.randn(8, 4).astype("f4"))
    y = pt.to_tensor(np.random.randn(8, 2).astype("f4"))
    loss = scaler.scale(F.mse_loss(m(x), y))
    loss.backward()
    params = list(m.parameters())
    params[0]._grad = params[0]._grad * np.float32("inf")
    scaler.step(o)
    o.clear_grad()
    for p in params:
        if p.stop_gradient:
            continue
        slots = o._accumulators[id(p)]
        assert np.isfinite(np.asarray(slots["moment1"].data)).all()
        assert float(slots["beta1_pow"].data) == 1.0
    # next good step trains normally
    loss = scaler.scale(F.mse_loss(m(x), y))
    loss.backward()
    scaler.step(o)
    for p in params:
        assert np.isfinite(np.asarray(p.data)).all()


def test_grad_scaler_composes_with_to_static():
    from paddle_tpu import jit
    m = _tiny_model()
    o = optimizer.SGD(learning_rate=0.05, parameters=m.parameters())
    scaler = amp.GradScaler(init_loss_scaling=8.0, incr_every_n_steps=3)
    x = pt.to_tensor(np.random.randn(16, 4).astype("f4"))
    y = pt.to_tensor(np.random.randn(16, 2).astype("f4"))

    def step(x, y):
        loss = F.mse_loss(m(x), y)
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.unscale_(o)
        scaler.step(o)
        o.clear_grad()
        return loss

    cstep = jit.to_static(step, models=[m], optimizers=[o],
                          scalers=[scaler])
    vals = [float(cstep(x, y).numpy()) for _ in range(6)]
    assert vals[-1] < vals[0]
    # dynamic scale growth happened inside the compiled step
    assert scaler.state_dict()["scale"] == 32.0


# ---------------------------------------------------------------------------
# round-3 advisor findings


def test_preprocess_img_per_channel_mean_and_flatten():
    from paddle_tpu.utils.image_util import preprocess_img
    img = (np.random.rand(40, 40, 3) * 255).astype("u1")
    out = preprocess_img(img, [104.0, 117.0, 124.0], 32, is_train=False)
    assert out.shape == (3 * 32 * 32,)          # flattened CHW
    # each channel had its own mean subtracted (broadcast, not reshape)
    chw = out.reshape(3, 32, 32)
    for c, m in enumerate([104.0, 117.0, 124.0]):
        np.testing.assert_allclose(
            chw[c].mean(), img[4:36, 4:36, :].transpose(2, 0, 1)[c].mean()
            - m, atol=1.5)
    # full mean image still accepted
    full = preprocess_img(img, np.zeros((3, 32, 32), "f4"), 32,
                          is_train=False)
    assert full.shape == (3 * 32 * 32,)


def test_hsigmoid_param_shape_and_custom_raises():
    hs = nn.HSigmoid(8, 10)
    assert tuple(hs.weight.shape) == (9, 8)     # num_classes-1 rows
    assert tuple(hs.bias.shape) == (9,)
    with pytest.raises(NotImplementedError):
        nn.HSigmoid(8, 10, is_custom=True)
    with pytest.raises(NotImplementedError):
        nn.HSigmoid(8, 10, is_sparse=True)


def test_recompute_function_branch_accepts_none_args():
    from paddle_tpu import jit
    pt.seed(3)
    lin = nn.Linear(6, 6)

    def block(x, mask):
        h = lin(x)
        if mask is not None:
            h = h + mask
        return F.relu(h)

    x = pt.to_tensor(np.random.randn(4, 6).astype("f4"))
    x.stop_gradient = False
    out = jit.recompute(block, x, None)          # None positional arg
    out.sum().backward()
    assert x.grad is not None
    ref = F.relu(lin(x))
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5)


def test_hsigmoid_no_bias():
    hs = nn.HSigmoid(8, 10, bias_attr=False)
    assert hs.bias is None
    x = pt.to_tensor(np.random.randn(4, 8).astype("f4"))
    lbl = pt.to_tensor(np.random.randint(0, 10, (4, 1)).astype("i4"))
    out = hs(x, lbl)
    assert tuple(out.shape) == (4, 1)
    assert np.isfinite(out.numpy()).all()


# ---- round-4 ADVICE.md findings ----

def test_compat_dict_conversion():
    """low: to_text/to_bytes convert dict keys AND values like the
    reference compat.py; inplace honors the dict identity."""
    from paddle_tpu import compat
    d = {b"k": b"v", "s": [b"a", "b"], "n": 3}
    out = compat.to_text(d)
    assert out == {"k": "v", "s": ["a", "b"], "n": 3}
    assert d[b"k"] == b"v"  # not mutated

    back = compat.to_bytes({"k": "v", "nest": {"a": "b"}})
    assert back == {b"k": b"v", b"nest": {b"a": b"b"}}

    d2 = {b"x": b"y"}
    same = compat.to_text(d2, inplace=True)
    assert same is d2 and d2 == {"x": "y"}

    with pytest.raises(TypeError):
        compat.to_bytes({"k": 1.5})


def test_pallas_enabled_unknown_kernel_raises_valueerror():
    """low: enabled() on an unknown kernel name raises the same
    ValueError configure() does, not a bare KeyError."""
    from paddle_tpu.ops import pallas as P
    with pytest.raises(ValueError, match="unknown pallas kernel"):
        P.enabled("not_a_kernel")


def test_summarize_trace_filters_host_lanes_by_pid(tmp_path):
    """low: summarize_trace aggregates only device-lane pids when the
    trace names them, so host 'X' events can't inflate op totals."""
    import gzip
    import json
    from paddle_tpu.utils.profiler import summarize_trace

    trace = {"traceEvents": [
        {"ph": "M", "name": "process_name", "pid": 1,
         "args": {"name": "/host:CPU python"}},
        {"ph": "M", "name": "process_name", "pid": 2,
         "args": {"name": "/device:TPU:0 (pid 2)"}},
        {"ph": "X", "pid": 1, "name": "fusion", "dur": 9000},
        {"ph": "X", "pid": 2, "name": "fusion.1", "dur": 500},
        {"ph": "X", "pid": 2, "name": "convolution", "dur": 250},
    ]}
    p = tmp_path / "t" / "x.trace.json.gz"
    p.parent.mkdir()
    with gzip.open(p, "wt") as fh:
        json.dump(trace, fh)
    fams = dict(summarize_trace(str(tmp_path)))
    assert fams == {"fusion": 0.5, "convolution": 0.25}
