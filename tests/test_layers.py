"""Layer construction/forward shapes + Layer-base machinery (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn


def rand(*shape):
    return pt.to_tensor(np.random.randn(*shape).astype("f4"))


def test_linear():
    fc = nn.Linear(8, 4)
    out = fc(rand(2, 8))
    assert out.shape == [2, 4]
    fc2 = nn.Linear(8, 4, bias_attr=False)
    assert fc2.bias is None


def test_conv2d_shapes():
    conv = nn.Conv2D(3, 16, 3, stride=2, padding=1)
    out = conv(rand(2, 3, 32, 32))
    assert out.shape == [2, 16, 16, 16]
    convg = nn.Conv2D(16, 16, 3, groups=4, padding=1)
    assert convg(out).shape == [2, 16, 16, 16]


def test_conv2d_matches_numpy():
    """3x3 conv vs naive numpy (NCHW)."""
    x = np.random.randn(1, 2, 5, 5).astype("f4")
    w = np.random.randn(3, 2, 3, 3).astype("f4")
    from paddle_tpu.nn import functional as F
    out = F.conv2d(pt.to_tensor(x), pt.to_tensor(w)).numpy()
    ref = np.zeros((1, 3, 3, 3), "f4")
    for o in range(3):
        for i in range(3):
            for j in range(3):
                ref[0, o, i, j] = (x[0, :, i:i + 3, j:j + 3] * w[o]).sum()
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_conv2d_transpose():
    deconv = nn.Conv2DTranspose(8, 4, 2, stride=2)
    out = deconv(rand(2, 8, 7, 7))
    assert out.shape == [2, 4, 14, 14]


def test_pools():
    x = rand(2, 4, 8, 8)
    assert nn.MaxPool2D(2, 2)(x).shape == [2, 4, 4, 4]
    assert nn.AvgPool2D(2, 2)(x).shape == [2, 4, 4, 4]
    assert nn.AdaptiveAvgPool2D(1)(x).shape == [2, 4, 1, 1]
    g = nn.Pool2D(global_pooling=True, pool_type="avg")(x)
    assert g.shape == [2, 4, 1, 1]


def test_avg_pool_matches_numpy():
    x = np.random.randn(1, 1, 4, 4).astype("f4")
    out = nn.AvgPool2D(2, 2)(pt.to_tensor(x)).numpy()
    ref = x.reshape(1, 1, 2, 2, 2, 2).mean(axis=(3, 5))
    np.testing.assert_allclose(out, ref, atol=1e-5)


def test_batch_norm_train_eval():
    bn = nn.BatchNorm2D(4)
    x = rand(8, 4, 5, 5)
    bn.train()
    out = bn(x)
    assert out.shape == [8, 4, 5, 5]
    # batch-normalized output:近 zero mean unit var per channel
    o = out.numpy()
    assert abs(o.mean()) < 0.1
    # running stats moved off init
    assert not np.allclose(bn._mean.numpy(), 0.0)
    bn.eval()
    out2 = bn(x)
    assert out2.shape == [8, 4, 5, 5]


def test_layer_norm():
    ln = nn.LayerNorm(16)
    out = ln(rand(4, 16))
    o = out.numpy()
    np.testing.assert_allclose(o.mean(-1), 0.0, atol=1e-4)
    np.testing.assert_allclose(o.std(-1), 1.0, atol=1e-2)


def test_group_instance_norm():
    x = rand(2, 8, 4, 4)
    assert nn.GroupNorm(2, 8)(x).shape == [2, 8, 4, 4]
    assert nn.InstanceNorm2D(8)(x).shape == [2, 8, 4, 4]


def test_embedding():
    emb = nn.Embedding(100, 16, padding_idx=0)
    ids = pt.to_tensor(np.array([[1, 2, 0], [4, 0, 6]]))
    out = emb(ids)
    assert out.shape == [2, 3, 16]
    np.testing.assert_allclose(out.numpy()[0, 2], np.zeros(16))


def test_dropout_modes():
    x = rand(1000)
    drop = nn.Dropout(0.5)
    drop.train()
    y = drop(x).numpy()
    frac_zero = (y == 0).mean()
    assert 0.3 < frac_zero < 0.7
    drop.eval()
    np.testing.assert_allclose(drop(x).numpy(), x.numpy())


def test_containers():
    seq = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    assert seq(rand(3, 4)).shape == [3, 2]
    assert len(seq) == 3
    ll = nn.LayerList([nn.Linear(4, 4) for _ in range(3)])
    ll.append(nn.Linear(4, 4))
    assert len(ll) == 4
    x = rand(2, 4)
    for l in ll:
        x = l(x)
    assert x.shape == [2, 4]
    named = nn.Sequential(("a", nn.Linear(2, 2)), ("b", nn.ReLU()))
    assert named(rand(1, 2)).shape == [1, 2]


def test_state_dict_roundtrip():
    m1 = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
    m2 = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
    sd = m1.state_dict()
    assert any("weight" in k for k in sd)
    m2.set_state_dict(sd)
    for (k1, v1), (k2, v2) in zip(sorted(m1.state_dict().items()),
                                  sorted(m2.state_dict().items())):
        np.testing.assert_allclose(v1.numpy(), v2.numpy())


def test_named_parameters_and_apply():
    m = nn.Sequential(nn.Linear(4, 8), nn.Linear(8, 2))
    names = [n for n, _ in m.named_parameters()]
    assert "0.weight" in names and "1.bias" in names
    m.eval()
    assert all(not l.training for l in m.sublayers())


def test_sublayer_attr_plumbing():
    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(2, 2)
            self.w = self.create_parameter((2,))

        def forward(self, x):
            return self.fc(x) + self.w

    m = M()
    assert len(m.parameters()) == 3
    assert m(rand(1, 2)).shape == [1, 2]
    # replacing a sublayer updates the registry
    m.fc = nn.Linear(2, 2, bias_attr=False)
    assert len(m.parameters()) == 2


def test_spectral_norm_and_misc_layers():
    sn = nn.SpectralNorm((4, 3))
    w = rand(4, 3)
    out = sn(w)
    assert out.shape == [4, 3]
    # largest singular value ≈ 1 after normalization (power iters converge)
    for _ in range(20):
        out = sn(w)
    s = np.linalg.svd(out.numpy(), compute_uv=False)[0]
    assert abs(s - 1.0) < 0.1

    btp = nn.BilinearTensorProduct(3, 4, 5)
    assert btp(rand(2, 3), rand(2, 4)).shape == [2, 5]

    gru = nn.GRUUnit(3 * 6)
    h, _, _ = gru(rand(2, 18), rand(2, 6))
    assert h.shape == [2, 6]

    pr = nn.PRelu(mode="channel", channel=4)
    assert pr(rand(2, 4, 3, 3)).shape == [2, 4, 3, 3]


def test_activation_layers():
    x = rand(4, 4)
    for cls in [nn.ReLU, nn.GELU, nn.Sigmoid, nn.Tanh, nn.LeakyReLU,
                nn.Softmax, nn.Swish, nn.Hardswish, nn.ELU, nn.Mish]:
        assert cls()(x).shape == [4, 4]


def test_upsample_and_pad():
    x = rand(1, 2, 4, 4)
    up = nn.Upsample(scale_factor=2, mode="nearest")
    assert up(x).shape == [1, 2, 8, 8]
    pad = nn.Pad2D([1, 1, 2, 2])
    assert pad(x).shape == [1, 2, 8, 6]


def test_hsigmoid_trains_class_apart():
    """HSigmoid loss drops when training to separate two classes, and the
    complete-binary-tree codes give a proper probability: loss for the
    true class < loss for a wrong class after training."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as opt

    pt.seed(0)
    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype("f4")
    lab = (x[:, 0] > 0).astype("i8").reshape(-1, 1) * 3  # classes {0, 3}
    hs = nn.HSigmoid(8, 6)
    o = opt.Adam(learning_rate=0.1, parameters=hs.parameters())
    losses = []
    for _ in range(25):
        loss = hs(pt.to_tensor(x), pt.to_tensor(lab)).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5
    true_l = hs(pt.to_tensor(x), pt.to_tensor(lab)).numpy().mean()
    wrong = hs(pt.to_tensor(x), pt.to_tensor(3 - lab)).numpy().mean()
    assert true_l < wrong


def test_batch_norm_large_mean_numerics():
    """One-pass BN moments must not cancel catastrophically on
    large-mean inputs (raw E[x^2]-E[x]^2 in f32 loses the entire
    variance at mean ~1e3, std ~1; the sample-shifted form keeps it)."""
    rng = np.random.RandomState(0)
    x = (rng.randn(64, 8).astype("f4") + 1000.0)
    bn = nn.BatchNorm1D(8)
    bn.train()
    out = bn(pt.to_tensor(x)).numpy()
    # normalized output of a ~N(1000, 1) batch must be ~N(0, 1)
    assert abs(out.mean()) < 0.1
    assert 0.8 < out.std() < 1.2, f"BN variance cancelled: std={out.std()}"


def test_batch_norm_no_bias():
    """bias_attr=False BN (weight-only affine) must work in training on
    both the XLA and Pallas paths (zeros substituted for the bias)."""
    from paddle_tpu.ops import pallas as P

    rng = np.random.RandomState(2)
    x = rng.randn(16, 6).astype("f4")
    for use in (False, True):
        P.configure(batch_norm=use)
        try:
            pt.seed(0)
            bn = nn.BatchNorm1D(6, bias_attr=False, data_format="NLC")
            bn.train()
            out = bn(pt.to_tensor(x))
            loss = (out ** 2).mean()
            loss.backward()
            assert bn.bias is None
            assert bn.weight.grad is not None
            np.testing.assert_allclose(out.numpy().mean(axis=0), 0.0,
                                       atol=1e-4)
        finally:
            P.configure(batch_norm=None)


def test_batch_norm_no_weight():
    """weight_attr=False BN: the real bias parameter must still be
    applied and trained (ones substituted for the scale)."""
    rng = np.random.RandomState(3)
    x = rng.randn(16, 6).astype("f4")
    pt.seed(0)
    bn = nn.BatchNorm1D(6, weight_attr=False, data_format="NLC")
    bn.train()
    out = bn(pt.to_tensor(x))
    loss = ((out - 1.0) ** 2).mean()
    loss.backward()
    assert bn.weight is None
    assert bn.bias.grad is not None
    # bias starts at 0 so normalized output has ~0 mean, and the bias
    # actually reaches the output: shift it and the output follows
    bn2 = nn.BatchNorm1D(6, weight_attr=False, data_format="NLC")
    bn2.train()
    bn2.bias.set_value(np.full((6,), 5.0, "f4"))
    out2 = bn2(pt.to_tensor(x))
    np.testing.assert_allclose(out2.numpy().mean(axis=0), 5.0, atol=1e-3)


def test_untested_layer_tail():
    """Smoke+numeric coverage for the layers nothing else exercises:
    BatchNorm3D, Flatten, SimpleRNNCell/SimpleRNN, ParameterList."""
    rng = np.random.RandomState(0)

    bn3 = nn.BatchNorm3D(4)
    bn3.train()
    x5 = pt.to_tensor(rng.randn(2, 4, 3, 3, 3).astype("f4"))
    out = bn3(x5)
    assert tuple(out.shape) == (2, 4, 3, 3, 3)
    np.testing.assert_allclose(
        out.numpy().mean(axis=(0, 2, 3, 4)), 0.0, atol=1e-4)

    fl = nn.Flatten()
    assert tuple(fl(pt.to_tensor(
        rng.randn(2, 3, 4).astype("f4"))).shape) == (2, 12)

    cell = nn.SimpleRNNCell(5, 7)
    h = pt.to_tensor(rng.randn(2, 7).astype("f4"))
    xt = pt.to_tensor(rng.randn(2, 5).astype("f4"))
    out, new_h = cell(xt, h)
    # h' = tanh(x Wi + h Wh + b) by hand
    ref = np.tanh(xt.numpy() @ cell.weight_ih.numpy() +
                  h.numpy() @ cell.weight_hh.numpy() +
                  cell.bias.numpy())
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-5)
    np.testing.assert_allclose(new_h.numpy(), ref, atol=1e-5)

    rnn = nn.SimpleRNN(5, 7)
    seq = pt.to_tensor(rng.randn(2, 6, 5).astype("f4"))
    ys, last = rnn(seq)
    assert tuple(ys.shape) == (2, 6, 7)

    pl = nn.ParameterList([pt.Parameter(np.ones((3,), "f4")),
                           pt.Parameter(np.zeros((2,), "f4"))])
    assert len(list(pl.parameters())) == 2
    assert tuple(pl[0].shape) == (3,)


def test_static_rnn_unroll():
    """StaticRNN (parity shim): registered step fns unroll over the
    python-level sequence; the recorded step drives a real cell."""
    rng = np.random.RandomState(1)
    cell = nn.SimpleRNNCell(3, 4)
    srnn = nn.StaticRNN()

    @srnn.step
    def _step(x, h):
        out, new_h = cell(x, h)
        return new_h

    xs = [pt.to_tensor(rng.randn(2, 3).astype("f4")) for _ in range(5)]
    h0 = pt.to_tensor(np.zeros((2, 4), "f4"))
    outs, last = srnn(xs, h0)
    assert len(outs) == 5
    # matches driving the cell by hand
    h = h0
    for x in xs:
        _, h = cell(x, h)
    np.testing.assert_allclose(last.numpy(), h.numpy(), atol=1e-6)
