"""Profile-guided auto-sharding planner (parallel/planner.py).

Covers the PR 11 satellite checklist: regex precedence (first match
wins), unmatched-leaf default, mesh-axis validation errors, lists-form
round-trip for every spec the planner can emit — plus the tentpole
gates that are cheap enough for tier-1: MEGATRON_RULES bit-identity
with the hand specs, plan_key stability, degradation accounting, the
flat-arena fallback warning, advisor determinism, and the arena
layout-contract raise.
"""
import re
import warnings

import numpy as np
import pytest

import jax
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import monitor, nn, optimizer as opt
from paddle_tpu.parallel import layout, planner
from paddle_tpu.parallel import megatron as M


def _mesh(shape, axes):
    devs = np.asarray(jax.devices()[: int(np.prod(shape))])
    return Mesh(devs.reshape(shape), axes)


# ---------------------------------------------------------------------------
# rule matching

def test_first_match_wins():
    mesh = _mesh((2, 2), ("dp", "tp"))
    p = planner.MeshPlan(
        ((r"fc", P(None, "tp")),       # earlier, broader
         (r"fc1\.weight$", P("tp", None))),  # never reached
        mesh=mesh)
    assert p.match("block.fc1.weight") == P(None, "tp")
    # order flipped: the specific rule now wins
    p2 = planner.MeshPlan(
        ((r"fc1\.weight$", P("tp", None)),
         (r"fc", P(None, "tp"))),
        mesh=mesh)
    assert p2.match("block.fc1.weight") == P("tp", None)
    assert p2.match("block.fc2.weight") == P(None, "tp")


def test_unmatched_leaf_gets_default():
    mesh = _mesh((2, 2), ("dp", "tp"))
    p = planner.MeshPlan(((r"^qkv", P(None, "tp")),), mesh=mesh)
    assert p.match("layernorm.weight") == P()           # replicated default
    assert p.spec_for("layernorm.weight", (8, 8)) == P()
    pd = planner.MeshPlan(((r"^qkv", P(None, "tp")),), mesh=mesh,
                          default=P("dp"))
    assert pd.match("other") == P("dp")
    # scalars are always replicated, rules notwithstanding
    assert p.spec_for("qkv_scale", ()) == P()


def test_axis_validation_raises():
    dp_only = _mesh((4,), ("dp",))
    with pytest.raises(ValueError, match="axis 'tp'"):
        planner.MeshPlan(((r"w", P(None, "tp")),), mesh=dp_only)
    with pytest.raises(ValueError, match="data axis"):
        planner.MeshPlan((), mesh=dp_only, data_axes=("dp", "sp"))
    with pytest.raises(ValueError):
        planner.MeshPlan((), mesh=dp_only, default=P("tp"))


def test_spec_round_trip_every_emittable_spec():
    """spec_to_lists/spec_from_lists is lossless on everything the
    canonical rule tables (and the default) can emit."""
    specs = ([s for _, s in planner.MEGATRON_RULES]
             + [s for _, s in planner.TRANSFORMER_RULES]
             + [P(), P("dp"), P(("dp", "tp"), None)])
    for spec in specs:
        nd = max(len(tuple(spec)), 1)
        lists = layout.spec_to_lists(spec, nd)
        back = layout.spec_from_lists(lists)
        assert layout.spec_to_lists(back, nd) == lists, spec


# ---------------------------------------------------------------------------
# tentpole: MEGATRON_RULES reproduce the hand layout

def test_megatron_rules_match_hand_specs():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    mesh, _ = M.make_mesh(8, sizes={"dp": 2, "tp": 2, "pp": 2})
    cfg = M.MegatronConfig(vocab_size=64, hidden=32, n_heads=2,
                           layers_per_stage=1, seq_len=16, microbatch=2,
                           n_micro=2)
    params, hand = M.init_params(cfg, mesh)
    plan = planner.MeshPlan(planner.MEGATRON_RULES, mesh=mesh)
    for name, value in params.items():
        nd = np.asarray(jax.device_get(value)).ndim
        want = layout.spec_to_lists(hand[name], nd)
        got = layout.spec_to_lists(plan.spec_for(name, np.shape(value)), nd)
        assert got == want, (name, got, want)
    assert plan.degraded == {}


def test_plan_key_stable_and_changes():
    mesh = _mesh((2, 2), ("dp", "tp"))
    a = planner.MeshPlan(planner.TRANSFORMER_RULES, mesh=mesh)
    b = planner.MeshPlan(planner.TRANSFORMER_RULES, mesh=mesh)
    assert a.plan_key() == b.plan_key()
    assert a.signature() == b.signature()
    c = planner.MeshPlan(planner.TRANSFORMER_RULES[:1], mesh=mesh)
    assert c.plan_key() != a.plan_key()
    d = planner.MeshPlan(planner.TRANSFORMER_RULES,
                         mesh=_mesh((2, 2), ("tp", "dp")))
    assert d.plan_key() != a.plan_key()  # axis order is part of the key


# ---------------------------------------------------------------------------
# degradation accounting (satellite: layout.adapt_spec)

def test_degradation_warns_once_and_counts():
    mesh = _mesh((2, 2), ("dp", "tp"))
    before = monitor.registry().value("layout.degraded", 0)
    name = "degrade_probe_%d" % np.random.randint(1 << 30)
    p = planner.MeshPlan(((re.escape(name), P(None, "tp")),), mesh=mesh)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        spec = p.spec_for(name, (4, 7))   # 7 % 2 != 0 -> replicated
        p.spec_for(name, (4, 7))          # second call: counted, no warn
    assert spec == P()
    assert p.degraded.get(name) == 28
    msgs = [str(x.message) for x in w if "degraded" in str(x.message)]
    assert len(msgs) == 1 and name in msgs[0] and "dim 1" in msgs[0]
    after = monitor.registry().value("layout.degraded", 0)
    assert after - before == 2


# ---------------------------------------------------------------------------
# flat-arena fallback (satellite: megatron)

def test_flat_fallback_warns_once_per_config_and_counts():
    cfg = M.MegatronConfig(vocab_size=64, hidden=32, n_heads=2,
                           flat_arena=True,
                           seq_len=16, microbatch=1, n_micro=1)
    M._flat_fallback_warned.discard(repr(cfg))
    before = monitor.registry().value("arena.flat_fallback", 0)
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        M._warn_flat_fallback(cfg)
        M._warn_flat_fallback(cfg)
    msgs = [x for x in w if "flat_arena" in str(x.message)]
    assert len(msgs) == 1           # once per config...
    after = monitor.registry().value("arena.flat_fallback", 0)
    assert after - before == 2      # ...but every occurrence is counted


# ---------------------------------------------------------------------------
# advisor

def test_advise_ranked_and_deterministic():
    cfg = M.MegatronConfig(vocab_size=64, hidden=32, n_heads=4,
                           layers_per_stage=1, seq_len=16, microbatch=2,
                           n_micro=1, use_moe=False)
    t1 = planner.advise(n_devices=8, cfg=cfg)
    t2 = planner.advise(n_devices=8, cfg=cfg)
    assert len(t1) >= 2
    assert [r["sizes"] for r in t1] == [r["sizes"] for r in t2]
    assert [r["rank"] for r in t1] == list(range(1, len(t1) + 1))
    preds = [r["pred_step_s"] for r in t1]
    assert preds == sorted(preds)
    for row in t1:
        assert row["pred_step_s"] > 0
        assert row["bound"] in ("compute", "memory", "comm")


def test_candidate_sizes_complete_factorizations():
    cands = planner.candidate_sizes(8, axes=("dp", "tp"))
    as_tuples = {(c["dp"], c["tp"]) for c in cands}
    assert as_tuples == {(8, 1), (4, 2), (2, 4), (1, 8)}
    for c in cands:
        assert c["dp"] * c["tp"] == 8


# ---------------------------------------------------------------------------
# arena layout contract

def test_arena_bucket_bounds_rejects_sharding_plan():
    from paddle_tpu.optimizer.arena import ParamArena
    pt.seed(0)
    m = nn.Linear(8, 8)
    arena = ParamArena(list(m.parameters()))
    mesh = _mesh((2, 2), ("dp", "tp"))
    sharding = planner.MeshPlan(((r"param", P(None, "tp")),), mesh=mesh)
    with pytest.raises(ValueError, match="mesh_plan shards arena member"):
        arena.bucket_bounds(plan=sharding)
    benign = planner.MeshPlan((), mesh=mesh)
    assert arena.bucket_bounds(plan=benign)  # replicated plan passes


# ---------------------------------------------------------------------------
# plan()/resolve() surface

def test_resolve_accepts_rules_plans_and_none():
    mesh = _mesh((2, 2), ("dp", "tp"))
    assert planner.resolve(None) is None
    p = planner.MeshPlan((), mesh=mesh)
    assert planner.resolve(p) is p
    r = planner.resolve(((r"w", P(None, "tp")),), mesh=mesh)
    assert isinstance(r, planner.MeshPlan)
    assert r.match("w") == P(None, "tp")


def test_plan_auto_records_decision():
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = M.MegatronConfig(vocab_size=64, hidden=32, n_heads=4,
                           layers_per_stage=1, seq_len=16, microbatch=2,
                           n_micro=1, use_moe=False)
    p = planner.plan(auto=True, cfg=cfg, n_devices=8)
    assert p.advice and p.advice[0]["rank"] == 1
    dec = planner.last_decision()
    assert dec is not None and dec["auto"]
    assert dec["chosen"] == p.advice[0]["sizes"]
    assert dec["candidates"] == len(p.advice)
    assert monitor.registry().value("planner.plan", 0) >= 1
    assert monitor.registry().value("planner.auto_pick", 0) >= 1
    assert monitor.registry().value("planner.candidates", 0) == len(p.advice)
