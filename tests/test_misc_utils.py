"""Coverage for aux modules: logger, profiler, image_util, distributed
utils, framework/imperative facades (SURVEY §5 aux subsystems)."""
import argparse
import logging

import numpy as np
import pytest

import paddle_tpu as pt


def test_logger():
    from paddle_tpu.utils.log import get_logger
    lg = get_logger("paddle_tpu.test", level=logging.DEBUG)
    lg2 = get_logger("paddle_tpu.test")
    assert lg is lg2  # no duplicate handlers
    lg.info("hello")


def test_profiler_records_scope():
    from paddle_tpu.utils import profiler as P
    P.reset_profiler()
    P.start_profiler()
    with P.scope("matmul_block"):
        a = pt.to_tensor(np.ones((64, 64), "f4"))
        (a @ a).numpy()
    P.stop_profiler()
    P.print_stats()


def test_image_util_roundtrip():
    from paddle_tpu.utils import image_util as IU
    im = (np.random.rand(40, 50, 3) * 255).astype("u1")
    assert min(IU.resize_image(im, 32).shape[:2]) == 32
    assert IU.crop_img(im, 24).shape[:2] == (24, 24)
    assert IU.crop_img(im, 24, test=False).shape[:2] == (24, 24)
    assert IU.oversample(im, 24).shape == (10, 24, 24, 3)
    chw = np.transpose(im, (2, 0, 1))
    assert IU.flip(chw).shape == chw.shape
    mean = np.zeros((3, 24, 24), "f4")
    out = IU.preprocess_img(im, mean, 24, is_train=False)
    # reference parity: returns the flattened CHW image
    assert out.shape == (3 * 24 * 24,) and out.dtype == np.float32
    out_pc = IU.preprocess_img(im, [10.0, 20.0, 30.0], 24, is_train=False)
    assert out_pc.shape == (3 * 24 * 24,)


def test_distributed_cluster_descriptors():
    from paddle_tpu.distributed import utils as U
    cluster, pod = U.get_cluster(["10.0.0.1", "10.0.0.2"], "10.0.0.2",
                                 [8071, 8072], [0, 1])
    assert cluster.trainers_nranks() == 4
    assert pod.rank == 1
    assert cluster.trainers_endpoints()[0] == "10.0.0.1:8071"
    assert cluster.pods_endpoints() == ["10.0.0.1:8071", "10.0.0.2:8071"]
    ports = U.find_free_ports(3)
    assert len(ports) == 3
    ap = argparse.ArgumentParser()
    U.add_arguments("node_ip", str, "127.0.0.1", "ip", ap)
    assert ap.parse_args([]).node_ip == "127.0.0.1"


def test_cloud_cluster_from_env(monkeypatch):
    from paddle_tpu.distributed import cloud_utils as CU
    monkeypatch.setenv("PADDLE_TRAINERS", "1.1.1.1,2.2.2.2")
    monkeypatch.setenv("POD_IP", "2.2.2.2")
    monkeypatch.setenv("PADDLE_PORT", "9000")
    cluster, pod = CU.get_cloud_cluster(selected_accelerators=[0])
    assert cluster.trainers_nranks() == 2
    assert pod.addr == "2.2.2.2" and pod.port == 9000


def test_framework_imperative_facades():
    assert pt.framework.manual_seed is pt.seed
    with pt.imperative.guard():
        v = pt.imperative.to_variable(np.ones(3, "f4"))
        assert v.shape == [3]
    bs = pt.imperative.BackwardStrategy()
    assert bs.sort_sum_gradient is False
    # grad through the imperative facade
    x = pt.to_tensor(np.asarray([2.0], "f4"))
    x.stop_gradient = False
    (gx,) = pt.imperative.grad((x * x).sum(), [x])
    np.testing.assert_allclose(np.asarray(gx.numpy()), [4.0], atol=1e-6)


def test_distributed_batch_reader_shards(monkeypatch):
    from paddle_tpu.fluid.contrib import distributed_batch_reader
    monkeypatch.setenv("PADDLE_TRAINER_ID", "1")
    monkeypatch.setenv("PADDLE_TRAINERS_NUM", "2")

    def reader():
        for i in range(6):
            yield i

    got = list(distributed_batch_reader(reader)())
    assert got == [1, 3, 5]


def test_profiler_summarize_trace(tmp_path):
    """summarize_trace aggregates device-op families from a Chrome-format
    trace, excluding host frames and jit wrappers."""
    import gzip
    import json
    from paddle_tpu.utils import profiler

    d = tmp_path / "plugins" / "profile" / "2026"
    d.mkdir(parents=True)
    ev = [
        {"ph": "X", "dur": 4000, "name": "multiply_reduce_fusion.2"},
        {"ph": "X", "dur": 2000, "name": "multiply_reduce_fusion.7"},
        {"ph": "X", "dur": 3000, "name": "fusion.1"},
        {"ph": "X", "dur": 9999, "name": "$jit.py:134 __call__"},
        {"ph": "X", "dur": 9999, "name": "jit_traced(123)"},
        {"ph": "X", "dur": 9999, "name": "0"},
        {"ph": "M", "name": "meta-no-dur"},
    ]
    with gzip.open(d / "vm.trace.json.gz", "wt") as f:
        json.dump({"traceEvents": ev}, f)
    fams = profiler.summarize_trace(str(tmp_path), steps=2)
    d_ = dict(fams)
    assert d_["multiply_reduce_fusion"] == 3.0  # (4000+2000)us / 2 steps
    assert d_["fusion"] == 1.5
    assert len(fams) == 2
