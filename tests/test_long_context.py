"""Long-context pieces working together: flash kernel at longer seq,
recompute through the encoder, ring attention on the sp mesh (SURVEY §2
row 30)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops.pallas import flash_attention


def test_flash_longer_seq_causal_matches_sdpa():
    from paddle_tpu.nn import functional as F
    rng = np.random.RandomState(0)
    b, h, s, d = 1, 2, 256, 32
    q = rng.randn(b, h, s, d).astype("f4")
    k = rng.randn(b, h, s, d).astype("f4")
    v = rng.randn(b, h, s, d).astype("f4")
    out = flash_attention(pt.to_tensor(q), pt.to_tensor(k),
                          pt.to_tensor(v), causal=True, block_q=128,
                          block_k=128, force=True)
    ref = F.scaled_dot_product_attention(
        pt.to_tensor(q), pt.to_tensor(k), pt.to_tensor(v), is_causal=True)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), atol=2e-3)


@pytest.mark.slow
def test_bert_long_seq_recompute_flash_trains():
    """Tiny-width BERT at seq 512 with recompute on: the long-context
    configuration (flash stays off on CPU via the auto gate — it runs on
    TPU; recompute is exercised for real)."""
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu import optimizer as opt, jit

    pt.seed(0)
    cfg = BertConfig(vocab_size=128, hidden_size=32, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=64,
                     max_position_embeddings=512, use_recompute=True)
    m = BertForPretraining(cfg)
    o = opt.AdamW(learning_rate=1e-3, parameters=m.parameters())
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 128, (1, 512)).astype("i4")
    mlm = np.where(rng.rand(1, 512) < 0.15,
                   rng.randint(0, 128, (1, 512)), -1).astype("i4")
    nsp = np.zeros((1,), "i4")

    def step(i, ml, ns):
        lo, nl = m(i)
        loss = m.loss(lo, nl, ml, ns)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    f = jit.to_static(step, models=[m], optimizers=[o])
    args = [pt.to_tensor(a) for a in (ids, mlm, nsp)]
    losses = [float(f(*args).numpy()) for _ in range(4)]
    assert losses[-1] < losses[0]


def test_ring_attention_causal_matches_full():
    """Causal ring attention over sp=4 equals single-device causal
    attention (the long-seq scaling path)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from paddle_tpu.parallel.ring_attention import _ring_attention_impl

    rng = np.random.RandomState(1)
    b, hd, s, d = 2, 2, 32, 8
    q = rng.randn(b, hd, s, d).astype("f4")
    k = rng.randn(b, hd, s, d).astype("f4")
    v = rng.randn(b, hd, s, d).astype("f4")

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("sp",))
    f = jax.jit(jax.shard_map(
        lambda q, k, v: _ring_attention_impl(q, k, v, "sp", True, None),
        mesh=mesh,
        in_specs=(P(None, None, "sp", None),) * 3,
        out_specs=P(None, None, "sp", None), check_vma=False))
    out = np.asarray(f(q, k, v))

    logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
    mask = np.tril(np.ones((s, s), bool))
    logits = np.where(mask, logits, -1e30)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = np.einsum("bhqk,bhkd->bhqd", p, v)
    np.testing.assert_allclose(out, ref, atol=2e-4)
