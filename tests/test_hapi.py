"""hapi Model API end-to-end (reference: incubate/hapi/model.py +
callbacks + metrics + loss)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt, hapi


def _toy_data(n=64, d=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype("f4")
    y = (x @ w).argmax(-1).astype("i8")
    return x, y


def _dataset(x, y):
    from paddle_tpu.io import TensorDataset
    return TensorDataset(x, y.astype("i4"))


def test_fit_reduces_loss_and_evaluates():
    pt.seed(0)
    x, y = _toy_data()
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.Adam(learning_rate=0.05,
                                 parameters=m.parameters()),
              loss_function=hapi.CrossEntropy(),
              metrics=hapi.Accuracy())
    hist = m.fit(_dataset(x, y), batch_size=16, epochs=8, verbose=0,
                 shuffle=True)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
    res = m.evaluate(_dataset(x, y), batch_size=16, verbose=0)
    assert res["acc"] > 0.8
    preds = m.predict(_dataset(x, y), batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 3)


def test_save_load_roundtrip(tmp_path):
    pt.seed(0)
    x, y = _toy_data(32)
    net = nn.Sequential(nn.Linear(8, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    m.fit(_dataset(x, y), batch_size=16, epochs=1, verbose=0)
    p = str(tmp_path / "ckpt")
    m.save(p)
    before = m.predict([[x[:4]]])[0][0]

    pt.seed(1)
    net2 = nn.Sequential(nn.Linear(8, 3))
    m2 = hapi.Model(net2)
    m2.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                 parameters=m2.parameters()),
               loss_function=hapi.CrossEntropy())
    m2.load(p)
    after = m2.predict([[x[:4]]])[0][0]
    np.testing.assert_allclose(before, after, atol=1e-6)


def test_callbacks_and_early_stopping():
    pt.seed(0)
    x, y = _toy_data(32)
    events = []

    class Spy(hapi.Callback):
        def on_epoch_begin(self, epoch, logs=None):
            events.append(("begin", epoch))

        def on_epoch_end(self, epoch, logs=None):
            events.append(("end", epoch, logs["loss"]))

    net = nn.Sequential(nn.Linear(8, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.0,
                                parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    # lr=0 → loss never improves → early stopping fires after patience
    es = hapi.EarlyStopping(monitor="loss", patience=1)
    m.fit(_dataset(x, y), batch_size=16, epochs=10, verbose=0,
          callbacks=[es])
    epochs_run = len([e for e in events if e[0] == "end"])
    assert es.stopped and epochs_run < 10


def test_accuracy_metric_topk():
    m = hapi.Accuracy(topk=(1, 2))
    pred = pt.to_tensor(np.asarray([[0.1, 0.7, 0.2],
                                    [0.8, 0.1, 0.1]], "f4"))
    label = pt.to_tensor(np.asarray([[2], [0]], "i4"))
    (correct,) = m.add_metric_op(pred, label)
    m.update(correct)
    top1, top2 = m.accumulate()
    assert abs(top1 - 0.5) < 1e-6   # second row right, first wrong
    assert abs(top2 - 1.0) < 1e-6   # label 2 is in top-2 of first row
    assert m.name() == ["acc_top1", "acc_top2"]


def test_model_subclass_style():
    pt.seed(0)

    class MyModel(hapi.Model):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 3)

        def forward(self, x):
            return self.fc(x)

    x, y = _toy_data(32)
    m = MyModel()
    m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    hist = m.fit(_dataset(x, y), batch_size=16, epochs=3, verbose=0)
    assert hist["loss"][-1] <= hist["loss"][0]
    m.summary()
