"""hapi Model API end-to-end (reference: incubate/hapi/model.py +
callbacks + metrics + loss)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt, hapi, io


def _toy_data(n=64, d=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype("f4")
    y = (x @ w).argmax(-1).astype("i8")
    return x, y


def _dataset(x, y):
    from paddle_tpu.io import TensorDataset
    return TensorDataset(x, y.astype("i4"))


def test_fit_reduces_loss_and_evaluates():
    pt.seed(0)
    x, y = _toy_data()
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.Adam(learning_rate=0.05,
                                 parameters=m.parameters()),
              loss_function=hapi.CrossEntropy(),
              metrics=hapi.Accuracy())
    hist = m.fit(_dataset(x, y), batch_size=16, epochs=8, verbose=0,
                 shuffle=True)
    assert hist["loss"][-1] < hist["loss"][0] * 0.5
    res = m.evaluate(_dataset(x, y), batch_size=16, verbose=0)
    assert res["acc"] > 0.8
    preds = m.predict(_dataset(x, y), batch_size=16, stack_outputs=True)
    assert preds[0].shape == (64, 3)


def test_save_load_roundtrip(tmp_path):
    pt.seed(0)
    x, y = _toy_data(32)
    net = nn.Sequential(nn.Linear(8, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    m.fit(_dataset(x, y), batch_size=16, epochs=1, verbose=0)
    p = str(tmp_path / "ckpt")
    m.save(p)
    before = m.predict([[x[:4]]])[0][0]

    pt.seed(1)
    net2 = nn.Sequential(nn.Linear(8, 3))
    m2 = hapi.Model(net2)
    m2.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                 parameters=m2.parameters()),
               loss_function=hapi.CrossEntropy())
    m2.load(p)
    after = m2.predict([[x[:4]]])[0][0]
    np.testing.assert_allclose(before, after, atol=1e-6)


def test_callbacks_and_early_stopping():
    pt.seed(0)
    x, y = _toy_data(32)
    events = []

    class Spy(hapi.Callback):
        def on_epoch_begin(self, epoch, logs=None):
            events.append(("begin", epoch))

        def on_epoch_end(self, epoch, logs=None):
            events.append(("end", epoch, logs["loss"]))

    net = nn.Sequential(nn.Linear(8, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.SGD(learning_rate=0.0,
                                parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    # lr=0 → loss never improves → early stopping fires after patience
    es = hapi.EarlyStopping(monitor="loss", patience=1)
    m.fit(_dataset(x, y), batch_size=16, epochs=10, verbose=0,
          callbacks=[es])
    epochs_run = len([e for e in events if e[0] == "end"])
    assert es.stopped and epochs_run < 10


def test_accuracy_metric_topk():
    m = hapi.Accuracy(topk=(1, 2))
    pred = pt.to_tensor(np.asarray([[0.1, 0.7, 0.2],
                                    [0.8, 0.1, 0.1]], "f4"))
    label = pt.to_tensor(np.asarray([[2], [0]], "i4"))
    (correct,) = m.add_metric_op(pred, label)
    m.update(correct)
    top1, top2 = m.accumulate()
    assert abs(top1 - 0.5) < 1e-6   # second row right, first wrong
    assert abs(top2 - 1.0) < 1e-6   # label 2 is in top-2 of first row
    assert m.name() == ["acc_top1", "acc_top2"]


def test_model_subclass_style():
    pt.seed(0)

    class MyModel(hapi.Model):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(8, 3)

        def forward(self, x):
            return self.fc(x)

    x, y = _toy_data(32)
    m = MyModel()
    m.prepare(optimizer=opt.SGD(learning_rate=0.1,
                                parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    hist = m.fit(_dataset(x, y), batch_size=16, epochs=3, verbose=0)
    assert hist["loss"][-1] <= hist["loss"][0]
    m.summary()


# ---------------------------------------------------------------------------
# hapi tail: DistributedBatchSampler, datasets, download, progressbar
# (reference: incubate/hapi/{distributed,datasets,download,progressbar}.py)


def test_distributed_batch_sampler_partitions_exclusively():
    from paddle_tpu.hapi import DistributedBatchSampler

    class DS:
        def __len__(self):
            return 10

    seen = []
    for rank in range(4):
        s = DistributedBatchSampler(DS(), batch_size=2, num_replicas=4,
                                    rank=rank)
        got = [i for b in s for i in b]
        assert len(got) == 3  # ceil(10/4) with padding
        seen.append(got)
    flat = [i for g in seen for i in g]
    assert set(flat) == set(range(10))  # every sample covered
    # epoch-seeded reshuffle changes the order deterministically
    s = DistributedBatchSampler(DS(), batch_size=2, shuffle=True,
                                num_replicas=2, rank=0)
    s.set_epoch(1)
    a = [i for b in s for i in b]
    s.set_epoch(1)
    b = [i for bb in s for i in bb]
    assert a == b


def test_hapi_mnist_dataset_with_transform_and_loader():
    from paddle_tpu.hapi.datasets import MNIST
    ds = MNIST(mode="train", transform=lambda im: (im / 255.0) - 0.5)
    img, lab = ds[0]
    assert img.shape == (28, 28) and img.max() <= 0.5
    assert 0 <= int(lab) <= 9
    loader = io.DataLoader(ds, batch_size=16)
    xb, yb = next(iter(loader))
    assert xb.shape == (16, 28, 28) and yb.shape == (16,)


def test_dataset_folder_walks_classes(tmp_path):
    from paddle_tpu.hapi.datasets import DatasetFolder, ImageFolder
    for cls, n in (("cat", 3), ("dog", 2)):
        d = tmp_path / cls
        d.mkdir()
        for i in range(n):
            np.save(str(d / f"{i}.npy"),
                    np.full((4, 4, 3), i, "f4"))
    ds = DatasetFolder(str(tmp_path))
    assert ds.classes == ["cat", "dog"]
    assert len(ds) == 5
    img, lab = ds[4]
    assert int(lab) == 1 and img.shape == (4, 4, 3)
    flat = ImageFolder(str(tmp_path))
    assert len(flat) == 5


def test_download_local_cache_only(tmp_path):
    from paddle_tpu.hapi import download
    p = tmp_path / "weights.bin"
    p.write_bytes(b"abc")
    # local path passes straight through
    assert download.get_path_from_url(str(p)) == str(p)
    # cached basename resolves
    got = download.get_path_from_url("https://example.com/weights.bin",
                                     root_dir=str(tmp_path))
    assert got == str(p)
    with pytest.raises(FileNotFoundError, match="no network egress"):
        download.get_path_from_url("https://example.com/absent.bin",
                                   root_dir=str(tmp_path))


def test_progressbar_renders(capsys):
    from paddle_tpu.hapi.progressbar import ProgressBar
    bar = ProgressBar(num=4, verbose=2)
    for i in range(1, 5):
        bar.update(i, [("loss", 0.5 / i)])
    out = capsys.readouterr().out
    assert "step 4/4" in out and "loss: 0.1250" in out


def test_download_md5_mismatch_and_check_exist(tmp_path):
    from paddle_tpu.hapi import download
    p = tmp_path / "w.bin"
    p.write_bytes(b"abc")
    with pytest.raises(ValueError, match="md5 does not match"):
        download.get_path_from_url("https://x/w.bin",
                                   root_dir=str(tmp_path), md5sum="0" * 32)
    # check_exist=False trusts the cached file
    got = download.get_path_from_url("https://x/w.bin",
                                     root_dir=str(tmp_path),
                                     md5sum="0" * 32, check_exist=False)
    assert got == str(p)


def test_fleet_module_delegates_to_singleton():
    import paddle_tpu.fleet as fl
    assert callable(fl.distributed_model)
    assert callable(fl.shard_batch)
    from paddle_tpu.hapi.vision.models import LeNet  # real package path
    assert LeNet.__name__ == "LeNet"


def test_distributed_batch_sampler_many_ranks_small_dataset():
    """total_size > 2*len(dataset): every rank still yields the same
    number of batches (lockstep-safe padding)."""
    from paddle_tpu.hapi import DistributedBatchSampler

    class DS:
        def __len__(self):
            return 3

    counts = []
    for rank in range(8):
        s = DistributedBatchSampler(DS(), batch_size=1, num_replicas=8,
                                    rank=rank)
        counts.append(sum(1 for _ in s))
    assert counts == [1] * 8


def test_vision_transforms_pipeline():
    from paddle_tpu.hapi.vision import transforms as T
    rng = np.random.RandomState(0)
    img = (rng.rand(50, 40, 3) * 255).astype("u1")

    tf = T.Compose([T.Resize(48), T.CenterCrop(32), T.ToTensor()])
    out = tf(img)
    assert out.shape == (3, 32, 32) and out.dtype == np.float32
    assert 0.0 <= out.min() and out.max() <= 1.0

    # deterministic random transforms via injected rng
    r = np.random.RandomState(3)
    tf2 = T.Compose([T.RandomResizedCrop(16, rng=r),
                     T.RandomHorizontalFlip(prob=1.0),
                     T.Normalize([127.5] * 3, [127.5] * 3),
                     T.Transpose()])
    out2 = tf2(img)
    assert out2.shape == (3, 16, 16)
    assert abs(float(out2.mean())) < 1.5  # roughly centered

    # exact-size resize + flip identity checks
    assert T.Resize((20, 24))(img).shape == (20, 24, 3)
    np.testing.assert_array_equal(
        T.RandomHorizontalFlip(prob=1.0)(img), img[:, ::-1])
    np.testing.assert_array_equal(
        T.RandomVerticalFlip(prob=1.0)(img), img[::-1])


def test_vision_transforms_with_dataset_folder(tmp_path):
    """transforms compose into DatasetFolder + the multiprocess loader —
    the decode/augment pipeline the worker processes exist for."""
    from paddle_tpu.hapi.datasets import DatasetFolder
    from paddle_tpu.hapi.vision import transforms as T
    rng = np.random.RandomState(0)
    for cls in ("a", "b"):
        d = tmp_path / cls
        d.mkdir()
        for i in range(3):
            np.save(str(d / f"{i}.npy"),
                    (rng.rand(20, 20, 3) * 255).astype("u1"))
    tf = T.Compose([T.CenterCrop(16), T.ToTensor()])
    ds = DatasetFolder(str(tmp_path), transform=tf)
    loader = io.DataLoader(ds, batch_size=2, num_workers=2,
                           use_native=False)
    batches = list(loader)
    assert sum(x.shape[0] for x, _ in batches) == 6
    assert batches[0][0].shape == (2, 3, 16, 16)


def test_vision_transforms_edge_semantics():
    from paddle_tpu.hapi.vision import transforms as T
    small = (np.random.RandomState(0).rand(10, 10, 3) * 255).astype("u1")
    with pytest.raises(ValueError, match="smaller than the crop"):
        T.CenterCrop(16)(small)
    with pytest.raises(ValueError, match="smaller than the crop"):
        T.RandomCrop(16)(small)
    # brightness range follows DTYPE inside the transform
    class AlphaUp:  # deterministic rng: alpha = 1.4
        @staticmethod
        def uniform(lo, hi):
            return 0.4

    bt = T.BrightnessTransform(0.4, rng=AlphaUp())
    dark = np.ones((4, 4, 3), "u1")          # max pixel 1
    np.testing.assert_allclose(bt(dark), 1.4)  # NOT clipped to 1.0
    bright = np.full((2, 2, 3), 200, "u1")
    np.testing.assert_allclose(bt(bright), 255.0)  # uint8 ceiling
    signed = np.array([[-1.0, 1.0]], "f4")   # float: no clipping
    np.testing.assert_allclose(bt(signed), [[-1.4, 1.4]], rtol=1e-6)
