"""CompiledProgram.with_data_parallel / ParallelExecutor over the 8-device
mesh (VERDICT r2 #9; reference: python/paddle/fluid/compiler.py,
parallel_executor.py): feeds batch-shard over the mesh and training
matches the single-device Executor numerically."""
import numpy as np
import pytest
import jax

import paddle_tpu as pt
from paddle_tpu import static, optimizer as opt
from paddle_tpu.fluid import layers as FL


def _build_program():
    prog, sprog = static.Program(), static.Program()
    with static.program_guard(prog, sprog):
        x = static.data("x", [None, 8], "float32")
        y = static.data("y", [None, 1], "float32")
        h = FL.fc(x, 16, act="relu")
        out = FL.fc(h, 1)
        loss = ((out - y) ** 2).mean()
        sgd = opt.SGD(learning_rate=0.1)
        sgd.minimize(loss)
    return prog, sprog, loss


def _data(n=64):
    rng = np.random.RandomState(0)
    x = rng.rand(n, 8).astype("f4")
    y = (x.sum(-1, keepdims=True) * 0.5).astype("f4")
    return x, y


def test_with_data_parallel_matches_single_device():
    x, y = _data()

    pt.enable_static()
    try:
        pt.seed(7)
        prog, sprog, loss = _build_program()
        exe = static.Executor()
        exe.run(sprog)
        ref = [float(exe.run(prog, feed={"x": x, "y": y},
                             fetch_list=[loss])[0]) for _ in range(5)]

        pt.seed(7)
        prog2, sprog2, loss2 = _build_program()
        exe2 = static.Executor()
        exe2.run(sprog2)
        cp = static.CompiledProgram(prog2).with_data_parallel(
            loss_name=loss2.name)
        got = [float(exe2.run(cp, feed={"x": x, "y": y},
                              fetch_list=[loss2])[0]) for _ in range(5)]
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

        # params actually live replicated on the 8-device mesh
        p = next(iter(prog2.param_vars.values()))
        assert len(p.data.sharding.device_set) == len(jax.devices())
        assert ref[-1] < ref[0]
    finally:
        pt.disable_static()


def test_with_data_parallel_rejects_indivisible_batch():
    pt.enable_static()
    try:
        pt.seed(0)
        prog, sprog, loss = _build_program()
        exe = static.Executor()
        exe.run(sprog)
        cp = static.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        x, y = _data(n=30)  # 30 % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            exe.run(cp, feed={"x": x, "y": y}, fetch_list=[loss])
    finally:
        pt.disable_static()


def test_parallel_executor_runs_sharded():
    x, y = _data()
    pt.enable_static()
    try:
        pt.seed(3)
        prog, sprog, loss = _build_program()
        static.Executor().run(sprog)
        pe = static.ParallelExecutor(loss_name=loss.name,
                                     main_program=prog)
        losses = [float(pe.run(feed={"x": x, "y": y},
                               fetch_list=[loss])[0]) for _ in range(5)]
        assert losses[-1] < losses[0]
    finally:
        pt.disable_static()
