"""The benchmark's robustness machinery (VERDICT r3 #1a: 'make the perf
number un-losable') — unit-locked so a refactor can't silently lose the
always-parseable-JSON or partial-credit behavior the r4 tunnel outage
proved out."""
import contextlib
import importlib.util
import io as _io
import json
import os
import sys

import pytest


@pytest.fixture()
def bench():
    spec = importlib.util.spec_from_file_location(
        "bench_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    mod._RESULTS.clear()
    return mod


def _capture_json(fn, *args):
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        fn(*args)
    lines = [l for l in buf.getvalue().splitlines() if l.strip()]
    assert len(lines) == 1, "exactly ONE line, and it must be JSON"
    return json.loads(lines[0])


def test_fail_json_zero_schema(bench):
    d = _capture_json(bench._fail_json, "boom")
    for key in ("metric", "value", "unit", "vs_baseline",
                "resnet50_images_per_sec", "resnet50_vs_baseline"):
        assert key in d
    assert d["value"] == 0.0 and d["error"] == "boom"


def test_fail_json_partial_credit(bench):
    bench._RESULTS.update(value=123.4, vs_baseline=4.936,
                          bert_seq2048_tokens_per_sec=9.0)
    d = _capture_json(bench._fail_json, "tunnel died mid-run")
    assert d["value"] == 123.4                       # real, banked
    assert d["bert_seq2048_tokens_per_sec"] == 9.0
    assert d["resnet50_images_per_sec"] == 0.0       # never reached
    assert "tunnel died" in d["error"]


def test_subprocess_probe_ok_on_cpu(bench, monkeypatch):
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    monkeypatch.delenv("PALLAS_AXON_POOL_IPS", raising=False)
    ok, msg = bench._subprocess_probe(timeout_s=240)
    assert ok, msg
    assert "PROBE_OK" in msg


def test_subprocess_probe_times_out_on_hang(bench, monkeypatch):
    """A wedged backend = uninterruptible block; the probe must come back
    anyway (that is its whole reason to exist)."""
    real_exe = sys.executable
    # simulate the wedge: the probe command sleeps forever
    import subprocess as sp
    real_run = sp.run

    def fake_run(cmd, **kw):
        return real_run([real_exe, "-c", "import time; time.sleep(60)"],
                        **kw)

    monkeypatch.setattr(sp, "run", fake_run)
    ok, msg = bench._subprocess_probe(timeout_s=1)
    assert not ok and "no backend response" in msg


def test_init_retry_gives_fail_json_when_probe_never_succeeds(
        bench, monkeypatch):
    monkeypatch.setattr(bench, "_subprocess_probe",
                        lambda timeout_s=300: (False, "still wedged"))
    monkeypatch.setattr(bench.time, "sleep", lambda s: None)
    buf = _io.StringIO()
    with contextlib.redirect_stdout(buf):
        ok = bench._init_backend_with_retry(attempts=3, backoff=0)
    assert not ok
    lines = [l for l in buf.getvalue().splitlines()
             if l.startswith("{")]
    d = json.loads(lines[-1])
    assert "still wedged" in d["error"] and d["value"] == 0.0


@pytest.fixture()
def battery():
    spec = importlib.util.spec_from_file_location(
        "battery_under_test",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "scripts", "watcher_battery.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_battery_parses_last_json_line(battery):
    out = 'noise\n{"value": 1.5}\n{"value": 2.5, "x": 1}\ntrailing'
    assert battery._last_json_line(out) == {"value": 2.5, "x": 1}
    assert battery._last_json_line("rubbish only") is None
    assert battery._last_json_line("{broken json}\nrest") is None


def test_battery_refreshes_latest_only_on_positive_value(battery,
                                                         tmp_path,
                                                         monkeypatch):
    latest = tmp_path / "latest.json"
    monkeypatch.setattr(battery, "LATEST", str(latest))
    monkeypatch.setattr(battery, "LOGS", str(tmp_path / "logs"))
    calls = []

    def fake_run(cmd, log_name, timeout_s):
        calls.append(cmd)
        if "bench.py" in cmd[-1]:
            return 0, '{"value": 123.0, "unit": "tokens/s"}'
        return 0, ""

    monkeypatch.setattr(battery, "_run", fake_run)
    battery.main()
    data = json.loads(latest.read_text())
    assert data["value"] == 123.0
    assert "measured_at" in data and "git_rev" in data

    # zero/failed bench must NOT clobber a previous good record
    def fake_run_zero(cmd, log_name, timeout_s):
        if "bench.py" in cmd[-1]:
            return 0, '{"value": 0.0, "error": "tunnel wedged"}'
        return 0, ""

    monkeypatch.setattr(battery, "_run", fake_run_zero)
    battery.main()
    assert json.loads(latest.read_text())["value"] == 123.0


def test_main_fast_and_full_stage_selection(bench, monkeypatch):
    """--fast runs only the two headline stages; the full path runs
    pipeline + seq-512 + seq-2048 and banks their metrics."""
    import sys as _sys
    monkeypatch.setattr(bench, "_arm_watchdog", lambda *a, **k: None)
    monkeypatch.setattr(bench, "_enable_monitoring_and_cache",
                        lambda: None)
    monkeypatch.setattr(bench, "_init_backend_with_retry",
                        lambda *a, **k: True)
    monkeypatch.setattr(bench, "_probe_pallas_kernels", lambda: None)
    monkeypatch.setattr(bench, "bench_bert",
                        lambda **k: (111111.0, 2.5))
    monkeypatch.setattr(bench, "bench_resnet",
                        lambda **k: (2500.0, 3.1))
    calls = []
    monkeypatch.setattr(bench, "bench_resnet_pipeline",
                        lambda **k: calls.append("pipe") or (1.0, 2.0))
    monkeypatch.setattr(bench, "bench_bert_seq512",
                        lambda **k: calls.append("s512") or (1.0, 0.0))
    monkeypatch.setattr(bench, "bench_bert_long",
                        lambda **k: calls.append("s2048") or (1.0, 0.0))
    # The subprocess-launching stages (each spawns its own python+jax
    # and runs a full smoke script) are stage-selection no-ops here:
    # their behavior is gated by their own scripts/*_smoke.sh entries in
    # run_full_suite.sh, and running them for real turns this wiring
    # test into a multi-minute integration run.
    monkeypatch.setattr(bench, "bench_serving",
                        lambda **k: (1.0, 2.0, 300.0, 3.0))
    monkeypatch.setattr(bench, "bench_serving_degraded", lambda **k: {
        "serving_degraded_goodput": 1.0,
        "serving_degraded_high_goodput": 1.0})
    monkeypatch.setattr(bench, "bench_collective_overlap", lambda **k: {
        "collective_overlap_ratio": 0.5})
    monkeypatch.setattr(bench, "bench_fused_optimizer", lambda **k: {
        "fused_optimizer_bytes_reduction": 0.5})
    monkeypatch.setattr(bench, "bench_planner", lambda **k: {
        "planner_chosen": "x", "planner_candidates": 1})
    monkeypatch.setattr(bench, "bench_memory_plan", lambda **k: {
        "memory_plan_picked": "none", "memory_plan_ceiling_multiple": 1.0})
    monkeypatch.setattr(bench, "bench_decode", lambda **k: {
        "decode_tokens_per_s": 1.0, "decode_speedup_x": 2.0})
    monkeypatch.setattr(bench, "bench_spec_decode", lambda **k: {
        "decode_spec_speedup_x": 1.5, "decode_accept_rate": 0.95})
    monkeypatch.setattr(bench, "bench_lifecycle", lambda **k: {
        "lifecycle_drain_p99_ms": 1.0, "lifecycle_swap_dropped": 0,
        "lifecycle_soak_goodput": 1.0})
    for argv, expect_extra in ((["bench.py", "--fast"], False),
                               (["bench.py"], True)):
        bench._RESULTS.clear()
        calls.clear()
        monkeypatch.setattr(_sys, "argv", argv)
        import contextlib as _ctx
        import io as _io2
        buf = _io2.StringIO()
        with _ctx.redirect_stdout(buf):
            bench.main()
        out = json.loads(
            [l for l in buf.getvalue().splitlines()
             if l.startswith("{")][-1])
        assert out["value"] == 111111.0
        assert out["resnet50_images_per_sec"] == 2500.0
        assert (len(calls) > 0) == expect_extra
        if expect_extra:
            assert out["bert_seq512_tokens_per_sec"] == 1.0
            assert out["bert_seq2048_tokens_per_sec"] == 1.0
