"""Tests for nn.decode: BeamSearchDecoder / dynamic_decode / helpers
(mirrors reference unittests test_rnn_decode_api.py)."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import nn
from paddle_tpu.nn.decode import (BeamSearchDecoder, dynamic_decode,
                                  gather_tree, GreedyEmbeddingHelper,
                                  BasicDecoder, basic_decode,
                                  TrainingHelper)


def _seq2seq_parts(vocab=13, hidden=16):
    pt.seed(42)
    emb = nn.Embedding(vocab, hidden)
    cell = nn.GRUCell(hidden, hidden)
    proj = nn.Linear(hidden, vocab)
    return emb, cell, proj


def test_gather_tree_backtrace():
    # T=3, B=1, K=2 hand-built lattice
    ids = np.array([[[2, 3]], [[4, 5]], [[6, 7]]], np.int32)      # [T,1,K]
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int32)
    out = np.asarray(gather_tree(ids, parents))
    # final beam 0: t2 token 6 parent 0 -> t1 token 4 parent 1 -> t0 3
    np.testing.assert_array_equal(out[:, 0, 0], [3, 4, 6])
    # final beam 1: t2 token 7 parent 1 -> t1 token 5 parent 0 -> t0 2
    np.testing.assert_array_equal(out[:, 0, 1], [2, 5, 7])


def test_beam1_equals_greedy():
    """Beam size 1 must reproduce greedy decoding step by step."""
    vocab, hidden, b = 13, 16, 3
    emb, cell, proj = _seq2seq_parts(vocab, hidden)
    h0 = pt.to_tensor(np.random.RandomState(0).randn(b, hidden)
                      .astype("f4"))

    decoder = BeamSearchDecoder(cell, start_token=1, end_token=2,
                                beam_size=1, embedding_fn=emb,
                                output_fn=proj)
    ids, scores = dynamic_decode(decoder, h0, max_step_num=8)
    ids = np.asarray(ids.numpy())[:, :, 0]  # [B, T]

    # manual greedy rollout
    with pt.no_grad():
        tok = np.full((b,), 1, np.int32)
        h = h0
        greedy = []
        for _ in range(8):
            e = emb(pt.to_tensor(tok))
            out, h = cell(e, h)
            logits = np.asarray(proj(out).numpy())
            tok = logits.argmax(-1).astype(np.int32)
            greedy.append(tok)
    greedy = np.stack(greedy, 1)
    # compare up to each row's first end token
    for bi in range(b):
        ends = np.where(greedy[bi] == 2)[0]
        upto = (ends[0] + 1) if len(ends) else greedy.shape[1]
        np.testing.assert_array_equal(ids[bi, :upto], greedy[bi, :upto])


def test_beam_scores_sorted_and_finite():
    vocab, hidden, b, k = 11, 8, 2, 4
    emb, cell, proj = _seq2seq_parts(vocab, hidden)
    h0 = pt.to_tensor(np.random.RandomState(1).randn(b, hidden)
                      .astype("f4"))
    decoder = BeamSearchDecoder(cell, start_token=1, end_token=2,
                                beam_size=k, embedding_fn=emb,
                                output_fn=proj)
    ids, scores, lengths = dynamic_decode(decoder, h0, max_step_num=10,
                                          return_length=True)
    ids, scores = np.asarray(ids.numpy()), np.asarray(scores.numpy())
    assert ids.shape == (b, 10, k)
    assert scores.shape == (b, k)
    # top-k returns beams sorted by score
    assert (np.diff(scores, axis=1) <= 1e-6).all()
    assert np.isfinite(scores).all()
    assert (np.asarray(lengths.numpy()) <= 10).all()


def test_beam_search_beats_greedy_score():
    """The best beam-4 hypothesis must score >= the greedy hypothesis
    under the model's own log-probabilities."""
    vocab, hidden, b = 13, 16, 4
    emb, cell, proj = _seq2seq_parts(vocab, hidden)
    h0 = pt.to_tensor(np.random.RandomState(2).randn(b, hidden)
                      .astype("f4"))

    def rollout_score(tokens_bt):
        """Sum log p of a [B, T] token matrix under the model."""
        with pt.no_grad():
            tok = np.full((b,), 1, np.int32)
            h = h0
            total = np.zeros(b)
            done = np.zeros(b, bool)
            for t in range(tokens_bt.shape[1]):
                e = emb(pt.to_tensor(tok))
                out, h = cell(e, h)
                lp = jax.nn.log_softmax(
                    jnp.asarray(proj(out).numpy()), -1)
                sel = tokens_bt[:, t]
                total += np.where(done, 0.0,
                                  np.asarray(lp)[np.arange(b), sel])
                done |= sel == 2
                tok = sel.astype(np.int32)
            return total

    g = BeamSearchDecoder(cell, 1, 2, 1, embedding_fn=emb, output_fn=proj)
    gids, gsc = dynamic_decode(g, h0, max_step_num=8)
    b4 = BeamSearchDecoder(cell, 1, 2, 4, embedding_fn=emb, output_fn=proj)
    bids, bsc = dynamic_decode(b4, h0, max_step_num=8)

    greedy_score = rollout_score(np.asarray(gids.numpy())[:, :, 0])
    beam_score = rollout_score(np.asarray(bids.numpy())[:, :, 0])
    assert (beam_score >= greedy_score - 1e-4).all()
    # and the decoder's own reported score agrees with the rollout
    np.testing.assert_allclose(np.asarray(bsc.numpy())[:, 0], beam_score,
                               rtol=1e-3, atol=1e-3)


def test_greedy_embedding_helper_basic_decode():
    vocab, hidden, b = 9, 8, 2
    emb, cell, proj = _seq2seq_parts(vocab, hidden)
    h0 = pt.to_tensor(np.random.RandomState(3).randn(b, hidden)
                      .astype("f4"))
    helper = GreedyEmbeddingHelper(emb, np.full((b,), 1, np.int32),
                                   end_token=2)
    dec = BasicDecoder(cell, helper, output_fn=proj)
    outputs, sample_ids, lengths = basic_decode(dec, h0, max_step_num=6)
    assert np.asarray(sample_ids.numpy()).shape == (b, 6)
    assert np.asarray(outputs.numpy()).shape == (b, 6, vocab)

    # greedy basic_decode == beam-1 ids (up to length)
    bd = BeamSearchDecoder(cell, 1, 2, 1, embedding_fn=emb, output_fn=proj)
    ids, _ = dynamic_decode(bd, h0, max_step_num=6)
    ids = np.asarray(ids.numpy())[:, :, 0]
    sids = np.asarray(sample_ids.numpy())
    lens = np.asarray(lengths.numpy())
    for bi in range(b):
        n = min(lens[bi], 6)
        np.testing.assert_array_equal(sids[bi, :n], ids[bi, :n])


def test_training_helper_teacher_forcing():
    vocab, hidden, b, t = 9, 8, 2, 5
    emb, cell, proj = _seq2seq_parts(vocab, hidden)
    rs = np.random.RandomState(4)
    gold = rs.randint(0, vocab, (b, t)).astype("i4")
    inputs = emb(pt.to_tensor(gold))
    helper = TrainingHelper(inputs, np.array([5, 3], np.int32))
    h0 = pt.to_tensor(rs.randn(b, hidden).astype("f4"))
    dec = BasicDecoder(cell, helper, output_fn=proj)
    outputs, sample_ids, lengths = basic_decode(dec, h0, max_step_num=t)
    assert np.asarray(outputs.numpy()).shape == (b, t, vocab)
    np.testing.assert_array_equal(np.asarray(lengths.numpy()), [5, 3])


def test_transformer_generate_beam_search():
    from paddle_tpu.models.transformer import Transformer
    pt.seed(0)
    m = Transformer(src_vocab_size=50, tgt_vocab_size=50, d_model=32,
                    num_heads=4, num_encoder_layers=2,
                    num_decoder_layers=2, d_ff=64, dropout=0.0,
                    max_length=32)
    src = np.random.RandomState(5).randint(3, 50, (2, 7)).astype("i4")
    ids, scores = m.generate(pt.to_tensor(src), beam_size=3, max_len=10,
                             bos_id=1, eos_id=2)
    ids = np.asarray(ids.numpy())
    scores = np.asarray(scores.numpy())
    assert ids.shape == (2, 10, 3)
    assert scores.shape == (2, 3)
    assert (np.diff(scores, axis=1) <= 1e-6).all()
    assert np.isfinite(scores).all()
    assert ((ids >= 0) & (ids < 50)).all()


def test_transformer_generate_kv_cache_matches_prefix_oracle():
    """The O(T) KV-cached incremental decoder must produce EXACTLY the
    beams of the full-prefix re-decode path (use_cache=False oracle),
    including cache reordering by parent beam at every step."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.models.transformer import Transformer

    pt.seed(0)
    rng = np.random.RandomState(0)
    V, B, T = 30, 3, 6
    m = Transformer(src_vocab_size=V, tgt_vocab_size=V, d_model=16,
                    num_heads=2, d_ff=32, num_encoder_layers=1,
                    num_decoder_layers=2, max_length=32, dropout=0.0)
    src = pt.to_tensor(rng.randint(3, V, (B, T)).astype("i8"))
    ids_c, sc_c = m.generate(src, beam_size=3, max_len=10, bos_id=0,
                             eos_id=1, use_cache=True)
    ids_p, sc_p = m.generate(src, beam_size=3, max_len=10, bos_id=0,
                             eos_id=1, use_cache=False)
    np.testing.assert_array_equal(np.asarray(ids_c.numpy()),
                                  np.asarray(ids_p.numpy()))
    np.testing.assert_allclose(np.asarray(sc_c.numpy()),
                               np.asarray(sc_p.numpy()), atol=1e-4)
