"""Continuous-batching generative decode (PR 15): the KV-cache pool's
slot/capacity/byte discipline, the GenerateEngine's bit-parity with
both a full-recompute reference and the classic single-sequence
``nn.decode`` stack, zero-recompile churn, the continuous-vs-drain
refill A/B, ragged-prompt coalescing in the fixed-shape engine, and
the decode-SLO supervisor scale-up. All CPU, all fast."""
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from paddle_tpu import inference, nn, serving
from paddle_tpu.io.bucketing import grow_buckets, next_bucket
from paddle_tpu.nn import decode as nnd
from paddle_tpu.serving import kv_cache
from paddle_tpu.serving import metrics as smetrics
from paddle_tpu.serving.generate import GenerateEngine, MultiDecodeEngine
from paddle_tpu.serving.supervisor import ServingSupervisor


@pytest.fixture(scope="module")
def model():
    return serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                              max_len=64, seed=1)


def _greedy_recompute(model, prompt, n, eos=None):
    """Reference decode: full-prompt recompute per step, no KV cache."""
    seq = list(prompt)
    out = []
    for _ in range(n):
        toks = jnp.asarray([seq], jnp.int32)
        _, last = model.prefill_fn(model.state, toks,
                                   jnp.asarray([len(seq)], jnp.int32))
        t = int(jnp.argmax(last, axis=-1)[0])
        seq.append(t)
        out.append(t)
        if eos is not None and t == eos:
            break
    return out


# ---------------------------------------------------------------------------
# grow_buckets (satellite 1): the closed geometric family


def test_grow_buckets_monotone_and_covers_cap():
    for base in (1, 3, 16, 64):
        for factor in (1.3, 1.5, 2.0, 3.0):
            for cap in (base, base + 1, base * 7, 1024):
                if cap < base:
                    continue
                fam = grow_buckets(base, factor, cap)
                assert fam[0] == base
                assert fam[-1] >= cap
                assert all(b < a for b, a in zip(fam, fam[1:]))
                assert all(isinstance(b, int) for b in fam)


def test_grow_buckets_stable_family_key():
    a = grow_buckets(16, 2.0, 100)
    b = grow_buckets(16, 2.0, 100)
    assert isinstance(a, tuple) and a == b and hash(a) == hash(b)
    assert a == (16, 32, 64, 128)
    # a different family never aliases the same key
    assert grow_buckets(16, 3.0, 100) != a


def test_grow_buckets_validation():
    with pytest.raises(ValueError):
        grow_buckets(0, 2.0, 8)
    with pytest.raises(ValueError):
        grow_buckets(8, 1.0, 64)
    with pytest.raises(ValueError):
        grow_buckets(8, 2.0, None)
    with pytest.raises(ValueError):
        grow_buckets(8, 2.0, 4)


def test_grow_buckets_near_one_factor_still_increases():
    fam = grow_buckets(4, 1.01, 12)
    assert all(b < a for b, a in zip(fam, fam[1:]))
    assert fam[-1] >= 12


# ---------------------------------------------------------------------------
# KVCachePool: slots, capacity schedule, byte honesty


SPEC = {"k0": ((2, 8), "float32"), "v0": ((2, 8), "float32")}


def test_pool_alloc_free_cycle():
    pool = kv_cache.KVCachePool(SPEC, slots=2, page=16, max_len=32)
    a, b = pool.alloc(), pool.alloc()
    assert {a, b} == {0, 1}
    assert pool.alloc() is None
    assert pool.used_slots() == 2 and pool.free_slots() == 0
    pool.free(a)
    assert pool.alloc() == a
    pool.free(a)
    with pytest.raises(ValueError):
        pool.free(a)


def test_pool_capacity_schedule():
    pool = kv_cache.KVCachePool(SPEC, slots=2, page=16, factor=2.0,
                                max_len=64)
    assert pool.seq_buckets == (16, 32, 64)
    assert pool.capacity == 16
    assert pool.capacity_for(16) == 16
    assert pool.capacity_for(17) == 32
    assert not pool.needs_growth(16)
    assert pool.needs_growth(33)
    with pytest.raises(ValueError):
        pool.capacity_for(65)
    with pytest.raises(ValueError):
        pool.grow_to(48, lambda bufs, old, new: bufs)  # not in family


def test_pool_bytes_accounting():
    pool = kv_cache.KVCachePool(SPEC, slots=4, page=16, factor=2.0,
                                max_len=64)
    per_tok = kv_cache.bytes_per_token(SPEC)
    assert per_tok == 2 * 2 * 8 * 4
    assert pool.bytes() == 4 * 16 * per_tok == pool.allocated_bytes()
    assert pool.max_bytes() == 4 * 64 * per_tok

    def grow(bufs, old, new):
        return {k: jnp.pad(v, [(0, 0), (0, new - old)]
                           + [(0, 0)] * (v.ndim - 2))
                for k, v in bufs.items()}

    pool.grow_to(32, grow)
    assert pool.capacity == 32
    assert pool.bytes() == pool.allocated_bytes() == 4 * 32 * per_tok
    assert pool.stats()["grows"] == 1


def test_fits_budget_and_plan_slots():
    per_tok = kv_cache.bytes_per_token(SPEC)
    need = 4 * 64 * per_tok
    fits, needed, lim = kv_cache.fits_budget(SPEC, 4, 64,
                                             limit_bytes=need)
    assert fits and needed == need and lim == need
    fits, _, _ = kv_cache.fits_budget(SPEC, 4, 64, limit_bytes=need - 1)
    assert not fits
    # reserve half the budget -> half the slots fit
    assert kv_cache.plan_slots(SPEC, 64, limit_bytes=2 * need,
                               reserve_frac=0.5) == 4
    assert kv_cache.fits_budget(SPEC, 4, 64, limit_bytes=None)[0] in \
        (None, True, False)  # no-budget CPU: never invents a verdict


# ---------------------------------------------------------------------------
# GenerateEngine: bit-parity, churn, zero recompiles


def test_engine_parity_three_way(model):
    """Engine under slot churn == full recompute == the classic
    nn.decode single-sequence stack (KVCacheCell + BasicDecoder +
    GreedyEmbeddingHelper), token for token, every request."""
    max_new = 12
    prompts = [[1, 2, 3], [5, 4, 3, 2, 1, 9, 8], [7] * 11]
    eng = GenerateEngine(model, slots=2, page=16, factor=2.0,
                         max_len=64, prompt_buckets=(4, 8, 16),
                         start=False, shed=False)
    futs = [eng.submit(p, max_new_tokens=max_new, eos_token=None)
            for p in prompts]
    for _ in range(80):
        eng.tick()
    got = [list(map(int, f.result(timeout=10))) for f in futs]
    eng.close()

    for p, toks in zip(prompts, got):
        assert toks == _greedy_recompute(model, p, max_new)

        # the single-sequence twin: prefill seeds the cell, the helper
        # feeds argmax ids back through an identity embedding
        pl = jnp.asarray([len(p)], jnp.int32)
        kv, last = model.prefill_fn(model.state,
                                    jnp.asarray([p], jnp.int32), pl)
        first = int(jnp.argmax(last, axis=-1)[0])
        cell = nnd.KVCacheCell(model.decode_fn, model.state, max_len=64)
        helper = nnd.GreedyEmbeddingHelper(
            lambda t: t, jnp.asarray([first], jnp.int32), end_token=-1)
        _, sids, _ = nnd.basic_decode(nnd.BasicDecoder(cell, helper),
                                      cell.init_states(kv, pl),
                                      max_step_num=max_new - 1)
        twin = [first] + list(map(int, np.asarray(sids.data)[0]))
        assert toks == twin


def test_engine_eos_early_stop(model):
    # seed-1 DemoLM emits 12 within a few steps for this prompt
    ref = _greedy_recompute(model, [1, 2, 3], 12, eos=12)
    assert ref[-1] == 12 and len(ref) < 12
    eng = GenerateEngine(model, slots=1, page=16, factor=2.0,
                         max_len=64, prompt_buckets=(4,),
                         start=False, shed=False)
    fut = eng.submit([1, 2, 3], max_new_tokens=12, eos_token=12)
    for _ in range(20):
        eng.tick()
    assert list(map(int, fut.result(timeout=10))) == ref
    eng.close()


def test_zero_compiles_under_churn(model):
    """Join/leave churn after warmup mints no executable and performs
    no retrace — the acceptance criterion that makes continuous
    batching TPU-viable."""
    eng = GenerateEngine(model, slots=3, page=16, factor=2.0,
                         max_len=32, prompt_buckets=(4, 8),
                         start=False, shed=False)
    eng.warmup()
    n_exec, n_trace = eng.executables()
    rng = np.random.RandomState(3)
    futs = []
    for i in range(14):
        plen = int(rng.randint(1, 9))
        futs.append(eng.submit(rng.randint(1, 31, size=plen).tolist(),
                               max_new_tokens=int(rng.randint(1, 20)),
                               eos_token=12 if i % 2 else None))
    for _ in range(120):
        eng.tick()
    for f in futs:
        assert len(f.result(timeout=10)) >= 1
    assert eng.executables() == (n_exec, n_trace)
    assert eng.pool.allocated_bytes() == eng.pool.bytes()
    eng.close()


def test_capacity_grow_is_precompiled(model):
    eng = GenerateEngine(model, slots=2, page=16, factor=2.0,
                         max_len=64, prompt_buckets=(8,),
                         start=False, shed=False)
    eng.warmup()
    n_exec, n_trace = eng.executables()
    fut = eng.submit([2] * 8, max_new_tokens=50)  # crosses 16 and 32
    for _ in range(60):
        eng.tick()
    assert len(fut.result(timeout=10)) == 50
    assert eng.pool.capacity == 64 and eng.pool.stats()["grows"] == 2
    assert eng.executables() == (n_exec, n_trace)
    eng.close()


def test_continuous_refill_beats_drain(model):
    """Same tail-skewed workload, same slots, same executables: the
    continuous engine needs strictly fewer decode ticks (it refills
    freed slots mid-flight; drain waits on the longest member), and
    runs at strictly higher slot occupancy. Tick counts are scheduling
    facts — deterministic, unlike wall-clock."""
    wl = [([1, 2, 3], 4), ([4, 5], 24), ([6], 4), ([7, 8, 9], 4),
          ([2, 4], 4), ([3], 24), ([8], 4), ([9, 1], 4)]
    stats = {}
    for mode in ("continuous", "drain"):
        eng = GenerateEngine(model, slots=2, page=32, factor=2.0,
                             max_len=32, prompt_buckets=(4,),
                             queue_depth=32, refill=mode,
                             start=False, shed=False)
        futs = [eng.submit(p, max_new_tokens=n, eos_token=None)
                for p, n in wl]
        for _ in range(200):
            eng.tick()
        for f, (_, n) in zip(futs, wl):
            assert len(f.result(timeout=10)) == n
        stats[mode] = eng.stats()
        eng.close()
    assert stats["continuous"]["ticks"] < stats["drain"]["ticks"]
    assert (stats["continuous"]["avg_occupancy"]
            > stats["drain"]["avg_occupancy"])


def test_rejects_oversized_requests(model):
    eng = GenerateEngine(model, slots=1, page=16, max_len=32,
                         prompt_buckets=(8,), start=False, shed=False)
    with pytest.raises(ValueError):
        eng.make_request([1] * 9, max_new_tokens=4)     # past bucket
    with pytest.raises(ValueError):
        eng.make_request([1] * 8, max_new_tokens=25)    # past max_len
    with pytest.raises(ValueError):
        eng.make_request([], max_new_tokens=4)
    eng.close()


def test_queue_full_fast_reject(model):
    eng = GenerateEngine(model, slots=1, page=16, max_len=32,
                         prompt_buckets=(4,), queue_depth=2,
                         start=False, shed=False)
    for _ in range(2):
        eng.submit([1, 2], max_new_tokens=4)
    with pytest.raises(serving.QueueFullError):
        eng.submit([1, 2], max_new_tokens=4)
    eng.close(drain=False)


# ---------------------------------------------------------------------------
# ragged-prompt coalescing in the fixed-shape engine (satellite 2)


def test_seq_buckets_coalesce_ragged_prompts():
    """Requests whose sequence axes differ must land in ONE batch once
    the engine pads to a shared seq bucket BEFORE signature grouping —
    and scatter back bit-exact at their real lengths."""
    model = nn.ReLU()
    eng = serving.ServingEngine(
        inference.Predictor(model), buckets=[4], max_batch=4,
        timeout_ms=200.0, seq_buckets=(8, 16))
    xs = [np.random.RandomState(i).randn(1, n, 3).astype("f4")
          for i, n in enumerate((5, 7, 8, 3))]
    futs = [eng.submit(x) for x in xs]
    outs = [f.result(timeout=30) for f in futs]
    st = eng.stats()
    eng.close()
    for x, y in zip(xs, outs):
        assert y.shape == x.shape
        np.testing.assert_array_equal(y, np.maximum(x, 0.0))
    # all four ragged lengths coalesced into a single executed batch
    assert st["batches"] == 1


def test_seq_bucket_request_fields():
    eng = serving.ServingEngine(
        inference.Predictor(nn.ReLU()), buckets=[4], max_batch=4,
        timeout_ms=1.0, seq_buckets=(8, 16))
    req = eng.make_request((np.zeros((1, 5, 3), "f4"),), 1)
    assert req.seq_real == 5 and req.seq_padded == 8
    assert req.inputs[0].shape[1] == 8
    eng.close()


# ---------------------------------------------------------------------------
# KVCacheCell seeding


def test_kv_cache_cell_init_states_pads(model):
    cell = nnd.KVCacheCell(model.decode_fn, model.state, max_len=64)
    kv, _ = model.prefill_fn(model.state,
                             jnp.asarray([[1, 2, 3]], jnp.int32),
                             jnp.asarray([3], jnp.int32))
    padded, lengths = cell.init_states(kv, jnp.asarray([3], jnp.int32))
    for name, buf in padded.items():
        assert buf.shape[1] == 64
        np.testing.assert_array_equal(np.asarray(buf[:, :3]),
                                      np.asarray(kv[name]))
    assert int(lengths[0]) == 3


# ---------------------------------------------------------------------------
# decode metrics windows


def test_decode_metrics_window_fills_without_monitor():
    smetrics.reset_windows()
    for _ in range(3):
        smetrics.record_decode_tick(2, 4, 2, 1.5)
    smetrics.record_prefill(8, 2.0, 8)
    tps, p99 = smetrics.tokens_window()
    assert tps is not None and tps > 0
    assert p99 == 1.5
    roll = smetrics.decode_rollup()
    assert roll["tokens_per_s"] == tps
    assert roll["prefill_p50_ms"] == 2.0
    assert 0 < roll["prefill_ratio"] < 1
    smetrics.reset_windows()
    assert smetrics.tokens_window() == (None, None)


# ---------------------------------------------------------------------------
# decode-SLO supervisor scaling


def _two_replica_fleet(model):
    dev = jax.devices()[0]
    return MultiDecodeEngine(
        model, devices=[dev, dev], hedge_ms=0, supervise=False,
        initial_active=1, slots=2, page=16, factor=2.0, max_len=32,
        prompt_buckets=(4,), shed=False)


def test_tokens_floor_scale_up(model):
    smetrics.reset_windows()
    fleet = _two_replica_fleet(model)
    sup = ServingSupervisor(fleet, start=False, goodput_floor=0.0,
                            tokens_floor=10_000_000.0)
    try:
        futs = [fleet.submit([1, 2, 3], max_new_tokens=6)
                for _ in range(4)]
        for f in futs:
            f.result(timeout=30)
        assert fleet._active_count() == 1
        sup.tick(fleet)
        assert fleet._active_count() == 2
        d = sup.last_decision()
        assert d["decision"] == "scale_up"
        assert d["tokens_per_s"] < d["tokens_floor"]
    finally:
        sup.stop()
        fleet.close()
        smetrics.reset_windows()


def test_idle_engine_is_not_a_breach(model):
    """No decode traffic in the window -> tokens_per_s is None -> the
    supervisor must NOT scale up on a floor it can't even measure."""
    smetrics.reset_windows()
    fleet = _two_replica_fleet(model)
    sup = ServingSupervisor(fleet, start=False, goodput_floor=0.0,
                            tokens_floor=10_000_000.0)
    try:
        sup.tick(fleet)
        assert fleet._active_count() == 1
        d = sup.last_decision()
        assert d is None or d["decision"] != "scale_up"
    finally:
        sup.stop()
        fleet.close()
