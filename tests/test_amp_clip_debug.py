"""Coverage for the aux surfaces nothing else exercised: amp.auto_cast
semantics (the context every bench runs under), clip classes, nan
guard / Print / Assert, initializer tail, regularizer L1, sequence
expand/concat (SURVEY §2 rows 14/15/27/36)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import amp, initializer as I, nn, optimizer as opt
from paddle_tpu.clip import (ClipGradByValue, ClipGradByNorm,
                             clip_grad_norm_)
from paddle_tpu.utils import debug


def test_auto_cast_flips_compute_dtype():
    """Inside auto_cast, white-listed ops (matmul/linear) compute in
    bf16 while params stay fp32 (master weights); outside, fp32."""
    import jax.numpy as jnp

    lin = nn.Linear(8, 8)
    x = pt.to_tensor(np.random.RandomState(0).randn(4, 8).astype("f4"))
    assert not amp.is_enabled()
    out_fp32 = lin(x)
    assert out_fp32.numpy().dtype == np.float32
    with amp.auto_cast(dtype="bfloat16"):
        assert amp.is_enabled()
        assert amp.compute_dtype() == jnp.bfloat16
        out_bf16 = lin(x)
        assert out_bf16.data.dtype == jnp.bfloat16
        # params are untouched master fp32
        assert lin.weight.data.dtype == jnp.float32
    assert not amp.is_enabled()
    # bf16 result approximates the fp32 one
    np.testing.assert_allclose(out_bf16.numpy().astype("f4"),
                               out_fp32.numpy(), atol=0.1)
    # maybe_cast: identity when disabled, casts floats when enabled
    a = jnp.ones((2,), jnp.float32)
    (b,) = amp.maybe_cast(a)
    assert b.dtype == jnp.float32
    with amp.auto_cast():
        (b,) = amp.maybe_cast(a)
        assert b.dtype == jnp.bfloat16
        (c,) = amp.maybe_cast(jnp.ones((2,), jnp.int32))
        assert c.dtype == jnp.int32  # non-floats pass through


def test_auto_cast_nested_restores():
    import jax.numpy as jnp
    with amp.auto_cast(dtype="bfloat16"):
        with amp.auto_cast(enable=True, dtype="float16"):
            assert amp.compute_dtype() == jnp.float16
        assert amp.compute_dtype() == jnp.bfloat16
    assert not amp.is_enabled()


def test_clip_classes():
    g = np.asarray([3.0, -4.0], "f4")  # norm 5
    pg = [(None, pt.to_tensor(g).data)]

    (_, out), = ClipGradByValue(max=2.0)(pg)
    np.testing.assert_allclose(np.asarray(out), [2.0, -2.0], atol=0)

    (_, out), = ClipGradByNorm(clip_norm=1.0)(pg)
    np.testing.assert_allclose(np.asarray(out), g / 5.0, atol=1e-6)

    # norm below the clip: unchanged
    (_, out), = ClipGradByNorm(clip_norm=10.0)(pg)
    np.testing.assert_allclose(np.asarray(out), g, atol=1e-6)

    # torch-style in-place helper over parameters
    w = pt.Parameter(np.zeros((2,), "f4"))
    w._grad = pt.to_tensor(g).data
    clip_grad_norm_([w], max_norm=1.0)
    np.testing.assert_allclose(np.asarray(w._grad), g / 5.0, atol=1e-6)


def test_optimizer_grad_clip_integration():
    """grad_clip= on the optimizer applies before the update
    (reference: minimize's grad-clip hook ordering)."""
    w = pt.Parameter(np.zeros((2,), "f4"))
    o = opt.SGD(learning_rate=1.0, parameters=[w],
                grad_clip=ClipGradByValue(max=0.1))
    (w * pt.to_tensor(np.asarray([10.0, -10.0], "f4"))).sum().backward()
    o.step()
    np.testing.assert_allclose(w.numpy(), [-0.1, 0.1], atol=1e-6)


def test_nan_guard_and_checks():
    x = pt.to_tensor(np.asarray([1.0, np.nan], "f4"))
    with pytest.raises(FloatingPointError):
        debug.check_nan_inf(x, name="x")
    ok = pt.to_tensor(np.ones((2,), "f4"))
    assert debug.check_nan_inf(ok) is False

    # Print returns its input (chainable) and Assert raises on false
    y = debug.Print(ok, message="val")
    np.testing.assert_allclose(y.numpy(), ok.numpy(), atol=0)
    with pytest.raises(AssertionError):
        debug.Assert(pt.to_tensor(np.asarray([True, False])))
    debug.Assert(pt.to_tensor(np.asarray([True, True])))

    debug.enable_nan_guard(True)
    try:
        import jax
        assert jax.config.jax_debug_nans
    finally:
        debug.enable_nan_guard(False)


def test_initializer_tail():
    pt.seed(0)
    v = np.asarray(I.TruncatedNormal(mean=1.0, std=0.5)((2000,)))
    assert np.abs(v - 1.0).max() <= 1.0 + 1e-5  # truncated at 2 std
    assert abs(v.mean() - 1.0) < 0.05

    # Bilinear: 4-D conv-transpose upsampling kernel, peak at center
    k = np.asarray(I.Bilinear()((1, 1, 4, 4)))[0, 0]
    assert k[1, 1] == k.max()
    with pytest.raises(ValueError):
        I.Bilinear()((3, 3))


def test_l1_decay_grad_term():
    from paddle_tpu import regularizer as R
    w = pt.Parameter(np.asarray([0.5, -0.5, 0.0], "f4"))
    w.regularizer = R.L1Decay(0.1)
    o = opt.SGD(learning_rate=1.0, parameters=[w])
    (w * 0.0).sum().backward()  # zero data grad: only the L1 term moves
    o.step()
    np.testing.assert_allclose(w.numpy(), [0.4, -0.4, 0.0], atol=1e-6)


def test_sequence_expand_concat():
    from paddle_tpu.ops import sequence as S
    x = np.arange(6, dtype="f4").reshape(3, 2)
    out = S.sequence_expand(pt.to_tensor(x), 2)
    np.testing.assert_allclose(out.numpy(), np.repeat(x, 2, axis=0),
                               atol=0)
    # sequence_concat joins along the TIME axis (axis=1, LoD-style)
    out = S.sequence_concat([pt.to_tensor(x), pt.to_tensor(x * 2)])
    np.testing.assert_allclose(out.numpy(),
                               np.concatenate([x, x * 2], axis=1), atol=0)
