"""Zero-downtime serving lifecycle (ISSUE 18): process-level preempt
broadcast (subscribe/notify, stacked-handler LIFO uninstall,
multi-callback attach), graceful replica + fleet drain with zero-loss
migration, rolling live weight hot-swap (live tree and validated
sharded checkpoint sources, corrupt-publish quarantine, whole-roll
unwind on probe failure, version stamping into reqtrace records), the
supervisor's ``preempt_replica`` drain decision, and the /healthz +
snapshot surfaces. All CPU, all fast; the end-to-end story (bit-exact
streams through a drain, chaos soak) lives in
scripts/lifecycle_smoke.py and scripts/soak_chaos.py."""
import os
import signal
import subprocess
import sys
import tempfile
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import inference, nn, serving
from paddle_tpu.resilience import faults, preempt
from paddle_tpu.serving import MultiDeviceEngine
from paddle_tpu.serving.multi import NoHealthyReplicaError


@pytest.fixture
def mon():
    from paddle_tpu import monitor
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


@pytest.fixture(autouse=True)
def _no_fault_leak():
    faults.clear()
    yield
    faults.clear()


def _mlp(seed=0):
    pt.seed(seed)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _fleet(n=2, seed=0, **kw):
    import jax
    kw.setdefault("max_batch", 8)
    kw.setdefault("timeout_ms", 1.0)
    kw.setdefault("supervise", False)
    kw.setdefault("hedge_ms", 0)
    return MultiDeviceEngine(inference.Predictor(_mlp(seed)),
                             devices=jax.local_devices()[:n], **kw)


# ---------------------------------------------------------------------------
# preempt.py as a process-level lifecycle signal


def test_preempt_subscribe_notify_unsubscribe(mon):
    got = []
    cb1 = preempt.subscribe(lambda sig: got.append(("a", sig)))
    cb2 = preempt.subscribe(lambda sig: got.append(("b", sig)))
    try:
        preempt.notify(signal.SIGTERM)
        assert got == [("a", signal.SIGTERM), ("b", signal.SIGTERM)]
        assert mon.registry().value("resilience.preempt.notice", 0) == 1
        preempt.unsubscribe(cb1)
        preempt.unsubscribe(cb1)            # idempotent
        preempt.notify(None)
        assert got[-1] == ("b", None) and len(got) == 3
    finally:
        preempt.unsubscribe(cb1)
        preempt.unsubscribe(cb2)


def test_preempt_broken_subscriber_does_not_block_others(mon):
    got = []

    def boom(sig):
        raise RuntimeError("subscriber bug")

    cb1 = preempt.subscribe(boom)
    cb2 = preempt.subscribe(lambda sig: got.append(sig))
    try:
        with pytest.warns(UserWarning, match="subscriber"):
            preempt.notify(signal.SIGTERM)
        assert got == [signal.SIGTERM]
    finally:
        preempt.unsubscribe(cb1)
        preempt.unsubscribe(cb2)


def test_preempt_handler_request_broadcasts(mon):
    got = []
    cb = preempt.subscribe(lambda sig: got.append(sig))
    h = preempt.PreemptionHandler(signals=())
    try:
        h.request(signal.SIGTERM)
        assert got == [signal.SIGTERM] and h.triggered
        h.request(signal.SIGTERM)           # latched: one broadcast
        assert len(got) == 1
    finally:
        preempt.unsubscribe(cb)


def test_preempt_multi_attach_accumulates_save_fns():
    h = preempt.PreemptionHandler(signals=())
    calls = []

    def save_a(step):
        calls.append(("a", step))

    h.attach(save_fn=save_a)
    h.attach(save_fn=save_a)                # dedup: registered once
    h.attach(save_fn=lambda step: calls.append(("b", step)))
    h.notify_step(7)
    h.request(signal.SIGTERM)
    assert calls == [("a", 7), ("b", 7)]
    assert h.flushed_step == 7
    h.detach(save_fn=save_a)
    assert len(h._save_fns) == 1


def test_preempt_stacked_handlers_uninstall_lifo_safe():
    """Two handlers chain on the same signal; removing the FIRST one
    must splice it out of the chain instead of clobbering the second's
    registration."""
    h1 = preempt.PreemptionHandler(signals=(signal.SIGUSR2,))
    h1.install()
    h2 = preempt.PreemptionHandler(signals=(signal.SIGUSR2,))
    h2.install()
    try:
        h1.uninstall()                      # out of order: splice
        os.kill(os.getpid(), signal.SIGUSR2)
        deadline = time.monotonic() + 5.0
        while not h2.triggered and time.monotonic() < deadline:
            time.sleep(0.01)
        assert h2.triggered and not h1.triggered
    finally:
        h2.uninstall()


# ---------------------------------------------------------------------------
# graceful drain


def test_drain_replica_migrates_and_refuses_then_readmits():
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    x = np.random.RandomState(0).rand(2, 16).astype("f4")
    try:
        futs = [eng.submit(x) for _ in range(4)]
        moved = eng.drain_replica(0, reason="test")
        assert eng._replicas[0].draining
        assert eng._replicas[0].state == "draining"
        assert eng._replicas[0].breaker.state != "open"
        for f in futs:
            f.result(10)                    # zero loss through the drain
        before = eng._replicas[0].engine.stats()["submitted"]
        for _ in range(4):
            eng.run(x, timeout=10)
        assert eng._replicas[0].engine.stats()["submitted"] == before
        assert eng.stats()["draining_replicas"] == 1
        assert eng._lifecycle["event"] == "drain" or moved >= 0
        eng.undrain_replica(0, reason="test")
        assert not eng._replicas[0].draining
        eng.run(x, timeout=10)
    finally:
        eng.close(drain=False, timeout=2.0)


def test_drain_fleet_finishes_inflight_then_sheds():
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    x = np.random.RandomState(1).rand(2, 16).astype("f4")
    try:
        futs = [eng.submit(x) for _ in range(6)]
        eng.drain_fleet(reason="test")
        for f in futs:
            f.result(10)                    # in-flight completes
        assert eng.drain_wait(timeout_s=10.0)
        with pytest.raises(NoHealthyReplicaError):
            eng.submit(x)                   # post-drain: shed, not hang
        assert eng.health()["all_open"]     # fully drained reads as
    finally:                                # refusing traffic
        eng.close(drain=False, timeout=2.0)


def test_sigterm_broadcast_drains_fleet_and_close_unsubscribes():
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    h = preempt.PreemptionHandler(signals=())
    try:
        h.request(signal.SIGTERM)
        assert all(r.draining for r in eng._replicas)
        assert eng._lifecycle["event"] == "drain_fleet"
        assert "preempt" in eng._lifecycle["reason"]
    finally:
        eng.close(drain=False, timeout=2.0)
    # closed fleet is unsubscribed: a later notify must not touch it
    h2 = preempt.PreemptionHandler(signals=())
    h2.request(signal.SIGTERM)              # would explode on a dead ref


# ---------------------------------------------------------------------------
# live weight hot-swap


def test_swap_weights_live_tree_changes_outputs_zero_compiles():
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    x = np.random.RandomState(2).rand(2, 16).astype("f4")
    try:
        y0 = np.asarray(eng.run(x, timeout=10))
        execs = [len(r.predictor._compiled) for r in eng._replicas]
        v = eng.swap_weights(inference.Predictor(_mlp(seed=7)).state)
        assert v == 1 and eng.weights_version == 1
        assert [e.weights_version for e in eng.engines] == [1, 1]
        y1 = np.asarray(eng.run(x, timeout=10))
        assert not np.allclose(y0, y1)      # new weights actually serve
        assert [len(r.predictor._compiled)
                for r in eng._replicas] == execs
        assert eng.stats()["weights_version"] == 1
        assert eng.health()["weights_version"] == 1
        assert not any(r.draining for r in eng._replicas)
        assert eng._lifecycle["event"] == "swap"
    finally:
        eng.close(drain=False, timeout=2.0)


def test_swap_weights_checkpoint_source_validates_quorum():
    import jax
    from paddle_tpu.io import sharded
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    try:
        with tempfile.TemporaryDirectory() as d:
            ck = os.path.join(d, "pub-1.sharded")
            sharded.save_state(
                ck, jax.device_get(inference.Predictor(_mlp(5)).state))
            assert eng.swap_weights(ck) == 1
            assert eng.weights_version == 1
    finally:
        eng.close(drain=False, timeout=2.0)


def test_corrupt_publish_refused_quarantined_version_unchanged(mon):
    import jax
    from paddle_tpu.io import sharded
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    try:
        with tempfile.TemporaryDirectory() as d:
            ck = os.path.join(d, "pub-bad.sharded")
            sharded.save_state(
                ck, jax.device_get(inference.Predictor(_mlp(5)).state))
            faults.inject("publish_corrupt", times=1)
            with pytest.raises(ValueError, match="quorum"):
                eng.swap_weights(ck)
            assert os.path.isdir(ck + ".corrupt")   # quarantined
            assert not os.path.isdir(ck)
        assert eng.weights_version == 0
        assert [e.weights_version for e in eng.engines] == [0, 0]
        assert eng._lifecycle["event"] == "swap_refused"
        assert mon.registry().value(
            "serving.lifecycle.swap_refused", 0) >= 1
        x = np.random.RandomState(3).rand(2, 16).astype("f4")
        eng.run(x, timeout=10)              # fleet kept serving
    finally:
        eng.close(drain=False, timeout=2.0)


def test_swap_shape_mismatch_refused():
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    pt.seed(9)
    other = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                          nn.Linear(64, 4))
    try:
        with pytest.raises(ValueError, match="shape"):
            eng.swap_weights(inference.Predictor(other).state)
        assert eng.weights_version == 0
    finally:
        eng.close(drain=False, timeout=2.0)


def test_swap_probe_failure_unwinds_the_whole_roll(monkeypatch):
    """Replica 0 swaps clean, replica 1's probe rejects the new
    weights: the roll must unwind replica 0 too — a fleet serving
    mixed weights would break bit-reproducibility."""
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    x = np.random.RandomState(4).rand(2, 16).astype("f4")
    try:
        y0 = np.asarray(eng.run(x, timeout=10))
        monkeypatch.setattr(eng.engines[1], "probe",
                            lambda timeout_s=None: False)
        with pytest.raises(RuntimeError, match="unwound"):
            eng.swap_weights(inference.Predictor(_mlp(seed=7)).state)
        assert eng.weights_version == 0
        assert [e.weights_version for e in eng.engines] == [0, 0]
        assert eng._lifecycle["event"] == "swap_failed"
        y1 = np.asarray(eng.run(x, timeout=10))
        np.testing.assert_allclose(y0, y1, rtol=1e-6)  # old weights on
    finally:                                           # EVERY replica
        eng.close(drain=False, timeout=2.0)


def test_decode_swap_stamps_weights_version_into_records(mon):
    import jax
    from paddle_tpu.serving import reqtrace
    reqtrace.reset()
    model = serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                               max_len=64, seed=1)
    eng = serving.MultiDecodeEngine(
        model, devices=jax.local_devices()[:2], slots=2, page=16,
        max_len=32, prompt_buckets=(16,), supervise=False)
    eng.warmup()
    eng.start()
    try:
        eng.submit([5, 3, 9], max_new_tokens=4, seed=1).result(30)
        swap_to = serving.demo_model(vocab=32, dim=16, heads=2,
                                     layers=2, max_len=64, seed=2)
        assert eng.swap_weights(swap_to.state) == 1
        eng.submit([5, 3, 9], max_new_tokens=4, seed=1).result(30)
        versions = [r.get("weights_version")
                    for r in reqtrace.recent()
                    if r.get("reqkind") == "decode"]
        assert 0 in versions and 1 in versions
    finally:
        eng.close(drain=False, timeout=2.0)


# ---------------------------------------------------------------------------
# supervisor: the preempt_replica fault becomes a drain decision


def test_supervisor_preempt_fault_drains_replica():
    eng = _fleet(3, supervise=True, supervisor_interval_s=0.05)
    eng.warmup([((16,), "float32")])
    x = np.random.RandomState(5).rand(2, 16).astype("f4")
    try:
        faults.inject("preempt_replica", replica=1, times=1)
        deadline = time.monotonic() + 10.0
        while (not eng._replicas[1].draining
               and time.monotonic() < deadline):
            time.sleep(0.02)
        assert eng._replicas[1].draining
        assert "drain" in [d["decision"]
                           for d in eng.supervisor.decisions]
        eng.run(x, timeout=10)              # peers keep serving
        h = eng.health()
        assert h["replicas"][1]["state"] == "draining"
        assert h["all_open"] is False
    finally:
        eng.close(drain=False, timeout=2.0)


# ---------------------------------------------------------------------------
# /healthz + snapshot surfaces


def test_healthz_draining_distinct_from_open_and_snapshot(mon):
    from paddle_tpu.monitor import export
    eng = _fleet(2)
    eng.warmup([((16,), "float32")])
    try:
        eng.drain_replica(0, reason="maintenance")
        status, payload = export.health_payload()
        rep = payload["serving"][0]["replicas"][0]
        assert rep["state"] == "draining"
        assert rep["draining"] is True
        assert rep["breaker"] != "open"
        assert status == 200                # a peer still admits
        snap = export.snapshot_payload()
        last = snap["serving"]["last_lifecycle"]
        assert last["event"] == "drain" and last["reason"] \
            == "maintenance"
    finally:
        eng.close(drain=False, timeout=2.0)


# ---------------------------------------------------------------------------
# the short chaos soak, end to end (slow: ~40s wall)


@pytest.mark.slow
def test_soak_chaos_short_mode_holds_invariants(tmp_path):
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=4")
    proc = subprocess.run(
        [sys.executable, os.path.join(root, "scripts", "soak_chaos.py"),
         "--out-dir", str(tmp_path), "--duration", "15"],
        capture_output=True, text=True, timeout=500, env=env)
    assert proc.returncode == 0, (proc.stdout or "")[-800:] + \
        (proc.stderr or "")[-800:]
