"""The reference's book chapters, end-to-end through the fluid facade
(reference: python/paddle/fluid/tests/book/*.py). Each test builds the
chapter's model in static mode (or dygraph where the book does), trains a
few steps on synthetic data, and asserts the loss drops — the ported-user
experience check."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid

layers = fluid.layers


def _run_static(build, feeds, steps=25, lr=0.1, opt_cls=None):
    """Build a program with `build()` -> loss, train `steps` on `feeds`."""
    from paddle_tpu import static, optimizer as opt
    pt.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            loss = build()
            (opt_cls or opt.SGD)(learning_rate=lr).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        losses = []
        for _ in range(steps):
            (lv,) = exe.run(main, feed=feeds, fetch_list=[loss])
            losses.append(float(np.asarray(lv)))
        return losses
    finally:
        pt.disable_static()


def test_fit_a_line():
    """reference book/test_fit_a_line.py — linear regression."""
    rng = np.random.RandomState(0)
    x = rng.rand(64, 13).astype("f4")
    y = (x @ rng.rand(13, 1)).astype("f4")

    def build():
        xd = fluid.data("x", [None, 13], "float32")
        yd = fluid.data("y", [None, 1], "float32")
        pred = layers.fc(xd, size=1)
        return layers.mean(layers.square_error_cost(pred, yd))

    losses = _run_static(build, {"x": x, "y": y}, lr=0.05)
    assert losses[-1] < losses[0] * 0.5


def test_recognize_digits_conv():
    """reference book/test_recognize_digits.py — LeNet-ish conv net."""
    pt.seed(0)
    rng = np.random.RandomState(0)
    img = rng.rand(16, 1, 28, 28).astype("f4")
    lab = rng.randint(0, 10, (16, 1)).astype("i8")

    def build():
        x = fluid.data("img", [None, 1, 28, 28], "float32")
        y = fluid.data("label", [None, 1], "int64")
        c1 = layers.conv2d(x, num_filters=6, filter_size=5, act="relu")
        p1 = layers.pool2d(c1, pool_size=2, pool_stride=2)
        c2 = layers.conv2d(p1, num_filters=16, filter_size=5, act="relu")
        p2 = layers.pool2d(c2, pool_size=2, pool_stride=2)
        pred = layers.fc(p2, size=10, act="softmax")
        return layers.mean(layers.cross_entropy(pred, y))

    losses = _run_static(build, {"img": img, "label": lab}, steps=15,
                         lr=0.1)
    assert losses[-1] < losses[0]


def test_word2vec():
    """reference book/test_word2vec.py — n-gram LM over embeddings."""
    pt.seed(0)
    rng = np.random.RandomState(1)
    V, E = 50, 16
    ctx = rng.randint(0, V, (32, 4)).astype("i8")
    nxt = rng.randint(0, V, (32, 1)).astype("i8")

    def build():
        words = fluid.data("ctx", [None, 4], "int64")
        label = fluid.data("next", [None, 1], "int64")
        emb = layers.embedding(words, size=[V, E])
        flat = layers.reshape(emb, (-1, 4 * E))
        h = layers.fc(flat, size=32, act="relu")
        pred = layers.fc(h, size=V, act="softmax")
        return layers.mean(layers.cross_entropy(pred, label))

    losses = _run_static(build, {"ctx": ctx, "next": nxt}, steps=25,
                         lr=0.2)
    assert losses[-1] < losses[0] * 0.8


def test_recommender_system():
    """reference book/test_recommender_system.py — two-tower embedding
    model with cosine similarity."""
    pt.seed(0)
    rng = np.random.RandomState(2)
    usr = rng.randint(0, 30, (32, 1)).astype("i8")
    mov = rng.randint(0, 40, (32, 1)).astype("i8")
    score = rng.rand(32, 1).astype("f4") * 5

    def build():
        u = fluid.data("usr", [None, 1], "int64")
        m = fluid.data("mov", [None, 1], "int64")
        y = fluid.data("score", [None, 1], "float32")
        ue = layers.fc(layers.reshape(
            layers.embedding(u, size=[30, 16]), (-1, 16)), size=16)
        me = layers.fc(layers.reshape(
            layers.embedding(m, size=[40, 16]), (-1, 16)), size=16)
        sim = layers.cos_sim(ue, me)
        pred = layers.scale(sim, scale=5.0)
        return layers.mean(layers.square_error_cost(pred, y))

    losses = _run_static(build, {"usr": usr, "mov": mov, "score": score},
                         steps=30, lr=0.3)
    assert losses[-1] < losses[0]


def test_understand_sentiment_conv():
    """reference book/notest_understand_sentiment.py — sequence conv net
    on padded text."""
    pt.seed(0)
    rng = np.random.RandomState(3)
    V, T = 60, 12
    sent = rng.randint(0, V, (16, T)).astype("i8")
    lab = rng.randint(0, 2, (16, 1)).astype("i8")

    def build():
        s = fluid.data("sent", [None, T], "int64")
        y = fluid.data("lab", [None, 1], "int64")
        emb = layers.embedding(s, size=[V, 16])
        conv = layers.sequence_conv(emb, num_filters=8, filter_size=3,
                                    act="relu")
        pooled = layers.sequence_pool(conv, "max")
        pred = layers.fc(pooled, size=2, act="softmax")
        return layers.mean(layers.cross_entropy(pred, y))

    losses = _run_static(build, {"sent": sent, "lab": lab}, steps=20,
                         lr=0.2)
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_label_semantic_roles_crf():
    """reference book/test_label_semantic_roles.py — BiLSTM + linear
    chain CRF (dygraph form: the static CRF path is the same op)."""
    pt.seed(0)
    rng = np.random.RandomState(4)
    B, T, V, NT = 4, 6, 40, 5
    words = rng.randint(0, V, (B, T)).astype("i4")
    tags = rng.randint(0, NT, (B, T)).astype("i4")
    lens = np.asarray([6, 5, 6, 4], "i4")

    from paddle_tpu import nn, optimizer as opt
    from paddle_tpu.ops.crf import linear_chain_crf, crf_decoding

    emb = nn.Embedding(V, 16)
    lstm = nn.LSTM(16, 8, direction="bidirect")
    proj = nn.Linear(16, NT)
    trans = pt.Parameter(np.zeros((NT + 2, NT), "f4"))
    params = (list(emb.parameters()) + list(lstm.parameters()) +
              list(proj.parameters()) + [trans])
    o = opt.Adam(learning_rate=0.05, parameters=params)

    def step():
        e = emb(pt.to_tensor(words))
        h, _ = lstm(e)
        logits = proj(h)
        nll = linear_chain_crf(logits, pt.to_tensor(tags), trans,
                               pt.to_tensor(lens))
        loss = nll.mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return float(loss.numpy())

    losses = [step() for _ in range(12)]
    assert losses[-1] < losses[0]
    # decode runs and respects lengths
    e = emb(pt.to_tensor(words))
    h, _ = lstm(e)
    path = crf_decoding(proj(h), trans, length=pt.to_tensor(lens))
    assert path.shape == [B, T]


def test_rnn_encoder_decoder():
    """reference book/test_rnn_encoder_decoder.py — GRU encoder-decoder
    trained teacher-forced (padded redesign)."""
    pt.seed(0)
    rng = np.random.RandomState(5)
    V, T, B = 40, 7, 8
    src = rng.randint(1, V, (B, T)).astype("i8")
    tgt = rng.randint(1, V, (B, T)).astype("i8")

    def build():
        s = fluid.data("src", [None, T], "int64")
        t = fluid.data("tgt", [None, T], "int64")
        semb = layers.embedding(s, size=[V, 16])
        enc = layers.dynamic_gru(layers.fc(semb, size=3 * 16,
                                           num_flatten_dims=2), size=16)
        ctx = layers.sequence_last_step(enc)
        temb = layers.embedding(t, size=[V, 16])
        dec_in = layers.concat(
            [temb, layers.expand(layers.unsqueeze(ctx, [1]), [1, T, 1])],
            axis=-1)
        dec = layers.dynamic_gru(layers.fc(dec_in, size=3 * 16,
                                           num_flatten_dims=2), size=16)
        pred = layers.fc(dec, size=V, num_flatten_dims=2, act="softmax")
        # shift-by-one LM loss on the target
        return layers.mean(layers.cross_entropy(pred, layers.unsqueeze(
            t, [2])))

    losses = _run_static(build, {"src": src, "tgt": tgt}, steps=20,
                         lr=0.5)
    assert losses[-1] < losses[0] * 0.9


@pytest.mark.slow
def test_machine_translation_beam_decode():
    """reference book/test_machine_translation.py — train briefly, then
    beam-search decode with the Transformer zoo model (the modern path the
    rebuild ships for MT)."""
    pt.seed(0)
    from paddle_tpu.models.transformer import Transformer
    from paddle_tpu import optimizer as opt
    rng = np.random.RandomState(6)
    V, B, T = 32, 4, 6
    model = Transformer(src_vocab_size=V, tgt_vocab_size=V, d_model=16,
                        num_heads=2, d_ff=32, num_encoder_layers=1,
                        num_decoder_layers=1, max_length=32)
    o = opt.Adam(learning_rate=0.01, parameters=model.parameters())
    src = pt.to_tensor(rng.randint(2, V, (B, T)).astype("i8"))
    tgt = pt.to_tensor(rng.randint(2, V, (B, T)).astype("i8"))

    def step():
        logits = model(src, tgt)
        loss = model.loss(logits, tgt)
        loss.backward()
        o.step()
        o.clear_grad()
        return float(loss.numpy())

    losses = [step() for _ in range(6)]
    assert losses[-1] < losses[0]
    out = model.generate(src, beam_size=2, max_len=8, bos_id=0, eos_id=1)
    ids = out[0] if isinstance(out, (list, tuple)) else out
    assert ids.shape[0] == B
