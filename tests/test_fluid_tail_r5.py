"""Round-5 fluid namespace tail: dygraph decay classes, legacy RNN
cells, dataset/train_from_dataset, fluid.save/load, flags, and the
small utility modules (reference: the corresponding fluid/*.py)."""
import math
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import fluid, nn, optimizer, static
from paddle_tpu.fluid import dygraph


# ---- dygraph decay classes -------------------------------------------------

def test_cosine_decay_epoch_granular():
    d = dygraph.CosineDecay(0.1, step_each_epoch=10, epochs=4)
    first = [d() for _ in range(10)]
    # whole first epoch stays at base lr (cur_epoch = 0)
    assert all(v == pytest.approx(0.1) for v in first)
    v = d()  # epoch 1
    assert v == pytest.approx(0.1 * 0.5 * (math.cos(math.pi / 4) + 1))


def test_piecewise_natural_exp_inverse_time():
    p = dygraph.PiecewiseDecay([2, 4], [1.0, 0.5, 0.1], begin=0)
    assert [p() for _ in range(5)] == [1.0, 1.0, 0.5, 0.5, 0.1]

    n = dygraph.NaturalExpDecay(1.0, decay_steps=2, decay_rate=0.5,
                                staircase=True)
    n()  # step 0
    assert n() == pytest.approx(1.0)           # floor(1/2)=0
    assert n() == pytest.approx(math.exp(-0.5))  # floor(2/2)=1

    it = dygraph.InverseTimeDecay(1.0, decay_steps=1, decay_rate=1.0)
    assert it() == pytest.approx(1.0)
    assert it() == pytest.approx(0.5)
    assert it() == pytest.approx(1 / 3)


def test_polynomial_exponential_noam_warmup():
    pd = dygraph.PolynomialDecay(1.0, decay_steps=10,
                                 end_learning_rate=0.0, power=1.0)
    assert pd() == pytest.approx(1.0)
    assert pd() == pytest.approx(0.9)

    e = dygraph.ExponentialDecay(1.0, decay_steps=1, decay_rate=0.5)
    assert e() == pytest.approx(1.0)
    assert e() == pytest.approx(0.5)
    assert e() == pytest.approx(0.25)

    nd = dygraph.NoamDecay(d_model=64, warmup_steps=4)
    vals = [nd() for _ in range(8)]
    assert np.argmax(vals) == 3  # peak at warmup boundary
    assert vals[3] == pytest.approx((64 ** -0.5) * (4 ** -0.5))

    w = dygraph.LinearLrWarmup(0.1, warmup_steps=5, start_lr=0.0,
                               end_lr=0.1)
    ramp = [w() for _ in range(4)]  # begin=1: steps 1..4
    np.testing.assert_allclose(ramp, [0.02, 0.04, 0.06, 0.08],
                               rtol=1e-6)
    assert w() == pytest.approx(0.1)  # step 5 >= warmup
    with pytest.raises(AssertionError):
        dygraph.LinearLrWarmup(0.1, 5, start_lr=1.0, end_lr=0.1)
    with pytest.raises(TypeError):
        dygraph.LinearLrWarmup("lr", 5, 0.0, 0.1)


def test_decay_drives_optimizer_per_step():
    """The optimizer advances the 1.x decay on each step() (reference
    dygraph minimize path), and checkpoints carry step_num."""
    w = pt.Parameter(np.zeros((1,), "f4"))
    decay = dygraph.PiecewiseDecay([1, 2], [1.0, 0.1, 0.01], begin=0)
    o = optimizer.SGD(learning_rate=decay, parameters=[w])
    for _ in range(3):
        (w * 1.0).sum().backward()  # grad = 1
        o.step()
        o.clear_grad()
    # steps applied lrs 1.0, 0.1, 0.01
    np.testing.assert_allclose(w.numpy(), [-1.11], rtol=1e-5)
    state = o.state_dict()
    assert state["__lr_decay__"]["step_num"] == 3
    o2 = optimizer.SGD(
        learning_rate=dygraph.PiecewiseDecay([1, 2], [1.0, 0.1, 0.01],
                                             begin=0),
        parameters=[w])
    o2.set_state_dict(state)
    assert o2._lr_decay.step_num == 3


# ---- legacy dygraph RNN cells ----------------------------------------------

def test_dygraph_lstm_cell_both_impls():
    pt.seed(0)
    for cudnn in (True, False):
        cell = dygraph.LSTMCell(8, 4, use_cudnn_impl=cudnn)
        x = pt.to_tensor(np.random.randn(2, 4).astype("f4"))
        h = pt.to_tensor(np.zeros((2, 8), "f4"))
        c = pt.to_tensor(np.zeros((2, 8), "f4"))
        nh, nc = cell(x, h, c)
        assert tuple(nh.shape) == (2, 8) and tuple(nc.shape) == (2, 8)
        nh.sum().backward()
        grads = [p.grad for p in cell.parameters() if p.grad is not None]
        assert grads and all(np.isfinite(np.asarray(g)).all()
                             for g in grads)


def test_dygraph_gru_cell_both_impls():
    pt.seed(1)
    for cudnn in (True, False):
        cell = dygraph.GRUCell(8, 4, use_cudnn_impl=cudnn)
        x = pt.to_tensor(np.random.randn(2, 4).astype("f4"))
        h = pt.to_tensor(np.zeros((2, 8), "f4"))
        nh = cell(x, h)
        assert tuple(nh.shape) == (2, 8)
        nh.sum().backward()
        grads = [p.grad for p in cell.parameters() if p.grad is not None]
        assert grads and all(np.isfinite(np.asarray(g)).all()
                             for g in grads)


def test_declarative_decorator():
    lin = nn.Linear(4, 2)

    @dygraph.declarative
    def f(x):
        return lin(x) * 2.0

    x = pt.to_tensor(np.ones((3, 4), "f4"))
    out = f(x)
    ref = (lin(x) * 2.0).numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5)
    assert callable(dygraph.dygraph_to_static_func(lambda x: x))


# ---- fluid.dataset + train_from_dataset ------------------------------------

def _write_multislot(path, n=16):
    rng = np.random.RandomState(0)
    with open(path, "w") as fh:
        for _ in range(n):
            x = rng.rand(2)
            y = [x[0] * 2 + x[1]]
            fh.write(f"2 {x[0]:.4f} {x[1]:.4f} 1 {y[0]:.4f}\n")


def test_inmemory_dataset_batches(tmp_path):
    f = tmp_path / "a.txt"
    _write_multislot(str(f), n=10)
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(4)
    ds.set_filelist([str(f)])

    class V:
        def __init__(self, name):
            self.name = name
    ds.set_use_var([V("x"), V("y")])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 10
    ds.local_shuffle()
    batches = list(ds._batches())
    assert [b["x"].shape for b in batches] == [(4, 2), (4, 2), (2, 2)]
    assert batches[0]["y"].shape == (4, 1)


def test_preload_into_memory_matches_serial_load(tmp_path):
    """preload_into_memory(thread_num) + wait_preload_done must produce
    the exact record store load_into_memory builds — same count, same
    order, same batch contents — on both the native-columnar and the
    python-record parse paths."""
    files = []
    for i in range(4):
        f = tmp_path / f"p{i}.txt"
        _write_multislot(str(f), n=6)
        files.append(str(f))

    class V:
        def __init__(self, name):
            self.name = name

    def make(native=True):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(5)
        ds.set_filelist(files)
        ds.set_use_var([V("x"), V("y")])
        ds.use_native_parse = native
        return ds

    for native in (True, False):
        a = make(native)
        a.load_into_memory()
        b = make(native)
        b.preload_into_memory(thread_num=4)
        b.wait_preload_done()
        assert a.get_memory_data_size() == b.get_memory_data_size() == 24
        for ba, bb in zip(a._batches(), b._batches()):
            for k in ba:
                np.testing.assert_array_equal(ba[k], bb[k])
    # wait without a preload in flight is a no-op, and double-wait is safe
    b.wait_preload_done()


@pytest.mark.slow
def test_preload_into_memory_thread_scaling(tmp_path):
    """4 preload threads must cut wall-clock >= 2x over 1 thread. The
    per-file cost is pinned in the pipe command (a GIL-releasing
    subprocess wait), so the bound is deterministic on any host — the
    only way to beat the serial floor is genuinely concurrent file
    loads."""
    import time
    files = []
    for i in range(8):
        f = tmp_path / f"s{i}.txt"
        _write_multislot(str(f), n=4)
        files.append(str(f))

    class V:
        def __init__(self, name):
            self.name = name

    def run(threads):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_filelist(files)
        ds.set_use_var([V("x"), V("y")])
        ds.set_pipe_command("sleep 0.2; cat")
        t0 = time.perf_counter()
        ds.preload_into_memory(thread_num=threads)
        ds.wait_preload_done()
        elapsed = time.perf_counter() - t0
        assert ds.get_memory_data_size() == 32
        return elapsed

    serial = run(1)     # >= 8 * 0.2s by construction
    parallel = run(4)   # ideal ~2 waves of 0.2s
    assert serial / parallel >= 2.0, (serial, parallel)


def test_queue_dataset_shuffle_raises(tmp_path):
    ds = fluid.DatasetFactory().create_dataset("QueueDataset")
    with pytest.raises(NotImplementedError):
        ds.local_shuffle()
    with pytest.raises(ValueError):
        fluid.DatasetFactory().create_dataset("NoSuchDataset")


def test_train_from_dataset(tmp_path):
    f = tmp_path / "train.txt"
    _write_multislot(str(f), n=32)
    pt.enable_static()
    try:
        prog = static.Program()
        startup = static.Program()
        with static.program_guard(prog, startup):
            x = static.data("x", [None, 2], "float32")
            y = static.data("y", [None, 1], "float32")
            pred = fluid.layers.fc(x, size=1)
            loss = fluid.layers.reduce_mean(
                fluid.layers.square(pred - y))
            optimizer.SGD(learning_rate=0.1).minimize(loss)
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(8)
        ds.set_filelist([str(f)])
        ds.set_use_var([x, y])
        ds.load_into_memory()
        exe = static.Executor()
        exe.run(startup)
        losses = []
        for _ in range(20):
            exe.train_from_dataset(prog, ds, fetch_list=[loss])
            out, = exe.run(prog, feed={"x": np.zeros((1, 2), "f4"),
                                       "y": np.zeros((1, 1), "f4")},
                           fetch_list=[loss])
            losses.append(float(out))
        assert losses[-1] < losses[0]
    finally:
        pt.disable_static()


# ---- fluid.save / fluid.load ------------------------------------------------

def test_fluid_save_load_roundtrip(tmp_path):
    pt.enable_static()
    try:
        prog = static.Program()
        startup = static.Program()
        with static.program_guard(prog, startup):
            x = static.data("x", [None, 3], "float32")
            yv = fluid.layers.fc(x, size=2)
            loss = fluid.layers.reduce_mean(yv)
            optimizer.Adam(learning_rate=0.01).minimize(loss)
        exe = static.Executor()
        exe.run(startup)
        feed = {"x": np.ones((4, 3), "f4")}
        exe.run(prog, feed=feed, fetch_list=[loss])
        before = {n: v.numpy().copy()
                  for n, v in prog.param_vars.items()}
        opt_before = prog.optimizers[0][0].state_dict()
        fluid.save(prog, str(tmp_path / "model"))
        assert (tmp_path / "model.pdparams").exists()  # exact suffix
        assert (tmp_path / "model.pdopt").exists()  # Adam has slots
        # perturb then restore through the same prefix save used
        for v in prog.param_vars.values():
            v.set_value(np.zeros_like(v.numpy()))
        fluid.load(prog, str(tmp_path / "model"))
        for n, v in prog.param_vars.items():
            np.testing.assert_allclose(v.numpy(), before[n])
        # optimizer slot state restored too (moment slots roundtrip)
        opt_after = prog.optimizers[0][0].state_dict()
        restored = {k: v for k, v in opt_after.items()
                    if hasattr(v, "numpy")}
        assert restored  # Adam created moment slots
        for k, v in restored.items():
            np.testing.assert_allclose(
                np.asarray(v.numpy()),
                np.asarray(opt_before[k].numpy()))
        with pytest.raises(ValueError):
            fluid.save(prog, str(tmp_path) + "/")
    finally:
        pt.disable_static()


# ---- flags / misc utility modules ------------------------------------------

def test_set_get_flags():
    fluid.set_flags({"FLAGS_eager_delete_tensor_gb": 1.5})
    assert fluid.get_flags("FLAGS_eager_delete_tensor_gb") == {
        "FLAGS_eager_delete_tensor_gb": 1.5}
    out = fluid.get_flags(["FLAGS_eager_delete_tensor_gb",
                           "FLAGS_use_mkldnn"])
    assert out["FLAGS_use_mkldnn"] is False
    with pytest.raises(TypeError):
        fluid.set_flags(["FLAGS_use_mkldnn"])
    with pytest.raises(TypeError):
        fluid.get_flags(3)
    with pytest.raises(ValueError):
        fluid.get_flags("FLAGS_never_heard_of_it")
    with pytest.raises(RuntimeError):
        fluid.framework.load_op_library("libcustom.so")
    with pytest.raises(RuntimeError):
        with fluid.profiler.cuda_profiler("out.txt"):
            pass


def test_lod_tensor_constructors():
    t = fluid.create_lod_tensor(np.ones((5, 3), "f4"), [[2, 3]], None)
    assert t.recursive_sequence_lengths() == [[2, 3]]
    assert t.has_valid_recursive_sequence_lengths()
    with pytest.raises(AssertionError):
        fluid.create_lod_tensor(np.ones((5, 3), "f4"), [[2, 2]], None)
    r = fluid.create_random_int_lodtensor([[2, 1]], [4], None, 0, 9)
    assert tuple(r.shape) == (3, 4)
    arr = r.numpy()
    assert arr.min() >= 0 and arr.max() <= 9


def test_weighted_average_and_helpers(capsys):
    from paddle_tpu.fluid.average import WeightedAverage
    wa = WeightedAverage()
    with pytest.raises(ValueError):
        wa.eval()
    wa.add(1.0, 1)
    wa.add(3.0, 3)
    assert wa.eval() == pytest.approx(2.5)
    with pytest.raises(ValueError):
        wa.add("x", 1)

    from paddle_tpu.fluid.annotations import deprecated

    @deprecated(since="1.0", instead="new_api")
    def old(v):
        return v + 1

    assert old(1) == 2
    assert "deprecated since 1.0" in capsys.readouterr().err

    from paddle_tpu.fluid.log_helper import get_logger
    import logging
    lg = get_logger("t5", logging.INFO, fmt="%(message)s")
    assert get_logger("t5", logging.INFO) is lg
    assert len(lg.handlers) == 1  # no duplicate handlers

    from paddle_tpu.fluid.wrapped_decorator import (
        wrap_decorator, signature_safe_contextmanager)

    def dec(f):
        def inner(*a):
            return f(*a) * 10
        return inner

    @wrap_decorator(dec)
    def g(v):
        """doc"""
        return v

    assert g(2) == 20 and g.__doc__ == "doc"

    @signature_safe_contextmanager
    def ctx(v):
        yield v * 2

    with ctx(3) as got:
        assert got == 6


def test_default_scope_funcs():
    from paddle_tpu.fluid import default_scope_funcs as dsf
    base = dsf.get_cur_scope()
    dsf.enter_local_scope()
    dsf.var("a")
    dsf.get_cur_scope().vars["a"] = 7
    assert dsf.find_var("a") == 7
    dsf.leave_local_scope()
    assert dsf.get_cur_scope() is base
    assert dsf.scoped_function(lambda: 42) == 42


def test_fetch_handler_surface():
    from paddle_tpu.fluid.trainer_factory import (FetchHandler,
                                                  FetchHandlerMonitor)
    with pytest.raises(ValueError):
        FetchHandler(None)

    class V:
        name = "v"
    h = FetchHandler(var_dict={"v": V()}, period_secs=60)
    scope = static.Scope()
    scope.vars["v"] = 3
    m = FetchHandlerMonitor(scope, h)
    m.start()
    m.stop()
    from paddle_tpu.fluid.trainer_desc import DownpourSGDOPT
    from paddle_tpu.fluid import device_worker
    assert device_worker.DownpourSGDOPT is DownpourSGDOPT


# ---- review-pass regressions -------------------------------------------------

def test_fluid_embedding_callable():
    """fluid.embedding (input.py signature, incl. is_distributed) must
    actually run, not just resolve."""
    ids = pt.to_tensor(np.array([[1], [3]], "i4"))
    out = fluid.embedding(ids, (10, 4), is_distributed=True)
    assert tuple(out.shape)[-1] == 4


def test_static_mode_rejects_dygraph_decay():
    pt.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            x = static.data("x", [None, 2], "float32")
            loss = fluid.layers.reduce_mean(fluid.layers.fc(x, size=1))
            o = optimizer.SGD(
                learning_rate=dygraph.ExponentialDecay(0.1, 1, 0.5))
            with pytest.raises(TypeError, match="dygraph-only"):
                o.minimize(loss)
    finally:
        pt.disable_static()


def test_decay_get_lr_before_first_step():
    w = pt.Parameter(np.zeros((1,), "f4"))
    o = optimizer.SGD(
        learning_rate=dygraph.PiecewiseDecay([5], [0.3, 0.1], begin=0),
        parameters=[w])
    assert o.get_lr() == pytest.approx(0.3)


def test_dataset_int_slots_preserve_large_ids(tmp_path):
    big = 2 ** 24 + 1  # not representable in float32
    f = tmp_path / "ids.txt"
    f.write_text(f"2 {big} 7 1 0.5\n")

    class V:
        def __init__(self, name, dtype):
            self.name, self.dtype = name, dtype
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(1)
    ds.set_filelist([str(f)])
    ds.set_use_var([V("ids", "int64"), V("val", "float32")])
    ds.load_into_memory()
    batch = next(iter(ds._batches()))
    assert batch["ids"].dtype == np.int64
    assert batch["ids"][0, 0] == big
    assert batch["val"].dtype == np.float32


def test_dataset_pipe_command_blank_lines(tmp_path):
    f = tmp_path / "p.txt"
    f.write_text("1 1.0 1 2.0\n\n1 3.0 1 4.0\n")

    class V:
        def __init__(self, name):
            self.name = name
    ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
    ds.set_batch_size(2)
    ds.set_filelist([str(f)])
    ds.set_pipe_command("sed s/x/x/")  # non-cat pipe passthrough
    ds.set_use_var([V("a"), V("b")])
    ds.load_into_memory()
    assert ds.get_memory_data_size() == 2  # blank line skipped


# ---- top-level paddle tail (r5 full-tree sweep) -----------------------------

def test_unbind_and_diag_embed():
    t = pt.to_tensor(np.arange(6).reshape(2, 3).astype("f4"))
    parts = pt.unbind(t, axis=0)
    assert len(parts) == 2 and tuple(parts[0].shape) == (3,)
    np.testing.assert_allclose(parts[1].numpy(), [3, 4, 5])
    s = (parts[0] * 2).sum()
    s.backward()  # differentiable through the list output

    d = pt.diag_embed(pt.to_tensor(np.array([1., 2.], "f4")), offset=1)
    np.testing.assert_allclose(
        d.numpy(), [[0, 1, 0], [0, 0, 2], [0, 0, 0]])


def test_compose_not_aligned_exception():
    from paddle_tpu import reader

    def r1():
        yield from [1, 2, 3]

    def r2():
        yield from [4, 5]

    with pytest.raises(reader.ComposeNotAligned):
        list(reader.compose(r1, r2)())
    assert issubclass(reader.ComposeNotAligned, ValueError)
    got = list(reader.compose(r1, r2, check_alignment=False)())
    assert got == [(1, 4), (2, 5)]


def test_utils_profiler_classes():
    from paddle_tpu.utils import (Profiler, ProfilerOptions, get_profiler,
                                  Ploter)
    opts = ProfilerOptions({"state": "CPU"})
    assert opts["state"] == "CPU"
    assert opts["profile_path"] is None  # 'none' -> None
    with pytest.raises(ValueError):
        opts["no_such_option"]
    p = Profiler(enabled=False)
    with p:
        p.record_step()
    assert p.batch_id == 0  # disabled: no counting
    assert get_profiler() is not None

    pl = Ploter("train", "test")
    pl.append("train", 0, 1.0)
    pl.append("train", 1, 0.5)
    with pytest.raises(ValueError):
        pl.append("nope", 0, 1.0)
    assert pl.__plot_data__["train"].value == [1.0, 0.5]
    pl.reset()
    assert pl.__plot_data__["train"].value == []


def test_fs_wrapper_localfs(tmp_path):
    from paddle_tpu.distributed.fs_wrapper import FS, LocalFS, BDFS
    fs = LocalFS()
    d = tmp_path / "a"
    fs.mkdir(str(d))
    assert fs.stat(str(d))
    (d / "x.txt").write_text("hi")
    assert fs.ls_dir(str(d)) == ["x.txt"]
    assert fs.list_dirs(str(tmp_path)) == ["a"]
    fs.download(str(d / "x.txt"), str(tmp_path / "y.txt"))
    assert (tmp_path / "y.txt").read_text() == "hi"
    fs.delete(str(d))
    assert not fs.stat(str(d))
    assert not fs.need_upload_download()
    assert issubclass(LocalFS, FS)
    with pytest.raises(RuntimeError):
        BDFS()


def test_dataset_tail_helpers(tmp_path, monkeypatch):
    from paddle_tpu.dataset import common, imdb, movielens

    monkeypatch.chdir(tmp_path)

    def rdr():
        yield from range(25)

    files = common.split(rdr, 10)
    assert len(files) >= 2
    back = sorted(common.cluster_files_reader(
        str(tmp_path / "*.pickle"), 1, 0)())
    assert back == list(range(25))
    # two trainers partition the files disjointly
    a = list(common.cluster_files_reader(str(tmp_path / "*.pickle"),
                                         2, 0)())
    b = list(common.cluster_files_reader(str(tmp_path / "*.pickle"),
                                         2, 1)())
    assert sorted(a + b) == list(range(25))

    assert imdb.build_dict() == imdb.word_dict()
    assert len(movielens.movie_categories()) == movielens.NUM_CATEGORIES
    assert len(movielens.get_movie_title_dict()) == movielens.TITLE_VOCAB


def test_nn_functional_one_x_surface():
    from paddle_tpu.nn import functional as F
    x = pt.to_tensor(np.array([[-1.0, 0.5]], "f4"))
    out = F.logsigmoid(x)
    np.testing.assert_allclose(
        out.numpy(), np.log(1 / (1 + np.exp([[1.0, -0.5]]))), rtol=1e-5)
    assert callable(F.roi_align) and callable(F.yolov3_loss)
    assert callable(F.noam_decay) and callable(F.tanh_shrink)


def test_profiler_batch_range_starts_mid_run(monkeypatch):
    """Review regression: batch_range [2, 3] must START the trace at
    batch 2 (the old `_current_profiler is self` gate never did)."""
    from paddle_tpu.utils import profiler as prof
    calls = []

    def fake_start(**kw):
        calls.append("start")
        prof._profiling_active = True

    def fake_stop(**kw):
        calls.append("stop")
        prof._profiling_active = False

    monkeypatch.setattr(prof, "start_profiler", fake_start)
    monkeypatch.setattr(prof, "stop_profiler", fake_stop)
    monkeypatch.setattr(prof, "_profiling_active", False)
    opts = prof.ProfilerOptions({"batch_range": [2, 3]})
    with prof.Profiler(enabled=True, options=opts) as p:
        for _ in range(4):
            p.record_step()
    assert "start" in calls, calls
    assert calls.index("start") < calls.index("stop")


def test_dataset_native_parse_matches_python(tmp_path):
    """The C MultiSlot parser (csrc ptc_multislot_parse) and the python
    fallback produce identical batches — including full-range int64 ids
    a float64 lane would corrupt — and both reject malformed text."""
    big = 2 ** 62 + 12345  # beyond float64's 2^53 exact-integer range
    f = tmp_path / "m.txt"
    f.write_text(
        f"2 {big} 7 2 0.5 -1.25\n"
        "1 42 1 3.75\n"
        "\n"  # blank lines are plain whitespace in the token stream
        "3 1 2 3 0\n")  # zero-count float slot

    class V:
        def __init__(self, name, dtype):
            self.name, self.dtype = name, dtype

    def load(use_native):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(4)
        ds.set_filelist([str(f)])
        ds.set_use_var([V("ids", "int64"), V("x", "float32")])
        ds.use_native_parse = use_native
        ds.load_into_memory()
        return list(ds._batches())

    native_b = load(True)
    python_b = load(False)
    assert len(native_b) == len(python_b) == 1
    for key in ("ids", "x"):
        np.testing.assert_array_equal(native_b[0][key], python_b[0][key])
    assert native_b[0]["ids"].dtype == np.int64
    assert native_b[0]["ids"][0, 0] == big  # exact through the i64 lane

    # malformed: truncated record
    from paddle_tpu.io import native
    with pytest.raises(ValueError):
        native.multislot_parse(b"2 1.0", 2, [False, False])
    with pytest.raises(ValueError):
        native.multislot_parse(b"x 1.0 1 2.0", 2, [False, False])


def test_dataset_native_rejects_misaligned_tokens(tmp_path):
    """Review regression: a float count token ('1.5') must be rejected
    by BOTH parsers, not silently consumed as count 1 + value 0.5."""
    from paddle_tpu.io import native
    with pytest.raises(ValueError):
        native.multislot_parse(b"1.5 2.0 3.0", 1, [False])

    f = tmp_path / "bad.txt"
    f.write_text("1.5 2.0 3.0\n")

    class V:
        def __init__(self, name):
            self.name = name
    for use_native in (True, False):
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_filelist([str(f)])
        ds.set_use_var([V("x")])
        ds.use_native_parse = use_native
        with pytest.raises(ValueError):
            ds.load_into_memory()


def test_dataset_columnar_batches_match_python_after_shuffle(tmp_path):
    """The columnar (native-parse) batch assembler must produce the
    SAME batches as the python record path — including after
    local_shuffle (both draw the same RandomState permutation)."""
    f = tmp_path / "c.txt"
    rng = np.random.RandomState(3)
    with open(f, "w") as fh:
        for _ in range(23):
            n = rng.randint(1, 5)
            ids = rng.randint(0, 10**7, n)
            fh.write(f"{n} " + " ".join(map(str, ids)) +
                     f" 1 {rng.rand():.4f}\n")

    class V:
        def __init__(self, name, dtype):
            self.name, self.dtype = name, dtype

    def batches(use_native):
        pt.seed(7)  # same shuffle seed both paths
        ds = fluid.DatasetFactory().create_dataset("InMemoryDataset")
        ds.set_batch_size(6)
        ds.set_filelist([str(f)])
        ds.set_use_var([V("ids", "int64"), V("x", "float32")])
        ds.use_native_parse = use_native
        ds.load_into_memory()
        ds.local_shuffle()
        return list(ds._batches())

    nat = batches(True)
    py = batches(False)
    assert len(nat) == len(py) == 4  # 23 records / 6
    for a, b in zip(nat, py):
        for key in ("ids", "x"):
            np.testing.assert_array_equal(a[key], b[key])
    assert nat[0]["ids"].dtype == np.int64
