"""Regression tests for code-review findings (round 1)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, io
from paddle_tpu.nn import functional as F


def test_conv2d_transpose_nhwc_matches_nchw():
    x = np.random.randn(2, 8, 5, 5).astype("f4")
    w = np.random.randn(8, 4, 3, 3).astype("f4")  # IOHW
    ref = F.conv2d_transpose(pt.to_tensor(x), pt.to_tensor(w),
                             stride=2).numpy()
    out = F.conv2d_transpose(pt.to_tensor(x.transpose(0, 2, 3, 1)),
                             pt.to_tensor(w), stride=2,
                             data_format="NHWC").numpy()
    np.testing.assert_allclose(out.transpose(0, 3, 1, 2), ref, atol=1e-4)


def test_cross_entropy_negative_ignore_index():
    logits = np.random.randn(6, 4).astype("f4")
    labels = np.array([0, 1, -1, 2, -1, 3])
    loss = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels),
                           ignore_index=-1)
    # equals mean over the 4 valid positions only
    valid = labels >= 0
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    ref = -logp[np.arange(6), np.clip(labels, 0, 3)][valid].mean()
    np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-5)


def test_cross_entropy_class_weight():
    logits = np.random.randn(4, 3).astype("f4")
    labels = np.array([0, 1, 2, 1])
    w = np.array([1.0, 2.0, 0.5], "f4")
    loss = F.cross_entropy(pt.to_tensor(logits), pt.to_tensor(labels),
                           weight=pt.to_tensor(w))
    logp = logits - np.log(np.exp(logits).sum(-1, keepdims=True))
    per = -logp[np.arange(4), labels] * w[labels]
    ref = per.sum() / w[labels].sum()
    np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-5)


def test_multinomial_without_replacement_unique():
    probs = pt.to_tensor(np.ones((3, 10), "f4") / 10)
    out = pt.multinomial(probs, num_samples=8, replacement=False).numpy()
    for row in out:
        assert len(set(row.tolist())) == 8


def test_adaptive_pool_non_divisible():
    x = pt.to_tensor(np.random.randn(1, 2, 7, 7).astype("f4"))
    out = F.adaptive_avg_pool2d(x, 3)
    assert out.shape == [1, 2, 3, 3]
    # paddle formula: bucket [floor(i*H/os), ceil((i+1)*H/os))
    xn = x.numpy()
    np.testing.assert_allclose(out.numpy()[0, 0, 0, 0],
                               xn[0, 0, 0:3, 0:3].mean(), rtol=1e-5)
    outm = F.adaptive_max_pool2d(x, 3)
    np.testing.assert_allclose(outm.numpy()[0, 1, 2, 2],
                               xn[0, 1, 4:7, 4:7].max(), rtol=1e-5)


def test_save_dygraph_routes_opt_state(tmp_path):
    from paddle_tpu import optimizer as opt
    m = nn.Linear(2, 2)
    o = opt.Adam(learning_rate=0.1, parameters=m.parameters())
    m(pt.to_tensor(np.ones((1, 2), "f4"))).mean().backward()
    o.step()
    base = str(tmp_path / "ck")
    io.save_dygraph(m.state_dict(), base)
    io.save_dygraph(o.state_dict(), base)
    params, optstate = io.load_dygraph(base)
    assert params is not None and "weight" in params
    assert optstate is not None and "lr" in optstate


def test_double_backward_shared_subgraph_raises():
    w = pt.Parameter(np.ones(2, "f4"))
    shared = w * 2.0
    l1 = (shared * 3.0).sum()
    l2 = (shared * 5.0).sum()
    l1.backward()
    with pytest.raises(RuntimeError, match="freed"):
        l2.backward()


def test_bce_elementwise_weight():
    p = pt.to_tensor(np.array([0.9, 0.1], "f4"))
    y = pt.to_tensor(np.array([1.0, 0.0], "f4"))
    w = pt.to_tensor(np.array([2.0, 1.0], "f4"))
    loss = F.binary_cross_entropy(p, y, weight=w, reduction="sum")
    ref = -(2.0 * np.log(0.9) + 1.0 * np.log(0.9))
    np.testing.assert_allclose(float(loss.numpy()), ref, rtol=1e-4)
