"""Sharded, topology-elastic checkpoints + the elastic recovery loop
(paddle_tpu.io.sharded / resilience.elastic): per-shard save with a
checksummed manifest, quorum fallback on missing/corrupt shards,
restore onto a different dp×tp factorization bit-identically, the
SIGTERM signal-path flush, and host-loss → mesh-shrink → resume."""
import os
import time
import warnings

import numpy as np
import pytest
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import hapi, io, monitor, nn, optimizer as popt
from paddle_tpu.io import CheckpointManager, TensorDataset
from paddle_tpu.io import sharded as shio
from paddle_tpu.parallel import collective, layout
from paddle_tpu.resilience import (ElasticSupervisor, HostLossError,
                                   PreemptionHandler, faults)


@pytest.fixture(autouse=True)
def _clean():
    faults.clear()
    yield
    faults.clear()
    collective.set_mesh(None)


@pytest.fixture
def jsonl(tmp_path):
    path = str(tmp_path / "events.jsonl")
    monitor.enable(path)
    yield path
    monitor.disable()


def _toy():
    rng = np.random.RandomState(0)
    w = rng.randn(8, 3)
    x = rng.randn(64, 8).astype("f4")
    y = (x @ w).argmax(-1).astype("i4")
    return TensorDataset(x, y)


def _model(mesh=None, tp="tp"):
    """The resilience-test toy model; with a mesh, weights go tp-column
    sharded so sharded saves produce real multi-file shards."""
    pt.seed(7)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    m = hapi.Model(net)
    if mesh is not None:
        for p in m.parameters():
            if p.data.ndim == 2 and all(
                    d % mesh.shape[tp] == 0 for d in (p.shape[0],)):
                collective.shard(p, P(tp, None), mesh)
            else:
                collective.replicated(p, mesh)
    m.prepare(optimizer=popt.SGD(learning_rate=0.05,
                                 parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    return m


def _params(m):
    return {n: np.asarray(p.numpy()) for n, p in m.network.named_parameters()
            } if hasattr(m, "network") else {
        n: np.asarray(p.numpy()) for n, p in m.named_parameters()}


# -- layout math ------------------------------------------------------------

def test_mesh_signature_and_equality():
    mesh = collective.make_mesh({"dp": 4, "tp": 2})
    sig = layout.mesh_signature(mesh)
    assert sig["axes"] == {"dp": 4, "tp": 2} and sig["n_devices"] == 8
    mesh2 = collective.make_mesh({"dp": 2, "tp": 4})
    assert not layout.same_signature(sig, layout.mesh_signature(mesh2))
    assert layout.same_signature(sig, dict(sig, platform="tpu"))


def test_spec_lists_roundtrip():
    lists = layout.spec_to_lists(P("dp", None, ("tp", "dp")), 4)
    assert lists == [["dp"], None, ["tp", "dp"], None]
    assert tuple(layout.spec_from_lists(lists))[:3] == \
        tuple(P("dp", None, ("tp", "dp")))[:3]


def test_adapt_spec_degrades_never_fails():
    mesh = collective.make_mesh({"dp": 2, "tp": 2},
                                devices=jax.devices()[:4])
    # unknown axis dropped
    spec, changed = layout.adapt_spec([["sp"], ["tp"]], (8, 8), mesh)
    assert tuple(spec) == (None, "tp") and changed
    # non-divisible dim falls back to replication
    spec, changed = layout.adapt_spec([["dp"], None], (7, 8), mesh)
    assert tuple(spec) == (None, None) and changed
    # clean fit passes through
    spec, changed = layout.adapt_spec([["dp"], ["tp"]], (8, 8), mesh)
    assert tuple(spec) == ("dp", "tp") and not changed


# -- sharded format ---------------------------------------------------------

def test_sharded_save_layout_and_manifest(tmp_path):
    mesh = collective.make_mesh({"dp": 4, "tp": 2})
    x = jax.device_put(np.arange(64, dtype="f4").reshape(8, 8),
                       NamedSharding(mesh, P("dp", "tp")))
    man = shio.save_state(str(tmp_path / "ck"), {"w": x, "step": 3},
                          step=3)
    d = tmp_path / "ck"
    assert (d / "manifest.json").is_file()
    npys = sorted(f for f in os.listdir(d) if f.endswith(".npy"))
    assert len(npys) == 8  # one unique shard per device position
    assert man["mesh"]["axes"] == {"dp": 4, "tp": 2}
    ok, why = shio.validate(str(d))
    assert ok, why
    state, man2 = shio.load_state(str(d))
    assert np.array_equal(state["w"], np.asarray(x))
    assert state["step"] == 3 and man2["step"] == 3


def test_sharded_vs_unsharded_bit_identical(tmp_path):
    mesh = collective.make_mesh({"dp": 4, "tp": 2})
    m = _model(mesh)
    m.fit(_toy(), batch_size=16, epochs=1, verbose=0, shuffle=False)
    want = _params(m)

    cm_s = CheckpointManager(str(tmp_path / "s"), sharded=True)
    cm_p = CheckpointManager(str(tmp_path / "p"))
    cm_s.save(0, model=m, optimizer=m._optimizer)
    cm_p.save(0, model=m, optimizer=m._optimizer)

    r_s, r_p = _model(mesh), _model(mesh)
    cm_s.restore(model=r_s, optimizer=r_s._optimizer)
    cm_p.restore(model=r_p, optimizer=r_p._optimizer)
    for n in want:
        got_s, got_p = _params(r_s)[n], _params(r_p)[n]
        assert np.array_equal(got_s, want[n]), n
        assert np.array_equal(got_s, got_p), n


def test_restore_onto_resized_meshes_bit_identical(tmp_path, jsonl):
    mesh = collective.make_mesh({"dp": 4, "tp": 2})
    m = _model(mesh)
    m.fit(_toy(), batch_size=16, epochs=1, verbose=0, shuffle=False)
    want = _params(m)
    cm = CheckpointManager(str(tmp_path), sharded=True)
    cm.save(4, model=m, optimizer=m._optimizer)

    for axes, ndev in (({"dp": 2, "tp": 4}, 8), ({"dp": 2, "tp": 2}, 4)):
        mesh2 = collective.make_mesh(axes, devices=jax.devices()[:ndev])
        m2 = _model(mesh2)
        state = cm.restore(model=m2, optimizer=m2._optimizer)
        assert state["step"] == 4
        for n, v in _params(m2).items():
            assert np.array_equal(v, want[n]), (axes, n)
        # restored params live on the NEW mesh
        p0 = next(iter(m2.parameters()))
        assert p0.data.sharding.mesh.shape == mesh2.shape
    events = [r for r in monitor.read_jsonl(jsonl)
              if r.get("kind") == "ckpt"
              and r.get("event") == "restore_resharded"]
    assert events, "resized restores must emit ckpt.restore_resharded"


def test_place_true_reshards_standalone(tmp_path):
    mesh = collective.make_mesh({"dp": 4, "tp": 2})
    x = jax.device_put(np.arange(64, dtype="f4").reshape(8, 8),
                       NamedSharding(mesh, P("dp", "tp")))
    shio.save_state(str(tmp_path / "ck"), {"w": x}, step=0)
    mesh2 = collective.make_mesh({"dp": 2, "tp": 4})
    state, _ = shio.load_state(str(tmp_path / "ck"), mesh=mesh2,
                               place=True)
    assert np.array_equal(np.asarray(state["w"]), np.asarray(x))
    assert state["w"].sharding.mesh.shape == mesh2.shape


# -- quorum rule ------------------------------------------------------------

def _two_sharded_saves(tmp_path):
    mesh = collective.make_mesh({"dp": 4, "tp": 2})
    m = _model(mesh)
    cm = CheckpointManager(str(tmp_path), sharded=True)
    cm.save(1, model=m, optimizer=m._optimizer)
    m.fit(_toy(), batch_size=16, epochs=1, verbose=0, shuffle=False)
    cm.save(2, model=m, optimizer=m._optimizer)
    return cm, m, mesh


def test_missing_shard_falls_back_to_complete(tmp_path, jsonl):
    cm, m, mesh = _two_sharded_saves(tmp_path)
    d2 = cm._sharded_path(2)
    os.remove(os.path.join(d2, sorted(
        f for f in os.listdir(d2) if f.endswith(".npy"))[0]))
    assert cm.latest_step() == 1
    m2 = _model(mesh)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        state = cm.restore(model=m2, optimizer=m2._optimizer)
    assert state["step"] == 1
    assert os.path.isdir(d2 + ".corrupt")  # quarantined, never wins
    events = [r for r in monitor.read_jsonl(jsonl)]
    assert any(r.get("event") == "quorum_fallback" for r in events)
    assert any(r.get("event") == "ckpt_quarantine" for r in events)


def test_bad_checksum_falls_back_to_complete(tmp_path):
    cm, m, mesh = _two_sharded_saves(tmp_path)
    d2 = cm._sharded_path(2)
    shard = sorted(f for f in os.listdir(d2) if f.endswith(".npy"))[0]
    faults.garble_file(os.path.join(d2, shard))
    ok, why = shio.validate(d2)
    assert not ok and "checksum" in why
    m2 = _model(mesh)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        state = cm.restore(model=m2, optimizer=m2._optimizer)
    assert state["step"] == 1


def test_explicit_corrupt_step_raises(tmp_path):
    cm, m, _mesh = _two_sharded_saves(tmp_path)
    d2 = cm._sharded_path(2)
    os.remove(os.path.join(d2, "manifest.json"))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(ValueError):
            cm.restore(model=m, step=2)


def test_in_progress_tmp_skipped_silently(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    m = _model()
    cm.save(3, model=m)
    # step 5: a truncated final + a warm .tmp == save in progress
    bad = cm._path(5)
    with open(bad, "wb") as f:
        f.write(b"partial")
    with open(bad + ".tmp", "wb") as f:
        f.write(b"still writing")
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any warning fails the test
        assert cm.latest_step() == 3
    # the same state 2 minutes later is a crashed save: warn as corrupt
    old = time.time() - 120
    os.utime(bad + ".tmp", (old, old))
    with pytest.warns(UserWarning, match="corrupt"):
        assert cm.latest_step() == 3


# -- faults + metrics -------------------------------------------------------

def test_shard_corrupt_fault_breaks_quorum(tmp_path):
    mesh = collective.make_mesh({"dp": 4, "tp": 2})
    m = _model(mesh)
    cm = CheckpointManager(str(tmp_path), sharded=True)
    spec = faults.inject("shard_corrupt", step=7)
    cm.save(7, model=m)
    assert spec.fired == 1
    ok, why = shio.validate(cm._sharded_path(7))
    assert not ok and "checksum" in why


def test_shard_slow_write_and_metrics(tmp_path, jsonl):
    spec = faults.inject("shard_slow_write", times=None, delay=0.01)
    m = _model()
    cm = CheckpointManager(str(tmp_path), sharded=True)
    t0 = time.perf_counter()
    cm.save(0, model=m)
    assert time.perf_counter() - t0 >= 0.01
    assert spec.fired >= 1
    snap = monitor.snapshot("ckpt.")
    assert snap["ckpt.shard_bytes"] > 0
    assert snap["ckpt.shard_seconds"]["count"] >= 1


def test_host_loss_fault_raises_typed_error():
    faults.inject("host_loss", step=2, lost=4)
    with pytest.raises(HostLossError) as ei:
        _model().fit(_toy(), batch_size=16, epochs=1, verbose=0,
                     shuffle=False)
    assert ei.value.lost == 4


# -- preempt flush ----------------------------------------------------------

def test_signal_flush_saves_last_completed_step(jsonl):
    saved = []
    h = PreemptionHandler().attach(save_fn=saved.append)
    h.notify_step(4)
    h.request(signum=15)
    assert saved == [4] and h.flushed_step == 4
    events = [r for r in monitor.read_jsonl(jsonl)
              if r.get("event") == "preempt_save"]
    assert events and events[0]["step"] == 4
    assert events[0]["where"] == "signal_flush"


def test_signal_flush_failure_never_raises():
    def boom(step):
        raise OSError("disk gone")
    h = PreemptionHandler().attach(save_fn=boom)
    h.notify_step(1)
    with pytest.warns(UserWarning, match="final save"):
        h.request(signum=15)
    assert h.triggered and h.flushed_step is None


# -- elastic recovery loop --------------------------------------------------

def test_elastic_resize_resumes_exact_next_step(tmp_path, jsonl):
    cm = CheckpointManager(str(tmp_path), sharded=True)
    sup = ElasticSupervisor(checkpoint=cm, mesh_axes={"dp": 4, "tp": 2},
                            max_restarts=2)
    faults.inject("host_loss", step=5, lost=4)

    def train(attempt):
        m = _model(attempt.mesh)
        return m.fit(_toy(), batch_size=16, epochs=3, verbose=0,
                     shuffle=False, checkpoint=cm, save_steps=2,
                     auto_resume=attempt.auto_resume)

    sup.run(train)
    assert [a.axes for a in sup.attempts] == \
        [{"dp": 4, "tp": 2}, {"dp": 2, "tp": 2}]
    events = monitor.read_jsonl(jsonl)
    kinds = [r.get("event") for r in events]
    assert "elastic_restart" in kinds and "elastic_resize" in kinds
    # host died at step 5; last periodic save was step 3 → resume at 4
    resumes = [r for r in events if r.get("event") == "auto_resume"]
    assert resumes and resumes[-1]["step"] == 4
    resized = [r for r in events if r.get("event") == "elastic_resize"]
    assert resized[0]["planned"] == {"dp": 2, "tp": 2}


def test_elastic_budget_exhaustion_reraises(tmp_path):
    cm = CheckpointManager(str(tmp_path), sharded=True)
    sup = ElasticSupervisor(checkpoint=cm, mesh_axes={"dp": 4, "tp": 2},
                            max_restarts=0)
    faults.inject("host_loss", step=1)

    def train(attempt):
        m = _model(attempt.mesh)
        return m.fit(_toy(), batch_size=16, epochs=1, verbose=0,
                     shuffle=False, checkpoint=cm,
                     auto_resume=attempt.auto_resume)

    with pytest.raises(HostLossError):
        sup.run(train)
