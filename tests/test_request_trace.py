"""Request-scoped tracing (reqtrace): the per-request stage waterfall,
exactly-once terminal records across hedges/retries, TTFT/TPOT
semantics, failover hop lineage, the per-slot decode timeline export,
exemplar rings, and the slo.ttft/tpot rollup. All CPU, all fast."""
import json
import time

import pytest

from paddle_tpu import monitor, serving
from paddle_tpu.serving import metrics as smetrics
from paddle_tpu.serving import reqtrace
from paddle_tpu.serving.generate import GenerateEngine
from paddle_tpu.serving.reqtrace import RECON_TOL


@pytest.fixture(autouse=True)
def _clean():
    monitor.disable(flush_counters=False)
    monitor.trace.disable()
    monitor.trace.clear()
    reqtrace.reset()
    yield
    monitor.disable(flush_counters=False)
    monitor.trace.disable()
    monitor.trace.clear()
    reqtrace.reset()


@pytest.fixture
def mon():
    monitor.enable()        # in-memory: no sink, records still mint
    smetrics.reset_windows()
    yield
    monitor.disable(flush_counters=False)


@pytest.fixture(scope="module")
def model():
    return serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                              max_len=64, seed=1)


def _drive(eng, reqs, max_ticks=400):
    for _ in range(max_ticks):
        eng.tick()
        if all(r.future.done() for r in reqs):
            return
    raise AssertionError("engine did not drain")


# ---------------------------------------------------------------------------
# disabled mode: the one-flag-check contract


def test_disabled_mints_no_trace(model):
    assert reqtrace.new_trace() is None
    assert reqtrace.attach(None, kind="decode") is None
    eng = GenerateEngine(model, slots=1, page=16, factor=2.0, max_len=64,
                         prompt_buckets=(4,), start=False, shed=False)
    req = eng.make_request([1, 2], max_new_tokens=3, eos_token=None)
    assert req.trace is None
    eng.submit_request(req)
    _drive(eng, [req])
    eng.close()
    assert len(req.future.result(timeout=5)) == 3
    assert reqtrace.recent() == []


# ---------------------------------------------------------------------------
# the stage machine: blame-derived attribution reconciles by construction


def test_stage_sum_reconciles_exactly(mon):
    att = reqtrace.attach(None, kind="decode", priority=1)
    time.sleep(0.01)
    att.to("prefill")
    time.sleep(0.01)
    att.first_token()
    time.sleep(0.01)
    att.note_tokens(5)
    rec = att.finalize("ok")
    assert rec["outcome"] == "ok" and rec["origin"] == "submit"
    assert rec["recon"] == pytest.approx(1.0, abs=1e-3)
    assert rec["stage_sum_ms"] == pytest.approx(rec["e2e_ms"], rel=1e-3)
    for stage in ("queue_ms", "prefill_ms", "decode_ms"):
        assert rec[stage] > 0
    # ttft is the prefill exit, not the submit or the completion
    assert 0 < rec["ttft_ms"] < rec["e2e_ms"]
    assert rec["tpot_ms"] == pytest.approx(
        (rec["e2e_ms"] - rec["ttft_ms"]) / 4, rel=1e-2)


def test_serve_kind_ttft_is_e2e(mon):
    att = reqtrace.attach(None, kind="serve")
    time.sleep(0.005)
    rec = att.finalize("ok")
    assert rec["reqkind"] == "serve"
    assert rec["ttft_ms"] == rec["e2e_ms"]
    assert rec["tpot_ms"] is None


def test_failed_outcome_has_no_slo_fields(mon):
    att = reqtrace.attach(None, kind="decode")
    rec = att.finalize("error", error="boom")
    assert rec["outcome"] == "error" and rec["error"] == "boom"
    assert rec["ttft_ms"] is None and rec["tpot_ms"] is None
    # even a request that died in queue reconciles
    assert rec["recon"] == pytest.approx(1.0, abs=1e-2)


# ---------------------------------------------------------------------------
# exactly once: the done-latch across attempts


def test_double_finalize_is_swallowed(mon):
    att = reqtrace.attach(None, kind="decode")
    first = att.finalize("ok")
    assert first is not None
    assert att.finalize("error", error="late loser") is None
    assert att.ctx.record() is first
    assert len(reqtrace.recent()) == 1


def test_hedge_shadow_shares_context_one_record(mon):
    primary = reqtrace.attach(None, kind="decode")
    ctx = primary.ctx
    time.sleep(0.01)
    shadow = ctx.attempt("hedge", replica=1)
    ctx.hop("hedge", replica=1)
    shadow.first_token()
    shadow.note_tokens(3)
    rec = shadow.finalize("ok")          # the shadow wins the race
    assert primary.finalize("ok") is None
    assert len(reqtrace.recent()) == 1
    assert rec["origin"] == "hedge" and rec["attempts"] == 2
    # the submit->dispatch gap is blamed on the hedge stage
    assert rec["hedge_ms"] >= 9.0
    assert any(h["hop"] == "hedge" for h in rec["hops"])
    # a post-finalize transition on the loser can't corrupt the record
    primary.to("prefill")
    assert ctx.record() is rec


def test_shed_retry_continuity(mon):
    att = reqtrace.attach(None, kind="decode", priority=2)
    att.shed(level=1, retry_after_ms=5.0)
    time.sleep(0.01)                      # caller backoff before resubmit
    retry = reqtrace.attach(att, kind="decode")   # resubmit w/ same trace
    assert retry.ctx is att.ctx
    retry.first_token()
    retry.note_tokens(2)
    rec = retry.finalize("ok")
    assert rec["origin"] == "retry"
    assert rec["attempts"] == 2 and rec["sheds"] == 1
    assert rec["shed_retry_ms"] >= 9.0    # the backoff gap is blamed
    assert any(h["hop"] == "shed" and h["level"] == 1
               for h in rec["hops"])
    assert rec["recon"] == pytest.approx(1.0, abs=RECON_TOL)


# ---------------------------------------------------------------------------
# engine integration: real records off a real decode engine


def test_engine_decode_record_waterfall(model, mon):
    eng = GenerateEngine(model, slots=2, page=16, factor=2.0, max_len=64,
                         prompt_buckets=(4, 8), start=False, shed=False)
    req = eng.make_request([1, 2, 3], max_new_tokens=6, eos_token=None)
    assert req.trace is not None
    eng.submit_request(req)
    _drive(eng, [req])
    eng.close()
    assert len(req.future.result(timeout=5)) == 6
    rec = req.trace.ctx.record()
    assert rec is not None
    assert rec["reqkind"] == "decode" and rec["outcome"] == "ok"
    assert rec["tokens"] == 6
    assert rec["ttft_ms"] is not None and rec["tpot_ms"] is not None
    assert rec["prefill_ms"] > 0 and rec["decode_ms"] > 0
    assert abs(rec["recon"] - 1.0) <= RECON_TOL
    assert rec["hops"][0]["hop"] == "enqueue"


def test_engine_churn_exactly_one_record_each(model, mon):
    eng = GenerateEngine(model, slots=2, page=16, factor=2.0, max_len=64,
                         prompt_buckets=(4, 8), start=False, shed=False)
    reqs = []
    for i in range(16):
        r = eng.make_request([1 + i % 7, 2, 3][: 1 + i % 3],
                             max_new_tokens=2 + i % 5, eos_token=None)
        eng.submit_request(r)
        reqs.append(r)
    _drive(eng, reqs)
    eng.close()
    recs = [r.trace.ctx.record() for r in reqs]
    assert all(rec is not None for rec in recs)
    rids = [rec["rid"] for rec in recs]
    assert len(set(rids)) == 16
    emitted = [rec["rid"] for rec in reqtrace.recent()]
    assert sorted(emitted) == sorted(rids)      # no lost, no duplicate
    assert all(rec["outcome"] == "ok" for rec in recs)
    assert all(abs(rec["recon"] - 1.0) <= RECON_TOL for rec in recs)


def test_engine_requeue_failover_lineage(model, mon):
    """A failed-over request re-enters at queue front with a requeue hop
    and its stage machine back in queue; ttft re-stamps on re-prefill."""
    eng = GenerateEngine(model, slots=1, page=16, factor=2.0, max_len=64,
                         prompt_buckets=(4,), start=False, shed=False)
    req = eng.make_request([3, 1], max_new_tokens=3, eos_token=None)
    req.trace.to("prefill")               # pretend a first dispatch began
    eng.requeue([req])                    # supervisor failover path
    _drive(eng, [req])
    eng.close()
    rec = req.trace.ctx.record()
    assert rec["outcome"] == "ok"
    assert any(h["hop"] == "requeue" for h in rec["hops"])
    assert rec["ttft_ms"] is not None


# ---------------------------------------------------------------------------
# per-slot decode timeline + flow arrows in the Chrome export


def test_slot_lanes_and_flow_arrows(model, mon, tmp_path):
    monitor.trace.enable()
    eng = GenerateEngine(model, slots=2, page=16, factor=2.0, max_len=64,
                         prompt_buckets=(4, 8), start=False, shed=False)
    reqs = []
    for i in range(4):
        r = eng.make_request([1 + i, 2], max_new_tokens=3, eos_token=None)
        eng.submit_request(r)
        reqs.append(r)
    _drive(eng, reqs)
    eng.close()

    lanes = monitor.trace.lanes()
    assert any(name.startswith("kv.slot") for name in lanes)
    path = str(tmp_path / "trace.json")
    monitor.trace.export_chrome_trace(path)
    evs = json.load(open(path))["traceEvents"]

    lane_tids = {lanes[n] for n in lanes if n.startswith("kv.slot")}
    occupancy = [e for e in evs if e.get("ph") == "X"
                 and e.get("tid") in lane_tids]
    assert len(occupancy) >= 4            # >=1 interval per request
    assert {e["tid"] for e in occupancy} == lane_tids   # every slot lane
    named = [e for e in evs if e.get("ph") == "M"
             and e.get("name") == "thread_name"
             and e.get("tid") in lane_tids]
    assert len(named) == len(lane_tids)

    starts = {e["id"] for e in evs if e.get("ph") == "s"}
    ends = {e["id"] for e in evs if e.get("ph") == "f"}
    assert starts & ends                  # at least one linked arrow
    assert all(e.get("bp") == "e" for e in evs if e.get("ph") == "f")


# ---------------------------------------------------------------------------
# exemplar rings, rollup gauges, bucket family


def test_exemplar_rings_bounded_and_sorted(mon, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_REQ_EXEMPLARS", "3")
    for ttft in (5.0, 50.0, 20.0, 80.0, 1.0, 35.0):
        reqtrace._remember({"rid": f"r{ttft}", "ttft_ms": ttft,
                            "tpot_ms": ttft / 10.0})
    ex = reqtrace.exemplars()
    assert ex["cap"] == 3
    assert [r["ttft_ms"] for r in ex["worst_ttft"]] == [80.0, 50.0, 35.0]
    assert [r["tpot_ms"] for r in ex["worst_tpot"]] == [8.0, 5.0, 3.5]
    assert len(reqtrace.recent()) == 6    # the recent buffer keeps all


def test_slo_rollup_and_snapshot_surface(mon):
    att = reqtrace.attach(None, kind="decode")
    att.first_token()
    att.note_tokens(4)
    att.finalize("ok")
    roll = smetrics.slo_rollup()
    assert roll["ttft_p50_ms"] is not None
    assert roll["ttft_p99_ms"] is not None
    assert roll["tpot_p99_ms"] is not None
    from paddle_tpu.monitor import export
    snap = export.snapshot_payload()
    assert "slow_requests" in snap
    assert snap["slow_requests"]["worst_ttft"]


def test_latency_bucket_family():
    b = smetrics.LATENCY_BUCKETS_MS
    assert b[0] == pytest.approx(0.001)
    assert b[-1] == pytest.approx(10000.0)
    assert all(x < y for x, y in zip(b, b[1:]))
    # three buckets per decade, sub-ms through 10s
    assert len(b) == 22
