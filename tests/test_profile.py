"""paddle_tpu.monitor.profile — HLO parse → per-op attribution, roofline
classification, fusion-menu ranking, ceilings, and the disabled-mode
zero-cost contract."""
import json

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import jit, monitor, nn, optimizer as opt
import paddle_tpu.nn.functional as F
from paddle_tpu.monitor import profile
from paddle_tpu.monitor.registry import read_jsonl


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """profile + monitor are process-global; every test starts dark."""
    for var in ("PADDLE_TPU_FLOPS_CEILING", "PADDLE_TPU_HBM_GBPS",
                "PADDLE_TPU_ROOFLINE_DEVICE", "PADDLE_TPU_PROFILE"):
        monkeypatch.delenv(var, raising=False)
    monitor.disable(flush_counters=False)
    monitor.reset()
    profile.disable()
    profile.reset()
    yield
    monitor.disable(flush_counters=False)
    monitor.reset()
    profile.disable()
    profile.reset()


# -- synthetic HLO for the parser units --------------------------------------

DOT_HLO = """\
HloModule test, is_scheduled=true

ENTRY %main.1 (a: f32[4,8], b: f32[8,16]) -> f32[4,16] {
  %a = f32[4,8]{1,0} parameter(0)
  %b = f32[8,16]{1,0} parameter(1)
  ROOT %dot.1 = f32[4,16]{1,0} dot(f32[4,8]{1,0} %a, f32[8,16]{1,0} %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/jit(main)/root/L0/dot_general"}
}
"""

FUSED_HLO = """\
HloModule test2, is_scheduled=true

%fused_computation (p0: f32[4,8]) -> f32[4,8] {
  %p0 = f32[4,8]{1,0} parameter(0)
  %exp.1 = f32[4,8]{1,0} exponential(f32[4,8]{1,0} %p0), metadata={op_name="jit(f)/jit(main)/root/F.softmax/exp"}
  ROOT %add.1 = f32[4,8]{1,0} add(f32[4,8]{1,0} %exp.1, f32[4,8]{1,0} %p0), metadata={op_name="jit(f)/jit(main)/root/transpose(jvp(F.softmax))/add"}
}

%region.1 (x: f32[], y: f32[]) -> f32[] {
  %x = f32[] parameter(0)
  %y = f32[] parameter(1)
  ROOT %add.2 = f32[] add(f32[] %x, f32[] %y)
}

ENTRY %main.2 (a: f32[4,8]) -> f32[4] {
  %a = f32[4,8]{1,0} parameter(0)
  %fus = f32[4,8]{1,0} fusion(f32[4,8]{1,0} %a), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(f)/jit(main)/root/F.softmax/add"}
  %c0 = f32[] constant(0)
  ROOT %reduce.1 = f32[4]{0} reduce(f32[4,8]{1,0} %fus, f32[] %c0), dimensions={1}, to_apply=%region.1, metadata={op_name="jit(f)/jit(main)/root/F.softmax/reduce_sum"}
}
"""


def test_parse_dot_flops_and_bytes():
    profile.register_scope("root", "root")
    profile.register_scope("L0", "layer")
    a = profile.attribute(DOT_HLO)
    assert a["total_flops"] == 2 * (4 * 16) * 8       # 2·out·K
    assert a["attributed_frac"] == 1.0
    (row,) = a["ops"]
    assert row["opcode"] == "dot"
    assert row["region"] == "L0"
    # operands (128 + 512) + output 256 bytes, f32
    assert row["bytes"] == 4 * (4 * 8 + 8 * 16 + 4 * 16)


def test_parse_fusion_reduce_transcendentals():
    profile.register_scope("root", "root")
    profile.register_scope("F.softmax", "functional")
    a = profile.attribute(FUSED_HLO)
    rows = {r["name"]: r for r in a["ops"]}
    # fusion = inner add (32 flops) + inner exp (32 transcendentals);
    # the transpose(jvp(...)) wrapper still resolves to F.softmax
    assert rows["fus"]["flops"] == 32
    assert rows["fus"]["transcendentals"] == 32
    assert rows["fus"]["region"] == "F.softmax"
    # reduce = in − out, its to_apply region body is folded, not counted
    assert rows["reduce.1"]["flops"] == 32 - 4
    assert a["total_flops"] == 32 + 28
    assert a["transcendentals"] == 32
    assert a["attributed_frac"] == 1.0


def test_unregistered_scopes_bucket_as_unattributed():
    # nothing registered: the root/L0 tokens mean nothing -> 0% attributed
    a = profile.attribute(DOT_HLO)
    assert a["attributed_frac"] == 0.0
    assert a["ops"][0]["region"] == profile.UNATTRIBUTED


def test_root_scope_never_attributes():
    # only the root is registered — everything under it must still
    # bucket as unattributed (the ≥90% bar must not be trivially true)
    profile.register_scope("root", "root")
    a = profile.attribute(DOT_HLO)
    assert a["attributed_frac"] == 0.0


# -- roofline ceilings --------------------------------------------------------

def test_roofline_ceilings_known_kind():
    c = profile.roofline_ceilings("TPU v5p")
    assert c["peak_flops"] == 459e12
    assert c["hbm_bytes_per_sec"] == 2765e9
    assert not c["assumed"]
    assert c["ridge_flops_per_byte"] == pytest.approx(459e12 / 2765e9)


def test_roofline_ceilings_unknown_kind_assumes_v5e():
    c = profile.roofline_ceilings("M2 Ultra")
    assert c["assumed"]
    assert "assumed" in c["device_kind"]
    assert c["peak_flops"] == 197e12
    assert c["hbm_bytes_per_sec"] == 819e9


def test_roofline_ceilings_env_overrides(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLOPS_CEILING", "2e12")
    monkeypatch.setenv("PADDLE_TPU_HBM_GBPS", "100")
    c = profile.roofline_ceilings("whatever")
    assert c["peak_flops"] == 2e12
    assert c["hbm_bytes_per_sec"] == 100e9
    assert not c["assumed"]          # both ceilings pinned by the user


def test_roofline_device_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_ROOFLINE_DEVICE", "TPU v4")
    c = profile.roofline_ceilings()
    assert c["peak_flops"] == 275e12
    assert c["hbm_bytes_per_sec"] == 1228e9
    assert not c["assumed"]


def test_step_bandwidth_lookup_and_env(monkeypatch):
    from paddle_tpu.monitor import step as mstep
    assert mstep.ceilings_for_kind("TPU v5 lite")[1] == 819e9
    assert mstep.ceilings_for_kind("TPU v6e")[0] == 918e12
    assert mstep.ceilings_for_kind("cpu") == (None, None)
    monkeypatch.setenv("PADDLE_TPU_HBM_GBPS", "123")
    assert mstep.peak_hbm_bandwidth_for_device() == 123e9


# -- roofline classification boundaries ---------------------------------------

def test_classification_boundaries():
    ceil = {"peak_flops": 1.0, "hbm_bytes_per_sec": 1.0,
            "ridge_flops_per_byte": 1.0, "device_kind": "unit",
            "assumed": False}
    mk = lambda f, b: {"flops": float(f), "bytes": float(b),
                       "transcendentals": 0.0}
    above, below, at = profile._rooflined(
        [mk(100, 10), mk(10, 100), mk(50, 50)], ceil)
    assert above["bound"] == "compute" and above["headroom_s"] == 0.0
    assert below["bound"] == "memory"
    assert below["headroom_s"] == pytest.approx(100.0 - 10.0)
    assert below["mfu"] == pytest.approx(0.1)
    assert at["bound"] == "compute"   # exactly on the ridge: compute


def test_report_classifies_with_env_roofline(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLOPS_CEILING", "1e9")
    monkeypatch.setenv("PADDLE_TPU_HBM_GBPS", "1")     # ridge = 1 F/B
    profile.register_scope("root", "root")
    profile.register_scope("L0", "layer")
    rep = profile.report(hlo=DOT_HLO)
    (row,) = rep["ops"]
    # dot: 1024 flops / 896 bytes -> AI > ridge -> compute-bound
    assert row["bound"] == "compute"
    assert row["arith_intensity"] == pytest.approx(1024 / 896)
    assert rep["hotspots"][0]["region"] == "L0"


# -- hlo_text truncation (satellite fix) --------------------------------------

def test_hlo_text_truncates_at_line_boundary(tmp_path):
    import jax
    import jax.numpy as jnp
    monitor.enable(str(tmp_path))
    fn = jax.jit(lambda x: jnp.tanh(x) @ x)
    monitor.xla.aot_capture(fn, "trunc", (np.eye(8, dtype="float32"),))
    full = monitor.xla.hlo_text("trunc", max_bytes=0) or \
        monitor.xla.executable("trunc").as_text()
    # big enough that whole lines fit under the limit (the first
    # HloModule header line alone is a few hundred bytes)
    cut = monitor.xla.hlo_text("trunc", max_bytes=len(full) // 2)
    assert cut is not None and cut != full
    body, tail = cut.rstrip("\n").rsplit("\n", 1)
    assert tail.startswith("... [truncated ") and tail.endswith(" bytes]")
    # every byte up to the marker is a prefix of whole lines
    assert full.startswith(body)
    assert full[len(body)] == "\n"
    dropped = int(tail.split("[truncated ")[1].split(" ")[0])
    assert dropped == len(full) - len(body)


# -- end-to-end: jitted MLP + Adam on CPU -------------------------------------

def _mlp_step(tmp_path, hidden=32):
    monitor.enable(str(tmp_path))
    profile.enable()
    model = nn.Sequential(nn.Linear(16, hidden), nn.ReLU(),
                          nn.Linear(hidden, 10))
    adam = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

    @jit.to_static(models=[model], optimizers=[adam])
    def step(x, y):
        logits = model(x)
        loss = F.cross_entropy(logits, y)
        loss.backward()
        adam.step()
        return loss

    x = pt.to_tensor(np.random.RandomState(0).randn(8, 16)
                     .astype("float32"))
    y = pt.to_tensor(np.arange(8).astype("int64") % 10)
    step(x, y)
    return step


def test_mlp_adam_attribution_and_reconciliation(tmp_path):
    _mlp_step(tmp_path)
    rep = profile.report(top_k=8)
    assert rep is not None and rep["label"] == "jit.step"
    assert rep["label"] in monitor.xla.labels()
    # every flop lands in a named scope or the <unattributed> bucket,
    # and the parser's total agrees with XLA's own count within 1%
    assert rep["attributed_frac"] >= 0.90
    assert rep["flops_reconciliation"] == pytest.approx(1.0, abs=0.01)
    total = sum(o["flops"] for o in rep["ops"])
    assert total == pytest.approx(rep["total_flops"])
    regions = {r["region"] for r in rep["regions"]}
    # the SURVEY §2 fusion candidates surface from measurement
    assert "opt.Adam" in regions
    assert "F.cross_entropy" in regions
    assert any("Linear_0" in r for r in regions)
    for o in rep["ops"]:
        assert o["bound"] in ("compute", "memory")
        assert o["est_time_s"] >= 0
    # hotspot JSONL records landed in the sink
    recs = [r for r in read_jsonl(monitor.jsonl_path())
            if r.get("kind") == "hotspot"]
    assert recs and recs[0]["rank"] == 1
    assert {r["region"] for r in recs} <= regions
    # /snapshot surfaces the evidence pointers
    snap = monitor.export.snapshot_payload()
    assert snap["xla_cost"]["last_label"] == "jit.step"
    assert "jit.step" in snap["xla_cost"]["labels"]
    assert snap["hotspots"]["attributed_frac"] >= 0.90
    assert snap["hotspots"]["hotspots"][0]["rank"] == 1


def test_ranking_stable_across_reports(tmp_path):
    _mlp_step(tmp_path)
    r1 = profile.report(top_k=10)
    r2 = profile.report(top_k=10)
    order1 = [(h["rank"], h["region"]) for h in r1["hotspots"]]
    order2 = [(h["rank"], h["region"]) for h in r2["hotspots"]]
    assert order1 == order2
    assert [h["rank"] for h in r1["hotspots"]] == \
        list(range(1, len(order1) + 1))
    # headroom is monotonically non-increasing down the menu
    heads = [h["headroom_s"] for h in r1["hotspots"]]
    assert heads == sorted(heads, reverse=True)


def test_layer_scope_names_stable_per_instance(tmp_path):
    profile.enable()
    l0, l1 = nn.Linear(4, 4), nn.Linear(4, 4)
    x = pt.to_tensor(np.zeros((2, 4), dtype="float32"))
    l0(x), l1(x), l0(x)
    assert l0._profile_scope == "Linear_0"
    assert l1._profile_scope == "Linear_1"
    scopes = profile.scopes()
    assert scopes["Linear_0"] == "layer" and scopes["Linear_1"] == "layer"
    # a reset keeps instance names on re-entry instead of renumbering
    profile.reset()
    l0(x)
    assert l0._profile_scope == "Linear_0"
    assert profile.scopes()["Linear_0"] == "layer"


def test_format_table_renders(tmp_path):
    _mlp_step(tmp_path)
    rep = profile.report()
    table = profile.format_table(rep)
    assert "opt.Adam" in table and "region" in table
    assert "attributed" in table
    assert profile.format_table(None).startswith("profile: no captured")


def test_flight_record_bundles_op_ledger(tmp_path):
    _mlp_step(tmp_path)
    profile.report()
    d = monitor.trace.flight_record("test", directory=str(tmp_path / "fl"))
    assert d is not None
    ledger = json.load(open(f"{d}/op_ledger.json"))
    assert ledger["label"] == "jit.step"
    assert float(ledger["attributed_frac"]) >= 0.90


# -- disabled mode: one flag check, nothing else ------------------------------

def test_disabled_mode_no_scope_no_parse(monkeypatch):
    assert profile.scopes_on is False
    bomb = lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("profiling touched while disabled"))
    monkeypatch.setattr(profile, "layer_scope", bomb)
    monkeypatch.setattr(profile, "fscope", bomb)
    monkeypatch.setattr(profile, "optimizer_scope", bomb)
    monkeypatch.setattr(profile, "parse_hlo", bomb)
    model = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
    adam = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

    @jit.to_static(models=[model], optimizers=[adam])
    def step(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        adam.step()
        return loss

    x = pt.to_tensor(np.ones((2, 4), dtype="float32"))
    y = pt.to_tensor(np.zeros((2,), dtype="int64"))
    step(x, y)       # labels, forward, backward, update: no bomb trips
    assert profile.last_report() is None


def test_enable_env_var(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_PROFILE", "1")
    monitor.enable(str(tmp_path))
    assert profile.scopes_on is True
