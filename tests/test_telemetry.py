"""Live telemetry plane: OpenMetrics exporter endpoints, the periodic
sampler, /healthz stall semantics, teardown hygiene, serving SLO
rollups + qps decay, device-memory hardening, and the perf regression
sentinel's verdicts."""
import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import monitor
from paddle_tpu.monitor import export, sampler
from paddle_tpu.monitor.registry import Registry
from paddle_tpu.resilience import faults
from paddle_tpu.resilience.watchdog import Watchdog
from paddle_tpu.serving import metrics as smetrics

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_plane():
    """Exporter/sampler/windows are process-global: every test starts
    and ends with the whole plane down and empty."""
    monitor.disable(flush_counters=False)
    monitor.reset()
    faults.clear()
    smetrics.reset_windows()
    yield
    faults.clear()
    smetrics.reset_windows()
    monitor.disable(flush_counters=False)
    monitor.reset()


def _serve():
    srv = monitor.serve(port=0, sampler=False)
    assert srv.port > 0
    return srv


def _get(port, path):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=10) as r:
        return r.status, r.read().decode("utf-8"), \
            r.headers.get("Content-Type", "")


def _parse_openmetrics(text):
    """{series_name: value} for every sample line; histogram bucket
    lines keep their le label in the key."""
    assert text.rstrip().endswith("# EOF"), "missing OpenMetrics EOF"
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        key, val = line.rsplit(" ", 1)
        assert key not in out, f"duplicate sample {key}"
        out[key] = float(val)
    return out


# ---------------------------------------------------------------------------
# renderer semantics

def test_counter_and_gauge_render():
    reg = Registry()
    reg.counter("executor.run").inc(7)
    reg.gauge("step.toy.mfu").set(0.375)
    reg.gauge("never.set")  # None gauge must be skipped, not rendered
    text = export.render_openmetrics(reg)
    samples = _parse_openmetrics(text)
    assert samples["executor_run_total"] == 7
    assert samples["step_toy_mfu"] == 0.375
    assert not any(k.startswith("never_set") for k in samples)
    assert "# TYPE executor_run counter" in text
    assert "# TYPE step_toy_mfu gauge" in text


def test_histogram_openmetrics_bucket_semantics():
    reg = Registry()
    h = reg.histogram("lat", buckets=(1.0, 10.0, 100.0))
    for v in (0.5, 0.7, 5.0, 50.0, 1e6):  # last lands past all bounds
        h.observe(v)
    samples = _parse_openmetrics(export.render_openmetrics(reg))
    # cumulative le ladder: 2 <=1, 3 <=10, 4 <=100, +Inf == count
    assert samples['lat_bucket{le="1"}'] == 2
    assert samples['lat_bucket{le="10"}'] == 3
    assert samples['lat_bucket{le="100"}'] == 4
    assert samples['lat_bucket{le="+Inf"}'] == 5
    assert samples["lat_count"] == 5
    assert samples["lat_sum"] == pytest.approx(0.5 + 0.7 + 5 + 50 + 1e6)


def test_name_sanitization_and_collision():
    reg = Registry()
    reg.counter("a.b-c").inc(1)
    reg.counter("a.b_c").inc(99)  # sanitizes to the same name
    samples = _parse_openmetrics(export.render_openmetrics(reg))
    # first (sorted) wins; the scrape stays parseable either way
    assert samples["a_b_c_total"] in (1, 99)
    assert sum(1 for k in samples if k == "a_b_c_total") == 1


# ---------------------------------------------------------------------------
# endpoints

def test_metrics_endpoint_live_and_content_type():
    monitor.enable()
    monitor.counter("executor.run").inc(3)
    srv = _serve()
    status, text, ctype = _get(srv.port, "/metrics")
    assert status == 200
    assert "openmetrics-text" in ctype
    assert _parse_openmetrics(text)["executor_run_total"] == 3
    # a scrape is live, not a snapshot: bump and re-scrape
    monitor.counter("executor.run").inc(2)
    _, text2, _ = _get(srv.port, "/metrics")
    assert _parse_openmetrics(text2)["executor_run_total"] == 5


def test_unknown_path_404():
    srv = _serve()
    with pytest.raises(urllib.error.HTTPError) as e:
        _get(srv.port, "/nope")
    assert e.value.code == 404


def test_snapshot_endpoint():
    monitor.enable()
    monitor.counter("executor.run").inc(11)
    srv = _serve()
    status, body, ctype = _get(srv.port, "/snapshot")
    assert status == 200 and "json" in ctype
    snap = json.loads(body)
    assert snap["monitor_enabled"] is True
    assert snap["counters"]["executor.run"] == 11
    assert "flight_dir" in snap


def test_scrape_under_load_parses_and_is_monotonic():
    """8 writer threads hammer counters + a histogram while the main
    thread scrapes; every scrape must parse and every counter must be
    monotonic scrape-over-scrape."""
    monitor.enable()
    srv = _serve()
    stop = threading.Event()

    def writer(k):
        while not stop.is_set():
            monitor.counter(f"load.c{k % 4}").inc()
            monitor.histogram("load.h").observe(float(k))

    threads = [threading.Thread(target=writer, args=(k,), daemon=True)
               for k in range(8)]
    for t in threads:
        t.start()
    try:
        prev = {}
        for _ in range(25):
            _, text, _ = _get(srv.port, "/metrics")
            samples = _parse_openmetrics(text)  # asserts parseability
            for key, val in samples.items():
                if key.endswith("_total") or key.endswith("_count") \
                        or "_bucket{" in key:
                    assert val >= prev.get(key, 0), key
                    prev[key] = val
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    assert prev.get("load_c0_total", 0) > 0
    assert prev.get("load_h_count", 0) > 0


def test_healthz_flips_on_injected_slow_step_stall():
    """A resilience.faults slow_step injection that overruns the
    watchdog deadline must flip /healthz to 503/stalled while the step
    is stuck, and back to 200/ok once it completes."""
    monitor.enable()
    srv = _serve()
    wd = Watchdog(min_deadline=0.2, poll=0.02)
    wd.start()
    faults.inject("slow_step", step=0, delay=1.2)
    try:
        status0, body0, _ = _get(srv.port, "/healthz")
        assert status0 == 200 and json.loads(body0)["status"] == "ok"

        def stuck_step():
            with wd.step(0):
                faults.maybe_sleep("slow_step", 0)

        t = threading.Thread(target=stuck_step, daemon=True)
        t.start()
        saw_stalled = None
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            try:
                _get(srv.port, "/healthz")
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    saw_stalled = json.loads(e.read().decode())
                    break
            time.sleep(0.05)
        assert saw_stalled is not None, "healthz never went 503"
        assert saw_stalled["status"] == "stalled"
        stalled_wd = [w for w in saw_stalled["watchdogs"]
                      if w.get("stalled")]
        assert stalled_wd and stalled_wd[0]["elapsed_s"] > 0.2
        t.join(timeout=5)
        status1, body1, _ = _get(srv.port, "/healthz")
        assert status1 == 200 and json.loads(body1)["status"] == "ok"
    finally:
        wd.stop()


def test_healthz_reports_nan_guard_trips():
    from paddle_tpu.resilience.guard import total_trips
    monitor.enable()
    srv = _serve()
    before = total_trips()
    _, body, _ = _get(srv.port, "/healthz")
    assert json.loads(body)["nan_guard"]["trips"] == before


# ---------------------------------------------------------------------------
# lifecycle: serve/disable, env autostart, zero-cost-off

def test_disable_tears_down_server_and_sampler():
    monitor.enable()
    srv = monitor.serve(port=0)  # sampler=True path
    port = srv.port
    assert export.active() is not None and sampler.active() is not None
    _get(port, "/healthz")
    monitor.disable()
    assert export.active() is None and sampler.active() is None
    with pytest.raises((urllib.error.URLError, ConnectionError, OSError)):
        _get(port, "/healthz")
    time.sleep(0.1)
    assert not [t.name for t in threading.enumerate()
                if t.name.startswith(("paddle_tpu-metrics",
                                      "paddle_tpu-sampler"))]


def test_serve_is_idempotent():
    srv1 = _serve()
    srv2 = monitor.serve(port=0)
    assert srv2 is srv1


def test_env_port_autostarts_with_enable(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_METRICS_PORT", "0")
    monitor.enable()
    assert export.active() is not None
    _get(export.port(), "/metrics")


def test_no_plane_threads_when_not_served():
    monitor.enable()
    monitor.counter("executor.run").inc()
    assert export.active() is None and sampler.active() is None
    assert not [t for t in threading.enumerate()
                if t.name.startswith(("paddle_tpu-metrics",
                                      "paddle_tpu-sampler"))]


# ---------------------------------------------------------------------------
# sampler

def test_sample_once_publishes_mem_and_rss():
    reg = Registry()
    sampler.sample_once(reg)
    names = reg.names()
    assert any(n == "mem.host.rss_bytes" for n in names)
    assert reg.value("mem.host.rss_bytes") > 0


def test_sampler_provider_lifecycle():
    reg = Registry()
    calls = {"n": 0}

    def provider():
        calls["n"] += 1
        return {"toy.depth": 3}

    key = sampler.register_provider("toy", provider)
    sampler.sample_once(reg)
    assert reg.value("toy.depth") == 3 and calls["n"] == 1
    sampler.unregister_provider(key)
    sampler.sample_once(reg)
    assert calls["n"] == 1  # gone

    # a provider returning None (owner died) is dropped after one poll
    sampler.register_provider("dead", lambda: None)
    sampler.sample_once(reg)
    sampler.register_provider("boom",
                              lambda: (_ for _ in ()).throw(ValueError()))
    sampler.sample_once(reg)
    with sampler._providers_lock:
        assert "dead" not in sampler._providers
        assert "boom" not in sampler._providers


def test_prefetch_registers_queue_depth_provider():
    from paddle_tpu.io.prefetch import prefetch_to_device
    reg = Registry()
    it = prefetch_to_device(iter([np.ones((4,), "f4")] * 3), size=2)
    next(it)
    sampler.sample_once(reg)
    assert reg.value("prefetch.queue_depth", None) is not None
    it.close()
    # provider unregisters with the generator: no stale keys left
    with sampler._providers_lock:
        assert not any(k.startswith("prefetch-")
                       for k in sampler._providers)


def test_sampler_thread_samples_and_joins():
    monitor.enable()
    s = sampler.start(interval_s=0.05)
    time.sleep(0.2)
    assert s.running()
    assert monitor.registry().value("mem.host.rss_bytes", 0) > 0
    sampler.stop()
    assert not s.running()


# ---------------------------------------------------------------------------
# serving rollups: qps decay + SLO window

def test_qps_decays_to_zero_when_traffic_stops():
    monitor.enable()
    smetrics.record_completed(5, [1.0] * 5)
    assert monitor.registry().value("serving.qps") > 0
    # the sampler's sweep, 20 simulated seconds later: window empty
    val = smetrics.qps_now(now=time.monotonic() + 20.0)
    assert val == 0.0
    assert monitor.registry().value("serving.qps") == 0.0


def test_slo_rollup_goodput_and_percentiles():
    monitor.enable()
    now = time.monotonic()
    for _ in range(10):
        smetrics.record_submit(1)
    smetrics.record_completed(8, [float(i + 1) for i in range(8)],
                              within_sla=[True] * 6 + [False] * 2)
    smetrics.record_expired()  # 9th outcome: counted against goodput
    out = smetrics.slo_rollup(now=now)
    assert out["submitted"] == 10
    assert out["completed"] == 8          # expired has no latency
    assert out["within_sla"] == 6
    assert out["goodput"] == pytest.approx(0.6)
    assert out["p50_ms"] == pytest.approx(4.0, abs=1.01)
    assert out["p99_ms"] == pytest.approx(8.0)
    reg = monitor.registry()
    assert reg.value("slo.goodput") == pytest.approx(0.6)
    assert reg.value("slo.window_submitted") == 10
    # the window ages out: an hour later everything is gone
    out2 = smetrics.slo_rollup(now=now + 3600.0)
    assert out2["submitted"] == 0 and out2["goodput"] is None


def test_slo_series_reach_the_scrape():
    monitor.enable()
    srv = _serve()
    smetrics.record_submit(4)
    smetrics.record_completed(1, [2.5], within_sla=[True])
    smetrics.publish_rollups()
    _, text, _ = _get(srv.port, "/metrics")
    samples = _parse_openmetrics(text)
    assert samples["slo_goodput"] == pytest.approx(1.0)
    assert "serving_qps" in samples


# ---------------------------------------------------------------------------
# device_memory_stats hardening (satellite: CPU backends)

def test_device_memory_stats_cpu_returns_empty_dicts():
    import jax
    stats = monitor.device_memory_stats()
    assert set(stats) == {str(d.id) for d in jax.local_devices()}
    if jax.local_devices()[0].platform == "cpu":
        assert all(v == {} for v in stats.values())


def test_step_monitor_omits_empty_device_memory():
    import jax
    if jax.local_devices()[0].platform != "cpu":
        pytest.skip("CPU-only: needs a backend without memory stats")
    sm = monitor.StepMonitor(items_per_step=8, label="t",
                             memory_every=1).start()
    rec = sm.step()
    rec = sm.step()
    assert rec is not None and "device_memory" not in rec


# ---------------------------------------------------------------------------
# perf sentinel

def _sentinel():
    spec = importlib.util.spec_from_file_location(
        "perf_sentinel", os.path.join(_ROOT, "scripts",
                                      "perf_sentinel.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(scope="module")
def sentinel():
    return _sentinel()


BASE = {"bert_base_seq128_tokens_per_sec": 100000.0,
        "resnet50_images_per_sec": 2000.0, "serving_p99_ms": 10.0}


def test_sentinel_flags_regression(sentinel):
    rows = sentinel.compare({"value": 80000.0,
                             "resnet50_images_per_sec": 1990.0}, BASE)
    v = {r["metric"]: r["verdict"] for r in rows}
    assert v["bert_tokens_per_sec"] == "regression"
    assert v["resnet50_images_per_sec"] == "ok"


def test_sentinel_within_band_and_improved(sentinel):
    rows = sentinel.compare({"value": 95000.0,
                             "resnet50_images_per_sec": 2400.0,
                             "serving_p99_ms": 11.0}, BASE)
    v = {r["metric"]: r["verdict"] for r in rows}
    assert v["bert_tokens_per_sec"] == "ok"          # -5% < 10% band
    assert v["resnet50_images_per_sec"] == "improved"
    assert v["serving_p99_ms"] == "ok"               # +10% < 50% band


def test_sentinel_lower_is_better_latency(sentinel):
    rows = sentinel.compare({"value": 100000.0, "serving_p99_ms": 16.0},
                            BASE)
    v = {r["metric"]: r["verdict"] for r in rows}
    assert v["serving_p99_ms"] == "regression"       # +60% > 50% band


def test_sentinel_outage_skipped_not_failed(sentinel):
    rows = sentinel.compare(
        {"value": 0.0, "resnet50_images_per_sec": 0.0,
         "error": "backend init failed: tunnel wedged"}, BASE)
    assert all(r["verdict"] == "outage" for r in rows
               if r["candidate"] is not None)


def test_sentinel_silent_zero_is_regression(sentinel):
    # zero WITHOUT an error field is slow code, not a dead tunnel
    rows = sentinel.compare({"value": 0.0}, BASE)
    v = {r["metric"]: r["verdict"] for r in rows}
    assert v["bert_tokens_per_sec"] == "regression"


def _write(path, blob):
    with open(path, "w") as fh:
        json.dump(blob, fh)


def test_sentinel_end_to_end_repo_layout(sentinel, tmp_path):
    """Driver-format rounds: old slow round is NOT judged (history,
    not candidate); the newest outage round exits 0; a regressed
    newest JSONL artifact exits 1."""
    root = str(tmp_path)
    os.makedirs(os.path.join(root, "docs"))
    _write(os.path.join(root, "BENCH_r01.json"),
           {"n": 1, "cmd": "python bench.py", "rc": 0, "tail": "",
            "parsed": {"value": 60000.0,
                       "resnet50_images_per_sec": 1500.0}})
    _write(os.path.join(root, "BENCH_r02.json"),
           {"n": 2, "cmd": "python bench.py", "rc": 1, "tail": "",
            "parsed": {"value": 0.0, "error": "tunnel wedged",
                       "last_committed_measurement": BASE,
                       "last_committed_measurement_file":
                           "docs/bench_r04_measured.json"}})
    _write(os.path.join(root, "docs", "bench_r04_measured.json"), BASE)

    assert sentinel.main(["--repo-root", root]) == 0  # outage round

    # a driver round with parsed=None (raw-traceback round) also skips
    _write(os.path.join(root, "BENCH_r03.json"),
           {"n": 3, "cmd": "python bench.py", "rc": 1,
            "tail": "Traceback ...", "parsed": None})
    assert sentinel.main(["--repo-root", root]) == 0

    jsonl = os.path.join(root, "bench.jsonl")
    with open(jsonl, "w") as fh:
        fh.write(json.dumps({"value": 99000.0}) + "\n")   # old line
        fh.write(json.dumps({"value": 70000.0}) + "\n")   # newest: bad
    assert sentinel.main(["--repo-root", root,
                          "--jsonl", jsonl]) == 1

    with open(jsonl, "a") as fh:
        fh.write(json.dumps({"value": 101000.0}) + "\n")  # recovered
    assert sentinel.main(["--repo-root", root,
                          "--jsonl", jsonl]) == 0


def test_sentinel_baseline_discovery_prefers_banked(sentinel, tmp_path):
    root = str(tmp_path)
    _write(os.path.join(root, "BENCH_r01.json"),
           {"n": 1, "cmd": "c", "rc": 0, "tail": "",
            "parsed": {"value": 50000.0,
                       "last_committed_measurement": BASE}})
    blob, src = sentinel.discover_baseline(root)
    assert blob["bert_base_seq128_tokens_per_sec"] == 100000.0
    assert "BENCH_r01.json" in src


def test_sentinel_no_data_is_clean(sentinel, tmp_path):
    assert sentinel.main(["--repo-root", str(tmp_path)]) == 0
