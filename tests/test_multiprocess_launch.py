"""REAL multi-process distributed training (reference:
distributed/launch.py spawning worker processes + NCCL init;
TPU rebuild: jax.distributed over two local processes — the same
coordinator/collective path a multi-host pod uses over DCN, exercised
with CPU devices so it runs anywhere).

The launcher fans out 2 processes x 4 virtual devices = one 8-device
GLOBAL mesh; each process feeds its local batch shard; losses and final
weights must agree bit-exactly across ranks."""
import json
import os
import subprocess
import sys

import numpy as np
import pytest


def test_launch_two_process_global_mesh(tmp_path):
    out_base = str(tmp_path / "result.json")
    env = dict(os.environ)
    # hermetic forced-CPU children: never let the TPU plugin grab them
    for var in ("TPU_NAME", "TPU_LIBRARY_PATH", "PALLAS_AXON_POOL_IPS",
                "PJRT_DEVICE", "TPU_WORKER_HOSTNAMES"):
        env.pop(var, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["MULTIPROC_OUT"] = out_base
    worker = os.path.join(os.path.dirname(__file__),
                          "multiproc_worker.py")
    proc = subprocess.run(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nproc_per_node", "2", worker],
        env=env, cwd=os.path.dirname(os.path.dirname(worker)),
        capture_output=True, text=True, timeout=540)
    assert proc.returncode == 0, proc.stderr[-3000:]

    results = []
    for rank in range(2):
        with open(out_base + f".{rank}") as f:
            results.append(json.load(f))
    r0, r1 = sorted(results, key=lambda r: r["rank"])
    # both ranks saw the SAME global loss every step (grads psum'd
    # across processes inside the jitted step)
    np.testing.assert_allclose(r0["losses"], r1["losses"], rtol=0)
    # training progressed and the replicated weights stayed in sync
    assert r0["losses"][-1] < r0["losses"][0]
    np.testing.assert_allclose(r0["weight"], r1["weight"], rtol=0)
