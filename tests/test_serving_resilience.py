"""Self-healing serving (ISSUE 14): circuit breakers, serving fault
kinds, the shed ladder's edge cases, stranded-future guarantees on
close(drain=False), retry-after plumbing, and routing around an open
breaker. All CPU, all fast; the end-to-end failover/hedge/overload
story lives in scripts/serving_chaos_smoke.py."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import inference, nn, serving
from paddle_tpu.resilience import faults, retry
from paddle_tpu.resilience.deadline import Deadline
from paddle_tpu.serving import (AdmissionController, CircuitBreaker,
                                DeadlineExpired, MultiDeviceEngine,
                                QueueFullError, ShedError)
from paddle_tpu.serving.batcher import DynamicBatcher, Request
from paddle_tpu.serving.multi import NoHealthyReplicaError


@pytest.fixture
def mon():
    from paddle_tpu import monitor
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


@pytest.fixture(autouse=True)
def _no_fault_leak():
    faults.clear()
    yield
    faults.clear()


def _mlp():
    pt.seed(0)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _req(n=1, priority=1, deadline=None, sig="s"):
    return Request((np.zeros((n, 4), "f4"),), n, sig,
                   deadline=deadline, priority=priority)


# ---------------------------------------------------------------------------
# CircuitBreaker: the full lifecycle on a fake clock

def test_breaker_lifecycle_fake_clock():
    t = [100.0]
    b = CircuitBreaker("r0", failure_threshold=2, cooldown_s=5.0,
                       half_open_probes=1, clock=lambda: t[0])
    assert b.state == "closed" and b.allow()
    b.record_failure("boom")
    assert b.state == "closed"          # 1 of 2: not yet
    b.record_failure("boom")
    assert b.state == "open" and b.open_count == 1
    assert not b.allow()                # open: nothing routed
    t[0] = 104.9
    assert b.state == "open"            # cooldown not elapsed
    t[0] = 105.0
    assert b.state == "half_open"       # promoted on read
    assert b.allow()                    # consumes the one probe slot
    assert not b.allow()                # probe budget spent
    b.record_success()
    assert b.state == "closed" and b.allow()


def test_breaker_success_resets_consecutive_count():
    b = CircuitBreaker(failure_threshold=3)
    b.record_failure()
    b.record_failure()
    b.record_success()                  # streak broken
    b.record_failure()
    b.record_failure()
    assert b.state == "closed"          # 2 < 3 since the reset
    b.record_failure()
    assert b.state == "open"


def test_breaker_half_open_failure_reopens():
    t = [0.0]
    b = CircuitBreaker(failure_threshold=1, cooldown_s=1.0,
                       clock=lambda: t[0])
    b.record_failure()
    t[0] = 1.0
    assert b.state == "half_open"
    b.record_failure("probe")
    assert b.state == "open" and b.open_count == 2
    t[0] = 1.5
    assert b.state == "open"            # cooldown restarted at reopen
    t[0] = 2.0
    assert b.state == "half_open"


def test_breaker_trip_records_gauge_and_counters(mon):
    t = [0.0]
    b = CircuitBreaker("rX", cooldown_s=1.0, clock=lambda: t[0])
    b.trip("hung")
    reg = mon.registry()
    assert reg.value("serving.breaker_state.rX") == 2
    assert reg.value("serving.breaker_open", 0) == 1
    t[0] = 1.0
    assert b.allow()                    # half-open probe
    b.record_success()
    assert reg.value("serving.breaker_state.rX") == 0
    assert reg.value("serving.breaker_closed", 0) == 1


def test_breaker_threshold_validation():
    with pytest.raises(ValueError):
        CircuitBreaker(failure_threshold=0)


# ---------------------------------------------------------------------------
# serving fault kinds: replica targeting + behaviours

def test_fault_replica_targeting():
    spec = faults.inject("replica_error", replica=1, times=1)
    faults.maybe_serving_fault(0)       # wrong replica: no fire
    assert spec.fired == 0
    with pytest.raises(retry.TransientError):
        faults.maybe_serving_fault(1)
    assert spec.fired == 1
    faults.maybe_serving_fault(1)       # times budget spent
    assert spec.fired == 1


def test_fault_replica_list_targeting():
    spec = faults.inject("replica_error", replica=[0, 2], times=None)
    with pytest.raises(retry.TransientError):
        faults.maybe_serving_fault(0)
    faults.maybe_serving_fault(1)
    with pytest.raises(retry.TransientError):
        faults.maybe_serving_fault(2)
    assert spec.fired == 2


def test_fault_replica_slow_sleeps_delay():
    faults.inject("replica_slow", replica=0, delay=0.05)
    t0 = time.monotonic()
    faults.maybe_serving_fault(0)
    assert time.monotonic() - t0 >= 0.04


def test_fault_replica_hang_honours_explicit_delay():
    # default hang is 30s (only supervision resolves it); an explicit
    # delay keeps unit tests fast
    faults.inject("replica_hang", delay=0.05)
    t0 = time.monotonic()
    faults.maybe_serving_fault(3)       # untargeted spec: any replica
    assert 0.04 <= time.monotonic() - t0 < 5.0


# ---------------------------------------------------------------------------
# DynamicBatcher: no future is ever lost, not even mid-dispatch

def test_close_nodrain_resolves_dispatched_future():
    release = threading.Event()

    def process(group):
        release.wait(10.0)              # a "hung replica"
        for r in group:
            r.resolve_result(None)

    b = DynamicBatcher(process, AdmissionController(), max_batch=4,
                       timeout_ms=1.0)
    b.start()
    r = _req()
    b.submit(r)
    for _ in range(200):                # wait for dispatch
        if b.inflight_token() is not None:
            break
        time.sleep(0.005)
    assert b.inflight_token() is not None
    b.close(drain=False, timeout=0.2)   # bounded join, thread is wedged
    assert r.future.done()
    with pytest.raises(RuntimeError, match="still dispatched"):
        r.future.result()
    release.set()                       # let the wedged thread exit


def test_close_nodrain_leaves_disowned_inflight_alone():
    release = threading.Event()

    def process(group):
        release.wait(10.0)

    b = DynamicBatcher(process, AdmissionController(), max_batch=4,
                       timeout_ms=1.0)
    b.start()
    r = _req()
    b.submit(r)
    for _ in range(200):
        if b.inflight_token() is not None:
            break
        time.sleep(0.005)
    taken = b.disown_inflight()         # failover took ownership
    assert taken == [r]
    b.close(drain=False, timeout=0.2)
    assert not r.future.done()          # new owner resolves it, not close
    r.resolve_result("rescued")
    release.set()
    assert r.future.result() == "rescued"


# ---------------------------------------------------------------------------
# the shed ladder

def test_shed_ladder_priorities_and_retry_after():
    a = AdmissionController(max_queue_depth=100, slo_goodput_floor=None)
    # level 1 (depth >= 50): low shed, normal + high admitted
    with pytest.raises(ShedError) as ei:
        a.admit(_req(priority=2), depth=50)
    assert ei.value.level == 1 and ei.value.priority == 2
    assert ei.value.retry_after_ms == 25.0
    assert abs(ei.value.retry_after_s - 0.025) < 1e-9
    assert retry.is_transient(ei.value)
    a.admit(_req(priority=1), depth=50)
    a.admit(_req(priority=0), depth=50)
    # level 2 (depth >= 75): normal shed too, retry-after doubles
    with pytest.raises(ShedError) as ei:
        a.admit(_req(priority=1), depth=75)
    assert ei.value.level == 2 and ei.value.retry_after_ms == 50.0
    a.admit(_req(priority=0), depth=75)
    # level 3 (depth >= 90): even high shed, doubled again
    with pytest.raises(ShedError) as ei:
        a.admit(_req(priority=0), depth=90)
    assert ei.value.level == 3 and ei.value.retry_after_ms == 100.0
    # hard cap: QueueFullError, itself a retryable ShedError
    with pytest.raises(QueueFullError) as ei:
        a.admit(_req(priority=0), depth=100)
    assert isinstance(ei.value, ShedError)
    assert retry.is_transient(ei.value)
    assert ei.value.retry_after_ms == 100.0


def test_shed_disabled_admits_everyone_below_cap():
    a = AdmissionController(max_queue_depth=100, shed=False)
    a.admit(_req(priority=2), depth=99)
    with pytest.raises(QueueFullError):
        a.admit(_req(priority=0), depth=100)


def test_effective_max_batch_shrinks_with_the_ladder():
    a = AdmissionController(max_queue_depth=100, slo_goodput_floor=None)
    assert a.effective_max_batch(32, depth=0) == 32
    assert a.effective_max_batch(32, depth=50) == 32    # level 1: no cut
    assert a.effective_max_batch(32, depth=75) == 16    # level 2: halved
    assert a.effective_max_batch(32, depth=90) == 8     # level 3: quartered
    assert a.effective_max_batch(2, depth=90) == 1      # floor at 1


def test_equal_priority_fifo_preserved_under_shed():
    """A shrunken cap must shorten flushes, never reorder or skip-fill
    within a signature."""
    groups = []

    def process(group):
        groups.append(list(group))
        for r in group:
            r.resolve_result(None)

    a = AdmissionController(max_queue_depth=8, slo_goodput_floor=None)
    b = DynamicBatcher(process, a, max_batch=8, timeout_ms=1.0)
    reqs = [_req(n=2, priority=0) for _ in range(7)]
    for r in reqs:
        b.submit(r)                     # high priority: admitted to depth 7
    # depth 7/8 = 0.875 -> ladder level 2 -> first pick caps at 8//2 = 4
    b.start()
    for r in reqs:
        r.future.result(timeout=5)
    b.close()
    flat = [r for g in groups for r in g]
    assert flat == reqs                 # FIFO survived the shrunken cap
    assert len(groups[0]) == 2          # 2 reqs x 2 rows = the level-2 cap


def test_expired_never_counted_as_shed(mon):
    events = []
    a = AdmissionController(max_queue_depth=8)
    a.on_event = events.append
    b = DynamicBatcher(lambda g: [r.resolve_result(None) for r in g], a,
                       max_batch=8, timeout_ms=1.0)
    dead = _req(deadline=Deadline.after_ms(0))   # expired before dispatch
    b.submit(dead)
    b.start()
    with pytest.raises(DeadlineExpired):
        dead.future.result(timeout=5)
    b.close()
    assert events == ["expired"]
    reg = mon.registry()
    assert reg.value("serving.deadline_expired", 0) == 1
    assert reg.value("serving.shed", 0) == 0


def test_retry_call_honours_retry_after_floor():
    calls = []

    def flaky():
        calls.append(time.monotonic())
        if len(calls) == 1:
            raise ShedError("shed", retry_after_ms=80.0)
        return "ok"

    # policy backoff alone would wait ~1ms; the shed hint floors it
    policy = retry.RetryPolicy(max_attempts=2, base_delay=0.001,
                               max_delay=0.001, jitter=0.0)
    assert retry.retry_call(flaky, policy=policy) == "ok"
    assert calls[1] - calls[0] >= 0.07


# ---------------------------------------------------------------------------
# fleet routing: an open breaker takes a replica out of rotation

def test_multi_engine_routes_around_open_breaker():
    import jax
    eng = MultiDeviceEngine(
        inference.Predictor(_mlp()), devices=jax.local_devices()[:2],
        max_batch=8, timeout_ms=1.0, supervise=False, hedge_ms=0)
    try:
        eng._replicas[0].breaker.trip("test")
        x = np.random.RandomState(0).rand(2, 16).astype("f4")
        before = eng._replicas[0].engine.stats()["submitted"]
        for _ in range(6):
            eng.run(x, timeout=10)
        assert eng._replicas[0].engine.stats()["submitted"] == before
        assert eng._replicas[1].engine.stats()["submitted"] >= 6
        assert eng.stats()["breakers"][0] == "open"
        # second breaker opens too: no capacity, retryable, with a hint
        eng._replicas[1].breaker.trip("test")
        with pytest.raises(NoHealthyReplicaError) as ei:
            eng.submit(x)
        assert retry.is_transient(ei.value)
        assert ei.value.retry_after_ms > 0
        assert eng.health()["all_open"]
    finally:
        eng.close(drain=False, timeout=2.0)


def test_healthz_degrades_to_503_when_fleet_all_open(mon):
    import jax
    from paddle_tpu.monitor import export
    eng = MultiDeviceEngine(
        inference.Predictor(_mlp()), devices=jax.local_devices()[:2],
        max_batch=8, timeout_ms=1.0, supervise=False, hedge_ms=0)
    try:
        status, payload = export.health_payload()
        assert status == 200
        assert payload["serving"][0]["all_open"] is False
        for rep in eng._replicas:
            rep.breaker.trip("test")
        status, payload = export.health_payload()
        assert status == 503 and payload["status"] == "degraded"
        assert payload["serving"][0]["all_open"] is True
    finally:
        eng.close(drain=False, timeout=2.0)
