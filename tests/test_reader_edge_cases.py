"""Edge-case regressions from round-2 code review (readers, ctc lengths,
to_static discovery of fleet optimizers)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import reader as R


def test_cache_partial_pass_not_corrupted():
    c = R.cache(lambda: iter(range(10)))
    got = []
    for i, x in enumerate(c()):
        if i == 3:
            break
        got.append(x)
    # a broken-off pass must not poison the cache
    assert list(c()) == list(range(10))
    assert list(c()) == list(range(10))


def test_xmap_readers_propagates_mapper_error():
    def bad_mapper(x):
        if x == 5:
            raise ValueError("boom")
        return x

    r = R.xmap_readers(bad_mapper, lambda: iter(range(10)), 2, 4)
    with pytest.raises(ValueError, match="boom"):
        list(r())

    def bad_reader():
        yield 1
        raise RuntimeError("reader broke")

    r = R.xmap_readers(lambda x: x, bad_reader, 2, 4)
    with pytest.raises(RuntimeError, match="reader broke"):
        list(r())


def test_warpctc_zero_padded_labels():
    from paddle_tpu import ops
    rs = np.random.RandomState(0)
    logits = rs.randn(2, 10, 6).astype("f4")
    # labels padded with 0 == blank (the common paddle batch layout)
    labels_padded = np.array([[1, 2, 3, 0, 0], [4, 5, 0, 0, 0]], np.int32)
    out_pad0 = ops.warpctc(pt.to_tensor(logits), labels_padded).numpy()
    # explicit lengths must give the identical result
    out_explicit = ops.ctc_loss(
        pt.to_tensor(logits), labels_padded,
        np.array([10, 10], np.int32), np.array([3, 2], np.int32),
        blank=0, reduction="none").numpy()
    np.testing.assert_allclose(out_pad0[:, 0], out_explicit, rtol=1e-5)


def test_to_static_discovers_fleet_distributed_optimizer():
    from paddle_tpu import nn, optimizer, jit
    from paddle_tpu.parallel.fleet import Fleet

    pt.seed(0)
    fleet = Fleet()
    fleet.init(mesh_shape={"dp": 2})
    m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    fleet.distributed_model(m)
    o = fleet.distributed_optimizer(
        optimizer.Adam(learning_rate=1e-2, parameters=m.parameters()))

    x = pt.to_tensor(np.random.RandomState(0).randn(8, 4).astype("f4"))
    y = pt.to_tensor(np.random.RandomState(1).randn(8, 2).astype("f4"))

    def step(x, y):
        loss = pt.nn.functional.mse_loss(m(x), y)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    # NO explicit optimizers=: closure discovery must find the wrapper
    cstep = jit.to_static(step)
    vals = [float(cstep(x, y).numpy()) for _ in range(5)]
    assert vals[-1] < vals[0]


def test_sequence_conv_even_filter_default():
    from paddle_tpu import ops
    x = np.arange(8, dtype="f4").reshape(1, 4, 2)
    w = np.eye(8, 3).astype("f4")
    # fs=4 -> reference default padding_start = -2
    out = ops.sequence_conv(pt.to_tensor(x), pt.to_tensor(w),
                            filter_size=4).numpy()
    ref = ops.sequence_conv(pt.to_tensor(x), pt.to_tensor(w),
                            filter_size=4, padding_start=-2).numpy()
    np.testing.assert_allclose(out, ref)
