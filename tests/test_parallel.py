"""Distribution on the 8-device CPU mesh (SURVEY §4): collectives,
GSPMD data parallelism, ring attention, sharded embedding."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P, NamedSharding

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt, jit
from paddle_tpu.parallel import (collective, fleet, ring_attention,
                                 sharded_lookup)


@pytest.fixture
def mesh8():
    mesh = collective.make_mesh({"dp": 8})
    yield mesh
    collective.set_mesh(None)


def test_eight_devices_present():
    assert jax.device_count() == 8


def test_collectives_inside_shard_map(mesh8):
    def f(x):
        s = collective.all_reduce(pt.Tensor(x), op="sum", axis_name="dp")
        g = collective.all_gather(pt.Tensor(x), axis_name="dp")
        return s.data, g.data

    xs = jnp.arange(8.0).reshape(8, 1)
    out_sum, out_gather = jax.shard_map(
        f, mesh=mesh8, in_specs=P("dp"), out_specs=(P("dp"), P("dp")))(xs)
    np.testing.assert_allclose(np.asarray(out_sum).ravel(), [28.0] * 8)
    assert out_gather.shape == (64, 1)


def test_broadcast_and_ppermute(mesh8):
    def f(x):
        b = collective.broadcast(pt.Tensor(x), src=3, axis_name="dp")
        p = collective.ppermute(pt.Tensor(x),
                                [(i, (i + 1) % 8) for i in range(8)],
                                axis_name="dp")
        return b.data, p.data

    xs = jnp.arange(8.0).reshape(8, 1)
    b, p = jax.shard_map(f, mesh=mesh8, in_specs=P("dp"),
                         out_specs=(P("dp"), P("dp")))(xs)
    np.testing.assert_allclose(np.asarray(b).ravel(), [3.0] * 8)
    np.testing.assert_allclose(np.asarray(p).ravel(),
                               np.roll(np.arange(8.0), 1))


def test_gspmd_data_parallel_training(mesh8):
    """Params replicated + batch sharded on dp -> XLA inserts the grad
    allreduce; result must equal single-device training on the full batch."""
    pt.seed(5)
    model_dp = nn.Linear(4, 2)
    model_ref = nn.Linear(4, 2)
    model_ref.set_state_dict(model_dp.state_dict())

    o_dp = opt.SGD(learning_rate=0.1, parameters=model_dp.parameters())
    o_ref = opt.SGD(learning_rate=0.1, parameters=model_ref.parameters())

    f = fleet
    f.init(mesh_shape={"dp": 8})
    f.shard_model(model_dp)

    x = np.random.RandomState(0).randn(16, 4).astype("f4")
    y = np.random.RandomState(1).randn(16, 2).astype("f4")

    def step(m, o, xb, yb):
        loss = (m(xb) - yb).square().mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    sx, sy = f.shard_batch(x, y)
    dp_step = jit.to_static(lambda a, b: step(model_dp, o_dp, a, b),
                            models=[model_dp], optimizers=[o_dp])
    l_dp = float(dp_step(sx, sy).numpy())
    l_ref = float(step(model_ref, o_ref, pt.to_tensor(x),
                       pt.to_tensor(y)).numpy())
    np.testing.assert_allclose(l_dp, l_ref, rtol=1e-5)
    np.testing.assert_allclose(model_dp.weight.numpy(),
                               model_ref.weight.numpy(), atol=1e-5)


def test_ring_attention_matches_full(mesh8):
    b, h, s, d = 2, 2, 32, 8  # s sharded into 8 blocks of 4
    rng = np.random.RandomState(0)
    q = rng.randn(b, h, s, d).astype("f4")
    k = rng.randn(b, h, s, d).astype("f4")
    v = rng.randn(b, h, s, d).astype("f4")

    def ref_attn(causal):
        logits = np.einsum("bhqd,bhkd->bhqk", q, k) / np.sqrt(d)
        if causal:
            mask = np.tril(np.ones((s, s), bool))
            logits = np.where(mask, logits, -1e30)
        e = np.exp(logits - logits.max(-1, keepdims=True))
        p = e / e.sum(-1, keepdims=True)
        return np.einsum("bhqk,bhkd->bhqd", p, v)

    for causal in (False, True):
        def f(qb, kb, vb):
            return ring_attention(pt.Tensor(qb), pt.Tensor(kb),
                                  pt.Tensor(vb), axis_name="sp",
                                  causal=causal).data
        mesh = Mesh(np.array(jax.devices()).reshape(8), ("sp",))
        out = jax.shard_map(
            f, mesh=mesh, in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None))(q, k, v)
        np.testing.assert_allclose(np.asarray(out), ref_attn(causal),
                                   atol=2e-3)


def test_sharded_lookup(mesh8):
    vocab, dim = 64, 4
    table = np.random.RandomState(0).randn(vocab, dim).astype("f4")
    ids = np.array([[0, 5, 63], [8, 9, 31]])

    def f(local_rows, ids):
        return sharded_lookup(pt.Tensor(ids), pt.Tensor(local_rows),
                              axis_name="mp").data

    mesh = Mesh(np.array(jax.devices()).reshape(8), ("mp",))
    out = jax.shard_map(f, mesh=mesh, in_specs=(P("mp", None), P(None, None)),
                        out_specs=P(None, None, None))(table, ids)
    np.testing.assert_allclose(np.asarray(out), table[ids], atol=1e-6)


def test_sharded_embedding_gspmd(mesh8):
    mesh = collective.make_mesh({"mp": 8})
    from paddle_tpu.parallel.embedding import ShardedEmbedding
    emb = ShardedEmbedding(64, 16, axis_name="mp", mesh=mesh)
    ids = pt.to_tensor(np.array([[1, 2], [60, 63]]))
    out = emb(ids)
    assert out.shape == [2, 2, 16]
    np.testing.assert_allclose(out.numpy()[0, 0],
                               np.asarray(emb.weight.data)[1], atol=1e-6)


def test_dataparallel_wrapper(mesh8):
    fleet.init(mesh_shape={"dp": 8})
    m = nn.Linear(4, 2)
    dp = pt.parallel.DataParallel(m)
    out = dp(pt.to_tensor(np.random.randn(8, 4).astype("f4")))
    assert out.shape == [8, 2]
    assert dp.scale_loss(out) is out
    # params are now mesh-placed (replicated)
    sh = m.weight.data.sharding
    assert getattr(sh, "mesh", None) is not None


@pytest.mark.slow
def test_megatron_dryrun_entry():
    """__graft_entry__.dryrun_multichip contract: full 5-axis train step."""
    import importlib, sys
    sys.path.insert(0, "/root/repo")
    import __graft_entry__ as g
    g.dryrun_multichip(8)


@pytest.mark.slow
def test_megatron_loss_decreases():
    from paddle_tpu.parallel import megatron as M
    import numpy as np
    mesh, sizes = M.make_mesh(8)
    cfg = M.MegatronConfig(lr=5e-3)
    state, step = M.build_train_step(cfg, mesh)
    toks = np.random.RandomState(0).randint(
        0, cfg.vocab_size,
        (cfg.n_micro, cfg.microbatch * sizes["dp"], cfg.seq_len)).astype("i4")
    losses = []
    for _ in range(4):
        state, loss = step(state, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0]


@pytest.mark.slow
def test_megatron_8dev_matches_single_device():
    """Gold SPMD-correctness test: one train step on the dp2/pp2/tp2 mesh
    must produce the SAME logical parameters as the identical model run on
    a 1-device mesh (pp stages folded into one stage). Catches any missing
    or double-counted cross-rank gradient reduction."""
    from paddle_tpu.parallel import megatron as M
    import jax

    # 8-device: pp=2 stages x 2 layers; 1-device: 1 stage x 4 layers.
    # use_moe off: capacity-based MoE buckets tokens per LOCAL batch, so
    # its forward differs across dp layouts by design — its gradient
    # correctness is covered by the loss-decrease test instead.
    cfg8 = M.MegatronConfig(layers_per_stage=2, lr=1e-2, seq_len=16,
                            microbatch=2, n_micro=2, hidden=32, n_heads=2,
                            vocab_size=64, use_moe=False)
    cfg1 = cfg8._replace(layers_per_stage=4)

    mesh8, sizes8 = M.make_mesh(8)
    assert sizes8 == {"dp": 2, "pp": 2, "tp": 2, "sp": 1, "ep": 1}
    mesh1, _ = M.make_mesh(1, devices=jax.devices()[:1])

    s8, step8 = M.build_train_step(cfg8, mesh8)
    s1, step1 = M.build_train_step(cfg1, mesh1)
    p8, p1 = s8["params"], s1["params"]

    toks = np.random.RandomState(0).randint(
        0, cfg8.vocab_size, (cfg8.n_micro, cfg8.microbatch * 2,
                             cfg8.seq_len)).astype("i4")

    # identical logical init (same seed; stage-stacked shapes are row-major
    # compatible: [2,2,...] vs [1,4,...])
    for k in p8:
        a = np.asarray(jax.device_get(p8[k]))
        b = np.asarray(jax.device_get(p1[k]))
        np.testing.assert_allclose(a.reshape(b.shape), b, atol=1e-6,
                                   err_msg=f"init mismatch {k}")

    s8, l8 = step8(s8, toks)
    s1, l1 = step1(s1, toks)
    p8, p1 = s8["params"], s1["params"]
    np.testing.assert_allclose(float(l8), float(l1), rtol=1e-4)
    for k in p8:
        a = np.asarray(jax.device_get(p8[k]))
        b = np.asarray(jax.device_get(p1[k]))
        np.testing.assert_allclose(
            a.reshape(b.shape), b, atol=5e-4,
            err_msg=f"param {k} diverged between 8-dev and 1-dev")


@pytest.mark.slow
def test_megatron_fused_adam_matches_fallback():
    """The Pallas fused-adam kernel running on per-device shards INSIDE
    shard_map (interpret mode here) must match the plain-XLA adam rule the
    CPU default takes."""
    from paddle_tpu.parallel import megatron as M
    from paddle_tpu.ops import pallas as P

    cfg = M.MegatronConfig(hidden=32, n_heads=2, vocab_size=64, seq_len=16,
                           microbatch=1, n_micro=2, use_moe=False)
    toks = np.random.RandomState(0).randint(
        0, cfg.vocab_size, (cfg.n_micro, 2, cfg.seq_len)).astype("i4")

    def one_step(force):
        mesh, sizes = M.make_mesh(8)
        P.configure(fused_adam=force)
        try:
            state, step = M.build_train_step(cfg, mesh)
            state, loss = step(state, toks)
        finally:
            P.configure(fused_adam=None)
        return state, float(loss)

    s_fused, l_fused = one_step(True)
    s_plain, l_plain = one_step(False)
    np.testing.assert_allclose(l_fused, l_plain, rtol=1e-5)
    import jax
    for k in s_fused["params"]:
        np.testing.assert_allclose(
            np.asarray(jax.device_get(s_fused["params"][k])),
            np.asarray(jax.device_get(s_plain["params"][k])),
            atol=2e-5, err_msg=f"param {k}")


def test_sync_batch_norm_matches_global_batch():
    """SyncBatchNorm inside a dp=4 shard_map: per-shard batches of 4
    normalize with GLOBAL (16-sample) statistics — output and updated
    running stats must equal ordinary BatchNorm over the full batch on
    one device. Outside SPMD it degrades to ordinary BN (same layer)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P

    rng = np.random.RandomState(0)
    x = (rng.randn(16, 6, 4, 4) * 2 + 1).astype("f4")

    # reference: plain BN over the whole batch
    pt.seed(0)
    bn_ref = nn.BatchNorm2D(6)
    bn_ref.train()
    out_ref = bn_ref(pt.to_tensor(x)).numpy()

    pt.seed(0)
    sbn = nn.SyncBatchNorm(6, axis_name="dp")
    sbn.train()

    mesh = Mesh(np.asarray(jax.devices()[:4]), ("dp",))

    def shard_fn(xs):
        out = sbn(pt.to_tensor(xs))
        return out.data, sbn._mean.data, sbn._variance.data

    f = jax.jit(jax.shard_map(
        shard_fn, mesh=mesh,
        in_specs=P("dp", None, None, None),
        out_specs=(P("dp", None, None, None), P(None), P(None)),
        check_vma=False))
    out, rm, rv = f(x)
    np.testing.assert_allclose(np.asarray(out), out_ref, atol=2e-4)
    np.testing.assert_allclose(np.asarray(rm), bn_ref._mean.numpy(),
                               atol=1e-4)
    np.testing.assert_allclose(np.asarray(rv), bn_ref._variance.numpy(),
                               rtol=1e-2, atol=1e-3)

    # outside SPMD: behaves as ordinary BN on the local batch
    pt.seed(0)
    sbn2 = nn.SyncBatchNorm(6)
    sbn2.train()
    out_local = sbn2(pt.to_tensor(x)).numpy()
    np.testing.assert_allclose(out_local, out_ref, atol=2e-4)


@pytest.mark.slow
def test_megatron_multi_tensor_adam_matches():
    """fused_adam_multi on (interpret mode, shard_map over dp2) must
    train exactly like the per-tensor adam path: the r5 multi-tensor
    dispatch composes with sharded slot state."""
    from paddle_tpu.parallel import megatron as M
    from paddle_tpu.ops import pallas as P

    def run(multi):
        mesh, sizes = M.make_mesh(2, devices=jax.devices()[:2])
        cfg = M.MegatronConfig(layers_per_stage=2, lr=1e-2, seq_len=16,
                               microbatch=2, n_micro=2, hidden=32,
                               n_heads=2, vocab_size=64, use_moe=False)
        if multi:
            P.configure(fused_adam_multi=True)
        try:
            state, step = M.build_train_step(cfg, mesh)
            toks = np.random.RandomState(0).randint(
                0, cfg.vocab_size,
                (cfg.n_micro, cfg.microbatch * sizes["dp"],
                 cfg.seq_len)).astype("i4")
            losses = []
            for _ in range(3):
                state, loss = step(state, toks)
                losses.append(float(loss))
            return losses
        finally:
            P.configure(fused_adam_multi=None)

    base = run(False)
    multi = run(True)
    np.testing.assert_allclose(multi, base, rtol=2e-5)


def test_quantized_allreduce_approximates_psum():
    """int8-wire ring all-reduce (collective.all_reduce_quantized): all
    ranks agree, result within quantization error of exact psum, odd
    (non-divisible) tensor lengths pad correctly."""
    from paddle_tpu.parallel.collective import all_reduce_quantized
    from jax.sharding import Mesh, PartitionSpec as P

    rng = np.random.RandomState(0)
    per_dev = rng.randn(8, 1003).astype("f4")  # odd length: pad path
    exact = per_dev.sum(0)
    mesh = Mesh(np.array(jax.devices()).reshape(8), ("dp",))
    out = np.asarray(jax.jit(jax.shard_map(
        lambda x: all_reduce_quantized(x, axis_name="dp"), mesh=mesh,
        in_specs=P("dp", None), out_specs=P("dp", None)))(per_dev))
    scale = np.abs(exact).max()
    for rk in range(8):
        assert np.abs(out[rk] - exact).max() / scale < 0.05
    # all ranks identical (the all-gather hop distributes ONE result)
    for rk in range(1, 8):
        np.testing.assert_array_equal(out[rk], out[0])
    with pytest.raises(ValueError):
        all_reduce_quantized(np.ones(4), bits=2)  # 4 is now a real width


@pytest.mark.slow
def test_megatron_quantized_grads_trains():
    """cfg.quantized_grad_allreduce: loss still descends with the int8
    gradient ring (error is noise-level for training)."""
    from paddle_tpu.parallel import megatron as M
    mesh, sizes = M.make_mesh(4, devices=jax.devices()[:4],
                              sizes={"dp": 4})
    cfg = M.MegatronConfig(layers_per_stage=2, lr=1e-2, seq_len=16,
                           microbatch=2, n_micro=2, hidden=32,
                           n_heads=2, vocab_size=64, use_moe=False,
                           quantized_grad_allreduce=True)
    state, step = M.build_train_step(cfg, mesh)
    toks = np.random.RandomState(0).randint(
        0, cfg.vocab_size,
        (cfg.n_micro, cfg.microbatch * sizes["dp"],
         cfg.seq_len)).astype("i4")
    losses = []
    for _ in range(4):
        state, loss = step(state, toks)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


@pytest.mark.parametrize("n", [2, 3, 5])
def test_quantized_allreduce_odd_rings(n):
    """Non-power-of-2 ring sizes and degenerate inputs (zeros, single
    element) stay correct."""
    from paddle_tpu.parallel.collective import all_reduce_quantized
    from jax.sharding import Mesh, PartitionSpec as P

    rng = np.random.RandomState(n)
    per_dev = rng.randn(n, 37).astype("f4")
    per_dev[0] = 0.0  # one all-zero contribution
    exact = per_dev.sum(0)
    mesh = Mesh(np.array(jax.devices()[:n]), ("dp",))
    out = np.asarray(jax.jit(jax.shard_map(
        lambda x: all_reduce_quantized(x, axis_name="dp"), mesh=mesh,
        in_specs=P("dp", None), out_specs=P("dp", None)))(per_dev))
    scale = max(np.abs(exact).max(), 1e-6)
    for rk in range(n):
        assert np.abs(out[rk] - exact).max() / scale < 0.08
        np.testing.assert_array_equal(out[rk], out[0])

    # all-zero everywhere: exact zeros out
    zeros = np.zeros((n, 8), "f4")
    out0 = np.asarray(jax.jit(jax.shard_map(
        lambda x: all_reduce_quantized(x, axis_name="dp"), mesh=mesh,
        in_specs=P("dp", None), out_specs=P("dp", None)))(zeros))
    np.testing.assert_array_equal(out0, zeros)
