"""Cross-validation of numerically-tricky ops against torch CPU (the
suite's independent oracle, like the existing ctc-vs-torch check):
grid sampling, affine grids, KL divergence, and the legacy dygraph
LSTM/GRU cells weight-mapped onto torch.nn.LSTMCell/GRUCell."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as pt
from paddle_tpu import fluid
from paddle_tpu.fluid import dygraph


def test_affine_grid_matches_torch():
    rng = np.random.RandomState(0)
    theta = rng.randn(2, 2, 3).astype("f4")
    out = fluid.layers.affine_grid(pt.to_tensor(theta),
                                   [2, 3, 5, 7]).numpy()
    ref = torch.nn.functional.affine_grid(
        torch.tensor(theta), (2, 3, 5, 7), align_corners=True).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_grid_sampler_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 6, 5).astype("f4")
    grid = (rng.rand(2, 4, 7, 2).astype("f4") * 2 - 1)
    out = fluid.layers.grid_sampler(pt.to_tensor(x),
                                    pt.to_tensor(grid)).numpy()
    ref = torch.nn.functional.grid_sample(
        torch.tensor(x), torch.tensor(grid), mode="bilinear",
        padding_mode="zeros", align_corners=True).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_kldiv_loss_matches_torch():
    rng = np.random.RandomState(2)
    logp = np.log(rng.dirichlet(np.ones(6), size=8).astype("f4") + 1e-8)
    tgt = rng.dirichlet(np.ones(6), size=8).astype("f4")
    for reduction in ("mean", "sum", "batchmean", "none"):
        out = fluid.layers.kldiv_loss(pt.to_tensor(logp),
                                      pt.to_tensor(tgt),
                                      reduction=reduction).numpy()
        ref = torch.nn.functional.kl_div(
            torch.tensor(logp), torch.tensor(tgt),
            reduction=reduction).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)


def _copy_cell_weights(cell, tcell, n_gates):
    """paddle cudnn-layout cells and torch cells share the (gates*h, in)
    weight layout and gate order — copy torch's init over."""
    cell._weight_ih.set_value(tcell.weight_ih.detach().numpy())
    cell._weight_hh.set_value(tcell.weight_hh.detach().numpy())
    cell._bias_ih.set_value(tcell.bias_ih.detach().numpy())
    cell._bias_hh.set_value(tcell.bias_hh.detach().numpy())


def test_dygraph_lstm_cell_matches_torch():
    """fluid.dygraph.LSTMCell (cudnn layout, i/f/g/o chunks) == torch
    LSTMCell under identical weights."""
    rng = np.random.RandomState(3)
    hidden, inp, batch = 8, 5, 4
    tcell = torch.nn.LSTMCell(inp, hidden)
    cell = dygraph.LSTMCell(hidden, inp, use_cudnn_impl=True)
    _copy_cell_weights(cell, tcell, 4)

    x = rng.randn(batch, inp).astype("f4")
    h = rng.randn(batch, hidden).astype("f4")
    c = rng.randn(batch, hidden).astype("f4")
    th, tc = tcell(torch.tensor(x), (torch.tensor(h), torch.tensor(c)))
    nh, nc = cell(pt.to_tensor(x), pt.to_tensor(h), pt.to_tensor(c))
    np.testing.assert_allclose(nh.numpy(), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(nc.numpy(), tc.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_dygraph_gru_cell_matches_torch():
    """fluid.dygraph.GRUCell (cudnn layout, r/u/c chunks) == torch
    GRUCell under identical weights (u==z, cand==n)."""
    rng = np.random.RandomState(4)
    hidden, inp, batch = 8, 5, 4
    tcell = torch.nn.GRUCell(inp, hidden)
    cell = dygraph.GRUCell(hidden, inp, use_cudnn_impl=True)
    _copy_cell_weights(cell, tcell, 3)

    x = rng.randn(batch, inp).astype("f4")
    h = rng.randn(batch, hidden).astype("f4")
    th = tcell(torch.tensor(x), torch.tensor(h))
    nh = cell(pt.to_tensor(x), pt.to_tensor(h))
    np.testing.assert_allclose(nh.numpy(), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_norm_layers_match_torch_training_mode():
    """BatchNorm (training stats + running-stat update), GroupNorm,
    InstanceNorm, LayerNorm vs torch under identical affine params."""
    from paddle_tpu import nn
    rng = np.random.RandomState(5)
    x = rng.randn(4, 6, 5, 5).astype("f4") * 2 + 1

    # BatchNorm2D training forward + running stats
    bn = nn.BatchNorm2D(6, momentum=0.9)
    tbn = torch.nn.BatchNorm2d(6, momentum=0.1)  # torch momentum = 1-m
    w = rng.rand(6).astype("f4") + 0.5
    b = rng.randn(6).astype("f4")
    bn.weight.set_value(w)
    bn.bias.set_value(b)
    with torch.no_grad():
        tbn.weight.copy_(torch.tensor(w))
        tbn.bias.copy_(torch.tensor(b))
    bn.train()
    tbn.train()
    out = bn(pt.to_tensor(x)).numpy()
    ref = tbn(torch.tensor(x)).detach().numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(bn._mean.numpy()), tbn.running_mean.numpy(),
        rtol=1e-3, atol=1e-5)
    # torch tracks UNBIASED running var, the reference (and this
    # framework) biased: var_torch = 0.9 + 0.1*biased*n/(n-1) while
    # ours = 0.9 + 0.1*biased — relate them exactly
    n = x.shape[0] * x.shape[2] * x.shape[3]
    biased_from_torch = (tbn.running_var.numpy() - 0.9) / 0.1 \
        * (n - 1) / n
    np.testing.assert_allclose(
        np.asarray(bn._variance.numpy()),
        0.9 + 0.1 * biased_from_torch, rtol=1e-3, atol=1e-5)

    # GroupNorm
    gn = nn.GroupNorm(num_groups=3, num_channels=6)
    tgn = torch.nn.GroupNorm(3, 6)
    np.testing.assert_allclose(
        gn(pt.to_tensor(x)).numpy(),
        tgn(torch.tensor(x)).detach().numpy(), rtol=1e-3, atol=1e-4)

    # InstanceNorm
    inn = nn.InstanceNorm2D(6)
    tin = torch.nn.InstanceNorm2d(6, affine=False)
    np.testing.assert_allclose(
        inn(pt.to_tensor(x)).numpy(),
        tin(torch.tensor(x)).detach().numpy(), rtol=1e-3, atol=1e-4)

    # LayerNorm over trailing dims
    ln = nn.LayerNorm([6, 5, 5])
    tln = torch.nn.LayerNorm([6, 5, 5])
    np.testing.assert_allclose(
        ln(pt.to_tensor(x)).numpy(),
        tln(torch.tensor(x)).detach().numpy(), rtol=1e-3, atol=1e-4)


def test_conv_transpose_and_pool_match_torch():
    from paddle_tpu import nn
    rng = np.random.RandomState(6)
    x = rng.randn(2, 3, 8, 8).astype("f4")

    m = nn.Conv2DTranspose(3, 5, 3, stride=2, padding=1)
    tm = torch.nn.ConvTranspose2d(3, 5, 3, stride=2, padding=1)
    with torch.no_grad():
        tm.weight.copy_(torch.tensor(np.asarray(m.weight.numpy())))
        tm.bias.copy_(torch.tensor(np.asarray(m.bias.numpy())))
    np.testing.assert_allclose(
        m(pt.to_tensor(x)).numpy(),
        tm(torch.tensor(x)).detach().numpy(), rtol=1e-3, atol=1e-4)

    # max + avg pool with uneven stride/padding
    mp = nn.MaxPool2D(3, stride=2, padding=1)
    tmp_ = torch.nn.MaxPool2d(3, stride=2, padding=1)
    np.testing.assert_allclose(
        mp(pt.to_tensor(x)).numpy(),
        tmp_(torch.tensor(x)).numpy(), rtol=1e-5)
    ap = nn.AvgPool2D(2, stride=2)
    tap = torch.nn.AvgPool2d(2, stride=2)
    np.testing.assert_allclose(
        ap(pt.to_tensor(x)).numpy(),
        tap(torch.tensor(x)).numpy(), rtol=1e-5)


def test_prelu_and_activations_match_torch():
    from paddle_tpu.nn import functional as F
    rng = np.random.RandomState(7)
    x = rng.randn(4, 9).astype("f4")
    tx = torch.tensor(x)
    pairs = [
        (lambda t: F.elu(t), torch.nn.functional.elu),
        (lambda t: F.gelu(t), lambda v: torch.nn.functional.gelu(v)),
        (lambda t: F.softplus(t), torch.nn.functional.softplus),
        (lambda t: F.hardtanh(t), torch.nn.functional.hardtanh),
        (lambda t: F.log_sigmoid(t), torch.nn.functional.logsigmoid),
        (lambda t: F.tanhshrink(t), torch.nn.functional.tanhshrink),
    ]
    for mine, theirs in pairs:
        np.testing.assert_allclose(
            mine(pt.to_tensor(x)).numpy(), theirs(tx).numpy(),
            rtol=1e-4, atol=1e-5)
