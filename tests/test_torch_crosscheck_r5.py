"""Cross-validation of numerically-tricky ops against torch CPU (the
suite's independent oracle, like the existing ctc-vs-torch check):
grid sampling, affine grids, KL divergence, and the legacy dygraph
LSTM/GRU cells weight-mapped onto torch.nn.LSTMCell/GRUCell."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")

import paddle_tpu as pt
from paddle_tpu import fluid
from paddle_tpu.fluid import dygraph


def test_affine_grid_matches_torch():
    rng = np.random.RandomState(0)
    theta = rng.randn(2, 2, 3).astype("f4")
    out = fluid.layers.affine_grid(pt.to_tensor(theta),
                                   [2, 3, 5, 7]).numpy()
    ref = torch.nn.functional.affine_grid(
        torch.tensor(theta), (2, 3, 5, 7), align_corners=True).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)


def test_grid_sampler_matches_torch():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 3, 6, 5).astype("f4")
    grid = (rng.rand(2, 4, 7, 2).astype("f4") * 2 - 1)
    out = fluid.layers.grid_sampler(pt.to_tensor(x),
                                    pt.to_tensor(grid)).numpy()
    ref = torch.nn.functional.grid_sample(
        torch.tensor(x), torch.tensor(grid), mode="bilinear",
        padding_mode="zeros", align_corners=True).numpy()
    np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)


def test_kldiv_loss_matches_torch():
    rng = np.random.RandomState(2)
    logp = np.log(rng.dirichlet(np.ones(6), size=8).astype("f4") + 1e-8)
    tgt = rng.dirichlet(np.ones(6), size=8).astype("f4")
    for reduction in ("mean", "sum", "batchmean", "none"):
        out = fluid.layers.kldiv_loss(pt.to_tensor(logp),
                                      pt.to_tensor(tgt),
                                      reduction=reduction).numpy()
        ref = torch.nn.functional.kl_div(
            torch.tensor(logp), torch.tensor(tgt),
            reduction=reduction).numpy()
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)


def _copy_cell_weights(cell, tcell, n_gates):
    """paddle cudnn-layout cells and torch cells share the (gates*h, in)
    weight layout and gate order — copy torch's init over."""
    cell._weight_ih.set_value(tcell.weight_ih.detach().numpy())
    cell._weight_hh.set_value(tcell.weight_hh.detach().numpy())
    cell._bias_ih.set_value(tcell.bias_ih.detach().numpy())
    cell._bias_hh.set_value(tcell.bias_hh.detach().numpy())


def test_dygraph_lstm_cell_matches_torch():
    """fluid.dygraph.LSTMCell (cudnn layout, i/f/g/o chunks) == torch
    LSTMCell under identical weights."""
    rng = np.random.RandomState(3)
    hidden, inp, batch = 8, 5, 4
    tcell = torch.nn.LSTMCell(inp, hidden)
    cell = dygraph.LSTMCell(hidden, inp, use_cudnn_impl=True)
    _copy_cell_weights(cell, tcell, 4)

    x = rng.randn(batch, inp).astype("f4")
    h = rng.randn(batch, hidden).astype("f4")
    c = rng.randn(batch, hidden).astype("f4")
    th, tc = tcell(torch.tensor(x), (torch.tensor(h), torch.tensor(c)))
    nh, nc = cell(pt.to_tensor(x), pt.to_tensor(h), pt.to_tensor(c))
    np.testing.assert_allclose(nh.numpy(), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(nc.numpy(), tc.detach().numpy(),
                               rtol=1e-4, atol=1e-5)


def test_dygraph_gru_cell_matches_torch():
    """fluid.dygraph.GRUCell (cudnn layout, r/u/c chunks) == torch
    GRUCell under identical weights (u==z, cand==n)."""
    rng = np.random.RandomState(4)
    hidden, inp, batch = 8, 5, 4
    tcell = torch.nn.GRUCell(inp, hidden)
    cell = dygraph.GRUCell(hidden, inp, use_cudnn_impl=True)
    _copy_cell_weights(cell, tcell, 3)

    x = rng.randn(batch, inp).astype("f4")
    h = rng.randn(batch, hidden).astype("f4")
    th = tcell(torch.tensor(x), torch.tensor(h))
    nh = cell(pt.to_tensor(x), pt.to_tensor(h))
    np.testing.assert_allclose(nh.numpy(), th.detach().numpy(),
                               rtol=1e-4, atol=1e-5)
