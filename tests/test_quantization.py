"""Quantization tests (VERDICT r2 #10; reference:
contrib/slim/quantization/quantization_pass.py + tests in
contrib/slim/tests/test_quantization_pass.py)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt
from paddle_tpu import quantization as Q


def test_fake_quant_levels_and_ste():
    x = pt.to_tensor(np.linspace(-0.95, 0.95, 64).astype("f4"))
    x.stop_gradient = False
    out = Q.fake_quant(x, 1.0, bits=8)
    vals = np.unique(np.round(out.numpy() * 127).astype("i4"))
    assert vals.min() >= -127 and vals.max() <= 127
    # quantization error bounded by half a step
    assert np.abs(out.numpy() - x.numpy()).max() <= (1 / 127) / 2 + 1e-6
    out.sum().backward()
    # straight-through estimator: gradient is 1 inside the clip range
    np.testing.assert_allclose(np.asarray(x.grad), 1.0, atol=1e-6)

    # low-bit: 4-bit has 15 distinct levels max
    out4 = Q.fake_quant(pt.to_tensor(np.linspace(-1, 1, 64).astype("f4")),
                        1.0, bits=4)
    assert len(np.unique(out4.numpy())) <= 15


def test_quant_aware_wraps_and_trains():
    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    model = Q.quant_aware(model)
    kinds = [type(m).__name__ for m in model.sublayers()]
    assert kinds.count("QuantedLinear") == 2
    o = opt.Adam(learning_rate=0.01, parameters=model.parameters())
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.rand(32, 8).astype("f4"))
    y = pt.to_tensor((rng.rand(32, 1) * 2).astype("f4"))
    losses = []
    for _ in range(30):
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5
    # observer accumulated a scale
    for m in model.sublayers():
        if isinstance(m, Q.QuantedLinear):
            assert float(m.act_scale.numpy()) > 0


def test_convert_int8_storage_and_accuracy():
    pt.seed(1)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    rng = np.random.RandomState(1)
    x = pt.to_tensor(rng.rand(8, 16).astype("f4"))
    model.eval()
    ref = model(x).numpy()
    qmodel = Q.convert(model)
    kinds = [type(m).__name__ for m in qmodel.sublayers()]
    assert kinds.count("QuantizedLinear") == 2
    for m in qmodel.sublayers():
        if isinstance(m, Q.QuantizedLinear):
            assert str(m.qweight.numpy().dtype) == "int8"
    got = qmodel(x).numpy()
    # int8 per-channel quantization keeps outputs close
    denom = np.maximum(np.abs(ref), 1e-2)
    assert np.median(np.abs(got - ref) / denom) < 0.05


def test_quant_post_static_calibrates():
    pt.seed(2)
    model = nn.Sequential(nn.Linear(8, 8), nn.ReLU(), nn.Linear(8, 2))
    rng = np.random.RandomState(2)
    batches = [pt.to_tensor(rng.rand(16, 8).astype("f4"))
               for _ in range(4)]
    ref = model(batches[0]).numpy()
    qmodel = Q.quant_post_static(model, batches)
    got = qmodel(batches[0]).numpy()
    assert np.abs(got - ref).max() < 0.2


def test_quant_aware_trains_under_jit():
    """Regression (review r3): QAT under jit.to_static — the observer
    must advance as threaded buffer state and the scale select must be
    traced, not host-evaluated (a zero scale used to collapse activations
    to ±1e-8 under tracing)."""
    from paddle_tpu import jit
    pt.seed(4)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
    model = Q.quant_aware(model)
    o = opt.Adam(learning_rate=0.01, parameters=model.parameters())
    rng = np.random.RandomState(4)
    x = pt.to_tensor(rng.rand(32, 8).astype("f4"))
    y = pt.to_tensor((rng.rand(32, 1) * 2).astype("f4"))

    def step(xb, yb):
        loss = ((model(xb) - yb) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    losses = [float(fn(x, y).numpy()) for _ in range(30)]
    assert losses[-1] < losses[0] * 0.5, losses[::10]
    for m in model.sublayers():
        if isinstance(m, Q.QuantedLinear):
            assert float(m.act_scale.numpy()) > 0.01


def test_quanted_conv2d():
    pt.seed(3)
    m = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU())
    m = Q.quant_aware(m)
    assert any(isinstance(s, Q.QuantedConv2D) for s in m.sublayers())
    x = pt.to_tensor(np.random.rand(2, 3, 8, 8).astype("f4"))
    out = m(x)
    assert out.shape == [2, 8, 8, 8]
    qm = Q.convert(m)
    out2 = qm(x)
    assert out2.shape == [2, 8, 8, 8]


def test_predictor_int8_path():
    """Config.enable_int8 routes the Predictor through PTQ conversion;
    outputs stay close to fp32 on a small net."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.inference import Predictor, Config

    pt.seed(0)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    net.eval()
    x = np.random.RandomState(0).randn(4, 8).astype("f4")
    ref = Predictor(net).run(x)
    ref = ref[0] if isinstance(ref, list) else ref

    q = Predictor(net, Config().enable_int8(calibration_data=[x]))
    out = q.run(x)
    out = out[0] if isinstance(out, list) else out
    assert np.mean(np.abs(out - ref)) < 0.15 * np.mean(np.abs(ref)) + 1e-3


# ---------------------------------------------------------------------------
# int8 COMPUTE path (VERDICT r3 #7): calibrated Predictor layers multiply
# in int8 (dot_general/conv preferred_element_type=int32), float edges only


def test_quantized_linear_int8_compute_parity():
    from paddle_tpu import quantization as Q
    pt.seed(0)
    lin = nn.Linear(16, 8)
    rng = np.random.RandomState(0)
    x = pt.to_tensor(rng.randn(4, 16).astype("f4"))
    ref = lin(x).numpy()

    # PTQ-calibrate -> frozen layer must take the int8 compute path
    model = Q.quant_post_static(nn.Sequential(lin), [x])
    ql = model[0]
    assert isinstance(ql, Q.QuantizedLinear) and ql._int8_compute
    got = model(x).numpy()
    # int8 weights + int8 activations: ~1% of dynamic range tolerance
    tol = 3.0 * float(np.abs(ref).max()) / 127.0
    np.testing.assert_allclose(got, ref, atol=tol)

    # uncalibrated convert stays on the dequant float path
    lin2 = nn.Linear(16, 8)
    m2 = Q.convert(nn.Sequential(lin2))
    assert not m2[0]._int8_compute


def test_quantized_conv_int8_compute_parity():
    from paddle_tpu import quantization as Q
    pt.seed(1)
    conv = nn.Conv2D(3, 6, 3, padding=1)
    rng = np.random.RandomState(1)
    x = pt.to_tensor(rng.randn(2, 3, 8, 8).astype("f4"))
    ref = conv(x).numpy()
    model = Q.quant_post_static(nn.Sequential(conv), [x])
    qc = model[0]
    assert isinstance(qc, Q.QuantizedConv2D) and qc._int8_compute
    got = model(x).numpy()
    tol = 3.0 * float(np.abs(ref).max()) / 127.0
    np.testing.assert_allclose(got, ref, atol=tol)


def test_int8_dot_really_int8():
    """The lowered computation must contain an integer dot (the point of
    the path is MXU int8 throughput, not numerics theater)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu import quantization as Q
    pt.seed(2)
    lin = nn.Linear(8, 4)
    x = pt.to_tensor(np.random.RandomState(2).randn(2, 8).astype("f4"))
    model = Q.quant_post_static(nn.Sequential(lin), [x])
    ql = model[0]

    def f(xv):
        return jax.lax.dot_general(
            jnp.clip(jnp.round(xv), -127, 127).astype(jnp.int8),
            ql.qweight.data, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.int32)

    jaxpr = str(jax.make_jaxpr(f)(x.data))
    assert "preferred_element_type=int32" in jaxpr
    # and the model's own forward output dtype stays float at the edge
    out = model(x)
    assert out.numpy().dtype == np.float32


def test_predictor_stablehlo_export_roundtrip(tmp_path):
    """Predictor.export -> portable StableHLO artifact -> load_exported
    runs WITHOUT the model (weights baked in), bit-matching the live
    Predictor (docs/scope.md serving story)."""
    from paddle_tpu.inference import Config, Predictor, load_exported
    pt.seed(4)
    m = nn.Sequential(nn.Linear(6, 12), nn.ReLU(), nn.Linear(12, 3))
    p = Predictor(m, Config())
    x = np.random.RandomState(4).randn(5, 6).astype("f4")
    ref = p.run(x)
    path = str(tmp_path / "model.stablehlo")
    p.export(path, x)
    assert len(open(path, "rb").read()) > 100
    runner = load_exported(path)
    got = runner(x)
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_int8_gate_follows_loaded_state_dict():
    """A calibrated state_dict loaded into a convert()-built model must
    flip the layer onto the int8 compute path (and zeroing the scale
    must flip it back to the dequant path, not produce garbage)."""
    from paddle_tpu import quantization as Q
    import jax.numpy as jnp
    pt.seed(5)
    lin = nn.Linear(8, 4)
    x = pt.to_tensor(np.random.RandomState(5).randn(3, 8).astype("f4"))
    calibrated = Q.quant_post_static(nn.Sequential(lin), [x])
    state = calibrated.state_dict()

    fresh = Q.convert(nn.Sequential(nn.Linear(8, 4)))
    assert not fresh[0]._int8_compute
    fresh.set_state_dict(state)
    out = fresh(x)  # forward refreshes the gate from the loaded buffer
    assert fresh[0]._int8_compute
    np.testing.assert_allclose(out.numpy(), calibrated(x).numpy(),
                               atol=1e-6)

    # zeroed scale -> back to the (uncalibrated) float path, sane output
    fresh[0].act_scale.data = jnp.zeros((), jnp.float32)
    out2 = fresh(x)
    assert not fresh[0]._int8_compute
    assert np.abs(out2.numpy()).max() < 1e3


def test_predictor_int8_does_not_mutate_callers_model():
    """enable_int8 must quantize a COPY — a later float Predictor from
    the same model object has to produce float results."""
    from paddle_tpu.inference import Config, Predictor
    pt.seed(6)
    m = nn.Sequential(nn.Linear(8, 4))
    x = np.random.RandomState(6).randn(3, 8).astype("f4")
    ref = Predictor(m, Config()).run(x)
    _ = Predictor(m, Config().enable_int8([pt.to_tensor(x)]))
    assert isinstance(m[0], nn.Linear)  # caller's layer untouched
    again = Predictor(m, Config()).run(x)
    np.testing.assert_array_equal(ref, again)


def test_predictor_run_device_chain():
    """run_device returns device arrays (no D2H) and chains: output of
    one call feeds the next; run() still returns numpy."""
    import jax
    import paddle_tpu as pt
    from paddle_tpu import nn
    from paddle_tpu.inference import Config, Predictor

    pt.seed(0)
    m = nn.Sequential(nn.Linear(8, 8), nn.ReLU())
    x = (np.random.RandomState(0).randn(2, 8) * 0.1).astype("f4")
    p = Predictor(m, Config().enable_int8([pt.to_tensor(x)]))
    y = p.run_device(x)
    assert isinstance(y, jax.Array)
    y2 = p.run_device(y)
    assert np.isfinite(np.asarray(y2)).all()
    assert isinstance(p.run(x), np.ndarray)
