"""paddle_tpu.memory_plan — budget-driven rematerialization, overlapped
optimizer-state host offload, bf16 master weights, and the predicted-peak
auto-picker: every mechanism on every surface, with the exactness each
one claims (remat/offload bit-identical, bf16-master tolerance-gated)."""
import os

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import hapi, jit, monitor, nn, optimizer as opt
from paddle_tpu import memory_plan as mp
from paddle_tpu.io import TensorDataset
from paddle_tpu.monitor import memory, profile, trace


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """memory_plan + monitor are process-global; start dark."""
    for var in ("PADDLE_TPU_HBM_LIMIT_BYTES", "PADDLE_TPU_HBM_GB",
                "PADDLE_TPU_HOST_MEM_LIMIT_BYTES",
                "PADDLE_TPU_HOST_LINK_GBPS"):
        monkeypatch.delenv(var, raising=False)
    monitor.disable(flush_counters=False)
    monitor.reset()
    profile.disable()
    profile.reset()
    memory.reset()
    mp.reset()
    trace.disable()
    trace.clear()
    yield
    monitor.disable(flush_counters=False)
    monitor.reset()
    profile.disable()
    profile.reset()
    memory.reset()
    mp.reset()
    trace.disable()
    trace.clear()


# -- policy resolution --------------------------------------------------------

def test_resolve_coercions():
    assert mp.resolve(None) is None
    assert mp.resolve("auto") == "auto"
    p = mp.resolve("full")
    assert p.remat == "full" and not p.offload and not p.master_weights
    p = mp.resolve("offload")
    assert p.offload and p.remat is None
    p = mp.resolve({"remat": "dots", "offload": True,
                    "master_weights": True})
    assert p.remat == "dots" and p.offload and p.master_weights
    rules = (("Linear_0", "full"), (".*", "none"))
    p = mp.resolve(rules)
    assert isinstance(p.remat, tuple) and p.remat[0][0] == "Linear_0"
    existing = mp.MemoryPolicy(remat="full")
    assert mp.resolve(existing) is existing
    with pytest.raises(ValueError):
        mp.resolve("activation_checkpointing")
    with pytest.raises(ValueError):
        mp.resolve({"remat": "full", "bogus_knob": 1})


def test_policy_key_stable_and_canonical():
    assert mp.policy_key(None) == "none"
    assert mp.policy_key("auto") == "auto"
    # an all-defaults policy is the same cache key as no policy
    assert mp.policy_key(mp.resolve({"remat": "none"})) == "none"
    assert mp.policy_key(mp.resolve("full")) == "remat=full"
    assert mp.policy_key(mp.resolve("offload")) == "remat=none,offload"
    k = mp.policy_key(mp.resolve((("fc", "dots"),)))
    assert "rules:" in k and "fc->dots" in k
    # MemoryPolicy is immutable + hashable (it rides in cache keys)
    p = mp.resolve("full")
    with pytest.raises(AttributeError):
        p.remat = "dots"
    hash(p)


# -- shared fixtures ----------------------------------------------------------

class _MLP(nn.Layer):
    def __init__(self, remat=None):
        super().__init__(remat=remat)
        self.l1 = nn.Linear(8, 32)
        self.l2 = nn.Linear(32, 8)

    def forward(self, x):
        return self.l2(nn.functional.relu(self.l1(x)))


def _toy(n=64, d=8, classes=3, seed=0):
    rng = np.random.RandomState(seed)
    w = rng.randn(d, classes)
    x = rng.randn(n, d).astype("f4")
    y = (x @ w).argmax(-1).astype("i4")
    return x, y


def _model(seed=0, lr=0.05):
    pt.seed(seed)
    x, y = _toy()
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=opt.Adam(learning_rate=lr,
                                 parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    return m, x, y


# -- rematerialization: eager + to_static, bit-exact --------------------------

def test_layer_remat_eager_grads_match():
    pt.seed(0)
    m1 = _MLP()
    m2 = _MLP(remat="full")
    m2.set_state_dict(m1.state_dict())
    x = pt.to_tensor(np.random.RandomState(0).randn(4, 8).astype("f4"))
    y1, y2 = m1(x), m2(x)
    np.testing.assert_array_equal(np.asarray(y1.numpy()),
                                  np.asarray(y2.numpy()))
    (y1 * y1).sum().backward()
    (y2 * y2).sum().backward()
    np.testing.assert_array_equal(np.asarray(m1.l1.weight.grad),
                                  np.asarray(m2.l1.weight.grad))


def _tostatic_losses(remat, steps=4):
    pt.seed(0)
    m = _MLP()
    o = opt.Adam(learning_rate=1e-2, parameters=m.parameters())

    def step(xb, yb):
        loss = ((m(xb) - yb) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    sf = jit.to_static(step, models=[m], optimizers=[o], remat=remat)
    out = []
    for i in range(steps):
        rng = np.random.RandomState(42 + i)
        out.append(float(np.asarray(sf(
            pt.to_tensor(rng.randn(4, 8).astype("f4")),
            pt.to_tensor(rng.randn(4, 8).astype("f4"))).numpy())))
    return out


def test_to_static_remat_bit_identical():
    base = _tostatic_losses(None)
    assert _tostatic_losses("full") == base
    assert _tostatic_losses("dots") == base
    # per-layer rules path compiles and matches too
    assert _tostatic_losses((("Linear_0", "full"),)) == base


def test_to_static_remat_marks_hlo(tmp_path):
    monitor.enable(str(tmp_path / "m.jsonl"))
    profile.enable()
    _tostatic_losses("full", steps=1)
    txt = monitor.xla.hlo_text("jit.step")
    assert txt and ("rematted_computation" in txt
                    or "jvp(checkpoint)" in txt)
    rep = memory.report(label="jit.step", emit_records=False)
    assert rep["by_class"].get("remat", 0) > 0
    # the by-class report stays honest: remat bytes came OUT of the
    # stored-activation class, and attribution does not degrade
    profile.reset()
    _tostatic_losses(None, steps=1)
    rep0 = memory.report(label="jit.step", emit_records=False)
    assert (rep["by_class"]["activation"]
            < rep0["by_class"]["activation"])
    assert rep["attributed_frac"] >= rep0["attributed_frac"] - 1e-6


# -- fit(memory=): toggle + auto ----------------------------------------------

def _compiles():
    c = monitor.registry().get("jit.compile")
    return int(c.value) if c is not None else 0


def test_fit_memory_toggle_recompiles_exactly_once(tmp_path):
    monitor.enable(str(tmp_path / "m.jsonl"))
    m, x, y = _model()
    ds = TensorDataset(x, y)
    m.fit(ds, batch_size=16, epochs=1, verbose=0, shuffle=False)
    c0 = _compiles()
    m.fit(ds, batch_size=16, epochs=1, verbose=0, shuffle=False,
          memory="full")
    assert _compiles() - c0 == 1
    c1 = _compiles()
    m.fit(ds, batch_size=16, epochs=1, verbose=0, shuffle=False,
          memory="full")
    assert _compiles() - c1 == 0  # same policy: cache hit


def test_fit_memory_auto_picks_none_when_it_fits(tmp_path):
    monitor.enable(str(tmp_path / "m.jsonl"))
    profile.enable()
    m, x, y = _model()
    m.fit(TensorDataset(x, y), batch_size=16, epochs=1, verbose=0,
          shuffle=False, memory="auto")
    d = mp.last_decision()
    assert d is not None and d["kind"] == "memory_plan"
    assert d["picked"] == "none"  # no HBM limit on CPU: all feasible
    assert mp.policy_key(m._memory) == "none"


# -- offload ------------------------------------------------------------------

def _fit_offload(patched, epochs=2, grad_sync=None, seed=0):
    m, x, y = _model(seed=seed)
    h = m.fit(TensorDataset(x, y), batch_size=16, epochs=epochs,
              verbose=0, shuffle=False, memory="offload",
              grad_sync=grad_sync)
    return m, h["loss"]


def test_offload_bit_identical_to_split_without_paging(monkeypatch):
    """The exactness offload claims: paging the arena's slot buffers to
    host and back changes NOTHING numerically. Both runs use the same
    split fwd/bwd + eager-apply step; only the paging differs."""
    _, on = _fit_offload(False)

    class _Noop(mp.ArenaOffloader):
        def collect(self, arena, count_exposed=True):
            pass

        def page_out(self, arena):
            pass

    real = mp.ArenaOffloader
    monkeypatch.setattr(mp, "ArenaOffloader", _Noop)
    try:
        _, off = _fit_offload(True)
    finally:
        monkeypatch.setattr(mp, "ArenaOffloader", real)
    assert on == off


def test_offload_pages_and_spans_on_own_track():
    trace.enable()
    m, _ = _fit_offload(False, epochs=1)
    off = m._optimizer._offloader
    assert off is not None and off.steps >= 3
    assert off.bytes_out > 0 and off.bytes_in == off.bytes_out
    evs = trace.events()
    d2h = [e for e in evs if e[1] == "offload.d2h"]
    h2d = [e for e in evs if e[1] == "offload.h2d"]
    fit_tids = {e[2] for e in evs if e[1] == "fit.step"}
    assert d2h and h2d
    # worker-thread spans land on their own track, not the step loop's
    assert {e[2] for e in d2h} - fit_tids


def test_offload_checkpoint_resumes_bit_identical(tmp_path):
    """Save mid-training with state offloaded (incl. grad_sync="overlap"
    lag-1 in-flight grads) — restore must produce the exact next step."""
    x, y = _toy()
    for gs in (None, "overlap"):
        m, _ = _fit_offload(False, epochs=1, grad_sync=gs)
        p = str(tmp_path / f"ck_{gs}")
        m.save(p)
        h_a = m.fit(TensorDataset(x, y), batch_size=16, epochs=1,
                    verbose=0, shuffle=False, memory="offload",
                    grad_sync=gs)

        m2, _, _ = _model(seed=1)
        m2.load(p)
        h_b = m2.fit(TensorDataset(x, y), batch_size=16, epochs=1,
                     verbose=0, shuffle=False, memory="offload",
                     grad_sync=gs)
        assert h_a["loss"] == h_b["loss"], f"grad_sync={gs}"


def test_offload_detach_materializes_and_toggles_back():
    m, _ = _fit_offload(False, epochs=1)
    o = m._optimizer
    assert o._offloader is not None
    m.fit(TensorDataset(*_toy()), batch_size=16, epochs=1, verbose=0,
          shuffle=False, memory="none")
    assert o._offloader is None
    assert not m._train_step_split
    # all slot buffers back on device (numpy works, values finite)
    for grp in o._arena.groups:
        for t in grp.slots.values():
            assert np.isfinite(np.asarray(t.numpy())).all()


# -- bf16 master weights ------------------------------------------------------

def test_master_weights_tolerance_and_fp32_checkpoint():
    m_a, x, y = _model()
    h_a = m_a.fit(TensorDataset(x, y), batch_size=16, epochs=2,
                  verbose=0, shuffle=False, flat_arena=True)
    m_b, x, y = _model()
    h_b = m_b.fit(TensorDataset(x, y), batch_size=16, epochs=2,
                  verbose=0, shuffle=False,
                  memory={"master_weights": True})
    for a, b in zip(h_a["loss"], h_b["loss"]):
        assert abs(a - b) < 0.05  # bf16 compute, fp32 master: close
    # outside the trace the leaves are the exact fp32 master
    for p in m_b._optimizer._parameter_list:
        assert str(p.data.dtype) == "float32"
    sd = m_b.network.state_dict()
    for v in sd.values():
        assert str(np.asarray(v.numpy()).dtype) == "float32"


# -- static Executor surface --------------------------------------------------

def _exe_losses(memory, steps=3):
    import paddle_tpu.fluid as fluid
    fluid.enable_static()
    try:
        main, startup = fluid.Program(), fluid.Program()
        with fluid.program_guard(main, startup):
            pt.seed(0)
            x_in = fluid.data("x", [None, 8], "float32")
            y_in = fluid.data("y", [None, 1], "float32")
            h = fluid.layers.fc(x_in, size=16, act="relu")
            p = fluid.layers.fc(h, size=1)
            loss = fluid.layers.mean((p - y_in) * (p - y_in))
            fluid.optimizer.Adam(learning_rate=1e-2).minimize(loss)
        exe = fluid.Executor()
        exe.run(startup)
        rng = np.random.RandomState(0)
        xb = rng.randn(16, 8).astype("f4")
        yb = rng.randn(16, 1).astype("f4")
        out = []
        for _ in range(steps):
            (lv,) = exe.run(main, feed={"x": xb, "y": yb},
                            fetch_list=[loss], memory=memory)
            out.append(float(np.asarray(lv)))
        return out
    finally:
        fluid.disable_static()


def test_executor_remat_bit_identical():
    base = _exe_losses(None)
    assert _exe_losses("full") == base
    assert _exe_losses("dots") == base


def test_executor_offload_falls_back_with_warning():
    base = _exe_losses(None)
    with pytest.warns(RuntimeWarning, match="offload"):
        got = _exe_losses("offload")
    assert got == base  # remat part only (none here): byte-identical


def test_executor_auto_is_loop_level():
    with pytest.raises(ValueError, match="loop-level"):
        _exe_losses("auto", steps=1)


# -- megatron -----------------------------------------------------------------

def test_megatron_remat_tracks_baseline():
    from paddle_tpu.parallel import megatron as M
    mesh, sizes = M.make_mesh(len(__import__("jax").devices()))
    cfg = M.MegatronConfig(hidden=32, n_heads=2, vocab_size=64,
                           seq_len=16, lr=1e-2, use_moe=False)
    toks = np.random.RandomState(0).randint(
        0, cfg.vocab_size,
        (cfg.n_micro, cfg.microbatch * sizes["dp"],
         cfg.seq_len)).astype("i4")

    def run(remat):
        state, step = M.build_train_step(cfg._replace(remat=remat), mesh)
        out = []
        for _ in range(3):
            state, loss = step(state, toks)
            out.append(float(loss))
        return out

    base = run(None)
    got = run("full")
    np.testing.assert_allclose(got, base, rtol=1e-5)


# -- the auto-picker ----------------------------------------------------------

def _captured_report(tmp_path):
    monitor.enable(str(tmp_path / "m.jsonl"))
    profile.enable()
    _tostatic_losses(None, steps=1)
    return memory.report(label="jit.step", emit_records=False)


def test_plan_memory_ladder(tmp_path):
    rep = _captured_report(tmp_path)
    peak = rep["predicted_peak_bytes"]
    act = (rep["by_class"]["activation"]
           + rep["by_class"].get("remat", 0))
    # generous: everything fits -> "none", zero overhead
    d = mp.plan_memory(auto=True, label="jit.step", limit=int(peak * 10))
    assert d["picked"] == "none" and d["overhead_s"] == 0.0
    # between dots and none -> cheapest fitting is dots
    d = mp.plan_memory(auto=True, label="jit.step",
                       limit=int(peak - 0.4 * act))
    assert d["picked"] == "dots"
    assert d["predicted_peak_bytes"] <= d["hbm_limit_bytes"]
    # nothing fits -> refuse with actionable error
    with pytest.raises(ValueError, match="exceeds the budget"):
        mp.plan_memory(auto=True, label="jit.step", limit=1024)
    # decision recorded in the monitor ledger like planner.plan
    assert mp.last_decision()["kind"] == "memory_plan"
    c = monitor.registry().get("memory_plan.auto_pick")
    assert c is not None and int(c.value) >= 2


def test_plan_memory_refuses_host_over_budget(tmp_path, monkeypatch):
    rep = _captured_report(tmp_path)
    peak = rep["predicted_peak_bytes"]
    act = (rep["by_class"]["activation"]
           + rep["by_class"].get("remat", 0))
    opt_b = rep["by_class"]["opt_state"]
    only_offload_fits = int(peak - 0.9 * act - opt_b + 1)
    monkeypatch.setenv("PADDLE_TPU_HOST_MEM_LIMIT_BYTES", "1")
    with pytest.raises(ValueError):
        mp.plan_memory(auto=True, label="jit.step",
                       limit=only_offload_fits)
    # with host room it picks the offload rung instead
    monkeypatch.setenv("PADDLE_TPU_HOST_MEM_LIMIT_BYTES",
                       str(64 << 30))
    d = mp.plan_memory(auto=True, label="jit.step",
                       limit=only_offload_fits)
    assert d["policy"].offload


def test_host_headroom_gauge_published(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_HOST_MEM_LIMIT_BYTES",
                       str(1 << 40))
    from paddle_tpu.monitor import sampler
    reg = monitor.registry()
    sampler.sample_once(reg)
    g = reg.get("mem.host.headroom_bytes")
    assert g is not None
    assert 0 < g.value < (1 << 40)


def test_advise_gains_memory_columns():
    from paddle_tpu.parallel import planner
    from paddle_tpu.parallel.megatron import MegatronConfig
    cfg = MegatronConfig(hidden=32, n_heads=2, vocab_size=64,
                         seq_len=16, use_moe=False)
    rows = planner.advise(n_devices=8, cfg=cfg)
    assert rows
    for r in rows:
        assert r["remat"] in ("none", "dots", "full")
        assert isinstance(r["offload"], bool)
        assert r["mem_overhead_s"] >= 0.0
    # no limit -> everything fits as-is -> advisory columns all "none"
    assert all(r["remat"] == "none" for r in rows
               if r["hbm_limit_bytes"] is None)
    # squeeze: under a tight budget the advisory suggests a rung and
    # feasible/rank semantics stay the as-is verdict
    tight = min(r["peak_hbm_bytes"] for r in rows) * 0.5
    rows2 = planner.advise(n_devices=8, cfg=cfg, hbm_limit=tight)
    assert any(r["remat"] != "none" or r["offload"] for r in rows2)
    assert all(r["feasible"] is False for r in rows2
               if r["peak_hbm_bytes"] > tight)
