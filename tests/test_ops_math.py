"""Per-op numeric tests vs numpy (SURVEY §4; mirrors reference
unittests/test_*_op.py) including finite-difference gradient checks."""
import numpy as np
import pytest

import paddle_tpu as pt


def fd_grad(fn, x, eps=1e-3):
    """Central finite differences of scalar fn at x (numpy)."""
    g = np.zeros_like(x)
    it = np.nditer(x, flags=["multi_index"])
    while not it.finished:
        i = it.multi_index
        xp = x.copy(); xp[i] += eps
        xm = x.copy(); xm[i] -= eps
        g[i] = (fn(xp) - fn(xm)) / (2 * eps)
        it.iternext()
    return g


@pytest.mark.parametrize("op,npop", [
    ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
    ("divide", np.divide), ("maximum", np.maximum), ("minimum", np.minimum),
])
def test_binary_ops(op, npop):
    a = np.random.rand(3, 4).astype("f4") + 0.5
    b = np.random.rand(3, 4).astype("f4") + 0.5
    out = getattr(pt, op)(pt.to_tensor(a), pt.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), npop(a, b), rtol=1e-5)


def test_broadcasting():
    a = np.random.rand(3, 1, 4).astype("f4")
    b = np.random.rand(5, 1).astype("f4")
    out = pt.add(pt.to_tensor(a), pt.to_tensor(b))
    np.testing.assert_allclose(out.numpy(), a + b, rtol=1e-6)


@pytest.mark.parametrize("op,npop", [
    ("exp", np.exp), ("log", np.log), ("sqrt", np.sqrt),
    ("tanh", np.tanh), ("abs", np.abs), ("square", np.square),
    ("floor", np.floor), ("ceil", np.ceil), ("sign", np.sign),
])
def test_unary_ops(op, npop):
    a = np.random.rand(3, 4).astype("f4") + 0.5
    out = getattr(pt, op)(pt.to_tensor(a))
    np.testing.assert_allclose(out.numpy(), npop(a), rtol=1e-5)


@pytest.mark.parametrize("axis,keepdim", [(None, False), (0, False),
                                          (1, True), ((0, 1), False)])
def test_reductions(axis, keepdim):
    a = np.random.rand(3, 4, 2).astype("f4")
    for op, npop in [("sum", np.sum), ("mean", np.mean), ("max", np.max),
                     ("min", np.min)]:
        out = getattr(pt, op)(pt.to_tensor(a), axis=axis, keepdim=keepdim)
        np.testing.assert_allclose(out.numpy(),
                                   npop(a, axis=axis, keepdims=keepdim),
                                   rtol=1e-5)


def test_matmul_transpose_flags():
    a = np.random.rand(4, 3).astype("f4")
    b = np.random.rand(4, 5).astype("f4")
    out = pt.matmul(pt.to_tensor(a), pt.to_tensor(b), transpose_x=True)
    np.testing.assert_allclose(out.numpy(), a.T @ b, rtol=1e-5)


def test_matmul_gradient_fd():
    a = np.random.rand(3, 4).astype("f8")
    b = np.random.rand(4, 2).astype("f8")
    ta = pt.to_tensor(a.astype("f4"), stop_gradient=False)
    tb = pt.to_tensor(b.astype("f4"), stop_gradient=False)
    pt.matmul(ta, tb).sum().backward()
    ga = fd_grad(lambda x: (x @ b).sum(), a)
    gb = fd_grad(lambda y: (a @ y).sum(), b)
    np.testing.assert_allclose(ta.grad, ga, atol=1e-2)
    np.testing.assert_allclose(tb.grad, gb, atol=1e-2)


def test_softmax_xent_gradient_fd():
    logits = np.random.randn(4, 5).astype("f8")
    labels = np.array([1, 0, 3, 2])
    t = pt.to_tensor(logits.astype("f4"), stop_gradient=False)
    loss = pt.ops.loss.softmax_with_cross_entropy(
        t, pt.to_tensor(labels)).mean()
    loss.backward()

    def ref(lg):
        m = lg - lg.max(-1, keepdims=True)
        lse = np.log(np.exp(m).sum(-1)) + lg.max(-1)
        picked = lg[np.arange(4), labels]
        return (lse - picked).mean()

    np.testing.assert_allclose(t.grad, fd_grad(ref, logits), atol=1e-2)


def test_topk_argmax():
    a = np.random.rand(3, 6).astype("f4")
    vals, idx = pt.topk(pt.to_tensor(a), k=2)
    ref_idx = np.argsort(-a, axis=-1)[:, :2]
    np.testing.assert_allclose(np.sort(vals.numpy(), -1),
                               np.sort(np.take_along_axis(a, ref_idx, -1), -1),
                               rtol=1e-6)
    am = pt.argmax(pt.to_tensor(a), axis=1)
    np.testing.assert_array_equal(am.numpy(), a.argmax(1))


def test_comparisons_nondiff():
    a = pt.to_tensor([1.0, 2.0], stop_gradient=False)
    b = pt.to_tensor([2.0, 1.0])
    out = a < b
    assert out.stop_gradient
    np.testing.assert_array_equal(out.numpy(), [True, False])


def test_where_clip():
    a = np.random.randn(4, 4).astype("f4")
    out = pt.clip(pt.to_tensor(a), -0.5, 0.5)
    np.testing.assert_allclose(out.numpy(), np.clip(a, -0.5, 0.5))
    cond = a > 0
    w = pt.where(pt.to_tensor(cond), pt.to_tensor(a), pt.to_tensor(-a))
    np.testing.assert_allclose(w.numpy(), np.abs(a), rtol=1e-6)


def test_cumsum_norm():
    a = np.random.rand(3, 4).astype("f4")
    np.testing.assert_allclose(pt.cumsum(pt.to_tensor(a), axis=1).numpy(),
                               np.cumsum(a, axis=1), rtol=1e-5)
    np.testing.assert_allclose(pt.norm(pt.to_tensor(a)).numpy(),
                               np.linalg.norm(a), rtol=1e-5)


def test_tensor_methods_and_operators():
    a = pt.to_tensor([[1.0, 2.0], [3.0, 4.0]])
    np.testing.assert_allclose((a + 1).numpy(), [[2, 3], [4, 5]])
    np.testing.assert_allclose((2 * a).numpy(), [[2, 4], [6, 8]])
    np.testing.assert_allclose((-a).numpy(), [[-1, -2], [-3, -4]])
    np.testing.assert_allclose((a ** 2).numpy(), [[1, 4], [9, 16]])
    np.testing.assert_allclose(a.reshape([4]).numpy(), [1, 2, 3, 4])
    np.testing.assert_allclose(a[0].numpy(), [1, 2])
    np.testing.assert_allclose(a[:, 1].numpy(), [2, 4])
    np.testing.assert_allclose(a.t().numpy() if hasattr(a, 't')
                               else a.transpose([1, 0]).numpy(),
                               [[1, 3], [2, 4]])


def test_sequence_ops():
    from paddle_tpu.ops import sequence as S
    x = np.arange(24, dtype="f4").reshape(2, 4, 3)
    ln = np.array([2, 4])
    pooled = S.sequence_pool(pt.to_tensor(x), "sum", pt.to_tensor(ln))
    np.testing.assert_allclose(pooled.numpy()[0], x[0, :2].sum(0))
    np.testing.assert_allclose(pooled.numpy()[1], x[1].sum(0))
    last = S.sequence_pool(pt.to_tensor(x), "last", pt.to_tensor(ln))
    np.testing.assert_allclose(last.numpy()[0], x[0, 1])
    sm = S.sequence_softmax(pt.to_tensor(x[..., 0]), pt.to_tensor(ln))
    np.testing.assert_allclose(sm.numpy().sum(1), [1.0, 1.0], atol=1e-5)
    assert (sm.numpy()[0, 2:] == 0).all()
    rev = S.sequence_reverse(pt.to_tensor(x), pt.to_tensor(ln))
    np.testing.assert_allclose(rev.numpy()[0, 0], x[0, 1])
    np.testing.assert_allclose(rev.numpy()[0, 2], x[0, 2])  # pad untouched
    padded, lens = S.sequence_pad([np.ones((2, 3)), np.ones((5, 3))])
    assert padded.shape == [2, 5, 3] and lens.numpy().tolist() == [2, 5]
    unp = S.sequence_unpad(padded, lens)
    assert unp[0].shape == (2, 3) and unp[1].shape == (5, 3)


def test_paddle20_tensor_api_tail():
    """Top-level parity ops vs numpy (reference: python/paddle/tensor)."""
    import numpy as np
    import paddle_tpu as pt
    rng = np.random.RandomState(0)

    a = rng.randn(3, 3).astype("f4")
    spd = (a @ a.T + 3 * np.eye(3)).astype("f4")
    L = pt.cholesky(pt.to_tensor(spd)).numpy()
    np.testing.assert_allclose(L @ L.T, spd, atol=1e-4)
    U = pt.cholesky(pt.to_tensor(spd), upper=True).numpy()
    np.testing.assert_allclose(U.T @ U, spd, atol=1e-4)

    inv = pt.inverse(pt.to_tensor(spd)).numpy()
    np.testing.assert_allclose(inv @ spd, np.eye(3), atol=1e-4)

    x = rng.randn(3, 5).astype("f4")
    y = rng.randn(3, 5).astype("f4")
    # cross with axis=None finds the first length-3 axis (paddle rule)
    np.testing.assert_allclose(
        pt.cross(pt.to_tensor(x), pt.to_tensor(y)).numpy(),
        np.cross(x, y, axis=0), atol=1e-5)

    np.testing.assert_allclose(
        pt.kron(pt.to_tensor(x[:2, :2]), pt.to_tensor(y[:2, :2])).numpy(),
        np.kron(x[:2, :2], y[:2, :2]), atol=1e-5)

    np.testing.assert_allclose(
        float(pt.dist(pt.to_tensor(x), pt.to_tensor(y), p=2).numpy()),
        np.linalg.norm((x - y).ravel()), rtol=1e-5)

    np.testing.assert_allclose(
        float(pt.trace(pt.to_tensor(a)).numpy()), np.trace(a), rtol=1e-5)

    np.testing.assert_allclose(
        pt.std(pt.to_tensor(x), axis=1).numpy(), x.std(1, ddof=1),
        rtol=1e-4)
    np.testing.assert_allclose(
        pt.var(pt.to_tensor(x), axis=0, unbiased=False).numpy(),
        x.var(0), rtol=1e-4)

    idx = rng.randint(0, 5, (3, 2)).astype("i4")
    np.testing.assert_allclose(
        pt.index_sample(pt.to_tensor(x), pt.to_tensor(idx)).numpy(),
        np.take_along_axis(x, idx, axis=1), atol=1e-6)

    z = np.asarray([[1, 0], [0, 2]], "f4")
    nz = pt.nonzero(pt.to_tensor(z)).numpy()
    np.testing.assert_array_equal(nz, [[0, 0], [1, 1]])

    assert bool(pt.allclose(pt.to_tensor(x), pt.to_tensor(x + 1e-9)).numpy())
    assert not bool(pt.has_nan(pt.to_tensor(x)).numpy())
    assert bool(pt.has_inf(pt.to_tensor(
        np.asarray([np.inf], "f4"))).numpy())

    np.testing.assert_allclose(
        pt.addcmul(pt.to_tensor(x), pt.to_tensor(y), pt.to_tensor(y),
                   value=0.5).numpy(), x + 0.5 * y * y, atol=1e-5)

    np.testing.assert_allclose(
        pt.stanh(pt.to_tensor(x)).numpy(),
        1.7159 * np.tanh(0.67 * x), atol=1e-5)

    # reduce_* reference dim/keep_dim signature
    np.testing.assert_allclose(
        pt.reduce_sum(pt.to_tensor(x), dim=1, keep_dim=True).numpy(),
        x.sum(1, keepdims=True), rtol=1e-5)

    assert int(pt.rank(pt.to_tensor(x)).numpy()) == 2
    np.testing.assert_array_equal(pt.shape(pt.to_tensor(x)).numpy(),
                                  [3, 5])
    ct = pt.crop_tensor(pt.to_tensor(x), shape=[2, 3], offsets=[1, 1])
    np.testing.assert_allclose(ct.numpy(), x[1:3, 1:4], atol=1e-6)
