"""Explicit pipeline schedules (GPipe / 1F1B / interleaved) + the
manual-vjp executor (VERDICT r3 #3; reference: fluid/optimizer.py
PipelineOptimizer section programs).

Parity: the executor's loss AND grads on a pp mesh must match a plain
single-device forward/backward over the same stages, for both schedule
kinds, at 8 microbatches."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from paddle_tpu.parallel.pipeline import (build_schedule, pipeline_step,
                                          PipelineSchedule)


# ---------------------------------------------------------------------------
# schedule analytics


def test_1f1b_memory_beats_gpipe_equal_time():
    """Non-interleaved 1F1B: same timeline length as GPipe, far lower
    peak activation memory (the reference's section runner is GPipe-only,
    i.e. always at the `m` end)."""
    for n, m in ((2, 8), (4, 8), (4, 16)):
        g = build_schedule("gpipe", n, m)
        f = build_schedule("1f1b", n, m)
        assert f.n_ticks == g.n_ticks
        assert f.bubble_fraction() == pytest.approx(g.bubble_fraction())
        assert f.peak_live_activations() == min(m, n)
        assert g.peak_live_activations() == m
        assert f.peak_live_activations() < g.peak_live_activations()


def test_interleaved_bubble_beats_gpipe():
    """Interleaved 1F1B (v virtual stages per rank) shrinks the TIME
    bubble vs GPipe at n_micro >= 4."""
    for n, m in ((2, 4), (4, 8), (2, 8)):
        g = build_schedule("gpipe", n, m)
        i2 = build_schedule("interleaved", n, m, n_chunks=2)
        assert i2.bubble_fraction() < g.bubble_fraction()


def test_schedule_tables_are_dependency_valid():
    """Every F(s, mb) fires strictly after F(s-1, mb); every B(s, mb)
    strictly after F(s, mb) and B(s+1, mb)."""
    for kind, v in (("gpipe", 1), ("1f1b", 1), ("interleaved", 2)):
        s = build_schedule(kind, 4, 8, n_chunks=v)
        done_f, done_b = {}, {}
        for t in range(s.n_ticks):
            row = s.table[t]
            for r in range(s.n_ranks):
                op, mb, c = row[r]
                stage = c * s.n_ranks + r
                if op == 1:
                    if stage > 0:
                        assert done_f[(stage - 1, mb)] < t
                    done_f[(stage, mb)] = t
                elif op == 2:
                    assert done_f[(stage, mb)] < t
                    if stage < v * s.n_ranks - 1:
                        assert done_b[(stage + 1, mb)] < t
                    done_b[(stage, mb)] = t
        total = v * s.n_ranks * s.n_micro
        assert len(done_f) == total and len(done_b) == total


# ---------------------------------------------------------------------------
# executor parity


def _stage_fn(x, p):
    return x + jnp.tanh(x @ p["w"] + p["b"])


def _loss_fn(y, lab):
    return jnp.mean((y - lab) ** 2)


def _make_problem(n_stages, m, mb=4, h=8, seed=0):
    rng = np.random.RandomState(seed)
    params = {
        "w": jnp.asarray(rng.randn(n_stages, h, h) * 0.3, jnp.float32),
        "b": jnp.asarray(rng.randn(n_stages, h) * 0.1, jnp.float32),
    }
    x = jnp.asarray(rng.randn(m, mb, h), jnp.float32)
    lab = jnp.asarray(rng.randn(m, mb, h), jnp.float32)
    return params, x, lab


def _reference(params, x, lab, stage_order):
    """Plain autodiff over sequentially-applied stages. stage_order[s] is
    the index into the stacked params holding stage s (identity for v=1,
    the rank-major permutation for interleaved)."""

    def loss(params):
        tot = 0.0
        for i in range(x.shape[0]):
            h = x[i]
            for s in stage_order:
                h = _stage_fn(h, jax.tree_util.tree_map(
                    lambda l: l[s], params))
            tot = tot + _loss_fn(h, lab[i])
        return tot / x.shape[0]

    return jax.value_and_grad(loss)(params)


def _run_on_mesh(schedule, params, x, lab, n_ranks):
    mesh = Mesh(np.asarray(jax.devices()[:n_ranks]), ("pp",))

    def fn(params, x, lab):
        return pipeline_step(schedule, _stage_fn, _loss_fn, params, x,
                             lab, axis="pp")

    return jax.jit(jax.shard_map(
        fn, mesh=mesh,
        in_specs=(jax.tree_util.tree_map(lambda _: P("pp"), params),
                  P(), P()),
        out_specs=(P(), jax.tree_util.tree_map(lambda _: P("pp"), params)),
        check_vma=False))(params, x, lab)


@pytest.mark.parametrize("kind", ["gpipe", "1f1b"])
def test_executor_matches_single_device_8_micro(kind):
    n, m = 4, 8
    params, x, lab = _make_problem(n, m)
    ref_loss, ref_grads = _reference(params, x, lab, range(n))

    sched = build_schedule(kind, n, m)
    loss, grads = _run_on_mesh(sched, params, x, lab, n)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(ref_grads[k]),
                                   rtol=1e-4, atol=1e-6)


def test_executor_interleaved_matches_single_device():
    """v=2 virtual stages per rank: the stacked params are rank-major
    (global index r*v + c holds stage c*n + r)."""
    n, v, m = 2, 2, 8
    n_stages = n * v
    params, x, lab = _make_problem(n_stages, m)
    # stage s lives at stacked index (s % n) * v + s // n
    order = [(s % n) * v + s // n for s in range(n_stages)]
    ref_loss, ref_grads = _reference(params, x, lab, order)

    sched = build_schedule("interleaved", n, m, n_chunks=v)
    loss, grads = _run_on_mesh(sched, params, x, lab, n)
    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(np.asarray(grads[k]),
                                   np.asarray(ref_grads[k]),
                                   rtol=1e-4, atol=1e-6)


def test_executor_trains():
    """SGD over pipeline_step grads actually reduces the loss."""
    n, m = 2, 4
    params, x, lab = _make_problem(n, m, seed=3)
    sched = build_schedule("1f1b", n, m)
    losses = []
    for _ in range(6):
        loss, grads = _run_on_mesh(sched, params, x, lab, n)
        losses.append(float(loss))
        params = jax.tree_util.tree_map(lambda p, g: p - 0.2 * g,
                                        params, grads)
    assert losses[-1] < losses[0] * 0.7, losses


def test_bubble_fraction_bwd_weighted_and_render():
    """Cost-weighted LOCKSTEP accounting (bwd = 2x fwd, tick = max over
    ranks — exactly how the scan executor runs): GPipe's homogeneous
    phases waste nothing on mixed ticks, while interleaved's steady state
    pairs F and B across ranks and stalls the cheap op — so under
    lockstep the interleaved TIME win holds at equal op costs but erodes
    at bwd=2x (an async runtime keeps it; ours keeps the memory win).
    The analytics report this honestly rather than quoting Megatron's
    async-model bubble for a lockstep engine."""
    g = build_schedule("gpipe", 4, 8)
    i2 = build_schedule("interleaved", 4, 8, n_chunks=2)
    assert i2.bubble_fraction() < g.bubble_fraction()          # equal cost
    assert i2.bubble_fraction(bwd_cost=2.0) > g.bubble_fraction(
        bwd_cost=2.0)                                          # lockstep tax
    # weighted gpipe == unweighted gpipe (phases are homogeneous)
    assert g.bubble_fraction(bwd_cost=2.0) == pytest.approx(
        g.bubble_fraction())
    txt = build_schedule("1f1b", 2, 4).render()
    lines = txt.splitlines()
    assert len(lines) == 2 and lines[0].startswith("rank0:")
    assert "F0" in lines[0] and "B3" in lines[1]
