"""Gradient-communication plane (ISSUE 8): bucketed/overlapped/
quantized collectives — ring properties over lengths {2,4,8}, the
fused matmul-reduce-scatter, sync_tree, and the GradSyncScheduler's
lag-1 + checkpoint discipline."""
import numpy as np
import pytest
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu.parallel import collective, overlap


def _ring(n):
    return Mesh(np.array(jax.devices()[:n]).reshape(n), ("dp",))


# -- satellite: quantized ring widths over ring lengths {2,4,8} -----------

@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("bits,rel_bound", [(8, 0.05), (4, 0.35)])
def test_quantized_ring_bounded_error_and_bit_equality(n, bits,
                                                       rel_bound):
    """Property pair the wire format must satisfy at every ring length:
    max-abs error bounded relative to the exact sum's scale (per-hop
    requant compounds, so int4 gets the looser bound), and the
    dequantized result BIT-IDENTICAL on every rank (the all-gather hop
    distributes one owner-quantized chunk; ranks never dequantize
    independently)."""
    rng = np.random.RandomState(n * 10 + bits)
    per_dev = rng.randn(n, 501).astype("f4")  # odd len: int4 pad path
    exact = per_dev.sum(0)
    out = np.asarray(jax.jit(collective.shard_map_compat(
        lambda x: collective.all_reduce_quantized(
            x, axis_name="dp", bits=bits),
        _ring(n), in_specs=P("dp", None),
        out_specs=P("dp", None), check_vma=False))(per_dev))
    scale = np.abs(exact).max()
    assert np.abs(out[0] - exact).max() / scale < rel_bound
    for rk in range(1, n):
        np.testing.assert_array_equal(out[rk], out[0])


@pytest.mark.parametrize("n", [2, 4, 8])
def test_quantized_ring_mean_op(n):
    """op="mean" divides ONCE after the ring — same bit-equality as
    sum, value == sum/n exactly."""
    rng = np.random.RandomState(n)
    per_dev = rng.randn(n, 64).astype("f4")

    def body(x):
        s = collective.all_reduce_quantized(x, axis_name="dp", op="sum")
        m = collective.all_reduce_quantized(x, axis_name="dp",
                                            op="mean")
        return s, m

    s, m = jax.jit(collective.shard_map_compat(
        body, _ring(n), in_specs=P("dp", None),
        out_specs=(P("dp", None), P("dp", None)),
        check_vma=False))(per_dev)
    np.testing.assert_array_equal(np.asarray(m),
                                  np.asarray(s) / np.float32(n))
    for rk in range(1, n):
        np.testing.assert_array_equal(np.asarray(m)[rk],
                                      np.asarray(m)[0])


# -- satellite (r10): error ENVELOPE over ring lengths {2,4,8,16} ---------
# Per-hop requantization compounds once per ring hop, so the relative
# error grows roughly linearly in log2(ring length). The envelope below
# is the measured worst case (5 seeds, 501-elem odd-length payload)
# with ~35% headroom; docs/performance.md §6 turns it into dp-size
# guidance (int8 fine through dp=16, int4 recommended dp<=8).

_QUANT_ENVELOPE = {
    8: lambda n: 0.006 * np.log2(n) + 0.006,
    4: lambda n: 0.10 * np.log2(n) + 0.08,
}


def _quant_worst_rel_err(n, bits, mesh):
    worst = 0.0
    for seed in range(3):
        rng = np.random.RandomState(1000 * n + 17 * bits + seed)
        per_dev = rng.randn(n, 501).astype("f4")
        exact = per_dev.sum(0)
        out = np.asarray(jax.jit(collective.shard_map_compat(
            lambda x: collective.all_reduce_quantized(
                x, axis_name="dp", bits=bits),
            mesh, in_specs=P("dp", None), out_specs=P("dp", None),
            check_vma=False))(per_dev))
        worst = max(worst, float(np.abs(out[0] - exact).max()
                                 / np.abs(exact).max()))
    return worst


@pytest.mark.parametrize("n", [2, 4, 8])
@pytest.mark.parametrize("bits", [8, 4])
def test_quantized_ring_error_envelope(n, bits):
    """Worst-case relative error stays under the published envelope at
    every in-process ring length (the envelope is what the dp-size
    guidance in docs/performance.md promises users)."""
    err = _quant_worst_rel_err(n, bits, _ring(n))
    assert err <= _QUANT_ENVELOPE[bits](n), (n, bits, err)


def test_quantized_ring_error_envelope_dp16():
    """Ring length 16 exceeds the suite's 8 virtual devices, so the
    same envelope check runs in a child process with a 16-device CPU
    topology — the largest dp size the guidance table covers."""
    import os
    import subprocess
    import sys
    import textwrap
    code = textwrap.dedent("""
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["XLA_FLAGS"] = \
            "--xla_force_host_platform_device_count=16"
        import numpy as np, jax
        from jax.sharding import Mesh, PartitionSpec as P
        from paddle_tpu.parallel import collective
        mesh = Mesh(np.array(jax.devices()[:16]).reshape(16), ("dp",))
        for bits, bound in ((8, 0.006 * 4 + 0.006), (4, 0.10 * 4 + 0.08)):
            worst = 0.0
            for seed in range(3):
                rng = np.random.RandomState(16000 + 17 * bits + seed)
                per_dev = rng.randn(16, 501).astype("f4")
                exact = per_dev.sum(0)
                out = np.asarray(jax.jit(collective.shard_map_compat(
                    lambda x: collective.all_reduce_quantized(
                        x, axis_name="dp", bits=bits),
                    mesh, in_specs=P("dp", None),
                    out_specs=P("dp", None), check_vma=False))(per_dev))
                worst = max(worst, float(np.abs(out[0] - exact).max()
                                         / np.abs(exact).max()))
            assert worst <= bound, (bits, worst, bound)
        print("ENVELOPE_OK")
    """)
    env = dict(os.environ)
    env.pop("XLA_FLAGS", None)
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.run([sys.executable, "-c", code], env=env,
                          capture_output=True, text=True, timeout=300)
    assert proc.returncode == 0, proc.stderr[-3000:]
    assert "ENVELOPE_OK" in proc.stdout


def test_quantized_width_and_op_validation():
    """Unsupported widths fail loudly, naming the supported set."""
    with pytest.raises(ValueError, match=r"4, 8"):
        collective.all_reduce_quantized(np.ones(4), bits=2)
    with pytest.raises(ValueError, match=r"16"):
        collective.all_reduce_quantized(np.ones(4), bits=16)
    with pytest.raises(ValueError):
        collective.all_reduce_quantized(np.ones(4), op="max")


# -- satellite: first-class mean reduce -----------------------------------

def test_all_reduce_mean_first_class():
    """op="mean" routes through lax.pmean directly (no hand-divide),
    and an unknown op names the supported set."""
    per_dev = np.arange(8.0, dtype="f4").reshape(8, 1)
    out = collective.shard_map_compat(
        lambda x: collective.all_reduce(pt.Tensor(x), op="mean",
                                        axis_name="dp").data,
        _ring(8), in_specs=P("dp"), out_specs=P("dp"))(per_dev)
    np.testing.assert_allclose(np.asarray(out).ravel(), [3.5] * 8)
    with pytest.raises(ValueError, match="supported"):
        collective.shard_map_compat(
            lambda x: collective.all_reduce(pt.Tensor(x), op="median",
                                            axis_name="dp").data,
            _ring(8), in_specs=P("dp"), out_specs=P("dp"))(per_dev)


# -- tentpole: fused matmul-then-reduce-scatter (tp path) -----------------

@pytest.mark.parametrize("n", [2, 4, 8])
def test_matmul_reduce_scatter_matches_unfused(n):
    """The fused ring schedule (per-block matmul interleaved with
    ppermute hops of the accumulator) must equal the unfused
    psum_scatter(x @ w) reference at every ring length."""
    rng = np.random.RandomState(n)
    m, k, N = 8, 4 * n, 16
    xs = rng.randn(n, m, k // n).astype("f4")
    w = rng.randn(k // n, N).astype("f4")

    def run(fused):
        return np.asarray(jax.jit(collective.shard_map_compat(
            lambda x: collective.matmul_reduce_scatter(
                x[0], w, axis_name="dp", fused=fused).data[None],
            _ring(n), in_specs=P("dp"),
            out_specs=P("dp"), check_vma=False))(xs))

    np.testing.assert_allclose(run(True), run(False), atol=1e-4)
    # eager fallback (no axis context) is a plain matmul
    eager = collective.matmul_reduce_scatter(xs[0], w)
    np.testing.assert_allclose(np.asarray(eager.data), xs[0] @ w,
                               rtol=1e-6)


# -- tentpole: bucket planning + in-SPMD bucketed sync --------------------

def test_plan_buckets_properties():
    sizes = [10, 20, 1000, 5, 5, 2000, 1]
    plan = overlap.plan_buckets(sizes, bucket_bytes=400, itemsize=4)
    # partition: every index exactly once, order preserved
    flat = [i for b in plan for i in b]
    assert flat == list(range(len(sizes)))
    cap = 400 // 4
    for b in plan:
        total = sum(sizes[i] for i in b)
        assert total <= cap or len(b) == 1  # oversized leaf rides alone
    assert [1000] == [sizes[i] for b in plan for i in b if len(b) == 1
                      and sizes[b[0]] > cap][:1]
    assert overlap.plan_buckets([], 400) == []


@pytest.mark.parametrize("mode", ["exact", "quantized", "overlap"])
def test_sync_tree_inside_shard_map(mode):
    """sync_tree reduces every leaf over the axis (mean), restoring
    shapes/dtypes, for all three modes; quantized within wire error."""
    rng = np.random.RandomState(0)
    tree = {"w": rng.randn(8, 6, 5).astype("f4"),
            "b": rng.randn(8, 5).astype("f4")}
    want = {k: v.mean(0) for k, v in tree.items()}
    out = jax.jit(collective.shard_map_compat(
        lambda t: jax.tree_util.tree_map(
            lambda x: x[None],
            overlap.sync_tree(
                jax.tree_util.tree_map(lambda x: x[0], t),
                axis_name="dp", mode=mode, bucket_bytes=64)),
        _ring(8), in_specs=P("dp"), out_specs=P("dp"),
        check_vma=False))(tree)
    tol = 0.2 if mode == "quantized" else 1e-6
    for k in want:
        got = np.asarray(out[k])[0]
        assert got.shape == want[k].shape
        np.testing.assert_allclose(got, want[k], atol=tol)
    with pytest.raises(ValueError, match="mode"):
        overlap.sync_tree(tree, mode="bogus")


# -- tentpole: explicit-DDP scheduler -------------------------------------

def _stacked_grads(rng, n=8):
    return {"w": rng.randn(n, 7, 3).astype("f4"),
            "b": rng.randn(n, 3).astype("f4")}


def test_local_value_and_grad_stacked():
    """Per-rank grads stack [n, *shape]; their mean equals the
    full-batch gradient."""
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(4, 1).astype("f4")}
    x = rng.randn(16, 4).astype("f4")
    y = rng.randn(16, 1).astype("f4")

    def loss_fn(p, batch):
        xb, yb = batch
        return jnp.mean((xb @ p["w"] - yb) ** 2)

    lvg = overlap.local_value_and_grad(loss_fn, _ring(8))
    loss, grads = lvg(params, (jnp.asarray(x), jnp.asarray(y)))
    assert loss.shape == (8,)
    assert grads["w"].shape == (8, 4, 1)
    full = jax.grad(loss_fn)(params, (jnp.asarray(x), jnp.asarray(y)))
    np.testing.assert_allclose(np.asarray(grads["w"]).mean(0),
                               np.asarray(full["w"]), atol=1e-5)


@pytest.mark.parametrize("mode", ["exact", "quantized", "overlap"])
def test_scheduler_reduces_to_rank_mean(mode):
    rng = np.random.RandomState(1)
    grads = _stacked_grads(rng)
    want = {k: v.mean(0) for k, v in grads.items()}
    s = overlap.GradSyncScheduler(mode=mode, mesh=_ring(8),
                                  bucket_bytes=64, async_apply=False)
    try:
        out = s.reduce(grads)
        assert s.compiled_buckets >= 2  # bucket_bytes forces a split
        tol = 0.2 if mode == "quantized" else 1e-6
        for k in want:
            np.testing.assert_allclose(np.asarray(out[k]), want[k],
                                       atol=tol)
        # second reduce with same signature mints no new executables
        minted = s.compiled_buckets
        s.reduce(grads)
        assert s.compiled_buckets == minted
    finally:
        s.shutdown()


def test_scheduler_lag1_semantics():
    """async_apply: warm-up returns None, then each reduce returns the
    PREVIOUS step's synced tree; flush drains the tail exactly once."""
    rng = np.random.RandomState(2)
    g0, g1 = _stacked_grads(rng), _stacked_grads(rng)
    s = overlap.GradSyncScheduler(mode="overlap", mesh=_ring(8),
                                  bucket_bytes=64)
    try:
        assert s.reduce(g0) is None
        out1 = s.reduce(g1)
        np.testing.assert_allclose(np.asarray(out1["w"]),
                                   g0["w"].mean(0), atol=1e-6)
        tail = s.flush()
        np.testing.assert_allclose(np.asarray(tail["w"]),
                                   g1["w"].mean(0), atol=1e-6)
        assert s.flush() is None
    finally:
        s.shutdown()


def test_scheduler_state_dict_bit_identity():
    """Checkpoint mid-pipeline: state_dict MATERIALISES the pending
    synced grads (never flushes them into an early apply); both the
    continuing scheduler and a restored one serve the identical
    numpy-round-tripped tree on their next reduce."""
    rng = np.random.RandomState(3)
    g0, g1, g2 = (_stacked_grads(rng) for _ in range(3))
    mesh = _ring(8)
    a = overlap.GradSyncScheduler(mode="overlap", mesh=mesh,
                                  bucket_bytes=64)
    b = overlap.GradSyncScheduler(mode="overlap", mesh=mesh,
                                  bucket_bytes=64)
    try:
        a.reduce(g0)
        a.reduce(g1)          # pending = synced(g1)
        sd = a.state_dict()
        assert "pending" in sd and all(
            isinstance(x, np.ndarray) for x in sd["pending"])
        b.set_state_dict(sd)
        out_a = a.reduce(g2)  # continuing run serves restored g1-sync
        out_b = b.reduce(g2)
        for k in out_a:
            np.testing.assert_array_equal(np.asarray(out_a[k]),
                                          np.asarray(out_b[k]))
        # and the value really is g1's synced mean
        np.testing.assert_allclose(np.asarray(out_a["w"]),
                                   g1["w"].mean(0), atol=1e-6)
    finally:
        a.shutdown()
        b.shutdown()


def test_scheduler_eager_fallback_and_validation():
    """No mesh: the stacking axis is the reduce axis (host mean); bad
    mode/width rejected at construction."""
    rng = np.random.RandomState(4)
    grads = _stacked_grads(rng, n=4)
    s = overlap.GradSyncScheduler(mode="exact", mesh=None,
                                  async_apply=False)
    out = s.reduce(grads)
    np.testing.assert_allclose(np.asarray(out["w"]),
                               grads["w"].mean(0), atol=1e-6)
    s.shutdown()
    with pytest.raises(ValueError, match="mode"):
        overlap.GradSyncScheduler(mode="sorta")
    with pytest.raises(ValueError, match=r"4, 8"):
        overlap.GradSyncScheduler(bits=3)


# -- wiring: Optimizer.step hook ------------------------------------------

def test_optimizer_set_grad_sync_lag1():
    """Optimizer.set_grad_sync threads the scheduler into _step_body:
    the warm-up step applies nothing (lag-1), the next applies the
    previous grads; "exact" detaches the hook."""
    from paddle_tpu import nn, optimizer as opt

    pt.seed(0)
    lin = nn.Linear(3, 1)
    sgd = opt.SGD(learning_rate=0.1, parameters=lin.parameters())
    sgd.set_grad_sync("overlap")
    assert isinstance(sgd._grad_sync, overlap.GradSyncScheduler)

    x = pt.Tensor(np.ones((2, 3), "f4"))
    w0 = np.asarray(lin.weight.data).copy()
    loss = lin(x).mean()
    loss.backward()
    sgd.step()          # warm-up: grads staged, params untouched
    np.testing.assert_array_equal(np.asarray(lin.weight.data), w0)
    sgd.clear_grad()
    loss = lin(x).mean()
    loss.backward()
    sgd.step()          # applies the staged step-0 grads
    assert not np.array_equal(np.asarray(lin.weight.data), w0)
    sgd._grad_sync.shutdown()
    sgd.set_grad_sync("exact")
    assert sgd._grad_sync is None


def test_scheduler_process_passthrough_sync():
    """Non-async scheduler: process() is the identity on eager pairs
    (GSPMD grads arrive already reduced — accounting only)."""
    s = overlap.GradSyncScheduler(mode="exact", async_apply=False)
    pairs = [(np.zeros(3), np.ones(3, "f4"))]
    assert s.process(pairs) is pairs
    s.shutdown()
