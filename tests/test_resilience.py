"""Fault-tolerant training runtime (paddle_tpu.resilience): fault
injection, retry/backoff, NaN guard, watchdog, preemption-safe
checkpointing and auto-resume — every fault class driven end-to-end."""
import os
import pickle
import signal
import time

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import hapi, io, monitor, nn, optimizer as popt
from paddle_tpu.io import CheckpointManager, TensorDataset
from paddle_tpu.resilience import (NaNGuard, NonFiniteError,
                                   PreemptionHandler, RetryExhausted,
                                   RetryPolicy, TransientError, Watchdog,
                                   faults, retry)
from paddle_tpu.resilience.faults import FaultSpec


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.clear()
    yield
    faults.clear()


@pytest.fixture
def jsonl(tmp_path):
    path = str(tmp_path / "events.jsonl")
    monitor.enable(path)
    yield path
    monitor.disable()


# -- retry/backoff ----------------------------------------------------------

def test_retry_recovers_transient():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientError("hiccup")
        return "ok"

    fast = RetryPolicy(max_attempts=3, base_delay=0.0)
    assert retry.retry_call(flaky, policy=fast) == "ok"
    assert len(calls) == 3


def test_retry_terminal_propagates_immediately():
    calls = []

    def broken():
        calls.append(1)
        raise ValueError("a bug, not flakiness")

    with pytest.raises(ValueError):
        retry.retry_call(broken, policy=RetryPolicy(max_attempts=5,
                                                    base_delay=0.0))
    assert len(calls) == 1


def test_retry_exhaustion_chains_cause():
    def always():
        raise TransientError("persistent")

    with pytest.raises(RetryExhausted) as ei:
        retry.retry_call(always, policy=RetryPolicy(max_attempts=2,
                                                    base_delay=0.0))
    assert isinstance(ei.value.__cause__, TransientError)


def test_retry_never_retries_keyboard_interrupt():
    calls = []

    def interrupted():
        calls.append(1)
        raise KeyboardInterrupt

    with pytest.raises(KeyboardInterrupt):
        retry.retry_call(interrupted,
                         policy=RetryPolicy(max_attempts=5, base_delay=0.0))
    assert len(calls) == 1


def test_backoff_schedule_deterministic():
    a = RetryPolicy(max_attempts=5, base_delay=0.1, seed=42)
    b = RetryPolicy(max_attempts=5, base_delay=0.1, seed=42)
    assert [a.delay(i) for i in range(5)] == [b.delay(i) for i in range(5)]


# -- fault injection --------------------------------------------------------

def test_fault_fires_at_exact_steps_with_budget():
    spec = faults.inject("loader", step=[2, 5], times=2)
    fired = [i for i in range(8) if faults.fire("loader", i)]
    assert fired == [2, 5]
    assert spec.fired == 2
    assert faults.fire("loader", 2) is None  # budget spent


def test_fault_probability_deterministic():
    a = FaultSpec("x", probability=0.5, times=None, seed=123)
    b = FaultSpec("x", probability=0.5, times=None, seed=123)
    assert [a.should_fire(i) for i in range(50)] == \
        [b.should_fire(i) for i in range(50)]


def test_faults_from_env(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FAULTS",
                       '[{"kind": "loader", "step": 3}]')
    specs = faults.load_env()
    assert len(specs) == 1 and specs[0].steps == frozenset((3,))
    with pytest.raises(TransientError):
        faults.maybe_raise("loader", step=3)


# -- DataLoader / prefetch producer recovery --------------------------------

def _range_dataset(n=16, d=4):
    rng = np.random.RandomState(0)
    return TensorDataset(rng.randn(n, d).astype("f4"),
                         np.arange(n, dtype="i4"))


def test_dataloader_retries_injected_loader_fault():
    spec = faults.inject("loader", step=0, times=2)
    dl = io.DataLoader(_range_dataset(), batch_size=4)
    batches = list(dl)
    assert len(batches) == 4  # both transient raises absorbed
    assert spec.fired == 2


def test_dataloader_retry_exhaustion_is_terminal():
    faults.inject("loader", step=0, times=10)
    dl = io.DataLoader(_range_dataset(), batch_size=4)
    with pytest.raises(RetryExhausted):
        list(dl)


def test_dataloader_retry_false_disables():
    faults.inject("loader", step=0, times=1)
    dl = io.DataLoader(_range_dataset(), batch_size=4, retry=False)
    with pytest.raises(TransientError):
        list(dl)


def test_prefetch_producer_survives_transient_fault(jsonl):
    from paddle_tpu.io.prefetch import prefetch_to_device
    spec = faults.inject("loader", step=1, times=2)
    src = [np.full((4,), i, "f4") for i in range(5)]
    out = list(prefetch_to_device(iter(src), size=2))
    assert [int(b[0]) for b in out] == [0, 1, 2, 3, 4]
    assert spec.fired == 2
    assert monitor.counter("resilience.retry").value >= 2


def test_prefetch_drops_after_budget_then_continues(jsonl):
    from paddle_tpu.io.prefetch import prefetch_to_device
    # enough budget to exhaust retries at slot 1: the slot is dropped
    # (counted) and the stream keeps going — no permanent stall
    faults.inject("loader", step=1, times=3)
    src = [np.full((4,), i, "f4") for i in range(5)]
    out = list(prefetch_to_device(iter(src), size=2))
    assert [int(b[0]) for b in out] == [0, 1, 2, 3, 4]
    assert monitor.counter("prefetch.drops").value == 1


def test_prefetch_terminal_error_propagates():
    from paddle_tpu.io.prefetch import prefetch_to_device

    def gen():
        yield np.zeros((4,), "f4")
        raise ValueError("terminal")

    with pytest.raises(ValueError):
        list(prefetch_to_device(gen(), size=2))


# -- checkpoint hardening ---------------------------------------------------

class _Net(nn.Layer):
    def __init__(self):
        super().__init__()
        self.fc = nn.Linear(4, 2)

    def forward(self, x):
        return self.fc(x)


def test_checkpoint_save_is_atomic(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    cm.save(3, model=_Net())
    files = sorted(os.listdir(tmp_path))
    assert files == ["ckpt-3.pkl", "ckpt-3.pkl.sha256"]  # no stray .tmp
    with open(tmp_path / "ckpt-3.pkl", "rb") as f:
        state = pickle.load(f)
    assert state["step"] == 3 and "model" in state


def test_truncated_checkpoint_never_wins(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    net = _Net()
    cm.save(1, model=net)
    cm.save(2, model=net)
    with open(cm._path(2), "wb") as f:
        f.write(b"\x80truncated-mid-write")  # simulated SIGKILL mid-save
    with pytest.warns(UserWarning, match="skipping"):
        assert cm.latest_step() == 1


def test_restore_quarantines_corrupt_and_falls_back(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    net = _Net()
    cm.save(1, model=net)
    w1 = net.fc.weight.numpy().copy()
    net.fc.weight.set_value(w1 + 1.0)
    cm.save(2, model=net)
    with open(cm._path(2), "ab") as f:
        f.write(b"garbage")  # checksum mismatch
    with pytest.warns(UserWarning, match="quarantining"):
        state = cm.restore(model=net)
    assert state["step"] == 1
    np.testing.assert_array_equal(net.fc.weight.numpy(), w1)
    assert os.path.exists(cm._path(2) + ".corrupt")
    assert not os.path.exists(cm._path(2))


def test_restore_explicit_corrupt_step_raises(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    cm.save(1, model=_Net())
    with open(cm._path(1), "wb") as f:
        f.write(b"junk")
    with pytest.warns(UserWarning):
        with pytest.raises(ValueError):
            cm.restore(model=_Net(), step=1)


def test_checkpoint_without_sidecar_validates_by_unpickle(tmp_path):
    cm = CheckpointManager(str(tmp_path))
    cm.save(4, model=_Net())
    os.remove(cm._path(4) + ".sha256")  # crash between data and sidecar
    assert cm.latest_step() == 4


# -- NaN guard --------------------------------------------------------------

def _sgd_step(net, x, y):
    o = popt.SGD(learning_rate=0.1, parameters=net.parameters())
    pred = net(pt.to_tensor(x))
    loss = (pred - pt.to_tensor(y)).square().mean()
    loss.backward()
    o.step()
    o.clear_grad()
    return loss


def test_guard_skip_leaves_params_unchanged():
    net = _Net()
    w0 = net.fc.weight.numpy().copy()
    x = np.full((4, 4), np.nan, "f4")
    y = np.zeros((4, 2), "f4")
    with NaNGuard("skip") as g:
        _sgd_step(net, x, y)
    np.testing.assert_array_equal(net.fc.weight.numpy(), w0)
    assert g.total_nonfinite == 1
    # a finite step afterwards still applies
    _sgd_step(net, np.ones((4, 4), "f4"), y)
    assert not np.array_equal(net.fc.weight.numpy(), w0)


def test_guard_raise_policy():
    net = _Net()
    x = np.full((4, 4), np.nan, "f4")
    with NaNGuard("raise"):
        with pytest.raises(NonFiniteError):
            _sgd_step(net, x, np.zeros((4, 2), "f4"))


def test_guard_max_consecutive_bounds_skip():
    net = _Net()
    x = np.full((4, 4), np.nan, "f4")
    y = np.zeros((4, 2), "f4")
    with NaNGuard("skip", max_consecutive=2) as g:
        _sgd_step(net, x, y)
        _sgd_step(net, x, y)
        with pytest.raises(NonFiniteError):
            _sgd_step(net, x, y)
    assert g.total_nonfinite == 3


def test_guard_skip_vs_rollback_parity(tmp_path):
    """Static-graph parity: a skipped NaN step leaves params exactly at
    their pre-step values; a rollback restores exactly the checkpoint."""
    from paddle_tpu import static

    static.reset_default_programs()
    pt.enable_static()
    try:
        net = nn.Linear(3, 1)
        x = static.data("x", [None, 3], "float32")
        y = static.data("y", [None, 1], "float32")
        loss = (net(x) - y).square().mean()
        popt.SGD(learning_rate=0.1).minimize(loss)
        exe = static.Executor()
        main = static.default_main_program()
        rng = np.random.RandomState(0)
        feed = {"x": rng.randn(4, 3).astype("f4"),
                "y": rng.randn(4, 1).astype("f4")}
        bad = dict(feed, x=np.full((4, 3), np.nan, "f4"))

        g = NaNGuard("skip")
        exe.run(feed=feed, fetch_list=[loss], nan_guard=g)
        before = {n: np.asarray(p.data) for n, p in main.param_vars.items()}
        exe.run(feed=bad, fetch_list=[loss], nan_guard=g)
        for n, v in before.items():
            np.testing.assert_array_equal(
                v, np.asarray(main.param_vars[n].data))
        assert g.total_nonfinite == 1

        cm = CheckpointManager(str(tmp_path))
        cm.save(7, program=main)
        ckpt = {n: np.asarray(p.data) for n, p in main.param_vars.items()}
        exe.run(feed=feed, fetch_list=[loss], nan_guard=g)  # params move on
        g2 = NaNGuard("rollback_to_last_ckpt", checkpoint_manager=cm)
        exe.run(feed=bad, fetch_list=[loss], nan_guard=g2)
        for n, v in ckpt.items():
            np.testing.assert_array_equal(
                v, np.asarray(main.param_vars[n].data))
        assert g2.total_nonfinite == 1
    finally:
        pt.disable_static()
        static.reset_default_programs()


# -- hapi fit end-to-end ----------------------------------------------------

def _toy():
    rng = np.random.RandomState(0)
    w = rng.randn(8, 3)
    x = rng.randn(64, 8).astype("f4")
    y = (x @ w).argmax(-1).astype("i4")
    return TensorDataset(x, y)


def _model():
    pt.seed(7)
    net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 3))
    m = hapi.Model(net)
    m.prepare(optimizer=popt.SGD(learning_rate=0.05,
                                 parameters=m.parameters()),
              loss_function=hapi.CrossEntropy())
    return m


def test_fit_nan_skip_keeps_loss_finite(jsonl):
    spec = faults.inject("nan_grad", step=1)
    g = NaNGuard("skip")
    h = _model().fit(_toy(), batch_size=16, epochs=2, verbose=0,
                     shuffle=False, nan_guard=g)
    assert spec.fired == 1
    assert g.total_nonfinite == 1
    assert np.isfinite(h["loss"]).all()
    events = [r["event"] for r in monitor.read_jsonl(jsonl)
              if r.get("kind") == "resilience"]
    assert "nan_skip" in events and "fault_injected" in events


def test_fit_nan_rollback_restores_checkpoint(tmp_path):
    faults.inject("nan_grad", step=2)
    g = NaNGuard("rollback_to_last_ckpt")
    h = _model().fit(_toy(), batch_size=16, epochs=1, verbose=0,
                     shuffle=False, checkpoint=str(tmp_path),
                     save_steps=1, nan_guard=g)
    assert g.total_nonfinite == 1
    assert np.isfinite(h["loss"]).all()


def test_fit_preempt_fault_saves_and_resumes(tmp_path, jsonl):
    # 4 steps/epoch; preempt at global step 5 = epoch 1, batch 1
    faults.inject("preempt", step=5)
    cm = CheckpointManager(str(tmp_path))
    m = _model()
    m.fit(_toy(), batch_size=16, epochs=4, verbose=0, shuffle=False,
          checkpoint=cm)
    assert m.stop_training
    assert cm.latest_step() == 5
    w_saved = m.network[0].weight.numpy().copy()

    faults.clear()
    m2 = _model()
    h = m2.fit(_toy(), batch_size=16, epochs=4, verbose=0, shuffle=False,
               checkpoint=cm, auto_resume=True)
    assert np.isfinite(h["loss"]).all()
    records = [r for r in monitor.read_jsonl(jsonl)
               if r.get("kind") == "resilience"]
    events = {r["event"] for r in records}
    assert {"preempt_save", "auto_resume"} <= events
    resume = next(r for r in records if r["event"] == "auto_resume")
    assert resume["step"] == 6  # continues at the step AFTER the save
    # the resumed run picked up the preempted run's weights, then trained
    assert not np.array_equal(m2.network[0].weight.numpy(), w_saved)


def test_fit_real_sigterm_triggers_cooperative_save(tmp_path):
    cm = CheckpointManager(str(tmp_path))

    class _Preempt(hapi.Callback):
        def on_train_batch_end(self, step, logs=None):
            if step == 1:
                signal.raise_signal(signal.SIGTERM)

    m = _model()
    m.fit(_toy(), batch_size=16, epochs=2, verbose=0, shuffle=False,
          checkpoint=cm, callbacks=[_Preempt()])
    assert m.stop_training
    assert cm.latest_step() == 1  # saved at the signalled step's boundary
    # handler restored: a later SIGTERM must not be swallowed
    assert signal.getsignal(signal.SIGTERM) == signal.SIG_DFL


def test_executor_train_from_dataset_resumes(tmp_path):
    from paddle_tpu import static

    static.reset_default_programs()
    pt.enable_static()
    try:
        class _Ds:
            def __init__(self, n):
                self.n = n

            def _batches(self):
                rng = np.random.RandomState(0)
                for _ in range(self.n):
                    yield {"x": rng.randn(4, 3).astype("f4"),
                           "y": rng.randn(4, 1).astype("f4")}

        net = nn.Linear(3, 1)
        x = static.data("x", [None, 3], "float32")
        y = static.data("y", [None, 1], "float32")
        loss = (net(x) - y).square().mean()
        popt.SGD(learning_rate=0.05).minimize(loss)
        exe = static.Executor()
        faults.inject("preempt", step=2)
        exe.train_from_dataset(dataset=_Ds(6), fetch_list=[loss],
                               checkpoint=str(tmp_path))
        cm = CheckpointManager(str(tmp_path))
        assert cm.latest_step() == 2
        faults.clear()
        exe.train_from_dataset(dataset=_Ds(6), fetch_list=[loss],
                               checkpoint=cm, auto_resume=True,
                               nan_guard="skip")
    finally:
        pt.disable_static()
        static.reset_default_programs()


# -- watchdog ---------------------------------------------------------------

def test_watchdog_flags_slow_step(jsonl):
    wd = Watchdog(min_deadline=0.05, poll=0.01).start()
    try:
        with wd.step(0):
            time.sleep(0.02)  # fast: no stall
        assert wd.stall_count == 0
        with wd.step(1):
            time.sleep(0.2)  # hung
    finally:
        wd.stop()
    assert wd.stall_count == 1
    dumps = [r for r in monitor.read_jsonl(jsonl)
             if r.get("kind") == "watchdog_dump"]
    assert dumps and dumps[0]["step"] == 1 and "counters" in dumps[0]


def test_watchdog_deadline_tracks_p99():
    wd = Watchdog(min_deadline=0.01, factor=4.0, warmup=3)
    assert wd.deadline() == 0.01
    for _ in range(10):
        wd._durations.append(0.1)
    assert wd.deadline() == pytest.approx(0.4)


def test_fit_watchdog_on_injected_slow_step():
    faults.inject("slow_step", step=2, delay=0.5)
    wd = Watchdog(min_deadline=10.0, poll=0.02)
    # force a tiny deadline only for the injected stall: min_deadline
    # high enough that compile steps don't trip it would make the test
    # slow, so drive the deadline directly
    wd.min_deadline = 0.25
    _model().fit(_toy(), batch_size=16, epochs=1, verbose=0, shuffle=False,
                 watchdog=wd)
    assert wd.stall_count >= 1


# -- preemption handler unit ------------------------------------------------

def test_preemption_handler_chains_and_restores():
    seen = []
    prev = signal.getsignal(signal.SIGTERM)
    signal.signal(signal.SIGTERM, lambda s, f: seen.append(s))
    try:
        h = PreemptionHandler(signals=(signal.SIGTERM,)).install()
        signal.raise_signal(signal.SIGTERM)
        assert h.triggered
        assert seen == [signal.SIGTERM]  # previous handler still ran
        h.uninstall()
        signal.raise_signal(signal.SIGTERM)
        assert seen == [signal.SIGTERM, signal.SIGTERM]
    finally:
        signal.signal(signal.SIGTERM, prev)


def test_preemption_request_without_signal():
    h = PreemptionHandler()
    assert not h.triggered
    h.request()
    assert h.triggered
