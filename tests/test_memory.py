"""paddle_tpu.monitor.memory — buffer liveness over scheduled HLO,
peak-occupancy simulation + XLA reconciliation, per-scope contributor
attribution, planner HBM feasibility, OOM forensics, and the
zero-cost-when-disabled contract."""
import json

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import jit, monitor, nn, optimizer as opt
import paddle_tpu.nn.functional as F
from paddle_tpu.monitor import memory, profile, trace
from paddle_tpu.monitor.registry import read_jsonl


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    """memory + profile + monitor are process-global; start dark."""
    for var in ("PADDLE_TPU_HBM_LIMIT_BYTES", "PADDLE_TPU_HBM_GB",
                "PADDLE_TPU_PROFILE"):
        monkeypatch.delenv(var, raising=False)
    monitor.disable(flush_counters=False)
    monitor.reset()
    profile.disable()
    profile.reset()
    memory.reset()
    trace.disable()
    trace.clear()
    # the flight recorder's rate cap is a process-global counter; restore
    # it so the dumps these tests trigger don't starve later test files
    flight_dumps = trace._flight_dumps
    yield
    trace._flight_dumps = flight_dumps
    monitor.disable(flush_counters=False)
    monitor.reset()
    profile.disable()
    profile.reset()
    memory.reset()
    trace.disable()
    trace.clear()


# -- synthetic HLO for the liveness units -------------------------------------

# two temps with overlapping intervals feeding the root
CHAIN_HLO = """\
HloModule chain, is_scheduled=true

ENTRY %main.1 (a: f32[4,8], b: f32[8,16]) -> f32[4,16] {
  %a = f32[4,8]{1,0} parameter(0)
  %b = f32[8,16]{1,0} parameter(1)
  %dot.1 = f32[4,16]{1,0} dot(f32[4,8]{1,0} %a, f32[8,16]{1,0} %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/jit(main)/root/L0/dot_general"}
  %exp.1 = f32[4,16]{1,0} exponential(f32[4,16]{1,0} %dot.1), metadata={op_name="jit(f)/jit(main)/root/L0/exp"}
  ROOT %add.1 = f32[4,16]{1,0} add(f32[4,16]{1,0} %dot.1, f32[4,16]{1,0} %exp.1), metadata={op_name="jit(f)/jit(main)/root/L0/add"}
}
"""

# output 0 is written in place into donated parameter 0
DONATED_HLO = """\
HloModule donate, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias) }

ENTRY %main.2 (p0: f32[8,8], p1: f32[8,8]) -> (f32[8,8], f32[8,8]) {
  %p0 = f32[8,8]{1,0} parameter(0)
  %p1 = f32[8,8]{1,0} parameter(1)
  %add.1 = f32[8,8]{1,0} add(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p1)
  %mul.1 = f32[8,8]{1,0} multiply(f32[8,8]{1,0} %p0, f32[8,8]{1,0} %p1)
  ROOT %tuple.1 = (f32[8,8]{1,0}, f32[8,8]{1,0}) tuple(f32[8,8]{1,0} %add.1, f32[8,8]{1,0} %mul.1)
}
"""

# the fusion body's %exp.1 is internal — only the fusion output is a buffer
FUSED_HLO = """\
HloModule fused, is_scheduled=true

%fused_computation (p0: f32[4,8]) -> f32[4,8] {
  %p0 = f32[4,8]{1,0} parameter(0)
  %exp.1 = f32[4,8]{1,0} exponential(f32[4,8]{1,0} %p0)
  ROOT %iadd.1 = f32[4,8]{1,0} add(f32[4,8]{1,0} %exp.1, f32[4,8]{1,0} %p0)
}

%region.1 (x: f32[], y: f32[]) -> f32[] {
  %x = f32[] parameter(0)
  %y = f32[] parameter(1)
  ROOT %radd.2 = f32[] add(f32[] %x, f32[] %y)
}

ENTRY %main.3 (a: f32[4,8]) -> f32[4] {
  %a = f32[4,8]{1,0} parameter(0)
  %fus = f32[4,8]{1,0} fusion(f32[4,8]{1,0} %a), kind=kLoop, calls=%fused_computation
  %c0 = f32[] constant(0)
  ROOT %reduce.1 = f32[4]{0} reduce(f32[4,8]{1,0} %fus, f32[] %c0), dimensions={1}, to_apply=%region.1
}
"""

# params labeled the way jit.to_static labels them; one consumed only
# by the optimizer scope, one a data array, one a weight
CLASS_HLO = """\
HloModule klass, is_scheduled=true

ENTRY %main.4 (w: f32[8,8], x: f32[8,8], m: f32[8,8]) -> f32[8,8] {
  %w = f32[8,8]{1,0} parameter(0), metadata={op_name="state_vals[0]"}
  %x = f32[8,8]{1,0} parameter(1), metadata={op_name="arrays[0]"}
  %m = f32[8,8]{1,0} parameter(2), metadata={op_name="state_vals[1]"}
  %dot.1 = f32[8,8]{1,0} dot(f32[8,8]{1,0} %w, f32[8,8]{1,0} %x), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(f)/jit(main)/root/L0/dot_general"}
  %madd.1 = f32[8,8]{1,0} add(f32[8,8]{1,0} %m, f32[8,8]{1,0} %m), metadata={op_name="jit(f)/jit(main)/root/opt.Adam/add"}
  ROOT %mul.1 = f32[8,8]{1,0} multiply(f32[8,8]{1,0} %dot.1, f32[8,8]{1,0} %madd.1), metadata={op_name="jit(f)/jit(main)/root/opt.Adam/mul"}
}
"""

SCOPES = {"root": "root", "L0": "layer", "opt.Adam": "optimizer"}


# -- liveness units -----------------------------------------------------------

def test_intervals_overlap_at_peak():
    live = memory.liveness(CHAIN_HLO, scope_map=SCOPES)
    b = live["buffers"]
    # params resident the whole schedule
    assert b["a"]["def_idx"] == 0 and b["a"]["last_use"] == 4
    assert b["a"]["space"] == "argument"
    # dot.1 defined at slot 2, kept alive through the root's read
    assert (b["dot.1"]["def_idx"], b["dot.1"]["last_use"]) == (2, 4)
    assert (b["exp.1"]["def_idx"], b["exp.1"]["last_use"]) == (3, 4)
    # root output lives to the end
    assert b["add.1"]["space"] == "output"
    sim = memory.simulate(CHAIN_HLO, scope_map=SCOPES)
    # peak: both params + dot + exp + out all live at the last slot
    args = 4 * (4 * 8 + 8 * 16)
    assert sim["argument_bytes"] == args
    assert sim["predicted_peak_bytes"] == args + 3 * (4 * 4 * 16)
    assert sim["peak_index"] == 4
    assert sim["curve"][0] == args          # only params before slot 2
    assert sim["attributed_frac"] == 1.0    # everything reaches L0


def test_donated_output_contributes_no_bytes():
    assert memory.parse_io_alias(DONATED_HLO) == {0: 0}
    sim = memory.simulate(DONATED_HLO)
    b = memory.liveness(DONATED_HLO)["buffers"]
    assert b["add.1"]["donated"] and not b["mul.1"]["donated"]
    assert b["mul.1"]["space"] == "output"
    assert sim["n_donated"] == 1
    assert sim["donated_bytes"] == 256      # f32[8,8] counted once
    # peak = two 256B params + the one non-donated output
    assert sim["predicted_peak_bytes"] == 2 * 256 + 256
    assert sim["output_bytes"] == 256


def test_fusion_internal_temps_excluded():
    live = memory.liveness(FUSED_HLO)
    b = live["buffers"]
    # the fusion body's exp never allocates at top level; the constant
    # and the ROOT reduce's to_apply body don't either
    assert "exp.1" not in b and "iadd.1" not in b and "radd.2" not in b
    assert "c0" not in b
    assert set(b) == {"a", "fus", "reduce.1"}
    assert live["schedule_len"] == 4
    sim = memory.simulate(FUSED_HLO)
    # peak: param 128 + fusion out 128 + reduce out 16
    assert sim["predicted_peak_bytes"] == 128 + 128 + 16


def test_contributor_classification():
    sim = memory.simulate(CLASS_HLO, scope_map=SCOPES)
    k = {c["name"]: c["class"] for c in sim["contributors"]}
    assert k["w"] == "param"            # weight read by a layer
    assert k["x"] == "activation"       # arrays[...] data input
    assert k["m"] == "opt_state"        # consumed only by opt.Adam
    assert k["dot.1"] == "activation"   # layer-scope intermediate
    assert k["madd.1"] == "opt_state"   # optimizer-scope intermediate
    by = sim["by_class"]
    # w | x + dot.1 | m + madd.1 + the opt-scoped root output mul.1
    assert by["param"] == 256 and by["opt_state"] == 3 * 256
    assert sim["attributed_frac"] == 1.0
    # ledger is ranked, largest first, ranks dense from 1
    ranks = [c["rank"] for c in sim["contributors"]]
    assert ranks == list(range(1, len(ranks) + 1))
    sizes = [c["bytes"] for c in sim["contributors"]]
    assert sizes == sorted(sizes, reverse=True)


def test_curve_counter_events_decimate_and_preserve_peak():
    sim = memory.simulate(CHAIN_HLO, scope_map=SCOPES)
    sim["label"] = "unit"
    evs = memory.curve_counter_events(sim, max_points=2)
    assert 0 < len(evs) <= 2
    assert all(name == "hbm.predicted[unit]" for name, _, _ in evs)
    assert max(v["bytes"] for _, v, _ in evs) == \
        sim["predicted_peak_bytes"]
    ts = [t for _, _, t in evs]
    assert ts == sorted(ts)


# -- the device budget --------------------------------------------------------

def test_device_hbm_limit_env_overrides(monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_HBM_LIMIT_BYTES", "12345")
    assert memory.device_hbm_limit() == 12345
    monkeypatch.delenv("PADDLE_TPU_HBM_LIMIT_BYTES")
    monkeypatch.setenv("PADDLE_TPU_HBM_GB", "2")
    assert memory.device_hbm_limit() == 2 * (1 << 30)


def test_device_hbm_limit_kind_table():
    assert memory.device_hbm_limit("TPU v5p") == 95 * (1 << 30)
    assert memory.device_hbm_limit("TPU v5 lite") == 16 * (1 << 30)
    # unknown kind: no budget, no invented verdicts
    assert memory.device_hbm_limit("M2 Ultra") is None


# -- OOM detection ------------------------------------------------------------

def test_is_oom_error_shapes():
    assert memory.is_oom_error(MemoryError())
    assert memory.is_oom_error(RuntimeError(
        "RESOURCE_EXHAUSTED: Out of memory while trying to allocate "
        "1073741824 bytes"))
    assert memory.is_oom_error(RuntimeError("Allocation of 4.0G exceeds "
                                            "free HBM"))
    assert not memory.is_oom_error(ValueError("shapes do not match"))
    # "OOM" must match as a word — not the tail of "boom"/"zoom"
    assert memory.is_oom_error(RuntimeError("OOM when allocating tensor"))
    assert not memory.is_oom_error(RuntimeError("boom"))
    # the cause chain is walked
    try:
        try:
            raise RuntimeError("RESOURCE_EXHAUSTED: oom")
        except RuntimeError as inner:
            raise ValueError("step failed") from inner
    except ValueError as outer:
        assert memory.is_oom_error(outer)


def test_handle_oom_ignores_non_oom():
    assert memory.handle_oom(ValueError("nope"), where="unit") is None
    assert memory.last_oom() is None


# -- end-to-end: jitted MLP + Adam on CPU -------------------------------------

def _mlp_step(tmp_path, hidden=32):
    monitor.enable(str(tmp_path))
    profile.enable()
    model = nn.Sequential(nn.Linear(16, hidden), nn.ReLU(),
                          nn.Linear(hidden, 10))
    adam = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

    @jit.to_static(models=[model], optimizers=[adam])
    def step(x, y):
        logits = model(x)
        loss = F.cross_entropy(logits, y)
        loss.backward()
        adam.step()
        return loss

    x = pt.to_tensor(np.random.RandomState(0).randn(8, 16)
                     .astype("float32"))
    y = pt.to_tensor(np.arange(8).astype("int64") % 10)
    step(x, y)
    return step


def test_mlp_adam_reconciliation_and_attribution(tmp_path):
    _mlp_step(tmp_path)
    rep = memory.report(top_k=8)
    assert rep is not None and rep["label"] == "jit.step"
    # the acceptance bars: predicted within 10% of XLA's own peak,
    # ≥90% of live-at-peak bytes attributed to a framework scope
    assert rep["xla_peak_bytes"] and rep["xla_peak_bytes"] > 0
    assert rep["reconciliation"] == pytest.approx(1.0, abs=0.10)
    assert rep["attributed_frac"] >= 0.90
    # donation found: Adam updates weights/slots in place
    assert rep["n_donated"] > 0 and rep["donated_bytes"] > 0
    # all four classes carry bytes in a train step
    by = rep["by_class"]
    assert by["param"] > 0 and by["opt_state"] > 0
    assert by["activation"] > 0
    # ledger sorted + Adam slots visible among contributors
    classes = {c["class"] for c in rep["contributors"]}
    assert "param" in classes and "opt_state" in classes
    # gauges + JSONL landed
    assert monitor.registry().value(
        "memory.predicted_peak_bytes.jit.step", 0) == \
        rep["predicted_peak_bytes"]
    recs = [r for r in read_jsonl(monitor.jsonl_path())
            if r.get("kind") == "memory_report"]
    assert recs and recs[-1]["label"] == "jit.step"
    assert recs[-1]["attributed_frac"] >= 0.90
    # /snapshot carries the compact block
    snap = monitor.export.snapshot_payload()
    assert snap["memory"]["report"]["label"] == "jit.step"
    assert len(snap["memory"]["report"]["contributors"]) <= 3


def test_report_emits_curve_when_tracing(tmp_path):
    _mlp_step(tmp_path)
    trace.enable()
    memory.report()
    cs = [e for e in trace.events() if e[0] == "C"]
    assert cs and all(e[1] == "hbm.predicted[jit.step]" for e in cs)
    assert len(cs) <= 512


def test_chrome_export_renders_counter_events():
    trace.enable()
    trace.counter("hbm.predicted[x]", {"bytes": 7})
    doc = trace.export_chrome_trace()
    recs = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert recs and recs[0]["name"] == "hbm.predicted[x]"
    assert recs[0]["args"] == {"bytes": 7}


def test_oom_flight_record_bundles_memory_report(tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_MAX", "10000")
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "fl"))
    _mlp_step(tmp_path)
    memory.report()
    err = RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying "
                       "to allocate 99 bytes")
    d = memory.handle_oom(err, where="unit", step=3)
    assert d is not None
    mem = json.load(open(f"{d}/memory_report.json"))
    assert mem["label"] == "jit.step"
    assert mem["contributors"] and mem["contributors"][0]["rank"] == 1
    meta = json.load(open(f"{d}/meta.json"))
    assert meta["reason"] == "oom"
    last = memory.last_oom()
    assert last["where"] == "unit" and last["step"] == 3
    assert monitor.registry().value("memory.oom", 0) >= 1
    # /snapshot points at the postmortem
    snap = monitor.export.snapshot_payload()
    assert snap["memory"]["last_oom"]["path"] == d


def test_executor_crash_path_routes_oom(tmp_path, monkeypatch):
    """An OOM-shaped crash inside Executor.run leaves an 'oom' flight
    record (with the memory report) instead of a generic crash dump."""
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_MAX", "10000")
    monkeypatch.setenv("PADDLE_TPU_FLIGHT_DIR", str(tmp_path / "fl"))
    _mlp_step(tmp_path)
    from paddle_tpu import static
    exe = static.Executor()
    boom = RuntimeError("RESOURCE_EXHAUSTED: Out of memory while "
                        "trying to allocate 123 bytes")
    monkeypatch.setattr(static.Executor, "_run_impl",
                        lambda self, *a, **k: (_ for _ in ()).throw(boom))
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        exe.run(feed={}, fetch_list=[])
    last = memory.last_oom()
    assert last is not None and last["where"] == "executor.run"
    meta = json.load(open(f"{last['path']}/meta.json"))
    assert meta["reason"] == "oom"


# -- planner feasibility ------------------------------------------------------

def _mcfg():
    from paddle_tpu.parallel import megatron as M
    return M.MegatronConfig(vocab_size=64, hidden=32, n_heads=4,
                            layers_per_stage=1, seq_len=16, microbatch=2,
                            n_micro=1, use_moe=False)


def test_advise_rows_carry_budget_columns(monkeypatch):
    from paddle_tpu.parallel import planner
    table = planner.advise(n_devices=8, cfg=_mcfg())
    for row in table:
        assert row["peak_hbm_bytes"] > 0
        assert row["feasible"] is True          # no limit -> no verdicts
        assert row["hbm_limit_bytes"] is None


def test_advise_marks_over_budget_infeasible_and_sorts_last(monkeypatch):
    from paddle_tpu.parallel import planner
    cfg = _mcfg()
    free = planner.advise(n_devices=8, cfg=cfg)
    peaks = sorted(r["peak_hbm_bytes"] for r in free)
    # a budget below the largest candidate but above the smallest:
    # at least one row flips infeasible, at least one survives
    limit = (peaks[0] + peaks[-1]) / 2.0
    monkeypatch.setenv("PADDLE_TPU_HBM_LIMIT_BYTES", str(limit))
    table = planner.advise(n_devices=8, cfg=cfg)
    flags = [r["feasible"] for r in table]
    assert True in flags and False in flags
    # every feasible row ranks strictly ahead of every infeasible one
    assert flags == sorted(flags, reverse=True)
    assert all(r["hbm_limit_bytes"] == limit for r in table)
    for r in table:
        assert r["feasible"] == (r["peak_hbm_bytes"] <= limit)


def test_plan_auto_never_picks_infeasible(monkeypatch):
    import jax
    from paddle_tpu.parallel import planner
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = _mcfg()
    free = planner.advise(n_devices=8, cfg=cfg)
    peaks = sorted(r["peak_hbm_bytes"] for r in free)
    limit = (peaks[0] + peaks[-1]) / 2.0
    monkeypatch.setenv("PADDLE_TPU_HBM_LIMIT_BYTES", str(limit))
    p = planner.plan(auto=True, cfg=cfg, n_devices=8)
    chosen = planner.last_decision()["chosen"]
    row = next(r for r in p.advice if dict(r["sizes"]) == dict(chosen))
    assert row["feasible"]
    assert planner.last_decision()["infeasible"] >= 1
    assert planner.last_decision()["hbm_limit_bytes"] == limit


def test_plan_auto_all_infeasible_raises(monkeypatch):
    import jax
    from paddle_tpu.parallel import planner
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    monkeypatch.setenv("PADDLE_TPU_HBM_LIMIT_BYTES", "1")
    with pytest.raises(ValueError, match="exceeds the device HBM"):
        planner.plan(auto=True, cfg=_mcfg(), n_devices=8)


# -- disabled mode: nothing runs, nothing is retained -------------------------

def test_counter_noop_when_trace_disabled():
    trace.counter("hbm.predicted[x]", {"bytes": 1})
    assert trace.events() == []


def test_disabled_step_leaves_no_memory_state(monkeypatch):
    """An ordinary (monitor-off) jitted step must never touch the
    liveness machinery or retain a report."""
    bomb = lambda *a, **k: (_ for _ in ()).throw(
        AssertionError("memory model touched while disabled"))
    monkeypatch.setattr(memory, "liveness", bomb)
    monkeypatch.setattr(memory, "simulate", bomb)
    model = nn.Sequential(nn.Linear(4, 4), nn.ReLU())
    adam = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

    @jit.to_static(models=[model], optimizers=[adam])
    def step(x, y):
        loss = F.cross_entropy(model(x), y)
        loss.backward()
        adam.step()
        return loss

    x = pt.to_tensor(np.ones((2, 4), dtype="float32"))
    y = pt.to_tensor(np.zeros((2,), dtype="int64"))
    step(x, y)
    assert memory.last_report() is None
    assert memory.last_oom() is None
