"""The fluid.layers parity tail (layers_extra / layers_extra2): every
remaining reference layer name exists, and the numeric ones compute
correct values (reference: python/paddle/fluid/layers __all__ union)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.fluid import layers as FL


def t(x):
    return pt.to_tensor(np.asarray(x))


class TestMeta:
    def test_shape_rank_size(self):
        x = t(np.zeros((3, 4), "f4"))
        np.testing.assert_array_equal(FL.shape(x).numpy(), [3, 4])
        assert int(FL.rank(x).numpy()) == 2
        assert int(FL.size(x).numpy()) == 12
        assert not bool(FL.is_empty(x).numpy())

    def test_nan_inf_reduce(self):
        x = t(np.array([1.0, np.nan], "f4"))
        assert bool(FL.has_nan(x).numpy())
        assert not bool(FL.has_inf(t(np.ones(3, "f4"))).numpy())
        b = t(np.array([[True, False], [True, True]]))
        np.testing.assert_array_equal(FL.reduce_all(b, dim=1).numpy(),
                                      [False, True])
        np.testing.assert_array_equal(FL.reduce_any(b, dim=0).numpy(),
                                      [True, True])

    def test_sums_multiplex_unbind(self):
        a, b = t(np.ones((2, 2), "f4")), t(np.full((2, 2), 2.0, "f4"))
        np.testing.assert_allclose(FL.sums([a, b]).numpy(), 3.0)
        x1 = t(np.zeros((2, 3), "f4"))
        x2 = t(np.ones((2, 3), "f4"))
        idx = t(np.array([[1], [0]], "i4"))
        out = FL.multiplex([x1, x2], idx)
        np.testing.assert_allclose(out.numpy(), [[1, 1, 1], [0, 0, 0]])
        parts = FL.unbind(t(np.arange(6, dtype="f4").reshape(2, 3)))
        assert len(parts) == 2 and parts[1].shape == [3]

    def test_unique_scatter_nd_hash(self):
        u, i, c = FL.unique_with_counts(t(np.array([3, 1, 3, 2], "i4")))
        assert u.shape == [4]
        out = FL.scatter_nd(t(np.array([[1], [3]], "i4")),
                            t(np.array([9.0, 8.0], "f4")), [5])
        np.testing.assert_allclose(out.numpy(), [0, 9, 0, 8, 0])
        h = FL.hash(t(np.array([[5], [9]], "i8")), hash_size=100,
                    num_hash=2)
        assert h.shape == [2, 1, 2]
        assert h.numpy().max() < 100

    def test_creation_helpers(self):
        v = FL.create_global_var([2, 2], 1.5, "float32")
        np.testing.assert_allclose(v.numpy(), 1.5)
        p = FL.create_parameter([3, 3], "float32")
        assert p.shape == [3, 3]
        x = t(np.zeros((5, 2), "f4"))
        f = FL.fill_constant_batch_size_like(x, [1, 7], "float32", 3.0)
        assert f.shape == [5, 7]
        g = FL.gaussian_random([128, 4], mean=1.0, std=0.1, seed=3)
        assert abs(float(g.numpy().mean()) - 1.0) < 0.05
        u = FL.uniform_random_batch_size_like(x, [1, 3], min=0.0, max=1.0)
        assert u.shape == [5, 3]
        c1 = FL.autoincreased_step_counter("t_counter")
        c2 = FL.autoincreased_step_counter("t_counter")
        assert int(c2.numpy()) == int(c1.numpy())  # same holder, bumped

    def test_sampling_and_pyfunc(self):
        probs = t(np.array([[0.0, 1.0], [1.0, 0.0]], "f4"))
        sid = FL.sampling_id(probs, seed=1)
        np.testing.assert_array_equal(sid.numpy(), [1, 0])

        out_t = pt.to_tensor(np.zeros((2, 2), "f4"))
        res = FL.py_func(lambda a: a * 3.0, t(np.ones((2, 2), "f4")),
                         out_t)
        np.testing.assert_allclose(res.numpy(), 3.0)

    def test_py_func_backward(self):
        """Regression (review r3): backward_func installs as a custom
        VJP host callback."""
        x = t(np.array([1.0, 2.0], "f4"))
        x.stop_gradient = False
        out_t = pt.to_tensor(np.zeros((2,), "f4"))
        res = FL.py_func(lambda a: a * a, x, out_t,
                         backward_func=lambda a, o, g: 2.0 * a * g)
        res.sum().backward()
        np.testing.assert_allclose(np.asarray(x.grad), [2.0, 4.0],
                                   rtol=1e-5)

    def test_tensor_array_to_tensor(self):
        arr = FL.create_array()
        FL.array_write(t(np.ones((2, 3), "f4")), 0, arr)
        FL.array_write(t(np.zeros((2, 3), "f4")), 1, arr)
        out, sizes = FL.tensor_array_to_tensor(arr, axis=0)
        assert out.shape == [4, 3]


class TestActivationsMath:
    def test_brelu_soft_relu_stanh(self):
        x = t(np.array([-50.0, 0.5, 50.0], "f4"))
        np.testing.assert_allclose(FL.brelu(x, 0.0, 24.0).numpy(),
                                   [0.0, 0.5, 24.0])
        assert FL.soft_relu(x).numpy()[1] == pytest.approx(
            np.log1p(np.exp(0.5)), rel=1e-5)
        assert FL.stanh(x, 0.67, 1.7159).numpy()[1] == pytest.approx(
            1.7159 * np.tanh(0.67 * 0.5), rel=1e-5)

    def test_clip_by_norm_l2_normalize_cos_sim(self):
        x = t(np.array([3.0, 4.0], "f4"))
        np.testing.assert_allclose(FL.clip_by_norm(x, 1.0).numpy(),
                                   [0.6, 0.8], rtol=1e-5)
        n = FL.l2_normalize(t(np.array([[3.0, 4.0]], "f4")))
        np.testing.assert_allclose(np.linalg.norm(n.numpy()), 1.0,
                                   rtol=1e-5)
        c = FL.cos_sim(t(np.array([[1.0, 0.0]], "f4")),
                       t(np.array([[1.0, 0.0]], "f4")))
        np.testing.assert_allclose(c.numpy(), [[1.0]], rtol=1e-5)


class TestImageOps:
    def test_pads_crops(self):
        x = t(np.ones((1, 1, 2, 2), "f4"))
        p = FL.pad2d(x, (1, 1, 2, 2))
        assert p.shape == [1, 1, 4, 6]
        y = FL.pad_constant_like(t(np.zeros((2, 4), "f4")),
                                 t(np.ones((2, 2), "f4")), 7.0)
        assert y.shape == [2, 4] and y.numpy()[0, -1] == 7.0
        c = FL.crop_tensor(t(np.arange(16, dtype="f4").reshape(4, 4)),
                           shape=[2, 2], offsets=[1, 1])
        np.testing.assert_allclose(c.numpy(), [[5, 6], [9, 10]])
        r = FL.random_crop(t(np.zeros((2, 8, 8), "f4")), [4, 4], seed=1)
        assert r.shape == [2, 4, 4]

    def test_space_shuffle_shift(self):
        x = t(np.arange(16, dtype="f4").reshape(1, 1, 4, 4))
        s = FL.space_to_depth(x, 2)
        assert s.shape == [1, 4, 2, 2]
        sc = FL.shuffle_channel(t(np.zeros((1, 4, 2, 2), "f4")), 2)
        assert sc.shape == [1, 4, 2, 2]
        ts = FL.temporal_shift(t(np.zeros((4, 4, 2, 2), "f4")), 2, 0.25)
        assert ts.shape == [4, 4, 2, 2]

    def test_resizes(self):
        x = t(np.random.rand(1, 2, 4, 4).astype("f4"))
        assert FL.resize_bilinear(x, out_shape=[8, 8]).shape == \
            [1, 2, 8, 8]
        assert FL.resize_nearest(x, out_shape=[2, 2]).shape == [1, 2, 2, 2]
        assert FL.image_resize_short(x, 8).shape == [1, 2, 8, 8]
        x1 = t(np.random.rand(1, 2, 6).astype("f4"))
        assert FL.resize_linear(x1, out_shape=[12]).shape == [1, 2, 12]
        x3 = t(np.random.rand(1, 1, 2, 2, 2).astype("f4"))
        assert FL.resize_trilinear(x3, out_shape=[4, 4, 4]).shape == \
            [1, 1, 4, 4, 4]

    def test_pools(self):
        x = t(np.random.rand(1, 2, 4, 4).astype("f4"))
        assert FL.adaptive_pool2d(x, [2, 2], "avg").shape == [1, 2, 2, 2]
        x3 = t(np.random.rand(1, 2, 4, 4, 4).astype("f4"))
        assert FL.adaptive_pool3d(x3, 2, "max").shape == [1, 2, 2, 2, 2]
        assert FL.pool3d(x3, 2, "avg", 2).shape == [1, 2, 2, 2, 2]
        assert FL.pool3d(x3, global_pooling=True).shape == [1, 2, 1, 1, 1]

    def test_affine_grid_sampler_identity(self):
        x = t(np.random.rand(1, 1, 5, 5).astype("f4"))
        theta = t(np.array([[[1.0, 0, 0], [0, 1.0, 0]]], "f4"))
        grid = FL.affine_grid(theta, [1, 1, 5, 5])
        out = FL.grid_sampler(x, grid)
        np.testing.assert_allclose(out.numpy(), x.numpy(), atol=1e-4)

    def test_row_conv_fsp(self):
        pt.seed(0)
        x = t(np.random.rand(2, 5, 3).astype("f4"))
        assert FL.row_conv(x, 2).shape == [2, 5, 3]
        a = t(np.random.rand(2, 3, 4, 4).astype("f4"))
        b = t(np.random.rand(2, 5, 4, 4).astype("f4"))
        assert FL.fsp_matrix(a, b).shape == [2, 3, 5]

    def test_affine_channel_lrn_data_norm(self):
        x = t(np.ones((1, 2, 2, 2), "f4"))
        out = FL.affine_channel(x, t(np.array([2.0, 3.0], "f4")),
                                t(np.array([1.0, 0.0], "f4")))
        assert out.numpy()[0, 0, 0, 0] == 3.0
        assert out.numpy()[0, 1, 0, 0] == 3.0
        assert FL.lrn(t(np.random.rand(1, 4, 3, 3).astype("f4"))).shape \
            == [1, 4, 3, 3]
        dn = FL.data_norm(t(np.random.rand(8, 4).astype("f4")))
        np.testing.assert_allclose(dn.numpy().mean(0), 0.0, atol=1e-5)

    def test_im2sequence_deformable(self):
        x = t(np.random.rand(1, 2, 4, 4).astype("f4"))
        seq = FL.im2sequence(x, filter_size=2, stride=2)
        assert seq.shape == [1, 4, 8]
        pt.seed(1)
        off = t(np.zeros((1, 2 * 9, 4, 4), "f4"))
        msk = t(np.ones((1, 9, 4, 4), "f4"))
        out = FL.deformable_conv(x, off, msk, num_filters=3, filter_size=3,
                                 padding=1)
        assert out.shape == [1, 3, 4, 4]

    def test_conv3d_transpose(self):
        pt.seed(2)
        x = t(np.random.rand(1, 2, 3, 3, 3).astype("f4"))
        out = FL.conv3d_transpose(x, num_filters=4, filter_size=2,
                                  stride=2)
        assert out.shape == [1, 4, 6, 6, 6]


class TestLosses:
    def test_simple_losses(self):
        x = t(np.array([[1.0, 2.0]], "f4"))
        y = t(np.array([[0.0, 0.0]], "f4"))
        np.testing.assert_allclose(FL.mse_loss(x, y).numpy(), 2.5)
        s = FL.smooth_l1(x, y)
        assert s.shape == [1, 1]
        k = FL.kldiv_loss(t(np.log(np.array([[0.5, 0.5]], "f4"))),
                          t(np.array([[0.5, 0.5]], "f4")))
        np.testing.assert_allclose(k.numpy(), 0.0, atol=1e-6)
        d = FL.dice_loss(t(np.array([[0.9, 0.1]], "f4")),
                         t(np.array([[1.0, 0.0]], "f4")))
        assert 0 <= float(d.numpy()) < 0.2
        m = FL.margin_rank_loss(t(np.array([1.0], "f4")),
                                t(np.array([0.2], "f4")),
                                t(np.array([0.5], "f4")), margin=0.1)
        np.testing.assert_allclose(m.numpy(), 0.4, rtol=1e-5)

    def test_npair_center_tsl(self):
        pt.seed(3)
        a = t(np.random.rand(4, 8).astype("f4"))
        p = t(np.random.rand(4, 8).astype("f4"))
        y = t(np.array([0, 1, 0, 1], "i4"))
        assert np.isfinite(float(FL.npair_loss(a, p, y).numpy()))
        cl = FL.center_loss(a, t(np.array([[0], [1], [0], [1]], "i4")),
                            num_classes=3, alpha=0.1)
        assert cl.shape == [4, 1]
        ts = FL.teacher_student_sigmoid_loss(
            t(np.array([[0.5]], "f4")), t(np.array([[1.4]], "f4")))
        assert np.isfinite(ts.numpy()).all()

    def test_sampled_softmax_nce_hsigmoid(self):
        pt.seed(4)
        logits = t(np.random.randn(4, 50).astype("f4"))
        lbl = t(np.random.randint(0, 50, (4, 1)).astype("i4"))
        out = FL.sampled_softmax_with_cross_entropy(logits, lbl, 10,
                                                    seed=5)
        assert out.shape == [4, 1] and (out.numpy() > 0).all()
        x = t(np.random.rand(4, 8).astype("f4"))
        n = FL.nce(x, lbl, num_total_classes=50, num_neg_samples=5,
                   seed=5)
        assert n.shape == [4, 1] and np.isfinite(n.numpy()).all()
        h = FL.hsigmoid(x, lbl, num_classes=50)
        assert h.shape == [4, 1] and np.isfinite(h.numpy()).all()

    def test_bilinear_spectral(self):
        pt.seed(5)
        x = t(np.random.rand(3, 4).astype("f4"))
        y = t(np.random.rand(3, 6).astype("f4"))
        out = FL.bilinear_tensor_product(x, y, size=5)
        assert out.shape == [3, 5]
        w = t(np.random.randn(6, 4).astype("f4"))
        sn = FL.spectral_norm(w, power_iters=20)
        s = np.linalg.svd(sn.numpy(), compute_uv=False)
        np.testing.assert_allclose(s[0], 1.0, rtol=1e-2)


class TestMetricsFns:
    def test_auc_perfect(self):
        p = t(np.array([[0.9, 0.1], [0.2, 0.8], [0.7, 0.3], [0.3, 0.7]],
                       "f4"))
        y = t(np.array([[0], [1], [0], [1]], "i4"))
        a, _, _ = FL.auc(p, y)
        np.testing.assert_allclose(float(a.numpy()), 1.0)

    def test_mean_iou(self):
        pred = t(np.array([0, 1, 1, 0], "i4"))
        lab = t(np.array([0, 1, 0, 0], "i4"))
        miou, iou, cm = FL.mean_iou(pred, lab, 2)
        # class0: inter 2, union 3; class1: inter 1, union 2
        np.testing.assert_allclose(float(miou.numpy()),
                                   (2 / 3 + 1 / 2) / 2, rtol=1e-5)

    def test_edit_distance(self):
        a = t(np.array([[1, 2, 3]], "i4"))
        b = t(np.array([[1, 3, 3]], "i4"))
        d, n = FL.edit_distance(a, b, normalized=False)
        np.testing.assert_allclose(d.numpy(), [[1.0]])


class TestLrDecays:
    def test_functional_decays(self):
        ne = FL.natural_exp_decay(0.1, 10, 0.5)
        it = FL.inverse_time_decay(0.1, 10, 0.5)
        assert ne() == pytest.approx(0.1)
        assert it() == pytest.approx(0.1)
        for _ in range(10):
            ne.step()
            it.step()
        assert ne() == pytest.approx(0.1 * np.exp(-0.5), rel=1e-5)
        assert it() == pytest.approx(0.1 / 1.5, rel=1e-5)


class TestLodCompat:
    def test_lod_reset_reorder(self):
        x = t(np.random.rand(3, 4).astype("f4"))
        x2, lens = FL.lod_reset(x, target_lod=[2, 1])
        assert lens.shape == [2]
        out = FL.reorder_lod_tensor_by_rank(
            x, t(np.array([2, 0, 1], "i4")))
        np.testing.assert_allclose(out.numpy()[0], x.numpy()[2])


class TestDetectionTail:
    def test_rpn_and_retinanet_assign(self):
        anchors = t(np.array([[0, 0, 10, 10], [20, 20, 40, 40],
                              [100, 100, 120, 120]], "f4"))
        gt = t(np.array([[0, 0, 11, 11], [19, 19, 41, 41]], "f4"))
        loc_t, score_t, fg, valid = FL.rpn_target_assign(
            None, None, anchors, None, gt)
        assert bool(fg.numpy()[0]) and bool(fg.numpy()[1])
        lbls = t(np.array([3, 7], "i4"))
        loc2, cls2, fg2, valid2, fgn = FL.retinanet_target_assign(
            None, None, anchors, None, gt, lbls)
        assert cls2.numpy()[0] == 3 and cls2.numpy()[1] == 7
        assert cls2.numpy()[2] == 0

    def test_psroi_prroi(self):
        x = t(np.random.rand(1, 8, 6, 6).astype("f4"))
        rois = t(np.array([[0.0, 0.0, 5.0, 5.0]], "f4"))
        ps = FL.psroi_pool(x, rois, output_channels=2, spatial_scale=1.0,
                           pooled_height=2, pooled_width=2)
        assert ps.shape == [1, 2, 2, 2]
        xc = t(np.full((1, 1, 6, 6), 2.0, "f4"))
        pr = FL.prroi_pool(xc, rois, 1.0, 2, 2)
        np.testing.assert_allclose(pr.numpy(), 2.0, rtol=1e-4)

    def test_deformable_roi_pooling_zero_offsets(self):
        xc = t(np.full((1, 2, 6, 6), 5.0, "f4"))
        rois = t(np.array([[0.0, 0.0, 5.0, 5.0]], "f4"))
        tr = t(np.zeros((1, 2, 2, 2), "f4"))
        out = FL.deformable_roi_pooling(xc, rois, tr, pooled_height=2,
                                        pooled_width=2, sample_per_part=2)
        np.testing.assert_allclose(out.numpy(), 5.0, rtol=1e-5)

    def test_locality_aware_nms_and_retina_out(self):
        boxes = t(np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                             [30, 30, 40, 40]]], "f4"))
        scores = t(np.array([[[0.9, 0.8, 0.7]]], "f4").transpose(0, 1, 2))
        out, num = FL.locality_aware_nms(boxes, scores, 0.1, 3, 3, 0.5)
        assert out.shape == [1, 3, 6]
        assert int(num.numpy()[0]) >= 1

    def test_generate_proposal_labels(self):
        rois = t(np.array([[0, 0, 10, 10], [50, 50, 60, 60]], "f4"))
        gtc = t(np.array([2, 5], "i4"))
        gt = t(np.array([[0, 0, 9, 9], [100, 100, 110, 110]], "f4"))
        out = FL.generate_proposal_labels(rois, gtc, None, gt,
                                          None)
        rois_o, labels, tgt, iw, ow = out
        assert labels.numpy()[0] == 2  # IoU > 0.5 with gt0
        assert labels.numpy()[1] == 0  # background

    def test_detection_map(self):
        det = t(np.array([[[1, 0.9, 0, 0, 10, 10],
                           [1, 0.1, 50, 50, 60, 60]]], "f4"))
        lab = t(np.array([[[1, 0, 0, 10, 10]]], "f4"))
        m = FL.detection_map(det, lab, class_num=2)
        np.testing.assert_allclose(float(m.numpy()), 1.0)

    def test_roi_perspective_transform_identity(self):
        x = t(np.random.rand(1, 1, 8, 8).astype("f4"))
        # quad = the full image corners → identity-ish warp
        rois = t(np.array([[0.0, 0.0, 7.0, 0.0, 7.0, 7.0, 0.0, 7.0]],
                          "f4"))
        out = FL.roi_perspective_transform(x, rois, 8, 8)
        np.testing.assert_allclose(out.numpy()[0, 0], x.numpy()[0, 0],
                                   atol=1e-3)


class TestMiscNlpCtr:
    def test_add_position_encoding(self):
        x = t(np.zeros((1, 4, 8), "f4"))
        out = FL.add_position_encoding(x, 1.0, 1.0)
        assert out.shape == [1, 4, 8]
        assert abs(float(out.numpy()[0, 0, 4]) - 1.0) < 1e-5  # cos(0)=1

    def test_cvm_filter_instag(self):
        x = t(np.random.rand(2, 6).astype("f4"))
        cvm = t(np.random.rand(2, 2).astype("f4"))
        assert FL.continuous_value_model(x, cvm, True).shape == [2, 6]
        assert FL.continuous_value_model(x, cvm, False).shape == [2, 4]
        ins = t(np.random.rand(3, 4).astype("f4"))
        tags = t(np.array([1, 2, 3], "i8"))
        ftag = t(np.array([2], "i8"))
        out, idx, w = FL.filter_by_instag(ins, tags, ftag)
        np.testing.assert_allclose(w.numpy(), [0, 1, 0])

    def test_while_class(self):
        i = pt.to_tensor(np.array([0.0], "f4"))
        total = pt.to_tensor(np.array([0.0], "f4"))
        w = FL.While(i < 3.0)

        def body():
            total.set_value(total.numpy() + 2.0)
            i.set_value(i.numpy() + 1.0)
        # While re-evaluates `cond` — it must reference the live tensor
        w.cond = i < 3.0
        with pytest.raises(Exception):
            with w.block():
                pass  # no recorded body + true cond → clear error

    def test_while_record_pattern(self):
        state = {"i": 0}
        flag = pt.to_tensor(np.array([1.0], "f4"))
        w = FL.While(flag > 0.0)
        with w.block():
            @FL.While.record
            def _body():
                state["i"] += 1
                if state["i"] >= 3:
                    flag.set_value(np.array([0.0], "f4"))
                w.cond = flag > 0.0
        assert state["i"] == 3
