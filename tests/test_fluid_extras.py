"""fluid.nets composites, DataFeeder/py_reader compat, utils logger
(VERDICT r2 missing #6/#7 + ADVICE A5)."""
import logging

import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import fluid, static
from paddle_tpu.fluid import nets, layers as FL
from paddle_tpu.fluid.data_feeder import DataFeeder, PyReader, py_reader, \
    read_file, double_buffer


class TestNets:
    def test_simple_img_conv_pool(self):
        pt.seed(0)
        x = pt.to_tensor(np.random.rand(2, 3, 16, 16).astype("f4"))
        out = nets.simple_img_conv_pool(x, num_filters=8, filter_size=3,
                                        pool_size=2, pool_stride=2,
                                        conv_padding=1, act="relu")
        assert out.shape == [2, 8, 8, 8]
        assert float(out.min()) >= 0.0

    def test_img_conv_group(self):
        pt.seed(1)
        x = pt.to_tensor(np.random.rand(2, 3, 16, 16).astype("f4"))
        out = nets.img_conv_group(x, conv_num_filter=[8, 8], pool_size=2,
                                  conv_act="relu", pool_stride=2,
                                  conv_with_batchnorm=True)
        assert out.shape == [2, 8, 8, 8]

    def test_glu(self):
        x = pt.to_tensor(np.random.randn(4, 10).astype("f4"))
        out = nets.glu(x, dim=-1)
        assert out.shape == [4, 5]
        a, b = x.numpy()[:, :5], x.numpy()[:, 5:]
        ref = a * (1 / (1 + np.exp(-b)))
        np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-6)

    def test_sequence_conv_pool(self):
        pt.seed(2)
        x = pt.to_tensor(np.random.rand(3, 7, 6).astype("f4"))
        out = nets.sequence_conv_pool(x, num_filters=4, filter_size=3,
                                      act="sigmoid")
        assert out.shape == [3, 4]

    def test_scaled_dot_product_attention(self):
        pt.seed(3)
        q = pt.to_tensor(np.random.rand(2, 5, 8).astype("f4"))
        out = nets.scaled_dot_product_attention(q, q, q, num_heads=2)
        assert out.shape == [2, 5, 8]


class TestDataFeeder:
    def test_feed_builds_named_batches(self):
        pt.enable_static()
        try:
            prog, sprog = static.Program(), static.Program()
            with static.program_guard(prog, sprog):
                x = static.data("img", [None, 4], "float32")
                y = static.data("lbl", [None, 1], "int64")
                feeder = fluid.DataFeeder(feed_list=[x, y])
            batch = feeder.feed([(np.ones(4), [1]), (np.zeros(4), [0])])
            assert set(batch) == {"img", "lbl"}
            assert batch["img"].shape == (2, 4)
            assert batch["img"].dtype == np.float32
            # int64 canonicalizes to int32 (jax x64-off, the TPU dtype)
            assert batch["lbl"].dtype in (np.int32, np.int64)
        finally:
            pt.disable_static()

    def test_feed_rejects_ragged_rows(self):
        feeder = DataFeeder(feed_list=["a", "b"])
        with pytest.raises(ValueError, match="fields"):
            feeder.feed([(1,)])


class TestPyReader:
    def test_sample_list_generator(self):
        pt.enable_static()
        try:
            prog, sprog = static.Program(), static.Program()
            with static.program_guard(prog, sprog):
                reader = py_reader(capacity=8, shapes=[[None, 2], [None]],
                                   dtypes=["float32", "int64"])
                xs = read_file(reader)
            assert len(xs) == 2

            def gen():
                for i in range(3):
                    yield [(np.full(2, i), i), (np.full(2, i + 10), i)]

            reader.decorate_sample_list_generator(gen)
            reader.start()
            feeds = list(reader)
            assert len(feeds) == 3
            first = feeds[0]
            assert set(first) == {xs[0].name, xs[1].name}
            assert first[xs[0].name].shape == (2, 2)
            assert double_buffer(reader) is reader
        finally:
            pt.disable_static()

    def test_batch_generator(self):
        r = PyReader(feed_list=[])

        def gen():
            yield {"a": np.zeros(3)}
        r.decorate_batch_generator(gen)
        out = list(r)
        assert out[0]["a"].shape == (3,)


class TestLogger:
    def test_get_logger_configured(self):
        from paddle_tpu.utils import get_logger
        lg = get_logger("paddle_tpu.test")
        assert lg.propagate is False
        assert lg.handlers
        lg2 = get_logger("paddle_tpu.test")
        assert lg is lg2 and len(lg2.handlers) == 1

    def test_set_level(self):
        from paddle_tpu.utils import get_logger
        from paddle_tpu.utils.log import set_level
        lg = get_logger("paddle_tpu.lvl")
        set_level("DEBUG")
        assert lg.level == logging.DEBUG
        set_level("INFO")
