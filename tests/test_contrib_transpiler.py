"""fluid.contrib (mixed precision, slim) + fluid.transpiler facades
(reference: contrib/mixed_precision/decorator.py, transpiler/)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import fluid, nn, optimizer


def test_mixed_precision_decorate_trains():
    pt.seed(0)
    m = nn.Linear(4, 1)
    o = optimizer.SGD(learning_rate=0.1, parameters=m.parameters())
    dec = fluid.contrib.mixed_precision.decorate(
        o, init_loss_scaling=2.0 ** 10)
    x = pt.to_tensor(np.random.RandomState(0).rand(8, 4).astype("f4"))
    y = pt.to_tensor(np.random.RandomState(1).rand(8, 1).astype("f4"))
    losses = []
    for _ in range(10):
        loss = ((m(x) - y) ** 2).mean()
        losses.append(float(loss.numpy()))
        dec.minimize(loss)
    assert losses[-1] < losses[0]
    # wrapped attributes delegate
    assert dec._parameter_list is o._parameter_list


def test_amp_lists_parity():
    lists = fluid.contrib.mixed_precision.AutoMixedPrecisionLists(
        custom_white_list={"matmul"}, custom_black_list={"softmax"})
    assert "matmul" in lists.white_list


def test_slim_quantization_alias():
    from paddle_tpu import quantization
    assert fluid.contrib.slim.quantization is quantization
    assert fluid.contrib.quantize is quantization


def test_distribute_transpiler_roles():
    t = fluid.DistributeTranspiler(fluid.DistributeTranspilerConfig())
    t.transpile(trainer_id=0, trainers=4)
    assert t.get_trainer_program() is not None
    with pytest.raises(RuntimeError, match="parameter server"):
        t.get_pserver_program("127.0.0.1:6174")


def test_memory_optimize_noop():
    assert fluid.memory_optimize() is None
    assert fluid.release_memory(None) is None


def test_ps_dispatchers():
    from paddle_tpu.fluid.transpiler import HashName, RoundRobin

    class V:
        def __init__(self, name):
            self.name = name

    eps = ["a:1", "b:2"]
    rr = RoundRobin(eps)
    out = rr.dispatch([V("x"), V("y"), V("z")])
    assert out == ["a:1", "b:2", "a:1"]
    hn = HashName(eps)
    assert all(e in eps for e in hn.dispatch([V("x"), V("y")]))
