"""Per-op numeric tests vs numpy references for public ops nothing else
exercised (SURVEY §4's test_*_op.py style — found by grepping op names
against tests/)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.nn.functional as F
from paddle_tpu.ops import math as M, manip, creation


def _t(a, **kw):
    return pt.to_tensor(np.asarray(a), **kw)


X = np.random.RandomState(0).randn(4, 6).astype("f4") * 2


@pytest.mark.parametrize("fn,ref", [
    (F.relu6, lambda x: np.clip(x, 0, 6)),
    (F.leaky_relu, lambda x: np.where(x >= 0, x, 0.01 * x)),
    (F.elu, lambda x: np.where(x > 0, x, np.expm1(x))),
    (F.selu, lambda x: 1.0507009873554805 * np.where(
        x > 0, x, 1.6732632423543772 * np.expm1(x))),
    (F.gelu, lambda x: x * 0.5 * (1 + np.vectorize(__import__("math").erf)(
        x / np.sqrt(2)))),
    (F.log_sigmoid, lambda x: -np.log1p(np.exp(-np.abs(x))) +
        np.minimum(x, 0)),
    (F.hard_sigmoid, lambda x: np.clip(0.2 * x + 0.5, 0, 1)),
    (F.hard_swish, lambda x: x * np.clip(x + 3, 0, 6) / 6),
    (F.swish, lambda x: x / (1 + np.exp(-x))),
    (F.mish, lambda x: x * np.tanh(np.log1p(np.exp(x)))),
    (F.softplus, lambda x: np.where(x > 20, x, np.log1p(np.exp(
        np.minimum(x, 20))))),
    (F.softsign, lambda x: x / (1 + np.abs(x))),
    (F.softshrink, lambda x: np.where(x > 0.5, x - 0.5,
                                      np.where(x < -0.5, x + 0.5, 0))),
    (F.hard_shrink, lambda x: np.where(np.abs(x) > 0.5, x, 0)),
])
def test_activation_matches_numpy(fn, ref):
    out = fn(_t(X))
    np.testing.assert_allclose(out.numpy(), ref(X).astype("f4"),
                               atol=2e-5)


def test_activation_grads_finite():
    for fn in (F.relu6, F.leaky_relu, F.elu, F.selu, F.gelu, F.swish,
               F.mish, F.softplus, F.softsign):
        t = _t(X, stop_gradient=False)
        fn(t).sum().backward()
        assert np.isfinite(np.asarray(t.grad)).all(), fn


def test_prelu_shapes():
    x = np.random.RandomState(1).randn(2, 3, 4, 4).astype("f4")
    # single alpha
    out = F.prelu(_t(x), _t(np.asarray([0.25], "f4")))
    np.testing.assert_allclose(out.numpy(),
                               np.where(x >= 0, x, 0.25 * x), atol=1e-6)
    # per-channel alpha (NCHW)
    a = np.asarray([0.1, 0.2, 0.3], "f4")
    out = F.prelu(_t(x), _t(a))
    ref = np.where(x >= 0, x, a.reshape(1, 3, 1, 1) * x)
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-6)


def test_math_tail():
    rng = np.random.RandomState(2)
    a = rng.randn(3, 4).astype("f4")
    b = rng.randn(2, 4, 5).astype("f4")
    ab = rng.randn(2, 3, 4).astype("f4")

    assert M.cast(_t(a), "int32").numpy().dtype == np.int32
    np.testing.assert_allclose(M.cumprod(_t(a), dim=1).numpy(),
                               np.cumprod(a, axis=1), rtol=1e-5)
    np.testing.assert_array_equal(M.argmin(_t(a), axis=1).numpy(),
                                  np.argmin(a, axis=1))
    np.testing.assert_allclose(M.bmm(_t(ab), _t(b)).numpy(),
                               ab @ b, atol=1e-5)
    inp = rng.randn(3, 5).astype("f4")
    x2 = rng.randn(3, 4).astype("f4")
    y2 = rng.randn(4, 5).astype("f4")
    np.testing.assert_allclose(
        M.addmm(_t(inp), _t(x2), _t(y2), beta=0.5, alpha=2.0).numpy(),
        0.5 * inp + 2.0 * (x2 @ y2), atol=1e-5)
    np.testing.assert_allclose(M.maximum_(_t(a), _t(a * 0)).numpy(),
                               np.maximum(a, 0), atol=1e-6)
    np.testing.assert_allclose(M.increment(_t(a)).numpy(), a + 1.0,
                               atol=1e-6)
    pred = np.eye(4, 5, dtype="f4")
    lab = np.asarray([0, 1, 2, 0], "i4")
    assert abs(float(M.accuracy_top1(_t(pred), _t(lab)).numpy()) -
               0.75) < 1e-6
    np.testing.assert_allclose(
        M.elementwise_sum([_t(a), _t(a), _t(a)]).numpy(), 3 * a,
        atol=1e-6)
    np.testing.assert_array_equal(
        M.elementwise_equal(_t(lab), _t(lab)).numpy(),
        np.ones(4, bool))


def test_manip_tail():
    rng = np.random.RandomState(3)
    x = rng.randn(2, 3, 4).astype("f4")

    # paddle.flatten default start_axis=0, stop_axis=-1: full flatten
    np.testing.assert_allclose(manip.flatten(_t(x)).numpy(),
                               x.reshape(-1), atol=0)
    parts = manip.unstack(_t(x), axis=1)
    assert len(parts) == 3
    np.testing.assert_allclose(parts[1].numpy(), x[:, 1], atol=0)
    np.testing.assert_allclose(
        manip.squeeze(_t(x[:1]), axis=0).numpy(), x[0], atol=0)
    small = rng.randn(1, 4).astype("f4")
    np.testing.assert_allclose(
        manip.expand_as(_t(small), _t(x[:, 0, :])).numpy(),
        np.broadcast_to(small, (2, 4)), atol=0)
    np.testing.assert_allclose(
        manip.strided_slice(_t(x), axes=[2], starts=[0], ends=[4],
                            strides=[2]).numpy(), x[:, :, ::2], atol=0)
    pts = rng.randn(5, 3).astype("f4")
    idx2 = np.asarray([[0], [2]], "i4")
    np.testing.assert_allclose(manip.gather_nd(_t(pts), _t(idx2)).numpy(),
                               pts[[0, 2]], atol=0)
    np.testing.assert_allclose(
        manip.index_select(_t(pts), _t(np.asarray([2, 0], "i4"))).numpy(),
        pts[[2, 0]], atol=0)
    upd = np.full((2, 3), 9.0, "f4")
    out = manip.scatter(_t(pts), _t(np.asarray([1, 3], "i4")), _t(upd))
    ref = pts.copy()
    ref[[1, 3]] = 9.0
    np.testing.assert_allclose(out.numpy(), ref, atol=0)
    out = manip.scatter_nd_add(_t(pts), _t(np.asarray([[1], [1]], "i4")),
                               _t(np.ones((2, 3), "f4")))
    ref = pts.copy()
    ref[1] += 2.0
    np.testing.assert_allclose(out.numpy(), ref, atol=1e-6)
    idx = np.zeros((5, 1), "i8")
    out = manip.put_along_axis(_t(pts), _t(idx), _t(np.zeros((5, 1), "f4")),
                               axis=1)
    ref = pts.copy()
    ref[:, 0] = 0.0
    np.testing.assert_allclose(out.numpy(), ref, atol=0)
    mask = pts > 0
    np.testing.assert_allclose(
        manip.masked_select(_t(pts), _t(mask)).numpy(), pts[mask], atol=0)
    sq = rng.randn(4, 4).astype("f4")
    np.testing.assert_allclose(manip.triu(_t(sq)).numpy(), np.triu(sq),
                               atol=0)
    g = manip.meshgrid(_t(np.arange(2, dtype="f4")),
                       _t(np.arange(3, dtype="f4")))
    r0, r1 = np.meshgrid(np.arange(2), np.arange(3), indexing="ij")
    np.testing.assert_allclose(g[0].numpy(), r0, atol=0)
    np.testing.assert_allclose(g[1].numpy(), r1, atol=0)
    cks = manip.chunk(_t(x), 3, axis=1)
    assert len(cks) == 3 and tuple(cks[0].shape) == (2, 1, 4)
    ids = np.asarray([0, 3, 7, 11], "i8")
    out = manip.shard_index(_t(ids), index_num=12, nshards=3, shard_id=1)
    np.testing.assert_array_equal(out.numpy(), [-1, -1, 3, -1])


def test_creation_tail():
    pt.seed(7)
    x = np.random.RandomState(4).randn(3, 4).astype("f4")
    np.testing.assert_allclose(creation.ones_like(_t(x)).numpy(),
                               np.ones_like(x), atol=0)
    np.testing.assert_allclose(creation.full_like(_t(x), 2.5).numpy(),
                               np.full_like(x, 2.5), atol=0)
    n = creation.normal(mean=3.0, std=0.5, shape=[2000])
    assert abs(float(n.numpy().mean()) - 3.0) < 0.1
    assert abs(float(n.numpy().std()) - 0.5) < 0.05
    p = creation.randperm(16)
    np.testing.assert_array_equal(np.sort(p.numpy()), np.arange(16))
    probs = np.full((2000,), 0.3, "f4")
    b = creation.bernoulli(_t(probs))
    assert set(np.unique(b.numpy())) <= {0.0, 1.0}
    assert abs(float(b.numpy().mean()) - 0.3) < 0.08
