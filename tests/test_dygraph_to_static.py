"""AST to_static conversion (VERDICT r2 #4; reference:
dygraph_to_static/program_translator.py + ifelse/loop transformers).
The headline test: code whose trip count / branch depends on DATA gives
wrong results under trace-only conversion and right ones with the AST
pass."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import jit
from paddle_tpu.dygraph_to_static import (ProgramTranslator,
                                          convert_function)


def collatz_steps(x):
    """Data-dependent while: halve-until-below-one; trip count depends
    on the value."""
    n = pt.ops.zeros([], dtype="float32")
    while x > 1.0:
        x = x / 2.0
        n = n + 1.0
    return n


def sign_scale(x):
    """Data-dependent if."""
    if x.sum() > 0:
        y = x * 2.0
    else:
        y = x - 100.0
    return y


class TestAstPathCorrectness:
    def test_while_trip_count_follows_data(self):
        fn = jit.to_static(collatz_steps)
        # first call compiles with x=8 (3 halvings)
        out1 = fn(pt.to_tensor(np.float32(8.0)))
        assert float(out1.numpy()) == 3.0
        # SAME compiled function, different data: a baked trace would
        # still answer 3; lax.while_loop answers 5
        out2 = fn(pt.to_tensor(np.float32(32.0)))
        assert float(out2.numpy()) == 5.0

    def test_trace_only_path_cannot_compile_data_dependent_loop(self):
        """The failure the AST pass fixes: without it, a data-dependent
        python `while` cannot trace at all (TracerBoolConversionError from
        bool(tracer)) — with it, the same source compiles and follows the
        data (test above)."""
        import jax
        ProgramTranslator().enable(False)
        try:
            fn = jit.to_static(collatz_steps)
            with pytest.raises(jax.errors.TracerBoolConversionError):
                fn(pt.to_tensor(np.float32(8.0)))
        finally:
            ProgramTranslator().enable(True)

    def test_if_branch_follows_data(self):
        fn = jit.to_static(sign_scale)
        pos = np.ones((4,), "f4")
        neg = -np.ones((4,), "f4")
        np.testing.assert_allclose(fn(pt.to_tensor(pos)).numpy(), pos * 2)
        np.testing.assert_allclose(fn(pt.to_tensor(neg)).numpy(),
                                   neg - 100.0)


class TestEagerEquivalence:
    def test_converted_function_runs_eagerly_identical(self):
        conv = convert_function(collatz_steps)
        out = conv(pt.to_tensor(np.float32(40.0)))
        # 40→20→10→5→2.5→1.25→0.625: 6 steps
        assert float(out.numpy()) == 6.0

    def test_python_values_keep_python_semantics(self):
        def f(flag, x):
            if flag:
                y = x + 1
            else:
                y = x - 1
            i = 0
            while i < 3:
                y = y * 2
                i = i + 1
            return y, i

        conv = convert_function(f)
        y, i = conv(True, 5)
        assert (y, i) == (48, 3) and isinstance(i, int)
        y2, _ = conv(False, 5)
        assert y2 == 32

    def test_bool_ops_on_tensors(self):
        def f(x):
            if x.sum() > 0 and x.max() < 10:
                y = x * 1.0
            else:
                y = x * 0.0
            return y

        fn = jit.to_static(f)
        a = np.array([1.0, 2.0], "f4")
        np.testing.assert_allclose(fn(pt.to_tensor(a)).numpy(), a)
        b = np.array([1.0, 50.0], "f4")
        np.testing.assert_allclose(fn(pt.to_tensor(b)).numpy(), [0, 0])

    def test_undefined_var_in_tensor_branch_raises(self):
        def f(x):
            if x.sum() > 0:
                z = x * 2
            else:
                z = x * 3
            # w only defined on one python path:
            if x.sum() > 0:
                w = z + 1
            return z

        # w is assigned in only one branch of a tensor `if` with no else;
        # entering traced mode must raise a clear error
        fn = jit.to_static(f)
        with pytest.raises(ValueError, match="must be defined"):
            fn(pt.to_tensor(np.ones((2,), "f4")))

    def test_augassign_unbound_still_raises(self):
        """Regression (review r3): `c += 1` in both branches of a tensor
        `if` with c unbound must raise (AugAssign is a read), not be
        silently seeded with 0.0."""
        def f(x):
            if x.sum() > 0:
                c += 1.0  # noqa: F821 — deliberate unbound read
            else:
                c += 2.0  # noqa: F821
            return c

        fn = jit.to_static(f)
        with pytest.raises((ValueError, NameError, UnboundLocalError)):
            fn(pt.to_tensor(np.ones((2,), "f4")))

    def test_break_loops_stay_python(self):
        def f(x):
            total = x
            for i in range(4):
                if i == 2:
                    break
                total = total + 1.0
            return total

        conv = convert_function(f)
        out = conv(pt.to_tensor(np.float32(0.0)))
        assert float(out.numpy()) == 2.0


class TestTranslatorSwitch:
    def test_toggle_applies_at_call_time(self):
        """Regression (review r3): flipping the translator after the
        StaticFunction exists changes behavior (reference semantics)."""
        import jax
        fn = jit.to_static(collatz_steps)
        ProgramTranslator().enable(False)
        try:
            with pytest.raises(jax.errors.TracerBoolConversionError):
                fn(pt.to_tensor(np.float32(8.0)))
        finally:
            ProgramTranslator().enable(True)
        out = fn(pt.to_tensor(np.float32(8.0)))
        assert float(out.numpy()) == 3.0

    def test_singleton_and_enable(self):
        a = ProgramTranslator()
        b = ProgramTranslator.get_instance()
        assert a is b
        a.enable(False)
        assert not ProgramTranslator.is_enabled()
        a.enable(True)
        assert ProgramTranslator.is_enabled()


# ---------------------------------------------------------------------------
# break/continue + for conversion (VERDICT r3 #4; reference:
# break_continue_transformer.py, loop_transformer.py)


def first_power_above(x, limit):
    """Tensor-dependent while WITH break: doubles x until above limit."""
    n = pt.ops.zeros([], dtype="float32")
    while n < 100.0:
        if x > limit:
            break
        x = x * 2.0
        n = n + 1.0
    return n


def sum_skip_negatives(xs):
    """Tensor-dependent continue inside a for over Tensor rows."""
    total = pt.ops.zeros([], dtype="float32")
    for v in xs:
        if v.sum() < 0.0:
            continue
        total = total + v.sum()
    return total


def sum_range(t):
    """for over range(tensor) — trip count is DATA."""
    s = pt.ops.zeros([], dtype="float32")
    for i in range(t):
        s = s + 1.0 + 0.0 * i
    return s


class TestLoopTransforms:
    def test_while_break_follows_data(self):
        fn = jit.to_static(first_power_above)
        out1 = fn(pt.to_tensor(np.float32(1.0)),
                  pt.to_tensor(np.float32(10.0)))
        assert float(out1.numpy()) == 4.0   # 1->2->4->8->16, breaks at 16
        # same compiled fn, different data: a baked trace would answer 4
        out2 = fn(pt.to_tensor(np.float32(1.0)),
                  pt.to_tensor(np.float32(100.0)))
        assert float(out2.numpy()) == 7.0   # breaks when x=128
    def test_eager_semantics_preserved_with_break(self):
        # the converted function still runs correct plain python
        f = convert_function(first_power_above)
        out = f(pt.to_tensor(np.float32(1.0)),
                pt.to_tensor(np.float32(10.0)))
        assert float(out.numpy()) == 4.0

    def test_for_over_tensor_rows_with_continue(self):
        xs = np.array([[1.0, 2.0], [-5.0, 1.0], [3.0, 4.0]], "f4")
        f = convert_function(sum_skip_negatives)
        out = f(pt.to_tensor(xs))
        assert float(out.numpy()) == pytest.approx(10.0)  # skips row 1
        # compiled too (leading dim static -> unrolled, but guards traced)
        fn = jit.to_static(sum_skip_negatives)
        out = fn(pt.to_tensor(xs))
        assert float(out.numpy()) == pytest.approx(10.0)

    def test_for_over_traced_range(self):
        fn = jit.to_static(sum_range)
        out1 = fn(pt.to_tensor(np.int32(4)))
        assert float(out1.numpy()) == 4.0
        # SAME executable, new bound — lax.while_loop follows the data
        out2 = fn(pt.to_tensor(np.int32(9)))
        assert float(out2.numpy()) == 9.0

    def test_for_python_iterable_unchanged(self):
        def poly(x):
            acc = x * 0.0
            for c in [1.0, 2.0, 3.0]:
                acc = acc * x + c
            return acc

        f = convert_function(poly)
        x = pt.to_tensor(np.float32(2.0))
        assert float(f(x).numpy()) == float(poly(x).numpy()) == 11.0
        fn = jit.to_static(poly)
        assert float(fn(x).numpy()) == 11.0

    def test_for_range_with_break(self):
        def find_first_ge(xs, thresh):
            idx = pt.ops.zeros([], dtype="float32")
            found = pt.ops.zeros([], dtype="float32")
            for i in range(xs.shape[0]):
                if (xs[i] >= thresh).astype("float32").sum() > 0.0:
                    found = found + 1.0
                    idx = idx + 0.0
                    break
                idx = idx + 1.0
            return idx, found

        xs = np.array([1.0, 3.0, 7.0, 2.0], "f4")
        f = convert_function(find_first_ge)
        idx, found = f(pt.to_tensor(xs), pt.to_tensor(np.float32(5.0)))
        assert float(idx.numpy()) == 2.0 and float(found.numpy()) == 1.0
        fn = jit.to_static(find_first_ge)
        idx, found = fn(pt.to_tensor(xs), pt.to_tensor(np.float32(5.0)))
        assert float(idx.numpy()) == 2.0 and float(found.numpy()) == 1.0

    def test_for_enumerate_zip_generator_still_work(self):
        """len-less iterables (enumerate/zip/generators) must keep their
        python semantics through the for-conversion (materialized once)."""
        def f(x):
            acc = x * 0.0
            for i, c in enumerate([1.0, 2.0, 3.0]):
                acc = acc + c * (i + 1)
            for a, b in zip([1.0, 2.0], [10.0, 20.0]):
                acc = acc + a * b
            for g in (v * 2.0 for v in [1.0, 2.0]):
                acc = acc + g
            return acc

        x = pt.to_tensor(np.float32(0.0))
        ref = 1 + 4 + 9 + 10 + 40 + 2 + 4
        out = convert_function(f)(x)
        assert float(out.numpy()) == ref
        assert float(jit.to_static(f)(x).numpy()) == ref

    def test_nested_loops_with_breaks(self):
        """Each loop owns its break; inner tensor-dependent break inside
        an outer python loop."""
        def f(x):
            total = x * 0.0
            for _ in range(3):            # python outer
                s = x * 0.0
                while s < 10.0:           # tensor inner with break
                    s = s + x
                    if s > 4.0:
                        break
                total = total + s
            return total

        x = pt.to_tensor(np.float32(2.0))
        # inner: 2,4,6 -> breaks at 6; x3 outer => 18
        assert float(convert_function(f)(x).numpy()) == 18.0
        assert float(jit.to_static(f)(x).numpy()) == 18.0

    def test_while_continue_only(self):
        def f(x):
            i = pt.ops.zeros([], dtype="float32")
            acc = x * 0.0
            while i < 6.0:
                i = i + 1.0
                if (i % 2.0) > 0.5:       # odd -> skip
                    continue
                acc = acc + i
            return acc

        x = pt.to_tensor(np.float32(0.0))
        assert float(convert_function(f)(x).numpy()) == 12.0  # 2+4+6
        assert float(jit.to_static(f)(x).numpy()) == 12.0

    def test_for_over_dict_items(self):
        def f(x):
            acc = x * 0.0
            for k, v in {"a": 1.0, "b": 2.0}.items():
                acc = acc + v
            return acc

        x = pt.to_tensor(np.float32(0.0))
        assert float(convert_function(f)(x).numpy()) == 3.0
        assert float(jit.to_static(f)(x).numpy()) == 3.0
