"""AST to_static conversion (VERDICT r2 #4; reference:
dygraph_to_static/program_translator.py + ifelse/loop transformers).
The headline test: code whose trip count / branch depends on DATA gives
wrong results under trace-only conversion and right ones with the AST
pass."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import jit
from paddle_tpu.dygraph_to_static import (ProgramTranslator,
                                          convert_function)


def collatz_steps(x):
    """Data-dependent while: halve-until-below-one; trip count depends
    on the value."""
    n = pt.ops.zeros([], dtype="float32")
    while x > 1.0:
        x = x / 2.0
        n = n + 1.0
    return n


def sign_scale(x):
    """Data-dependent if."""
    if x.sum() > 0:
        y = x * 2.0
    else:
        y = x - 100.0
    return y


class TestAstPathCorrectness:
    def test_while_trip_count_follows_data(self):
        fn = jit.to_static(collatz_steps)
        # first call compiles with x=8 (3 halvings)
        out1 = fn(pt.to_tensor(np.float32(8.0)))
        assert float(out1.numpy()) == 3.0
        # SAME compiled function, different data: a baked trace would
        # still answer 3; lax.while_loop answers 5
        out2 = fn(pt.to_tensor(np.float32(32.0)))
        assert float(out2.numpy()) == 5.0

    def test_trace_only_path_cannot_compile_data_dependent_loop(self):
        """The failure the AST pass fixes: without it, a data-dependent
        python `while` cannot trace at all (TracerBoolConversionError from
        bool(tracer)) — with it, the same source compiles and follows the
        data (test above)."""
        import jax
        ProgramTranslator().enable(False)
        try:
            fn = jit.to_static(collatz_steps)
            with pytest.raises(jax.errors.TracerBoolConversionError):
                fn(pt.to_tensor(np.float32(8.0)))
        finally:
            ProgramTranslator().enable(True)

    def test_if_branch_follows_data(self):
        fn = jit.to_static(sign_scale)
        pos = np.ones((4,), "f4")
        neg = -np.ones((4,), "f4")
        np.testing.assert_allclose(fn(pt.to_tensor(pos)).numpy(), pos * 2)
        np.testing.assert_allclose(fn(pt.to_tensor(neg)).numpy(),
                                   neg - 100.0)


class TestEagerEquivalence:
    def test_converted_function_runs_eagerly_identical(self):
        conv = convert_function(collatz_steps)
        out = conv(pt.to_tensor(np.float32(40.0)))
        # 40→20→10→5→2.5→1.25→0.625: 6 steps
        assert float(out.numpy()) == 6.0

    def test_python_values_keep_python_semantics(self):
        def f(flag, x):
            if flag:
                y = x + 1
            else:
                y = x - 1
            i = 0
            while i < 3:
                y = y * 2
                i = i + 1
            return y, i

        conv = convert_function(f)
        y, i = conv(True, 5)
        assert (y, i) == (48, 3) and isinstance(i, int)
        y2, _ = conv(False, 5)
        assert y2 == 32

    def test_bool_ops_on_tensors(self):
        def f(x):
            if x.sum() > 0 and x.max() < 10:
                y = x * 1.0
            else:
                y = x * 0.0
            return y

        fn = jit.to_static(f)
        a = np.array([1.0, 2.0], "f4")
        np.testing.assert_allclose(fn(pt.to_tensor(a)).numpy(), a)
        b = np.array([1.0, 50.0], "f4")
        np.testing.assert_allclose(fn(pt.to_tensor(b)).numpy(), [0, 0])

    def test_undefined_var_in_tensor_branch_raises(self):
        def f(x):
            if x.sum() > 0:
                z = x * 2
            else:
                z = x * 3
            # w only defined on one python path:
            if x.sum() > 0:
                w = z + 1
            return z

        # w is assigned in only one branch of a tensor `if` with no else;
        # entering traced mode must raise a clear error
        fn = jit.to_static(f)
        with pytest.raises(ValueError, match="must be defined"):
            fn(pt.to_tensor(np.ones((2,), "f4")))

    def test_augassign_unbound_still_raises(self):
        """Regression (review r3): `c += 1` in both branches of a tensor
        `if` with c unbound must raise (AugAssign is a read), not be
        silently seeded with 0.0."""
        def f(x):
            if x.sum() > 0:
                c += 1.0  # noqa: F821 — deliberate unbound read
            else:
                c += 2.0  # noqa: F821
            return c

        fn = jit.to_static(f)
        with pytest.raises((ValueError, NameError, UnboundLocalError)):
            fn(pt.to_tensor(np.ones((2,), "f4")))

    def test_break_loops_stay_python(self):
        def f(x):
            total = x
            for i in range(4):
                if i == 2:
                    break
                total = total + 1.0
            return total

        conv = convert_function(f)
        out = conv(pt.to_tensor(np.float32(0.0)))
        assert float(out.numpy()) == 2.0


class TestTranslatorSwitch:
    def test_toggle_applies_at_call_time(self):
        """Regression (review r3): flipping the translator after the
        StaticFunction exists changes behavior (reference semantics)."""
        import jax
        fn = jit.to_static(collatz_steps)
        ProgramTranslator().enable(False)
        try:
            with pytest.raises(jax.errors.TracerBoolConversionError):
                fn(pt.to_tensor(np.float32(8.0)))
        finally:
            ProgramTranslator().enable(True)
        out = fn(pt.to_tensor(np.float32(8.0)))
        assert float(out.numpy()) == 3.0

    def test_singleton_and_enable(self):
        a = ProgramTranslator()
        b = ProgramTranslator.get_instance()
        assert a is b
        a.enable(False)
        assert not ProgramTranslator.is_enabled()
        a.enable(True)
        assert ProgramTranslator.is_enabled()
