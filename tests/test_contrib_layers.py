"""fluid.contrib surface (reference: contrib/layers/{nn,rnn_impl,
metric_op}.py + model_stat/memory_usage_calc/op_frequence/
extend_optimizer)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.fluid import contrib as C


def test_fused_elemwise_activation_matches_compose():
    rng = np.random.RandomState(0)
    x = rng.randn(3, 5).astype("f4")
    y = rng.randn(3, 5).astype("f4")
    out = C.fused_elemwise_activation(pt.to_tensor(x), pt.to_tensor(y),
                                      ["elementwise_add", "relu"])
    np.testing.assert_allclose(out.numpy(), np.maximum(x + y, 0),
                               atol=1e-6)


def test_partial_concat_and_sum():
    rng = np.random.RandomState(1)
    x = rng.randn(2, 6).astype("f4")
    y = rng.randn(2, 6).astype("f4")
    pc = C.partial_concat([pt.to_tensor(x), pt.to_tensor(y)], 1, 2)
    np.testing.assert_allclose(
        pc.numpy(), np.concatenate([x[:, 1:3], y[:, 1:3]], 1), atol=1e-6)
    ps = C.partial_sum([pt.to_tensor(x), pt.to_tensor(y)], 1, 2)
    np.testing.assert_allclose(ps.numpy(), x[:, 1:3] + y[:, 1:3],
                               atol=1e-6)


def test_match_matrix_tensor_einsum():
    pt.seed(0)
    rng = np.random.RandomState(2)
    a = rng.randn(2, 3, 4).astype("f4")
    b = rng.randn(2, 5, 4).astype("f4")
    out, tmp = C.match_matrix_tensor(pt.to_tensor(a), pt.to_tensor(b),
                                     channel_num=2)
    assert out.shape == [2, 2, 3, 5]
    # spot check one cell against the created weight is not possible
    # (weight internal) — instead verify bilinearity: doubling x doubles out
    pt.seed(0)
    out2, _ = C.match_matrix_tensor(pt.to_tensor(2 * a), pt.to_tensor(b),
                                    channel_num=2)
    np.testing.assert_allclose(out2.numpy(), 2 * out.numpy(), rtol=1e-4)


def test_sequence_topk_avg_pooling_values():
    x = np.zeros((1, 1, 2, 4), "f4")
    x[0, 0, 0] = [4, 1, 3, 2]
    x[0, 0, 1] = [10, 20, 0, 0]
    out = C.sequence_topk_avg_pooling(pt.to_tensor(x), None, None,
                                      [1, 2], 1)
    # row 0: top1=4, top2 avg=(4+3)/2=3.5; row 1: 20, 15
    np.testing.assert_allclose(out.numpy()[0, 0], [4.0, 3.5], atol=1e-6)
    np.testing.assert_allclose(out.numpy()[0, 1], [20.0, 15.0], atol=1e-6)


def test_fused_embedding_seq_pool_sum_and_padding():
    pt.seed(0)
    ids = np.asarray([[1, 2], [0, 0]], "i4")
    out = C.fused_embedding_seq_pool(pt.to_tensor(ids), (5, 3),
                                     padding_idx=0)
    assert out.shape == [2, 3]
    np.testing.assert_allclose(out.numpy()[1], 0.0, atol=1e-6)


def test_basic_gru_lstm_and_units():
    pt.seed(0)
    x = pt.to_tensor(np.random.RandomState(3).randn(2, 5, 4).astype("f4"))
    og, lh = C.basic_gru(x, None, 3, num_layers=2)
    assert og.shape == [2, 5, 3] and lh.shape == [2, 2, 3]
    ol, h, c = C.basic_lstm(x, None, None, 3, bidirectional=True)
    assert ol.shape == [2, 5, 6] and h.shape == [2, 2, 3]
    gu = C.BasicGRUUnit(hidden_size=3)
    hs = gu(pt.to_tensor(np.random.randn(2, 4).astype("f4")),
            pt.to_tensor(np.zeros((2, 3), "f4")))
    assert hs.shape == [2, 3]
    lu = C.BasicLSTMUnit(hidden_size=3)
    h1, c1 = lu(pt.to_tensor(np.random.randn(2, 4).astype("f4")),
                pt.to_tensor(np.zeros((2, 3), "f4")),
                pt.to_tensor(np.zeros((2, 3), "f4")))
    assert h1.shape == [2, 3] and c1.shape == [2, 3]


def test_multilayer_rnn_initial_state_used():
    """Regression: _MultiLayerRNN used to silently ignore
    initial_states."""
    pt.seed(0)
    from paddle_tpu.nn.rnn import GRU
    g = GRU(4, 3, num_layers=2)
    x = pt.to_tensor(np.zeros((2, 1, 4), "f4"))
    _, f0 = g(x)
    h0 = pt.to_tensor(np.ones((2, 2, 3), "f4") * 0.7)
    _, f1 = g(x, initial_states=h0)
    a = np.stack([np.asarray(s.numpy()) for s in f0])
    b = np.stack([np.asarray(s.numpy()) for s in f1])
    assert not np.allclose(a, b)


def test_ctr_metric_bundle_values():
    p = np.asarray([[0.2], [0.8]], "f4")
    y = np.asarray([[0.0], [1.0]], "f4")
    sq, ab, prob, q = C.ctr_metric_bundle(pt.to_tensor(p), pt.to_tensor(y))
    np.testing.assert_allclose(float(sq.numpy()), 0.04 + 0.04, atol=1e-6)
    np.testing.assert_allclose(float(ab.numpy()), 0.4, atol=1e-6)
    np.testing.assert_allclose(float(prob.numpy()), 1.0, atol=1e-6)
    np.testing.assert_allclose(float(q.numpy()), 1.0, atol=1e-6)


def test_tdm_child_and_sampler():
    pt.seed(0)
    ids = pt.to_tensor(np.asarray([[1], [2]], "i4"))
    ch, mask = C.tdm_child(ids, node_nums=8, child_nums=2)
    assert ch.shape == [2, 1, 2] and mask.shape == [2, 1, 2]
    outs = C.tdm_sampler(ids, [1, 1], [2, 4], leaf_node_num=8)
    assert len(outs) == 6  # (out, label, mask) x 2 layers
    out0, lab0 = outs[0], outs[2]
    assert out0.shape == [2, 2]  # positive + 1 negative
    np.testing.assert_allclose(lab0.numpy()[:, 0], 1)


def test_extend_with_decoupled_weight_decay_matches_adamw():
    from paddle_tpu import optimizer as opt
    AdamX = C.extend_with_decoupled_weight_decay(opt.Adam)
    w1 = pt.Parameter(np.ones((4, 2), "f4"))
    w2 = pt.Parameter(np.ones((4, 2), "f4"))
    o1 = AdamX(weight_decay=0.1, learning_rate=0.1, parameters=[w1])
    o2 = opt.AdamW(learning_rate=0.1, weight_decay=0.1, parameters=[w2])
    for o, w in ((o1, w1), (o2, w2)):
        (w * w).sum().backward()
        o.step()
        o.clear_grad()
    np.testing.assert_allclose(w1.numpy(), w2.numpy(), atol=1e-6)


def test_model_stat_and_op_freq():
    from paddle_tpu import static
    import paddle_tpu.fluid as fluid
    pt.enable_static()
    try:
        main = static.Program()
        startup = static.Program()
        with static.program_guard(main, startup):
            x = static.data("x", [None, 4], "float32")
            h = fluid.layers.fc(x, size=8, act="relu")
            y = fluid.layers.fc(h, size=2)
        table = C.summary(main)
        assert "total params" in table
        uni, adj = C.op_freq_statistic(main)
        assert sum(uni.values()) >= 2
        lo, hi = C.memory_usage(main, batch_size=32)
        assert hi > lo > 0
    finally:
        pt.disable_static()
