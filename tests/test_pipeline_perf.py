"""Step-pipelining stack (ISSUE 2): device prefetch, shape bucketing,
async fetches, AOT warmup, the persistent compilation cache, and the
executor cache-key mesh regression — all observable through the monitor
counters docs/performance.md documents."""
import threading
import time

import numpy as np
import pytest
import jax

import paddle_tpu as pt
from paddle_tpu import io, jit, nn, hapi, static, optimizer as opt
from paddle_tpu.fluid import layers as FL
from paddle_tpu.io.bucketing import (next_bucket, pad_to_bucket,
                                     batch_mask, pad_feed_dict)


@pytest.fixture
def mon():
    from paddle_tpu import monitor
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


def _prefetch_threads():
    return [t for t in threading.enumerate()
            if t.name == "paddle_tpu-prefetch" and t.is_alive()]


# ---------------------------------------------------------------------------
# bucketing primitives

def test_next_bucket_pow2_and_explicit():
    assert next_bucket(12) == 16
    assert next_bucket(32) == 32
    assert next_bucket(33) == 64
    assert next_bucket(12, [32]) == 32
    assert next_bucket(40, [8, 32]) == 40  # past the largest: exact
    assert next_bucket(5, [8, 32]) == 8


def test_pad_to_bucket_modes():
    a = np.arange(6, dtype="f4").reshape(3, 2)
    r = pad_to_bucket(a, 5)  # repeat
    assert r.shape == (5, 2)
    np.testing.assert_array_equal(r[3], a[-1])
    np.testing.assert_array_equal(r[4], a[-1])
    z = pad_to_bucket(a, 5, mode="zeros")
    np.testing.assert_array_equal(z[3:], np.zeros((2, 2), "f4"))
    import jax.numpy as jnp
    j = pad_to_bucket(jnp.asarray(a), 4)
    assert isinstance(j, jax.Array) and j.shape == (4, 2)
    with pytest.raises(ValueError):
        pad_to_bucket(a, 2)
    m = batch_mask(3, 5)
    np.testing.assert_array_equal(m, [1, 1, 1, 0, 0])


def test_pad_feed_dict_consistent_and_ragged():
    feed = {"x": np.ones((12, 4), "f4"), "y": np.ones((12, 1), "f4")}
    out, real_n, padded_n = pad_feed_dict(feed, buckets=[32])
    assert (real_n, padded_n) == (12, 32)
    assert out["x"].shape == (32, 4) and out["y"].shape == (32, 1)
    # inconsistent batch dims: no slicing info
    out2, r2, p2 = pad_feed_dict({"a": np.ones((3, 2)),
                                  "b": np.ones((5, 2))})
    assert (r2, p2) == (None, None)
    assert out2["a"].shape == (4, 2) and out2["b"].shape == (8, 2)


# ---------------------------------------------------------------------------
# prefetch_to_device

def test_prefetch_order_and_device_placement(mon):
    batches = [{"x": np.full((4, 2), i, "f4"), "y": np.array([i], "i4")}
               for i in range(10)]
    got = list(io.prefetch_to_device(iter(batches), size=3))
    assert len(got) == 10
    for i, b in enumerate(got):
        assert isinstance(b["x"], jax.Array)  # already device-resident
        assert float(b["x"][0, 0]) == i       # order preserved
    assert mon.registry().value("prefetch.batches") == 10
    assert not _prefetch_threads()  # worker joined at exhaustion


def test_prefetch_mesh_sharding():
    from jax.sharding import Mesh
    devs = jax.devices()
    assert len(devs) == 8, "conftest forces an 8-device CPU mesh"
    mesh = Mesh(np.array(devs), ("dp",))
    batches = [(np.arange(16, dtype="f4").reshape(16, 1),
                np.float32(0.5))]  # scalar leaf: replicates
    (xb, sb), = list(io.prefetch_to_device(iter(batches), mesh=mesh))
    assert len(xb.sharding.device_set) == 8
    assert not xb.sharding.is_fully_replicated  # batch-sharded
    assert sb.sharding.is_fully_replicated
    # 1-device mesh: everything lands on that one device
    mesh1 = Mesh(np.array(devs[:1]), ("dp",))
    (xb1, _), = list(io.prefetch_to_device(iter(batches), mesh=mesh1))
    assert xb1.sharding.device_set == {devs[0]}


def test_prefetch_shutdown_no_thread_leak():
    def gen():
        for i in range(1000):
            yield np.full((2,), i, "f4")

    it = io.prefetch_to_device(gen(), size=2)
    first = next(it)
    assert float(first[0]) == 0
    it.close()  # abandoning mid-stream must stop + join the producer
    deadline = time.time() + 5
    while _prefetch_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert not _prefetch_threads()


def test_prefetch_propagates_producer_error():
    def gen():
        yield np.zeros((2,), "f4")
        raise RuntimeError("boom in the pipeline")

    it = io.prefetch_to_device(gen())
    next(it)
    with pytest.raises(RuntimeError, match="boom in the pipeline"):
        next(it)
    assert not _prefetch_threads()


def test_dataloader_prefetch_to_device_param():
    x = np.random.RandomState(0).rand(20, 3).astype("f4")
    dl = io.DataLoader(io.TensorDataset(x), batch_size=8,
                       prefetch_to_device=2)
    seen = 0
    for (xb,) in dl:
        assert isinstance(xb, jax.Array)
        seen += xb.shape[0]
    assert seen == 20
    assert not _prefetch_threads()


def test_dataloader_threaded_iterator_shutdown():
    x = np.random.RandomState(0).rand(400, 3).astype("f4")
    dl = io.DataLoader(io.TensorDataset(x), batch_size=2, use_native=False,
                       prefetch_factor=2)
    before = threading.active_count()
    it = iter(dl)
    next(it)
    it.close()  # abandoned epoch: producer must unblock from q.put + join
    deadline = time.time() + 5
    while threading.active_count() > before and time.time() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


# ---------------------------------------------------------------------------
# Executor: cache key, bucketing, async fetch, warmup

def _build_program(din=8):
    prog, sprog = static.Program(), static.Program()
    with static.program_guard(prog, sprog):
        x = static.data("x", [None, din], "float32")
        y = static.data("y", [None, 1], "float32")
        h = FL.fc(x, 16, act="relu")
        out = FL.fc(h, 1)
        loss = ((out - y) ** 2).mean()
        opt.SGD(learning_rate=0.05).minimize(loss)
    return prog, sprog, loss, out


def _data(n, din=8, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.rand(n, din).astype("f4")
    return x, (x.sum(-1, keepdims=True) * 0.5).astype("f4")


def test_executor_cache_key_includes_mesh():
    """Regression (ISSUE 2 satellite): a plain run and a
    with_data_parallel run with IDENTICAL feed shapes must compile two
    distinct executables, not collide on one cache slot."""
    pt.enable_static()
    try:
        prog, sprog, loss, _ = _build_program()
        exe = static.Executor()
        exe.run(sprog)
        x, y = _data(64)
        plain = exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss])
        cp = static.CompiledProgram(prog).with_data_parallel(
            loss_name=loss.name)
        exe.run(cp, feed={"x": x, "y": y}, fetch_list=[loss])
        assert len(exe._cache) == 2
        keys = list(exe._cache)
        assert keys[0][:2] == keys[1][:2]      # same program
        assert keys[0][3] != keys[1][3]        # different mesh signature
        assert np.isfinite(plain[0]).all()
    finally:
        pt.disable_static()


def test_executor_feed_keying_skips_device_transfer(mon):
    """Satellite: shapes/dtypes for the cache key come from the HOST
    arrays — and jnp.asarray's x64-off canonicalization is mirrored, so
    a float64/int64 feed hits the float32/int32 executable."""
    pt.enable_static()
    try:
        prog, sprog, loss, _ = _build_program()
        exe = static.Executor()
        exe.run(sprog)
        x, y = _data(16)
        exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss])
        exe.run(prog, feed={"x": x.astype("f8"), "y": y.astype("f8")},
                fetch_list=[loss])
        reg = mon.registry()
        assert reg.value("executor.compile") == 1
        assert reg.value("executor.cache_hit") == 1
    finally:
        pt.disable_static()


def test_executor_bucketing_single_compile_and_fetch_slicing(mon):
    pt.enable_static()
    try:
        prog, sprog, loss, out = _build_program()
        exe = static.Executor()
        exe.run(sprog)
        x, y = _data(300)
        for i in range(0, 300, 32):  # 9 full batches + a ragged 12
            res = exe.run(prog, feed={"x": x[i:i + 32], "y": y[i:i + 32]},
                          fetch_list=[loss, out], bucket=True,
                          buckets=[32])
        reg = mon.registry()
        assert reg.value("executor.compile") == 1
        assert reg.value("executor.recompile") == 0
        assert reg.value("executor.bucket_pad") == 1
        assert res[1].shape == (12, 1)  # per-example fetch sliced back

        # repeat-padding leaves the real rows' forward untouched: clone
        # the current params (host copies — donation would invalidate a
        # shared device buffer) and compare bucketed vs exact-shape runs
        prog2, sprog2, _, out2 = _build_program()
        exe2 = static.Executor()
        exe2.run(sprog2)
        for holder, src in zip(prog2.param_vars.values(),
                               prog.param_vars.values()):
            holder.data = np.asarray(src.data).copy()
        exact = exe2.run(prog2, feed={"x": x[288:], "y": y[288:]},
                         fetch_list=[out2])
        padded = exe.run(prog, feed={"x": x[288:], "y": y[288:]},
                         fetch_list=[loss, out], bucket=True,
                         buckets=[32])
        np.testing.assert_allclose(padded[1], exact[0], rtol=2e-5,
                                   atol=1e-6)
    finally:
        pt.disable_static()


def test_executor_recompile_counter_without_bucketing(mon):
    pt.enable_static()
    try:
        prog, sprog, loss, _ = _build_program()
        exe = static.Executor()
        exe.run(sprog)
        for n in (32, 12):  # second shape = the avoidable recompile
            x, y = _data(n)
            exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss])
        reg = mon.registry()
        assert reg.value("executor.compile") == 2
        assert reg.value("executor.recompile") == 1
    finally:
        pt.disable_static()


def test_executor_async_fetch_lag_and_flush(mon):
    pt.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            x = static.data("x", [None, 2], "float32")
            out = x * 2.0
        exe = static.Executor()
        vals = [np.full((4, 2), i, "f4") for i in range(3)]
        got = [exe.run(prog, feed={"x": v}, fetch_list=[out],
                       async_fetch=True) for v in vals]
        assert got[0] is None                      # nothing pending yet
        assert float(got[1][0][0, 0]) == 0.0       # step 0's fetch
        assert float(got[2][0][0, 0]) == 2.0       # step 1's fetch
        last = exe.flush_fetches()
        assert float(last[0][0, 0]) == 4.0         # step 2's fetch
        assert exe.flush_fetches() is None
        reg = mon.registry()
        assert reg.value("executor.fetch_blocking") == 0
        assert reg.value("executor.fetch_async") == 3
    finally:
        pt.disable_static()


def test_executor_fetch_period(mon):
    pt.enable_static()
    try:
        prog = static.Program()
        with static.program_guard(prog, static.Program()):
            x = static.data("x", [None], "float32")
            out = x + 1.0
        exe = static.Executor()
        got = [exe.run(prog, feed={"x": np.full((2,), i, "f4")},
                       fetch_list=[out], fetch_period=2)
               for i in range(4)]
        assert got[0] is None and got[2] is None
        assert got[1] is not None and got[3] is not None
        assert mon.registry().value("executor.fetch_skipped") == 2
    finally:
        pt.disable_static()


def test_executor_warmup_aot_precompiles(mon):
    pt.enable_static()
    try:
        prog, sprog, loss, _ = _build_program()
        exe = static.Executor()
        exe.run(sprog)
        key = exe.warmup(prog, feed_specs={"x": ((32, 8), "float32"),
                                           "y": ((32, 1), "float32")},
                         fetch_list=[loss], bucket=True, buckets=[32])
        assert key in exe._cache
        reg = mon.registry()
        assert reg.value("executor.aot_warmup") == 1
        assert reg.value("executor.compile") == 1
        x, y = _data(12)  # ragged: buckets to the warmed 32-row shape
        res = exe.run(prog, feed={"x": x, "y": y}, fetch_list=[loss],
                      bucket=True, buckets=[32])
        assert reg.value("executor.compile") == 1  # no new executable
        assert reg.value("executor.cache_hit") == 1
        assert np.isfinite(res[0]).all()
    finally:
        pt.disable_static()


def test_train_from_dataset_prefetch_and_bucket(mon):
    pt.enable_static()
    try:
        from paddle_tpu.fluid.dataset import InMemoryDataset
        prog, sprog, loss, _ = _build_program(din=4)
        exe = static.Executor()
        exe.run(sprog)
        ds = InMemoryDataset()
        ds.set_use_var([prog.feed_vars["x"], prog.feed_vars["y"]])
        ds.set_batch_size(8)
        rng = np.random.RandomState(0)
        # resident records, MultiSlot layout: [x slot values, y slot]
        ds._memory = [[[float(v) for v in rng.rand(4)], [0.5]]
                      for _ in range(20)]  # 2 full batches + ragged 4
        exe.train_from_dataset(prog, dataset=ds, fetch_list=[loss],
                               prefetch=2, bucket=True, buckets=[8])
        reg = mon.registry()
        assert reg.value("executor.compile") == 1
        assert reg.value("prefetch.batches") == 3
    finally:
        pt.disable_static()


# ---------------------------------------------------------------------------
# to_static bucketing + hapi fit

def test_to_static_bucketing_single_compile(mon):
    lin = nn.Linear(4, 2)

    @jit.to_static(models=[lin], bucket=True, buckets=[16])
    def fwd(x):
        return lin(x)

    full = fwd(pt.to_tensor(np.ones((16, 4), "f4")))
    ragged = fwd(pt.to_tensor(np.ones((5, 4), "f4")))
    assert tuple(ragged.shape) == (5, 2)  # output sliced to real length
    np.testing.assert_allclose(ragged.numpy(), full.numpy()[:5],
                               rtol=1e-6)
    reg = mon.registry()
    assert reg.value("jit.compile") == 1
    assert reg.value("jit.recompile") == 0
    assert reg.value("jit.bucket_pad") == 1
    assert reg.value("jit.cache_hit") == 1


def test_hapi_fit_bucket_and_prefetch(mon):
    pt.seed(0)
    rng = np.random.RandomState(0)
    x = rng.rand(40, 8).astype("f4")
    y = (x.sum(-1, keepdims=True) * 0.5).astype("f4")
    m = hapi.Model(nn.Sequential(nn.Linear(8, 16), nn.ReLU(),
                                 nn.Linear(16, 1)))
    m.prepare(optimizer=opt.SGD(learning_rate=0.05,
                                parameters=m.parameters()),
              loss_function=lambda o, lab: [((o - lab[0]) ** 2).mean()])
    hist = m.fit(io.TensorDataset(x, y), batch_size=32, epochs=3,
                 verbose=0, shuffle=False, bucket=True, prefetch=1)
    assert len(hist["loss"]) == 3
    assert hist["loss"][-1] < hist["loss"][0]
    reg = mon.registry()
    # 32-row + ragged 8-row batches share ONE executable
    assert reg.value("jit.compile") == 1
    assert reg.value("jit.recompile") == 0
    assert reg.value("jit.bucket_pad") == 3  # one ragged batch per epoch
    assert reg.value("prefetch.batches") == 6
    assert not _prefetch_threads()


# ---------------------------------------------------------------------------
# persistent compilation cache

def test_enable_compilation_cache(tmp_path):
    old = jax.config.jax_compilation_cache_dir
    try:
        p = pt.enable_compilation_cache(str(tmp_path / "xla"))
        assert p == str(tmp_path / "xla")
        import os
        assert os.path.isdir(p)
        assert jax.config.jax_compilation_cache_dir == p
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
