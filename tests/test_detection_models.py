"""YOLOv3 / SSD model zoo: end-to-end train step under jit + decode
(closing VERDICT r2 #3's "pipelines run under jit" at model level;
reference: the PaddleDetection-era YOLOv3/SSD configs over
fluid/layers/detection.py)."""
import numpy as np
import pytest

pytestmark = pytest.mark.slow

import paddle_tpu as pt
from paddle_tpu import jit, optimizer as opt
from paddle_tpu.models.detection import YOLOv3, SSD


class TestYOLOv3:
    def _setup(self):
        pt.seed(0)
        model = YOLOv3(num_classes=4, width=8)
        rng = np.random.RandomState(0)
        x = rng.rand(2, 3, 64, 64).astype("f4")
        gt = (rng.rand(2, 3, 4) * 0.5 + 0.25).astype("f4")
        gt[:, :, 2:] *= 0.4
        lbl = rng.randint(0, 4, (2, 3)).astype("i4")
        return model, x, gt, lbl

    def test_forward_shapes(self):
        model, x, gt, lbl = self._setup()
        outs = model(pt.to_tensor(x))
        assert len(outs) == 3
        # stride 32/16/8 on a 64px input
        assert outs[0].shape == [2, 3 * 9, 2, 2]
        assert outs[1].shape == [2, 3 * 9, 4, 4]
        assert outs[2].shape == [2, 3 * 9, 8, 8]

    def test_train_step_jits_and_descends(self):
        model, x, gt, lbl = self._setup()
        o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

        def step(xb, gtb, lblb):
            outs = model(xb)
            loss = model.loss(outs, gtb, lblb)
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        fn = jit.to_static(step, models=[model], optimizers=[o])
        t = (pt.to_tensor(x), pt.to_tensor(gt), pt.to_tensor(lbl))
        losses = [float(fn(*t).numpy()) for _ in range(8)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_predict_decodes(self):
        model, x, gt, lbl = self._setup()
        model.eval()
        outs = model(pt.to_tensor(x))
        img_size = pt.to_tensor(np.array([[64, 64], [64, 64]], "i4"))
        dets, nums = model.predict(outs, img_size, keep_top_k=10)
        assert dets.shape == [2, 10, 6]
        assert np.isfinite(dets.numpy()).all()


class TestSSD:
    def _setup(self):
        pt.seed(1)
        model = SSD(num_classes=5, image_size=64, width=8)
        rng = np.random.RandomState(1)
        x = rng.rand(2, 3, 64, 64).astype("f4")
        gt = np.zeros((2, 3, 4), "f4")
        gt[:, :2, :2] = rng.rand(2, 2, 2) * 0.5
        gt[:, :2, 2:] = gt[:, :2, :2] + 0.3
        lbl = rng.randint(1, 5, (2, 3)).astype("i4")
        lbl[:, 2] = 0  # padded slot (matches all-zero box)
        return model, x, gt, lbl

    def test_forward_and_priors(self):
        model, x, gt, lbl = self._setup()
        locs, confs, priors, pvars = model(pt.to_tensor(x))
        m = priors.shape[0]
        assert locs.shape == [2, m, 4]
        assert confs.shape == [2, m, 5]
        p = priors.numpy()
        assert p.min() >= 0.0 and p.max() <= 1.0

    def test_train_step_jits_and_descends(self):
        model, x, gt, lbl = self._setup()
        o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())

        def step(xb, gtb, lblb):
            locs, confs, priors, pvars = model(xb)
            loss = model.loss(locs, confs, priors, pvars, gtb, lblb)
            loss.backward()
            o.step()
            o.clear_grad()
            return loss

        fn = jit.to_static(step, models=[model], optimizers=[o])
        t = (pt.to_tensor(x), pt.to_tensor(gt), pt.to_tensor(lbl))
        losses = [float(fn(*t).numpy()) for _ in range(8)]
        assert all(np.isfinite(losses))
        assert losses[-1] < losses[0]

    def test_predict(self):
        model, x, gt, lbl = self._setup()
        model.eval()
        locs, confs, priors, pvars = model(pt.to_tensor(x))
        dets, nums = model.predict(locs, confs, priors, pvars,
                                   keep_top_k=8)
        assert dets.shape == [2, 8, 6]
