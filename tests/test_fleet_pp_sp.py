"""pp and sp axes through the user-facing fleet bridge (VERDICT r2 #8;
reference: Fleet pipeline strategy, fleet_base.py + PipelineOptimizer).

dp2×pp2×tp2: zoo-BERT whose encoder trunk is replaced by
fleet.pipeline_stack (stage-sharded stacked-scan, parallel/pipeline.py);
training losses must match the single-device run step for step.

sp: the token batch is sharded over (dp, sp) and GSPMD inserts the
sequence-parallel collectives; losses again match single-device."""
import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn, optimizer, jit
from paddle_tpu.models.bert import BertConfig, BertForPretraining
from paddle_tpu.parallel.fleet import Fleet, DistributedStrategy


def _bert_and_data(batch=8, seq=16):
    cfg = BertConfig.tiny(hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
    pt.seed(123)
    model = BertForPretraining(cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype("i4")
    mlm = np.where(rng.rand(batch, seq) < 0.2,
                   rng.randint(0, cfg.vocab_size, (batch, seq)),
                   -1).astype("i4")
    nsp = rng.randint(0, 2, (batch,)).astype("i4")
    return cfg, model, ids, mlm, nsp


def _make_step(model, o):
    def step(ids, mlm, nsp):
        logits, nsp_logits = model(ids)
        loss = model.loss(logits, nsp_logits, mlm, nsp)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss
    return jit.to_static(step, models=[model], optimizers=[o])


def _reference_losses(steps=3):
    cfg, model_ref, ids, mlm, nsp = _bert_and_data()
    o_ref = optimizer.SGD(learning_rate=0.1,
                          parameters=model_ref.parameters())
    step_ref = _make_step(model_ref, o_ref)
    return [float(step_ref(pt.to_tensor(ids), pt.to_tensor(mlm),
                           pt.to_tensor(nsp)).numpy())
            for _ in range(steps)], (ids, mlm, nsp)


@pytest.mark.slow
def test_fleet_bert_dp_pp_tp_matches_single_device():
    ref_losses, (ids, mlm, nsp) = _reference_losses()

    cfg, model, _, _, _ = _bert_and_data()
    fleet = Fleet()
    strategy = DistributedStrategy()
    strategy.mesh_shape = {"dp": 2, "pp": 2, "tp": 2}
    fleet.init(strategy=strategy)
    # stage-shard the encoder trunk over pp, THEN place the rest (tp)
    model.bert.encoder = fleet.pipeline_stack(list(model.bert.encoder))
    model = fleet.distributed_model(model)

    # the stacked trunk params really live on the pp axis
    stk = model.bert.encoder
    some = stk._parameters[stk._flat_names[0]]
    assert isinstance(some.data.sharding, jax.sharding.NamedSharding)
    assert some.data.sharding.spec[0] == "pp"

    o = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = _make_step(model, o)
    t = (pt.to_tensor(ids), pt.to_tensor(mlm), pt.to_tensor(nsp))
    losses = [float(step(*t).numpy()) for _ in range(3)]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_fleet_bert_sp_sharded_tokens_matches_single_device():
    ref_losses, (ids, mlm, nsp) = _reference_losses()

    cfg, model, _, _, _ = _bert_and_data()
    fleet = Fleet()
    strategy = DistributedStrategy()
    strategy.mesh_shape = {"dp": 2, "sp": 4}
    fleet.init(strategy=strategy)
    model = fleet.distributed_model(model)
    o = optimizer.SGD(learning_rate=0.1, parameters=model.parameters())
    step = _make_step(model, o)

    # shard tokens over (dp batch, sp sequence): GSPMD inserts the
    # sequence-parallel gathers for attention
    from jax.sharding import NamedSharding
    mesh = fleet.mesh
    tok_sharding = NamedSharding(mesh, P("dp", "sp"))
    row_sharding = NamedSharding(mesh, P("dp"))
    t_ids = pt.to_tensor(jax.device_put(ids, tok_sharding))
    t_mlm = pt.to_tensor(jax.device_put(mlm, tok_sharding))
    t_nsp = pt.to_tensor(jax.device_put(nsp, row_sharding))
    losses = [float(step(t_ids, t_mlm, t_nsp).numpy()) for _ in range(3)]
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-4)


def test_pipeline_stack_forward_matches_layerlist():
    """The stacked-scan trunk computes exactly what the LayerList did."""
    cfg, model, ids, _, _ = _bert_and_data()
    x = pt.to_tensor(ids)
    model.eval()
    ref, _ = model.bert(x)
    from paddle_tpu.parallel.pipeline import PipelineStack
    model.bert.encoder = PipelineStack(list(model.bert.encoder))
    got, _ = model.bert(x)
    np.testing.assert_allclose(got.numpy(), ref.numpy(), rtol=1e-5,
                               atol=1e-5)
