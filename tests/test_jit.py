"""jit.to_static: compiled train step parity with eager (SURVEY §3)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt, jit


def make_model():
    pt.seed(42)
    return nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))


def run_steps(model, o, compiled, n=5):
    pt.seed(7)
    losses = []
    xs = [np.random.RandomState(i).randn(8, 4).astype("f4") for i in range(n)]
    ys = [np.random.RandomState(100 + i).randn(8, 2).astype("f4")
          for i in range(n)]

    def step(x, y):
        out = model(x)
        loss = (out - y).square().mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o]) if compiled \
        else step
    for x, y in zip(xs, ys):
        losses.append(float(fn(pt.to_tensor(x), pt.to_tensor(y)).numpy()))
    return losses


def test_to_static_matches_eager():
    m1, m2 = make_model(), make_model()
    for (k1, v1), (k2, v2) in zip(sorted(m1.state_dict().items()),
                                  sorted(m2.state_dict().items())):
        np.testing.assert_allclose(v1.numpy(), v2.numpy())
    o1 = opt.Adam(learning_rate=0.01, parameters=m1.parameters())
    o2 = opt.Adam(learning_rate=0.01, parameters=m2.parameters())
    eager = run_steps(m1, o1, compiled=False)
    static = run_steps(m2, o2, compiled=True)
    np.testing.assert_allclose(eager, static, rtol=2e-3)
    # params also match after training
    for (_, v1), (_, v2) in zip(sorted(m1.state_dict().items()),
                                sorted(m2.state_dict().items())):
        np.testing.assert_allclose(v1.numpy(), v2.numpy(), atol=2e-4)


def test_to_static_caches_compilation():
    model = make_model()
    o = opt.SGD(learning_rate=0.01, parameters=model.parameters())

    calls = {"n": 0}

    def step(x):
        calls["n"] += 1
        loss = model(x).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    x = pt.to_tensor(np.random.randn(8, 4).astype("f4"))
    fn(x)
    fn(x)
    fn(x)
    assert calls["n"] == 1  # traced once, replayed compiled
    # new shape -> retrace
    fn(pt.to_tensor(np.random.randn(16, 4).astype("f4")))
    assert calls["n"] == 2


def test_to_static_dropout_rng_advances():
    model = nn.Sequential(nn.Dropout(0.5))
    model.train()
    fn = jit.to_static(lambda x: model(x), models=[model], optimizers=[])
    x = pt.to_tensor(np.ones((100,), "f4"))
    a = fn(x).numpy()
    b = fn(x).numpy()
    assert not np.allclose(a, b)  # key advanced between compiled calls


def test_to_static_closure_discovery():
    model = make_model()
    o = opt.SGD(learning_rate=0.1, parameters=model.parameters())

    @jit.to_static
    def step(x):
        loss = model(x).square().mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    x = pt.to_tensor(np.random.randn(4, 4).astype("f4"))
    l1 = float(step(x).numpy())
    l2 = float(step(x).numpy())
    assert l2 < l1  # params actually updated through compiled state carry


def test_to_static_batchnorm_stats_carry():
    model = nn.Sequential(nn.Linear(4, 8), nn.BatchNorm1D(8))
    model.train()
    fn = jit.to_static(lambda x: model(x).mean(), models=[model],
                       optimizers=[])
    bn = model[1]
    before = bn._mean.numpy().copy()
    fn(pt.to_tensor(np.random.randn(16, 8, 1).astype("f4")[:, :4, 0]))
    after = bn._mean.numpy()
    assert not np.allclose(before, after)


def test_recompute_matches_plain():
    pt.seed(0)
    block = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 4))
    x = pt.to_tensor(np.random.randn(2, 4).astype("f4"), stop_gradient=False)
    out = jit.recompute(block, x)
    loss = out.square().mean()
    loss.backward()
    g_remat = x.grad

    x2 = pt.to_tensor(x.numpy(), stop_gradient=False)
    loss2 = block(x2).square().mean()
    loss2.backward()
    np.testing.assert_allclose(np.asarray(g_remat), np.asarray(x2.grad),
                               atol=1e-5)


def test_to_static_multi_step_unrolled_matches_sequential():
    """bench.py runs `inner` REAL optimizer steps inside ONE compiled
    call (dispatch amortization); the unrolled trace must produce
    bit-comparable params to running the steps one compiled call each."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as opt, jit

    rng = np.random.RandomState(0)
    xs = rng.randn(3, 8, 4).astype("f4")
    ys = rng.randn(3, 8, 1).astype("f4")

    def make():
        pt.seed(0)
        m = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 1))
        o = opt.Adam(learning_rate=0.05, parameters=m.parameters())
        return m, o

    def body(m, o, xb, yb):
        loss = ((m(xb) - yb) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    # A: one call per step
    m1, o1 = make()
    f1 = jit.to_static(lambda xb, yb: body(m1, o1, xb, yb),
                       models=[m1], optimizers=[o1])
    for i in range(3):
        l1 = f1(pt.to_tensor(xs[i]), pt.to_tensor(ys[i]))

    # B: all three steps unrolled in one call
    m2, o2 = make()

    def step3(x_k, y_k):
        loss = None
        for i in range(3):
            loss = body(m2, o2, x_k[i], y_k[i])
        return loss

    f3 = jit.to_static(step3, models=[m2], optimizers=[o2])
    l3 = f3(pt.to_tensor(xs), pt.to_tensor(ys))

    np.testing.assert_allclose(float(l1.numpy()), float(l3.numpy()),
                               rtol=1e-5)
    for p1, p2 in zip(m1.parameters(), m2.parameters()):
        np.testing.assert_allclose(p1.numpy(), p2.numpy(), atol=1e-6)


@pytest.mark.slow
def test_bert_recompute_matches_plain():
    """use_recompute=True (per-layer jax.checkpoint, RNG threaded
    explicitly through the checkpointed region) must be bit-comparable to
    the plain path with dropout off, and train with dropout on."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    from paddle_tpu import optimizer as opt, jit

    kw = dict(use_flash_attention=False, hidden_dropout_prob=0.0,
              attention_probs_dropout_prob=0.0)
    pt.seed(0)
    m1 = BertForPretraining(BertConfig.tiny(use_recompute=True, **kw))
    pt.seed(0)
    m2 = BertForPretraining(BertConfig.tiny(**kw))
    rng = np.random.RandomState(0)
    ids = rng.randint(0, 1024, (2, 16)).astype("i4")
    mask = np.ones((2, 16), "i4")
    mask[1, 10:] = 0
    mlm = np.full((2, 16), -1, "i4")
    mlm[:, 3] = 5
    nsp = np.zeros((2,), "i4")

    def mk(m):
        o = opt.Adam(learning_rate=1e-3, parameters=m.parameters())

        def step(i, msk, ml, ns):
            lo, nl = m(i, attention_mask=msk)
            loss = m.loss(lo, nl, ml, ns)
            loss.backward()
            o.step()
            o.clear_grad()
            return loss
        return jit.to_static(step, models=[m], optimizers=[o])

    f1, f2 = mk(m1), mk(m2)
    args = [pt.to_tensor(a) for a in (ids, mask, mlm, nsp)]
    a = [float(f1(*args).numpy()) for _ in range(3)]
    b = [float(f2(*args).numpy()) for _ in range(3)]
    np.testing.assert_allclose(a, b, rtol=1e-5)
    assert a[-1] < a[0]  # actually training

    # dropout on: different (valid) mask stream, still trains
    pt.seed(1)
    m3 = BertForPretraining(BertConfig.tiny(use_recompute=True,
                                            use_flash_attention=False))
    f3 = mk(m3)
    c = [float(f3(*args).numpy()) for _ in range(3)]
    assert c[-1] < c[0]


def test_state_cache_sees_unfreeze():
    """Unfreezing a parameter AFTER a compiled step must invalidate the
    cached state map (stop_gradient is part of the validity key): the
    optimizer lazily creates slots for newly-trainable params inside
    _collect_state, so a stale cache would silently never train them."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu import nn, optimizer as opt, jit

    pt.seed(0)
    m = nn.Linear(4, 4)
    m.weight.stop_gradient = True
    o = opt.Adam(learning_rate=0.1, parameters=m.parameters())
    x = pt.to_tensor(np.random.RandomState(0).randn(8, 4).astype("f4"))

    def step(x):
        loss = (m(x) ** 2).mean()
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[m], optimizers=[o])
    fn(x)
    frozen = m.weight.numpy().copy()
    fn(x)
    np.testing.assert_array_equal(frozen, m.weight.numpy())

    m.weight.stop_gradient = False
    fn(x)
    assert not np.allclose(frozen, m.weight.numpy()), \
        "unfrozen weight never trained: stale jit state cache"
