"""Metrics (SURVEY §4; reference metrics.py unittests)."""
import numpy as np

import paddle_tpu as pt
from paddle_tpu import metric


def test_accuracy():
    m = metric.Accuracy()
    pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]], "f4")
    label = np.array([1, 0, 0])
    m.update(pred, label)
    assert abs(m.accumulate() - 2 / 3) < 1e-6
    m.reset()
    assert m.accumulate() == 0.0


def test_accuracy_topk():
    m = metric.Accuracy(topk=(1, 2))
    pred = np.array([[0.5, 0.3, 0.2], [0.1, 0.2, 0.7]], "f4")
    label = np.array([1, 1])
    m.update(pred, label)
    a1, a2 = m.accumulate()
    assert abs(a1 - 0.0) < 1e-6 and abs(a2 - 1.0) < 1e-6


def test_precision_recall():
    p = metric.Precision()
    r = metric.Recall()
    preds = np.array([1, 1, 0, 1])
    labels = np.array([1, 0, 1, 1])
    p.update(preds, labels)
    r.update(preds, labels)
    assert abs(p.accumulate() - 2 / 3) < 1e-6
    assert abs(r.accumulate() - 2 / 3) < 1e-6


def test_auc_perfect_and_random():
    auc = metric.Auc()
    preds = np.array([0.9, 0.8, 0.2, 0.1], "f4")
    labels = np.array([1, 1, 0, 0])
    auc.update(preds, labels)
    assert auc.accumulate() > 0.99
    auc.reset()
    auc.update(np.array([0.5, 0.5, 0.5, 0.5], "f4"), labels)
    assert abs(auc.accumulate() - 0.5) < 0.01


def test_chunk_evaluator():
    ce = metric.ChunkEvaluator()
    ce.update(np.array([10]), np.array([8]), np.array([6]))
    p, r, f1 = ce.accumulate()
    assert abs(p - 0.6) < 1e-6 and abs(r - 0.75) < 1e-6


def test_edit_distance():
    ed = metric.EditDistance()
    ed.update(["kitten"], ["sitting"])
    avg, err = ed.accumulate()
    assert abs(avg - 3 / 7) < 1e-6 and err == 1.0


def test_composite():
    cm = metric.CompositeMetric()
    cm.add_metric(metric.Precision())
    cm.add_metric(metric.Recall())
    cm.update(np.array([1, 0]), np.array([1, 1]))
    p, r = cm.accumulate()
    assert p == 1.0 and r == 0.5


def test_functional_accuracy():
    pred = pt.to_tensor(np.array([[0.9, 0.1], [0.4, 0.6]], "f4"))
    label = pt.to_tensor(np.array([0, 1]))
    acc = metric.accuracy(pred, label)
    assert abs(float(acc.numpy()) - 1.0) < 1e-6
