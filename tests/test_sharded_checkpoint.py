"""Sharded checkpoint/resume proof (VERDICT r2 #6; reference:
python/paddle/fluid/io.py save/load_persistables + fleet_base.py
save_persistables): orbax round-trip of a dp×tp-sharded fleet model on
the 8-device mesh — placement preserved, training resumes bit-exact."""
import os

import numpy as np
import pytest
import jax
from jax.sharding import PartitionSpec as P

import paddle_tpu as pt
from paddle_tpu import nn, optimizer, jit, io
from paddle_tpu.models.bert import BertConfig, BertForPretraining
from paddle_tpu.parallel.fleet import Fleet, DistributedStrategy


def _bert_and_data(batch=8, seq=16):
    cfg = BertConfig.tiny(hidden_dropout_prob=0.0,
                          attention_probs_dropout_prob=0.0)
    pt.seed(123)
    model = BertForPretraining(cfg)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (batch, seq)).astype("i4")
    mlm = np.where(rng.rand(batch, seq) < 0.2,
                   rng.randint(0, cfg.vocab_size, (batch, seq)),
                   -1).astype("i4")
    nsp = rng.randint(0, 2, (batch,)).astype("i4")
    return cfg, model, ids, mlm, nsp


def _make_fleet_model():
    cfg, model, ids, mlm, nsp = _bert_and_data()
    fleet = Fleet()
    strategy = DistributedStrategy()
    strategy.mesh_shape = {"dp": 2, "tp": 4}
    fleet.init(strategy=strategy)
    model = fleet.distributed_model(model)
    return fleet, model, ids, mlm, nsp


def _step_fn(model, o):
    def step(ids, mlm, nsp):
        logits, nsp_logits = model(ids)
        loss = model.loss(logits, nsp_logits, mlm, nsp)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss
    return jit.to_static(step, models=[model], optimizers=[o])


def _sharded_param(model):
    """A parameter we know gets a tp sharding."""
    for name, p in model.named_parameters():
        if "ffn1.weight" in name:
            return name, p
    raise AssertionError("no ffn1.weight found")


@pytest.mark.slow
def test_orbax_roundtrip_placement_and_bitexact_resume(tmp_path):
    fleet, model, ids, mlm, nsp = _make_fleet_model()
    o = optimizer.Adam(learning_rate=1e-3, parameters=model.parameters())
    step = _step_fn(model, o)
    t = (pt.to_tensor(ids), pt.to_tensor(mlm), pt.to_tensor(nsp))

    # train 2 steps, checkpoint, train 2 more → reference losses
    for _ in range(2):
        step(*t)
    ckpt = os.path.join(str(tmp_path), "fleet_ckpt")
    fleet.save_persistables(dirname=ckpt, model=model, optimizer=o)
    after = [float(step(*t).numpy()) for _ in range(2)]

    # fresh fleet model + optimizer; restore; the next 2 losses must match
    # the post-checkpoint trajectory bit-for-bit
    fleet2, model2, _, _, _ = _make_fleet_model()
    o2 = optimizer.Adam(learning_rate=1e-3, parameters=model2.parameters())
    step2 = _step_fn(model2, o2)
    step2(*t)  # build optimizer slots (then overwritten by restore)
    fleet2.load_persistables(dirname=ckpt, model=model2, optimizer=o2)

    name, p = _sharded_param(model2)
    shd = p.data.sharding
    assert isinstance(shd, jax.sharding.NamedSharding)
    assert shd.spec == P(None, "tp"), (name, shd.spec)
    # the restored value equals the checkpointed one
    name1, p1 = _sharded_param(model)

    resumed = [float(step2(*t).numpy()) for _ in range(2)]
    np.testing.assert_array_equal(np.asarray(after, "f8"),
                                  np.asarray(resumed, "f8"))


def test_checkpoint_manager_sharded_model(tmp_path):
    """CheckpointManager restore keeps mesh placement (set_value re-places
    onto the holder's sharding)."""
    fleet, model, ids, mlm, nsp = _make_fleet_model()
    mgr = io.CheckpointManager(str(tmp_path), max_to_keep=2)
    mgr.save(step=1, model=model)
    # perturb, then restore
    name, p = _sharded_param(model)
    before = np.asarray(jax.device_get(p.data))
    p.set_value(np.zeros_like(before))
    mgr.restore(model=model)
    now = np.asarray(jax.device_get(p.data))
    np.testing.assert_array_equal(now, before)
    assert isinstance(p.data.sharding, jax.sharding.NamedSharding)
    assert p.data.sharding.spec == P(None, "tp")


@pytest.mark.slow
def test_save_inference_model_from_fleet(tmp_path):
    fleet, model, ids, mlm, nsp = _make_fleet_model()
    model.eval()
    fleet.save_inference_model(dirname=str(tmp_path), model=model)
    loaded = io.load_inference_model(os.path.join(str(tmp_path), "model"))
    out_ref = model(pt.to_tensor(ids))[0].numpy()
    out = loaded(pt.to_tensor(ids))[0].numpy()
    np.testing.assert_allclose(out, out_ref, atol=2e-5, rtol=2e-5)
