"""Optimizer update rules vs closed form (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import optimizer as opt


def quad_param(v=None):
    return pt.Parameter(np.asarray(v if v is not None else [1.0, 2.0], "f4"))


def step_once(o, w):
    loss = (w * w).sum()
    loss.backward()
    o.step()
    o.clear_grad()


def test_sgd_closed_form():
    w = quad_param()
    o = opt.SGD(learning_rate=0.1, parameters=[w])
    step_once(o, w)  # w -= lr * 2w
    np.testing.assert_allclose(w.numpy(), [0.8, 1.6], atol=1e-6)


def test_momentum_closed_form():
    w = quad_param()
    o = opt.Momentum(learning_rate=0.1, momentum=0.9, parameters=[w])
    step_once(o, w)
    np.testing.assert_allclose(w.numpy(), [0.8, 1.6], atol=1e-6)
    step_once(o, w)
    # v2 = 0.9*[2,4] + 2*[0.8,1.6]; w2 = w1 - 0.1*v2
    np.testing.assert_allclose(w.numpy(), [0.8 - 0.1 * (1.8 + 1.6),
                                           1.6 - 0.1 * (3.6 + 3.2)],
                               atol=1e-5)


def test_adam_closed_form():
    w = quad_param([1.0])
    o = opt.Adam(learning_rate=0.1, parameters=[w])
    step_once(o, w)
    # first adam step ≈ -lr * sign(g)
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1], atol=1e-4)


def test_adamw_decoupled_decay():
    w = quad_param([1.0])
    o = opt.AdamW(learning_rate=0.1, parameters=[w], weight_decay=0.1)
    step_once(o, w)
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.1 - 0.1 * 0.1 * 1.0],
                               atol=1e-4)


def test_adagrad_rmsprop_adadelta_run():
    for cls in [opt.Adagrad, opt.RMSProp, opt.Adadelta, opt.Adamax,
                opt.Lamb, opt.Ftrl, opt.DecayedAdagrad, opt.LarsMomentum]:
        w = quad_param()
        o = cls(learning_rate=0.01, parameters=[w])
        before = w.numpy().copy()
        step_once(o, w)
        assert not np.allclose(w.numpy(), before), cls.__name__


def test_convergence_sgd_quadratic():
    w = quad_param([5.0, -3.0])
    o = opt.SGD(learning_rate=0.2, parameters=[w])
    for _ in range(50):
        step_once(o, w)
    np.testing.assert_allclose(w.numpy(), [0.0, 0.0], atol=1e-3)


def test_regularization_l2():
    w = quad_param([1.0])
    o = opt.SGD(learning_rate=0.1, parameters=[w],
                weight_decay=pt.regularizer.L2Decay(0.5))
    # grad = 2w + 0.5w = 2.5
    step_once(o, w)
    np.testing.assert_allclose(w.numpy(), [1.0 - 0.25], atol=1e-6)


def test_grad_clip_global_norm():
    w = quad_param([3.0, 4.0])  # grad = [6, 8], norm 10
    o = opt.SGD(learning_rate=1.0, parameters=[w],
                grad_clip=pt.ClipGradByGlobalNorm(1.0))
    step_once(o, w)
    np.testing.assert_allclose(w.numpy(), [3.0 - 0.6, 4.0 - 0.8], atol=1e-5)


def test_lr_scheduler_wiring():
    w = quad_param()
    sched = opt.lr.StepDecay(learning_rate=0.1, step_size=2, gamma=0.1)
    o = opt.SGD(learning_rate=sched, parameters=[w])
    assert abs(o.get_lr() - 0.1) < 1e-8
    sched.step()
    sched.step()
    assert abs(o.get_lr() - 0.01) < 1e-8
    # the device-side lr tensor followed
    assert abs(float(o._lr_tensor.numpy()) - 0.01) < 1e-8


@pytest.mark.parametrize("cls,kw", [
    (opt.lr.NoamDecay, dict(d_model=512, warmup_steps=100)),
    (opt.lr.ExponentialDecay, dict(learning_rate=0.1, gamma=0.9)),
    (opt.lr.PolynomialDecay, dict(learning_rate=0.1, decay_steps=10)),
    (opt.lr.CosineAnnealingDecay, dict(learning_rate=0.1, T_max=10)),
    (opt.lr.PiecewiseDecay, dict(boundaries=[2, 4], values=[0.1, 0.01, 0.001])),
    (opt.lr.MultiStepDecay, dict(learning_rate=0.1, milestones=[2, 4])),
    (opt.lr.LinearWarmup, dict(learning_rate=0.1, warmup_steps=5,
                               start_lr=0.0, end_lr=0.1)),
])
def test_schedulers_produce_positive_lrs(cls, kw):
    s = cls(**kw)
    vals = [s.step() for _ in range(6)]
    assert all(v >= 0 for v in vals)


def test_ema():
    w = quad_param([1.0])
    ema = opt.ExponentialMovingAverage(decay=0.5)
    ema.update([w])
    w.set_value(np.array([3.0], "f4"))
    ema.update([w])
    with ema.apply([w]):
        # shadow ≈ between 1 and 3
        assert 1.0 <= float(w.numpy()[0]) <= 3.0
    np.testing.assert_allclose(w.numpy(), [3.0])


def test_lookahead():
    w = quad_param([2.0])
    inner = opt.SGD(learning_rate=0.1, parameters=[w])
    la = opt.LookAhead(inner, alpha=0.5, k=2)
    for _ in range(4):
        loss = (w * w).sum()
        loss.backward()
        la.step()
        la.clear_grad()
    assert float(w.numpy()[0]) < 2.0


def test_optimizer_tail_untested():
    """Closed-form checks for the optimizers nothing else exercised:
    Dpsgd (clipped + noisy step moves params), ModelAverage (window
    average apply/restore), RecomputeOptimizer (delegates to inner).
    (DGCMomentum==Momentum lives in test_namespace_parity.)"""
    # Dpsgd: params move and stay finite (stochastic by design)
    pt.seed(0)
    w = pt.Parameter(np.ones((8,), "f4"))
    od = opt.Dpsgd(learning_rate=0.05, clip=1.0, sigma=0.1,
                   parameters=[w])
    before = w.numpy().copy()
    (w * w).sum().backward()
    od.step()
    od.clear_grad()
    assert np.isfinite(w.numpy()).all()
    assert not np.allclose(before, w.numpy())

    # ModelAverage: apply() swaps in the window average, restore() undoes
    w = pt.Parameter(np.zeros((2,), "f4"))
    ma = opt.ModelAverage(0.15)
    seen = []
    for step_val in (1.0, 2.0, 3.0):
        w.set_value(np.full((2,), step_val, "f4"))
        ma.update([w])
        seen.append(step_val)
    cur = w.numpy().copy()
    with ma.apply([w]):
        np.testing.assert_allclose(w.numpy(), np.mean(seen), atol=1e-6)
    np.testing.assert_allclose(w.numpy(), cur, atol=0)

    # RecomputeOptimizer: duck-types the inner optimizer
    w = pt.Parameter(np.ones((3,), "f4"))
    ro = opt.RecomputeOptimizer(opt.SGD(learning_rate=0.5,
                                        parameters=[w]))
    (w * w).sum().backward()
    ro.step()
    ro.clear_grad()
    np.testing.assert_allclose(w.numpy(), 0.0, atol=1e-6)


def test_lr_scheduler_tail_untested():
    """Closed-form checks for the schedulers nothing else exercised."""
    s = opt.lr.NaturalExpDecay(1.0, gamma=0.5)
    vals = []
    for _ in range(3):
        vals.append(s())
        s.step()
    np.testing.assert_allclose(vals, [1.0, np.exp(-0.5), np.exp(-1.0)],
                               rtol=1e-6)

    s = opt.lr.InverseTimeDecay(1.0, gamma=1.0)
    vals = []
    for _ in range(3):
        vals.append(s())
        s.step()
    np.testing.assert_allclose(vals, [1.0, 0.5, 1 / 3], rtol=1e-6)

    s = opt.lr.LambdaDecay(2.0, lr_lambda=lambda e: 0.9 ** e)
    vals = []
    for _ in range(3):
        vals.append(s())
        s.step()
    np.testing.assert_allclose(vals, [2.0, 1.8, 2.0 * 0.81], rtol=1e-6)

    # ReduceOnPlateau: lr drops by factor after patience non-improvements
    s = opt.lr.ReduceOnPlateau(1.0, factor=0.5, patience=2, cooldown=0)
    lrs = []
    for loss in (1.0, 1.0, 1.0, 1.0, 1.0):
        s.step(loss)
        lrs.append(s())
    # deterministic: exactly one halving after patience=2 bad epochs
    assert abs(lrs[-1] - 0.5) < 1e-6, lrs
