"""Numeric tests for sequence/LoD ops, linear-chain CRF, and CTC
(VERDICT r1 items 5; mirrors reference unittests test_sequence_*.py,
test_linear_chain_crf_op.py, test_warpctc_op.py)."""
import numpy as np
import pytest
import jax

import paddle_tpu as pt
from paddle_tpu import ops
from paddle_tpu.ops import crf as crf_ops
from paddle_tpu.ops import ctc as ctc_ops


# ---------------------------------------------------------------------------
# sequence ops

def test_sequence_conv_matches_window_sum():
    rs = np.random.RandomState(0)
    b, t, d, nf, fs = 2, 6, 4, 5, 3
    x = rs.randn(b, t, d).astype("f4")
    w = rs.randn(fs * d, nf).astype("f4")
    lens = np.array([6, 4], np.int32)
    out = ops.sequence_conv(pt.to_tensor(x), pt.to_tensor(w),
                            filter_size=fs, length=lens).numpy()

    # numpy reference: padding_start = -1 (centered window)
    ref = np.zeros((b, t, nf), "f4")
    for bi in range(b):
        for ti in range(lens[bi]):
            ctx = []
            for j in range(fs):
                src = ti - 1 + j
                if 0 <= src < lens[bi]:
                    ctx.append(x[bi, src])
                else:
                    ctx.append(np.zeros(d, "f4"))
            ref[bi, ti] = np.concatenate(ctx) @ w
    np.testing.assert_allclose(out, ref, atol=1e-4)


def test_sequence_slice_and_expand_as():
    x = np.arange(24, dtype="f4").reshape(2, 6, 2)
    out = ops.sequence_slice(pt.to_tensor(x), np.array([1, 2], np.int32),
                             np.array([3, 2], np.int32)).numpy()
    np.testing.assert_array_equal(out[0, :3], x[0, 1:4])
    np.testing.assert_array_equal(out[1, :2], x[1, 2:4])
    assert (out[0, 3:] == 0).all() and (out[1, 2:] == 0).all()

    v = np.array([[1.0, 2.0], [3.0, 4.0]], "f4")
    out = ops.sequence_expand_as(pt.to_tensor(v),
                                 np.array([3, 1], np.int32)).numpy()
    assert out.shape == (2, 3, 2)
    np.testing.assert_array_equal(out[0, :3], np.tile(v[0], (3, 1)))
    np.testing.assert_array_equal(out[1, 0], v[1])
    assert (out[1, 1:] == 0).all()


def test_sequence_reshape_scatter_enumerate():
    x = np.arange(12, dtype="f4").reshape(1, 3, 4)
    out = ops.sequence_reshape(pt.to_tensor(x), 6).numpy()
    assert out.shape == (1, 2, 6)
    np.testing.assert_array_equal(out.ravel(), x.ravel())

    base = np.zeros((2, 5), "f4")
    idx = np.array([[0, 2], [1, 1]], np.int64)
    upd = np.array([[1.0, 2.0], [3.0, 4.0]], "f4")
    out = ops.sequence_scatter(pt.to_tensor(base), idx,
                               pt.to_tensor(upd)).numpy()
    np.testing.assert_array_equal(out[0], [1, 0, 2, 0, 0])
    np.testing.assert_array_equal(out[1], [0, 7, 0, 0, 0])  # 3+4 at idx 1

    ids = np.array([[1, 2, 3, 4]], np.int64)
    win = ops.sequence_enumerate(ids, 2, pad_value=0,
                                 length=np.array([3], np.int32)).numpy()
    np.testing.assert_array_equal(win[0, 0], [1, 2])
    np.testing.assert_array_equal(win[0, 1], [2, 3])
    np.testing.assert_array_equal(win[0, 2], [3, 0])
    np.testing.assert_array_equal(win[0, 3], [0, 0])


def test_sequence_first_last_step():
    x = np.arange(12, dtype="f4").reshape(2, 3, 2)
    lens = np.array([2, 3], np.int32)
    first = ops.sequence_first_step(pt.to_tensor(x), length=lens).numpy()
    last = ops.sequence_last_step(pt.to_tensor(x), length=lens).numpy()
    np.testing.assert_array_equal(first, x[:, 0])
    np.testing.assert_array_equal(last[0], x[0, 1])
    np.testing.assert_array_equal(last[1], x[1, 2])


# ---------------------------------------------------------------------------
# CRF

def _np_crf_nll(emission, transition, label, lens):
    """Brute-force per-sequence NLL by enumerating all paths."""
    import itertools
    start, end, trans = transition[0], transition[1], transition[2:]
    b, t, d = emission.shape
    out = np.zeros(b)
    for bi in range(b):
        L = lens[bi]
        scores = []
        for path in itertools.product(range(d), repeat=L):
            s = start[path[0]] + emission[bi, 0, path[0]]
            for i in range(1, L):
                s += trans[path[i - 1], path[i]] + emission[bi, i, path[i]]
            s += end[path[-1]]
            scores.append(s)
        logz = np.logaddexp.reduce(scores)
        gold = start[label[bi, 0]] + emission[bi, 0, label[bi, 0]]
        for i in range(1, L):
            gold += trans[label[bi, i - 1], label[bi, i]] + \
                emission[bi, i, label[bi, i]]
        gold += end[label[bi, L - 1]]
        out[bi] = logz - gold
    return out


def test_linear_chain_crf_matches_bruteforce():
    rs = np.random.RandomState(1)
    b, t, d = 3, 4, 3
    emission = rs.randn(b, t, d).astype("f4")
    transition = rs.randn(d + 2, d).astype("f4")
    label = rs.randint(0, d, (b, t)).astype("i4")
    lens = np.array([4, 2, 3], np.int32)
    nll = ops.linear_chain_crf(pt.to_tensor(emission),
                               pt.to_tensor(label),
                               pt.to_tensor(transition),
                               length=lens).numpy()
    ref = _np_crf_nll(emission, transition, label, lens)
    np.testing.assert_allclose(nll[:, 0], ref, rtol=1e-4)


def test_crf_decoding_matches_bruteforce():
    import itertools
    rs = np.random.RandomState(2)
    b, t, d = 3, 5, 3
    emission = rs.randn(b, t, d).astype("f4")
    transition = rs.randn(d + 2, d).astype("f4")
    lens = np.array([5, 3, 4], np.int32)
    path = ops.crf_decoding(pt.to_tensor(emission),
                            pt.to_tensor(transition), length=lens).numpy()
    start, end, trans = transition[0], transition[1], transition[2:]
    for bi in range(b):
        L = lens[bi]
        best, best_s = None, -np.inf
        for p in itertools.product(range(d), repeat=L):
            s = start[p[0]] + emission[bi, 0, p[0]]
            for i in range(1, L):
                s += trans[p[i - 1], p[i]] + emission[bi, i, p[i]]
            s += end[p[-1]]
            if s > best_s:
                best, best_s = p, s
        np.testing.assert_array_equal(path[bi, :L], best)
        assert (path[bi, L:] == 0).all()


@pytest.mark.slow
def test_crf_trains_down():
    """CRF NLL decreases under SGD on the transition + emission params."""
    rs = np.random.RandomState(3)
    b, t, d = 4, 6, 4
    x = rs.randn(b, t, 8).astype("f4")
    label = rs.randint(0, d, (b, t)).astype("i4")
    lens = np.full((b,), t, np.int32)

    from paddle_tpu import nn, optimizer
    proj = nn.Linear(8, d)
    transition = pt.Parameter(rs.randn(d + 2, d).astype("f4") * 0.1)
    o = optimizer.SGD(learning_rate=0.1,
                      parameters=list(proj.parameters()) + [transition])
    losses = []
    for _ in range(25):
        em = proj(pt.to_tensor(x))
        nll = ops.linear_chain_crf(em, pt.to_tensor(label), transition,
                                   length=lens)
        loss = nll.mean()
        loss.backward()
        o.step()
        o.clear_grad()
        losses.append(float(loss.numpy()))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


# ---------------------------------------------------------------------------
# CTC

def test_ctc_loss_matches_torch():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(4)
    b, t, c, l = 3, 12, 6, 4
    logits = rs.randn(b, t, c).astype("f4")
    labels = rs.randint(1, c, (b, l)).astype("i4")
    ilen = np.array([12, 9, 11], np.int32)
    llen = np.array([4, 2, 3], np.int32)

    got = ops.ctc_loss(pt.to_tensor(logits), labels, ilen, llen,
                       blank=0, reduction="none").numpy()

    lp = torch.log_softmax(torch.tensor(logits), dim=-1).permute(1, 0, 2)
    ref = torch.nn.functional.ctc_loss(
        lp, torch.tensor(labels.astype("i8")), torch.tensor(ilen),
        torch.tensor(llen), blank=0, reduction="none").numpy()
    np.testing.assert_allclose(got, ref, rtol=1e-4)

    # mean reduction parity
    got_m = float(ops.ctc_loss(pt.to_tensor(logits), labels, ilen, llen,
                               blank=0, reduction="mean").numpy())
    ref_m = float(torch.nn.functional.ctc_loss(
        lp, torch.tensor(labels.astype("i8")), torch.tensor(ilen),
        torch.tensor(llen), blank=0, reduction="mean"))
    np.testing.assert_allclose(got_m, ref_m, rtol=1e-4)


def test_ctc_loss_gradients_match_torch():
    torch = pytest.importorskip("torch")
    rs = np.random.RandomState(5)
    b, t, c, l = 2, 8, 5, 3
    logits = rs.randn(b, t, c).astype("f4")
    labels = rs.randint(1, c, (b, l)).astype("i4")
    ilen = np.array([8, 6], np.int32)
    llen = np.array([3, 2], np.int32)

    lt = pt.to_tensor(logits)
    lt.stop_gradient = False
    loss = ops.ctc_loss(lt, labels, ilen, llen, blank=0, reduction="sum")
    loss.backward()
    got = np.asarray(jax.device_get(lt.grad))

    tl = torch.tensor(logits, requires_grad=True)
    lp = torch.log_softmax(tl, dim=-1).permute(1, 0, 2)
    ref = torch.nn.functional.ctc_loss(
        lp, torch.tensor(labels.astype("i8")), torch.tensor(ilen),
        torch.tensor(llen), blank=0, reduction="sum")
    ref.backward()
    np.testing.assert_allclose(got, tl.grad.numpy(), atol=2e-4)


def test_warpctc_shape_and_ctc_greedy_decoder():
    rs = np.random.RandomState(6)
    b, t, c = 2, 7, 5
    logits = rs.randn(b, t, c).astype("f4")
    out = ops.warpctc(pt.to_tensor(logits),
                      np.array([[1, 2], [3, -1]], np.int32)).numpy()
    assert out.shape == (b, 1) and np.isfinite(out).all()

    # greedy decode: force a known argmax pattern
    x = np.full((1, 6, 4), -5.0, "f4")
    seq = [1, 1, 0, 2, 2, 3]  # -> merge repeats, drop blanks: [1, 2, 3]
    for i, s in enumerate(seq):
        x[0, i, s] = 5.0
    dec, lens = ops.ctc_greedy_decoder(pt.to_tensor(x), blank=0)
    dec, lens = dec.numpy(), lens.numpy()
    assert lens[0] == 3
    np.testing.assert_array_equal(dec[0, :3], [1, 2, 3])
    assert (dec[0, 3:] == -1).all()
