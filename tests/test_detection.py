"""Detection op tests vs numpy references (SURVEY §4; reference test
strategy: python/paddle/fluid/tests/unittests/test_*_op.py for yolo_box,
multiclass_nms, iou_similarity, box_coder, roi_align...)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu.ops import detection as D


def np_iou(a, b, normalized=True):
    off = 0.0 if normalized else 1.0
    n, m = a.shape[0], b.shape[0]
    out = np.zeros((n, m), "f4")
    for i in range(n):
        for j in range(m):
            ix1 = max(a[i, 0], b[j, 0])
            iy1 = max(a[i, 1], b[j, 1])
            ix2 = min(a[i, 2], b[j, 2])
            iy2 = min(a[i, 3], b[j, 3])
            iw = max(ix2 - ix1 + off, 0.0)
            ih = max(iy2 - iy1 + off, 0.0)
            inter = iw * ih
            ua = max(a[i, 2] - a[i, 0] + off, 0) * \
                max(a[i, 3] - a[i, 1] + off, 0)
            ub = max(b[j, 2] - b[j, 0] + off, 0) * \
                max(b[j, 3] - b[j, 1] + off, 0)
            u = ua + ub - inter
            out[i, j] = inter / u if u > 0 else 0.0
    return out


def rand_boxes(rng, n, scale=100.0):
    xy = rng.rand(n, 2) * scale
    wh = rng.rand(n, 2) * scale * 0.3 + 1.0
    return np.concatenate([xy, xy + wh], -1).astype("f4")


class TestGeometry:
    def test_iou_similarity(self):
        rng = np.random.RandomState(0)
        a, b = rand_boxes(rng, 7), rand_boxes(rng, 5)
        got = D.iou_similarity(pt.to_tensor(a), pt.to_tensor(b)).numpy()
        np.testing.assert_allclose(got, np_iou(a, b), rtol=1e-5, atol=1e-6)

    def test_iou_unnormalized(self):
        rng = np.random.RandomState(1)
        a, b = rand_boxes(rng, 4), rand_boxes(rng, 4)
        got = D.iou_similarity(pt.to_tensor(a), pt.to_tensor(b),
                               box_normalized=False).numpy()
        np.testing.assert_allclose(got, np_iou(a, b, False), rtol=1e-5,
                                   atol=1e-6)

    def test_box_coder_roundtrip(self):
        rng = np.random.RandomState(2)
        priors = rand_boxes(rng, 6, 1.0)
        targets = rand_boxes(rng, 3, 1.0)
        var = [0.1, 0.1, 0.2, 0.2]
        enc = D.box_coder(pt.to_tensor(priors), var, pt.to_tensor(targets),
                          code_type="encode_center_size")
        dec = D.box_coder(pt.to_tensor(priors), var, enc,
                          code_type="decode_center_size", axis=0)
        # decoding the encoding of target t against prior m recovers t
        dec = dec.numpy()
        for i in range(3):
            for j in range(6):
                np.testing.assert_allclose(dec[i, j], targets[i], rtol=1e-4,
                                           atol=1e-4)

    def test_box_clip(self):
        rng = np.random.RandomState(3)
        boxes = rand_boxes(rng, 8, 300.0)
        im = np.array([[200.0, 150.0, 1.0]], "f4")
        got = D.box_clip(pt.to_tensor(boxes), pt.to_tensor(im)).numpy()
        assert got[..., 0].max() <= 149.0 and got[..., 1].max() <= 199.0
        assert got.min() >= 0.0

    def test_polygon_box_transform(self):
        rng = np.random.RandomState(4)
        x = rng.randn(2, 8, 3, 4).astype("f4")
        got = D.polygon_box_transform(pt.to_tensor(x)).numpy()
        # channel 0 is x-offset at every pixel: out = col_index - offset
        cols = np.tile(np.arange(4, dtype="f4"), (3, 1))
        np.testing.assert_allclose(got[0, 0], cols - x[0, 0], rtol=1e-6)
        rows = np.tile(np.arange(3, dtype="f4")[:, None], (1, 4))
        np.testing.assert_allclose(got[1, 3], rows - x[1, 3], rtol=1e-6)


class TestPriors:
    def test_prior_box_shapes_and_range(self):
        feat = pt.to_tensor(np.zeros((1, 8, 4, 6), "f4"))
        img = pt.to_tensor(np.zeros((1, 3, 64, 96), "f4"))
        boxes, var = D.prior_box(feat, img, min_sizes=[16.0],
                                 max_sizes=[32.0], aspect_ratios=[2.0],
                                 flip=True, clip=True)
        # priors: 1 (ar=1,min) + 1 (sqrt(min*max)) + 2 (ar=2, 1/2) = 4
        assert boxes.shape == [4, 6, 4, 4]
        b = boxes.numpy()
        assert b.min() >= 0.0 and b.max() <= 1.0
        assert var.shape == [4, 6, 4, 4]
        np.testing.assert_allclose(var.numpy()[0, 0, 0], [0.1, 0.1, 0.2,
                                                          0.2], rtol=1e-6)
        # centers step across the image uniformly
        cx = (b[..., 0] + b[..., 2]) / 2
        np.testing.assert_allclose(cx[0, 1, 0] - cx[0, 0, 0], 16.0 / 96,
                                   rtol=1e-4)

    def test_density_prior_box(self):
        feat = pt.to_tensor(np.zeros((1, 8, 3, 3), "f4"))
        img = pt.to_tensor(np.zeros((1, 3, 48, 48), "f4"))
        boxes, var = D.density_prior_box(feat, img, densities=[2],
                                         fixed_sizes=[8.0],
                                         fixed_ratios=[1.0],
                                         flatten_to_2d=True)
        assert boxes.shape == [3 * 3 * 4, 4]

    def test_anchor_generator(self):
        feat = pt.to_tensor(np.zeros((1, 8, 5, 5), "f4"))
        anchors, var = D.anchor_generator(feat, anchor_sizes=[64.0],
                                          aspect_ratios=[1.0],
                                          stride=[16.0, 16.0])
        assert anchors.shape == [5, 5, 1, 4]
        a = anchors.numpy()[2, 2, 0]
        # centered at (2.5*16) with size 64
        np.testing.assert_allclose((a[0] + a[2]) / 2, 40.0, atol=0.5)
        np.testing.assert_allclose(a[2] - a[0] + 1, 64.0, atol=1.0)


class TestYolo:
    def test_yolo_box_decode(self):
        rng = np.random.RandomState(5)
        n, na, c, h, w = 2, 2, 3, 4, 4
        x = rng.randn(n, na * (5 + c), h, w).astype("f4")
        img = np.array([[128, 128], [64, 96]], "i4")
        anchors = [10, 14, 23, 27]
        boxes, scores = D.yolo_box(pt.to_tensor(x), pt.to_tensor(img),
                                   anchors, c, 0.01, 32)
        assert boxes.shape == [n, h * w * na, 4]
        assert scores.shape == [n, h * w * na, c]
        # manual decode of one cell
        x5 = x.reshape(n, na, 5 + c, h, w)
        i, a, gy, gx = 0, 1, 1, 2
        sig = lambda v: 1 / (1 + np.exp(-v))
        bx = (gx + sig(x5[i, a, 0, gy, gx])) / w * 128
        by = (gy + sig(x5[i, a, 1, gy, gx])) / h * 128
        bw = np.exp(x5[i, a, 2, gy, gx]) * anchors[2] / (32 * h) * 128
        bh = np.exp(x5[i, a, 3, gy, gx]) * anchors[3] / (32 * h) * 128
        conf = sig(x5[i, a, 4, gy, gx])
        exp = np.array([max(bx - bw / 2, 0), max(by - bh / 2, 0),
                        min(bx + bw / 2, 127), min(by + bh / 2, 127)])
        flat = (gy * w + gx) * na + a
        got = boxes.numpy()[i, flat]
        if conf > 0.01:
            np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-3)
            np.testing.assert_allclose(
                scores.numpy()[i, flat],
                sig(x5[i, a, 5:, gy, gx]) * conf, rtol=1e-4, atol=1e-5)

    def test_yolov3_loss_runs_and_grads(self):
        rng = np.random.RandomState(6)
        n, nb, c, h, w = 2, 3, 4, 4, 4
        anchors = [10, 14, 23, 27, 37, 58]
        mask = [0, 1]
        x = pt.to_tensor(rng.randn(n, 2 * (5 + c), h, w).astype("f4"))
        x.stop_gradient = False
        gt = rng.rand(n, nb, 4).astype("f4") * 0.5 + 0.25
        gt[:, :, 2:] *= 0.3
        gt[1, 2] = 0  # padded slot
        lbl = rng.randint(0, c, (n, nb)).astype("i4")
        loss = D.yolov3_loss(x, pt.to_tensor(gt), pt.to_tensor(lbl),
                             anchors, mask, c, 0.7, 32)
        assert loss.shape == [n]
        total = loss.sum()
        total.backward()
        g = np.asarray(x.grad)
        assert np.isfinite(g).all() and np.abs(g).sum() > 0
        assert np.isfinite(loss.numpy()).all()

    def test_yolov3_loss_padded_slot_ignored(self):
        rng = np.random.RandomState(7)
        n, c, h, w = 1, 3, 4, 4
        anchors = [10, 14, 23, 27]
        x = rng.randn(n, 2 * (5 + c), h, w).astype("f4")
        gt1 = np.zeros((n, 2, 4), "f4")
        gt1[0, 0] = [0.5, 0.5, 0.2, 0.2]
        lbl1 = np.zeros((n, 2), "i4")
        gt2 = gt1[:, :1]
        lbl2 = lbl1[:, :1]
        l1 = D.yolov3_loss(pt.to_tensor(x), pt.to_tensor(gt1),
                           pt.to_tensor(lbl1), anchors, [0, 1], c, 0.7, 32)
        l2 = D.yolov3_loss(pt.to_tensor(x), pt.to_tensor(gt2),
                           pt.to_tensor(lbl2), anchors, [0, 1], c, 0.7, 32)
        np.testing.assert_allclose(l1.numpy(), l2.numpy(), rtol=1e-5)


class TestFocal:
    def test_sigmoid_focal_loss(self):
        rng = np.random.RandomState(8)
        n, c = 6, 5
        x = rng.randn(n, c).astype("f4")
        lbl = rng.randint(0, c + 1, (n, 1)).astype("i4")
        fg = np.array([3], "i4")
        got = D.sigmoid_focal_loss(pt.to_tensor(x), pt.to_tensor(lbl),
                                   pt.to_tensor(fg), gamma=2.0,
                                   alpha=0.25).numpy()
        sig = 1 / (1 + np.exp(-x))
        exp = np.zeros_like(x)
        for i in range(n):
            for j in range(c):
                t = 1.0 if lbl[i, 0] == j + 1 else 0.0
                p = sig[i, j]
                pt_ = t * p + (1 - t) * (1 - p)
                a_t = t * 0.25 + (1 - t) * 0.75
                ce = -(t * np.log(p) + (1 - t) * np.log(1 - p))
                exp[i, j] = a_t * (1 - pt_) ** 2 * ce / 3.0
        np.testing.assert_allclose(got, exp, rtol=1e-4, atol=1e-5)


class TestMatching:
    def test_bipartite_match_greedy(self):
        dist = np.array([[[0.9, 0.1, 0.3],
                          [0.8, 0.7, 0.2]]], "f4")
        mi, md = D.bipartite_match(pt.to_tensor(dist))
        # greedy: (0,0)=0.9 first, then row1 best remaining col=1 (0.7)
        np.testing.assert_array_equal(mi.numpy()[0], [0, 1, -1])
        np.testing.assert_allclose(md.numpy()[0], [0.9, 0.7, 0.0])

    def test_bipartite_per_prediction(self):
        dist = np.array([[[0.9, 0.6, 0.3],
                          [0.8, 0.7, 0.2]]], "f4")
        mi, md = D.bipartite_match(pt.to_tensor(dist),
                                   match_type="per_prediction",
                                   dist_threshold=0.5)
        # col2 best row is 0 with 0.3 < 0.5 → stays -1; col1 gets row 1
        assert mi.numpy()[0, 0] == 0 and mi.numpy()[0, 1] == 1
        assert mi.numpy()[0, 2] == -1

    def test_bipartite_zero_threshold_respected(self):
        """Regression (review r3): dist_threshold=0.0 must not silently
        become 0.5."""
        dist = np.array([[[0.9, 0.6, 0.3],
                          [0.8, 0.7, 0.2]]], "f4")
        mi, md = D.bipartite_match(pt.to_tensor(dist),
                                   match_type="per_prediction",
                                   dist_threshold=0.0)
        # col2 best row 0 at 0.3 > 0.0 → matched now
        assert mi.numpy()[0, 2] == 0

    def test_target_assign(self):
        inp = np.arange(24, dtype="f4").reshape(1, 6, 4)
        match = np.array([[2, -1, 0]], "i4")
        out, wt = D.target_assign(pt.to_tensor(inp), pt.to_tensor(match),
                                  mismatch_value=0)
        np.testing.assert_allclose(out.numpy()[0, 0], inp[0, 2])
        np.testing.assert_allclose(out.numpy()[0, 1], np.zeros(4))
        np.testing.assert_allclose(wt.numpy()[0, :, 0], [1, 0, 1])


class TestNMS:
    def test_nms_suppression(self):
        boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11], [50, 50, 60, 60],
                           [0, 0, 0, 0]]], "f4")
        scores = np.zeros((1, 2, 4), "f4")
        scores[0, 1] = [0.9, 0.8, 0.7, 0.0]  # class 1
        out, num = D.multiclass_nms(pt.to_tensor(boxes),
                                    pt.to_tensor(scores),
                                    score_threshold=0.1, nms_top_k=4,
                                    keep_top_k=3, nms_threshold=0.5,
                                    background_label=0)
        o, n = out.numpy()[0], int(num.numpy()[0])
        assert n == 2  # box1 suppressed by box0, zero-box below threshold
        assert o[0, 0] == 1 and abs(o[0, 1] - 0.9) < 1e-6
        np.testing.assert_allclose(o[0, 2:], [0, 0, 10, 10])
        np.testing.assert_allclose(o[1, 2:], [50, 50, 60, 60])
        assert o[2, 0] == -1  # sentinel

    def test_multiclass(self):
        boxes = np.array([[[0, 0, 10, 10], [20, 20, 30, 30]]], "f4")
        scores = np.zeros((1, 3, 2), "f4")
        scores[0, 1] = [0.9, 0.2]
        scores[0, 2] = [0.1, 0.8]
        out, num = D.multiclass_nms(pt.to_tensor(boxes),
                                    pt.to_tensor(scores), 0.15, 2, 4, 0.5,
                                    background_label=0)
        assert int(num.numpy()[0]) == 3
        labels = sorted(out.numpy()[0, :3, 0].tolist())
        assert labels == [1.0, 1.0, 2.0]

    def test_detection_output_runs(self):
        rng = np.random.RandomState(9)
        m = 8
        priors = np.sort(rng.rand(m, 4).astype("f4"), axis=-1)
        pvar = np.tile(np.array([0.1, 0.1, 0.2, 0.2], "f4"), (m, 1))
        loc = rng.randn(2, m, 4).astype("f4") * 0.1
        conf = rng.randn(2, m, 3).astype("f4")
        out, num = D.detection_output(pt.to_tensor(loc),
                                      pt.to_tensor(conf),
                                      pt.to_tensor(priors),
                                      pt.to_tensor(pvar),
                                      keep_top_k=5)
        assert out.shape == [2, 5, 6]
        assert np.isfinite(out.numpy()).all()


class TestSSDLoss:
    def test_ssd_loss_runs_and_positive(self):
        rng = np.random.RandomState(10)
        b, m, g, c = 2, 12, 3, 4
        priors = np.sort(rng.rand(m, 4).astype("f4") * 0.8, axis=-1)
        priors[:, 2:] = priors[:, :2] + 0.2
        loc = pt.to_tensor(rng.randn(b, m, 4).astype("f4") * 0.1)
        conf = pt.to_tensor(rng.randn(b, m, c).astype("f4"))
        loc.stop_gradient = False
        conf.stop_gradient = False
        gt = np.zeros((b, g, 4), "f4")
        gt[:, :2] = np.sort(rng.rand(b, 2, 4) * 0.8, axis=-1)
        gt[:, :2, 2:] = gt[:, :2, :2] + 0.25
        lbl = rng.randint(1, c, (b, g)).astype("i4")
        loss = D.ssd_loss(loc, conf, pt.to_tensor(gt), pt.to_tensor(lbl),
                          pt.to_tensor(priors))
        assert loss.shape == [b, m]
        s = loss.sum()
        assert float(s.numpy()) > 0
        s.backward()
        assert np.isfinite(np.asarray(conf.grad)).all()


class TestRoI:
    def test_roi_align_center_value(self):
        # constant image → every pooled value equals the constant
        x = np.full((1, 2, 8, 8), 3.0, "f4")
        rois = np.array([[0.0, 0.0, 7.0, 7.0]], "f4")
        out = D.roi_align(pt.to_tensor(x), pt.to_tensor(rois), 2, 2, 1.0)
        assert out.shape == [1, 2, 2, 2]
        np.testing.assert_allclose(out.numpy(), 3.0, rtol=1e-5)

    def test_roi_align_gradient(self):
        rng = np.random.RandomState(11)
        x = pt.to_tensor(rng.rand(1, 1, 6, 6).astype("f4"))
        x.stop_gradient = False
        rois = pt.to_tensor(np.array([[1.0, 1.0, 4.0, 4.0]], "f4"))
        out = D.roi_align(x, rois, 2, 2, 1.0, sampling_ratio=2)
        out.sum().backward()
        assert np.abs(np.asarray(x.grad)).sum() > 0

    def test_roi_pool_max(self):
        x = np.arange(16, dtype="f4").reshape(1, 1, 4, 4)
        rois = np.array([[0.0, 0.0, 3.0, 3.0]], "f4")
        out = D.roi_pool(pt.to_tensor(x), pt.to_tensor(rois), 2, 2, 1.0)
        np.testing.assert_allclose(out.numpy()[0, 0],
                                   [[5.0, 7.0], [13.0, 15.0]])


class TestProposals:
    def test_generate_proposals_shapes(self):
        rng = np.random.RandomState(12)
        n, a, h, w = 1, 3, 4, 4
        scores = rng.rand(n, a, h, w).astype("f4")
        deltas = rng.randn(n, 4 * a, h, w).astype("f4") * 0.1
        im_info = np.array([[64.0, 64.0, 1.0]], "f4")
        feat = pt.to_tensor(np.zeros((n, 8, h, w), "f4"))
        anchors, var = D.anchor_generator(feat, anchor_sizes=[16.0, 32.0,
                                                              64.0],
                                          aspect_ratios=[1.0],
                                          stride=[16.0, 16.0])
        props, sc = D.generate_proposals(pt.to_tensor(scores),
                                         pt.to_tensor(deltas),
                                         pt.to_tensor(im_info), anchors,
                                         var, pre_nms_top_n=20,
                                         post_nms_top_n=8, min_size=1.0)
        assert props.shape == [n, 8, 4]
        p = props.numpy()
        assert p.min() >= 0.0 and p.max() <= 63.0

    def test_distribute_and_collect_fpn(self):
        rng = np.random.RandomState(13)
        rois = rand_boxes(rng, 10, 200.0)
        outs = D.distribute_fpn_proposals(pt.to_tensor(rois), 2, 5, 4, 224)
        assert len(outs) == 2 * 4 + 1
        lvl_rois = [outs[2 * i] for i in range(4)]
        masks = [outs[2 * i + 1] for i in range(4)]
        total = sum(m.numpy().sum() for m in masks)
        assert total == 10
        scores = [pt.to_tensor(rng.rand(10).astype("f4")) for _ in range(4)]
        merged, ms = D.collect_fpn_proposals(lvl_rois, scores, 2, 5, 6)
        assert merged.shape == [6, 4]


class TestJit:
    def test_yolo_pipeline_under_jit(self):
        """SSD/YOLO loss pipelines compile under jit (VERDICT #3 done
        criterion)."""
        from paddle_tpu import jit
        rng = np.random.RandomState(14)
        n, nb, c, h, w = 2, 3, 4, 4, 4
        anchors = [10, 14, 23, 27]

        def step(x, gt, lbl):
            return D.yolov3_loss(x, gt, lbl, anchors, [0, 1], c, 0.7,
                                 32).sum()

        fn = jit.to_static(step)
        x = pt.to_tensor(rng.randn(n, 2 * (5 + c), h, w).astype("f4"))
        gt = pt.to_tensor((rng.rand(n, nb, 4) * 0.4 + 0.2).astype("f4"))
        lbl = pt.to_tensor(rng.randint(0, c, (n, nb)).astype("i4"))
        eager = step(x, gt, lbl)
        jitted = fn(x, gt, lbl)
        np.testing.assert_allclose(eager.numpy(), jitted.numpy(),
                                   rtol=1e-4, atol=1e-5)

    def test_nms_under_jit(self):
        from paddle_tpu import jit
        rng = np.random.RandomState(15)
        boxes = pt.to_tensor(rand_boxes(rng, 16, 50.0)[None])
        scores = pt.to_tensor(rng.rand(1, 3, 16).astype("f4"))

        def f(b, s):
            out, num = D.multiclass_nms(b, s, 0.2, 8, 5, 0.4,
                                        background_label=0)
            return out, num

        fn = jit.to_static(f)
        o1, n1 = f(boxes, scores)
        o2, n2 = fn(boxes, scores)
        np.testing.assert_allclose(o1.numpy(), o2.numpy(), rtol=1e-5)
        assert int(n1.numpy()[0]) == int(n2.numpy()[0])
