"""Model zoo: LeNet converges on synthetic MNIST (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt, jit
from paddle_tpu.models import LeNet


def synthetic_mnist(n=256, seed=0):
    """Class-separable synthetic digits: class k gets a bright kxk block."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 28, 28).astype("f4") * 0.1
    y = rng.randint(0, 10, size=(n,))
    for i in range(n):
        k = y[i]
        r, c = divmod(k, 4)
        x[i, 0, 3 + r * 8:9 + r * 8, 3 + c * 6:9 + c * 6] += 1.0
    return x, y.astype("i4")


def test_lenet_converges():
    pt.seed(0)
    model = LeNet()
    o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
    x, y = synthetic_mnist(256)

    def step(xb, yb):
        logits = model(xb)
        loss = pt.nn.functional.cross_entropy(logits, yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    first = None
    for epoch in range(6):
        for i in range(0, 256, 64):
            loss = fn(pt.to_tensor(x[i:i + 64]), pt.to_tensor(y[i:i + 64]))
    first = first or float(loss.numpy())
    # accuracy after training
    model.eval()
    logits = model(pt.to_tensor(x))
    acc = float((logits.argmax(-1).numpy() == y).mean())
    assert acc > 0.9, f"LeNet failed to fit synthetic MNIST: acc={acc}"


@pytest.mark.slow
def test_resnet50_forward_backward():
    from paddle_tpu.models.resnet import resnet50, resnet18
    m = resnet18(num_classes=10)
    x = pt.to_tensor(np.random.randn(2, 3, 32, 32).astype("f4"))
    y = pt.to_tensor(np.array([1, 2]))
    loss = pt.nn.functional.cross_entropy(m(x), y)
    loss.backward()
    grads = [p for p in m.parameters() if p.grad is not None]
    assert len(grads) == len([p for p in m.parameters()
                              if not p.stop_gradient])
    m50 = resnet50(num_classes=10)
    assert m50(x).shape == [2, 10]
    # param count sanity: resnet50 ~25.5M for 1000 classes
    n = sum(p.size for p in resnet50(num_classes=1000).parameters())
    assert 25_000_000 < n < 26_000_000


@pytest.mark.slow
def test_bert_tiny_forward_backward():
    from paddle_tpu.models.bert import BertConfig, BertForPretraining
    cfg = BertConfig.tiny()
    m = BertForPretraining(cfg)
    b, s = 2, 16
    ids = pt.to_tensor(np.random.randint(0, cfg.vocab_size, (b, s)))
    tt = pt.to_tensor(np.zeros((b, s), "i4"))
    mask = pt.to_tensor(np.ones((b, s), "i4"))
    mlm_labels = pt.to_tensor(np.where(np.random.rand(b, s) < 0.15,
                                       np.random.randint(0, cfg.vocab_size,
                                                         (b, s)), -1))
    nsp_labels = pt.to_tensor(np.array([0, 1]))
    logits, nsp = m(ids, tt, mask)
    assert logits.shape == [b, s, cfg.vocab_size]
    loss = m.loss(logits, nsp, mlm_labels, nsp_labels)
    loss.backward()
    assert m.bert.embeddings.word_embeddings.weight.grad is not None


@pytest.mark.slow
def test_transformer_seq2seq():
    from paddle_tpu.models.transformer import Transformer
    m = Transformer(src_vocab_size=100, tgt_vocab_size=100, d_model=32,
                    num_heads=4, num_encoder_layers=2, num_decoder_layers=2,
                    d_ff=64, max_length=32)
    src = pt.to_tensor(np.random.randint(1, 100, (2, 10)))
    tgt = pt.to_tensor(np.random.randint(1, 100, (2, 8)))
    mask = pt.to_tensor(np.ones((2, 10), "i4"))
    logits = m(src, tgt, mask)
    assert logits.shape == [2, 8, 100]
    labels = pt.to_tensor(np.random.randint(1, 100, (2, 8)))
    loss = m.loss(logits, labels)
    loss.backward()
    assert m.src_embed.weight.grad is not None


def test_ctr_models():
    from paddle_tpu.models.ctr import WideDeep, DeepFM
    ids = pt.to_tensor(np.random.randint(0, 1000, (4, 26)))
    dense = pt.to_tensor(np.random.rand(4, 13).astype("f4"))
    label = pt.to_tensor(np.array([0, 1, 1, 0]))
    for cls in (WideDeep, DeepFM):
        m = cls(sparse_feature_number=1000)
        logit = m(ids, dense)
        assert logit.shape == [4, 1]
        loss = m.loss(logit, label)
        loss.backward()


def test_word2vec():
    from paddle_tpu.models.word2vec import SkipGram
    m = SkipGram(vocab_size=100, embedding_dim=16)
    center = pt.to_tensor(np.random.randint(0, 100, (8,)))
    context = pt.to_tensor(np.random.randint(0, 100, (8,)))
    loss = m.train_batch_loss(center, context)
    loss.backward()
    assert m.emb_in.weight.grad is not None


@pytest.mark.slow
def test_vgg_mobilenet_smoke():
    from paddle_tpu.models.vgg import vgg16
    from paddle_tpu.models.mobilenet import MobileNetV1, MobileNetV2
    x = pt.to_tensor(np.random.randn(1, 3, 64, 64).astype("f4"))
    assert vgg16(num_classes=5, image_size=64)(x).shape == [1, 5]
    assert MobileNetV1(num_classes=5)(x).shape == [1, 5]
    assert MobileNetV2(num_classes=5)(x).shape == [1, 5]


@pytest.mark.slow
def test_resnet_nhwc_matches_nchw():
    """data_format='NHWC' plumbs through stem/blocks/pools and matches
    the NCHW model in eval mode (weights stay OIHW — layout-independent
    state dicts)."""
    import numpy as np
    import paddle_tpu as pt
    from paddle_tpu.models.resnet import resnet18

    np.random.seed(0)
    x = np.random.rand(2, 3, 32, 32).astype("f4")
    xh = np.transpose(x, (0, 2, 3, 1)).copy()
    pt.seed(0)
    m_nchw = resnet18(num_classes=10)
    pt.seed(0)
    m_nhwc = resnet18(num_classes=10, data_format="NHWC")
    m_nchw.eval()
    m_nhwc.eval()
    np.testing.assert_allclose(
        m_nhwc(pt.to_tensor(xh)).numpy(),
        m_nchw(pt.to_tensor(x)).numpy(), atol=1e-4)
    # identical state dicts regardless of layout
    for (k1, v1), (k2, v2) in zip(sorted(m_nchw.state_dict().items()),
                                  sorted(m_nhwc.state_dict().items())):
        assert k1 == k2 and v1.shape == v2.shape


@pytest.mark.slow
def test_se_resnext50_forward_and_grads():
    """SE-ResNeXt (grouped convs + SE gates) trains a step; the SE gate
    actually modulates (zeroing excite bias shifts outputs)."""
    from paddle_tpu.models.se_resnext import se_resnext50
    pt.seed(0)
    m = se_resnext50(num_classes=10)
    x = pt.to_tensor(np.random.RandomState(0).rand(2, 3, 48, 48)
                     .astype("f4"))
    y = pt.to_tensor(np.array([1, 7], "i4"))
    logits = m(x)
    assert tuple(logits.shape) == (2, 10)
    loss = nn.functional.cross_entropy(logits, y)
    loss.backward()
    o = opt.Momentum(learning_rate=0.05, momentum=0.9,
                     parameters=m.parameters())
    o.step()
    o.clear_grad()
    loss2 = nn.functional.cross_entropy(m(x), y)
    assert float(loss2.numpy()) < float(loss.numpy())
    # a grouped conv exists with cardinality 32
    from paddle_tpu.models.se_resnext import SEResNeXtBottleneck
    blk = next(l for l in m.sublayers()
               if isinstance(l, SEResNeXtBottleneck))
    assert blk.conv1._attrs["groups"] == 32


@pytest.mark.slow
def test_resnet_nhwc_pallas_bn_matches_nchw():
    """NHWC resnet == NCHW resnet on transposed input (same seed, same
    params): the layout knob changes memory order only. Also asserts
    the fused Pallas BN path (interpret mode) agrees end-to-end.
    Tolerance is loose (~1e-2): conv reduction order differs per
    layout and 18 BN divisions amplify it."""
    import numpy as np
    from paddle_tpu.models.resnet import resnet18
    from paddle_tpu.ops import pallas as P

    x = np.random.RandomState(0).rand(2, 3, 32, 32).astype("f4")

    def logits(fmt, pallas_bn=False):
        P.configure(batch_norm=pallas_bn)
        try:
            pt.seed(11)
            m = resnet18(num_classes=8, data_format=fmt)
            xin = x if fmt == "NCHW" else x.transpose(0, 2, 3, 1)
            return m(pt.to_tensor(xin)).numpy()
        finally:
            P.configure(batch_norm=None)

    a = logits("NCHW")
    b = logits("NHWC")
    np.testing.assert_allclose(b, a, rtol=3e-2, atol=3e-3)
    c = logits("NHWC", pallas_bn=True)
    np.testing.assert_allclose(c, a, rtol=3e-2, atol=3e-3)
