"""Model zoo: LeNet converges on synthetic MNIST (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, optimizer as opt, jit
from paddle_tpu.models import LeNet


def synthetic_mnist(n=256, seed=0):
    """Class-separable synthetic digits: class k gets a bright kxk block."""
    rng = np.random.RandomState(seed)
    x = rng.rand(n, 1, 28, 28).astype("f4") * 0.1
    y = rng.randint(0, 10, size=(n,))
    for i in range(n):
        k = y[i]
        r, c = divmod(k, 4)
        x[i, 0, 3 + r * 8:9 + r * 8, 3 + c * 6:9 + c * 6] += 1.0
    return x, y.astype("i4")


def test_lenet_converges():
    pt.seed(0)
    model = LeNet()
    o = opt.Adam(learning_rate=1e-3, parameters=model.parameters())
    x, y = synthetic_mnist(256)

    def step(xb, yb):
        logits = model(xb)
        loss = pt.nn.functional.cross_entropy(logits, yb)
        loss.backward()
        o.step()
        o.clear_grad()
        return loss

    fn = jit.to_static(step, models=[model], optimizers=[o])
    first = None
    for epoch in range(6):
        for i in range(0, 256, 64):
            loss = fn(pt.to_tensor(x[i:i + 64]), pt.to_tensor(y[i:i + 64]))
    first = first or float(loss.numpy())
    # accuracy after training
    model.eval()
    logits = model(pt.to_tensor(x))
    acc = float((logits.argmax(-1).numpy() == y).mean())
    assert acc > 0.9, f"LeNet failed to fit synthetic MNIST: acc={acc}"
