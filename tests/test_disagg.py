"""Disaggregated serving (PR 20): the export/import segment transport's
exact byte accounting, the PrefixCache's ref-counted LRU discipline,
bit-parity of the (prefill pool → priced handoff → decode pool)
topology against the single-engine oracle — greedy, sampled, prefix
hits, and mid-stream decode-replica drain — plus the reqtrace stage
waterfall (handoff_ms / prefix_lookup_ms / prefix_hit) reconciling.
All CPU, all fast."""
import numpy as np
import pytest

from paddle_tpu import monitor, serving
from paddle_tpu.serving import kv_cache, prefix_cache, reqtrace
from paddle_tpu.serving import metrics as smetrics
from paddle_tpu.serving.disagg import DisaggServer
from paddle_tpu.serving.generate import GenerateEngine
from paddle_tpu.serving.kv_cache import KVCachePool, bytes_per_token
from paddle_tpu.serving.prefix_cache import PrefixCache, prompt_key


@pytest.fixture(autouse=True)
def _clean():
    monitor.disable(flush_counters=False)
    reqtrace.reset()
    yield
    monitor.disable(flush_counters=False)
    reqtrace.reset()


@pytest.fixture(scope="module")
def model():
    return serving.demo_model(vocab=32, dim=16, heads=2, layers=2,
                              max_len=64, seed=1)


SPEC = {"k0": ((2, 4), "float32"), "v0": ((2, 4), "float32")}


def _segment(pad, length=None, fill=None):
    """A well-formed transport segment for SPEC."""
    length = pad if length is None else length
    rng = np.random.RandomState(0 if fill is None else fill)
    leaves = {name: rng.rand(pad, *tail).astype(np.float32)
              for name, (tail, _dt) in SPEC.items()}
    return {"length": length, "pad": pad,
            "bytes": sum(a.nbytes for a in leaves.values()),
            "leaves": leaves}


# ---------------------------------------------------------------------------
# export_slot / import_slot: the one segment transport (satellite c)


def test_export_import_roundtrip_exact_bytes():
    src = KVCachePool(SPEC, slots=2, page=32, factor=2.0, max_len=64)
    s = src.alloc()
    # land known content through the official import path, then read it
    # back out: the transport must be lossless and priced to the byte
    seg_in = _segment(16, length=10, fill=7)
    src.import_slot(s, seg_in)
    assert src.length(s) == 10

    before = src.allocated_bytes()
    seg = src.export_slot(s, pad_to=32)
    assert src.allocated_bytes() == before       # export never resizes
    assert seg["length"] == 10 and seg["pad"] == 32
    assert seg["bytes"] == bytes_per_token(SPEC) * 32
    for name, (tail, _dt) in SPEC.items():
        assert seg["leaves"][name].shape == (32, *tail)
        np.testing.assert_array_equal(seg["leaves"][name][:16],
                                      seg_in["leaves"][name])

    dst = KVCachePool(SPEC, slots=2, page=32, factor=2.0, max_len=64)
    d = dst.alloc()
    before = dst.allocated_bytes()
    got = dst.import_slot(d, seg)
    assert got == seg["bytes"]
    assert dst.allocated_bytes() == before       # import never resizes
    assert dst.length(d) == 10                   # ledger through note_length


def test_export_import_error_cases():
    pool = KVCachePool(SPEC, slots=1, page=16, factor=2.0, max_len=64)
    s = pool.alloc()
    pool.note_length(s, 12)
    with pytest.raises(ValueError, match="pad 8 < live length 12"):
        pool.export_slot(s, pad_to=8)
    with pytest.raises(ValueError, match="exceeds arena capacity"):
        pool.export_slot(s, pad_to=128)

    with pytest.raises(ValueError, match="exceeds arena capacity"):
        pool.import_slot(s, _segment(128))
    bad = _segment(16)
    bad["leaves"] = {"k0": bad["leaves"]["k0"]}         # missing v0
    with pytest.raises(ValueError, match="leaves"):
        pool.import_slot(s, bad)
    short = _segment(16)
    short["leaves"]["k0"] = short["leaves"]["k0"][:8]   # 8 rows, pad 16
    with pytest.raises(AssertionError, match="byte accounting"):
        pool.import_slot(s, short)


# ---------------------------------------------------------------------------
# PrefixCache: ref-counted LRU under a byte budget


def _seg_bytes(pad):
    return bytes_per_token(SPEC) * pad


def test_prefix_cache_hit_miss_and_refcount():
    cache = PrefixCache(SPEC, budget_bytes=_seg_bytes(16) * 4)
    prompt = [1, 2, 3]
    key, entry = cache.lookup(prompt)
    assert entry is None and key == prompt_key(prompt)
    assert cache.insert(key, _segment(16, length=3),
                        np.zeros(32, np.float32))
    key2, entry = cache.lookup(prompt)
    assert key2 == key and entry is not None
    assert entry.refs == 1 and entry.prompt_len == 3
    cache.release(key)
    assert cache.stats()["pinned"] == 0
    assert cache.hit_rate() == 0.5              # 1 hit / 2 lookups


def test_prefix_cache_key_is_length_salted():
    # a prompt that is a strict prefix of another must key differently
    assert prompt_key([1, 2, 3]) != prompt_key([1, 2, 3, 4])
    assert prompt_key([1, 2, 3]) == prompt_key(np.asarray([1, 2, 3]))


def test_prefix_cache_insert_asserts_spec_bytes():
    cache = PrefixCache(SPEC, budget_bytes=1 << 20)
    seg = _segment(16)
    seg["leaves"]["k0"] = seg["leaves"]["k0"][:8]
    with pytest.raises(AssertionError, match="spec-priced"):
        cache.insert("k", seg, np.zeros(32, np.float32))
    seg2 = _segment(16)
    seg2["bytes"] += 1
    with pytest.raises(AssertionError, match="self-reported"):
        cache.insert("k", seg2, np.zeros(32, np.float32))


def test_prefix_cache_lru_eviction_and_pinning():
    logits = np.zeros(32, np.float32)
    cache = PrefixCache(SPEC, budget_bytes=_seg_bytes(16) * 2)
    assert cache.insert("a", _segment(16), logits)
    assert cache.insert("b", _segment(16), logits)
    # LRU: "a" is oldest → evicted to make room for "c"
    assert cache.insert("c", _segment(16), logits)
    assert cache.stats()["evictions"] == 1
    assert "a" not in cache._entries
    assert "b" in cache._entries and "c" in cache._entries

    # pin "b" (a lookup takes a ref): "c" becomes the LRU victim
    cache._entries["b"].refs += 1
    assert cache.insert("d", _segment(16), logits)
    assert "b" in cache._entries and "c" not in cache._entries

    # everything pinned → insert refused, budget never broken
    cache._entries["d"].refs += 1
    assert not cache.insert("e", _segment(16), logits)
    assert cache.stats()["refused"] == 1
    assert cache.bytes() <= cache.budget_bytes


def test_prefix_cache_refuses_oversized_segment():
    cache = PrefixCache(SPEC, budget_bytes=_seg_bytes(16) - 1)
    assert not cache.insert("a", _segment(16), np.zeros(32, np.float32))
    assert len(cache) == 0


# ---------------------------------------------------------------------------
# topology bit-parity vs the single-engine oracle


def _oracle(model, jobs, **eng_kwargs):
    eng = GenerateEngine(model, start=False, **eng_kwargs)
    eng.warmup()
    futs = [eng.submit(p, max_new_tokens=n, sampling=sp, seed=seed)
            for p, n, sp, seed in jobs]
    for _ in range(2000):
        eng.tick()
        if all(f.done() for f in futs):
            break
    out = [[int(t) for t in f.result(timeout=5)] for f in futs]
    eng.close(drain=False)
    return out


def _disagg_execs(srv):
    return tuple(r.engine.executables()
                 for pool in (srv.prefill_pool, srv.decode_pool)
                 for r in pool._replicas)


def test_disagg_parity_greedy_and_sampled(model):
    monitor.enable()
    smetrics.reset_windows()
    sampled = {"temperature": 0.9, "top_k": 8}
    jobs = [([1, 2, 3], 8, None, None),
            ([5] * 20, 8, None, None),
            ([1, 2, 3], 8, sampled, 101),       # repeat → prefix hit
            ([1, 2, 3], 8, sampled, 202),       # repeat, different seed
            ([9, 8, 7, 6], 8, sampled, 303)]
    want = _oracle(model, jobs, slots=4, page=16, factor=2.0,
                   max_len=64, prompt_buckets=(8, 32))

    srv = DisaggServer(model, prefill_replicas=1, decode_replicas=1,
                       slots=4, page=16, factor=2.0, max_len=64,
                       prompt_buckets=(8, 32), supervise=False)
    srv.warmup()
    ex0 = _disagg_execs(srv)
    futs = [srv.submit(p, max_new_tokens=n, sampling=sp, seed=seed)
            for p, n, sp, seed in jobs]
    got = [[int(t) for t in f.result(timeout=30)] for f in futs]
    assert got == want                          # byte-for-byte streams

    # zero post-warmup compiles in BOTH pools — hits and handoffs land
    # on already-minted executables only
    assert _disagg_execs(srv) == ex0

    st = srv.stats()
    # repeats of [1,2,3] hit; each distinct prompt prefilled exactly once
    assert st["prefix"]["hits"] == 2
    assert st["prefix"]["misses"] == 3
    assert st["prefill"]["prefills"] == st["prefix"]["misses"]
    assert st["decode"]["prefills"] == 0        # decode pool never prefills
    assert st["decode"]["kv_imports"] == len(jobs)
    # every handoff priced exactly: per-token spec bytes × prompt bucket
    planned = sum(srv.planned_handoff_ms(len(p))[0]
                  for p, _n, _sp, _s in jobs)
    assert st["handoffs"] == len(jobs)
    assert st["handoff_bytes"] == planned
    srv.close()


def test_disagg_drain_midstream_parity(model):
    monitor.enable()
    smetrics.reset_windows()
    jobs = [([1, 2, 3], 40, {"temperature": 1.0, "top_k": 8}, 77),
            ([4, 5], 40, None, None)]
    want = _oracle(model, jobs, slots=4, page=16, factor=2.0,
                   max_len=64, prompt_buckets=(8, 32))

    srv = DisaggServer(model, prefill_replicas=1, decode_replicas=2,
                       slots=4, page=16, factor=2.0, max_len=64,
                       prompt_buckets=(8, 32), supervise=False)
    srv.warmup()
    futs = [srv.submit(p, max_new_tokens=n, sampling=sp, seed=seed)
            for p, n, sp, seed in jobs]
    # drain whichever decode replica seated work: its in-flight slots
    # export KV and resume mid-stream on the peer
    import time
    deadline = time.monotonic() + 10
    victim = None
    while victim is None and time.monotonic() < deadline:
        for r in srv.decode_pool._replicas:
            if r.engine.stats()["kv_imports"] > 0:
                victim = r
                break
        time.sleep(0.01)
    assert victim is not None
    srv.drain_decode_replica(victim.index, reason="test")
    got = [[int(t) for t in f.result(timeout=30)] for f in futs]
    assert got == want          # identical despite the mid-stream move
    srv.close()


def test_disagg_reqtrace_stages(model):
    monitor.enable()
    smetrics.reset_windows()
    reqtrace.reset()
    srv = DisaggServer(model, prefill_replicas=1, decode_replicas=1,
                       slots=4, page=16, factor=2.0, max_len=64,
                       prompt_buckets=(8, 32), supervise=False)
    srv.warmup()
    srv.run([1, 2, 3], max_new_tokens=6, timeout=30)   # miss
    srv.run([1, 2, 3], max_new_tokens=6, timeout=30)   # hit
    srv.close()

    recs = [r for r in reqtrace.recent() if r["outcome"] == "ok"]
    assert len(recs) == 2
    miss, hit = recs
    assert miss["prefix_hit"] is False and hit["prefix_hit"] is True
    for rec in recs:
        # the disagg stages appear and the waterfall still reconciles
        assert rec["prefix_lookup_ms"] >= 0.0
        assert rec["handoff_ms"] >= 0.0
        assert abs(rec["recon"] - 1.0) <= reqtrace.RECON_TOL
        assert rec["ttft_ms"] is not None
        assert any(h["hop"] == "handoff" for h in rec["hops"])
    assert "prefill_ms" in miss
    assert "prefill_ms" not in hit              # a hit never prefills
