"""Static Program/Executor parity with dygraph (SURVEY §4)."""
import numpy as np
import pytest

import paddle_tpu as pt
from paddle_tpu import nn, static, optimizer as opt


@pytest.fixture(autouse=True)
def _static_mode():
    static.reset_default_programs()
    pt.enable_static()
    yield
    pt.disable_static()


def test_forward_parity_with_dygraph():
    pt.seed(3)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))

    x = static.data("x", [None, 4], "float32")
    out = model(x)

    exe = static.Executor()
    xv = np.random.randn(6, 4).astype("f4")
    (res,) = exe.run(feed={"x": xv}, fetch_list=[out])

    pt.disable_static()
    ref = model(pt.to_tensor(xv)).numpy()
    np.testing.assert_allclose(res, ref, atol=1e-5)


def test_static_training_converges():
    pt.seed(0)
    model = nn.Linear(2, 1)
    x = static.data("x", [None, 2], "float32")
    y = static.data("y", [None, 1], "float32")
    pred = model(x)
    loss = (pred - y).square().mean()
    o = opt.SGD(learning_rate=0.1)
    o.minimize(loss)

    exe = static.Executor()
    rng = np.random.RandomState(0)
    w_true = np.array([[2.0], [-1.0]], "f4")
    losses = []
    for _ in range(60):
        xv = rng.randn(32, 2).astype("f4")
        yv = xv @ w_true
        (lv,) = exe.run(feed={"x": xv, "y": yv}, fetch_list=[loss])
        losses.append(float(lv))
    assert losses[-1] < 0.01
    np.testing.assert_allclose(model.weight.numpy(), w_true, atol=0.05)


def test_program_guard_isolation():
    main = static.Program()
    startup = static.Program()
    with static.program_guard(main, startup):
        x = static.data("x", [None, 3], "float32")
        y = x * 2.0
        assert y.program is main
    assert not static.default_main_program().global_block().ops


def test_executor_cache_reuse():
    model = nn.Linear(3, 3)
    x = static.data("x", [None, 3], "float32")
    out = model(x)
    exe = static.Executor()
    xv = np.random.randn(4, 3).astype("f4")
    r1 = exe.run(feed={"x": xv}, fetch_list=[out])[0]
    r2 = exe.run(feed={"x": xv}, fetch_list=[out])[0]
    np.testing.assert_allclose(r1, r2)
    assert len(exe._cache) == 1


def test_clone_for_test_drops_optimizer():
    model = nn.Linear(2, 1)
    x = static.data("x", [None, 2], "float32")
    loss = model(x).mean()
    o = opt.SGD(learning_rate=0.1)
    o.minimize(loss)
    prog = static.default_main_program()
    test_prog = prog.clone(for_test=True)
    assert prog.optimizers and not test_prog.optimizers


def test_static_aux_surface():
    """InputSpec, append_backward marking, Scope/global_scope,
    name_scope, and the Build/Execution strategy facades (reference
    static-mode aux names)."""
    from paddle_tpu import static

    spec = static.InputSpec([None, 8], "float32", name="x")
    assert spec.shape == (None, 8) and spec.name == "x"

    # static mode is already on via this file's autouse fixture
    prog = static.Program()
    with static.program_guard(prog):
        x = static.data("x", (None, 4), "float32")
        with static.name_scope("blk"):
            h = pt.fluid.layers.fc(x, size=2)
        loss = pt.fluid.layers.reduce_mean(h * h)
        grads = static.append_backward(loss)
        assert grads == []
        assert prog._loss_name == loss.name

    sc = static.global_scope()
    assert static.global_scope() is sc
    sc.vars["tmp"] = 1
    assert sc.find_var("tmp") == 1
    del sc.vars["tmp"]

    bs = static.BuildStrategy()
    es = static.ExecutionStrategy()
    assert bs is not None and es is not None


def test_create_predictor_factory(tmp_path):
    """paddle-inference-style factory: save_inference_model then
    create_predictor(Config(path)) serves the restored model."""
    import os
    from paddle_tpu import io, nn
    from paddle_tpu import inference

    pt.disable_static()  # this file's autouse fixture enables static
    pt.seed(0)
    m = nn.Sequential(nn.Linear(4, 2))
    path = os.path.join(str(tmp_path), "model")
    io.save_inference_model(path, m)

    pred = inference.create_predictor(inference.Config(path))
    xin = np.random.RandomState(0).randn(3, 4).astype("f4")
    out = pred.run(xin)
    assert np.asarray(out).shape == (3, 2)
    ref = m(pt.to_tensor(xin)).numpy()
    np.testing.assert_allclose(np.asarray(out), ref, atol=1e-5)
