"""paddle_tpu.serving (ISSUE 5): dynamic batching, SLA deadlines,
admission control, replica fan-out — plus the Predictor executable-cache
and compile_report satellites. All CPU, all fast."""
import threading
import time
import warnings

import numpy as np
import pytest
import jax
import jax.numpy as jnp

import paddle_tpu as pt
from paddle_tpu import inference, nn, serving
from paddle_tpu.io.bucketing import split_rows, unpad
from paddle_tpu.resilience import Deadline, TransientError
from paddle_tpu.serving import (DeadlineExpired, MultiDeviceEngine,
                                QueueFullError, ServingEngine)


@pytest.fixture
def mon():
    from paddle_tpu import monitor
    monitor.reset()
    monitor.enable()
    yield monitor
    monitor.disable()
    monitor.reset()


def _mlp(out_dim=4):
    pt.seed(0)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                         nn.Linear(32, out_dim))


class _TwoHead(nn.Layer):
    def __init__(self):
        super().__init__()
        self.a = nn.Linear(16, 4)
        self.b = nn.Linear(16, 2)

    def forward(self, x):
        return self.a(x), self.b(x)


def _reqs(sizes, rng=None, dim=16):
    rng = rng or np.random.RandomState(0)
    return [rng.rand(n, dim).astype("f4") for n in sizes]


# ---------------------------------------------------------------------------
# bucketing helpers (new this PR)

def test_split_rows_and_unpad():
    a = np.arange(20, dtype="f4").reshape(10, 2)
    parts = split_rows(a, [1, 3, 4])      # trailing 2 pad rows dropped
    assert [p.shape[0] for p in parts] == [1, 3, 4]
    np.testing.assert_array_equal(parts[1], a[1:4])
    np.testing.assert_array_equal(unpad(a, 7), a[:7])
    assert unpad(a, 10) is a              # no-op at exact size
    assert unpad(np.float32(3.0), 2) == np.float32(3.0)
    with pytest.raises(ValueError):
        split_rows(a, [8, 8])


# ---------------------------------------------------------------------------
# resilience.Deadline

def test_deadline_semantics():
    t = [100.0]
    d = Deadline(0.5, clock=lambda: t[0])
    assert not d.expired() and abs(d.remaining() - 0.5) < 1e-9
    t[0] = 100.6
    assert d.expired() and d.remaining() < 0
    assert Deadline.after_ms(0, clock=lambda: t[0]).expired()
    assert "expired" in repr(d)


# ---------------------------------------------------------------------------
# Predictor satellites: cache keys, warmup, bucket-aware run, report

def test_predictor_cache_shared_across_input_kinds(mon):
    p = inference.Predictor(_mlp())
    x = np.random.RandomState(0).rand(3, 16).astype("f4")
    r1 = p.run(x)                          # numpy -> compile
    r2 = p.run(pt.to_tensor(x))            # Tensor -> cache hit
    r3 = p.run(jnp.asarray(x))             # device array -> cache hit
    assert len(p._compiled) == 1
    reg = mon.registry()
    assert reg.value("inference.compile", 0) == 1
    assert reg.value("inference.cache_hit", 0) == 2
    np.testing.assert_array_equal(r1, r2)
    np.testing.assert_array_equal(r1, r3)


def test_predictor_float64_canonicalizes_to_same_entry(mon):
    p = inference.Predictor(_mlp())
    x = np.random.RandomState(0).rand(3, 16)          # float64
    p.run(x.astype("f4"))
    p.run(x)                                          # canonicalized f32
    assert len(p._compiled) == 1
    assert mon.registry().value("inference.compile", 0) == 1


def test_predictor_warmup_aot(mon):
    p = inference.Predictor(_mlp())
    keys = p.warmup([((8, 16), "float32")], [((4, 16), "float32")])
    assert len(keys) == 2 and len(p._compiled) == 2
    reg = mon.registry()
    assert reg.value("inference.aot_warmup", 0) == 2
    assert reg.value("inference.compile", 0) == 0
    p.run(np.zeros((8, 16), "f4"))        # warmed: no new compile
    assert reg.value("inference.compile", 0) == 0
    assert len(p._compiled) == 2


def test_predictor_bucket_aware_run(mon):
    p = inference.Predictor(_mlp())
    p.warmup([((8, 16), "float32")])
    x = np.random.RandomState(0).rand(5, 16).astype("f4")
    out = p.run(x, buckets=[8])
    assert out.shape == (5, 4)
    assert mon.registry().value("inference.compile", 0) == 0
    assert mon.registry().value("inference.bucket_pad", 0) == 1
    ref = inference.Predictor(_mlp()).run(np.asarray(
        np.concatenate([x, np.tile(x[-1:], (3, 1))]), "f4"))
    np.testing.assert_array_equal(out, ref[:5])


def test_compile_report_routes_through_xla(mon):
    p = inference.Predictor(_mlp())
    x = np.zeros((2, 16), "f4")
    rep = p.compile_report(x)
    assert rep.get("flops", 0) > 0
    # landed in monitor.xla under the predictor label
    assert any(lbl.startswith("predictor.") for lbl in mon.xla.labels())
    snap = mon.snapshot("xla.flops.predictor")
    assert snap


def test_compile_report_warns_once_on_empty(monkeypatch):
    import paddle_tpu.inference as inf
    p = inference.Predictor(_mlp())
    x = np.zeros((2, 16), "f4")
    monkeypatch.setattr(inf, "_COST_WARNED", False)
    from paddle_tpu.monitor import xla as mxla
    monkeypatch.setattr(mxla, "capture", lambda label, exe: {})
    with pytest.warns(RuntimeWarning, match="no cost"):
        assert p.compile_report(x) == {}
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert p.compile_report(x) == {}   # second call: silent


def test_export_and_build_share_one_body():
    # the dedup satellite: both paths go through _infer_fn and agree
    from paddle_tpu.inference import _infer_fn
    m = _mlp()
    p = inference.Predictor(m)
    x = np.random.RandomState(0).rand(2, 16).astype("f4")
    from paddle_tpu.nn.layer import state_pytree
    st = state_pytree(m.eval())
    closed = _infer_fn(m, state=st)
    open_fn = _infer_fn(m)
    np.testing.assert_array_equal(np.asarray(closed(x)),
                                  np.asarray(open_fn(st, x)))
    np.testing.assert_array_equal(np.asarray(closed(x)), p.run(x))


# ---------------------------------------------------------------------------
# ServingEngine: coalescing, bit-exactness, warmup, flush policy

def test_ragged_requests_coalesce_bit_exact(mon):
    m = _mlp()
    eng = ServingEngine(inference.Predictor(m), buckets=[8, 32],
                        max_batch=32, timeout_ms=20.0)
    eng.warmup([((16,), "float32")])
    xs = _reqs([1, 3, 7, 13])
    futs = [eng.submit(x) for x in xs]
    outs = [f.result(5) for f in futs]
    ref = inference.Predictor(m)
    for x, o in zip(xs, outs):
        assert o.shape == (x.shape[0], 4)
        np.testing.assert_array_equal(o, ref.run(x))
    st = eng.stats()
    assert st["batches"] == 1              # all four rode one flush
    assert st["coalesced_rows"] == 24 and st["padded_rows"] == 8
    eng.close()


def test_zero_compiles_after_warmup(mon):
    eng = ServingEngine(inference.Predictor(_mlp()), buckets=[8, 32],
                        max_batch=32, timeout_ms=2.0)
    warmed = eng.warmup([((16,), "float32")])
    assert warmed == 2                     # one per bucket
    reg = mon.registry()
    after_warmup = reg.value("serving.compiles", 0)
    assert after_warmup == warmed
    rng = np.random.RandomState(1)
    for sizes in ([2, 5], [8], [1, 1, 1], [13, 13], [32]):
        futs = [eng.submit(x) for x in _reqs(sizes, rng)]
        for f in futs:
            f.result(5)
    assert reg.value("serving.compiles", 0) == after_warmup
    assert eng.stats()["compiles"] == warmed
    eng.close()


def test_flush_on_max_batch_rows():
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=16,
                        timeout_ms=500.0)   # timeout too long to matter
    xs = _reqs([8, 8, 8, 8])
    t0 = time.monotonic()
    futs = [eng.submit(x) for x in xs]
    for f in futs:
        f.result(5)
    assert time.monotonic() - t0 < 2.0      # row cap, not timeout, flushed
    assert eng.stats()["batches"] == 2
    eng.close()


def test_flush_on_timeout_for_partial_batch():
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=32,
                        timeout_ms=30.0)
    f = eng.submit(_reqs([2])[0])
    out = f.result(5)                       # lone request still resolves
    assert out.shape == (2, 4)
    eng.close()


def test_multi_output_model_scatter(mon):
    m = _TwoHead().eval()
    eng = ServingEngine(inference.Predictor(m), max_batch=8,
                        timeout_ms=10.0)
    xs = _reqs([2, 3])
    futs = [eng.submit(x) for x in xs]
    ref = inference.Predictor(m)
    for x, f in zip(xs, futs):
        got = f.result(5)
        want = ref.run(x)
        assert isinstance(got, list) and len(got) == 2
        np.testing.assert_array_equal(got[0], want[0])
        np.testing.assert_array_equal(got[1], want[1])
    eng.close()


def test_signature_groups_do_not_mix():
    m = _mlp()
    eng = ServingEngine(inference.Predictor(m), max_batch=32,
                        timeout_ms=10.0)
    a = np.random.RandomState(0).rand(3, 16).astype("f4")
    b = np.random.RandomState(1).rand(2, 16).astype("f8")  # -> f4 canon
    c = np.random.RandomState(2).rand(2, 16).astype("f4")
    fa, fb, fc = eng.submit(a), eng.submit(b), eng.submit(c)
    ref = inference.Predictor(m)
    np.testing.assert_array_equal(fa.result(5), ref.run(a))
    np.testing.assert_array_equal(fb.result(5),
                                  ref.run(b.astype("f4")))
    np.testing.assert_array_equal(fc.result(5), ref.run(c))
    eng.close()


def test_run_blocking_and_context_manager():
    with ServingEngine(inference.Predictor(_mlp()), max_batch=8,
                       timeout_ms=5.0) as eng:
        out = eng.run(_reqs([3])[0], timeout=5)
        assert out.shape == (3, 4)
    with pytest.raises(RuntimeError):
        eng.submit(_reqs([1])[0])           # closed


def test_submit_validation():
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=8,
                        timeout_ms=5.0, start=False)
    with pytest.raises(ValueError):
        eng.submit()                        # no inputs
    with pytest.raises(ValueError):
        eng.submit(np.float32(1.0))         # no batch dim
    with pytest.raises(ValueError):
        eng.submit(np.zeros((0, 16), "f4"))  # empty
    with pytest.raises(ValueError):
        eng.submit(np.zeros((9, 16), "f4"))  # > max_batch
    with pytest.raises(ValueError):
        eng.submit(np.zeros((2, 16), "f4"),
                   np.zeros((3, 1), "f4"))  # inconsistent leading dims
    eng.close()


def test_close_drains_pending_requests():
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=32,
                        timeout_ms=5000.0, start=False)
    futs = [eng.submit(x) for x in _reqs([2, 3])]
    eng.start()
    eng.close(drain=True)                   # drain flushes immediately
    for f in futs:
        assert f.result(5).shape[1] == 4
    assert eng.stats()["completed"] == 2


def test_close_without_drain_fails_futures_not_lost():
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=32,
                        timeout_ms=5000.0, start=False)
    futs = [eng.submit(x) for x in _reqs([2, 3])]
    eng.close(drain=False)                  # no worker ever ran
    for f in futs:
        with pytest.raises(RuntimeError, match="closed"):
            f.result(1)


# ---------------------------------------------------------------------------
# admission control: backpressure + deadlines

def test_full_queue_fast_rejects(mon):
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=8,
                        timeout_ms=5.0, queue_depth=3, start=False)
    xs = _reqs([1, 1, 1, 1])
    futs = [eng.submit(x) for x in xs[:3]]
    t0 = time.perf_counter()
    with pytest.raises(QueueFullError):
        eng.submit(xs[3])
    assert time.perf_counter() - t0 < 0.05  # synchronous, no future made
    assert mon.registry().value("serving.rejected", 0) == 1
    assert eng.stats()["rejected"] == 1
    eng.start()
    for f in futs:
        f.result(5)
    eng.close()


def test_expired_deadline_never_occupies_batch_slot(mon):
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=32,
                        timeout_ms=5.0, start=False)
    dead = eng.submit(_reqs([7])[0], deadline_ms=0)   # born expired
    live = eng.submit(_reqs([3], np.random.RandomState(9))[0])
    time.sleep(0.01)
    eng.start()
    with pytest.raises(DeadlineExpired):
        dead.result(5)
    assert live.result(5).shape == (3, 4)
    st = eng.stats()
    # the expired request's 7 rows never reached a batch
    assert st["coalesced_rows"] == 3
    assert st["expired"] == 1 and st["completed"] == 1
    assert mon.registry().value("serving.deadline_expired", 0) == 1
    eng.close()


def test_default_deadline_stamped_by_engine():
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=8,
                        timeout_ms=5.0, deadline_ms=0.0, start=False)
    f = eng.submit(_reqs([1])[0])           # engine default: expires now
    time.sleep(0.005)
    eng.start()
    with pytest.raises(DeadlineExpired):
        f.result(5)
    eng.close()


# ---------------------------------------------------------------------------
# failure triage: retry vs isolation

def test_transient_batch_failure_retries(mon):
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=8,
                        timeout_ms=10.0, start=False)
    real = eng.predictor.run_device
    calls = {"n": 0}

    def flaky(*a, **k):
        calls["n"] += 1
        if calls["n"] == 1:
            raise TransientError("injected hiccup")
        return real(*a, **k)

    eng.predictor.run_device = flaky
    futs = [eng.submit(x) for x in _reqs([2, 3])]
    eng.start()
    for f in futs:
        assert f.result(5).shape[1] == 4
    assert eng.stats()["retries"] == 1
    assert mon.registry().value("serving.retries", 0) == 1
    eng.close()


def test_poisoned_request_fails_only_its_own_future(mon):
    m = _mlp()
    eng = ServingEngine(inference.Predictor(m), max_batch=32,
                        timeout_ms=10.0, start=False)
    real = eng.predictor.run_device

    def guarded(*arrays, **k):
        # host-side poison: any batch containing a NaN row fails the
        # whole executable call, the way a bad feed would
        if any(np.isnan(np.asarray(a)).any() for a in arrays):
            raise ValueError("poisoned feed")
        return real(*arrays, **k)

    eng.predictor.run_device = guarded
    rng = np.random.RandomState(3)
    good1, good2 = _reqs([2, 3], rng)
    poison = np.full((1, 16), np.nan, "f4")
    f1, fp, f2 = eng.submit(good1), eng.submit(poison), eng.submit(good2)
    eng.start()
    ref = inference.Predictor(m)
    np.testing.assert_array_equal(f1.result(5), ref.run(good1))
    np.testing.assert_array_equal(f2.result(5), ref.run(good2))
    with pytest.raises(ValueError, match="poisoned"):
        fp.result(5)
    st = eng.stats()
    assert st["failed"] == 1 and st["completed"] == 2
    reg = mon.registry()
    assert reg.value("serving.poisoned", 0) == 1
    assert reg.value("serving.isolated", 0) == 3
    eng.close()


# ---------------------------------------------------------------------------
# observability

def test_serving_metric_series(mon):
    eng = ServingEngine(inference.Predictor(_mlp()), buckets=[8],
                        max_batch=8, timeout_ms=10.0)
    eng.warmup([((16,), "float32")])
    futs = [eng.submit(x) for x in _reqs([1, 2, 3])]
    for f in futs:
        f.result(5)
    eng.close()
    reg = mon.registry()
    assert reg.value("serving.requests", 0) == 3
    assert reg.value("serving.rows", 0) == 6
    assert reg.value("serving.batches", 0) >= 1
    fill = reg.value("serving.batch_fill")
    assert fill and fill["count"] >= 1
    assert fill["sum"] / fill["count"] > 1     # requests coalesced
    occ = reg.value("serving.batch_occupancy")
    assert occ and 0 < occ["sum"] / occ["count"] <= 1
    lat = reg.value("serving.latency_ms")
    assert lat and lat["count"] == 3
    assert reg.value("serving.qps") > 0


def test_serving_spans_in_trace(mon):
    from paddle_tpu.monitor import trace
    trace.enable()
    try:
        eng = ServingEngine(inference.Predictor(_mlp()), max_batch=8,
                            timeout_ms=5.0)
        eng.warmup([((16,), "float32")])
        eng.run(_reqs([3])[0], timeout=5)
        eng.close()
        names = {e[1] for e in trace.events()}
        for want in ("serving.enqueue", "serving.batch_assemble",
                     "serving.execute", "serving.scatter",
                     "serving.warmup"):
            assert any(n.startswith(want) for n in names), want
    finally:
        trace.disable()
        trace.clear()


def test_metrics_noop_when_monitor_disabled():
    from paddle_tpu import monitor
    assert not monitor.enabled()
    eng = ServingEngine(inference.Predictor(_mlp()), max_batch=8,
                        timeout_ms=5.0)
    eng.run(_reqs([2])[0], timeout=5)       # must not touch the registry
    eng.close()
    assert monitor.registry().value("serving.requests", 0) == 0


# ---------------------------------------------------------------------------
# concurrency + multi-device fan-out

def test_concurrent_clients_all_resolve():
    m = _mlp()
    eng = ServingEngine(inference.Predictor(m), buckets=[8, 32],
                        max_batch=32, timeout_ms=2.0, queue_depth=512)
    eng.warmup([((16,), "float32")])
    ref = inference.Predictor(m)
    errors = []

    def client(k):
        rng = np.random.RandomState(k)
        for i in range(10):
            x = rng.rand(1 + (k + i) % 13, 16).astype("f4")
            try:
                np.testing.assert_array_equal(
                    eng.run(x, timeout=10), ref.run(x))
            except Exception as e:  # noqa: BLE001
                errors.append(e)

    threads = [threading.Thread(target=client, args=(k,))
               for k in range(6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    st = eng.stats()
    assert st["completed"] == 60 and st["submitted"] == 60
    assert st["batches"] <= 60              # some coalescing happened
    eng.close()


def test_replicate_places_state_per_device():
    p = inference.Predictor(_mlp())
    devs = jax.local_devices()[:2]
    reps = serving.replicate(p, devs)
    assert len(reps) == 2
    for r, d in zip(reps, devs):
        assert r.device == d
        leaf = next(iter(r.state.values()))
        assert list(leaf.devices()) == [d]
        assert r._compiled == {} and r.model is p.model


def test_multi_device_round_robin(mon):
    m = _mlp()
    me = MultiDeviceEngine(inference.Predictor(m),
                           devices=jax.local_devices()[:2],
                           max_batch=8, timeout_ms=5.0)
    me.warmup([((16,), "float32")])
    ref = inference.Predictor(m)
    xs = _reqs([2, 3, 1, 4], np.random.RandomState(7))
    futs = [me.submit(x) for x in xs]
    for x, f in zip(xs, futs):
        np.testing.assert_array_equal(f.result(5), ref.run(x))
    st = me.stats()
    assert st["completed"] == 4 and len(st["replicas"]) == 2
    # round robin: both replicas saw traffic
    assert all(r["submitted"] == 2 for r in st["replicas"])
    me.close()
