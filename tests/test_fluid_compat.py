"""fluid compatibility namespace: reference-style user code must run
(mirrors the reference book examples, e.g. test_recognize_digits)."""
import numpy as np
import pytest

import paddle_tpu as pt
import paddle_tpu.fluid as fluid


@pytest.fixture(autouse=True)
def _reset():
    from paddle_tpu import static
    static.reset_default_programs()
    fluid.layers._bn_stats.clear()
    yield
    fluid.disable_static()


def test_fluid_static_mnist_style_program():
    """The reference book's recognize_digits flow, fluid API verbatim."""
    fluid.enable_static()
    pt.seed(0)
    img = fluid.data("img", [None, 1, 28, 28], "float32")
    label = fluid.data("label", [None, 1], "int64")

    conv = fluid.layers.conv2d(img, num_filters=8, filter_size=5, act="relu")
    pool = fluid.layers.pool2d(conv, pool_size=2, pool_stride=2)
    hidden = fluid.layers.fc(pool, size=64, act="relu")
    prediction = fluid.layers.fc(hidden, size=10, act="softmax")
    loss = fluid.layers.mean(
        fluid.layers.cross_entropy(prediction, label))
    acc = fluid.layers.accuracy(prediction, label)

    opt = fluid.optimizer.Adam(learning_rate=1e-3)
    opt.minimize(loss)

    exe = fluid.Executor(fluid.CPUPlace())
    rng = np.random.RandomState(0)
    x = (rng.rand(64, 1, 28, 28) * 0.1).astype("f4")
    y = rng.randint(0, 10, (64, 1))
    for i in range(64):
        x[i, 0, 5:15, 5:15] += y[i, 0] / 10.0

    losses = []
    for _ in range(30):
        lv, av = exe.run(feed={"img": x, "label": y},
                         fetch_list=[loss, acc])
        losses.append(float(lv))
    assert losses[-1] < losses[0]


def test_fluid_dygraph_guard_style():
    """Reference dygraph user code via fluid.dygraph."""
    with fluid.dygraph.guard():
        model = fluid.dygraph.Sequential(
            fluid.dygraph.Linear(4, 16),
            fluid.dygraph.Linear(16, 2),
        )
        opt = fluid.optimizer.SGDOptimizer(
            learning_rate=0.1, parameters=model.parameters())
        x = fluid.dygraph.to_variable(
            np.random.randn(8, 4).astype("f4"))
        loss = model(x).square().mean()
        loss.backward()
        opt.minimize(loss)
        model.clear_gradients()


def test_fluid_program_guard_and_clone():
    fluid.enable_static()
    main, startup = fluid.Program(), fluid.Program()
    with fluid.program_guard(main, startup):
        x = fluid.data("x", [None, 3], "float32")
        out = fluid.layers.fc(x, size=2)
        loss = fluid.layers.mean(out)
        fluid.optimizer.SGD(learning_rate=0.1).minimize(loss)
    test_prog = main.clone(for_test=True)
    assert main.optimizers and not test_prog.optimizers
    exe = fluid.Executor()
    res = exe.run(test_prog, feed={"x": np.ones((2, 3), "f4")},
                  fetch_list=[out])
    assert res[0].shape == (2, 2)


def test_fluid_misc_surface():
    assert fluid.cuda_places()
    assert fluid.cpu_places(2)
    fluid.memory_optimize(None)
    fluid.set_flags({"FLAGS_check_nan_inf": False})
    m = fluid.layers.sequence_mask(pt.to_tensor(np.array([2, 4])), maxlen=5)
    np.testing.assert_array_equal(
        m.numpy(), [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0]])
